"""The resources registry — raft_tpu's "handle" system.

(ref: cpp/include/raft/core/resources.hpp:39-120 — a type-indexed container
of lazily-constructed resources: factories are registered per slot and the
resource is instantiated on first ``get_resource``, mutex-guarded; shallow
copies share resources. ref: core/device_resources.hpp:53-228 — the concrete
"handle" pre-registering device/stream factories.)

The registry design is kept — it is a good design — but the resource
vocabulary is TPU-native (see :mod:`raft_tpu.core.resource_types`): instead
of cuBLAS handles and CUDA streams, a handle owns its JAX device, an SPMD
``Mesh``, a threefry PRNG key stream, a compiled-executable cache, workspace
memory budgets, and (optionally) an injected communicator.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from raft_tpu.core.error import LogicError, expects
from raft_tpu.core.resource_types import ResourceType

ResourceFactory = Callable[["Resources"], Any]


class KeyStream:
    """Mutable threefry key stream scoped to a handle.

    The TPU-native replacement for per-call ``RngState`` plumbing: splitting
    is explicit and deterministic given the seed (counter-based threefry, the
    native TPU RNG — ref SURVEY §2.9 TPU mapping note).
    """

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)
        self._lock = threading.Lock()

    @property
    def seed(self) -> int:
        return self._seed

    def next_key(self):
        """Split off a fresh subkey (thread-safe)."""
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    def reseed(self, seed: int) -> None:
        with self._lock:
            self._seed = int(seed)
            self._key = jax.random.key(self._seed)


class CompileCache:
    """Memoization of AOT-lowered executables keyed by (fn, shapes).

    The TPU-native analog of the reference's precompiled ``libraft.so``
    instantiations (ref: cpp/CMakeLists.txt:275-309): expensive compilation
    happens once per shape signature and is reused.
    """

    def __init__(self):
        self._cache: Dict[Any, Any] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    _MISS = object()

    def get_or_compile(self, key, compile_fn: Callable[[], Any]):
        # observability bridge: lazy import (core must import first) and
        # called outside the cache lock (the hook takes the registry lock)
        from raft_tpu.observability import record_cache

        with self._lock:
            value = self._cache.get(key, CompileCache._MISS)
            if value is not CompileCache._MISS:
                self.hits += 1
        if value is not CompileCache._MISS:
            record_cache(hit=True)
            return value
        value = compile_fn()
        record_cache(hit=False)
        with self._lock:
            self.misses += 1
            self._cache.setdefault(key, value)
            return self._cache[key]

    def clear(self):
        with self._lock:
            self._cache.clear()


class WorkspaceResource:
    """Scratch-memory budget descriptor.

    (ref: core/resource/workspace_resource.hpp — an RMM limiting adaptor over
    the workspace pool). XLA owns allocation on TPU; what algorithms need is
    the *budget* so they can pick batch sizes that fit. ``allocation_limit``
    is in bytes.
    """

    def __init__(self, allocation_limit: Optional[int] = None):
        if allocation_limit is None:
            allocation_limit = self._default_limit()
        self.allocation_limit = int(allocation_limit)

    @staticmethod
    def _default_limit() -> int:
        try:
            stats = jax.devices()[0].memory_stats()
            if stats and "bytes_limit" in stats:
                # match the reference's default: a fraction of device memory
                return int(stats["bytes_limit"]) // 4
        except Exception:
            pass
        return 1 << 30  # 1 GiB fallback (e.g. CPU test platform)

    def batch_rows(self, row_bytes: int, minimum: int = 1) -> int:
        """How many rows of ``row_bytes`` fit in the budget."""
        return max(minimum, self.allocation_limit // max(1, row_bytes))


class Resources:
    """Type-indexed lazy resource container.

    (ref: core/resources.hpp:39 ``class resources`` — ``add_resource_factory``
    registers, ``get_resource<T>`` instantiates on first use under a mutex;
    copies share the underlying store.)
    """

    def __init__(self, _shared_from: Optional["Resources"] = None):
        if _shared_from is not None:
            # shallow copy shares factories and instantiated resources
            self._factories = _shared_from._factories
            self._resources = _shared_from._resources
            self._lock = _shared_from._lock
        else:
            self._factories: Dict[Any, ResourceFactory] = {}
            self._resources: Dict[Any, Any] = {}
            self._lock = threading.RLock()

    # -- registry ---------------------------------------------------------
    def add_resource_factory(self, rtype, factory: ResourceFactory) -> None:
        """Register (or replace) the factory for a slot.
        (ref: resources.hpp:79)"""
        with self._lock:
            self._factories[rtype] = factory
            self._resources.pop(rtype, None)

    def has_resource_factory(self, rtype) -> bool:
        with self._lock:
            return rtype in self._factories or rtype in self._resources

    def get_resource(self, rtype):
        """Get the resource in a slot, building it lazily on first access.
        (ref: resources.hpp:104-120)"""
        with self._lock:
            if rtype not in self._resources:
                factory = self._factories.get(rtype)
                if factory is None:
                    raise LogicError(f"no resource factory registered for {rtype}")
                self._resources[rtype] = factory(self)
            return self._resources[rtype]

    def set_resource(self, rtype, value) -> None:
        """Directly install an instantiated resource (used e.g. by comms
        injection — ref: core/resource/comms.hpp ``set_comms``)."""
        with self._lock:
            self._resources[rtype] = value

    # -- common accessors (ref: one-file-per-resource accessors under
    #    core/resource/*.hpp) ------------------------------------------------
    @property
    def device(self):
        return self.get_resource(ResourceType.DEVICE)

    @property
    def device_id(self) -> int:
        return self.get_resource(ResourceType.DEVICE_ID)

    @property
    def platform(self) -> str:
        return self.get_resource(ResourceType.PLATFORM)

    @property
    def mesh(self) -> jax.sharding.Mesh:
        return self.get_resource(ResourceType.MESH)

    def set_mesh(self, mesh: jax.sharding.Mesh) -> None:
        self.set_resource(ResourceType.MESH, mesh)

    @property
    def rng(self) -> KeyStream:
        return self.get_resource(ResourceType.RNG)

    @property
    def compile_cache(self) -> CompileCache:
        return self.get_resource(ResourceType.COMPILE_CACHE)

    # metrics sink (ref role: mr/resource_monitor.hpp + nvtx attribution;
    # here: the raft_tpu.observability registry)
    @property
    def metrics(self):
        """The handle's metrics sink. Falls back to the process-global
        :func:`raft_tpu.observability.get_registry` when no factory is
        registered, so every handle is observable by default."""
        if not self.has_resource_factory(ResourceType.METRICS):
            from raft_tpu.observability import get_registry

            return get_registry()
        return self.get_resource(ResourceType.METRICS)

    def set_metrics(self, registry) -> None:
        """Install a handle-scoped MetricsRegistry (e.g. to isolate one
        tenant's counters from the process-global registry)."""
        self.set_resource(ResourceType.METRICS, registry)

    # cost-model profiler (static XLA cost capture + roofline — see
    # raft_tpu.observability.profiler)
    @property
    def profiler(self):
        """The handle's cost-model profiler. Falls back to the
        process-global :func:`raft_tpu.observability.get_profiler` when
        no factory is registered — the same default-observable contract
        as ``metrics``."""
        if not self.has_resource_factory(ResourceType.PROFILER):
            from raft_tpu.observability import get_profiler

            return get_profiler()
        return self.get_resource(ResourceType.PROFILER)

    def set_profiler(self, profiler) -> None:
        """Install a handle-scoped Profiler (e.g. to pin roofline peaks
        to a non-default device, or isolate records per tenant)."""
        self.set_resource(ResourceType.PROFILER, profiler)

    # recovery policies (retry budgets + degradation ladders — see
    # raft_tpu.resilience.policy)
    @property
    def resilience(self):
        """The handle's recovery-policy table. Falls back to the
        process-global :func:`raft_tpu.resilience.get_policy_table`
        when no factory is registered — the same default contract as
        ``metrics``/``profiler``."""
        if not self.has_resource_factory(ResourceType.RESILIENCE):
            from raft_tpu.resilience.policy import get_policy_table

            return get_policy_table()
        return self.get_resource(ResourceType.RESILIENCE)

    def set_resilience(self, table) -> None:
        """Install a handle-scoped PolicyTable (e.g. to disable retries
        for one tenant, or tighten the ladder for a latency-bound
        caller)."""
        self.set_resource(ResourceType.RESILIENCE, table)

    @property
    def workspace(self) -> WorkspaceResource:
        return self.get_resource(ResourceType.WORKSPACE_RESOURCE)

    def set_workspace_resource(self, ws: WorkspaceResource) -> None:
        self.set_resource(ResourceType.WORKSPACE_RESOURCE, ws)

    @property
    def large_workspace(self) -> WorkspaceResource:
        return self.get_resource(ResourceType.LARGE_WORKSPACE_RESOURCE)

    # comms (ref: core/resource/comms.hpp, sub_comms.hpp)
    def set_comms(self, comms) -> None:
        self.set_resource(ResourceType.COMMUNICATOR, comms)

    def get_comms(self):
        expects(
            self.has_resource_factory(ResourceType.COMMUNICATOR)
            or ResourceType.COMMUNICATOR in self._resources,
            "communicator is not set on this handle",
        )
        return self.get_resource(ResourceType.COMMUNICATOR)

    def comms_initialized(self) -> bool:
        with self._lock:
            return ResourceType.COMMUNICATOR in self._resources

    def set_subcomm(self, key: str, comms) -> None:
        with self._lock:
            subs = self._resources.setdefault(ResourceType.SUB_COMMUNICATOR, {})
            subs[key] = comms

    def get_subcomm(self, key: str):
        with self._lock:
            subs = self._resources.get(ResourceType.SUB_COMMUNICATOR, {})
            expects(key in subs, "sub-communicator %r is not set", key)
            return subs[key]

    # sync (ref: device_resources::sync_stream → here: drain dispatched work)
    def sync(self, *arrays):
        """Block until given arrays (or nothing, for API parity) are done."""
        from raft_tpu.core import interruptible

        if arrays:
            return interruptible.synchronize(*arrays)
        return None


def _default_device_index() -> int:
    return 0


def _default_metrics_factory(res: Resources):
    """Default METRICS slot: the process-global observability registry
    (one substrate shared by all handles; override per handle with
    ``set_metrics``)."""
    from raft_tpu.observability import get_registry

    return get_registry()


def _default_resilience_factory(res: Resources):
    """Default RESILIENCE slot: the process-global recovery-policy
    table (override per handle with ``set_resilience``)."""
    from raft_tpu.resilience.policy import get_policy_table

    return get_policy_table()


def _default_profiler_factory(res: Resources):
    """Default PROFILER slot: a profiler whose roofline peaks match the
    HANDLE's device (not necessarily jax.devices()[0]) and whose records
    publish into the handle's metrics sink."""
    from raft_tpu.observability.profiler import Profiler
    from raft_tpu.utils.arch import chip_spec

    try:
        spec = chip_spec(res.device)
    except Exception:
        spec = None
    return Profiler(registry=None, spec=spec)


class DeviceResources(Resources):
    """The concrete per-device handle.

    (ref: core/device_resources.hpp:53 — pre-registers device_id, stream,
    stream-pool factories and exposes vendor-handle accessors. Here the
    pre-registered slots are device / platform / mesh(single device) /
    rng / compile cache / workspace budgets.)
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        seed: int = 0,
        workspace_limit: Optional[int] = None,
    ):
        super().__init__()
        dev = device if device is not None else jax.devices()[_default_device_index()]
        self.add_resource_factory(ResourceType.DEVICE, lambda r: dev)
        self.add_resource_factory(ResourceType.DEVICE_ID, lambda r: dev.id)
        self.add_resource_factory(ResourceType.PLATFORM, lambda r: dev.platform)
        self.add_resource_factory(
            ResourceType.DEVICE_PROPERTIES,
            lambda r: {
                "device_kind": dev.device_kind,
                "platform": dev.platform,
                "memory_stats": (dev.memory_stats() if hasattr(dev, "memory_stats") else None),
            },
        )
        self.add_resource_factory(
            ResourceType.MESH,
            lambda r: jax.sharding.Mesh(np.array([dev]), ("x",)),
        )
        self.add_resource_factory(ResourceType.RNG, lambda r: KeyStream(seed))
        self.add_resource_factory(ResourceType.COMPILE_CACHE, lambda r: CompileCache())
        self.add_resource_factory(
            ResourceType.WORKSPACE_RESOURCE,
            lambda r: WorkspaceResource(workspace_limit),
        )
        self.add_resource_factory(
            ResourceType.LARGE_WORKSPACE_RESOURCE,
            lambda r: WorkspaceResource(None),
        )
        self.add_resource_factory(ResourceType.MEMORY_KIND, lambda r: "device")
        self.add_resource_factory(ResourceType.HOST_MEMORY_KIND, lambda r: "pinned_host")
        self.add_resource_factory(ResourceType.METRICS, _default_metrics_factory)
        self.add_resource_factory(ResourceType.PROFILER, _default_profiler_factory)
        self.add_resource_factory(ResourceType.RESILIENCE,
                                  _default_resilience_factory)


def _device_resources_reduce(self):
    # Pickling recreates a FRESH handle (resources are process-local), the
    # contract pylibraft documents for its DeviceResources
    # (ref: common/handle.pyx:113-123). type(self) keeps subclasses
    # (e.g. DeviceResourcesSNMG) reconstructing as themselves.
    return (type(self), ())


DeviceResources.__reduce__ = _device_resources_reduce

# legacy alias (ref: core/handle.hpp ``handle_t``)
Handle = DeviceResources

_default_resources: Optional[DeviceResources] = None
_default_lock = threading.Lock()


def device_resources() -> DeviceResources:
    """Process-default handle, created on first use.

    (ref: core/device_resources_manager.hpp:75 ``get_device_resources()`` —
    the singleton handing out handles; the TPU runtime needs no per-thread
    stream pools, so one shared handle suffices.)
    """
    global _default_resources
    with _default_lock:
        if _default_resources is None:
            _default_resources = DeviceResources()
        return _default_resources


def ensure_resources(res: Optional[Resources]) -> Resources:
    """Accept ``None`` as "use the process-default handle" — the pythonic
    rendering of pylibraft's ``@auto_sync_handle`` default-handle behavior
    (ref: python/pylibraft/pylibraft/common/handle.pyx:196)."""
    return res if res is not None else device_resources()
