"""Logging.

TPU-native counterpart of the reference logger (ref:
cpp/include/raft/core/logger.hpp:25-67 — wraps rapids_logger, default sink
stderr or a file named by env var ``RAFT_DEBUG_LOG_FILE``, ``RAFT_LOG_*``
macros gated by ``RAFT_LOG_ACTIVE_LEVEL``). Here it is a thin configuration
of :mod:`logging` with the same env-var contract:

- ``RAFT_DEBUG_LOG_FILE`` — if set, log to that file instead of stderr.
- ``RAFT_TPU_LOG_LEVEL``  — initial level name (default ``INFO``).
- ``RAFT_LOG_ACTIVE_LEVEL`` — reference-spelled alias for the level
  (honored when ``RAFT_TPU_LOG_LEVEL`` is unset; accepts both plain
  names and the reference's ``RAFT_LEVEL_*`` macro spellings).
"""

from __future__ import annotations

import logging
import os
import sys

_LOGGER_NAME = "raft_tpu"

# The reference's finest level (RAFT_LEVEL_TRACE); register the name so
# log_trace output renders as "[TRACE]" rather than "[Level 5]".
TRACE = 5
logging.addLevelName(TRACE, "TRACE")


def _level_from_name(name: str, default: int = logging.INFO) -> int:
    """Level name → int, knowing TRACE and the reference's
    ``RAFT_LEVEL_<NAME>`` spellings; unknown names fall back to
    ``default``."""
    name = name.strip().upper()
    if name.startswith("RAFT_LEVEL_"):
        name = name[len("RAFT_LEVEL_"):]
    name = {"WARN": "WARNING", "ERR": "ERROR", "OFF": "CRITICAL"}.get(
        name, name)
    level = logging.getLevelName(name)
    return level if isinstance(level, int) else default


def _env_level(default: int = logging.INFO) -> int:
    """Initial level from env: ``RAFT_TPU_LOG_LEVEL`` wins, then the
    reference-compatible ``RAFT_LOG_ACTIVE_LEVEL`` alias."""
    raw = (os.environ.get("RAFT_TPU_LOG_LEVEL")
           or os.environ.get("RAFT_LOG_ACTIVE_LEVEL"))
    return _level_from_name(raw, default) if raw else default


def default_logger() -> logging.Logger:
    """The process-wide raft_tpu logger, lazily configured.
    (ref: core/logger.hpp ``default_logger()``)"""
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        log_file = os.environ.get("RAFT_DEBUG_LOG_FILE")
        handler: logging.Handler
        if log_file:
            handler = logging.FileHandler(log_file)
        else:
            handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("[%(levelname)s] [%(asctime)s] %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(_env_level())
    return logger


def set_level(level: int | str) -> None:
    if isinstance(level, str):
        level = _level_from_name(level)
    default_logger().setLevel(level)


# RAFT_LOG_* macro equivalents (ref: core/logger.hpp:58+).
def log_trace(fmt: str, *args) -> None:
    default_logger().log(TRACE, fmt, *args)


def log_debug(fmt: str, *args) -> None:
    default_logger().debug(fmt, *args)


def log_info(fmt: str, *args) -> None:
    default_logger().info(fmt, *args)


def log_warn(fmt: str, *args) -> None:
    default_logger().warning(fmt, *args)


def log_error(fmt: str, *args) -> None:
    default_logger().error(fmt, *args)


def log_critical(fmt: str, *args) -> None:
    default_logger().critical(fmt, *args)
