"""Logging.

TPU-native counterpart of the reference logger (ref:
cpp/include/raft/core/logger.hpp:25-67 — wraps rapids_logger, default sink
stderr or a file named by env var ``RAFT_DEBUG_LOG_FILE``, ``RAFT_LOG_*``
macros gated by ``RAFT_LOG_ACTIVE_LEVEL``). Here it is a thin configuration
of :mod:`logging` with the same env-var contract:

- ``RAFT_DEBUG_LOG_FILE`` — if set, log to that file instead of stderr.
- ``RAFT_TPU_LOG_LEVEL``  — initial level name (default ``INFO``).
"""

from __future__ import annotations

import logging
import os
import sys

_LOGGER_NAME = "raft_tpu"


def default_logger() -> logging.Logger:
    """The process-wide raft_tpu logger, lazily configured.
    (ref: core/logger.hpp ``default_logger()``)"""
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        log_file = os.environ.get("RAFT_DEBUG_LOG_FILE")
        handler: logging.Handler
        if log_file:
            handler = logging.FileHandler(log_file)
        else:
            handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("[%(levelname)s] [%(asctime)s] %(message)s")
        )
        logger.addHandler(handler)
        level = os.environ.get("RAFT_TPU_LOG_LEVEL", "INFO").upper()
        logger.setLevel(getattr(logging, level, logging.INFO))
    return logger


def set_level(level: int | str) -> None:
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    default_logger().setLevel(level)


# RAFT_LOG_* macro equivalents (ref: core/logger.hpp:58+).
def log_trace(fmt: str, *args) -> None:
    default_logger().log(5, fmt, *args)


def log_debug(fmt: str, *args) -> None:
    default_logger().debug(fmt, *args)


def log_info(fmt: str, *args) -> None:
    default_logger().info(fmt, *args)


def log_warn(fmt: str, *args) -> None:
    default_logger().warning(fmt, *args)


def log_error(fmt: str, *args) -> None:
    default_logger().error(fmt, *args)


def log_critical(fmt: str, *args) -> None:
    default_logger().critical(fmt, *args)
