"""mdspan/mdarray/mdbuffer — the data-layer vocabulary over ``jax.Array``.

(ref: cpp/include/raft/core/mdspan.hpp:26, core/mdarray.hpp:124,
core/mdbuffer.cuh:391, core/memory_type.hpp:21, core/host_device_accessor.hpp,
core/{host,device,managed,pinned}_md{span,array}.hpp.)

Design stance (SURVEY §7): do not transliterate accessor/container-policy
template machinery. ``jax.Array`` already is an owning, device-placed,
layout-carrying n-d array; what the reference's layer adds on top is a
*vocabulary*: where the memory lives (:class:`MemoryType`), how it is laid
out (:class:`Layout`), non-owning views (:class:`MdSpan`), owning arrays
(:class:`MdArray`), a maybe-owning cross-memory bridge (:class:`MdBuffer`),
and factory functions (``make_device_matrix`` …). That vocabulary is kept;
the representation is a ``jax.Array`` (or ``numpy.ndarray`` for host memory)
plus metadata.

Column-major note: XLA’s logical layout is row-major; COL_MAJOR here is a
*logical* tag meaning "indexing follows Fortran order", realized by storing
the transposed buffer. ``as_jax()`` always returns the logically-indexed
array so math code never branches on layout.
"""

from __future__ import annotations

import enum
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.core.resources import Resources, ensure_resources


class MemoryType(enum.Enum):
    """(ref: core/memory_type.hpp:21 — host/pinned/device/managed)"""

    HOST = "host"
    PINNED = "pinned_host"
    DEVICE = "device"
    # TPU has no managed memory; map to device (XLA may spill to host).
    MANAGED = "device"


class Layout(enum.Enum):
    """(ref: layout_c_contiguous / layout_f_contiguous / padded layouts)"""

    ROW_MAJOR = "C"
    COL_MAJOR = "F"


def is_row_major(x: "MdSpan | Any") -> bool:
    """(ref: core/mdspan.hpp ``is_row_major``)"""
    return getattr(x, "layout", Layout.ROW_MAJOR) == Layout.ROW_MAJOR


def is_col_major(x: "MdSpan | Any") -> bool:
    return getattr(x, "layout", Layout.ROW_MAJOR) == Layout.COL_MAJOR


class MdSpan:
    """Non-owning nd view: array + (memory_type, layout) metadata.
    (ref: core/mdspan.hpp:26)"""

    __slots__ = ("_data", "memory_type", "layout")

    def __init__(self, data, memory_type: MemoryType, layout: Layout):
        self._data = data
        self.memory_type = memory_type
        self.layout = layout

    # -- shape/dtype in LOGICAL index order ------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        s = self._data.shape
        return tuple(reversed(s)) if self.layout == Layout.COL_MAJOR else tuple(s)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self) -> int:
        return self._data.ndim

    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def extent(self, i: int) -> int:
        return self.shape[i]

    # -- access ----------------------------------------------------------
    def as_jax(self) -> jax.Array:
        """The logically-indexed jnp array (transposes COL_MAJOR storage)."""
        arr = jnp.asarray(self._data)
        return arr.T if self.layout == Layout.COL_MAJOR else arr

    def as_numpy(self) -> np.ndarray:
        arr = np.asarray(self._data)
        return arr.T if self.layout == Layout.COL_MAJOR else arr

    def raw(self):
        """The underlying storage in physical order."""
        return self._data

    def __getitem__(self, idx):
        return self.as_jax()[idx]

    def __repr__(self):
        return (
            f"MdSpan(shape={self.shape}, dtype={self.dtype}, "
            f"memory={self.memory_type.name}, layout={self.layout.name})"
        )


class MdArray(MdSpan):
    """Owning nd array (same metadata; owns its buffer).
    (ref: core/mdarray.hpp:124 — mdarray via container policies; the
    container policy here is simply "jax.Array on a device" or
    "numpy.ndarray on host".)"""

    def view(self) -> MdSpan:
        return MdSpan(self._data, self.memory_type, self.layout)


def _alloc(shape, dtype, memory_type: MemoryType, layout: Layout, device=None):
    phys_shape = tuple(reversed(shape)) if layout == Layout.COL_MAJOR else tuple(shape)
    if memory_type == MemoryType.HOST:
        return np.zeros(phys_shape, dtype=dtype)
    arr = jnp.zeros(phys_shape, dtype=dtype)
    if device is not None:
        arr = jax.device_put(arr, device)
    return arr


# ---- factories (ref: core/device_mdarray.hpp make_device_matrix etc.) ----
def make_device_mdarray(
    res: Optional[Resources],
    shape: Sequence[int],
    dtype=jnp.float32,
    layout: Layout = Layout.ROW_MAJOR,
) -> MdArray:
    res = ensure_resources(res)
    return MdArray(
        _alloc(tuple(shape), dtype, MemoryType.DEVICE, layout, res.device),
        MemoryType.DEVICE,
        layout,
    )


def make_device_matrix(res, n_rows: int, n_cols: int, dtype=jnp.float32,
                       layout: Layout = Layout.ROW_MAJOR) -> MdArray:
    return make_device_mdarray(res, (n_rows, n_cols), dtype, layout)


def make_device_vector(res, n: int, dtype=jnp.float32) -> MdArray:
    return make_device_mdarray(res, (n,), dtype)


def make_device_scalar(res, value, dtype=None) -> MdArray:
    res = ensure_resources(res)
    arr = jnp.asarray(value, dtype=dtype)
    return MdArray(jax.device_put(arr, res.device), MemoryType.DEVICE, Layout.ROW_MAJOR)


def make_host_mdarray(shape, dtype=np.float32, layout: Layout = Layout.ROW_MAJOR) -> MdArray:
    return MdArray(_alloc(tuple(shape), dtype, MemoryType.HOST, layout), MemoryType.HOST, layout)


def make_host_matrix(n_rows: int, n_cols: int, dtype=np.float32,
                     layout: Layout = Layout.ROW_MAJOR) -> MdArray:
    return make_host_mdarray((n_rows, n_cols), dtype, layout)


def make_host_vector(n: int, dtype=np.float32) -> MdArray:
    return make_host_mdarray((n,), dtype)


def wrap(data, memory_type: Optional[MemoryType] = None,
         layout: Layout = Layout.ROW_MAJOR) -> MdSpan:
    """Wrap an existing array (no copy) as an MdSpan."""
    if memory_type is None:
        memory_type = MemoryType.HOST if isinstance(data, np.ndarray) else MemoryType.DEVICE
    return MdSpan(data, memory_type, layout)


class MdBuffer:
    """Maybe-owning buffer that converts to a requested memory type / dtype
    on demand, caching conversions. (ref: core/mdbuffer.cuh:391 — the
    cross-memory bridge; conversion here is ``jax.device_put`` across memory
    kinds + ``astype``.)"""

    def __init__(self, data: "MdSpan | Any", memory_type: Optional[MemoryType] = None):
        if not isinstance(data, MdSpan):
            data = wrap(data, memory_type)
        self._source = data
        self._cache: dict = {}

    @property
    def memory_type(self) -> MemoryType:
        return self._source.memory_type

    @property
    def dtype(self):
        return self._source.dtype

    @property
    def shape(self):
        return self._source.shape

    def view(self, memory_type: Optional[MemoryType] = None, dtype=None) -> MdSpan:
        memory_type = memory_type or self._source.memory_type
        dtype = np.dtype(dtype) if dtype is not None else np.dtype(self._source.dtype)
        if memory_type == self._source.memory_type and dtype == np.dtype(self._source.dtype):
            return self._source
        key = (memory_type, dtype)
        if key not in self._cache:
            logical = self._source.as_jax().astype(dtype)
            if memory_type == MemoryType.HOST:
                data: Any = np.asarray(logical)
            elif memory_type == MemoryType.PINNED:
                data = _to_memory_kind(logical, "pinned_host")
            else:
                data = _to_memory_kind(logical, "device")
            self._cache[key] = MdSpan(data, memory_type, Layout.ROW_MAJOR)
        return self._cache[key]


def _to_memory_kind(arr: jax.Array, kind: str) -> jax.Array:
    """Place an array into a named memory kind ("device" / "pinned_host"),
    degrading gracefully on platforms without that memory space."""
    try:
        dev = arr.devices().pop() if hasattr(arr, "devices") else jax.devices()[0]
        sharding = jax.sharding.SingleDeviceSharding(dev, memory_kind=kind)
        return jax.device_put(arr, sharding)
    except (ValueError, NotImplementedError):
        return jax.device_put(arr)


def copy(res: Optional[Resources], dst: "MdSpan | None", src: "MdSpan | Any") -> MdSpan:
    """Generic mdspan→mdspan copy across layouts and memory types.
    (ref: core/copy.cuh ``raft::copy`` — kernel / memcpy / host-loop
    dispatch; here: layout-normalizing ``device_put``.) Returns the
    destination view (functional style: if ``dst`` is None a new buffer in
    src's logical shape on the handle's device is returned)."""
    res = ensure_resources(res)
    if not isinstance(src, MdSpan):
        src = wrap(src)
    logical = src.as_jax()
    if dst is None:
        return MdSpan(jax.device_put(logical, res.device), MemoryType.DEVICE, Layout.ROW_MAJOR)
    expects(tuple(dst.shape) == tuple(src.shape),
            "copy: shape mismatch %s vs %s", dst.shape, src.shape)
    converted = logical.astype(dst.dtype)
    if dst.layout == Layout.COL_MAJOR:
        converted = converted.T
    if dst.memory_type == MemoryType.HOST:
        out: Any = np.asarray(converted)
    else:
        out = jax.device_put(converted, res.device)
    dst._data = out
    return dst
