"""Composable elementwise operator functors.

TPU-native equivalent of the reference's device functor vocabulary (ref:
cpp/include/raft/core/operators.hpp — ``identity_op``, ``sq_op``, ``add_op``,
``key_op``…) which are passed as template arguments into map/reduce kernels.
Here they are plain callables (usable both in traced JAX code and inside
Pallas kernel bodies) plus combinators for composition and argument binding.
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.kvp import KeyValuePair


# ---- nullary / unary ----
def identity_op(x, *_):
    return x


def const_op(value):
    def op(*_):
        return value

    return op


def cast_op(dtype):
    def op(x, *_):
        return x.astype(dtype) if hasattr(x, "astype") else dtype(x)

    return op


def key_op(kvp: KeyValuePair, *_):
    return kvp.key


def value_op(kvp: KeyValuePair, *_):
    return kvp.value


def sq_op(x, *_):
    return x * x


def abs_op(x, *_):
    return jnp.abs(x)


def sqrt_op(x, *_):
    return jnp.sqrt(x)


def nz_op(x, *_):
    return jnp.where(x != 0, jnp.ones_like(x), jnp.zeros_like(x))


# ---- binary ----
def add_op(a, b):
    return a + b


def sub_op(a, b):
    return a - b


def mul_op(a, b):
    return a * b


def div_op(a, b):
    return a / b


def div_checkzero_op(a, b):
    return jnp.where(b == 0, jnp.zeros_like(a * b), a / b)


def pow_op(a, b):
    return a**b


def mod_op(a, b):
    return a % b


def min_op(a, b):
    return jnp.minimum(a, b)


def max_op(a, b):
    return jnp.maximum(a, b)


def argmin_op(a: KeyValuePair, b: KeyValuePair) -> KeyValuePair:
    """KVP reduction keeping the smaller value (ties → smaller key).
    (ref: core/kvp.hpp use in argmin reductions)"""
    take_b = (b.value < a.value) | ((b.value == a.value) & (b.key < a.key))
    return KeyValuePair(
        key=jnp.where(take_b, b.key, a.key),
        value=jnp.where(take_b, b.value, a.value),
    )


def argmax_op(a: KeyValuePair, b: KeyValuePair) -> KeyValuePair:
    take_b = (b.value > a.value) | ((b.value == a.value) & (b.key < a.key))
    return KeyValuePair(
        key=jnp.where(take_b, b.key, a.key),
        value=jnp.where(take_b, b.value, a.value),
    )


def sqdiff_op(a, b):
    d = a - b
    return d * d


def absdiff_op(a, b):
    return jnp.abs(a - b)


# ---- combinators (ref: core/operators.hpp compose_op / plug_const_op) ----
def compose_op(*ops):
    """compose_op(f, g, h)(x) == f(g(h(x))) — innermost applied first,
    matching the reference's template ordering."""

    def composed(x, *args):
        for op in reversed(ops):
            x = op(x, *args)
        return x

    return composed


def plug_const_op(const, binary):
    """Bind the second argument of a binary op to a constant."""

    def op(x, *_):
        return binary(x, const)

    return op


def add_const_op(const):
    return plug_const_op(const, add_op)


def sub_const_op(const):
    return plug_const_op(const, sub_op)


def mul_const_op(const):
    return plug_const_op(const, mul_op)


def div_const_op(const):
    return plug_const_op(const, div_op)


def pow_const_op(const):
    return plug_const_op(const, pow_op)
