"""Host/device-safe scalar math.

(ref: cpp/include/raft/core/math.hpp — ``raft::min/max/log/sqrt/...`` that
work on both host and device). In JAX the same ``jnp`` functions trace on
device and evaluate eagerly on host, so these are thin aliases kept for API
parity; they also accept python scalars.
"""

from __future__ import annotations

import jax.numpy as jnp

abs = jnp.abs  # noqa: A001
exp = jnp.exp
log = jnp.log
log2 = jnp.log2
sqrt = jnp.sqrt
sin = jnp.sin
cos = jnp.cos
tanh = jnp.tanh
pow = jnp.power  # noqa: A001
min = jnp.minimum  # noqa: A001
max = jnp.maximum  # noqa: A001
atanh = jnp.arctanh
asin = jnp.arcsin
acos = jnp.arccos


def sgn(x):
    return jnp.sign(x)
