"""Error system.

TPU-native equivalent of the reference's exception hierarchy and check macros
(ref: cpp/include/raft/core/error.hpp — ``raft::exception`` with backtrace,
``RAFT_EXPECTS`` / ``RAFT_FAIL``, and the per-vendor-library error macros).
On TPU there are no cublas/cusolver/cusparse/nccl handles; what remains is a
single device-error type for XLA-side failures plus the logic/runtime pair.
Python already attaches tracebacks to exceptions, so no manual backtrace
capture is needed.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, List, Optional


class RaftException(Exception):
    """Base exception. (ref: core/error.hpp ``raft::exception``)"""


class LogicError(RaftException):
    """Invalid API usage / failed precondition.
    (ref: core/error.hpp ``raft::logic_error``)"""


def _flight_tail() -> List[dict]:
    """Last ~64 flight-recorder events at error-construction time —
    attached to device/deadline errors the way the span stack is, so a
    failure carries its own timeline. [] when tracing is disabled (no
    allocation); NEVER raises (an error constructor must not fail)."""
    try:
        from raft_tpu.observability.flight import error_tail

        return error_tail()
    except Exception:
        return []


class DeviceError(RaftException):
    """Accelerator-side failure (XLA compile/runtime error surfaced to the
    host). Carries ``flight_tail`` — the last ~64 timeline events at
    construction time (see :mod:`raft_tpu.observability.flight`).
    (ref: core/error.hpp ``raft::cuda_error``)"""

    def __init__(self, *args):
        super().__init__(*args)
        self.flight_tail = _flight_tail()


class OutOfMemoryError(DeviceError):
    """HBM exhaustion. (ref: rmm::bad_alloc path)"""


class DeadlineExceededError(RaftException):
    """A :func:`raft_tpu.resilience.deadline` scope expired before the
    guarded work completed — the TPU rendering of an NCCL collective
    timeout / watchdog abort. Carries the deadline budget, the active
    span stack of the cancelled thread at raise time, and the
    flight-recorder tail (``flight_tail``), so a hang converted into
    this error names WHERE the program was stuck and what led up to it.
    (ref: ncclCommAbort + the reference's interruptible::synchronize
    raising out of a spinning stream wait.)"""

    def __init__(self, message: str, seconds: Optional[float] = None,
                 span_stack: Optional[List[str]] = None):
        super().__init__(message)
        self.seconds = seconds
        self.span_stack = list(span_stack or [])
        self.flight_tail = _flight_tail()


# substrings of XLA / runtime status messages, checked upper-cased.
# RESOURCE_EXHAUSTED is the status code jaxlib surfaces for HBM/host
# allocation failure; the rest cover the prose variants seen in practice.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                "OUT OF MEMORY", "FAILED TO ALLOCATE", "ALLOCATION FAIL",
                "SCOPED-VMEM", "EXCEEDED MEMORY")
_DEADLINE_MARKERS = ("DEADLINE_EXCEEDED", "DEADLINE EXCEEDED",
                     "TIMED OUT", "TIMEOUT")
_DEVICE_MARKERS = ("INTERNAL:", "ABORTED:", "UNAVAILABLE:",
                   "DATA CORRUPTION", "HALT")


def _is_xla_error(exc: BaseException) -> bool:
    """jaxlib-layer exception, duck-typed by class name/module so the
    classifier needs no jaxlib import (and unit tests can use stubs)."""
    for klass in type(exc).__mro__:
        if klass.__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
        if klass.__module__.split(".")[0] in ("jaxlib", "jax"):
            return True
    return False


def classify_xla_error(exc: BaseException) -> Optional[RaftException]:
    """Map a raw runtime exception onto the raft taxonomy, or None.

    (ref: core/error.hpp's per-status ``RAFT_CUDA_TRY`` expansion — each
    vendor status code became a typed raft exception. On TPU the vendor
    surface is jaxlib's ``XlaRuntimeError`` whose *message* carries the
    absl status code.) Mapping: RESOURCE_EXHAUSTED/OOM →
    :class:`OutOfMemoryError`; DEADLINE_EXCEEDED/timeout →
    :class:`DeadlineExceededError`; INTERNAL/ABORTED (or any other
    jaxlib-layer failure) → :class:`DeviceError`. Exceptions already in
    the taxonomy pass through unchanged; exceptions that are neither
    (``ValueError`` from user input, ``KeyboardInterrupt``…) return
    None — the caller re-raises them unwrapped.

    Every classification is also a flight-recorder trigger: an
    ``error`` timeline event is emitted and, when
    ``RAFT_TPU_FLIGHT_DIR`` is set, the ring is dumped as Perfetto
    JSON for post-mortem — once per exception instance, so an error
    bubbling through nested ``device_errors`` scopes dumps once."""
    if isinstance(exc, RaftException):
        _flight_on_classify(exc)
        return exc
    if not isinstance(exc, Exception):
        return None          # KeyboardInterrupt/SystemExit are not ours
    msg = str(exc)
    upper = msg.upper()
    is_xla = _is_xla_error(exc)
    label = f"[{type(exc).__name__}] {msg}"
    classified: Optional[RaftException] = None
    if any(m in upper for m in _OOM_MARKERS):
        classified = OutOfMemoryError(label)
    elif is_xla and any(m in upper for m in _DEADLINE_MARKERS):
        classified = DeadlineExceededError(label)
    elif is_xla or any(m in upper for m in _DEVICE_MARKERS):
        classified = DeviceError(label)
    if classified is not None:
        _flight_on_classify(classified)
    return classified


def _flight_on_classify(error: RaftException) -> None:
    """Timeline event + post-mortem dump for one classified device
    failure — once per exception instance; never raises."""
    if getattr(error, "_flight_dumped", False):
        return
    try:
        error._flight_dumped = True
        from raft_tpu.observability import flight
        from raft_tpu.observability.timeline import emit_error

        emit_error(type(error).__name__, str(error))
        flight.post_mortem(f"classify-{type(error).__name__}",
                           error=error)
    except Exception:
        pass


@contextlib.contextmanager
def device_errors(context: str = "") -> Iterator[None]:
    """Scope that re-raises device-layer failures classified into the
    raft taxonomy (chained via ``raise ... from``), so callers of the
    runtime entry points never see raw jaxlib exceptions. Non-device
    exceptions propagate unwrapped. (ref: the RAFT_CUDA_TRY macro
    bracket around every launch.)"""
    try:
        yield
    except RaftException:
        raise
    except Exception as e:
        classified = classify_xla_error(e)
        if classified is not None:
            if context:
                classified.args = (f"{context}: {classified.args[0]}",)
            raise classified from e
        raise


def expects(condition: bool, fmt: str, *args) -> None:
    """Check a precondition; raise :class:`LogicError` on failure.
    (ref: core/error.hpp ``RAFT_EXPECTS``)"""
    if not condition:
        raise LogicError(fmt % args if args else fmt)


def fail(fmt: str, *args) -> None:
    """Unconditionally raise :class:`LogicError`.
    (ref: core/error.hpp ``RAFT_FAIL``)"""
    raise LogicError(fmt % args if args else fmt)
