"""Error system.

TPU-native equivalent of the reference's exception hierarchy and check macros
(ref: cpp/include/raft/core/error.hpp — ``raft::exception`` with backtrace,
``RAFT_EXPECTS`` / ``RAFT_FAIL``, and the per-vendor-library error macros).
On TPU there are no cublas/cusolver/cusparse/nccl handles; what remains is a
single device-error type for XLA-side failures plus the logic/runtime pair.
Python already attaches tracebacks to exceptions, so no manual backtrace
capture is needed.
"""

from __future__ import annotations


class RaftException(Exception):
    """Base exception. (ref: core/error.hpp ``raft::exception``)"""


class LogicError(RaftException):
    """Invalid API usage / failed precondition.
    (ref: core/error.hpp ``raft::logic_error``)"""


class DeviceError(RaftException):
    """Accelerator-side failure (XLA compile/runtime error surfaced to the
    host). (ref: core/error.hpp ``raft::cuda_error``)"""


class OutOfMemoryError(DeviceError):
    """HBM exhaustion. (ref: rmm::bad_alloc path)"""


def expects(condition: bool, fmt: str, *args) -> None:
    """Check a precondition; raise :class:`LogicError` on failure.
    (ref: core/error.hpp ``RAFT_EXPECTS``)"""
    if not condition:
        raise LogicError(fmt % args if args else fmt)


def fail(fmt: str, *args) -> None:
    """Unconditionally raise :class:`LogicError`.
    (ref: core/error.hpp ``RAFT_FAIL``)"""
    raise LogicError(fmt % args if args else fmt)
