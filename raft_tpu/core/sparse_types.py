"""Sparse matrix vocabulary types: COO and CSR with structure/values
separation.

(ref: cpp/include/raft/core/sparse_types.hpp, core/coo_matrix.hpp,
core/csr_matrix.hpp, core/device_coo_matrix.hpp, core/device_csr_matrix.hpp —
owning + view types where a ``*_structure`` (indices/indptr + shape) is held
separately from the values so several value arrays can share one structure.)

TPU-first: arrays are ``jax.Array``; both types are registered as JAX pytrees
so they can be passed through ``jit``/``vmap``/``shard_map`` directly. ``nnz``
and ``shape`` are static (Python ints) — XLA needs static shapes; sparsity
patterns with varying nnz are handled by padding (see
:mod:`raft_tpu.sparse.convert`). Padding convention: padded entries carry
``row = n_rows`` sentinel? No — padded entries use row/col = last valid
index with value 0, so every op is correct without masking.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class COOStructure:
    """(ref: core/coo_matrix.hpp ``coordinate_structure_t``)"""

    def __init__(self, rows, cols, shape: Tuple[int, int]):
        self.rows = rows
        self.cols = cols
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    def tree_flatten(self):
        return (self.rows, self.cols), self.shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


@jax.tree_util.register_pytree_node_class
class COOMatrix:
    """Owning COO matrix = structure + values.
    (ref: core/coo_matrix.hpp, sparse/coo.hpp ``raft::sparse::COO``)"""

    def __init__(self, rows, cols, values, shape: Tuple[int, int]):
        self.structure = COOStructure(rows, cols, shape)
        self.values = values

    # convenience accessors
    @property
    def rows(self):
        return self.structure.rows

    @property
    def cols(self):
        return self.structure.cols

    @property
    def shape(self) -> Tuple[int, int]:
        return self.structure.shape

    @property
    def nnz(self) -> int:
        return self.structure.nnz

    @property
    def dtype(self):
        return self.values.dtype

    def view(self) -> "COOMatrix":
        return self

    def with_values(self, values) -> "COOMatrix":
        """New COO sharing this structure (the structure/values split)."""
        return COOMatrix(self.rows, self.cols, values, self.shape)

    def to_dense(self) -> jax.Array:
        out = jnp.zeros(self.shape, dtype=self.values.dtype)
        return out.at[self.rows, self.cols].add(self.values)

    @classmethod
    def from_dense(cls, mat) -> "COOMatrix":
        mat = jnp.asarray(mat)
        import numpy as np

        host = np.asarray(mat)
        r, c = np.nonzero(host)
        return cls(jnp.asarray(r, jnp.int32), jnp.asarray(c, jnp.int32),
                   mat[r, c], mat.shape)

    def tree_flatten(self):
        return (self.rows, self.cols, self.values), self.shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], aux)

    def __repr__(self):
        return f"COOMatrix(shape={self.shape}, nnz={self.nnz}, dtype={self.dtype})"


@jax.tree_util.register_pytree_node_class
class CSRStructure:
    """(ref: core/csr_matrix.hpp ``compressed_structure_t``)"""

    def __init__(self, indptr, indices, shape: Tuple[int, int]):
        self.indptr = indptr
        self.indices = indices
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    def tree_flatten(self):
        return (self.indptr, self.indices), self.shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)


@jax.tree_util.register_pytree_node_class
class CSRMatrix:
    """Owning CSR matrix = compressed structure + values.
    (ref: core/csr_matrix.hpp, core/device_csr_matrix.hpp)"""

    def __init__(self, indptr, indices, values, shape: Tuple[int, int]):
        self.structure = CSRStructure(indptr, indices, shape)
        self.values = values

    @property
    def indptr(self):
        return self.structure.indptr

    @property
    def indices(self):
        return self.structure.indices

    @property
    def shape(self) -> Tuple[int, int]:
        return self.structure.shape

    @property
    def nnz(self) -> int:
        return self.structure.nnz

    @property
    def dtype(self):
        return self.values.dtype

    def with_values(self, values) -> "CSRMatrix":
        return CSRMatrix(self.indptr, self.indices, values, self.shape)

    def row_ids(self) -> jax.Array:
        """Expand indptr to one row id per nnz (the csr→coo row expansion,
        ref: sparse/convert/csr.cuh)."""
        n_rows = self.shape[0]
        counts = jnp.diff(self.indptr)
        return jnp.repeat(
            jnp.arange(n_rows, dtype=self.indices.dtype),
            counts,
            total_repeat_length=self.nnz,
        )

    def to_dense(self) -> jax.Array:
        out = jnp.zeros(self.shape, dtype=self.values.dtype)
        return out.at[self.row_ids(), self.indices].add(self.values)

    @classmethod
    def from_dense(cls, mat) -> "CSRMatrix":
        import numpy as np

        host = np.asarray(mat)
        r, c = np.nonzero(host)
        indptr = np.zeros(host.shape[0] + 1, np.int32)
        np.add.at(indptr, r + 1, 1)
        indptr = np.cumsum(indptr).astype(np.int32)
        return cls(jnp.asarray(indptr), jnp.asarray(c, jnp.int32),
                   jnp.asarray(host[r, c]), host.shape)

    def tree_flatten(self):
        return (self.indptr, self.indices, self.values), self.shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], aux)

    def __repr__(self):
        return f"CSRMatrix(shape={self.shape}, nnz={self.nnz}, dtype={self.dtype})"
