"""Process-global device resources manager.

(ref: cpp/include/raft/core/device_resources_manager.hpp:75-562 ``struct
device_resources_manager`` — a process singleton configured once (stream
pools per device, RMM pool sizes), after which ``get_device_resources()``
hands out per-thread handles round-robin. The TPU analog keeps the
configure-then-serve lifecycle: options are set before first use
(workspace budgets, seed policy, device set), then per-thread handles are
served round-robin over devices, sharing the process-wide compile cache.)
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax

from raft_tpu.core.error import expects
from raft_tpu.core.logger import log_warn
from raft_tpu.core.resource_types import ResourceType
from raft_tpu.core.resources import CompileCache, DeviceResources


class DeviceResourcesManager:
    """(ref: device_resources_manager.hpp:75)"""

    def __init__(self):
        self._lock = threading.Lock()
        self._initialized = False
        self._devices: Optional[List] = None
        self._workspace_limit: Optional[int] = None
        self._base_seed = 0
        self._handles: Dict[int, DeviceResources] = {}
        self._thread_slots: Dict[int, int] = {}
        self._next_slot = 0
        self._shared_cache = CompileCache()

    # -- configuration (before first get) ---------------------------------
    def _check_not_initialized(self, what: str):
        if self._initialized:
            log_warn("device_resources_manager: %s ignored after first use",
                     what)
            return False
        return True

    def set_devices(self, devices: Sequence) -> None:
        with self._lock:
            if self._check_not_initialized("set_devices"):
                self._devices = list(devices)

    def set_workspace_allocation_limit(self, nbytes: int) -> None:
        """(ref: set_workspace_memory_resource / pool options)"""
        with self._lock:
            if self._check_not_initialized("set_workspace_allocation_limit"):
                self._workspace_limit = int(nbytes)

    def set_base_seed(self, seed: int) -> None:
        with self._lock:
            if self._check_not_initialized("set_base_seed"):
                self._base_seed = int(seed)

    # -- serving -----------------------------------------------------------
    def get_device_resources(self) -> DeviceResources:
        """Per-thread handle, devices assigned round-robin.
        (ref: device_resources_manager.hpp ``get_device_resources()``)"""
        tid = threading.get_ident()
        with self._lock:
            self._initialized = True
            devices = self._devices if self._devices is not None else jax.devices()
            slot = self._thread_slots.get(tid)
            if slot is None:
                slot = self._next_slot % len(devices)
                self._thread_slots[tid] = slot
                self._next_slot += 1
            if slot not in self._handles:
                h = DeviceResources(device=devices[slot],
                                    seed=self._base_seed + slot,
                                    workspace_limit=self._workspace_limit)
                h.set_resource(ResourceType.COMPILE_CACHE, self._shared_cache)
                self._handles[slot] = h
            return self._handles[slot]


_manager = DeviceResourcesManager()


def get_device_resources_manager() -> DeviceResourcesManager:
    return _manager


def get_device_resources() -> DeviceResources:
    """(ref: ``raft::device_resources_manager::get_device_resources()``)"""
    return _manager.get_device_resources()
