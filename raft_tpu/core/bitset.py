"""Device bitset / bitmap.

(ref: cpp/include/raft/core/bitset.hpp:33 ``bitset_view``, :279 ``bitset``;
core/bitmap.hpp:34 ``bitmap_view``; util/popc.cuh.)

TPU-first design: the bitset is a ``uint32`` word array manipulated with
vectorized bit ops — test/set become gather + mask ops, ``popc`` is
``lax.population_count`` + sum, flip is bitwise-not. All methods are
functional (return new arrays) so they compose under ``jit``; the owning
:class:`Bitset` class carries the current words array for handle-style use.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects

_WORD_BITS = 32


def _n_words(n_bits: int) -> int:
    return (n_bits + _WORD_BITS - 1) // _WORD_BITS


class BitsetView:
    """Non-owning view over a words array. (ref: core/bitset.hpp:33)"""

    def __init__(self, words: jax.Array, n_bits: int):
        self.words = words
        self.n_bits = int(n_bits)

    def test(self, indices) -> jax.Array:
        """Gather bit values at ``indices`` → bool array.
        (ref: bitset.hpp ``bitset_view::test``)"""
        indices = jnp.asarray(indices)
        word = self.words[indices // _WORD_BITS]
        bit = (word >> (indices % _WORD_BITS).astype(jnp.uint32)) & jnp.uint32(1)
        return bit.astype(jnp.bool_)

    def to_dense(self) -> jax.Array:
        """All bits as a bool vector of length n_bits."""
        idx = jnp.arange(self.n_bits)
        return self.test(idx)

    def count(self) -> jax.Array:
        """Number of set bits. (ref: util/popc.cuh + bitset::count)"""
        mask = _tail_mask(self.n_bits, self.words.shape[0])
        return jnp.sum(jax.lax.population_count(self.words & mask)).astype(jnp.int32)

    def sparsity(self) -> jax.Array:
        return 1.0 - self.count() / jnp.float32(max(1, self.n_bits))


def _tail_mask(n_bits: int, n_words: int) -> jax.Array:
    """Mask clearing padding bits in the last word."""
    bits_in_last = n_bits - (n_words - 1) * _WORD_BITS
    full = jnp.full((n_words,), 0xFFFFFFFF, dtype=jnp.uint32)
    if bits_in_last == _WORD_BITS:
        return full
    last = jnp.uint32((1 << bits_in_last) - 1)
    return full.at[-1].set(last)


class Bitset(BitsetView):
    """Owning bitset. (ref: core/bitset.hpp:279)"""

    def __init__(self, n_bits: int, default_value: bool = True,
                 words: Optional[jax.Array] = None):
        if words is None:
            fill = jnp.uint32(0xFFFFFFFF) if default_value else jnp.uint32(0)
            words = jnp.full((_n_words(n_bits),), fill, dtype=jnp.uint32)
        super().__init__(words, n_bits)

    @classmethod
    def from_dense(cls, bits) -> "Bitset":
        bits = jnp.asarray(bits, dtype=jnp.bool_)
        n = bits.shape[0]
        pad = _n_words(n) * _WORD_BITS - n
        padded = jnp.concatenate([bits, jnp.zeros((pad,), jnp.bool_)]) if pad else bits
        chunks = padded.reshape(-1, _WORD_BITS).astype(jnp.uint32)
        weights = (jnp.uint32(1) << jnp.arange(_WORD_BITS, dtype=jnp.uint32))[None, :]
        words = jnp.sum(chunks * weights, axis=1, dtype=jnp.uint32)
        return cls(n, words=words)

    def set(self, indices, value: bool = True) -> "Bitset":
        """Set/clear bits at indices (functional: returns new Bitset).
        (ref: bitset.hpp ``bitset::set`` kernel)"""
        indices = jnp.asarray(indices)
        word_idx = indices // _WORD_BITS
        bit = jnp.uint32(1) << (indices % _WORD_BITS).astype(jnp.uint32)
        upd = _scatter_or(self.words.shape[0], word_idx, bit)
        words = self.words | upd if value else self.words & ~upd
        return Bitset(self.n_bits, words=words)

    def flip(self) -> "Bitset":
        mask = _tail_mask(self.n_bits, self.words.shape[0])
        return Bitset(self.n_bits, words=(~self.words) & mask)

    def reset(self, default_value: bool = True) -> "Bitset":
        return Bitset(self.n_bits, default_value)


def _scatter_or(n_words: int, word_idx: jax.Array, bits: jax.Array) -> jax.Array:
    """OR-scatter single-bit masks into a zeroed words array. Duplicate
    indices must OR together; integer scatter-add would carry across bit
    positions, so reduce each of the 32 bit-planes with a segment max
    (OR == max for 0/1 planes)."""
    out = jnp.zeros((n_words,), jnp.uint32)

    def body(b, acc):
        plane = ((bits >> jnp.uint32(b)) & jnp.uint32(1)).astype(jnp.uint32)
        has = jax.ops.segment_max(plane, word_idx, num_segments=n_words)
        return acc | (has.astype(jnp.uint32) << jnp.uint32(b))

    return jax.lax.fori_loop(0, _WORD_BITS, body, out)


class BitmapView:
    """2-D bitmap view over a bitset words array, rows×cols bit matrix.
    (ref: core/bitmap.hpp:34)"""

    def __init__(self, words: jax.Array, n_rows: int, n_cols: int):
        self.words = words
        self.n_rows = int(n_rows)
        self.n_cols = int(n_cols)
        self._bitset = BitsetView(words, self.n_rows * self.n_cols)

    def test(self, rows, cols) -> jax.Array:
        rows = jnp.asarray(rows)
        cols = jnp.asarray(cols)
        return self._bitset.test(rows * self.n_cols + cols)

    def to_dense(self) -> jax.Array:
        return self._bitset.to_dense().reshape(self.n_rows, self.n_cols)

    def count(self) -> jax.Array:
        return self._bitset.count()

    @classmethod
    def from_dense(cls, mat) -> "BitmapView":
        mat = jnp.asarray(mat, dtype=jnp.bool_)
        bs = Bitset.from_dense(mat.reshape(-1))
        return cls(bs.words, mat.shape[0], mat.shape[1])
