"""Serialization: mdspan ⇄ NumPy ``.npy``.

(ref: cpp/include/raft/core/serialize.hpp, core/numpy_serializer.hpp,
core/detail/mdspan_numpy_serializer.hpp — hand-rolled npy header writer.
Python has numpy; the contract kept is the wire format (standard .npy) and
the mdspan-level API names, incl. scalar serialization.)
"""

from __future__ import annotations

import io
from typing import Any, BinaryIO

import numpy as np

from raft_tpu.core.mdarray import MdSpan, wrap


def _logical_numpy(obj: Any) -> np.ndarray:
    if isinstance(obj, MdSpan):
        return obj.as_numpy()
    return np.asarray(obj)


def serialize_mdspan(res, stream: BinaryIO, obj: Any) -> None:
    """Write an array to a binary stream in .npy format.
    (ref: core/serialize.hpp ``serialize_mdspan``)"""
    np.save(stream, _logical_numpy(obj), allow_pickle=False)


def deserialize_mdspan(res, stream: BinaryIO) -> MdSpan:
    """Read a .npy array back as a host mdspan.
    (ref: core/serialize.hpp ``deserialize_mdspan``)"""
    arr = np.load(stream, allow_pickle=False)
    return wrap(arr)


def serialize_scalar(res, stream: BinaryIO, value) -> None:
    """(ref: core/serialize.hpp ``serialize_scalar``)"""
    np.save(stream, np.asarray(value), allow_pickle=False)


def deserialize_scalar(res, stream: BinaryIO):
    arr = np.load(stream, allow_pickle=False)
    return arr[()] if arr.ndim == 0 else arr.item()


def mdspan_to_bytes(obj: Any) -> bytes:
    buf = io.BytesIO()
    serialize_mdspan(None, buf, obj)
    return buf.getvalue()


def mdspan_from_bytes(data: bytes) -> MdSpan:
    return deserialize_mdspan(None, io.BytesIO(data))
