"""Serialization: mdspan ⇄ NumPy ``.npy``.

(ref: cpp/include/raft/core/serialize.hpp, core/numpy_serializer.hpp,
core/detail/mdspan_numpy_serializer.hpp — hand-rolled npy header writer.
Python has numpy; the contract kept is the wire format (standard .npy) and
the mdspan-level API names, incl. scalar serialization.)

The BYTES-level API (:func:`mdspan_to_bytes` / :func:`mdspan_from_bytes`)
frames the npy payload with a magic / version / length header so that a
truncated stream is detected HERE, with an honest message, instead of
surfacing as a raw ``np.load`` pickle error three layers down — the WAL
and checkpoint planes (:mod:`raft_tpu.mutable.wal`) depend on exactly
this property to classify torn records. ``mdspan_from_bytes`` still
reads the old unframed format (bare .npy bytes) for compatibility with
payloads written before the framing shipped. The STREAM-level API
(:func:`serialize_mdspan`) stays bare .npy — that is the RAFT wire
contract.
"""

from __future__ import annotations

import io
import struct
from typing import Any, BinaryIO, Tuple

import numpy as np

from raft_tpu.core.mdarray import MdSpan, wrap

#: framed-bytes header: magic + format version + payload length. The
#: magic cannot collide with .npy (which starts ``\x93NUMPY``), so the
#: unframed fallback is unambiguous.
FRAME_MAGIC = b"RTNP"
FRAME_VERSION = 1
_FRAME_HEADER = struct.Struct("<4sHQ")


def _logical_numpy(obj: Any) -> np.ndarray:
    if isinstance(obj, MdSpan):
        return obj.as_numpy()
    return np.asarray(obj)


def serialize_mdspan(res, stream: BinaryIO, obj: Any) -> None:
    """Write an array to a binary stream in .npy format.
    (ref: core/serialize.hpp ``serialize_mdspan``)"""
    np.save(stream, _logical_numpy(obj), allow_pickle=False)


def deserialize_mdspan(res, stream: BinaryIO) -> MdSpan:
    """Read a .npy array back as a host mdspan.
    (ref: core/serialize.hpp ``deserialize_mdspan``)"""
    arr = np.load(stream, allow_pickle=False)
    return wrap(arr)


def serialize_scalar(res, stream: BinaryIO, value) -> None:
    """(ref: core/serialize.hpp ``serialize_scalar``)"""
    np.save(stream, np.asarray(value), allow_pickle=False)


def deserialize_scalar(res, stream: BinaryIO):
    arr = np.load(stream, allow_pickle=False)
    return arr[()] if arr.ndim == 0 else arr.item()


def mdspan_to_bytes(obj: Any) -> bytes:
    """Framed bytes: magic + version + payload length, then the
    standard .npy payload — self-delimiting, so frames concatenate
    (:func:`read_framed`) and truncation is detectable."""
    buf = io.BytesIO()
    serialize_mdspan(None, buf, obj)
    payload = buf.getvalue()
    return _FRAME_HEADER.pack(FRAME_MAGIC, FRAME_VERSION,
                              len(payload)) + payload


def read_framed(data: bytes, offset: int = 0) -> Tuple[MdSpan, int]:
    """Decode ONE framed mdspan at ``offset``; returns (array, offset
    past the frame) — the sequential-parse primitive WAL payloads use.
    Raises ``ValueError`` with an honest message on a bad magic, a
    future version, or a truncated frame."""
    data = bytes(data)
    end_h = offset + _FRAME_HEADER.size
    if len(data) < end_h:
        raise ValueError(
            f"truncated framed mdspan stream: {len(data) - offset} "
            f"byte(s) at offset {offset}, header needs "
            f"{_FRAME_HEADER.size}")
    magic, version, plen = _FRAME_HEADER.unpack_from(data, offset)
    if magic != FRAME_MAGIC:
        raise ValueError(f"framed mdspan stream: bad magic {magic!r} "
                         f"at offset {offset}")
    if version > FRAME_VERSION:
        raise ValueError(f"framed mdspan stream: version {version} is "
                         f"newer than this reader ({FRAME_VERSION})")
    if len(data) < end_h + plen:
        raise ValueError(
            f"truncated framed mdspan stream: header promises {plen} "
            f"payload byte(s), only {len(data) - end_h} present")
    arr = deserialize_mdspan(None, io.BytesIO(data[end_h:end_h + plen]))
    return arr, end_h + plen


def mdspan_from_bytes(data: bytes) -> MdSpan:
    """Read one array from ``data``: framed (the current writer) or
    bare .npy (the pre-framing format, kept as a fallback reader)."""
    data = bytes(data)
    if data[:len(FRAME_MAGIC)] == FRAME_MAGIC:
        arr, _ = read_framed(data)
        return arr
    return deserialize_mdspan(None, io.BytesIO(data))
