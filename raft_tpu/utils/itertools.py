"""Host cartesian-product helper for test parameter generation.
(ref: cpp/include/raft/util/itertools.hpp — builds vectors of param structs
from value lists.)"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List


def product(make: Callable[..., Any], *value_lists: Iterable) -> List[Any]:
    """``product(Params, [1,2], ["a"])`` → ``[Params(1,"a"), Params(2,"a")]``"""
    return [make(*combo) for combo in itertools.product(*value_lists)]
