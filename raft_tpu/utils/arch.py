"""Accelerator-generation dispatch.

(ref: cpp/include/raft/util/arch.cuh — runtime SM-architecture ranges used
to pick kernel variants per GPU generation. The TPU equivalent keys off
``device_kind`` — v4/v5e/v5p/v6 … — so Pallas kernels can pick tile sizes
per generation.)
"""

from __future__ import annotations

import re
from typing import Optional

import jax


def device_kind(device: Optional[jax.Device] = None) -> str:
    dev = device or jax.devices()[0]
    return getattr(dev, "device_kind", "cpu")


def tpu_generation(device: Optional[jax.Device] = None) -> int:
    """TPU generation number (4, 5, 6, ...); 0 for non-TPU platforms."""
    kind = device_kind(device).lower()
    m = re.search(r"v(\d+)", kind)
    return int(m.group(1)) if m else 0


class ArchRange:
    """Half-open generation range for kernel dispatch.
    (ref: util/arch.cuh ``SM_range``)"""

    def __init__(self, min_gen: int, max_gen: int = 1 << 30):
        self.min_gen = min_gen
        self.max_gen = max_gen

    def contains(self, gen: Optional[int] = None) -> bool:
        g = tpu_generation() if gen is None else gen
        return self.min_gen <= g < self.max_gen
