"""Accelerator-generation dispatch + per-generation roofline peaks.

(ref: cpp/include/raft/util/arch.cuh — runtime SM-architecture ranges used
to pick kernel variants per GPU generation. The TPU equivalent keys off
``device_kind`` — v4/v5e/v5p/v6 … — so Pallas kernels can pick tile sizes
per generation.)

This module also carries the hardware half of the roofline model
(Williams et al., CACM 2009): :class:`ChipSpec` peak matmul FLOP/s and
HBM bandwidth per TPU generation, consumed by
:mod:`raft_tpu.observability.costmodel` to turn XLA ``cost_analysis``
FLOPs/bytes into %-of-roofline utilization. A CPU entry exists so the
full roofline path runs (deterministically) on the tier-1 CPU suite.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import jax


def device_kind(device: Optional[jax.Device] = None) -> str:
    dev = device or jax.devices()[0]
    return getattr(dev, "device_kind", "cpu")


def tpu_generation(device: Optional[jax.Device] = None) -> int:
    """TPU generation number (4, 5, 6, ...); 0 for non-TPU platforms."""
    kind = device_kind(device).lower()
    m = re.search(r"v(\d+)", kind)
    return int(m.group(1)) if m else 0


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-chip roofline peaks (public spec sheets, per chip — not per
    core/pod). ``peak_flops`` is the dense-matmul MXU peak at the native
    accumulation precision (bf16 inputs, f32 accumulate);
    ``peak_flops_f32`` is the ≈3-pass hi/lo-split f32 matmul rate (the
    split costs 3 MXU passes plus rounding overhead — an estimate, used
    only to place the f32 ridge point, never reported as a measurement).
    ``hbm_bw`` is bytes/s, ``hbm_bytes`` total device HBM.

    ``ici_bw`` is the per-chip AGGREGATE one-way inter-chip-interconnect
    bandwidth in bytes/s (all links; public spec-sheet Gbps ÷ 8) — the
    denominator of every busbw fraction the multichip artifacts record,
    and the wire term of :func:`raft_tpu.observability.costmodel.
    ici_time_model`. ``ici_latency`` is a per-collective-round latency
    estimate in seconds (link + XLA launch), the fixed cost that makes
    a log₂(p) tournament lose to one allgather at small payloads."""

    name: str
    peak_flops: float       # FLOP/s, bf16 matmul (MXU)
    peak_flops_f32: float   # FLOP/s, f32-grade matmul (split-pass estimate)
    hbm_bw: float           # bytes/s
    hbm_bytes: float        # bytes
    ici_bw: float = 0.0     # bytes/s, aggregate one-way per chip
    ici_latency: float = 1e-6   # seconds per collective round (estimate)

    @property
    def ridge(self) -> float:
        """Arithmetic intensity (FLOP/byte) where the bf16 roofline goes
        flat: below it a kernel is memory-bound, above compute-bound."""
        return self.peak_flops / self.hbm_bw

    @property
    def ridge_f32(self) -> float:
        return self.peak_flops_f32 / self.hbm_bw


# Public per-chip peaks. Keyed by (generation, variant); variant "" means
# the generation's only (or default) chip. ICI aggregates from the public
# spec sheets: v3 4×162.5 Gbps links ≈ 650 Gbps, v4 2400 Gbps (6 links,
# 3-D torus), v5e 1600 Gbps (4×400), v5p 4800 Gbps (6×800), v6e
# 3584 Gbps (4×896) — ÷8 for bytes/s.
_T = 1e12
_G = 1e9
TPU_SPECS = {
    (3, ""): ChipSpec("tpu v3", 123 * _T, 123 * _T / 3, 900 * _G, 32 * _G,
                      ici_bw=81 * _G),
    (4, ""): ChipSpec("tpu v4", 275 * _T, 275 * _T / 3, 1228 * _G, 32 * _G,
                      ici_bw=300 * _G),
    (5, "e"): ChipSpec("tpu v5e", 197 * _T, 197 * _T / 3, 819 * _G, 16 * _G,
                       ici_bw=200 * _G),
    (5, "p"): ChipSpec("tpu v5p", 459 * _T, 459 * _T / 3, 2765 * _G, 95 * _G,
                       ici_bw=600 * _G),
    (6, "e"): ChipSpec("tpu v6e", 918 * _T, 918 * _T / 3, 1640 * _G, 32 * _G,
                       ici_bw=448 * _G),
}

# The CPU fallback the tier-1 suite rooflines against: order-of-magnitude
# single-socket numbers, chosen so the ridge sits at 8 FLOP/byte — a GEMM
# (AI ~ d/6 for square operands ≥ 128) classifies compute-bound and an
# SpMV/elementwise pass (AI < 1) memory-bound, same as on real TPU specs.
# The synthetic "ICI" (the virtual-device memcpy fabric) is priced well
# below hbm_bw so merge-strategy ranking exercises the same wire-vs-
# select trade-off the TPU specs present.
CPU_SPEC = ChipSpec("cpu (synthetic roofline)", 200 * _G, 100 * _G,
                    25 * _G, 64 * _G, ici_bw=5 * _G, ici_latency=2e-6)


def chip_spec(device: Optional[jax.Device] = None) -> ChipSpec:
    """Roofline peaks for ``device`` (default: the first device).

    TPU kinds resolve by generation + lite/p variant (``TPU v5 lite`` /
    ``TPU v5e`` → v5e; ``TPU v5p`` → v5p); an unknown TPU generation
    falls back to the nearest known one so the report stays usable on
    new silicon (labelled by the table entry's name, never the device's).
    Non-TPU platforms get :data:`CPU_SPEC` — synthetic, but fixed, so
    tier-1 tests exercise the full classification path."""
    kind = device_kind(device).lower()
    gen = tpu_generation(device)
    if gen == 0:
        return CPU_SPEC
    variant = ""
    if "lite" in kind or re.search(r"v\d+\s*e", kind):
        variant = "e"
    elif re.search(r"v\d+\s*p", kind):
        variant = "p"
    spec = TPU_SPECS.get((gen, variant)) or TPU_SPECS.get((gen, ""))
    if spec is None:
        # unknown (gen, variant): nearest known generation, e-variant first
        for g in sorted({k[0] for k in TPU_SPECS}, key=lambda g: abs(g - gen)):
            spec = TPU_SPECS.get((g, variant)) or TPU_SPECS.get(
                (g, "")) or TPU_SPECS.get((g, "e"))
            if spec is not None:
                break
    return spec


class ArchRange:
    """Half-open generation range for kernel dispatch.
    (ref: util/arch.cuh ``SM_range``)"""

    def __init__(self, min_gen: int, max_gen: int = 1 << 30):
        self.min_gen = min_gen
        self.max_gen = max_gen

    def contains(self, gen: Optional[int] = None) -> bool:
        g = tpu_generation() if gen is None else gen
        return self.min_gen <= g < self.max_gen
