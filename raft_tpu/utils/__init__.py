"""raft_tpu.utils — host-side toolkit.

(ref: cpp/include/raft/util — SURVEY §2.2. Most of the reference's util
layer is warp/SM machinery that dissolves into Pallas/XLA idioms; what
survives host-side is kept here: power-of-two arithmetic, integer utilities,
test param generation, TPU-generation dispatch, the key→vector cache, the
prime sieve, and input validation.)
"""

from raft_tpu.utils.pow2 import Pow2, round_up_safe, round_down_safe, is_pow2
from raft_tpu.utils.integer_utils import ceildiv, alignTo, alignDown, gcd, lcm
from raft_tpu.utils.arch import tpu_generation, device_kind, ArchRange
from raft_tpu.utils.itertools import product as param_product
from raft_tpu.utils.cache import VectorCache
from raft_tpu.utils.seive import Seive
from raft_tpu.utils.input_validation import (
    is_contiguous,
    validate_matrix,
    validate_vector,
)

__all__ = [
    "Pow2", "round_up_safe", "round_down_safe", "is_pow2",
    "ceildiv", "alignTo", "alignDown", "gcd", "lcm",
    "tpu_generation", "device_kind", "ArchRange",
    "param_product", "VectorCache", "Seive",
    "is_contiguous", "validate_matrix", "validate_vector",
]
