"""Integer utilities. (ref: cpp/include/raft/util/integer_utils.hpp)"""

from __future__ import annotations

import math


def ceildiv(a: int, b: int) -> int:
    """(ref: util/integer_utils.hpp ``ceildiv`` / ``div_rounding_up_safe``)"""
    return -(-a // b)


def alignTo(v: int, align: int) -> int:
    return ceildiv(v, align) * align


def alignDown(v: int, align: int) -> int:
    return (v // align) * align


def gcd(a: int, b: int) -> int:
    return math.gcd(a, b)


def lcm(a: int, b: int) -> int:
    return a * b // math.gcd(a, b) if a and b else 0
