"""Sieve of Eratosthenes. (ref: cpp/include/raft/util/seive.hpp — host-side
prime sieve, spelling kept from the reference.)"""

from __future__ import annotations

import numpy as np


class Seive:
    def __init__(self, n: int):
        self.n = int(n)
        sieve = np.ones(self.n + 1, dtype=bool)
        sieve[:2] = False
        for p in range(2, int(self.n**0.5) + 1):
            if sieve[p]:
                sieve[p * p :: p] = False
        self._sieve = sieve

    def is_prime(self, k: int) -> bool:
        return bool(self._sieve[k])

    def primes(self) -> np.ndarray:
        return np.nonzero(self._sieve)[0]
