"""Input validation helpers.

(ref: cpp/include/raft/util/input_validation.hpp — mdspan contiguity/extent
checks. ``jax.Array``s are always dense; what remains meaningful is rank,
extent, and dtype validation with RAFT-style error messages.)
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import MdSpan


def _shape(x):
    return x.shape


def is_contiguous(x) -> bool:
    """jax arrays / MdSpans are always logically contiguous."""
    return True


def validate_matrix(x, name: str = "input", dtype=None):
    arr = x.as_jax() if isinstance(x, MdSpan) else jnp.asarray(x)
    expects(arr.ndim == 2, "%s must be a matrix (2-d), got %d-d", name, arr.ndim)
    if dtype is not None:
        expects(arr.dtype == dtype, "%s must have dtype %s, got %s", name, dtype, arr.dtype)
    return arr


def validate_vector(x, name: str = "input", dtype=None):
    arr = x.as_jax() if isinstance(x, MdSpan) else jnp.asarray(x)
    expects(arr.ndim == 1, "%s must be a vector (1-d), got %d-d", name, arr.ndim)
    if dtype is not None:
        expects(arr.dtype == dtype, "%s must have dtype %s, got %s", name, dtype, arr.dtype)
    return arr
