"""Key → vector cache.

(ref: cpp/include/raft/util/cache.cuh + cache_util.cuh — a GPU-resident
set-associative cache mapping integer keys to fixed-width vectors, used to
memoize expensive per-key vectors. TPU-first rendering: the cache store is a
dense ``jax.Array`` of shape (capacity, dim) living in HBM, with a host-side
hash index; assign/lookup are vectorized gather/scatter.)
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np


class VectorCache:
    def __init__(self, capacity: int, dim: int, dtype=jnp.float32):
        self.capacity = int(capacity)
        self.dim = int(dim)
        self.store = jnp.zeros((self.capacity, self.dim), dtype=dtype)
        self._slot_of: Dict[int, int] = {}
        self._order: list = []  # FIFO eviction order

    def assign(self, keys, vectors) -> None:
        """Insert vectors for keys (evicting FIFO on overflow)."""
        keys = np.asarray(keys).tolist()
        vectors = jnp.asarray(vectors)
        slots = []
        for k in keys:
            if k in self._slot_of:
                slots.append(self._slot_of[k])
                continue
            if len(self._order) < self.capacity:
                slot = len(self._order)
            else:
                evicted = self._order.pop(0)
                slot = self._slot_of.pop(evicted)
            self._slot_of[k] = slot
            self._order.append(k)
            slots.append(slot)
        self.store = self.store.at[jnp.asarray(slots, jnp.int32)].set(vectors)

    def lookup(self, keys) -> Tuple[jnp.ndarray, np.ndarray]:
        """Return (vectors, hit_mask); missing keys give zero vectors."""
        keys = np.asarray(keys).tolist()
        slots = np.array([self._slot_of.get(k, 0) for k in keys], np.int32)
        hits = np.array([k in self._slot_of for k in keys], bool)
        vecs = self.store[jnp.asarray(slots)]
        vecs = jnp.where(jnp.asarray(hits)[:, None], vecs, 0)
        return vecs, hits

    @property
    def size(self) -> int:
        return len(self._order)
