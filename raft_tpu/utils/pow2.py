"""Power-of-two arithmetic helpers.

(ref: cpp/include/raft/util/pow2_utils.cuh ``Pow2<Value>`` — compile-time
power-of-two div/mod/round helpers used for tiling. On TPU these survive as
host-side tiling math for Pallas block specs.)
"""

from __future__ import annotations


def is_pow2(v: int) -> bool:
    return v > 0 and (v & (v - 1)) == 0


def round_up_safe(value: int, multiple: int) -> int:
    """(ref: util/integer_utils.hpp round_up_safe)"""
    return ((value + multiple - 1) // multiple) * multiple


def round_down_safe(value: int, multiple: int) -> int:
    return (value // multiple) * multiple


class Pow2:
    """(ref: util/pow2_utils.cuh) — div/mod/round for a fixed power of two."""

    def __init__(self, value: int):
        if not is_pow2(value):
            raise ValueError(f"Pow2 requires a power of two, got {value}")
        self.value = value
        self.log2 = value.bit_length() - 1
        self.mask = value - 1

    def div(self, x: int) -> int:
        return x >> self.log2

    def mod(self, x: int) -> int:
        return x & self.mask

    def round_up(self, x: int) -> int:
        return (x + self.mask) & ~self.mask

    def round_down(self, x: int) -> int:
        return x & ~self.mask

    def is_aligned(self, x: int) -> bool:
        return (x & self.mask) == 0
