"""raft_tpu.runtime — the stable non-templated entry points.

(ref: cpp/include/raft_runtime/ + cpp/src/ — the compiled ``libraft.so``
surface: ``raft::runtime::solver::lanczos_solver`` (4 type combos,
cpp/src/raft_runtime/solver/lanczos_solver.cuh:11), ``randomized_svds``
(float/double), ``rmat_rectangular_generator`` (4 combos). In the reference
these exist so Cython can call pre-compiled code; the TPU analog is an
AOT-compiled, shape-specialized executable cached on the handle
(``CompileCache``) — compile once per (shape, dtype) signature, reuse across
calls, exactly the role of the explicit template instantiation.)
"""

from raft_tpu.runtime.entry_points import (
    lanczos_solver,
    randomized_svds,
    rmat_rectangular_generator,
)

__all__ = ["lanczos_solver", "randomized_svds", "rmat_rectangular_generator"]
