"""Runtime entry points (the libraft.so surface).

(ref: cpp/include/raft_runtime/solver/lanczos.hpp:23 ``lanczos_solver``;
raft_runtime/random/rmat_rectangular_generator.hpp; the randomized_svds
instantiations in cpp/src. See package docstring for the AOT-cache design.)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import device_errors
from raft_tpu.core.resources import ensure_resources
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.resilience import fault_point, run_with_policy


def _aot_call(res, name: str, statics: tuple, fn, *args):
    """AOT lower+compile ``fn`` once per (entry, statics, arg shapes) and
    reuse the executable from the handle's CompileCache — the TPU-native
    analog of the reference's precompiled libraft.so instantiations
    (ref: cpp/CMakeLists.txt:275-309). ``res.compile_cache.hits`` counts
    reuse (tested in tests/test_runtime_aot.py).

    Every compile miss also records the executable's static cost — XLA
    ``cost_analysis`` FLOPs/bytes and ``memory_analysis`` peak HBM — into
    ``res.profiler``, keyed by the same (entry, statics, shapes, sharding)
    signature as the cache, so roofline attribution covers every runtime
    entry without a second lowering (cache hits reuse the stored record).

    Resilience contract: compile AND dispatch run inside
    ``device_errors`` — callers never see raw jaxlib exceptions, only
    the classified taxonomy (OutOfMemoryError / DeviceError /
    DeadlineExceededError) — and the whole attempt is retried under the
    handle's ``runtime`` RetryPolicy (a failed compile is NOT cached,
    so a retry recompiles). Fault sites: ``aot_compile`` (inside the
    compile miss) and ``aot_dispatch`` (before every execution).
    Dispatch is async — an OOM XLA reports at completion time surfaces
    at the caller's sync point, already classified if the caller syncs
    through ``res.sync``/``device_errors``."""
    args = tuple(jnp.asarray(a) for a in args)
    # sharding/placement is part of the compiled executable's signature —
    # a cache hit with differently-committed args would raise at dispatch
    key = (name, statics,
           tuple((a.shape, str(a.dtype),
                  str(getattr(a, "sharding", None))) for a in args))

    def _compile():
        import time

        fault_point("aot_compile")
        t0 = time.perf_counter()
        with device_errors(f"{name} [compile]"):
            compiled = jax.jit(fn).lower(*args).compile()
        # compile wall time: timeline event + histogram on the COMPILE
        # bucket preset (DEFAULT_TIME_BUCKETS tops out at 30 s — a cold
        # north-star compile can exceed it; the preset reaches 300 s)
        try:
            from raft_tpu.observability.metrics import (
                COMPILE_TIME_BUCKETS, get_registry)
            from raft_tpu.observability.timeline import emit_compile

            dt = time.perf_counter() - t0
            emit_compile(name, seconds=dt, hit=False)
            get_registry().histogram(
                "raft_tpu_compile_seconds", {"entry": name},
                help="AOT compile wall time (compile bucket preset)",
                buckets=COMPILE_TIME_BUCKETS).observe(dt)
        except Exception:
            pass
        try:
            res.profiler.capture(name, compiled, key=str(key[1:]))
        except Exception:
            pass  # cost capture must never fail the entry point
        return compiled

    def _attempt(attempt):
        compiled = res.compile_cache.get_or_compile(key, _compile)
        fault_point("aot_dispatch")
        try:
            from raft_tpu.observability.timeline import emit_dispatch

            emit_dispatch(name)
        except Exception:
            pass
        with device_errors(name):
            return compiled(*args)

    return run_with_policy(f"runtime.{name}", _attempt,
                           policy=res.resilience.policy_for("runtime"))


def knn_query(res, index, x, k: int, rescore: Optional[bool] = None,
              certify: str = "kernel") -> Tuple[jax.Array, jax.Array]:
    """AOT serving entry: certified fused KNN against a PREPARED
    :class:`~raft_tpu.distance.knn_fused.KnnIndex`, compiled once per
    (index geometry, query-batch shape) and served from the handle's
    CompileCache — the data plane of the serving engine
    (:mod:`raft_tpu.serving`).

    Unlike :func:`raft_tpu.distance.knn_fused.knn_fused` (which jits
    lazily on first call), this entry lowers+compiles through
    :func:`_aot_call`, so the serving engine can PRE-WARM every bucket
    shape of its ladder at start-up and no live request ever pays a
    trace/compile: the cache key covers the query shape, so each bucket
    owns exactly one executable, and an index-snapshot swap of the same
    geometry re-uses them all (the index operands are ARGUMENTS, not
    baked-in constants). Feature/row padding to the kernel's block
    geometry happens INSIDE the compiled program — the key is the raw
    bucket shape the engine dispatches.
    """
    from raft_tpu.distance.knn_fused import (_LANES, _POOL_PAD, KnnIndex,
                                             _knn_fused_core,
                                             pool_select_algo,
                                             resolve_pool_algo)
    from raft_tpu.core.error import expects

    res = ensure_resources(res)
    expects(isinstance(index, KnnIndex),
            "knn_query: index must be a prepared KnnIndex (see "
            "distance.prepare_knn_index)")
    expects(getattr(index, "rows_valid", None) is None,
            "knn_query: ragged-layout indexes (rows_valid) query "
            "through knn_fused / the mutable plane, not the AOT entry")
    idx = index
    if certify not in ("kernel", "f32"):
        raise ValueError(f"knn_query: certify must be 'kernel' or "
                         f"'f32', got {certify!r}")
    x = jnp.asarray(x, jnp.float32)
    Q, d_x = x.shape
    expects(d_x == idx.d_orig, "knn_query: query width %d != index %d",
            d_x, idx.d_orig)
    expects(k <= idx.n_rows, "knn_query: k=%d > index size %d", k,
            idx.n_rows)
    if Q == 0:
        return (jnp.zeros((0, k), jnp.float32),
                jnp.zeros((0, k), jnp.int32))
    if rescore is None:
        rescore = idx.yp is not None
    if rescore and idx.yp is None:
        raise ValueError("knn_query: rescore=True needs a yp-storing "
                         "index (store_yp=True)")
    if idx.passes == 3:
        certify = "kernel"      # p3 is already f32-certified
    if certify == "f32" and not rescore:
        raise ValueError("knn_query: certify='f32' needs the exact "
                         "rescore (store_yp=True)")
    # pool geometry + effective selection algo, resolved per call like
    # knn_fused's own wrapper (the non-jitted decision point)
    n_tiles = idx.yyh_k.shape[1] // idx.T
    S_pool = -(-n_tiles // idx.g) * _LANES
    packed = idx.g * (idx.T // _LANES) <= (1 << idx.pbits)
    pool_len = S_pool if packed else 2 * S_pool
    if k > 2 * S_pool:
        raise NotImplementedError(
            f"knn_query: k={k} too large for pool {2 * S_pool}")
    pool_algo = resolve_pool_algo(pool_select_algo(), pool_len,
                                  min(k + _POOL_PAD, pool_len))
    Qb_eff = min(idx.Qb, ((Q + 7) // 8) * 8)
    has_yp = idx.yp is not None
    has_ylo = idx.y_lo is not None
    T_, g_, passes_ = idx.T, idx.g, idx.passes
    metric_, m_, pbits_ = idx.metric, idx.n_rows, idx.pbits
    order_ = idx.grid_order
    dtype_ = getattr(idx, "db_dtype", "bf16")
    quant = dtype_ == "int8"
    if quant and not rescore:
        raise ValueError("knn_query: an int8-streamed index is always "
                         "exact-rescored")

    def run(xq, *ops):
        it = iter(ops)
        yp = next(it) if has_yp else None
        if quant:
            y_hi = y_lo = None
            y_q, scale_k, eq = next(it), next(it), next(it)
            stream_w = y_q.shape[1]
        else:
            y_q = scale_k = eq = None
            y_hi = next(it)
            y_lo = next(it) if has_ylo else None
            stream_w = y_hi.shape[1]
        yyh_k = next(it)
        yy_raw = next(it)
        dpad = stream_w - xq.shape[1]
        if dpad:
            xq = jnp.concatenate(
                [xq, jnp.zeros((xq.shape[0], dpad), jnp.float32)], axis=1)
        qpad = (-Q) % Qb_eff
        if qpad:
            xq = jnp.concatenate(
                [xq, jnp.zeros((qpad, xq.shape[1]), jnp.float32)])
        vals, ids, n_fail, margin = _knn_fused_core(
            xq, yp, y_hi, y_lo, yyh_k, yy_raw,
            k=k, T=T_, Qb=Qb_eff, g=g_, passes=passes_, metric=metric_,
            m=m_, rescore=rescore, pbits=pbits_, certify=certify,
            pool_algo=pool_algo, grid_order=order_, db_dtype=dtype_,
            with_stats=True, y_q=y_q, y_scale_k=scale_k, eq_groups=eq)
        if qpad:
            vals, ids, margin = vals[:Q], ids[:Q], margin[:Q]
        if metric_ == "ip":
            vals = -vals        # internal −x·y ascending → IP descending
        return vals, ids, n_fail, margin

    statics = (k, T_, Qb_eff, g_, passes_, metric_, m_, bool(rescore),
               pbits_, certify, pool_algo, order_, dtype_, has_yp,
               has_ylo, Q)
    ops = [o for o in (idx.yp,) if o is not None]
    if quant:
        ops += [idx.y_q, idx.y_scale_k, idx.eq_groups]
    else:
        ops += [o for o in (idx.y_hi, idx.y_lo) if o is not None]
    ops += [idx.yyh_k, idx.yy_raw]
    vals, ids, n_fail, margin = _aot_call(res, "knn_query", statics,
                                          run, x, *ops)
    # certificate/fixup telemetry for the AOT serving plane: the
    # failure count stays a device scalar here (quality.drain resolves
    # it later — the live request path never syncs for telemetry); the
    # per-query margin is likewise only HELD (by reference) when an
    # explain capture is active, resolved at capture finalize
    try:
        from raft_tpu.distance.knn_fused import (fixup_tiers_for,
                                                 rescore_pool_width)
        from raft_tpu.observability import explain
        from raft_tpu.observability.quality import record_pending

        record_pending(
            "runtime.knn_query", n_fail,
            n_queries=Q + ((-Q) % Qb_eff),
            pool_width=rescore_pool_width(k, S_pool, packed),
            fix_tiers=fixup_tiers_for(idx.yyh_k.shape[1]),
            db_dtype=dtype_, passes=passes_)
        if explain.active() is not None:
            explain.note_margin("runtime.knn_query", margin)
            explain.note(plane="brute", db_dtype=dtype_,
                         grid_order=order_, passes=passes_,
                         pool_algo=pool_algo, certify=certify, k=k)
    except Exception:
        pass
    return vals, ids


def lanczos_solver(res, rows, cols, vals, n: int, n_components: int,
                   max_iterations: int = 1000, ncv: Optional[int] = None,
                   tolerance: float = 1e-6, which: str = "SA", seed: int = 42,
                   v0=None) -> Tuple[jax.Array, jax.Array]:
    """Flat-argument Lanczos entry (the ABI the Cython layer called).
    (ref: raft_runtime/solver/lanczos.hpp:23 — COO rows/cols/vals in,
    eigenpairs out.)"""
    from raft_tpu.sparse.solver.lanczos import lanczos_compute_eigenpairs
    from raft_tpu.sparse.solver.lanczos_types import LANCZOS_WHICH, LanczosSolverConfig

    res = ensure_resources(res)
    A = COOMatrix(jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32),
                  jnp.asarray(vals), (n, n))
    config = LanczosSolverConfig(
        n_components=n_components, max_iterations=max_iterations, ncv=ncv,
        tolerance=tolerance, which=LANCZOS_WHICH[which], seed=seed)
    return lanczos_compute_eigenpairs(res, A, config, v0=v0)


def randomized_svds(res, indptr, indices, vals, shape: Tuple[int, int],
                    n_components: int, n_oversamples: int = 10,
                    n_power_iters: int = 2, seed: int = 42):
    """Flat-argument sparse randomized SVD entry.
    (ref: raft_runtime ``randomized_svds`` float/double instantiations.)"""
    from raft_tpu.sparse.solver.randomized_svds import SvdsConfig
    from raft_tpu.sparse.solver.randomized_svds import randomized_svds as _svds

    res = ensure_resources(res)
    shape = tuple(int(s) for s in shape)
    cfg = SvdsConfig(n_components=n_components, n_oversamples=n_oversamples,
                     n_power_iters=n_power_iters, seed=seed)

    def run(ip, ix, v):
        return _svds(res, CSRMatrix(ip, ix, v, shape), cfg)

    return _aot_call(
        res, "randomized_svds",
        (shape, n_components, n_oversamples, n_power_iters, seed), run,
        jnp.asarray(indptr, jnp.int32), jnp.asarray(indices, jnp.int32),
        jnp.asarray(vals))


def rmat_rectangular_generator(res, theta, r_scale: int, c_scale: int,
                               n_edges: int, seed: int = 42):
    """(ref: raft_runtime/random/rmat_rectangular_generator.hpp — the 4
    type-combo instantiations collapse into one dtype-generic entry.)"""
    from raft_tpu.random.rmat import rmat_rectangular_gen
    from raft_tpu.random.rng_state import RngState

    res = ensure_resources(res)
    if theta is None:
        def run_default():
            return rmat_rectangular_gen(res, RngState(seed), n_edges,
                                        r_scale, c_scale)

        return _aot_call(res, "rmat_rectangular_generator",
                         (r_scale, c_scale, n_edges, seed, "default"),
                         run_default)

    def run(th):
        return rmat_rectangular_gen(res, RngState(seed), n_edges, r_scale,
                                    c_scale, theta=th)

    return _aot_call(res, "rmat_rectangular_generator",
                     (r_scale, c_scale, n_edges, seed), run,
                     jnp.asarray(theta, jnp.float32))
