"""Runtime entry points (the libraft.so surface).

(ref: cpp/include/raft_runtime/solver/lanczos.hpp:23 ``lanczos_solver``;
raft_runtime/random/rmat_rectangular_generator.hpp; the randomized_svds
instantiations in cpp/src. See package docstring for the AOT-cache design.)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import device_errors
from raft_tpu.core.resources import ensure_resources
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.resilience import fault_point, run_with_policy


def _aot_call(res, name: str, statics: tuple, fn, *args):
    """AOT lower+compile ``fn`` once per (entry, statics, arg shapes) and
    reuse the executable from the handle's CompileCache — the TPU-native
    analog of the reference's precompiled libraft.so instantiations
    (ref: cpp/CMakeLists.txt:275-309). ``res.compile_cache.hits`` counts
    reuse (tested in tests/test_runtime_aot.py).

    Every compile miss also records the executable's static cost — XLA
    ``cost_analysis`` FLOPs/bytes and ``memory_analysis`` peak HBM — into
    ``res.profiler``, keyed by the same (entry, statics, shapes, sharding)
    signature as the cache, so roofline attribution covers every runtime
    entry without a second lowering (cache hits reuse the stored record).

    Resilience contract: compile AND dispatch run inside
    ``device_errors`` — callers never see raw jaxlib exceptions, only
    the classified taxonomy (OutOfMemoryError / DeviceError /
    DeadlineExceededError) — and the whole attempt is retried under the
    handle's ``runtime`` RetryPolicy (a failed compile is NOT cached,
    so a retry recompiles). Fault sites: ``aot_compile`` (inside the
    compile miss) and ``aot_dispatch`` (before every execution).
    Dispatch is async — an OOM XLA reports at completion time surfaces
    at the caller's sync point, already classified if the caller syncs
    through ``res.sync``/``device_errors``."""
    args = tuple(jnp.asarray(a) for a in args)
    # sharding/placement is part of the compiled executable's signature —
    # a cache hit with differently-committed args would raise at dispatch
    key = (name, statics,
           tuple((a.shape, str(a.dtype),
                  str(getattr(a, "sharding", None))) for a in args))

    def _compile():
        import time

        fault_point("aot_compile")
        t0 = time.perf_counter()
        with device_errors(f"{name} [compile]"):
            compiled = jax.jit(fn).lower(*args).compile()
        # compile wall time: timeline event + histogram on the COMPILE
        # bucket preset (DEFAULT_TIME_BUCKETS tops out at 30 s — a cold
        # north-star compile can exceed it; the preset reaches 300 s)
        try:
            from raft_tpu.observability.metrics import (
                COMPILE_TIME_BUCKETS, get_registry)
            from raft_tpu.observability.timeline import emit_compile

            dt = time.perf_counter() - t0
            emit_compile(name, seconds=dt, hit=False)
            get_registry().histogram(
                "raft_tpu_compile_seconds", {"entry": name},
                help="AOT compile wall time (compile bucket preset)",
                buckets=COMPILE_TIME_BUCKETS).observe(dt)
        except Exception:
            pass
        try:
            res.profiler.capture(name, compiled, key=str(key[1:]))
        except Exception:
            pass  # cost capture must never fail the entry point
        return compiled

    def _attempt(attempt):
        compiled = res.compile_cache.get_or_compile(key, _compile)
        fault_point("aot_dispatch")
        try:
            from raft_tpu.observability.timeline import emit_dispatch

            emit_dispatch(name)
        except Exception:
            pass
        with device_errors(name):
            return compiled(*args)

    return run_with_policy(f"runtime.{name}", _attempt,
                           policy=res.resilience.policy_for("runtime"))


def lanczos_solver(res, rows, cols, vals, n: int, n_components: int,
                   max_iterations: int = 1000, ncv: Optional[int] = None,
                   tolerance: float = 1e-6, which: str = "SA", seed: int = 42,
                   v0=None) -> Tuple[jax.Array, jax.Array]:
    """Flat-argument Lanczos entry (the ABI the Cython layer called).
    (ref: raft_runtime/solver/lanczos.hpp:23 — COO rows/cols/vals in,
    eigenpairs out.)"""
    from raft_tpu.sparse.solver.lanczos import lanczos_compute_eigenpairs
    from raft_tpu.sparse.solver.lanczos_types import LANCZOS_WHICH, LanczosSolverConfig

    res = ensure_resources(res)
    A = COOMatrix(jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32),
                  jnp.asarray(vals), (n, n))
    config = LanczosSolverConfig(
        n_components=n_components, max_iterations=max_iterations, ncv=ncv,
        tolerance=tolerance, which=LANCZOS_WHICH[which], seed=seed)
    return lanczos_compute_eigenpairs(res, A, config, v0=v0)


def randomized_svds(res, indptr, indices, vals, shape: Tuple[int, int],
                    n_components: int, n_oversamples: int = 10,
                    n_power_iters: int = 2, seed: int = 42):
    """Flat-argument sparse randomized SVD entry.
    (ref: raft_runtime ``randomized_svds`` float/double instantiations.)"""
    from raft_tpu.sparse.solver.randomized_svds import SvdsConfig
    from raft_tpu.sparse.solver.randomized_svds import randomized_svds as _svds

    res = ensure_resources(res)
    shape = tuple(int(s) for s in shape)
    cfg = SvdsConfig(n_components=n_components, n_oversamples=n_oversamples,
                     n_power_iters=n_power_iters, seed=seed)

    def run(ip, ix, v):
        return _svds(res, CSRMatrix(ip, ix, v, shape), cfg)

    return _aot_call(
        res, "randomized_svds",
        (shape, n_components, n_oversamples, n_power_iters, seed), run,
        jnp.asarray(indptr, jnp.int32), jnp.asarray(indices, jnp.int32),
        jnp.asarray(vals))


def rmat_rectangular_generator(res, theta, r_scale: int, c_scale: int,
                               n_edges: int, seed: int = 42):
    """(ref: raft_runtime/random/rmat_rectangular_generator.hpp — the 4
    type-combo instantiations collapse into one dtype-generic entry.)"""
    from raft_tpu.random.rmat import rmat_rectangular_gen
    from raft_tpu.random.rng_state import RngState

    res = ensure_resources(res)
    if theta is None:
        def run_default():
            return rmat_rectangular_gen(res, RngState(seed), n_edges,
                                        r_scale, c_scale)

        return _aot_call(res, "rmat_rectangular_generator",
                         (r_scale, c_scale, n_edges, seed, "default"),
                         run_default)

    def run(th):
        return rmat_rectangular_gen(res, RngState(seed), n_edges, r_scale,
                                    c_scale, theta=th)

    return _aot_call(res, "rmat_rectangular_generator",
                     (r_scale, c_scale, n_edges, seed), run,
                     jnp.asarray(theta, jnp.float32))
