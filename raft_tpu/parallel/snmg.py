"""Single-controller multi-device handle (the SNMG pattern).

(ref: cpp/include/raft/core/device_resources_snmg.hpp:36-154 ``class
device_resources_snmg`` — a vector of per-GPU resources + root rank +
device setter; core/resource/multi_gpu.hpp; core/device_setter.hpp. Under
JAX's single controller, per-device handles exist for host-side bookkeeping
while computation runs SPMD over the mesh, so this handle owns BOTH: one
child ``DeviceResources`` per device and the shared mesh/comms.)
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from raft_tpu.core.error import expects
from raft_tpu.core.resource_types import ResourceType
from raft_tpu.core.resources import DeviceResources
from raft_tpu.comms.host_comms import HostComms


class DeviceResourcesSNMG(DeviceResources):
    """(ref: device_resources_snmg.hpp:36)"""

    def __init__(self, devices: Optional[Sequence] = None, root_rank: int = 0,
                 seed: int = 0):
        devs = list(devices) if devices is not None else jax.devices()
        expects(len(devs) >= 1, "SNMG: need at least one device")
        expects(0 <= root_rank < len(devs), "SNMG: bad root rank")
        super().__init__(device=devs[root_rank], seed=seed)
        self._devices = devs
        self._children: List[DeviceResources] = [
            DeviceResources(device=d, seed=seed + i) for i, d in enumerate(devs)
        ]
        mesh = Mesh(np.array(devs), ("x",))
        self.set_mesh(mesh)
        self.set_comms(HostComms(mesh, "x"))
        self.set_resource(ResourceType.ROOT_RANK, root_rank)
        self.set_resource(ResourceType.MULTI_DEVICE, devs)

    @property
    def root_rank(self) -> int:
        return self.get_resource(ResourceType.ROOT_RANK)

    def device_resources(self, rank: int) -> DeviceResources:
        """Per-device child handle. (ref: snmg ``set_device``/operator[])"""
        return self._children[rank]

    def device_count(self) -> int:
        return len(self._devices)

    def is_root_rank(self, rank: int) -> bool:
        return rank == self.root_rank
