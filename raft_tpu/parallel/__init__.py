"""raft_tpu.parallel — mesh/sharding helpers + SNMG handle. (ref: the
reference's MNMG machinery, SURVEY §2.12.)"""

from raft_tpu.parallel.mesh import (
    make_mesh,
    submesh,
    shard_rows,
    replicated,
    shard_array,
)
from raft_tpu.parallel.snmg import DeviceResourcesSNMG

__all__ = [
    "make_mesh", "submesh", "shard_rows", "replicated", "shard_array",
    "DeviceResourcesSNMG",
]
