"""Mesh / sharding helpers.

(ref: the reference's device-topology machinery — raft-dask worker→rank
mapping (comms.py:144 ``worker_info``), SNMG per-device resources
(core/device_resources_snmg.hpp:36), sub-communicator grids
(core/resource/sub_comms.hpp). TPU-native: a ``jax.sharding.Mesh`` over
named axes IS the topology; these helpers build meshes, sub-meshes, and
shardings the way the reference builds cliques and sub-cliques.)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from raft_tpu.core.error import expects


def make_mesh(shape: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh. ``shape`` maps axis name → size (one '-1' entry
    may infer its size from the device count). Default: 1-D "x" mesh over
    all devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if not shape:
        return Mesh(np.array(devs), ("x",))
    names = tuple(shape.keys())
    sizes = list(shape.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devs) // known
    expects(int(np.prod(sizes)) == len(devs),
            "make_mesh: shape %s != %d devices", dict(zip(names, sizes)), len(devs))
    return Mesh(np.array(devs).reshape(sizes), names)


def submesh(mesh: Mesh, axis: str, index: int) -> Mesh:
    """The sub-mesh at a fixed coordinate of ``axis`` — comm_split with a
    static color. (ref: core/comms.hpp:123 ``comm_split``)"""
    expects(axis in mesh.axis_names, "submesh: unknown axis %r", axis)
    ax = mesh.axis_names.index(axis)
    devs = np.take(mesh.devices, index, axis=ax)
    names = tuple(n for n in mesh.axis_names if n != axis)
    return Mesh(devs, names)


def shard_rows(mesh: Mesh, axis: str = "x") -> NamedSharding:
    """Rank-shard axis 0 (the OPG data model — one shard per rank)."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_array(x, mesh: Mesh, axis: str = "x"):
    """Place a host array rank-sharded over the mesh."""
    return jax.device_put(x, shard_rows(mesh, axis))
