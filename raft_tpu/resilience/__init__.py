"""raft_tpu.resilience — fault injection, recovery policies, deadlines.

The robustness layer the reference expresses as ``raft::interruptible``
+ ``RAFT_EXPECTS``/``RAFT_CUDA_TRY`` + NCCL abort/timeout handling,
grown into a testable subsystem:

- :mod:`~raft_tpu.resilience.faults` — named injection sites armed via
  ``RAFT_TPU_FAULTS`` (deterministic nth-call / seeded-probabilistic
  triggers), so OOM, device errors, collective timeout/hang, corrupt
  persistent reads and NaN poisoning are all simulable at every hot
  path. Statically gated by ``tools/check_instrumented.py``.
- :mod:`~raft_tpu.resilience.policy` — bounded retry with backoff
  (:func:`run_with_policy`, per-site :class:`RetryPolicy` via the
  ``res.resilience`` slot) and the graceful-degradation ladders
  (:func:`fused_degradation_ladder` for OOM,
  :func:`degrade_merge` for collective failure), every step counted in
  the metrics registry.
- :mod:`~raft_tpu.resilience.deadline` — :func:`deadline` scopes that
  convert hangs into :class:`~raft_tpu.core.error.DeadlineExceededError`
  (with the active span stack) via the interruptible token.

With ``RAFT_TPU_FAULTS`` unset and no deadline armed, the whole layer
is null-object pass-through: one boolean check per fault site, zero
extra dispatches, identical compile-cache behavior.
"""

from raft_tpu.core.error import (DeadlineExceededError, classify_xla_error,
                                 device_errors)
from raft_tpu.resilience.deadline import deadline
from raft_tpu.resilience.faults import (DATA_KINDS, FAULT_KINDS,
                                        INJECTIONS, KNOWN_SITES, FaultSpec,
                                        InjectedDeviceError, InjectedFault,
                                        InjectedOutOfMemory, InjectedTimeout,
                                        clear as clear_faults,
                                        configure as configure_faults,
                                        active as faults_active,
                                        fault_point, parse_faults)
from raft_tpu.resilience.policy import (DEGRADATIONS, EXHAUSTED,
                                        MERGE_LADDER, POISONED, RETRIES,
                                        FusedRung, PoisonedOutputError,
                                        PolicyTable, RetryPolicy,
                                        degradation_count,
                                        degradation_reasons,
                                        degrade_merge,
                                        fused_degradation_ladder,
                                        get_policy_table, record_degradation,
                                        record_exhausted, record_retry,
                                        run_with_policy)

__all__ = [
    "DATA_KINDS",
    "FAULT_KINDS",
    "INJECTIONS",
    "KNOWN_SITES",
    "FaultSpec",
    "InjectedDeviceError",
    "InjectedFault",
    "InjectedOutOfMemory",
    "InjectedTimeout",
    "clear_faults",
    "configure_faults",
    "faults_active",
    "fault_point",
    "parse_faults",
    "DeadlineExceededError",
    "classify_xla_error",
    "device_errors",
    "deadline",
    "DEGRADATIONS",
    "EXHAUSTED",
    "MERGE_LADDER",
    "POISONED",
    "RETRIES",
    "FusedRung",
    "PoisonedOutputError",
    "PolicyTable",
    "RetryPolicy",
    "degradation_count",
    "degradation_reasons",
    "degrade_merge",
    "fused_degradation_ladder",
    "get_policy_table",
    "record_degradation",
    "record_exhausted",
    "record_retry",
    "run_with_policy",
]
