"""Recovery policies: bounded retry, and the graceful-degradation ladders.

Two recovery shapes, both observable and both terminating:

- **Retry** (:func:`run_with_policy`): re-run the same work a bounded
  number of times with optional backoff — right for transient device
  errors and for nth-call injected faults. Every retry is counted
  (``raft_tpu_recovery_retries_total{site}``), exhaustion is counted
  and re-raises the last classified error. A
  :class:`~raft_tpu.core.error.DeadlineExceededError` is NEVER retried:
  a deadline is the caller's global budget, not a transient.
- **Degrade** (:func:`fused_degradation_ladder` /
  :func:`degrade_merge`): when the failure is structural (HBM
  exhaustion, a collective that keeps failing), retrying the same
  program cannot help — instead walk a finite ladder of configurations
  that trade speed for survival, each rung re-validated against the
  production fit predicate (``_valid_cfg`` + ``fit_config`` unshrunk)
  and each step counted under
  ``raft_tpu_degradations_total{site,action}``. Correctness is part of
  the ladder contract: every rung returns bit-identical ids to the
  undegraded oracle (values within the pack-perturbation bound) — the
  ladder-equality tests in tests/test_resilience.py pin that down.

The fused ladder order (cheapest give-up first):

1. halve ``Qb`` (pure throughput knob — certificate untouched);
2. halve ``T`` (smaller tiles, weaker streaming);
3. halve ``g`` (smaller certificate groups → bigger candidate pool);
4. ``grid_order`` db/dbuf → "query" (the packed database-major kernels
   give way to the general query-major pipeline — the packed→unpacked
   rung);
5. double ``micro_batches`` (sharded path only: smaller per-block
   footprint, more merge rounds).

``tools/bench_report.py --check`` refuses to gate (or baseline) any
round whose artifact recorded a nonzero degradation counter — perf
evidence from a degraded run is history, not a baseline.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Dict, Iterator, Optional, Tuple

from raft_tpu.core import env
from raft_tpu.core.error import (DeadlineExceededError, DeviceError,
                                 OutOfMemoryError, device_errors)

RETRIES = "raft_tpu_recovery_retries_total"
EXHAUSTED = "raft_tpu_recovery_exhausted_total"
DEGRADATIONS = "raft_tpu_degradations_total"
POISONED = "raft_tpu_output_poisoned_total"


class PoisonedOutputError(DeviceError):
    """Output validation found non-finite values where the contract
    promises finite ones (NaN poisoning — silent data corruption made
    loud). Recovered by bounded retry, not by degradation: the config
    was fine, the run was not."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry parameters for one site. ``retry_on`` must name
    taxonomy classes (see core.error) — raw jaxlib exceptions are
    classified before matching."""

    max_retries: int = 2
    backoff_s: float = 0.0
    backoff_mult: float = 2.0
    retry_on: Tuple[type, ...] = (OutOfMemoryError, DeviceError)


DEFAULT_POLICY = RetryPolicy()

# site (or site prefix before the first dot) → policy
DEFAULT_POLICIES: Dict[str, RetryPolicy] = {
    "runtime": RetryPolicy(max_retries=2),
    "distance.knn_fused_sharded": RetryPolicy(max_retries=2),
}


class PolicyTable:
    """Per-handle recovery-policy registry — the ``res.resilience``
    resource slot. Lookup falls back site → site's first dotted prefix
    → :data:`DEFAULT_POLICY`; ``RAFT_TPU_RETRY_MAX`` (env) caps
    ``max_retries`` globally (0 disables retries entirely — every
    failure surfaces on the first attempt)."""

    def __init__(self, overrides: Optional[Dict[str, RetryPolicy]] = None):
        self._policies: Dict[str, RetryPolicy] = dict(DEFAULT_POLICIES)
        if overrides:
            self._policies.update(overrides)

    def set_policy(self, site: str, policy: RetryPolicy) -> None:
        self._policies[site] = policy

    def policy_for(self, site: str) -> RetryPolicy:
        pol = self._policies.get(site)
        if pol is None:
            pol = self._policies.get(site.split(".")[0], DEFAULT_POLICY)
        cap = env.get("RAFT_TPU_RETRY_MAX")
        if cap is not None:
            pol = dataclasses.replace(pol,
                                      max_retries=max(0, int(cap)))
        return pol


_global_table: Optional[PolicyTable] = None
_table_lock = threading.Lock()


def get_policy_table() -> PolicyTable:
    """Process-default policy table (the RESILIENCE slot's default)."""
    global _global_table
    with _table_lock:
        if _global_table is None:
            _global_table = PolicyTable()
        return _global_table


def _registry():
    from raft_tpu.observability import get_registry

    return get_registry()


def record_retry(site: str, error: BaseException,
                 attempt: int = 0) -> None:
    try:
        from raft_tpu.observability.timeline import emit_retry

        reg = _registry()
        reg.counter(RETRIES, {"site": site},
                    help="Recovery retries, by site").inc()
        reg.emit({"type": "retry", "site": site, "attempt": attempt,
                  "error": f"{type(error).__name__}: {error}"[:200]})
        emit_retry(site, attempt, f"{type(error).__name__}: {error}")
    except Exception:
        pass


def record_exhausted(site: str) -> None:
    try:
        _registry().counter(
            EXHAUSTED, {"site": site},
            help="Recovery attempts that ran out of retries").inc()
    except Exception:
        pass


def record_degradation(site: str, action: str) -> None:
    """Count one ladder step. ``action`` is a stable machine-readable
    label like ``merge:tournament->allgather`` or ``fit:Qb:256->128``.
    Also emitted as a ``degradation`` timeline event, so ladder walks
    are visible in a Perfetto trace — not just counters."""
    try:
        from raft_tpu.observability.timeline import emit_degradation

        reg = _registry()
        reg.counter(DEGRADATIONS, {"site": site, "action": action},
                    help="Graceful-degradation ladder steps taken").inc()
        reg.emit({"type": "degradation", "site": site, "action": action})
        emit_degradation(site, action)
    except Exception:
        pass
    from raft_tpu.core.logger import log_warn

    log_warn("resilience: degrading %s (%s)", site, action)


def degradation_count(registry=None) -> float:
    """Total degradation-ladder steps recorded in ``registry`` (default:
    the process-global one) — stamped into BENCH artifacts so
    ``bench_report --check`` can refuse degraded evidence."""
    reg = registry if registry is not None else _registry()
    total = 0.0
    for metric in reg.collect():
        if getattr(metric, "name", None) == DEGRADATIONS:
            total += metric.value
    return total


def degradation_reasons(registry=None) -> list:
    """The recorded ladder steps as ``"site:action ×count"`` strings —
    the evidence a NAMED-artifact refresh prints when it REFUSES to
    overwrite committed evidence with a degraded round (see
    ``benchmarks/bench_ann.py``)."""
    reg = registry if registry is not None else _registry()
    out = []
    for metric in reg.collect():
        if getattr(metric, "name", None) != DEGRADATIONS:
            continue
        if metric.value <= 0:
            continue
        labels = getattr(metric, "labels", {}) or {}
        site = labels.get("site", "?")
        action = labels.get("action", "?")
        out.append(f"{site}:{action} x{metric.value:g}")
    return sorted(out)


def run_with_policy(site: str, fn: Callable[[int], object],
                    policy: Optional[RetryPolicy] = None,
                    on_retry: Optional[Callable] = None):
    """Run ``fn(attempt)`` under ``policy``: device-layer exceptions are
    classified into the raft taxonomy, matching ones are retried up to
    ``max_retries`` with backoff, and exhaustion re-raises the last
    classified error. Deadline errors always propagate immediately."""
    if policy is None:
        policy = get_policy_table().policy_for(site)
    attempt = 0
    delay = policy.backoff_s
    while True:
        try:
            with device_errors(site):
                return fn(attempt)
        except DeadlineExceededError:
            raise
        except policy.retry_on as e:
            attempt += 1
            if attempt > policy.max_retries:
                record_exhausted(site)
                raise
            record_retry(site, e, attempt)
            from raft_tpu.core.logger import log_warn

            log_warn("resilience: %s failed (%s: %s) — retry %d/%d",
                     site, type(e).__name__, str(e)[:120], attempt,
                     policy.max_retries)
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                time.sleep(delay)
                delay *= policy.backoff_mult


# ---------------------------------------------------------------------
# degradation ladders
# ---------------------------------------------------------------------

#: collective-failure ladder for the sharded merge: butterfly rounds →
#: one all-gather → no collective at all (per-shard candidates gathered
#: and merged on host). Every rung is deterministic rank-major, so the
#: merged ids stay bit-identical across rungs.
MERGE_LADDER = ("tournament", "allgather", "host")


def degrade_merge(strategy: str) -> Optional[str]:
    """Next rung down the merge ladder, or None at the bottom."""
    try:
        i = MERGE_LADDER.index(strategy)
    except ValueError:
        return None
    return MERGE_LADDER[i + 1] if i + 1 < len(MERGE_LADDER) else None


@dataclasses.dataclass(frozen=True)
class FusedRung:
    """One validated rung of the fused OOM ladder."""

    T: int
    Qb: int
    g: int
    grid_order: str
    micro_batches: int
    action: str          # what changed vs the previous rung


def fused_degradation_ladder(T: int, Qb: int, g: int, grid_order: str,
                             d: int, passes: int,
                             micro_batches: int = 1,
                             max_micro_batches: int = 64
                             ) -> Iterator[FusedRung]:
    """Yield successively degraded fused configs (see module doc for
    the rung order). Every yielded rung passes the PRODUCTION validity
    chain — ``_valid_cfg`` and ``fit_config`` unshrunk at feature width
    ``d`` — so the runtime never silently reshapes a rung it is handed;
    invalid intermediate points are skipped, and the generator is
    finite (each knob shrinks monotonically), so the ladder always
    terminates."""
    from raft_tpu.distance.knn_fused import (_LANES, GRID_ORDERS,
                                             _valid_cfg, fit_config)

    if grid_order not in GRID_ORDERS:
        raise ValueError(f"grid_order must be one of {GRID_ORDERS}, "
                         f"got {grid_order!r}")

    def _ok(T_, Qb_, g_, order_):
        return (_valid_cfg(T_, Qb_, g_, order_)
                and fit_config(T_, Qb_, d, passes, g_, order_) == (T_, Qb_))

    cur = dict(T=T, Qb=Qb, g=g, grid_order=grid_order,
               micro_batches=micro_batches)
    while cur["Qb"] > 8:
        new = max(8, (cur["Qb"] // 2) // 8 * 8)
        action = f"fit:Qb:{cur['Qb']}->{new}"
        cur["Qb"] = new
        if _ok(cur["T"], cur["Qb"], cur["g"], cur["grid_order"]):
            yield FusedRung(action=action, **cur)
    while cur["T"] > 2 * _LANES:
        new = max(2 * _LANES, (cur["T"] // 2) // _LANES * _LANES)
        action = f"fit:T:{cur['T']}->{new}"
        cur["T"] = new
        if _ok(cur["T"], cur["Qb"], cur["g"], cur["grid_order"]):
            yield FusedRung(action=action, **cur)
    while cur["g"] > 1:
        new = max(1, cur["g"] // 2)
        action = f"fit:g:{cur['g']}->{new}"
        cur["g"] = new
        if _ok(cur["T"], cur["Qb"], cur["g"], cur["grid_order"]):
            yield FusedRung(action=action, **cur)
    if cur["grid_order"] in ("db", "dbuf"):
        action = f"fit:grid_order:{cur['grid_order']}->query"
        cur["grid_order"] = "query"
        if _ok(cur["T"], cur["Qb"], cur["g"], cur["grid_order"]):
            yield FusedRung(action=action, **cur)
    while cur["micro_batches"] < max_micro_batches:
        new = cur["micro_batches"] * 2
        action = f"fit:micro_batches:{cur['micro_batches']}->{new}"
        cur["micro_batches"] = new
        yield FusedRung(action=action, **cur)
