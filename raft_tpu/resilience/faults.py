"""Fault injection: named sites, deterministic triggers, one env knob.

Every hot path in the port carries a named *injection site* — a
``fault_point("<site>")`` call in its non-jitted wrapper — so compile
OOM, dispatch ``RESOURCE_EXHAUSTED``, collective timeout/hang, corrupt
cache/tune-table reads, and NaN poisoning can all be simulated
deterministically, without touching the code under test. (ref: the
reference frames robustness as core vocabulary — ``RAFT_EXPECTS`` /
``RAFT_CUDA_TRY`` / ``raft::interruptible``; fault *injection* is the
missing half that makes those paths testable, the role nccl-tests'
abort harness plays for NCCL.)

DSL (env ``RAFT_TPU_FAULTS``, or :func:`configure` from tests)::

    site:kind[@call=N][:p=F] [; site:kind ...]

    RAFT_TPU_FAULTS="aot_compile:oom@call=2;merge_permute:timeout:p=1.0"

- ``kind`` ∈ :data:`FAULT_KINDS`:
  ``oom``      → raises :class:`InjectedOutOfMemory` (classifies like a
                 RESOURCE_EXHAUSTED XlaRuntimeError);
  ``error``    → raises :class:`InjectedDeviceError` (INTERNAL analog);
  ``timeout``  → raises :class:`InjectedTimeout` (collective timeout —
                 a recoverable DeviceError, NOT a deadline);
  ``hang``     → blocks in an interruptible poll loop until cancelled —
                 a :func:`raft_tpu.resilience.deadline` scope converts
                 it into ``DeadlineExceededError``; a safety cap
                 (``RAFT_TPU_FAULT_HANG_MAX_S``, default 30 s) raises
                 InjectedTimeout so an unguarded test can't hang CI;
  ``corrupt``/``nan`` → do NOT raise: ``fault_point`` returns the kind
                 string and the site applies it (treat a cache read as
                 torn, poison kernel output) — the site owns the data
                 plane, the registry owns the trigger.
- triggers: bare kind = every call; ``@call=N`` = exactly the Nth call
  to that site (1-based — the deterministic inject-then-recover
  pattern); ``p=F`` = per-call Bernoulli, derandomized by hashing
  (site, kind, call index, ``RAFT_TPU_FAULTS_SEED``) — the same seed
  replays the same fault schedule.

With no faults configured the whole layer is a single module-global
boolean check per site — the zero-overhead null-object contract the
no-fault parity tests pin down.

Injections are counted (``raft_tpu_fault_injections_total{site,kind}``)
and emitted as ``fault`` events through the observability registry.
``tools/check_instrumented.py``'s ``FAULT_SITES`` gate statically
asserts every hot-path module keeps its sites — a new hot path cannot
ship uninjectable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from raft_tpu.core import env
from raft_tpu.core.error import DeviceError, OutOfMemoryError

FAULT_KINDS = ("oom", "error", "timeout", "hang", "corrupt", "nan")
#: kinds fault_point RETURNS (site applies them) instead of raising
DATA_KINDS = ("corrupt", "nan")

INJECTIONS = "raft_tpu_fault_injections_total"

#: site name → kinds that are meaningful there (advisory — the matrix
#: test iterates this; ``fault_point`` accepts any registered name).
#: tools/check_instrumented.py's FAULT_SITES table is the STATIC mirror
#: of this registry (per defining module); a test pins them consistent.
KNOWN_SITES: Dict[str, Tuple[str, ...]] = {
    # runtime entry points (_aot_call)
    "aot_compile": ("oom", "error"),
    "aot_dispatch": ("oom", "error", "nan"),
    # fused KNN, single-device and sharded
    "knn_fused": ("oom", "error"),
    # int8 index quantization at build time (prepare_knn_index /
    # build_ivf_flat with db_dtype="int8"): a failing quantize must
    # surface at build, never as a silently-bf16 index
    "quantize_index": ("error",),
    "sharded_dispatch": ("oom", "error", "nan"),
    "merge_permute": ("oom", "error", "timeout", "hang"),
    "merge_allgather": ("oom", "error", "timeout", "hang"),
    # select / distance / sparse / solver hot paths
    "select_k": ("oom", "error"),
    "select_k_chunked": ("oom", "error"),
    "select_k_slotted": ("oom", "error"),
    "pairwise_distance": ("oom", "error"),
    "fused_l2nn": ("oom", "error"),
    "tile_csr": ("oom", "error"),
    "spmv_sharded": ("oom", "error"),
    "solve_lap": ("oom", "error"),
    # clustering + ANN tier (raft_tpu.cluster / raft_tpu.ann): the
    # fit entry + the per-Lloyd-iteration site, and the IVF index
    # build/search pair
    "kmeans_fit": ("oom", "error"),
    "kmeans_iteration": ("error",),
    "ivf_build": ("oom", "error"),
    "ivf_search": ("oom", "error"),
    # the list-major fine-scan dispatch (ISSUE 14): a failure here —
    # real or injected — must DEGRADE to the query-major scan with a
    # logged degradation and identical returned ids, never surface
    "fine_scan_list": ("error", "oom"),
    # the IVF-PQ compressed tier (ISSUE 15): a failing per-subspace
    # codebook train must surface at build (never a silently-flat
    # index), and a failing ADC dispatch must DEGRADE to the f32/int8
    # fine scan with a logged degradation and identical returned ids
    "pq_train": ("error",),
    "pq_scan": ("error", "oom"),
    # the PQ quality round (ISSUE 19): a failing OPQ rotation train
    # must surface at build (never a silently-unrotated index); a
    # failing widen-rung re-ADC must DEGRADE straight to the exact
    # rerun with a logged degradation and identical returned ids
    "opq_train": ("error",),
    "pq_widen": ("error", "oom"),
    # tuners + persistent stores
    "autotune_fused": ("error",),
    "autotune_sharded": ("error",),
    "autotune_fine_scan": ("error",),
    "tune_table_read": ("corrupt",),
    "plan_cache_read": ("corrupt",),
    # host-side comms
    "host_collective": ("oom", "error", "timeout", "hang"),
    "host_barrier": ("error", "timeout", "hang"),
    "host_sync": ("error", "hang"),
    # serving engine (raft_tpu.serving): admission at enqueue, the
    # batch flush (dispatch of a coalesced micro-batch), and the
    # background snapshot rebuild
    "serving_enqueue": ("error",),
    "serving_flush": ("oom", "error", "timeout", "hang"),
    "serving_snapshot": ("error",),
    # mutable indexes (raft_tpu.mutable): the delta-slab ingest, the
    # tombstone apply, and the background compaction fold — a crash at
    # any of them must leave the current snapshot serving (no torn
    # generation; pinned by tests/test_resilience.py)
    "mutate_ingest": ("error",),
    "tombstone_apply": ("error",),
    "compact_fold": ("oom", "error"),
    # durability plane (raft_tpu.mutable.wal / .checkpoint): the WAL
    # append + fsync pair and the checkpoint write / pointer-commit
    # pair — an injected failure at any of them must leave the index
    # state untouched and the on-disk state recoverable (the SIGKILL
    # crash matrix in tests/test_durability.py kills at the same
    # four sites)
    "wal_append": ("error",),
    "wal_fsync": ("error",),
    "checkpoint_write": ("error",),
    "manifest_commit": ("error",),
}


class InjectedFault:
    """Marker mixin: tells an injected failure apart from a real one
    (tests assert on it; recovery code must NOT — recovery treats
    injected and real failures identically, that is the point)."""


class InjectedOutOfMemory(OutOfMemoryError, InjectedFault):
    """Injected RESOURCE_EXHAUSTED."""


class InjectedDeviceError(DeviceError, InjectedFault):
    """Injected INTERNAL/ABORTED-class device failure."""


class InjectedTimeout(DeviceError, InjectedFault):
    """Injected collective timeout — recoverable (merge-ladder) device
    failure, deliberately NOT a DeadlineExceededError: a deadline is
    the caller's global budget and is never retried."""


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: site + kind + trigger (+ mutable call state)."""

    site: str
    kind: str
    nth_call: Optional[int] = None    # fire exactly on this call (1-based)
    probability: Optional[float] = None
    calls: int = 0
    fired: int = 0

    def should_fire(self, seed: int) -> bool:
        self.calls += 1
        if self.nth_call is not None:
            return self.calls == self.nth_call
        if self.probability is not None:
            h = hashlib.sha256(
                f"{self.site}|{self.kind}|{self.calls}|{seed}".encode()
            ).digest()
            draw = int.from_bytes(h[:8], "big") / float(1 << 64)
            return draw < self.probability
        return True


def parse_faults(spec: str) -> List[FaultSpec]:
    """Parse the fault DSL (see module doc). Raises ``ValueError`` on a
    malformed entry — callers that must not raise (the env loader)
    catch and log instead."""
    out: List[FaultSpec] = []
    for raw in spec.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        tokens = [t.strip() for t in entry.split(":")]
        if len(tokens) < 2:
            raise ValueError(f"fault entry {entry!r}: want site:kind[...]")
        site = tokens[0]
        kind_tok = tokens[1]
        nth = None
        if "@" in kind_tok:
            kind_tok, _, mod = kind_tok.partition("@")
            if not mod.startswith("call="):
                raise ValueError(f"fault entry {entry!r}: unknown "
                                 f"modifier {mod!r} (want @call=N)")
            nth = int(mod[len("call="):])
            if nth < 1:
                raise ValueError(f"fault entry {entry!r}: call index "
                                 f"must be ≥ 1")
        kind = kind_tok.strip().lower()
        if kind not in FAULT_KINDS:
            raise ValueError(f"fault entry {entry!r}: kind {kind!r} not "
                             f"in {FAULT_KINDS}")
        prob = None
        for extra in tokens[2:]:
            if extra.startswith("p="):
                prob = float(extra[2:])
                if not (0.0 <= prob <= 1.0):
                    raise ValueError(f"fault entry {entry!r}: p must be "
                                     f"in [0, 1]")
            elif extra.startswith("call="):
                nth = int(extra[len("call="):])
            elif extra:
                raise ValueError(f"fault entry {entry!r}: unknown "
                                 f"modifier {extra!r}")
        out.append(FaultSpec(site=site, kind=kind, nth_call=nth,
                             probability=prob))
    return out


_lock = threading.Lock()
_active: Dict[str, List[FaultSpec]] = {}
_armed = False          # module-global fast flag — THE no-fault fast path
_seed = 0


def _install(specs: List[FaultSpec], seed: Optional[int]) -> None:
    global _armed, _seed
    with _lock:
        _active.clear()
        for s in specs:
            _active.setdefault(s.site, []).append(s)
        if seed is not None:
            _seed = int(seed)
        _armed = bool(_active)


def configure(spec: str, seed: Optional[int] = None) -> List[FaultSpec]:
    """Arm faults programmatically (tests). Replaces the current set;
    raises on a malformed spec. Returns the installed specs (their
    mutable call state is live — tests can inspect ``fired``)."""
    specs = parse_faults(spec)
    _install(specs, seed)
    return specs


def clear() -> None:
    """Disarm all faults (back to the zero-overhead null-object mode)."""
    _install([], None)


def active() -> bool:
    """True when any fault is armed."""
    return _armed


def _load_env() -> None:
    spec = env.raw("RAFT_TPU_FAULTS") or ""
    seed = env.raw("RAFT_TPU_FAULTS_SEED")
    if not spec:
        return
    try:
        _install(parse_faults(spec), int(seed) if seed else None)
    except (ValueError, TypeError) as e:
        from raft_tpu.core.logger import log_error

        log_error("RAFT_TPU_FAULTS=%r is malformed (%s) — NO faults "
                  "armed", spec, e)


_load_env()


def _count_injection(site: str, kind: str) -> None:
    try:
        from raft_tpu.observability import get_registry
        from raft_tpu.observability.timeline import emit_fault

        reg = get_registry()
        reg.counter(INJECTIONS, {"site": site, "kind": kind},
                    help="Injected faults, by site and kind").inc()
        reg.emit({"type": "fault", "site": site, "kind": kind})
        emit_fault(site, kind)
    except Exception:
        pass


def _hang(site: str) -> None:
    """Block until cancelled (deadline/cancel) — the injectable
    collective hang. ``yield_`` raises out of the loop; the safety cap
    keeps an unguarded hang from freezing a suite forever."""
    from raft_tpu.core import interruptible

    max_s = env.get("RAFT_TPU_FAULT_HANG_MAX_S")
    t0 = time.monotonic()
    while time.monotonic() - t0 < max_s:
        interruptible.yield_()
        time.sleep(0.001)
    raise InjectedTimeout(
        f"injected hang at {site!r} gave up after {max_s}s with no "
        f"cancellation — guard it with resilience.deadline(...)")


def fault_point(site: str) -> Optional[str]:
    """The per-site injection hook. Returns None on the (overwhelmingly
    common) pass-through; raises for ``oom``/``error``/``timeout``
    (and ``hang``, via cancellation); returns ``"corrupt"``/``"nan"``
    for the data-plane kinds so the site applies the corruption
    itself. Thread-safe; call/fire state is per armed spec."""
    if not _armed:
        return None
    with _lock:
        specs = _active.get(site)
        if not specs:
            return None
        firing = None
        for s in specs:
            if s.should_fire(_seed):
                s.fired += 1
                firing = s
                break
        if firing is None:
            return None
    kind = firing.kind
    _count_injection(site, kind)
    from raft_tpu.core.logger import log_warn

    log_warn("fault injected: site=%s kind=%s (call %d)", site, kind,
             firing.calls)
    if kind == "oom":
        raise InjectedOutOfMemory(
            f"injected RESOURCE_EXHAUSTED at {site!r}")
    if kind == "error":
        raise InjectedDeviceError(f"injected INTERNAL error at {site!r}")
    if kind == "timeout":
        raise InjectedTimeout(f"injected collective timeout at {site!r}")
    if kind == "hang":
        _hang(site)
    return kind          # corrupt / nan — the site applies it
