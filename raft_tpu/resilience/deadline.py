"""Deadline scopes: convert hangs into typed, diagnosable errors.

(ref: core/interruptible.hpp — the reference converts a stuck stream
wait into ``interrupted_exception`` only when someone calls ``cancel``
from another thread; production NCCL deployments layer a watchdog on
top. :func:`deadline` IS that watchdog, packaged: a scope that arms the
calling thread's cancellation token from a timer thread, so every
cooperative cancellation point inside the scope —
``interruptible.synchronize``, ``interruptible.yield_``,
``HostComms.sync_stream``, ``HostComms.barrier``, an injected ``hang``
fault — raises :class:`~raft_tpu.core.error.DeadlineExceededError`
within one poll interval of expiry, carrying the thread's active span
stack for diagnosis.)

Usage::

    from raft_tpu.resilience import deadline

    with deadline(30.0, label="sharded-merge"):
        vals, ids = knn_fused_sharded(x, idx, k=64, mesh=mesh)
        res.sync(vals, ids)          # polling wait — cancellable

Scope semantics:

- The deadline binds to the CALLING thread's token; work dispatched to
  other threads is not covered (arm a scope per worker thread).
- Only cooperative cancellation points convert: a non-polling blocking
  call (``jax.block_until_ready``) cannot be interrupted mid-wait —
  use ``res.sync`` / ``interruptible.synchronize``, which poll.
- If the deadline fires while work is still running, the next
  cancellation point raises; if the body completes first the scope
  still raises at exit when the deadline has already expired (the
  budget WAS exceeded — honest semantics for SLO accounting). A scope
  that exits before expiry disarms its timer and is free.
- Scopes are RE-ENTRANT and thread-safe: they nest on one thread (the
  first-to-expire wins; a fired inner scope never clobbers an armed
  outer one, and exiting a scope only ever clears ITS OWN pending
  cancellation), and scopes on different threads are fully independent
  — tokens are thread-local, and every arm/fire/consume holds the
  token's lock, so concurrent request threads (the serving engine's
  batcher + client threads) cannot trample each other's watchdogs.
  Pinned by tests/test_resilience.py's concurrent-scope regression
  test.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

from raft_tpu.core import interruptible
from raft_tpu.core.error import expects


@contextlib.contextmanager
def deadline(seconds: float, label: Optional[str] = None) -> Iterator[None]:
    """Arm a watchdog that cancels this thread ``seconds`` from now.
    See the module doc for the exact scope semantics."""
    expects(seconds > 0, "deadline: seconds must be > 0 (got %s)",
            seconds)
    tok = interruptible.get_token()
    info = {"seconds": float(seconds), "label": label or "deadline"}
    fired = threading.Event()
    try:
        from raft_tpu.observability.timeline import emit_deadline

        emit_deadline(info["label"], info["seconds"], fired=False)
    except Exception:
        pass

    def _fire():
        # all under the token lock so the owning thread's check-and-
        # clear cannot interleave. Expiries queue in firing order —
        # the cancellation point reports the earliest, and each scope
        # removes only its own record at exit
        with tok.lock:
            tok.fired_deadlines.append(info)
            fired.set()
            tok.cancelled = True

    timer = threading.Timer(float(seconds), _fire)
    timer.daemon = True
    timer.start()
    try:
        yield
        # consume a deadline that fired after the last cancellation
        # point but before scope exit — the budget was exceeded
        interruptible.yield_()
    finally:
        timer.cancel()
        # un-poison the token if OUR deadline fired but was not
        # consumed (e.g. a different exception is propagating) — a
        # stale cancellation must not ambush the thread's next wait.
        # Only OUR arm record is removed: another scope's pending
        # expiry stays queued (and keeps the token cancelled).
        if fired.is_set():
            with tok.lock:
                try:
                    tok.fired_deadlines.remove(info)
                except ValueError:
                    pass        # already consumed by a yield_
                else:
                    if not tok.fired_deadlines:
                        tok.cancelled = False
