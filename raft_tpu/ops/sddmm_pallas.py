"""Blocked SDDMM kernel (Pallas/Mosaic) — the cusparse-SDDMM role on TPU.

(ref: sparse/linalg/sddmm.hpp:43 and the masked_matmul consumer
sparse/linalg/masked_matmul.cuh:47 — sampled dense-dense matmul at the
nonzero positions of a sparsity structure. The reference calls
cusparseSDDMM; GPUs gather A/B rows per nonzero. TPU-first re-design:
the structure is bucketed ONCE by (row tile × col tile)
(raft_tpu.sparse.tiled.tile_pairs), so each grid step owns E nonzeros
inside one [R, C] output block. The step contracts that block's dense
tile ``D = A_r @ B_cᵀ`` on the MXU — the FLOPs the op exists to do —
then folds per-entry values straight out of VMEM:

    Pt = Dᵀ-gather:  onehot_rows [R, EB] per sub-block — Pt[c, e] =
         D[row_local[e], c] as ONE MXU matmul (D contracted with the
         one-hot, exactly representable in bf16);
    out[e] = Σ_c [col_local[e] = c] · Pt[c, e] — a VPU masked reduce.

Pad entries carry row_local = R, whose one-hot column is all-zero, so
they contribute exact zeros. d (the contraction depth) is VMEM-bounded:
callers fall back to the XLA gather path past the envelope.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.utils import interpret_mode

_EB = 512    # entries folded per MXU gather step
MAX_D = 512  # A/B tile depth envelope (VMEM)


def _sddmm_kernel(rt_ref, ct_ref, a_ref, b_ref, rloc_ref, cloc_ref, out_ref,
                  dblk_ref, *, R: int, C: int, E: int):
    # The E axis is grid-blocked (see spmv_pallas layout note: in-kernel
    # vector slicing leaves illegal lane offsets for vector.broadcast on
    # Mosaic; full-block loads are offset-0). The dense [R, C] tile is
    # computed once per chunk (b == 0) into VMEM scratch that persists
    # across the chunk's sub-block steps.
    b = pl.program_id(1)

    @pl.when(b == 0)
    def _():
        dblk_ref[...] = jax.lax.dot_general(
            a_ref[0], b_ref[0], (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)          # [R, C]

    d_blk = dblk_ref[...]
    rloc = rloc_ref[0]                                   # [1, EB], pad = R
    cloc = cloc_ref[0]
    onehot_r = (jnp.broadcast_to(rloc, (R, _EB))
                == jax.lax.broadcasted_iota(jnp.int32, (R, _EB), 0)
                ).astype(jnp.float32)                    # [R, EB]
    # Pt[c, e] = Σ_r D[r, c]·onehot_r[r, e] = D[rloc[e], c]
    pt = jax.lax.dot_general(
        d_blk, onehot_r, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)              # [C, EB]
    mask = (jnp.broadcast_to(cloc, (C, _EB))
            == jax.lax.broadcasted_iota(jnp.int32, (C, _EB), 0))
    out_ref[0] = jnp.sum(jnp.where(mask, pt, 0.0), axis=0,
                         keepdims=True)                  # [1, EB]


@functools.partial(jax.jit, static_argnames=("R", "C", "E"))
def _sddmm_tiled_impl(a3, b3, row_local, col_local, chunk_row_tile,
                      chunk_col_tile, R: int, C: int, E: int) -> jax.Array:
    m_chunks = row_local.shape[0]
    d = a3.shape[2]
    nb = E // _EB
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(m_chunks, nb),
        in_specs=[
            pl.BlockSpec((1, R, d), lambda c, b, rt, ct: (rt[c], 0, 0),
                         memory_space=pltpu.VMEM),       # A row tile
            pl.BlockSpec((1, C, d), lambda c, b, rt, ct: (ct[c], 0, 0),
                         memory_space=pltpu.VMEM),       # Bt col tile
            pl.BlockSpec((1, 1, _EB), lambda c, b, rt, ct: (c, 0, b),
                         memory_space=pltpu.VMEM),       # row_local
            pl.BlockSpec((1, 1, _EB), lambda c, b, rt, ct: (c, 0, b),
                         memory_space=pltpu.VMEM),       # col_local
        ],
        out_specs=pl.BlockSpec((1, 1, _EB), lambda c, b, rt, ct: (c, 0, b),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((R, C), jnp.float32)],  # dense tile
    )
    return pl.pallas_call(
        functools.partial(_sddmm_kernel, R=R, C=C, E=E),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_chunks, 1, E), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_chunks * (R * C * d + R * C * E),
            bytes_accessed=m_chunks * ((R + C) * d * 4 + 3 * E * 4),
            transcendentals=0,
        ),
        interpret=interpret_mode(),
    )(chunk_row_tile, chunk_col_tile, a3, b3,
      row_local[:, None, :], col_local[:, None, :])


def sddmm_tiled(tiled, A, B) -> jax.Array:
    """Values of (A @ B) at ``tiled``'s nonzero positions, in the
    structure's ORIGINAL entry order. A [m, d], B [d, n];
    ``tiled`` is a :class:`raft_tpu.sparse.tiled.TiledPairs` over [m, n].
    """
    m, n = tiled.shape
    A = jnp.asarray(A, jnp.float32)
    B = jnp.asarray(B, jnp.float32)
    if A.ndim != 2 or B.ndim != 2 or A.shape[0] != m or B.shape[1] != n \
            or A.shape[1] != B.shape[0]:
        raise ValueError(
            f"sddmm_tiled: need A [{m}, d] @ B [d, {n}], got "
            f"{A.shape} @ {B.shape}")
    d = A.shape[1]
    if d > MAX_D:
        raise NotImplementedError(
            f"sddmm_tiled targets d <= {MAX_D} (VMEM tile); got {d}")
    # pad to tile grids; dpad keeps the MXU contraction lane-aligned
    dpad = (-d) % 128
    rpad = tiled.n_row_tiles * tiled.R - m
    cpad = tiled.n_col_tiles * tiled.C - n
    a3 = jnp.pad(A, ((0, rpad), (0, dpad))).reshape(
        tiled.n_row_tiles, tiled.R, d + dpad)
    b3 = jnp.pad(B.T, ((0, cpad), (0, dpad))).reshape(
        tiled.n_col_tiles, tiled.C, d + dpad)
    contrib = _sddmm_tiled_impl(
        a3, b3, tiled.row_local, tiled.col_local,
        tiled.chunk_row_tile, tiled.chunk_col_tile,
        R=tiled.R, C=tiled.C, E=tiled.E)
    return jnp.take(contrib.reshape(-1), tiled.pos)
