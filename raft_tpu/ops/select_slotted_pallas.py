"""Packed slotted select kernel — streaming top-k candidates (Pallas).

(ref: the role of matrix/detail/select_radix.cuh:639 /
select_warpsort.cuh:752 — stream the row once at memory bandwidth,
keeping per-bucket running minima in registers.)

This is :mod:`raft_tpu.ops.fused_l2_topk_pallas`'s packed group fold
with the MXU contraction removed: row tiles stream through VMEM and
merge into per-(lane, tile-group) packed top-2 + 3rd-min accumulators
(output blocks revisited across ``tpg`` consecutive tiles, candidate
codes in the low mantissa bits — see the PACKED block comment there).
One linear pass over the data; outputs are ~L/128 of the input. The
certified selection built on top lives in
raft_tpu.matrix.select_k_slotted.

The slots-per-group product ``tpg · (T/128)`` is pinned to the full
2^_PACK_BITS code space: the group kernel's measured-best configs sit
exactly there, and for pure selection there is no reason to waste code
space (fewer groups = smaller outputs = less pool work).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.fused_l2_topk_pallas import (
    _LANES, _PACK_BITS, _PACK_MASK, _PACK_PAD, _merge_chunk_top2_packed)
from raft_tpu.ops.utils import interpret_mode


def _select_kernel(v_ref, a1_ref, a2_ref, a3_ref,
                   *, T: int, Bb: int, tpg: int):
    j = pl.program_id(1)
    n_chunks = T // _LANES

    @pl.when(j % tpg == 0)
    def _():
        big = jnp.full((Bb, _LANES), _PACK_PAD, jnp.float32)
        a1_ref[...] = big
        a2_ref[...] = big
        a3_ref[...] = big

    b8 = Bb // 8
    a1 = a1_ref[...].reshape(b8, 8, _LANES)
    a2 = a2_ref[...].reshape(b8, 8, _LANES)
    a3 = a3_ref[...].reshape(b8, 8, _LANES)
    v = v_ref[...]                                       # [Bb, T]
    for r in range(n_chunks):
        sl = slice(r * _LANES, (r + 1) * _LANES)
        c = v[:, sl].reshape(b8, 8, _LANES)
        local = (j % tpg) * n_chunks + r                 # scalar code
        cp = jax.lax.bitcast_convert_type(
            (jax.lax.bitcast_convert_type(c, jnp.int32) & ~_PACK_MASK)
            | local, jnp.float32)
        a1, a2, a3 = _merge_chunk_top2_packed(cp, a1, a2, a3)
    a1_ref[...] = a1.reshape(Bb, _LANES)
    a2_ref[...] = a2.reshape(Bb, _LANES)
    a3_ref[...] = a3.reshape(Bb, _LANES)


@functools.partial(jax.jit, static_argnames=("T", "Bb", "tpg"))
def select_slot_topk_packed(v, T: int = 1024, Bb: int = 256,
                            tpg: int = 32):
    """Per-(lane, tile-group) packed top-2 + 3rd-min of ``v`` [B, L].

    Requirements (the caller — select_k_slotted — arranges all of
    them): L % T == 0, B % Bb == 0, padded entries hold the finite
    ``_PACK_PAD`` sentinel, |values| < _PACK_PAD/4 (rows violating this
    fail the downstream certificate and take the exact fallback), and
    tpg·(T/128) ≤ 2^_PACK_BITS. Returns (a1p, a2p, a3p), each
    ``[B, G·LANES]`` packed f32 with G = ceil(L/T/tpg); positions
    decode via distance.knn_fused.decode_packed_pool."""
    B, L = v.shape
    if L % T or B % Bb:
        raise ValueError(f"select_slot_topk_packed: L={L} % T={T} or "
                         f"B={B} % Bb={Bb} != 0")
    if tpg * (T // _LANES) > (1 << _PACK_BITS):
        raise ValueError("select_slot_topk_packed: packing envelope")
    n_tiles = L // T
    G = -(-n_tiles // tpg)
    spec_out = pl.BlockSpec((Bb, _LANES), lambda i, j: (i, j // tpg),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_select_kernel, T=T, Bb=Bb, tpg=tpg),
        grid=(B // Bb, n_tiles),
        in_specs=[pl.BlockSpec((Bb, T), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM)],
        out_specs=[spec_out] * 3,
        out_shape=[jax.ShapeDtypeStruct((B, G * _LANES), jnp.float32)] * 3,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * B * L, bytes_accessed=B * L * 4 + B * G * 128 * 12,
            transcendentals=0),
        interpret=interpret_mode(),
    )(v)
