"""Pallas select_k kernels (BITONIC streaming queue, RADIX histogram).

(ref: cpp/include/raft/matrix/detail/select_warpsort.cuh:752 block_kernel /
util/bitonic_sort.cuh, and matrix/detail/select_radix.cuuh:639 radix_kernel.
TPU re-design notes: no warp shuffles or SM atomics exist; the warpsort
queue becomes a VMEM-resident k-sized merge queue updated per VMEM block of
the row, and radix select becomes a multi-pass VPU histogram over bit
slices. See SURVEY §7 stage 3 / "hard parts" (a).)

Implemented in Stage I; callers fall back to XLA top_k until then.
"""

from __future__ import annotations


def select_k(in_val, in_idx, k: int, select_min: bool, algo=None):
    raise NotImplementedError("Pallas select_k lands in Stage I")
