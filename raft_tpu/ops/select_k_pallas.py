"""Pallas radix select_k — the TPU rendering of the reference's flagship
top-k kernels.

(ref: cpp/include/raft/matrix/detail/select_radix.cuh:639 ``radix_kernel``
— multi-pass 8-bit histogram filtering — and select_warpsort.cuh:752.
SURVEY §7 "hard parts" (a): TPU has no per-lane atomics or shared-memory
histograms, so the radix strategy is re-thought for VMEM + VPU/MXU.)

Design (one grid step per row; the row lives in VMEM as [L/128, 128]
tiles):
1. f32 keys bitcast to order-preserving uint32 ("sortable bits": negative
   → ~bits, positive → bits | 0x8000_0000; inverted for select-max).
2. Four MSB-first 8-bit digit passes. Each pass streams the VMEM-resident
   row in [Cr, 128] tiles, histograms digits with a broadcast one-hot
   compare+reduce (the VPU replacement for CUDA's atomic histogram), picks
   the k-th element's digit from a triangular-matmul cumulative sum, and
   narrows the active prefix — after 4 passes the EXACT k-th key is known,
   plus how many ties to keep.
3. One collect pass: qualifying elements get output slots from a 2-D
   log-step shifted-add prefix scan (lanes then sublanes — the scan-based
   replacement for warp-ballot compaction) and are gathered through a
   [k_pad, Cr, 128] one-hot reduction into an accumulator carry.

HBM traffic: the row is read exactly once — it stays in VMEM across all
five phases, like the reference's one-block variant
(radix_topk_one_block_kernel:1040).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.utils import interpret_mode, round_up

_LANES = 128


def _sortable_bits(vals: jax.Array, select_min: bool) -> jax.Array:
    bits = pltpu.bitcast(vals, jnp.uint32)
    neg = (bits >> 31).astype(jnp.uint32) * jnp.uint32(0xFFFFFFFF)
    u = bits ^ (neg | jnp.uint32(0x80000000))
    return u if select_min else ~u


def _scan_lanes(x, R: int):
    """Inclusive prefix sum along lanes (axis 1) of [R, 128] via log-step
    shifted adds (cumsum is not lowerable in Mosaic)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, _LANES), 1)
    s = 1
    while s < _LANES:
        shifted = pltpu.roll(x, s, 1)
        x = x + jnp.where(lane >= s, shifted, jnp.zeros_like(x))
        s *= 2
    return x


def _scan2d(x, R: int):
    """Row-major inclusive prefix sum over a [R, 128] tile: scan lanes,
    then add exclusive row offsets (scanned over sublanes)."""
    x = _scan_lanes(x, R)
    row_tot = jnp.broadcast_to(x[:, _LANES - 1:_LANES], (R, _LANES))
    row = jax.lax.broadcasted_iota(jnp.int32, (R, _LANES), 0)
    s = 1
    acc = row_tot
    # inclusive scan of row totals over sublanes
    while s < R:
        shifted = pltpu.roll(acc, s, 0)
        acc = acc + jnp.where(row >= s, shifted, jnp.zeros_like(acc))
        s *= 2
    exclusive = acc - row_tot
    return x + exclusive


def _select_k_kernel(val_ref, out_ref, u_scratch,
                     *, k: int, k_pad: int, Cr: int, R_total: int,
                     select_min: bool):
    n_chunks = R_total // Cr
    # phase 0: sortable keys into VMEM scratch
    u_scratch[:] = _sortable_bits(val_ref[0], select_min)

    iota256 = jax.lax.broadcasted_iota(jnp.int32, (256, Cr, _LANES), 0)
    tril = (jax.lax.broadcasted_iota(jnp.int32, (256, 256), 0)
            >= jax.lax.broadcasted_iota(jnp.int32, (256, 256), 1)
            ).astype(jnp.float32)

    def radix_pass(shift: int, high_mask: int, prefix, want):
        def chunk_hist(c, hist):
            u_c = u_scratch[pl.ds(c * Cr, Cr), :]
            active = (u_c & jnp.uint32(high_mask)) == \
                (prefix & jnp.uint32(high_mask))
            digit = ((u_c >> jnp.uint32(shift)) & jnp.uint32(255)).astype(jnp.int32)
            onehot = (digit[None] == iota256) & active[None]
            return hist + jnp.sum(onehot.astype(jnp.float32), axis=2).sum(
                axis=1, keepdims=True)

        hist = jax.lax.fori_loop(0, n_chunks, chunk_hist,
                                 jnp.zeros((256, 1), jnp.float32))
        cum = jax.lax.dot_general(tril, hist, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        d = jnp.sum((cum < want).astype(jnp.int32))
        below = jnp.sum(jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (256, 1), 0) < d, hist, 0.0))
        prefix = prefix | (d.astype(jnp.uint32) << jnp.uint32(shift))
        return prefix, want - below

    prefix = jnp.uint32(0)
    want = jnp.float32(k)
    for shift in (24, 16, 8, 0):
        high_mask = (~((1 << (shift + 8)) - 1)) & 0xFFFFFFFF
        prefix, want = radix_pass(shift, high_mask, prefix, want)
    threshold = prefix             # sortable bits of the k-th key
    n_ties = want                  # how many == threshold to keep
    n_less = jnp.float32(k) - n_ties

    # phase 5: collect into [k_pad] accumulators carried through the loop
    iota_kp = jax.lax.broadcasted_iota(jnp.int32, (k_pad, Cr, _LANES), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (Cr, _LANES), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (Cr, _LANES), 0)

    def chunk_collect(c, carry):
        prior_less, prior_eq, acc_v, acc_i = carry
        u_c = u_scratch[pl.ds(c * Cr, Cr), :]
        v_c = val_ref[0, pl.ds(c * Cr, Cr), :]
        base = (c * Cr * _LANES + row * _LANES + lane).astype(jnp.float32)
        is_less = u_c < threshold
        is_eq = u_c == threshold
        cum_less = _scan2d(is_less.astype(jnp.int32), Cr).astype(jnp.float32)
        cum_eq = _scan2d(is_eq.astype(jnp.int32), Cr).astype(jnp.float32)
        pos = jnp.where(
            is_less, prior_less + cum_less - 1.0,
            jnp.where(is_eq, n_less + prior_eq + cum_eq - 1.0,
                      jnp.float32(k_pad)))
        pos = jnp.where(pos < k, pos, jnp.float32(k_pad)).astype(jnp.int32)
        onehot = pos[None] == iota_kp                      # [k_pad, Cr, 128]
        acc_v = acc_v + jnp.sum(
            jnp.where(onehot, v_c[None], 0.0), axis=2).sum(axis=1)
        acc_i = acc_i + jnp.sum(
            jnp.where(onehot, base[None], 0.0), axis=2).sum(axis=1)
        return (prior_less + jnp.sum(is_less.astype(jnp.float32)),
                prior_eq + jnp.sum(is_eq.astype(jnp.float32)),
                acc_v, acc_i)

    zero_kp = jnp.zeros((k_pad,), jnp.float32)
    _, _, acc_v, acc_i = jax.lax.fori_loop(
        0, n_chunks, chunk_collect,
        (jnp.float32(0.0), jnp.float32(0.0), zero_kp, zero_kp))
    out_ref[0, 0, :] = acc_v
    out_ref[0, 1, :] = acc_i


@functools.partial(jax.jit, static_argnames=("k", "select_min", "chunk"))
def _select_k_rows(vals_padded, k: int, select_min: bool, chunk: int):
    batch, length = vals_padded.shape
    R_total = length // _LANES
    Cr = chunk // _LANES
    k_pad = round_up(max(k, _LANES), _LANES)
    vals3 = vals_padded.reshape(batch, R_total, _LANES)
    kernel = functools.partial(_select_k_kernel, k=k, k_pad=k_pad, Cr=Cr,
                               R_total=R_total, select_min=select_min)
    out = pl.pallas_call(
        kernel,
        grid=(batch,),
        in_specs=[pl.BlockSpec((1, R_total, _LANES), lambda b: (b, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, 8, k_pad), lambda b: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((batch, 8, k_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((R_total, _LANES), jnp.uint32)],
        interpret=interpret_mode(),
    )(vals3)
    return out[:, 0, :k], out[:, 1, :k].astype(jnp.int32)


def select_k(in_val, in_idx, k: int, select_min: bool, algo=None
             ) -> Tuple[jax.Array, jax.Array]:
    """Radix select_k over rows; returns (values sorted best-first,
    indices)."""
    in_val = jnp.asarray(in_val, jnp.float32)
    batch, length = in_val.shape
    if k > 256 or length < 1024:
        raise NotImplementedError("pallas select_k targets k<=256, len>=1024")
    if length >= 1 << 24:
        # indices accumulate through f32 one-hot sums, exact only < 2^24
        raise NotImplementedError("pallas select_k: row length must be < 2^24")
    chunk = 2048 if length >= 2048 else 1024
    pad = round_up(length, chunk) - length
    if pad:
        fill = jnp.inf if select_min else -jnp.inf
        in_val = jnp.pad(in_val, ((0, 0), (0, pad)), constant_values=fill)
    out_val, out_idx = _select_k_rows(in_val, k, select_min, chunk)
    if in_idx is not None:
        # translate positions through the caller's index array (for the
        # default 0..len-1 layout this gather is the identity; doing it
        # unconditionally keeps the path traced and per-row correct)
        out_idx = jnp.take_along_axis(jnp.asarray(in_idx), out_idx, axis=1)
    # sort each row's k results by key for parity with the XLA path
    order = jnp.argsort(out_val if select_min else -out_val, axis=1,
                        stable=True)
    return (jnp.take_along_axis(out_val, order, axis=1),
            jnp.take_along_axis(out_idx, order, axis=1))
