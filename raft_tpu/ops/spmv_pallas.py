"""Tiled-ELL SpMV kernels (Pallas/Mosaic) — the cusparse-SpMV role on TPU.

(ref: cpp/include/raft/sparse/detail/cusparse_wrappers.h:1 — the SpMV/SpMM
surface the reference gets from cusparse — and the Lanczos matvec dispatch
cpp/include/raft/sparse/solver/detail/lanczos.cuh:263-271.)

TPU-first re-design: GPUs do SpMV with hardware-threaded gather + atomic
scatter; TPUs have neither. Instead the matrix is re-laid-out ONCE
(raft_tpu.sparse.tiled.tile_csr) into fixed-size nonzero chunks whose
column (resp. row) footprint is a single tile, and both irregular sides
become per-chunk LANE-SELECT FOLDS — broadcast-compare + select + reduce,
all plain VPU ops every Mosaic version lowers:

- gather kernel: chunk c holds E nonzeros of one column tile; the x-tile
  for that chunk is chosen by a scalar-prefetched block index (data-
  dependent BlockSpec index_map — the Pallas idiom replacing pointer
  chasing). ``contrib[e] = val[e] · Σ_c [col[e] = c]·x_tile[c]``.
- a static permutation (XLA take) reorders contributions to row order —
  the permutation is precomputed host-side at conversion.
- scatter kernel: chunk c holds E contributions of one row tile; the
  output block (again scalar-prefetch-indexed) is zero-initialized on
  first visit and accumulated across the tile's consecutive chunks —
  Mosaic's sequential grid makes the revisited VMEM block the TPU
  replacement for CUDA's atomicAdd.

Layout note: x tiles and y tiles are carried as [n_tiles, C, 1] /
[n_tiles, R, 1] and the chunk arrays as [n_chunks, 1, E]: the leading axis
is grid-blocked and every block's trailing two dims EQUAL the array's or
are (8, 128)-divisible (Mosaic's block-shape rule). The E axis is ALSO
grid-blocked in ``_EB`` sub-blocks — slicing a loaded vector in-kernel
leaves a lane offset in its layout (e.g. ``{*, 512}``) that Mosaic's
apply-vector-layout pass rejects for ``vector.broadcast`` (caught on real
v5e by the TPU smoke lane); full-block loads are always offset-0, so the
sub-blocking lives in the grid, not the kernel body.

Pad entries carry value 0 (gather side) / row_local = R (scatter side), so
they contribute nothing. Row tiles with no nonzeros are never visited by
the grid; the caller zero-fills them via the conversion's visited mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.utils import interpret_mode

_EB = 512    # DEFAULT sub-block of the chunk folded per grid step; the
             # kernels take the actual width as the ``eb`` parameter
             # (grid steps = padded_nnz/eb, VMEM one-hot = [C|R, eb])


def _gather_kernel(col_tile_ref, vals_ref, cols_ref, xt_ref, out_ref,
                   *, C: int, eb: int):
    xt = xt_ref[0]                                     # [C, 1]
    cols = cols_ref[0]                                 # [1, eb]
    onehot = (jnp.broadcast_to(cols, (C, eb))
              == jax.lax.broadcasted_iota(jnp.int32, (C, eb), 0))
    contrib = jnp.sum(jnp.where(onehot, xt, 0.0), axis=0,
                      keepdims=True)                   # [1, eb]
    out_ref[0] = vals_ref[0] * contrib


def _scatter_kernel(row_tile_ref, contrib_ref, rloc_ref, y_ref,
                    *, R: int, eb: int):
    c = pl.program_id(0)
    b = pl.program_id(1)
    cur = row_tile_ref[c]
    prev = row_tile_ref[jnp.maximum(c - 1, 0)]
    first = (((c == 0) | (cur != prev))) & (b == 0)

    rloc = rloc_ref[0]                                 # [1, eb], pad = R
    contrib = contrib_ref[0]                           # [1, eb]
    onehot = (jnp.broadcast_to(rloc, (R, eb))
              == jax.lax.broadcasted_iota(jnp.int32, (R, eb), 0))
    acc = jnp.sum(jnp.where(onehot, contrib, 0.0), axis=1,
                  keepdims=True)                       # [R, 1]

    @pl.when(first)
    def _():
        y_ref[0] = acc

    @pl.when(jnp.logical_not(first))
    def _():
        y_ref[0] = y_ref[0] + acc


@functools.partial(jax.jit, static_argnames=("C", "R", "E", "n_col_tiles",
                                             "n_row_tiles", "eb"))
def _spmv_tiled_impl(vals, col_local, chunk_col_tile, perm, perm_rows,
                     row_local, chunk_row_tile, x_padded,
                     C: int, R: int, E: int,
                     n_col_tiles: int, n_row_tiles: int,
                     eb: int = _EB) -> jax.Array:
    n_chunks = vals.shape[0]
    m_chunks = row_local.shape[0]
    nb = E // eb
    xt = x_padded.reshape(n_col_tiles, C, 1)           # [n_tiles, C, 1]

    contrib = pl.pallas_call(
        functools.partial(_gather_kernel, C=C, eb=eb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_chunks, nb),
            in_specs=[
                pl.BlockSpec((1, 1, eb), lambda c, b, m: (c, 0, b),
                             memory_space=pltpu.VMEM),   # vals
                pl.BlockSpec((1, 1, eb), lambda c, b, m: (c, 0, b),
                             memory_space=pltpu.VMEM),   # cols
                pl.BlockSpec((1, C, 1), lambda c, b, m: (m[c], 0, 0),
                             memory_space=pltpu.VMEM),   # x tile
            ],
            out_specs=pl.BlockSpec((1, 1, eb), lambda c, b, m: (c, 0, b),
                                   memory_space=pltpu.VMEM),
        ),
        out_shape=jax.ShapeDtypeStruct((n_chunks, 1, E), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(chunk_col_tile, vals[:, None, :], col_local[:, None, :], xt)

    if perm_rows is not None:
        # 8-aligned bucket layout: the bridge is a ROW gather (fast XLA
        # path) with an appended zero row for pad slots; the scalar
        # variant below measured 15.4 ms of the 17.1 ms SpMV at 2M nnz
        contrib8 = jnp.concatenate(
            [contrib.reshape(-1, 8), jnp.zeros((1, 8), jnp.float32)])
        contrib_sorted = jnp.take(contrib8, perm_rows,
                                  axis=0).reshape(m_chunks, 1, E)
    else:
        contrib_sorted = jnp.take(
            contrib.reshape(-1), perm.reshape(-1)).reshape(m_chunks, 1, E)

    y3d = pl.pallas_call(
        functools.partial(_scatter_kernel, R=R, eb=eb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(m_chunks, nb),
            in_specs=[
                pl.BlockSpec((1, 1, eb), lambda c, b, m: (c, 0, b),
                             memory_space=pltpu.VMEM),   # contrib
                pl.BlockSpec((1, 1, eb), lambda c, b, m: (c, 0, b),
                             memory_space=pltpu.VMEM),   # row_local
            ],
            out_specs=pl.BlockSpec((1, R, 1), lambda c, b, m: (m[c], 0, 0),
                                   memory_space=pltpu.VMEM),
        ),
        out_shape=jax.ShapeDtypeStruct((n_row_tiles, R, 1), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret_mode(),
    )(chunk_row_tile, contrib_sorted, row_local[:, None, :])
    return y3d[:, :, 0]                                # [n_row_tiles, R]


def spmv_tiled(tiled, x, eb=None) -> jax.Array:
    """y = A @ x for a :class:`raft_tpu.sparse.tiled.TiledELL` operand.
    ``eb`` is the per-grid-step sub-block of each chunk (must divide E);
    larger eb = fewer grid steps (less per-step overhead) at more VMEM
    per step — the one-hot intermediates are [C, eb] / [R, eb].
    Default: the whole chunk (measured best at 2M nnz on v5e: 4.9 ms at
    eb=2048 vs 6.1 at the round-2 eb=512 — see R3_SPMV_EXP.json)."""
    n_rows, n_cols = tiled.shape
    if eb is None:
        # largest divisor of E ≤ 2048 (E is a 512-multiple, so one of
        # these always divides it)
        eb = next(w for w in (2048, 1024, 512)
                  if w <= tiled.E and tiled.E % w == 0)
    if tiled.E % eb:
        raise ValueError(f"spmv_tiled: eb={eb} must divide E={tiled.E}")
    x = jnp.asarray(x, jnp.float32)
    pad = tiled.n_col_tiles * tiled.C - n_cols
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), jnp.float32)])
    y2dt = _spmv_tiled_impl(
        tiled.vals, tiled.col_local, tiled.chunk_col_tile, tiled.perm,
        tiled.perm_rows, tiled.row_local, tiled.chunk_row_tile, x,
        C=tiled.C, R=tiled.R, E=tiled.E,
        n_col_tiles=tiled.n_col_tiles, n_row_tiles=tiled.n_row_tiles,
        eb=eb)
    # zero row tiles the grid never visited (rows with no nonzeros)
    y2d = jnp.where(tiled.visited_row_tiles[:, None], y2dt, 0.0)
    return y2d.reshape(-1)[:n_rows]


def _spmv_pair_kernel(row_tile_ref, col_tile_ref, vals_ref, cloc_ref,
                      rloc_ref, xt_ref, y_ref, *, R: int, C: int):
    """ONE fused gather·multiply·scatter step over a pair-tiled chunk
    sub-block: no HBM contribution intermediate and — the measured
    killer — no XLA scalar permutation between gather and scatter (15.4
    of the two-kernel pipeline's 17.1 ms at 2M nnz ran in `jnp.take`,
    XLA's scalar gather being ~0.5 GB/s on TPU)."""
    c = pl.program_id(0)
    b = pl.program_id(1)
    cur = row_tile_ref[c]
    prev = row_tile_ref[jnp.maximum(c - 1, 0)]
    first = ((c == 0) | (cur != prev)) & (b == 0)

    xt = xt_ref[0]                                     # [C, 1]
    cols = cloc_ref[0]                                 # [1, EB]
    oh_c = (jnp.broadcast_to(cols, (C, _EB))
            == jax.lax.broadcasted_iota(jnp.int32, (C, _EB), 0))
    xs = jnp.sum(jnp.where(oh_c, xt, 0.0), axis=0,
                 keepdims=True)                        # [1, EB]
    contrib = vals_ref[0] * xs
    rloc = rloc_ref[0]                                 # [1, EB], pad = R
    oh_r = (jnp.broadcast_to(rloc, (R, _EB))
            == jax.lax.broadcasted_iota(jnp.int32, (R, _EB), 0))
    acc = jnp.sum(jnp.where(oh_r, contrib, 0.0), axis=1,
                  keepdims=True)                       # [R, 1]

    @pl.when(first)
    def _():
        y_ref[0] = acc

    @pl.when(jnp.logical_not(first))
    def _():
        y_ref[0] = y_ref[0] + acc


@jax.jit
def spmv_pair_tiled(t, x) -> jax.Array:
    """y = A @ x for a :class:`raft_tpu.sparse.tiled.TiledPairsSpmv`
    operand — the single-kernel pair-tiled SpMV (see _spmv_pair_kernel).
    Chunks arrive sorted row-tile-major (tile_pairs' lexsort key), so
    the output block is revisited across a row tile's consecutive
    chunks and written to HBM once per tile."""
    p = t.pairs
    n_rows, n_cols = p.shape
    x = jnp.asarray(x, jnp.float32)
    pad = p.n_col_tiles * p.C - n_cols
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), jnp.float32)])
    xt = x.reshape(p.n_col_tiles, p.C, 1)
    nb = p.E // _EB
    m_chunks = p.m_chunks

    y3d = pl.pallas_call(
        functools.partial(_spmv_pair_kernel, R=p.R, C=p.C),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,                     # row tiles, col tiles
            grid=(m_chunks, nb),
            in_specs=[
                pl.BlockSpec((1, 1, _EB), lambda c, b, mr, mc: (c, 0, b),
                             memory_space=pltpu.VMEM),   # vals
                pl.BlockSpec((1, 1, _EB), lambda c, b, mr, mc: (c, 0, b),
                             memory_space=pltpu.VMEM),   # col_local
                pl.BlockSpec((1, 1, _EB), lambda c, b, mr, mc: (c, 0, b),
                             memory_space=pltpu.VMEM),   # row_local
                pl.BlockSpec((1, p.C, 1),
                             lambda c, b, mr, mc: (mc[c], 0, 0),
                             memory_space=pltpu.VMEM),   # x tile
            ],
            out_specs=pl.BlockSpec((1, p.R, 1),
                                   lambda c, b, mr, mc: (mr[c], 0, 0),
                                   memory_space=pltpu.VMEM),
        ),
        out_shape=jax.ShapeDtypeStruct((p.n_row_tiles, p.R, 1),
                                       jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret_mode(),
    )(p.chunk_row_tile, p.chunk_col_tile, t.vals,
      p.col_local[:, None, :], p.row_local[:, None, :], xt)
    # zero row tiles the grid never visited (rows with no nonzeros)
    y2d = jnp.where(t.visited[:, None], y3d[:, :, 0], 0.0)
    return y2d.reshape(-1)[:n_rows]


# ---------------------------------------------------------------------------
# SpMM: multi-vector operand — the one-hot select becomes an MXU matmul
# ---------------------------------------------------------------------------


def _gather_mm_kernel(col_tile_ref, vals_ref, cols_ref, x_ref, out_ref,
                      *, C: int, V: int, eb: int):
    """contrib[e, :] = val[e] · x_tile[col[e], :] via onehotᵀ @ x — for
    V ≥ ~8 columns the MXU does the selection (the one-hot rows are
    exactly representable in bf16, so with HIGHEST precision the gather
    error is the bf16x3 split residual of x, ~2⁻¹⁶ relative)."""
    x = x_ref[0]                                         # [C, V]
    cols = cols_ref[0]                                   # [1, eb]
    onehot = (jnp.broadcast_to(cols, (C, eb))
              == jax.lax.broadcasted_iota(jnp.int32, (C, eb), 0)
              ).astype(jnp.float32)                      # [C, eb]
    g = jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)              # [EB, V]
    out_ref[0] = vals_ref[0] * g                         # vals [EB, 1]


def _scatter_mm_kernel(row_tile_ref, contrib_ref, rloc_ref, y_ref,
                       *, R: int, V: int, eb: int):
    c = pl.program_id(0)
    b = pl.program_id(1)
    cur = row_tile_ref[c]
    prev = row_tile_ref[jnp.maximum(c - 1, 0)]
    first = ((c == 0) | (cur != prev)) & (b == 0)

    rloc = rloc_ref[0]                                   # [1, eb], pad = R
    onehot = (jnp.broadcast_to(rloc, (R, eb))
              == jax.lax.broadcasted_iota(jnp.int32, (R, eb), 0)
              ).astype(jnp.float32)                      # [R, eb]
    contrib = contrib_ref[0]                             # [eb, V]
    acc = jax.lax.dot_general(
        onehot, contrib, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)              # [R, V]

    @pl.when(first)
    def _():
        y_ref[0] = acc

    @pl.when(jnp.logical_not(first))
    def _():
        y_ref[0] = y_ref[0] + acc


@functools.partial(jax.jit, static_argnames=("C", "R", "E", "V",
                                             "n_col_tiles", "n_row_tiles",
                                             "eb"))
def _spmm_tiled_impl(vals, col_local, chunk_col_tile, perm, perm_rows,
                     row_local, chunk_row_tile, B_padded,
                     C: int, R: int, E: int, V: int,
                     n_col_tiles: int, n_row_tiles: int,
                     eb: int = _EB) -> jax.Array:
    n_chunks = vals.shape[0]
    m_chunks = row_local.shape[0]
    nb = E // eb
    x3d = B_padded.reshape(n_col_tiles, C, V)
    vals3 = vals.reshape(n_chunks, E, 1)                 # [EB, 1] blocks

    contrib = pl.pallas_call(
        functools.partial(_gather_mm_kernel, C=C, V=V, eb=eb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_chunks, nb),
            in_specs=[
                pl.BlockSpec((1, eb, 1), lambda c, b, m: (c, b, 0),
                             memory_space=pltpu.VMEM),   # vals
                pl.BlockSpec((1, 1, eb), lambda c, b, m: (c, 0, b),
                             memory_space=pltpu.VMEM),   # cols
                pl.BlockSpec((1, C, V), lambda c, b, m: (m[c], 0, 0),
                             memory_space=pltpu.VMEM),   # x tile
            ],
            out_specs=pl.BlockSpec((1, eb, V), lambda c, b, m: (c, b, 0),
                                   memory_space=pltpu.VMEM),
        ),
        out_shape=jax.ShapeDtypeStruct((n_chunks, E, V), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(chunk_col_tile, vals3, col_local[:, None, :], x3d)

    if perm_rows is not None:
        # 8-aligned bucket layout: gather 8-slot row groups ([8·V]-wide)
        c8 = jnp.concatenate(
            [contrib.reshape(-1, 8 * V),
             jnp.zeros((1, 8 * V), jnp.float32)])
        contrib_sorted = jnp.take(c8, perm_rows,
                                  axis=0).reshape(m_chunks, E, V)
    else:
        contrib_sorted = jnp.take(contrib.reshape(-1, V), perm.reshape(-1),
                                  axis=0).reshape(m_chunks, E, V)

    y3d = pl.pallas_call(
        functools.partial(_scatter_mm_kernel, R=R, V=V, eb=eb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(m_chunks, nb),
            in_specs=[
                pl.BlockSpec((1, eb, V), lambda c, b, m: (c, b, 0),
                             memory_space=pltpu.VMEM),   # contrib
                pl.BlockSpec((1, 1, eb), lambda c, b, m: (c, 0, b),
                             memory_space=pltpu.VMEM),   # row_local
            ],
            out_specs=pl.BlockSpec((1, R, V), lambda c, b, m: (m[c], 0, 0),
                                   memory_space=pltpu.VMEM),
        ),
        out_shape=jax.ShapeDtypeStruct((n_row_tiles, R, V), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret_mode(),
    )(chunk_row_tile, contrib_sorted, row_local[:, None, :])
    return y3d


def spmm_tiled(tiled, B) -> jax.Array:
    """Y = A @ B for a TiledELL operand and dense B [n_cols, V] — the
    cusparse-SpMM role with the one-hot selects running on the MXU.
    (ref: sparse/linalg/spmm.hpp:42 / cusparse_wrappers.h SpMM.)"""
    n_rows, n_cols = tiled.shape
    B = jnp.asarray(B, jnp.float32)
    if B.ndim != 2 or B.shape[0] != n_cols:
        raise ValueError(f"spmm_tiled: B must be [{n_cols}, V]")
    V = B.shape[1]
    if V > 512:
        # the [1, C, V] x-tile and [1, EB, V] contribution blocks are
        # VMEM-resident; past this width Mosaic fails to fit them with an
        # opaque error — fail early with an actionable one instead
        raise NotImplementedError(
            f"spmm_tiled targets V <= 512 dense columns (VMEM tile); got "
            f"{V} — chunk B column-wise or use the COO/CSR path")
    pad = tiled.n_col_tiles * tiled.C - n_cols
    if pad:
        B = jnp.concatenate([B, jnp.zeros((pad, V), jnp.float32)])
    # sub-block sized so BOTH the [eb, V] contrib tile and the
    # dominant [max(C,R), eb] one-hot buffers stay ≤ ~2/4 MB (same
    # grid-step-overhead logic as spmv_tiled's whole-chunk default);
    # falls back to the 512 floor for tilings where nothing larger fits
    cr = max(tiled.C, tiled.R)
    eb = next((w for w in (2048, 1024, 512)
               if w <= tiled.E and tiled.E % w == 0
               and w * max(V, 1) * 4 <= (2 << 20)
               and cr * w * 4 <= (4 << 20)), 512)
    y3d = _spmm_tiled_impl(
        tiled.vals, tiled.col_local, tiled.chunk_col_tile, tiled.perm,
        tiled.perm_rows, tiled.row_local, tiled.chunk_row_tile, B,
        C=tiled.C, R=tiled.R, E=tiled.E, V=V,
        n_col_tiles=tiled.n_col_tiles, n_row_tiles=tiled.n_row_tiles,
        eb=eb)
    y2d = jnp.where(tiled.visited_row_tiles[:, None, None], y3d, 0.0)
    return y2d.reshape(-1, V)[:n_rows]
