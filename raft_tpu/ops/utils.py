"""Shared Pallas kernel utilities."""

from __future__ import annotations

import functools

import jax

from raft_tpu.utils.pow2 import round_up_safe as round_up  # canonical helper


@functools.lru_cache(maxsize=1)
def interpret_mode() -> bool:
    """True when Pallas TPU kernels must run interpreted (non-TPU backend,
    e.g. the virtual CPU test platform)."""
    return jax.default_backend() != "tpu"
