"""Shared Pallas kernel utilities."""

from __future__ import annotations

import functools

import jax
import jax.experimental.pallas.tpu as pltpu

from raft_tpu.utils.pow2 import round_up_safe as round_up  # canonical helper

# jax renamed TPUCompilerParams → CompilerParams (~0.5); the kernels are
# written against the new name. Alias it on older jaxlib so every kernel
# module (they all import this one first) works on both sides of the
# rename — without this, EVERY Pallas path raises AttributeError on the
# older CPU test environment.
if not hasattr(pltpu, "CompilerParams") and hasattr(pltpu,
                                                    "TPUCompilerParams"):
    pltpu.CompilerParams = pltpu.TPUCompilerParams


@functools.lru_cache(maxsize=1)
def interpret_mode() -> bool:
    """True when Pallas TPU kernels must run interpreted (non-TPU backend,
    e.g. the virtual CPU test platform)."""
    return jax.default_backend() != "tpu"
