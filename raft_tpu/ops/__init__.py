"""raft_tpu.ops — Pallas TPU kernels for hot paths.

(ref: the CUDA kernel layer of the reference — select_radix.cuh /
select_warpsort.cuh / contractions.cuh / histogram.cuh — re-designed as
Mosaic/Pallas kernels. Each kernel has an XLA fallback in its caller, so the
framework is correct on any backend and fast on TPU.)
"""

from raft_tpu.ops.utils import interpret_mode
