"""Fused L2 distance + slotted top-k candidate kernel (Pallas/Mosaic).

The TPU rendering of the reference's fused distance→select pipeline:
(ref: cpp/include/raft/matrix/detail/select_radix.cuh:639 radix_kernel,
select_warpsort.cuh:752 warpsort queues, and the tiling substrate
cpp/include/raft/linalg/detail/contractions.cuh:1 — the role "distance
tiles are consumed by the selector without round-tripping global memory").

Design (TPU-first, not a translation):

- Grid ``(n_query_blocks, n_tiles)``; the index tile loop is the inner,
  sequential grid dimension, so VMEM-revisited output blocks accumulate
  across tiles (the Mosaic idiom replacing CUDA's global-memory atomics).
- Each cell contracts ``X_block[Qb,d] @ Y_tile[T,d]ᵀ`` on the MXU in
  bfloat16 (1 pass, ``passes=1``) or with a hi/lo bf16 split
  (``passes=3``: hi·hi + hi·lo + lo·hi — f32-grade accuracy at 3× bf16
  cost, the TPU replacement for fp32 SGEMM), then forms
  ``d2 = xx + yy − 2S`` with exact f32 norm corrections.
- The [Qb, T] distance tile NEVER leaves VMEM. It is folded lane-chunk by
  lane-chunk into per-slot running (min, argmin, 2nd-min) — a "slot" is a
  (tile, lane-class) bucket; the fold is pure VPU compare/selects, the
  scan-free replacement for warp-shuffle insertion sorts.
- Outputs: per-slot min ``m1 [Q, S]`` + its index ``i1 [Q, S]``, plus a
  per-query running min over slots of the slot 2nd-min (``m2min [Q, LANES]``
  — folded over tiles in-place). ``m2min`` powers the EXACTNESS
  CERTIFICATE in raft_tpu.distance.knn_fused: every non-candidate point is
  ≥ its slot's 2nd-min, so ``min_slots m2 ≥ θ`` proves the candidate top-k
  is the true top-k (see knn_fused for the fixup path when it fails).

Padded index rows are masked to +inf inside the kernel (the caller passes
the real row count); padded rows therefore never pollute slots.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.utils import interpret_mode

_LANES = 128

# Mosaic's scoped-VMEM stack limit on current TPU generations (the
# compiler rejects kernels whose live VMEM exceeds it); budget leaves
# headroom for temporaries the estimator can't see.
VMEM_LIMIT = 16 * 2 ** 20
VMEM_BUDGET = 15 * 2 ** 20


def vmem_footprint(T: int, Qb: int, d: int, passes: int,
                   dchunk: bool = False) -> int:
    """Estimated scoped-VMEM bytes of one fused-kernel grid cell.

    Calibrated against measured Mosaic compiles on v5e (tune sweep +
    driver bench): (T=2048, Qb=1024, d=128, passes=3) was rejected at
    20.35 MB against the 16 MB limit while the same shape at passes=1
    compiled and ran, and (T=4096, Qb=512, passes=3) was rejected. The
    dominant term is the [Qb, T] f32 score tile; passes=3 holds an
    accumulator plus a fresh dot result (~2 live copies + mask/fold
    temporaries) where passes=1 keeps ~1."""
    d2_bufs = 1.25 if passes == 1 else 2.25
    dc = min(d, 256) if dchunk else d
    bytes_ = int(Qb * T * 4 * d2_bufs)
    bytes_ += T * dc * 2 * 2 * (2 if passes == 3 else 1)  # y hi(/lo), 2 bufs
    bytes_ += Qb * dc * (4 + 2)                           # x f32 + bf16 cast
    bytes_ += T * 4 * 2 + Qb * 4                          # yy (2 bufs), xx
    bytes_ += Qb * _LANES * 12 * 2                        # slot outs + temps
    if dchunk:
        bytes_ += Qb * T * 4                              # score accumulator
    return bytes_


def _contract(x, yhi, ylo):
    """bf16 (ylo None) or bf16x3 MXU contraction of an f32 x block with a
    bf16-split y tile → f32 [Qb, T] partial scores."""
    xhi = x.astype(jnp.bfloat16)
    s = jax.lax.dot_general(
        xhi, yhi, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if ylo is not None:
        xlo = (x - xhi.astype(jnp.float32)).astype(jnp.bfloat16)
        s = s + jax.lax.dot_general(
            xhi, ylo, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = s + jax.lax.dot_general(
            xlo, yhi, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    return s


def _fold_and_write(d2, j, m_real_ref, m1_ref, i1_ref, m2min_ref,
                    T: int, Qb: int, mask: bool = True, track: bool = True):
    """Mask padded index rows, fold the [Qb, T] distance tile into LANES
    slots (per-slot top-2 + argmin-1), and write/accumulate the outputs.
    Shared by the single-shot and d-chunked kernels.

    ``mask=False`` / ``track=False`` are MEASUREMENT-ONLY knobs
    (benchmarks/profile_fused.py bounds the cost of the mask and of the
    index/2nd-min bookkeeping with them): mask=False requires pre-masked
    operands; track=False returns i1 = 0 and m2min = the slot MIN — not
    valid certificate inputs."""
    n_chunks = T // _LANES
    if mask:
        # mask padded index rows (global col ≥ m_real) to +inf
        col = j * T + jax.lax.broadcasted_iota(jnp.int32, (Qb, T), 1)
        d2 = jnp.where(col < m_real_ref[0], d2, jnp.inf)

    # slot class c collects columns {c, c+128, c+256, ...} of this tile
    # (chunk r contributes its lane c as global column j*T + r*128 + c).
    inf = jnp.full((Qb, _LANES), jnp.inf, jnp.float32)
    if not track:
        a1 = inf
        for r in range(n_chunks):
            a1 = jnp.minimum(a1, d2[:, r * _LANES:(r + 1) * _LANES])
        a2 = a1
        i1 = jnp.zeros((Qb, _LANES), jnp.int32)
    else:
        a1, a2 = inf, inf
        i1 = jnp.full((Qb, _LANES), -1, jnp.int32)
        lane = jax.lax.broadcasted_iota(jnp.int32, (Qb, _LANES), 1)
        for r in range(n_chunks):
            c = d2[:, r * _LANES:(r + 1) * _LANES]
            ci = j * T + r * _LANES + lane
            lt1 = c < a1
            a2 = jnp.where(lt1, a1, jnp.minimum(a2, c))
            a1 = jnp.where(lt1, c, a1)
            i1 = jnp.where(lt1, ci, i1)

    m1_ref[...] = a1
    i1_ref[...] = i1
    # running min over slots of the slot-2nd-min (certificate input);
    # the m2min output block is revisited by every tile of this q-block
    @pl.when(j == 0)
    def _():
        m2min_ref[...] = a2

    @pl.when(j != 0)
    def _():
        m2min_ref[...] = jnp.minimum(m2min_ref[...], a2)


def _fused_kernel(m_real_ref, x_ref, yhi_ref, xx_ref, yy_ref,
                  m1_ref, i1_ref, m2min_ref,
                  *, T: int, Qb: int, ylo_ref=None,
                  mask: bool = True, track: bool = True):
    """One (query-block, index-tile) cell. ``ylo_ref`` present ⇒ bf16x3."""
    j = pl.program_id(1)
    s = _contract(x_ref[...], yhi_ref[...],
                  None if ylo_ref is None else ylo_ref[...])
    d2 = xx_ref[...] + yy_ref[...] - 2.0 * s         # [Qb,1]+[1,T]-[Qb,T]
    _fold_and_write(d2, j, m_real_ref, m1_ref, i1_ref, m2min_ref,
                    T=T, Qb=Qb, mask=mask, track=track)


def _fused_kernel_dchunk(m_real_ref, x_ref, yhi_ref, xx_ref, yy_ref,
                         m1_ref, i1_ref, m2min_ref, acc_ref,
                         *, T: int, Qb: int, ylo_ref=None):
    """d-chunked cell (grid (nq, n_tiles, n_dchunks), d innermost): the
    partial contraction accumulates into a VMEM scratch [Qb, T]; the
    mask+fold runs only on the LAST d-chunk. Lifts the d ≤ 512 envelope
    — the d2 tile still never touches HBM."""
    j = pl.program_id(1)
    l = pl.program_id(2)
    n_dc = pl.num_programs(2)
    s = _contract(x_ref[...], yhi_ref[...],
                  None if ylo_ref is None else ylo_ref[...])

    @pl.when(l == 0)
    def _():
        acc_ref[...] = s

    @pl.when(l != 0)
    def _():
        acc_ref[...] = acc_ref[...] + s

    @pl.when(l == n_dc - 1)
    def _():
        d2 = xx_ref[...] + yy_ref[...] - 2.0 * acc_ref[...]
        _fold_and_write(d2, j, m_real_ref, m1_ref, i1_ref, m2min_ref,
                        T=T, Qb=Qb)


# --- scaffolding shared by the single-shot and d-chunked calls (the
# out-spec index maps take (i, j, *rest) so the same lambdas serve both
# grid arities; *rest swallows the extra grid index + prefetch refs) ---

def _slot_out_specs(Qb: int):
    return [
        pl.BlockSpec((Qb, _LANES), lambda i, j, *_: (i, j),
                     memory_space=pltpu.VMEM),          # m1
        pl.BlockSpec((Qb, _LANES), lambda i, j, *_: (i, j),
                     memory_space=pltpu.VMEM),          # i1
        pl.BlockSpec((Qb, _LANES), lambda i, j, *_: (i, 0),
                     memory_space=pltpu.VMEM),          # m2min (revisited)
    ]


def _slot_out_shape(Q: int, S: int):
    return [
        jax.ShapeDtypeStruct((Q, S), jnp.float32),
        jax.ShapeDtypeStruct((Q, S), jnp.int32),
        jax.ShapeDtypeStruct((Q, _LANES), jnp.float32),
    ]


def _slot_cost(Q: int, M: int, d: int, S: int, passes: int):
    return pl.CostEstimate(
        flops=2 * Q * M * d * passes,
        bytes_accessed=(Q * d * 4 + M * d * 2 * (2 if passes == 3 else 1)
                        + Q * S * 8),
        transcendentals=0,
    )


def _make_kernel(base, passes: int, T: int, Qb: int, **fold_kw):
    """Bind the base kernel for the passes mode; for passes == 3 reorder
    the y_lo ref out of the positional stream (*rest carries the output
    refs and, for the d-chunked kernel, the scratch ref)."""
    if passes != 3:
        return functools.partial(base, T=T, Qb=Qb, ylo_ref=None, **fold_kw)

    def kernel(m_real_ref, x_ref, yhi_ref, ylo_ref, xx_ref, yy_ref, *rest):
        base(m_real_ref, x_ref, yhi_ref, xx_ref, yy_ref, *rest,
             T=T, Qb=Qb, ylo_ref=ylo_ref, **fold_kw)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "mask", "track"))
def fused_l2_slot_topk(x, y_hi, y_lo, xx, yy, m_real,
                       T: int, Qb: int, passes: int,
                       mask: bool = True, track: bool = True
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Run the fused kernel. ``mask``/``track`` are measurement-only
    knobs (see _fold_and_write) — production callers use the defaults.

    Args:
      x: [Q, d] f32 queries (Q a multiple of Qb).
      y_hi, y_lo: [M, d] bf16 hi/lo split of the padded index (M a multiple
        of T); ``y_lo`` is only DMA'd/read when passes == 3.
      xx, yy: exact f32 squared norms, [Q, 1] and [1, M] (padded rows'
        yy = 0 — they are masked in-kernel anyway).
      m_real: [1] int32 — real (unpadded) index row count.
      T: index tile length; Qb: query block; passes: 1 (bf16) or 3 (bf16x3).

    Returns:
      m1 [Q, S] f32, i1 [Q, S] int32, m2min [Q, LANES] f32 with
      S = (M // T) * LANES; slot s = (tile = s // LANES) × (lane-class =
      s % LANES); i1 holds GLOBAL index-row ids; padded-only slots keep
      m1 = +inf, i1 = -1.
    """
    Q, d = x.shape
    M = y_hi.shape[0]
    n_tiles = M // T
    nq = Q // Qb
    S = n_tiles * _LANES

    in_specs = [
        pl.BlockSpec((Qb, d), lambda i, j, *_: (i, 0),
                     memory_space=pltpu.VMEM),          # x
        pl.BlockSpec((T, d), lambda i, j, *_: (j, 0),
                     memory_space=pltpu.VMEM),          # y_hi
        pl.BlockSpec((Qb, 1), lambda i, j, *_: (i, 0),
                     memory_space=pltpu.VMEM),          # xx
        pl.BlockSpec((1, T), lambda i, j, *_: (0, j),
                     memory_space=pltpu.VMEM),          # yy
    ]
    operands = [x, y_hi, xx, yy]
    if passes == 3:
        in_specs.insert(2, pl.BlockSpec((T, d), lambda i, j, *_: (j, 0),
                                        memory_space=pltpu.VMEM))  # y_lo
        operands.insert(2, y_lo)
    kernel = _make_kernel(_fused_kernel, passes, T, Qb,
                          mask=mask, track=track)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nq, n_tiles),
        in_specs=in_specs,
        out_specs=_slot_out_specs(Qb),
    )
    m1, i1, m2min = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_slot_out_shape(Q, S),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=_slot_cost(Q, M, d, S, passes),
        interpret=interpret_mode(),
    )(m_real, *operands)
    return m1, i1, m2min


@functools.partial(jax.jit, static_argnames=("T", "Qb", "passes", "dc"))
def fused_l2_slot_topk_dchunk(x, y_hi, y_lo, xx, yy, m_real,
                              T: int, Qb: int, passes: int, dc: int = 256
                              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """d-chunked variant of :func:`fused_l2_slot_topk` for wide features
    (d > 512): grid (nq, n_tiles, d/dc) with the score tile accumulated
    in VMEM scratch across d-chunks (see _fused_kernel_dchunk). Same
    contract and outputs; caller pads d to a multiple of ``dc``."""
    Q, d = x.shape
    M = y_hi.shape[0]
    if d % dc:
        raise ValueError(
            f"fused_l2_slot_topk_dchunk: d={d} must be a multiple of "
            f"dc={dc} (the tail would be silently dropped)")
    n_tiles = M // T
    nq = Q // Qb
    n_dc = d // dc
    S = n_tiles * _LANES

    in_specs = [
        pl.BlockSpec((Qb, dc), lambda i, j, l, *_: (i, l),
                     memory_space=pltpu.VMEM),          # x
        pl.BlockSpec((T, dc), lambda i, j, l, *_: (j, l),
                     memory_space=pltpu.VMEM),          # y_hi
        pl.BlockSpec((Qb, 1), lambda i, j, *_: (i, 0),
                     memory_space=pltpu.VMEM),          # xx
        pl.BlockSpec((1, T), lambda i, j, *_: (0, j),
                     memory_space=pltpu.VMEM),          # yy
    ]
    operands = [x, y_hi, xx, yy]
    if passes == 3:
        in_specs.insert(2, pl.BlockSpec((T, dc), lambda i, j, l, *_: (j, l),
                                        memory_space=pltpu.VMEM))  # y_lo
        operands.insert(2, y_lo)
    kernel = _make_kernel(_fused_kernel_dchunk, passes, T, Qb)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nq, n_tiles, n_dc),
        in_specs=in_specs,
        out_specs=_slot_out_specs(Qb),
        scratch_shapes=[pltpu.VMEM((Qb, T), jnp.float32)],  # score acc
    )
    m1, i1, m2min = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_slot_out_shape(Q, S),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        cost_estimate=_slot_cost(Q, M, d, S, passes),
        interpret=interpret_mode(),
    )(m_real, *operands)
    return m1, i1, m2min


def split_hi_lo(y: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Split f32 into bf16 hi + bf16 lo with y ≈ hi + lo (bf16x3 operand
    prep; the dropped lo·lo term is O(2⁻¹⁸·‖x‖‖y‖))."""
    y = jnp.asarray(y, jnp.float32)
    hi = y.astype(jnp.bfloat16)
    lo = (y - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo
