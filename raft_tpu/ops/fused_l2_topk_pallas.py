"""Fused L2 distance + slotted top-k candidate kernel (Pallas/Mosaic).

The TPU rendering of the reference's fused distance→select pipeline:
(ref: cpp/include/raft/matrix/detail/select_radix.cuh:639 radix_kernel,
select_warpsort.cuh:752 warpsort queues, and the tiling substrate
cpp/include/raft/linalg/detail/contractions.cuh:1 — the role "distance
tiles are consumed by the selector without round-tripping global memory").

Design (TPU-first, not a translation):

- Grid ``(n_query_blocks, n_tiles)``; the index tile loop is the inner,
  sequential grid dimension, so VMEM-revisited output blocks accumulate
  across tiles (the Mosaic idiom replacing CUDA's global-memory atomics).
- Each cell contracts ``X_block[Qb,d] @ Y_tile[T,d]ᵀ`` on the MXU in
  bfloat16 (1 pass, ``passes=1``) or with a hi/lo bf16 split
  (``passes=3``: hi·hi + hi·lo + lo·hi — f32-grade accuracy at 3× bf16
  cost, the TPU replacement for fp32 SGEMM), then forms
  ``d2 = xx + yy − 2S`` with exact f32 norm corrections.
- The [Qb, T] distance tile NEVER leaves VMEM. It is folded lane-chunk by
  lane-chunk into per-slot running (min, argmin, 2nd-min) — a "slot" is a
  (tile, lane-class) bucket; the fold is pure VPU compare/selects, the
  scan-free replacement for warp-shuffle insertion sorts.
- Outputs: per-slot min ``m1 [Q, S]`` + its index ``i1 [Q, S]``, plus a
  per-query running min over slots of the slot 2nd-min (``m2min [Q, LANES]``
  — folded over tiles in-place). ``m2min`` powers the EXACTNESS
  CERTIFICATE in raft_tpu.distance.knn_fused: every non-candidate point is
  ≥ its slot's 2nd-min, so ``min_slots m2 ≥ θ`` proves the candidate top-k
  is the true top-k (see knn_fused for the fixup path when it fails).

Padded index rows are masked to +inf inside the kernel (the caller passes
the real row count); padded rows therefore never pollute slots.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.utils import interpret_mode

_LANES = 128

_PACK_BITS = 8                   # default code width; kernels take the
                                 # actual width as the ``pbits`` static
                                 # (more codes = wider groups = narrower
                                 # pool, at 2^(pbits-23) value error)
_PACK_MASK = (1 << _PACK_BITS) - 1
_PBITS_MAX = 13                  # widest allowed codes: value error
                                 # 2^(13-23) must stay under the
                                 # certificate margins (ONE definition —
                                 # auto_pack_bits, prepare_knn_index and
                                 # footprint_for all consume this)
_PACK_PAD = float(2.0 ** 125)    # finite "never wins" sentinel


# Mosaic's scoped-VMEM stack limit on current TPU generations (the
# compiler rejects kernels whose live VMEM exceeds it); budget leaves
# headroom for temporaries the estimator can't see.
VMEM_LIMIT = 16 * 2 ** 20
VMEM_BUDGET = 15 * 2 ** 20


def vmem_budget() -> int:
    """The scoped-VMEM fit budget ``fit_config``/``footprint_for``
    validate against. ``RAFT_TPU_VMEM_BUDGET_MB`` (env) overrides the
    built-in :data:`VMEM_BUDGET` — the derate knob for a generation
    whose Mosaic limit differs from the calibrated v5e one, or for
    operators who keep hitting real compile OOMs at configs the model
    passes (the footprint factors are estimates; shrinking the budget
    makes every fit predicate — production routing, the tune sweeps'
    pruning, and the resilience degradation ladder's rung validation —
    conservative in one place)."""
    raw = os.environ.get("RAFT_TPU_VMEM_BUDGET_MB")
    if raw:
        try:
            return int(float(raw) * (1 << 20))
        except ValueError:
            pass
    return VMEM_BUDGET


def vmem_footprint(T: int, Qb: int, d: int, passes: int,
                   dchunk: bool = False, kernel: str = "group",
                   g: int = 16) -> int:
    """Estimated scoped-VMEM bytes of one fused-kernel grid cell.

    Calibrated against measured Mosaic compiles/rejections on v5e:
    - slot kernel: (T=2048, Qb=1024, d=128, p3) rejected at 20.35 MB
      vs the 16 MB limit; same shape at p1 compiled; (4096, 512, p3)
      rejected. Model: [Qb, T] f32 score tile × ~1.25 (p1) / ~2.25 (p3)
      live copies incl. the col-iota mask temporaries.
    - group kernel (production): (2048, 512, d=128, p1) rejected at
      16.36 MB WITH in-kernel masking; masking is since removed (yy
      carries +inf — two fewer [Qb, T] buffers) but the in-kernel merge
      holds more fold state, so its factors stay higher than the slot
      kernel's: ~2.2 (p1) / ~3.2 (p3).

    ``g`` (tiles per group) only enters the database-major models —
    "stream_db" holds a whole [g·T, d] y super-block resident,
    "stream_dbuf" holds 2 DMA tile slots but the fold state of the
    WHOLE query batch (callers pass the padded query count as Qb)."""
    if kernel == "stream_db":
        # database-major super-blocked cell: the y group block
        # [g·T, d] is VMEM-resident (double-buffered by the standard
        # Pallas pipeline so the next super-block DMA overlaps the
        # last cell of this one); fold state matches "stream"
        bytes_ = g * T * d * 2 * 2 * (2 if passes == 3 else 1)
        bytes_ += Qb * d * 6 + Qb * 8                 # x f32+bf16, xxh
        bytes_ += 8 * g * T * 4 * 2                   # yyh carrier
        bytes_ += Qb * _LANES * 4 * 20                # fold state + temps
        return bytes_
    if kernel == "stream_db_q8":
        # int8-quantized database super-block: one [g·T, d] int8 slab
        # (double-buffered by the standard pipeline) replaces the bf16
        # hi(/lo) pair — 1 byte/element streamed regardless of passes
        # (passes only splits the QUERY operand; y_q is exact in bf16).
        # The per-group [8, 128] f32 scale tile is noise next to it.
        bytes_ = g * T * d * 1 * 2
        bytes_ += Qb * d * 6 + Qb * 8                 # x f32+bf16, xxh
        bytes_ += 8 * g * T * 4 * 2                   # yyh carrier
        bytes_ += 8 * _LANES * 4 * 2                  # scale tile
        bytes_ += Qb * _LANES * 4 * 20                # fold state + temps
        return bytes_
    if kernel == "stream_dbuf_q8":
        # int8 explicit double-buffered streaming: 2 int8 DMA tile
        # slots; fold state covers the whole padded query batch like
        # "stream_dbuf" (callers pass that as Qb)
        bytes_ = 2 * T * d * 1                        # 2 int8 DMA slots
        bytes_ += Qb * d * 6 + Qb * 8                 # x f32+bf16, xxh
        bytes_ += 8 * g * T * 4 * 2                   # yyh carrier
        bytes_ += 8 * _LANES * 4 * 2                  # scale tile
        bytes_ += Qb * _LANES * 4 * 12                # fold state + temps
        return bytes_
    if kernel == "stream_dbuf":
        # explicit double-buffered streaming: y tiles ride a 2-slot
        # manual-DMA scratch (only 2 tiles resident, whatever g is) but
        # the cell covers the WHOLE query batch — Qb here is the padded
        # query count, so the fold-state term dominates. Factor 12 ≈
        # 3 accumulators + ~6 transient merge temps + pack/cast copies;
        # UNCALIBRATED estimate (no Mosaic compile/reject measured yet
        # for this kernel — the first TPU round recalibrates it the way
        # v5e rejections calibrated the factors above).
        bytes_ = 2 * T * d * 2 * (2 if passes == 3 else 1)  # 2 DMA slots
        bytes_ += Qb * d * 6 + Qb * 8                 # x f32+bf16, xxh
        bytes_ += 8 * g * T * 4 * 2                   # yyh carrier
        bytes_ += Qb * _LANES * 4 * 12                # fold state + temps
        return bytes_
    if kernel == "stream":
        # the streamed packed kernel (single-shot only — the d-chunked
        # packed kernel models as "packed") never materializes a
        # [Qb, T] score buffer: per-chunk [Qb, 128] temporaries only
        # (fold state + pack temps, ~20 live [Qb, 128]
        # f32-equivalents, conservative vs the ~14 the fold holds)
        assert not dchunk, "stream models the single-shot kernel"
        bytes_ = T * d * 2 * 2 * (2 if passes == 3 else 1)  # y hi(/lo)
        bytes_ += Qb * d * 6 + Qb * 8                 # x f32+bf16, xxh
        bytes_ += 8 * T * 4 * 2                       # yyh carrier
        bytes_ += Qb * _LANES * 4 * 20                # fold state + temps
        return bytes_
    if kernel == "group":
        d2_bufs = 2.2 if passes == 1 else 3.2
        n_out = 5
    elif kernel == "packed":
        # no i32 id carriers in the merge and 3 f32 outputs — measured
        # compiles at (1024, 256) both passes; factors kept conservative
        d2_bufs = 1.8 if passes == 1 else 2.8
        n_out = 3
    else:
        d2_bufs = 1.25 if passes == 1 else 2.25
        n_out = 3
    dc = min(d, 256) if dchunk else d
    bytes_ = int(Qb * T * 4 * d2_bufs)
    bytes_ += T * dc * 2 * 2 * (2 if passes == 3 else 1)  # y hi(/lo), 2 bufs
    bytes_ += Qb * dc * (4 + 2)                           # x f32 + bf16 cast
    bytes_ += T * 4 * 2 + Qb * 4                          # yy (2 bufs), xx
    bytes_ += Qb * _LANES * 4 * n_out * 2                 # out blocks + temps
    if dchunk:
        bytes_ += Qb * T * 4                              # score accumulator
    return bytes_


def _contract(x, yhi, ylo):
    """bf16 (ylo None) or bf16x3 MXU contraction of an f32 x block with a
    bf16-split y tile → f32 [Qb, T] partial scores.

    The ((1,),(1,)) NT contraction is used directly: a pre-transposed
    [d, T] y layout was A/B-measured on v5e (2048×1M×128) and LOST
    (5.29 vs 4.72 ms p1) — Mosaic handles NT natively and the XLA-side
    transpose costs more than it saves, so the knob was removed."""
    dims = (((1,), (1,)), ((), ()))
    xhi = x.astype(jnp.bfloat16)
    s = jax.lax.dot_general(
        xhi, yhi, dims, preferred_element_type=jnp.float32)
    if ylo is not None:
        # unbarriered ON PURPOSE: this body lowers through Mosaic, not
        # the XLA bf16-propagation pass that folds the split in
        # split_hi_lo (see its barrier note) — audited on hardware: the
        # fuzz battery's big-norm p3 rows exercise this exact split and
        # the kernel matched the numpy bf16x3 emulation bit-for-bit
        xlo = (x - xhi.astype(jnp.float32)).astype(jnp.bfloat16)
        s = s + jax.lax.dot_general(
            xhi, ylo, dims, preferred_element_type=jnp.float32)
        s = s + jax.lax.dot_general(
            xlo, yhi, dims, preferred_element_type=jnp.float32)
    return s


def _contract_q8(x, yq, passes: int):
    """MXU contraction of an f32 x block with an INT8-quantized y tile
    → f32 [Qb, T] partial scores in QUANTIZED units (the caller
    multiplies by the group scale AFTER accumulation — cheaper and more
    accurate than a per-element dequantize: int8 magnitudes ≤ 127 are
    EXACT in bf16's 8-bit mantissa, so the y factor carries zero
    rounding; only x is rounded). ``passes=3`` adds the x_lo pass
    (x ≈ hi + lo to ~2⁻¹⁶), halving the x-side error at 2× MXU cost —
    there is no y_lo: the quantization error is handled by the
    certificate's Eq widening, not by extra precision."""
    dims = (((1,), (1,)), ((), ()))
    xhi = x.astype(jnp.bfloat16)
    yqb = yq.astype(jnp.bfloat16)
    s = jax.lax.dot_general(
        xhi, yqb, dims, preferred_element_type=jnp.float32)
    if passes == 3:
        # unbarriered like _contract: Mosaic lowering, not the XLA
        # bf16-propagation pass that folds the split
        xlo = (x - xhi.astype(jnp.float32)).astype(jnp.bfloat16)
        s = s + jax.lax.dot_general(
            xlo, yqb, dims, preferred_element_type=jnp.float32)
    return s


def _fold_and_write(d2, j, m_real_ref, m1_ref, i1_ref, m2min_ref,
                    T: int, Qb: int, mask: bool = True, track: bool = True):
    """Mask padded index rows, fold the [Qb, T] distance tile into LANES
    slots (per-slot top-2 + argmin-1), and write/accumulate the outputs.
    Shared by the single-shot and d-chunked kernels.

    ``mask=False`` / ``track=False`` are MEASUREMENT-ONLY knobs
    (benchmarks/profile_fused.py bounds the cost of the mask and of the
    index/2nd-min bookkeeping with them): mask=False requires pre-masked
    operands; track=False returns i1 = 0 and m2min = the slot MIN — not
    valid certificate inputs."""
    n_chunks = T // _LANES
    if mask:
        # mask padded index rows (global col ≥ m_real) to +inf
        col = j * T + jax.lax.broadcasted_iota(jnp.int32, (Qb, T), 1)
        d2 = jnp.where(col < m_real_ref[0], d2, jnp.inf)

    # slot class c collects columns {c, c+128, c+256, ...} of this tile
    # (chunk r contributes its lane c as global column j*T + r*128 + c).
    inf = jnp.full((Qb, _LANES), jnp.inf, jnp.float32)
    if not track:
        a1 = inf
        for r in range(n_chunks):
            a1 = jnp.minimum(a1, d2[:, r * _LANES:(r + 1) * _LANES])
        a2 = a1
        i1 = jnp.zeros((Qb, _LANES), jnp.int32)
    else:
        a1, a2 = inf, inf
        i1 = jnp.full((Qb, _LANES), -1, jnp.int32)
        lane = jax.lax.broadcasted_iota(jnp.int32, (Qb, _LANES), 1)
        for r in range(n_chunks):
            c = d2[:, r * _LANES:(r + 1) * _LANES]
            ci = j * T + r * _LANES + lane
            lt1 = c < a1
            a2 = jnp.where(lt1, a1, jnp.minimum(a2, c))
            a1 = jnp.where(lt1, c, a1)
            i1 = jnp.where(lt1, ci, i1)

    m1_ref[...] = a1
    i1_ref[...] = i1
    # running min over slots of the slot-2nd-min (certificate input);
    # the m2min output block is revisited by every tile of this q-block
    @pl.when(j == 0)
    def _():
        m2min_ref[...] = a2

    @pl.when(j != 0)
    def _():
        m2min_ref[...] = jnp.minimum(m2min_ref[...], a2)


def _fused_kernel(m_real_ref, x_ref, yhi_ref, xx_ref, yy_ref,
                  m1_ref, i1_ref, m2min_ref,
                  *, T: int, Qb: int, ylo_ref=None,
                  mask: bool = True, track: bool = True):
    """One (query-block, index-tile) cell. ``ylo_ref`` present ⇒ bf16x3."""
    j = pl.program_id(1)
    s = _contract(x_ref[...], yhi_ref[...],
                  None if ylo_ref is None else ylo_ref[...])
    d2 = xx_ref[...] + yy_ref[...] - 2.0 * s         # [Qb,1]+[1,T]-[Qb,T]
    _fold_and_write(d2, j, m_real_ref, m1_ref, i1_ref, m2min_ref,
                    T=T, Qb=Qb, mask=mask, track=track)


def _fused_kernel_dchunk(m_real_ref, x_ref, yhi_ref, xx_ref, yy_ref,
                         m1_ref, i1_ref, m2min_ref, acc_ref,
                         *, T: int, Qb: int, ylo_ref=None):
    """d-chunked cell (grid (nq, n_tiles, n_dchunks), d innermost): the
    partial contraction accumulates into a VMEM scratch [Qb, T]; the
    mask+fold runs only on the LAST d-chunk. Lifts the d ≤ 512 envelope
    — the d2 tile still never touches HBM."""
    j = pl.program_id(1)
    l = pl.program_id(2)
    n_dc = pl.num_programs(2)
    s = _contract(x_ref[...], yhi_ref[...],
                  None if ylo_ref is None else ylo_ref[...])

    @pl.when(l == 0)
    def _():
        acc_ref[...] = s

    @pl.when(l != 0)
    def _():
        acc_ref[...] = acc_ref[...] + s

    @pl.when(l == n_dc - 1)
    def _():
        d2 = xx_ref[...] + yy_ref[...] - 2.0 * acc_ref[...]
        _fold_and_write(d2, j, m_real_ref, m1_ref, i1_ref, m2min_ref,
                        T=T, Qb=Qb)


# --- scaffolding shared by the single-shot and d-chunked calls (the
# out-spec index maps take (i, j, *rest) so the same lambdas serve both
# grid arities; *rest swallows the extra grid index + prefetch refs) ---

def _slot_out_specs(Qb: int):
    return [
        pl.BlockSpec((Qb, _LANES), lambda i, j, *_: (i, j),
                     memory_space=pltpu.VMEM),          # m1
        pl.BlockSpec((Qb, _LANES), lambda i, j, *_: (i, j),
                     memory_space=pltpu.VMEM),          # i1
        pl.BlockSpec((Qb, _LANES), lambda i, j, *_: (i, 0),
                     memory_space=pltpu.VMEM),          # m2min (revisited)
    ]


def _slot_out_shape(Q: int, S: int):
    return [
        jax.ShapeDtypeStruct((Q, S), jnp.float32),
        jax.ShapeDtypeStruct((Q, S), jnp.int32),
        jax.ShapeDtypeStruct((Q, _LANES), jnp.float32),
    ]


def _slot_cost(Q: int, M: int, d: int, S: int, passes: int):
    return pl.CostEstimate(
        flops=2 * Q * M * d * passes,
        bytes_accessed=(Q * d * 4 + M * d * 2 * (2 if passes == 3 else 1)
                        + Q * S * 8),
        transcendentals=0,
    )


def _check_tiling(T: int, Qb: int):
    """The folds iterate T // LANES lane-chunks and the 3-D carriers
    reshape Qb // 8: a non-multiple T would SILENTLY skip the tail
    columns of every tile (no pool entry, no certificate coverage), so
    the invariant is enforced at the kernel entry points, not just in
    knn_fused."""
    if T % _LANES:
        raise ValueError(f"T={T} must be a multiple of {_LANES}")
    if Qb % 8:
        raise ValueError(f"Qb={Qb} must be a multiple of 8")


def _check_pack_envelope(T: int, tpg: int, pbits: int = _PACK_BITS):
    if tpg * (T // _LANES) > (1 << pbits):
        raise ValueError(
            f"packed group kernel: tpg*T/128 = {tpg * T // _LANES} "
            f"exceeds the {1 << pbits}-code packing envelope")


def _check_pair_envelope(n_chunks: int):
    # silently falling back to the non-pair loop would make a benchmark
    # row labelled "pair" measure the baseline kernel
    if n_chunks % 2:
        raise ValueError(
            f"pair=True requires an even chunk count, got T/128 = "
            f"{n_chunks}")


def _make_kernel(base, passes: int, T: int, Qb: int, **fold_kw):
    """Bind the base kernel for the passes mode; for passes == 3 reorder
    the y_lo ref out of the positional stream (*rest carries the output
    refs and, for the d-chunked kernel, the scratch ref)."""
    if passes != 3:
        return functools.partial(base, T=T, Qb=Qb, ylo_ref=None, **fold_kw)

    def kernel(m_real_ref, x_ref, yhi_ref, ylo_ref, xx_ref, yy_ref, *rest):
        base(m_real_ref, x_ref, yhi_ref, xx_ref, yy_ref, *rest,
             T=T, Qb=Qb, ylo_ref=ylo_ref, **fold_kw)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "mask", "track"))
def fused_l2_slot_topk(x, y_hi, y_lo, xx, yy, m_real,
                       T: int, Qb: int, passes: int,
                       mask: bool = True, track: bool = True
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Run the fused kernel. ``mask``/``track`` are measurement-only
    knobs (see _fold_and_write) — production callers use the defaults.

    Args:
      x: [Q, d] f32 queries (Q a multiple of Qb).
      y_hi, y_lo: [M, d] bf16 hi/lo split of the padded index (M a multiple
        of T); ``y_lo`` is only DMA'd/read when passes == 3.
      xx, yy: exact f32 squared norms, [Q, 1] and [1, M] (padded rows'
        yy = 0 — they are masked in-kernel anyway).
      m_real: [1] int32 — real (unpadded) index row count.
      T: index tile length; Qb: query block; passes: 1 (bf16) or 3 (bf16x3).

    Returns:
      m1 [Q, S] f32, i1 [Q, S] int32, m2min [Q, LANES] f32 with
      S = (M // T) * LANES; slot s = (tile = s // LANES) × (lane-class =
      s % LANES); i1 holds GLOBAL index-row ids; padded-only slots keep
      m1 = +inf, i1 = -1.
    """
    _check_tiling(T, Qb)
    Q, d = x.shape
    M = y_hi.shape[0]
    n_tiles = M // T
    nq = Q // Qb
    S = n_tiles * _LANES

    y_spec = pl.BlockSpec((T, d), lambda i, j, *_: (j, 0),
                          memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((Qb, d), lambda i, j, *_: (i, 0),
                     memory_space=pltpu.VMEM),          # x
        y_spec,                                         # y_hi
        pl.BlockSpec((Qb, 1), lambda i, j, *_: (i, 0),
                     memory_space=pltpu.VMEM),          # xx
        pl.BlockSpec((1, T), lambda i, j, *_: (0, j),
                     memory_space=pltpu.VMEM),          # yy
    ]
    operands = [x, y_hi, xx, yy]
    if passes == 3:
        in_specs.insert(2, y_spec)                      # y_lo
        operands.insert(2, y_lo)
    kernel = _make_kernel(_fused_kernel, passes, T, Qb,
                          mask=mask, track=track)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nq, n_tiles),
        in_specs=in_specs,
        out_specs=_slot_out_specs(Qb),
    )
    m1, i1, m2min = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_slot_out_shape(Q, S),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=_slot_cost(Q, M, d, S, passes),
        interpret=interpret_mode(),
    )(m_real, *operands)
    return m1, i1, m2min


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "dc"))
def fused_l2_slot_topk_dchunk(x, y_hi, y_lo, xx, yy, m_real,
                              T: int, Qb: int, passes: int, dc: int = 256
                              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """d-chunked variant of :func:`fused_l2_slot_topk` for wide features
    (d > 512): grid (nq, n_tiles, d/dc) with the score tile accumulated
    in VMEM scratch across d-chunks (see _fused_kernel_dchunk). Same
    contract and outputs; caller pads d to a multiple of ``dc``."""
    _check_tiling(T, Qb)
    Q, d = x.shape
    M = y_hi.shape[0]
    if d % dc:
        raise ValueError(
            f"fused_l2_slot_topk_dchunk: d={d} must be a multiple of "
            f"dc={dc} (the tail would be silently dropped)")
    n_tiles = M // T
    nq = Q // Qb
    n_dc = d // dc
    S = n_tiles * _LANES

    y_spec = pl.BlockSpec((T, dc), lambda i, j, l, *_: (j, l),
                          memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((Qb, dc), lambda i, j, l, *_: (i, l),
                     memory_space=pltpu.VMEM),          # x
        y_spec,                                         # y_hi
        pl.BlockSpec((Qb, 1), lambda i, j, *_: (i, 0),
                     memory_space=pltpu.VMEM),          # xx
        pl.BlockSpec((1, T), lambda i, j, *_: (0, j),
                     memory_space=pltpu.VMEM),          # yy
    ]
    operands = [x, y_hi, xx, yy]
    if passes == 3:
        in_specs.insert(2, y_spec)                      # y_lo
        operands.insert(2, y_lo)
    kernel = _make_kernel(_fused_kernel_dchunk, passes, T, Qb)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nq, n_tiles, n_dc),
        in_specs=in_specs,
        out_specs=_slot_out_specs(Qb),
        scratch_shapes=[pltpu.VMEM((Qb, T), jnp.float32)],  # score acc
    )
    m1, i1, m2min = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_slot_out_shape(Q, S),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        cost_estimate=_slot_cost(Q, M, d, S, passes),
        interpret=interpret_mode(),
    )(m_real, *operands)
    return m1, i1, m2min


# --- in-kernel group fold: top-2 (+3rd-min) per (lane, tile-group) ---
#
# The slot kernel above writes one (min, argmin) per (tile, lane) slot —
# [Q, n_tiles·128] outputs that an XLA group-fold then compresses.
# MEASURED (v5e, 2048×1M×128): that fold alone costs 15.6 ms — 3× the
# whole Pallas kernel — because XLA re-reads the ~1 GB slot arrays from
# HBM. This variant keeps the fold INSIDE the kernel: output blocks are
# revisited across `tpg` CONSECUTIVE index tiles (block index j // tpg —
# consecutive, so Mosaic keeps the block VMEM-resident and writes it to
# HBM once per group), accumulating per-(lane, group) top-2 values+ids
# and the group 3rd-min. Outputs shrink ~tpg/2.5× and the XLA fold
# disappears. Keeping top-2 per group also upgrades the exactness
# certificate: a query now only fails when THREE true top-k share a
# (lane, group) — O(k³/S²) instead of O(k²/S) — so the fixup path runs
# orders of magnitude more rarely.


def _merge_chunk_top2(c, ci, a1, id1, a2, id2, a3):
    """Merge candidate chunk (values c, ids ci — [Qb, LANES]) into the
    running per-(lane, group) (top-2 + 3rd-min) accumulators. Pure VPU
    compare/selects; ~13 ops per element (vs 5 for the top-1 fold)."""
    lt1 = c < a1
    b1 = jnp.where(lt1, a1, c)          # loser of the round-1 compare
    bid1 = jnp.where(lt1, id1, ci)
    a1 = jnp.where(lt1, c, a1)
    id1 = jnp.where(lt1, ci, id1)
    lt2 = b1 < a2
    b2 = jnp.where(lt2, a2, b1)         # loser of the round-2 compare
    a2 = jnp.where(lt2, b1, a2)
    id2 = jnp.where(lt2, bid1, id2)
    a3 = jnp.minimum(a3, b2)
    return a1, id1, a2, id2, a3


def _group_fold_and_write(s, j, yyh_ref, a1_ref, id1_ref, a2_ref,
                          id2_ref, a3_ref, *, T: int, Qb: int, tpg: int):
    """Merge the [Qb, T] score tile ``s = x·y`` into the group
    accumulators (initialized at the first tile of each group), folding
    the half-score ``c = yy/2 − s`` chunk by chunk.

    VMEM discipline (every full [Qb, T] f32 live buffer is ~25% of the
    Mosaic 16 MB scoped stack at production tiles — measured 16.36 MB
    rejections at T=2048, Qb=512 before these cuts):
    - NO in-kernel padded-row masking: callers pass yy/2 = +inf for
      padded columns; +inf loses every strict `<`, so padded-only slots
      keep a=+inf, id=-1 (the old mask cost a col-iota + a masked copy).
    - the half-score is computed per [Qb, LANES] chunk from the [1, T]
      yy/2 block — never materialized at [Qb, T].
    - candidate ids enter the merge as broadcast [1, LANES] rows, not
      [Qb, LANES] tiles."""
    @pl.when(j % tpg == 0)
    def _():
        inf = jnp.full((Qb, _LANES), jnp.inf, jnp.float32)
        neg = jnp.full((Qb, _LANES), -1, jnp.int32)
        a1_ref[...] = inf
        a2_ref[...] = inf
        a3_ref[...] = inf
        id1_ref[...] = neg
        id2_ref[...] = neg

    # 3-D carriers [Qb/8, 8, LANES]: the [8, LANES] yy/2 slices and id
    # rows broadcast legally against them (numpy rules) and Mosaic keeps
    # native (8, 128) trailing tiles (a [1, N] source is an invalid-
    # layout broadcast; a full [Qb, T] materialization is a live-buffer
    # we can't afford)
    q8 = Qb // 8
    a1 = a1_ref[...].reshape(q8, 8, _LANES)
    id1 = id1_ref[...].reshape(q8, 8, _LANES)
    a2 = a2_ref[...].reshape(q8, 8, _LANES)
    id2 = id2_ref[...].reshape(q8, 8, _LANES)
    a3 = a3_ref[...].reshape(q8, 8, _LANES)
    lane = jax.lax.broadcasted_iota(jnp.int32, (8, _LANES), 1)
    yyh = yyh_ref[...]                                   # [8, T]
    for r in range(T // _LANES):
        sl = slice(r * _LANES, (r + 1) * _LANES)
        c = yyh[:, sl] - s[:, sl].reshape(q8, 8, _LANES)
        ci = j * T + r * _LANES + lane                   # [8, LANES]
        a1, id1, a2, id2, a3 = _merge_chunk_top2(
            c, ci, a1, id1, a2, id2, a3)
    a1_ref[...], id1_ref[...] = (a1.reshape(Qb, _LANES),
                                 id1.reshape(Qb, _LANES))
    a2_ref[...], id2_ref[...] = (a2.reshape(Qb, _LANES),
                                 id2.reshape(Qb, _LANES))
    a3_ref[...] = a3.reshape(Qb, _LANES)


# --- PACKED group fold: candidate code embedded in the value mantissa ---
#
# The unpacked merge spends ~half its VPU ops and register pressure on
# i32 id selects. Instead, the low _PACK_BITS mantissa bits of each
# half-score are REPLACED by the candidate's within-group code
# (tile-offset-in-group · chunks + chunk — the lane and group are
# implicit in the output position), so the merge is 3 compares + 4
# selects on f32 only, ids travel for free through every compare,
# top_k, and negation downstream, and the id output arrays + the pool
# id gather disappear. Cost: values carry a ≤ |v|·2⁻¹⁵ packing error —
# absorbed into the certificate's analytic bound (rescoring is exact
# f32 regardless). Envelope: tpg·(T/128) ≤ 2^_PACK_BITS slots per
# group (the measured-optimal configs sit exactly at 256), and padded
# columns use the finite _PACK_PAD sentinel (+inf would become NaN
# when id bits are OR'd into its mantissa).



def _merge_chunk_top2_packed(cp, a1, a2, a3):
    """5-op packed merge: top-2 + 3rd-min by packed-f32 order.

    Pure min/max network (no compare+select pairs — min/max are single
    VPU ops where lt+where is two): with the invariant a1 ≤ a2, the
    round-1 loser max(a1, cp) either stays ≥ a2 (cp wins nothing) or
    becomes the new 2nd; the round-2 loser max(a2, ·) is exactly the
    3rd-smallest seen, which feeds the certificate bound."""
    b1 = jnp.maximum(a1, cp)
    a1 = jnp.minimum(a1, cp)
    b2 = jnp.maximum(a2, b1)
    a2 = jnp.minimum(a2, b1)
    a3 = jnp.minimum(a3, b2)
    return a1, a2, a3


def _group_fold_and_write_packed(s, j, yyh_ref, a1_ref, a2_ref, a3_ref,
                                 *, T: int, Qb: int, tpg: int,
                                 pair: bool = False,
                                 pbits: int = _PACK_BITS, xxh_ref=None):
    """Packed variant of _group_fold_and_write: same VMEM discipline
    (per-chunk half-scores, 3-D carriers, no masking — callers pass
    yy/2 = _PACK_PAD on padded columns), but the merge runs on packed
    values only (see the block comment above).

    ``pair=True`` inserts a pairwise pre-reduction: adjacent chunks are
    min-combined BEFORE packing/merging (the pack + top-2 merge then run
    on half the stream — ~8 effective VPU ops/element vs ~10), and each
    pair's loser feeds the 3rd-min tracker directly, so the certificate
    stays sound: every value discarded anywhere still lower-bounds into
    a3. Cost: a query now also needs fixup when TWO true top-k collide
    in one (lane, chunk-pair) — ~2× the three-share-a-group rate, still
    single-digit per 2048 queries at production scale (measured)."""
    n_chunks = T // _LANES

    @pl.when(j % tpg == 0)
    def _():
        big = jnp.full((Qb, _LANES), _PACK_PAD, jnp.float32)
        a1_ref[...] = big
        a2_ref[...] = big
        a3_ref[...] = big

    q8 = Qb // 8
    a1 = a1_ref[...].reshape(q8, 8, _LANES)
    a2 = a2_ref[...].reshape(q8, 8, _LANES)
    a3 = a3_ref[...].reshape(q8, 8, _LANES)
    yyh = yyh_ref[...]                                   # [8, T]
    xxh = (None if xxh_ref is None
           else xxh_ref[...].reshape(q8, 8, 1))          # [Qb, 1] → 3-D

    def half_score(r):
        sl = slice(r * _LANES, (r + 1) * _LANES)
        c = yyh[:, sl] - s[:, sl].reshape(q8, 8, _LANES)
        # with the query half-norm folded in, c = d2/2 — SMALL, so the
        # pack perturbation is relative to the distances being
        # compared, not to the (often 10×) norm-dominated half-score
        return c if xxh is None else c + xxh

    def pack(c, code):
        return jax.lax.bitcast_convert_type(
            (jax.lax.bitcast_convert_type(c, jnp.int32)
             & ~((1 << pbits) - 1)) | code, jnp.float32)

    if pair:
        _check_pair_envelope(n_chunks)
        for r in range(0, n_chunks, 2):
            c0, c1 = half_score(r), half_score(r + 1)
            mn = jnp.minimum(c0, c1)
            a3 = jnp.minimum(a3, jnp.maximum(c0, c1))
            base = (j % tpg) * n_chunks + r              # even → bit0 free
            cp = pack(mn, jnp.where(mn == c1, base + 1, base))
            a1, a2, a3 = _merge_chunk_top2_packed(cp, a1, a2, a3)
    else:
        for r in range(n_chunks):
            local = (j % tpg) * n_chunks + r             # scalar code
            cp = pack(half_score(r), local)
            a1, a2, a3 = _merge_chunk_top2_packed(cp, a1, a2, a3)
    a1_ref[...] = a1.reshape(Qb, _LANES)
    a2_ref[...] = a2.reshape(Qb, _LANES)
    a3_ref[...] = a3.reshape(Qb, _LANES)


def _group_kernel_packed(m_real_ref, x_ref, yhi_ref, yyh_ref,
                         a1_ref, a2_ref, a3_ref,
                         *, T: int, Qb: int, tpg: int, pair: bool = False,
                         pbits: int = _PACK_BITS, ylo_ref=None,
                         xxh_ref=None):
    j = pl.program_id(1)
    s = _contract(x_ref[...], yhi_ref[...],
                  None if ylo_ref is None else ylo_ref[...])
    _group_fold_and_write_packed(s, j, yyh_ref, a1_ref, a2_ref, a3_ref,
                                 T=T, Qb=Qb, tpg=tpg, pair=pair,
                                 pbits=pbits, xxh_ref=xxh_ref)


def _group_kernel_packed_stream(m_real_ref, x_ref, yhi_ref, yyh_ref,
                                a1_ref, a2_ref, a3_ref,
                                *, T: int, Qb: int, tpg: int,
                                pair: bool = False,
                                pbits: int = _PACK_BITS, ylo_ref=None,
                                xxh_ref=None):
    """Streamed variant: the [Qb, T] contraction is split into T/LANES
    [Qb, LANES] chunk contractions interleaved with the fold of the
    PREVIOUS chunk. The big-matmul kernel serializes MXU (contract) then
    VPU (fold) per cell; emitting them as independent small ops lets
    Mosaic's VLIW scheduler co-issue fold(r) with contract(r+1) — the
    in-kernel analog of double-buffering, targeting
    max(matmul, fold) instead of matmul + fold per cell. Also drops the
    live [Qb, T] f32 score buffer (only [Qb, LANES] chunks live)."""
    j = pl.program_id(1)
    n_chunks = T // _LANES

    @pl.when(j % tpg == 0)
    def _():
        big = jnp.full((Qb, _LANES), _PACK_PAD, jnp.float32)
        a1_ref[...] = big
        a2_ref[...] = big
        a3_ref[...] = big

    q8 = Qb // 8
    a1 = a1_ref[...].reshape(q8, 8, _LANES)
    a2 = a2_ref[...].reshape(q8, 8, _LANES)
    a3 = a3_ref[...].reshape(q8, 8, _LANES)
    x = x_ref[...]
    yhi = yhi_ref[...]
    ylo = None if ylo_ref is None else ylo_ref[...]
    yyh = yyh_ref[...]                                   # [8, T]
    xxh = (None if xxh_ref is None
           else xxh_ref[...].reshape(q8, 8, 1))          # [Qb, 1] → 3-D

    def chunk_score(r):
        sl = slice(r * _LANES, (r + 1) * _LANES)
        s_r = _contract(x, yhi[sl, :], None if ylo is None else ylo[sl, :])
        c = yyh[:, sl] - s_r.reshape(q8, 8, _LANES)
        # c + xx/2 = d2/2 (see _group_fold_and_write_packed)
        return c if xxh is None else c + xxh

    def pack(c, code):
        return jax.lax.bitcast_convert_type(
            (jax.lax.bitcast_convert_type(c, jnp.int32)
             & ~((1 << pbits) - 1)) | code, jnp.float32)

    if pair:
        _check_pair_envelope(n_chunks)
        for r in range(0, n_chunks, 2):
            c0, c1 = chunk_score(r), chunk_score(r + 1)
            mn = jnp.minimum(c0, c1)
            a3 = jnp.minimum(a3, jnp.maximum(c0, c1))
            base = (j % tpg) * n_chunks + r              # even → bit0 free
            cp = pack(mn, jnp.where(mn == c1, base + 1, base))
            a1, a2, a3 = _merge_chunk_top2_packed(cp, a1, a2, a3)
    else:
        for r in range(n_chunks):
            cp = pack(chunk_score(r), (j % tpg) * n_chunks + r)
            a1, a2, a3 = _merge_chunk_top2_packed(cp, a1, a2, a3)
    a1_ref[...] = a1.reshape(Qb, _LANES)
    a2_ref[...] = a2.reshape(Qb, _LANES)
    a3_ref[...] = a3.reshape(Qb, _LANES)


def _group_kernel_packed_dchunk(m_real_ref, x_ref, yhi_ref, yyh_ref,
                                a1_ref, a2_ref, a3_ref, acc_ref,
                                *, T: int, Qb: int, tpg: int,
                                pair: bool = False,
                                pbits: int = _PACK_BITS, ylo_ref=None,
                                xxh_ref=None):
    j = pl.program_id(1)
    l = pl.program_id(2)
    n_dc = pl.num_programs(2)
    s = _contract(x_ref[...], yhi_ref[...],
                  None if ylo_ref is None else ylo_ref[...])

    @pl.when(l == 0)
    def _():
        acc_ref[...] = s

    @pl.when(l != 0)
    def _():
        acc_ref[...] = acc_ref[...] + s

    @pl.when(l == n_dc - 1)
    def _():
        _group_fold_and_write_packed(acc_ref[...], j, yyh_ref, a1_ref,
                                     a2_ref, a3_ref, T=T, Qb=Qb, tpg=tpg,
                                     pair=pair, pbits=pbits,
                                     xxh_ref=xxh_ref)


# --- DATABASE-MAJOR variants: stream y from HBM ~once ----------------
#
# The query-major grid (nq, n_tiles) re-fetches EVERY y tile for every
# query block: y HBM traffic = nq · M · d bytes. At the driver shape
# (2048×1M×128, Qb=256 ⇒ nq=8) that re-fetch alone accounts for most of
# the measured 460-vs-820 GB/s roofline gap (round 5). These variants
# invert the loop so the database streams ~once:
#
# - "db" (super-blocked): grid (n_groups, nq) with the WHOLE certificate
#   group [tpg·T, d] as one resident y block, index (sidx, i) → (sidx,)
#   — constant across the inner query loop, so Mosaic fetches each
#   super-block exactly once (y traffic = M·d·2 bytes total) and its
#   standard pipeline DMAs super-block sidx+1 while the last query block
#   of sidx computes (one cell ≈ Qb·tpg·T·d·2 MXU flops ≈ 2× the
#   super-block DMA time at production tiles — the prefetch hides).
#   Each cell folds the full group in one shot, so the group outputs are
#   written ONCE per (i, sidx) — no revisited-output accumulation to
#   keep legal under the inverted order. x blocks are re-fetched once
#   per super-block (n_groups · Q · d · 4 bytes — the traffic the
#   autotuner trades against the saved y stream; see
#   observability.costmodel.fused_traffic_model).
# - "dbuf" (explicit double-buffered): grid (n_groups,) with y in
#   ANY/HBM and a manual 2-slot async-copy pipeline: tile jj+1's DMA is
#   issued before tile jj's fold runs, so the HBM stream overlaps the
#   MXU/VPU work at TILE granularity and only 2 tiles are VMEM-resident
#   (the tpg envelope is no longer VMEM-bound). The cell covers the
#   WHOLE query batch (fold state [Q, 128] — the VMEM cost that
#   replaces the resident super-block), x is resident and fetched once:
#   y traffic = M·d·2, x traffic = Q·d·4, both single-stream.
#
# Both are packed-only (the production path): same outputs, codes and
# certificate semantics as fused_l2_group_topk_packed — group sidx maps
# to output columns [sidx·128, (sidx+1)·128), the within-group code is
# jj·(T/128) + chunk — so decode_packed_pool and the twin-pool
# certificate in knn_fused work unchanged. Callers pad the index to a
# whole number of groups (tpg·T rows); padded columns carry the
# _PACK_PAD sentinel in yy_half exactly as before.


def _fold_tile_packed(acc, x, ythi, ytlo, yyh_t, xxh, jj: int,
                      *, T: int, Qb: int, pair: bool, pbits: int,
                      scale=None, passes: int = 1):
    """Fold ONE y tile (rows [T, d], half-norms yyh_t [8, T]) into the
    packed (a1, a2, a3) carriers with within-group tile offset ``jj`` —
    the per-tile body shared by the database-major kernels. Chunk
    contractions are emitted individually (the "stream" idiom) so
    Mosaic co-issues fold(r) with contract(r+1).

    ``scale`` (an [8, LANES] group-replicated f32 tile) switches the
    tile to the INT8 path: ``ythi`` is then the int8 tile, ``ytlo`` is
    unused, the contraction runs through :func:`_contract_q8` (passes
    splits the x operand only) and the quantized partial scores are
    rescaled after accumulation — the in-register dequantize of the
    quantized-streaming design. The half-norm carrier must hold the
    DEQUANTIZED rows' norms, so the folded value is exactly
    d2(x, ŷ)/2 and every downstream consumer (codes, certificate,
    decode) is untouched."""
    a1, a2, a3 = acc
    n_chunks = T // _LANES
    q8 = Qb // 8

    def chunk_score(r):
        sl = slice(r * _LANES, (r + 1) * _LANES)
        if scale is None:
            s_r = _contract(x, ythi[sl, :],
                            None if ytlo is None else ytlo[sl, :])
            s3 = s_r.reshape(q8, 8, _LANES)
        else:
            s_r = _contract_q8(x, ythi[sl, :], passes)
            s3 = s_r.reshape(q8, 8, _LANES) * scale
        c = yyh_t[:, sl] - s3
        # c + xx/2 = d2/2 (see _group_fold_and_write_packed)
        return c if xxh is None else c + xxh

    def pack(c, code):
        return jax.lax.bitcast_convert_type(
            (jax.lax.bitcast_convert_type(c, jnp.int32)
             & ~((1 << pbits) - 1)) | code, jnp.float32)

    if pair:
        _check_pair_envelope(n_chunks)
        for r in range(0, n_chunks, 2):
            c0, c1 = chunk_score(r), chunk_score(r + 1)
            mn = jnp.minimum(c0, c1)
            a3 = jnp.minimum(a3, jnp.maximum(c0, c1))
            base = jj * n_chunks + r                     # even → bit0 free
            cp = pack(mn, jnp.where(mn == c1, base + 1, base))
            a1, a2, a3 = _merge_chunk_top2_packed(cp, a1, a2, a3)
    else:
        for r in range(n_chunks):
            cp = pack(chunk_score(r), jj * n_chunks + r)
            a1, a2, a3 = _merge_chunk_top2_packed(cp, a1, a2, a3)
    return a1, a2, a3


def _group_kernel_packed_db(m_real_ref, x_ref, yhi_ref, yyh_ref,
                            a1_ref, a2_ref, a3_ref,
                            *, T: int, Qb: int, tpg: int,
                            pair: bool = False, pbits: int = _PACK_BITS,
                            ylo_ref=None, xxh_ref=None):
    """Database-major super-blocked cell: the resident [tpg·T, d] y
    block is folded whole (static tile loop), outputs written once."""
    q8 = Qb // 8
    big = jnp.full((q8, 8, _LANES), _PACK_PAD, jnp.float32)
    acc = (big, big, big)
    x = x_ref[...]
    yyh = yyh_ref[...]                                   # [8, tpg·T]
    xxh = (None if xxh_ref is None
           else xxh_ref[...].reshape(q8, 8, 1))
    for jj in range(tpg):
        rs = slice(jj * T, (jj + 1) * T)
        acc = _fold_tile_packed(
            acc, x, yhi_ref[rs, :],
            None if ylo_ref is None else ylo_ref[rs, :],
            yyh[:, rs], xxh, jj, T=T, Qb=Qb, pair=pair, pbits=pbits)
    a1_ref[...] = acc[0].reshape(Qb, _LANES)
    a2_ref[...] = acc[1].reshape(Qb, _LANES)
    a3_ref[...] = acc[2].reshape(Qb, _LANES)


def _group_kernel_packed_dbuf(m_real_ref, x_ref, yhi_ref, yyh_ref,
                              a1_ref, a2_ref, a3_ref,
                              *, T: int, Qb: int, tpg: int,
                              pair: bool = False, pbits: int = _PACK_BITS,
                              ylo_ref=None, xxh_ref=None):
    """Explicit double-buffered database streaming: y_hi (and y_lo)
    stay in ANY/HBM; tiles ride a 2-slot VMEM scratch whose next-tile
    async copy is issued BEFORE the current tile's fold, so the DMA
    overlaps the MXU contraction. Grid (n_groups,) — one cell covers
    the whole query batch (Qb == padded Q)."""
    sidx = pl.program_id(0)
    d = yhi_ref.shape[1]
    q8 = Qb // 8

    def body(scratch_hi, sem_hi, scratch_lo=None, sem_lo=None):
        def dma(ref, scr, sem, slot, jj):
            return pltpu.make_async_copy(
                ref.at[pl.ds((sidx * tpg + jj) * T, T), :],
                scr.at[slot], sem.at[slot])

        def start(slot, jj):
            dma(yhi_ref, scratch_hi, sem_hi, slot, jj).start()
            if scratch_lo is not None:
                dma(ylo_ref, scratch_lo, sem_lo, slot, jj).start()

        def wait(slot, jj):
            dma(yhi_ref, scratch_hi, sem_hi, slot, jj).wait()
            if scratch_lo is not None:
                dma(ylo_ref, scratch_lo, sem_lo, slot, jj).wait()

        start(0, 0)
        big = jnp.full((q8, 8, _LANES), _PACK_PAD, jnp.float32)
        acc = (big, big, big)
        x = x_ref[...]
        yyh = yyh_ref[...]                               # [8, tpg·T]
        xxh = (None if xxh_ref is None
               else xxh_ref[...].reshape(q8, 8, 1))
        for jj in range(tpg):
            slot = jj % 2
            if jj + 1 < tpg:
                start((jj + 1) % 2, jj + 1)              # prefetch next
            wait(slot, jj)
            acc = _fold_tile_packed(
                acc, x, scratch_hi[slot],
                None if scratch_lo is None else scratch_lo[slot],
                yyh[:, jj * T:(jj + 1) * T], xxh, jj,
                T=T, Qb=Qb, pair=pair, pbits=pbits)
        a1_ref[...] = acc[0].reshape(Qb, _LANES)
        a2_ref[...] = acc[1].reshape(Qb, _LANES)
        a3_ref[...] = acc[2].reshape(Qb, _LANES)

    scoped = dict(scratch_hi=pltpu.VMEM((2, T, d), jnp.bfloat16),
                  sem_hi=pltpu.SemaphoreType.DMA((2,)))
    if ylo_ref is not None:
        scoped.update(scratch_lo=pltpu.VMEM((2, T, d), jnp.bfloat16),
                      sem_lo=pltpu.SemaphoreType.DMA((2,)))
    pl.run_scoped(body, **scoped)


def _group_kernel_packed_db_q8(m_real_ref, x_ref, yq_ref, yyh_ref,
                               scl_ref, xxh_ref,
                               a1_ref, a2_ref, a3_ref,
                               *, T: int, Qb: int, tpg: int, passes: int,
                               pair: bool = False,
                               pbits: int = _PACK_BITS):
    """INT8 database-major super-blocked cell: the resident [tpg·T, d]
    y block is the QUANTIZED int8 slab (half the bf16 stream, a quarter
    of the bf16x3 one); the per-group scale tile rescales the quantized
    partial scores in-register after the MXU contraction (see
    _contract_q8). Same outputs/codes/certificate semantics as
    _group_kernel_packed_db."""
    q8 = Qb // 8
    big = jnp.full((q8, 8, _LANES), _PACK_PAD, jnp.float32)
    acc = (big, big, big)
    x = x_ref[...]
    yyh = yyh_ref[...]                                   # [8, tpg·T]
    scale = scl_ref[0]                                   # [8, LANES]
    xxh = xxh_ref[...].reshape(q8, 8, 1)
    for jj in range(tpg):
        rs = slice(jj * T, (jj + 1) * T)
        acc = _fold_tile_packed(
            acc, x, yq_ref[rs, :], None, yyh[:, rs], xxh, jj,
            T=T, Qb=Qb, pair=pair, pbits=pbits, scale=scale,
            passes=passes)
    a1_ref[...] = acc[0].reshape(Qb, _LANES)
    a2_ref[...] = acc[1].reshape(Qb, _LANES)
    a3_ref[...] = acc[2].reshape(Qb, _LANES)


def _group_kernel_packed_dbuf_q8(m_real_ref, x_ref, yq_ref, yyh_ref,
                                 scl_ref, xxh_ref,
                                 a1_ref, a2_ref, a3_ref,
                                 *, T: int, Qb: int, tpg: int,
                                 passes: int, pair: bool = False,
                                 pbits: int = _PACK_BITS):
    """INT8 explicit double-buffered database streaming: like
    _group_kernel_packed_dbuf but the manual 2-slot DMA pipeline moves
    int8 tiles (1 byte/element on the wire; the dequantize is the
    post-accumulation rescale, never a widened copy in VMEM)."""
    sidx = pl.program_id(0)
    d = yq_ref.shape[1]
    q8 = Qb // 8

    def body(scratch_q, sem_q):
        def dma(slot, jj):
            return pltpu.make_async_copy(
                yq_ref.at[pl.ds((sidx * tpg + jj) * T, T), :],
                scratch_q.at[slot], sem_q.at[slot])

        dma(0, 0).start()
        big = jnp.full((q8, 8, _LANES), _PACK_PAD, jnp.float32)
        acc = (big, big, big)
        x = x_ref[...]
        yyh = yyh_ref[...]                               # [8, tpg·T]
        scale = scl_ref[0]                               # [8, LANES]
        xxh = xxh_ref[...].reshape(q8, 8, 1)
        for jj in range(tpg):
            slot = jj % 2
            if jj + 1 < tpg:
                dma((jj + 1) % 2, jj + 1).start()        # prefetch next
            dma(slot, jj).wait()
            acc = _fold_tile_packed(
                acc, x, scratch_q[slot], None,
                yyh[:, jj * T:(jj + 1) * T], xxh, jj,
                T=T, Qb=Qb, pair=pair, pbits=pbits, scale=scale,
                passes=passes)
        a1_ref[...] = acc[0].reshape(Qb, _LANES)
        a2_ref[...] = acc[1].reshape(Qb, _LANES)
        a3_ref[...] = acc[2].reshape(Qb, _LANES)

    pl.run_scoped(body, scratch_q=pltpu.VMEM((2, T, d), jnp.int8),
                  sem_q=pltpu.SemaphoreType.DMA((2,)))


def _group_kernel(m_real_ref, x_ref, yhi_ref, yyh_ref,
                  a1_ref, id1_ref, a2_ref, id2_ref, a3_ref,
                  *, T: int, Qb: int, tpg: int, ylo_ref=None):
    """Folds the HALF-SCORE r = yy/2 − s (NOT the full distance): per
    query row, d2 = 2·r + xx is a positive-scale + per-row-shift of r,
    so per-row top-2 ordering is identical and the caller recovers true
    distances on the tiny [Q, S'] outputs. Dropping xx and the ·2 from
    the kernel removes one live [Qb, T] f32 buffer from the broadcast
    chain — the difference between 16.36 MB (scoped-VMEM reject at
    T=2048, Qb=512) and fitting."""
    j = pl.program_id(1)
    s = _contract(x_ref[...], yhi_ref[...],
                  None if ylo_ref is None else ylo_ref[...])
    _group_fold_and_write(s, j, yyh_ref, a1_ref, id1_ref, a2_ref,
                          id2_ref, a3_ref, T=T, Qb=Qb, tpg=tpg)


def _group_kernel_dchunk(m_real_ref, x_ref, yhi_ref, yyh_ref,
                         a1_ref, id1_ref, a2_ref, id2_ref, a3_ref, acc_ref,
                         *, T: int, Qb: int, tpg: int, ylo_ref=None):
    j = pl.program_id(1)
    l = pl.program_id(2)
    n_dc = pl.num_programs(2)
    s = _contract(x_ref[...], yhi_ref[...],
                  None if ylo_ref is None else ylo_ref[...])

    @pl.when(l == 0)
    def _():
        acc_ref[...] = s

    @pl.when(l != 0)
    def _():
        acc_ref[...] = acc_ref[...] + s

    @pl.when(l == n_dc - 1)
    def _():
        _group_fold_and_write(acc_ref[...], j, yyh_ref, a1_ref, id1_ref,
                              a2_ref, id2_ref, a3_ref, T=T, Qb=Qb, tpg=tpg)


def _make_group_kernel(base, passes: int, T: int, Qb: int,
                       has_xxh: bool = False, **fold_kw):
    """Bind the group-kernel base for the passes mode, pulling the
    optional y_lo (passes == 3) and xxh (packed kernels with the query
    half-norm folded in) refs out of the positional operand stream."""

    def kernel(m_real_ref, x_ref, yhi_ref, *rest0):
        rest = list(rest0)
        ylo_ref = rest.pop(0) if passes == 3 else None
        yyh_ref = rest.pop(0)
        kw = dict(fold_kw)
        if has_xxh:
            kw["xxh_ref"] = rest.pop(0)
        base(m_real_ref, x_ref, yhi_ref, yyh_ref, *rest,
             T=T, Qb=Qb, ylo_ref=ylo_ref, **kw)

    return kernel


def _group_out_specs(Qb: int, tpg: int):
    spec = pl.BlockSpec((Qb, _LANES), lambda i, j, *_: (i, j // tpg),
                        memory_space=pltpu.VMEM)
    return [spec] * 5


def _group_out_shape(Q: int, Sg: int):
    return [
        jax.ShapeDtypeStruct((Q, Sg), jnp.float32),   # a1
        jax.ShapeDtypeStruct((Q, Sg), jnp.int32),     # id1
        jax.ShapeDtypeStruct((Q, Sg), jnp.float32),   # a2
        jax.ShapeDtypeStruct((Q, Sg), jnp.int32),     # id2
        jax.ShapeDtypeStruct((Q, Sg), jnp.float32),   # a3
    ]


def _packed_out_shape(Q: int, Sg: int):
    return [jax.ShapeDtypeStruct((Q, Sg), jnp.float32)] * 3


def _group_pallas_call(kernel_base, packed: bool,
                       x, y_hi, y_lo, yy_half, m_real,
                       *, T: int, Qb: int, passes: int, tpg: int,
                       dc=None, xxh=None, **fold_kw):
    """Shared scaffolding for the four group-fold entry points
    ((un)packed × (single-shot | d-chunked)) — specs, operands, grid and
    pallas_call in ONE place so the variants cannot drift."""
    _check_tiling(T, Qb)
    Q, d = x.shape
    M = y_hi.shape[0]
    n_tiles = M // T
    nq = Q // Qb
    G = -(-n_tiles // tpg)
    if dc is None:
        y_spec = pl.BlockSpec((T, d), lambda i, j, *_: (j, 0),
                              memory_space=pltpu.VMEM)
        x_spec = pl.BlockSpec((Qb, d), lambda i, j, *_: (i, 0),
                              memory_space=pltpu.VMEM)
        grid = (nq, n_tiles)
        semantics = ("parallel", "arbitrary")
        scratch = []
    else:
        if d % dc:
            raise ValueError(
                f"fused_l2_group_topk*_dchunk: d={d} must be a multiple "
                f"of dc={dc} (the tail would be silently dropped)")
        y_spec = pl.BlockSpec((T, dc), lambda i, j, l, *_: (j, l),
                              memory_space=pltpu.VMEM)
        x_spec = pl.BlockSpec((Qb, dc), lambda i, j, l, *_: (i, l),
                              memory_space=pltpu.VMEM)
        grid = (nq, n_tiles, d // dc)
        semantics = ("parallel", "arbitrary", "arbitrary")
        scratch = [pltpu.VMEM((Qb, T), jnp.float32)]  # score accumulator

    in_specs = [
        x_spec,
        y_spec,                                         # y_hi
        pl.BlockSpec((8, T), lambda i, j, *_: (0, j),
                     memory_space=pltpu.VMEM),          # yy_half
    ]
    operands = [x, y_hi, yy_half]
    if passes == 3:
        in_specs.insert(2, y_spec)                      # y_lo
        operands.insert(2, y_lo)
    if xxh is not None:
        in_specs.append(pl.BlockSpec((Qb, 1), lambda i, j, *_: (i, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(xxh)
    kernel = _make_group_kernel(kernel_base, passes, T, Qb, tpg=tpg,
                                has_xxh=xxh is not None, **fold_kw)

    n_out = 3 if packed else 5
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=_group_out_specs(Qb, tpg)[:n_out],
        scratch_shapes=scratch,
    )
    out_shape = (_packed_out_shape if packed else _group_out_shape)(
        Q, G * _LANES)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=semantics,
        ),
        cost_estimate=_slot_cost(Q, M, d, G * _LANES, passes),
        interpret=interpret_mode(),
    )(m_real, *operands)


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "tpg"))
def fused_l2_group_topk(x, y_hi, y_lo, yy_half, m_real,
                        T: int, Qb: int, passes: int, tpg: int = 16):
    """Fused kernel with the IN-KERNEL group fold (see block comment).

    Folds the HALF-SCORE ``r = yy/2 − x·y`` (see _group_kernel): callers
    pass ``yy_half`` as an ``[8, M]`` sublane-replicated carrier (8 =
    native vreg sublane count; Mosaic rejects [1, N]→[Qb, N] broadcasts
    of sliced rows) holding ‖y‖²/2 with +inf on padded index columns (no
    in-kernel mask; ``m_real`` stays as a prefetch operand for interface
    stability but is not read) and recover true squared distances as
    ``2·a + xx`` on the outputs. ``tpg`` = index tiles per group.
    Returns ``(a1, id1, a2, id2, a3)``, each ``[Q, G·LANES]`` with
    ``G = ceil(n_tiles / tpg)``: per (lane-class, tile-group) the two
    smallest half-scores with their GLOBAL index-row ids, and the
    3rd-smallest (certificate input: every point outside a group's
    top-2 is ≥ that group's a3). Padded-only groups keep a=+inf,
    id=-1."""
    return _group_pallas_call(_group_kernel, False, x, y_hi, y_lo,
                              yy_half, m_real, T=T, Qb=Qb, passes=passes,
                              tpg=tpg)


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "tpg", "dc"))
def fused_l2_group_topk_dchunk(x, y_hi, y_lo, yy_half, m_real,
                               T: int, Qb: int, passes: int, tpg: int = 16,
                               dc: int = 256):
    """d-chunked variant of :func:`fused_l2_group_topk` (wide features):
    grid (nq, n_tiles, d/dc), score accumulated in VMEM scratch, the
    group fold runs on the last d-chunk only. Same (half-score)
    outputs."""
    return _group_pallas_call(_group_kernel_dchunk, False, x, y_hi, y_lo,
                              yy_half, m_real, T=T, Qb=Qb, passes=passes,
                              tpg=tpg, dc=dc)


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "tpg", "pair",
                                    "stream", "pbits"))
def fused_l2_group_topk_packed(x, y_hi, y_lo, yy_half, m_real,
                               T: int, Qb: int, passes: int,
                               tpg: int = 16, pair: bool = False,
                               stream: bool = False,
                               pbits: int = _PACK_BITS, xxh=None):
    """Packed-id variant of :func:`fused_l2_group_topk` (see the PACKED
    block comment): returns ``(a1p, a2p, a3p)``, each ``[Q, G·LANES]``
    f32 whose low _PACK_BITS mantissa bits hold the candidate's
    within-group code ``tile_offset·(T/LANES) + chunk`` (a3p's code is
    meaningless — only its value is used). ``yy_half`` must carry the
    finite ``_PACK_PAD`` sentinel (NOT +inf) on padded columns.
    Requires tpg·(T/LANES) ≤ 2^_PACK_BITS. ``pair`` enables the
    pairwise pre-reduction (see _group_fold_and_write_packed);
    ``stream`` the chunked MXU/VPU-overlap contraction (see
    _group_kernel_packed_stream)."""
    _check_pack_envelope(T, tpg, pbits)
    base = _group_kernel_packed_stream if stream else _group_kernel_packed
    return _group_pallas_call(base, True, x, y_hi, y_lo,
                              yy_half, m_real, T=T, Qb=Qb, passes=passes,
                              tpg=tpg, pair=pair, pbits=pbits, xxh=xxh)


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "tpg", "dc",
                                    "pair", "pbits"))
def fused_l2_group_topk_packed_dchunk(x, y_hi, y_lo, yy_half, m_real,
                                      T: int, Qb: int, passes: int,
                                      tpg: int = 16, dc: int = 256,
                                      pair: bool = False,
                                      pbits: int = _PACK_BITS, xxh=None):
    """d-chunked packed variant (wide features): same contract as
    :func:`fused_l2_group_topk_packed`."""
    _check_pack_envelope(T, tpg, pbits)
    return _group_pallas_call(_group_kernel_packed_dchunk, True, x, y_hi,
                              y_lo, yy_half, m_real, T=T, Qb=Qb,
                              passes=passes, tpg=tpg, dc=dc, pair=pair,
                              pbits=pbits, xxh=xxh)


def _group_pallas_call_db(dbuf: bool, x, y_hi, y_lo, yy_half, m_real,
                          *, T: int, Qb: int, passes: int, tpg: int,
                          pair: bool, pbits: int, xxh, scale_k=None):
    """Scaffolding for the database-major packed entry points (specs,
    grid, pallas_call in ONE place, mirroring _group_pallas_call).

    ``scale_k`` ([n_groups, 8, LANES] f32, group-replicated) switches
    the call to the INT8 kernels: ``y_hi`` is then the int8 slab,
    ``y_lo`` must be None and ``xxh`` is required (the quantized path
    always folds the query half-norm — it is the production packed
    configuration)."""
    _check_tiling(T, Qb)
    _check_pack_envelope(T, tpg, pbits)
    Q, d = x.shape
    M = y_hi.shape[0]
    q8_mode = scale_k is not None
    if q8_mode and (y_lo is not None or xxh is None):
        raise ValueError("db-major q8 fused kernel: int8 mode takes no "
                         "y_lo and requires xxh")
    if M % (tpg * T):
        raise ValueError(
            f"database-major fused kernel: index rows M={M} must be a "
            f"whole number of [tpg·T = {tpg * T}]-row groups — pad the "
            f"index (knn_fused's _prepare_ops does when grid_order is "
            f"'db'/'dbuf')")
    n_groups = M // (tpg * T)
    if dbuf:
        # one cell spans the whole query batch (fold state [Q, 128])
        Qb = Q
    if Q % Qb:
        raise ValueError(f"db-major fused kernel: Q={Q} must be a "
                         f"multiple of Qb={Qb}")
    nq = Q // Qb

    if dbuf:
        grid = (n_groups,)
        x_spec = pl.BlockSpec((Qb, d), lambda s, *_: (0, 0),
                              memory_space=pltpu.VMEM)
        y_spec = pl.BlockSpec(memory_space=pltpu.ANY)   # manual DMA
        yy_spec = pl.BlockSpec((8, tpg * T), lambda s, *_: (0, s),
                               memory_space=pltpu.VMEM)
        xx_spec = pl.BlockSpec((Qb, 1), lambda s, *_: (0, 0),
                               memory_space=pltpu.VMEM)
        scl_spec = pl.BlockSpec((1, 8, _LANES), lambda s, *_: (s, 0, 0),
                                memory_space=pltpu.VMEM)
        out_spec = pl.BlockSpec((Qb, _LANES), lambda s, *_: (0, s),
                                memory_space=pltpu.VMEM)
        base = _group_kernel_packed_dbuf_q8 if q8_mode \
            else _group_kernel_packed_dbuf
    else:
        grid = (n_groups, nq)
        x_spec = pl.BlockSpec((Qb, d), lambda s, i, *_: (i, 0),
                              memory_space=pltpu.VMEM)
        # the WHOLE group as one resident block: constant over the
        # inner query loop ⇒ fetched once per group (the stream-once
        # invariant), double-buffered by the standard pipeline
        y_spec = pl.BlockSpec((tpg * T, d), lambda s, i, *_: (s, 0),
                              memory_space=pltpu.VMEM)
        yy_spec = pl.BlockSpec((8, tpg * T), lambda s, i, *_: (0, s),
                               memory_space=pltpu.VMEM)
        xx_spec = pl.BlockSpec((Qb, 1), lambda s, i, *_: (i, 0),
                               memory_space=pltpu.VMEM)
        scl_spec = pl.BlockSpec((1, 8, _LANES),
                                lambda s, i, *_: (s, 0, 0),
                                memory_space=pltpu.VMEM)
        out_spec = pl.BlockSpec((Qb, _LANES), lambda s, i, *_: (i, s),
                                memory_space=pltpu.VMEM)
        base = _group_kernel_packed_db_q8 if q8_mode \
            else _group_kernel_packed_db

    if q8_mode:
        in_specs = [x_spec, y_spec, yy_spec, scl_spec, xx_spec]
        operands = [x, y_hi, yy_half, scale_k, xxh]
        kernel = functools.partial(base, T=T, Qb=Qb, tpg=tpg,
                                   passes=passes, pair=pair, pbits=pbits)
    else:
        in_specs = [x_spec, y_spec, yy_spec]
        operands = [x, y_hi, yy_half]
        if passes == 3:
            in_specs.insert(2, y_spec)                  # y_lo
            operands.insert(2, y_lo)
        if xxh is not None:
            in_specs.append(xx_spec)
            operands.append(xxh)
        kernel = _make_group_kernel(base, passes, T, Qb, tpg=tpg,
                                    has_xxh=xxh is not None,
                                    pair=pair, pbits=pbits)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=in_specs,
        out_specs=[out_spec] * 3,
    )
    cost = _slot_cost(Q, M, d, n_groups * _LANES, passes)
    if q8_mode:
        # the y stream is 1 byte/element (int8), not bf16 hi(/lo)
        cost = pl.CostEstimate(
            flops=2 * Q * M * d * (2 if passes == 3 else 1),
            bytes_accessed=(Q * d * 4 + M * d
                            + Q * n_groups * _LANES * 8),
            transcendentals=0)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_packed_out_shape(Q, n_groups * _LANES),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",) * len(grid),
        ),
        cost_estimate=cost,
        interpret=interpret_mode(),
    )(m_real, *operands)


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "tpg", "pair",
                                    "pbits"))
def fused_l2_group_topk_packed_db(x, y_hi, y_lo, yy_half, m_real,
                                  T: int, Qb: int, passes: int,
                                  tpg: int = 16, pair: bool = False,
                                  pbits: int = _PACK_BITS, xxh=None):
    """Database-major super-blocked packed fused kernel (see the
    DATABASE-MAJOR block comment): same contract and outputs as
    :func:`fused_l2_group_topk_packed`, but the grid is
    ``(n_groups, nq)`` with the whole [tpg·T, d] certificate group
    VMEM-resident — y streams from HBM exactly once instead of
    ``nq`` times. Requires the index padded to whole groups
    (``M % (tpg·T) == 0``) and the packed envelope."""
    return _group_pallas_call_db(False, x, y_hi, y_lo, yy_half, m_real,
                                 T=T, Qb=Qb, passes=passes, tpg=tpg,
                                 pair=pair, pbits=pbits, xxh=xxh)


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "tpg", "pair",
                                    "pbits"))
def fused_l2_group_topk_packed_dbuf(x, y_hi, y_lo, yy_half, m_real,
                                    T: int, Qb: int, passes: int,
                                    tpg: int = 16, pair: bool = False,
                                    pbits: int = _PACK_BITS, xxh=None):
    """Explicitly double-buffered database-major packed fused kernel
    (see the DATABASE-MAJOR block comment): y stays in HBM and tiles
    ride a manual 2-slot async-copy pipeline (tile jj+1's DMA issued
    before tile jj's fold), so only two tiles are VMEM-resident and the
    HBM stream overlaps compute at tile granularity. One grid cell
    covers the whole query batch: ``Qb`` is accepted for interface
    parity but the effective query block is the padded Q (the VMEM
    footprint model prices the [Q, 128] fold state — see
    ``vmem_footprint(kernel="stream_dbuf")``)."""
    return _group_pallas_call_db(True, x, y_hi, y_lo, yy_half, m_real,
                                 T=T, Qb=Qb, passes=passes, tpg=tpg,
                                 pair=pair, pbits=pbits, xxh=xxh)


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "tpg", "pair",
                                    "pbits"))
def fused_l2_group_topk_packed_db_q8(x, y_q, yy_half, scale_k, m_real,
                                     T: int, Qb: int, passes: int,
                                     tpg: int = 16, pair: bool = False,
                                     pbits: int = _PACK_BITS, xxh=None):
    """INT8 database-major super-blocked packed fused kernel: the
    contract of :func:`fused_l2_group_topk_packed_db` with the database
    streamed as a QUANTIZED int8 slab — M·d·1 bytes instead of
    M·d·2(·2), the quantized-index-streaming tentpole.

    ``y_q`` [M, d] int8 is the per-certificate-group symmetric-scale
    quantization of the index (see knn_fused._prepare_ops_q8);
    ``scale_k`` [n_groups, 8, LANES] f32 carries each group's scale
    replicated to a native tile; ``yy_half`` must hold the DEQUANTIZED
    rows' half-norms (+ the _PACK_PAD sentinel on pads) so folded
    values are exactly d2(x, ŷ)/2 and the codes/certificate decode
    unchanged. ``passes`` splits only the x operand (int8 is exact in
    bf16); ``xxh`` is required."""
    return _group_pallas_call_db(False, x, y_q, None, yy_half, m_real,
                                 T=T, Qb=Qb, passes=passes, tpg=tpg,
                                 pair=pair, pbits=pbits, xxh=xxh,
                                 scale_k=scale_k)


@functools.partial(jax.jit,
                   static_argnames=("T", "Qb", "passes", "tpg", "pair",
                                    "pbits"))
def fused_l2_group_topk_packed_dbuf_q8(x, y_q, yy_half, scale_k, m_real,
                                       T: int, Qb: int, passes: int,
                                       tpg: int = 16, pair: bool = False,
                                       pbits: int = _PACK_BITS,
                                       xxh=None):
    """INT8 explicitly double-buffered database-major packed fused
    kernel: :func:`fused_l2_group_topk_packed_dbuf`'s manual 2-slot DMA
    pipeline moving int8 tiles — same contract as
    :func:`fused_l2_group_topk_packed_db_q8`."""
    return _group_pallas_call_db(True, x, y_q, None, yy_half, m_real,
                                 T=T, Qb=Qb, passes=passes, tpg=tpg,
                                 pair=pair, pbits=pbits, xxh=xxh,
                                 scale_k=scale_k)


def split_hi_lo(y: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Split f32 into bf16 hi + bf16 lo with y ≈ hi + lo (bf16x3 operand
    prep; the dropped lo·lo term is O(2⁻¹⁸·‖x‖‖y‖)).

    The optimization_barrier is LOAD-BEARING: without it, XLA:TPU's
    bf16-propagation pass simplifies the convert/subtract chain so lo
    collapses to ~0 (MEASURED on v5e: split residual 0.062 = one full
    bf16 ulp at 25-magnitude data, i.e. the whole lo term — which
    silently voided the bf16x3 certificate's error bound on
    norm-offset inputs; caught by the hardware fuzz battery, invisible
    to CPU interpret tests)."""
    y = jnp.asarray(y, jnp.float32)
    hi = y.astype(jnp.bfloat16)
    hi_f32 = jax.lax.optimization_barrier(hi).astype(jnp.float32)
    lo = (y - hi_f32).astype(jnp.bfloat16)
    return hi, lo
