"""Shared compare/select fold primitives for certified selection.

The group-fold is the common core of the certified-selection machinery
(distance.knn_fused pool building and matrix.select_k_slotted): compress
[B, S] slot-min arrays into per-group (top-2 values + ids, 3rd-min) with
pure compare/selects — no sort. The 3rd-min feeds the exactness
certificate (hidden entries of a group are ≥ its 3rd-min once the top-2
are pooled).
"""

from __future__ import annotations

import jax.numpy as jnp


def fold_group_top2(vals, ids, g: int):
    """[B, S] → per-group-of-g ``(a1, id1, a2, id2, a3)`` each [B, S/g];
    groups are contiguous runs of ``g`` slots. ``g`` is clamped to S and
    must then divide S."""
    B, S = vals.shape
    g = min(g, S)
    G = S // g
    v = vals.reshape(B, G, g)
    pid = ids.reshape(B, G, g)
    inf = jnp.full((B, G), jnp.inf, vals.dtype)
    a1, a2, a3 = inf, inf, inf
    id1 = jnp.full((B, G), -1, jnp.int32)
    id2 = jnp.full((B, G), -1, jnp.int32)
    for r in range(g):
        c = v[:, :, r]
        cid = pid[:, :, r]
        lt1 = c < a1
        lt2 = c < a2
        lt3 = c < a3
        a3 = jnp.where(lt2, a2, jnp.where(lt3, c, a3))
        id2 = jnp.where(lt1, id1, jnp.where(lt2, cid, id2))
        a2 = jnp.where(lt1, a1, jnp.where(lt2, c, a2))
        id1 = jnp.where(lt1, cid, id1)
        a1 = jnp.minimum(a1, c)
    return a1, id1, a2, id2, a3
