"""Streaming Pallas kernels for UNEXPANDED pairwise metrics.

(ref: the contraction tiling substrate serves every metric on the GPU —
cpp/include/raft/linalg/detail/contractions.cuh:313 keeps x/y tiles in
smem and accumulates [tile, tile] registers for L1/Linf/Canberra/… the
same way it does for L2. This kernel is that substrate's TPU role: the
|x−y| forms never touch HBM at [n, m, d] scale — terms are formed on
VMEM-resident tiles and fold into [Qb, 128] accumulators.)

TPU-first shape of the problem: unexpanded metrics have no matmul form,
so the O(n·m·d) per-feature terms run on the VPU — the performance
ceiling is the VPU's elementwise rate, not HBM or the MXU (measured
attribution lives in BENCH_UNEXPANDED.json). The kernel's job is to hit
that ceiling: stream y tiles through VMEM once per query block, keep
accumulators in VMEM, and let the two Mosaic-legal broadcast idioms do
the outer [Qb] × [128] pairing:

- the y feature row arrives as ``dc`` separate FULL-BLOCK ``(1, 128)``
  refs (block index maps select the feature) — offset-0 loads whose
  sublane broadcast Mosaic lowers natively (the SpMV kernels' idiom;
  a SLICED [1, N] broadcast is an invalid layout, measured round 2);
- the x column broadcast across lanes rides the MXU: a one-hot
  selector matmul ``x_split [Qb, 3·dc] @ OH_f [3·dc, 128]`` both
  SELECTS feature f and SUMS the exact bf16x3 split (hi+mid+lo) in
  f32 accumulation — one dot per feature, exact to f32, and the MXU
  work co-issues under the VPU fold (the round-3 co-issue lever).

Exactness: the bf16x3 split reconstructs f32 x exactly (8+8+8 mantissa
bits ≥ 24 with sign absorption; split under an optimization_barrier so
XLA:TPU's bf16-propagation pass cannot fold it — the round-3 hardware
fuzz finding); y enters untouched in f32. Terms and accumulation are
plain f32 VPU ops, so results match the jitted XLA path bit-for-bit up
to reduction order (tested against numpy oracles).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.distance.types import DistanceType
from raft_tpu.ops.utils import interpret_mode

_LANES = 128
_QB = 256          # query block (sublane dim of the accumulator)
_DC = 16           # features folded per grid step (y refs per kernel)

_SUPPORTED = (
    DistanceType.L1,
    DistanceType.Linf,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.LpUnexpanded,
    DistanceType.Canberra,
    DistanceType.HammingUnexpanded,
    DistanceType.BrayCurtis,
    DistanceType.KLDivergence,
    DistanceType.JensenShannon,
)


def _interpret_dispatch_enabled() -> bool:
    """Interpreted Pallas is a TEST vehicle (orders of magnitude slower
    than the jitted XLA path): production non-TPU callers keep the XLA
    path unless the suite explicitly opts in (tests/conftest.py sets
    this; round-4 advisor finding)."""
    import os

    return os.environ.get("RAFT_TPU_PALLAS_INTERPRET_DISPATCH",
                          "0") == "1"


def unexpanded_eligible(t: DistanceType, n: int, m: int, d: int,
                        x_dtype, y_dtype) -> bool:
    """Whether the streaming kernel path serves this call. Small shapes
    stay on the fused-XLA path (kernel dispatch isn't worth it below
    ~1M output cells); non-f32-representable inputs keep XLA's native
    dtype semantics. Shape/dtype-only, so the decision is valid under
    trace (the finiteness envelope is handled in-program by the
    dispatcher's lax.cond)."""
    if t not in _SUPPORTED:
        return False
    for dt in (x_dtype, y_dtype):
        if not (jnp.issubdtype(dt, jnp.floating)
                and jnp.finfo(dt).bits <= 32):
            return False
    if interpret_mode():
        return _interpret_dispatch_enabled() and n * m * d <= 2 ** 22
    return n * m >= (1 << 20)


def _kl(a, b):
    r = jnp.where((a > 0) & (b > 0), a / jnp.where(b > 0, b, 1.0), 1.0)
    return jnp.where(a > 0, a * jnp.log(r), 0.0)


def _term(t: DistanceType, p: float, xb, yb):
    """One feature's [Qb, 128] term(s). The Pallas twin of
    distance.pairwise._unexp_terms (same math, tested to agree)."""
    diff = xb - yb
    if t in (DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded):
        return (diff * diff,)
    if t in (DistanceType.L1, DistanceType.Linf):
        return (jnp.abs(diff),)
    if t == DistanceType.LpUnexpanded:
        return (jnp.abs(diff) ** p,)
    if t == DistanceType.Canberra:
        denom = jnp.abs(xb) + jnp.abs(yb)
        safe = jnp.where(denom == 0, 1.0, denom)
        return (jnp.where(denom == 0, 0.0, jnp.abs(diff) / safe),)
    if t == DistanceType.HammingUnexpanded:
        return ((xb != yb).astype(jnp.float32),)
    if t == DistanceType.BrayCurtis:
        return (jnp.abs(diff), jnp.abs(xb + yb))
    if t == DistanceType.KLDivergence:
        return (_kl(xb, yb),)
    if t == DistanceType.JensenShannon:
        mid = 0.5 * (xb + yb)
        return (_kl(xb, mid) + _kl(yb, mid),)
    raise NotImplementedError(t)


def _unexpanded_kernel(*refs, t: DistanceType, p: float, dc: int,
                       Qb: int, n_dch: int, d_true: int, n_acc: int):
    """Grid (iq, it, idch), idch innermost: out blocks [Qb, 128] are
    revisited across the d-chunk sweep (zero-init on first visit,
    finalize on last — Mosaic's sequential grid as the accumulator)."""
    y_refs = refs[:dc]
    xs_ref = refs[dc]
    out_refs = refs[dc + 1:dc + 1 + n_acc]
    idch = pl.program_id(2)

    xsplit = xs_ref[...]                        # [Qb, 3·dc] bf16
    rows3 = 3 * dc
    row_mod = jax.lax.broadcasted_iota(jnp.int32, (rows3, _LANES), 0) % dc

    combine = (jnp.maximum if t == DistanceType.Linf else jnp.add)
    accs = [jnp.zeros((Qb, _LANES), jnp.float32) for _ in range(n_acc)]
    for f in range(dc):
        # one-hot selector: picks feature f from each of the 3 split
        # planes and sums them exactly in the f32 MXU accumulator
        oh = jnp.where(row_mod == f, 1.0, 0.0).astype(jnp.bfloat16)
        xb = jax.lax.dot(xsplit, oh,
                         preferred_element_type=jnp.float32)  # [Qb, 128]
        yb = jnp.broadcast_to(y_refs[f][...], (Qb, _LANES))
        for a, tm in zip(range(n_acc), _term(t, p, xb, yb)):
            accs[a] = combine(accs[a], tm)

    @pl.when(idch == 0)
    def _init():
        for r, a in zip(out_refs, accs):
            r[...] = a

    @pl.when(idch != 0)
    def _fold():
        for r, a in zip(out_refs, accs):
            r[...] = combine(r[...], a)

    if n_dch > 0:
        @pl.when(idch == n_dch - 1)
        def _finalize():
            a = out_refs[0][...]
            if t == DistanceType.L2SqrtUnexpanded:
                out_refs[0][...] = jnp.sqrt(jnp.maximum(a, 0.0))
            elif t == DistanceType.LpUnexpanded:
                out_refs[0][...] = jnp.maximum(a, 0.0) ** (1.0 / p)
            elif t == DistanceType.HammingUnexpanded:
                out_refs[0][...] = a / d_true
            elif t == DistanceType.BrayCurtis:
                out_refs[0][...] = a / jnp.maximum(out_refs[1][...],
                                                   1e-30)
            elif t == DistanceType.JensenShannon:
                out_refs[0][...] = jnp.sqrt(jnp.maximum(0.5 * a, 0.0))


def _split3(x):
    """Exact bf16x3 split of f32 ``x`` → [n, 3, d] bf16 (hi, mid, lo).
    Barriers keep XLA:TPU's bf16-propagation pass from folding the
    residuals to zero (round-3 hardware fuzz finding)."""
    hi = x.astype(jnp.bfloat16)
    hi_b = jax.lax.optimization_barrier(hi)
    r1 = x - hi_b.astype(jnp.float32)
    mid = r1.astype(jnp.bfloat16)
    mid_b = jax.lax.optimization_barrier(mid)
    lo = (r1 - mid_b.astype(jnp.float32)).astype(jnp.bfloat16)
    return jnp.stack([hi, mid, lo], axis=1)


@functools.partial(jax.jit,
                   static_argnames=("t", "p", "d_true", "Qb", "dc"))
def _unexpanded_pallas_impl(x, y, t: DistanceType, p: float, d_true: int,
                            Qb: int, dc: int):
    """The WHOLE op — cast, pad, split, kernel, output slice — as one
    program: every eager op around a kernel costs a transport RTT on
    the tunneled device (measured ~2 ms each, round 3)."""
    n0, d0 = x.shape
    m0 = y.shape[0]
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    npad, mpad, dpad = (-n0) % Qb, (-m0) % _LANES, (-d0) % dc
    if npad:
        x = jnp.concatenate([x, jnp.zeros((npad, d0), x.dtype)])
    if dpad:
        # zero features are term-identities for every supported metric
        x = jnp.concatenate(
            [x, jnp.zeros((x.shape[0], dpad), x.dtype)], axis=1)
        y = jnp.concatenate(
            [y, jnp.zeros((m0, dpad), y.dtype)], axis=1)
    if mpad:
        y = jnp.concatenate(
            [y, jnp.zeros((mpad, y.shape[1]), y.dtype)])
    n, d = x.shape
    m = y.shape[0]
    n_dch = d // dc
    n_acc = 2 if t == DistanceType.BrayCurtis else 1

    # x: exact bf16x3 split, d-chunk-major column groups [n, nd·3·dc]
    xs = _split3(x)                                   # [n, 3, d]
    xs = xs.reshape(n, 3, n_dch, dc).transpose(0, 2, 1, 3)
    xs = xs.reshape(n, n_dch * 3 * dc)
    yT = y.T                                          # [d, m]

    grid = (n // Qb, m // _LANES, n_dch)
    y_specs = [
        pl.BlockSpec((1, _LANES),
                     functools.partial(
                         lambda iq, it, idch, f=0: (idch * dc + f, it),
                         f=f),
                     memory_space=pltpu.VMEM)
        for f in range(dc)]
    x_spec = pl.BlockSpec((Qb, 3 * dc), lambda iq, it, idch: (iq, idch),
                          memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((Qb, _LANES), lambda iq, it, idch: (iq, it),
                            memory_space=pltpu.VMEM)

    outs = pl.pallas_call(
        functools.partial(_unexpanded_kernel, t=t, p=p, dc=dc, Qb=Qb,
                          n_dch=n_dch, d_true=d_true, n_acc=n_acc),
        grid=grid,
        in_specs=y_specs + [x_spec],
        out_specs=[out_spec] * n_acc,
        out_shape=[jax.ShapeDtypeStruct((n, m), jnp.float32)] * n_acc,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(*_y_chunk_views(yT, dc), xs)
    return outs[0][:n0, :m0]


def _y_chunk_views(yT, dc):
    """The dc y-row refs all view the SAME [d, m] array — the per-ref
    BlockSpec index maps select different feature rows."""
    return [yT] * dc


def unexpanded_pairwise_tiled(x, y, t: DistanceType, p: float
                              ) -> jax.Array:
    """Full [n, m] unexpanded distance matrix via the streaming kernel
    — ONE jitted dispatch (cast/pad/split/slice all inside).

    Envelope: FINITE inputs only — a non-finite x value would turn the
    one-hot selector dot into 0·inf = NaN for its whole feature chunk
    (distance.pairwise guards this with an in-program lax.cond on
    finiteness; direct callers with possibly non-finite data should use
    the XLA path)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    n, d = x.shape
    m = y.shape[0]
    if d == 0:
        return jnp.zeros((n, m), jnp.float32)
    Qb = min(_QB, max(8, -(-n // 8) * 8))
    dc = _DC if d >= _DC else max(1, d)
    return _unexpanded_pallas_impl(x, y, t, float(p), d, Qb, dc)
