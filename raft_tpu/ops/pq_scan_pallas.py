"""List-major IVF-PQ ADC scan kernel (Pallas/Mosaic).

The compressed sibling of :mod:`raft_tpu.ops.fine_scan_pallas`: the
grid walks the PROBED LISTS in the same 8-list cells over the same
host-built schedule (``ann.ivf_flat.build_list_schedule`` — reused
verbatim), but the streamed operand is the PRODUCT-QUANTIZED codes
slab (~1/16 of the f32 bytes at 8-bit codes with ``pq_dim = d/4``,
~1/32 at 4-bit) plus the 4-byte ``‖ŷ‖²`` reconstruction-norm and
4-byte per-row quantization-error sidecars, never the f32 rows.

Scoring is asymmetric-distance computation (ADC) by TABLE LOOKUP, the
classic IVF-PQ structure (ref: neighbors/ivf_pq.cuh / cuVS
``ivf_pq::search``) re-shaped for the MXU:

- the per-query lookup table ``lut [nqp, pq_dim·K]`` holds every
  query-to-codeword dot product ``x_s · cb_s[j]`` (``K = 2^pq_bits``)
  — computed ONCE on entry by the caller and held VMEM-RESIDENT for
  the whole cell sweep (the "in-VMEM ADC" of the issue);
- a streamed code block decodes to one-hot lanes (``code == iota`` —
  exact 0/1 in bf16) and ONE hi/lo-split MXU contraction against the
  resident table evaluates every query's ADC sum for every row:
  ``Σ_s lut[q, s, code[w, s]]`` — the gather becomes a matmul, which
  is the only shape a TPU vector unit streams at full rate;
- the residual-coding cross term ``x · c_list`` rides the resident
  per-scheduled-list ``cdot [nqp, Lp]`` table (per query × probed
  list — tiny next to the slab), so the ADC score is exactly

  ``d2(x, ŷ) = ‖x‖² + ‖ŷ‖² − 2·x·c_l − 2·Σ_s x_s·cb_s[code_{w,s}]``

  against the RECONSTRUCTED row ``ŷ = c_l + concat_s cb_s[code]``.

What FOLDS into the pool is the per-row ADAPTIVE certificate score —
the certified true-distance lower bound

  ``lb(x, y) = max(√max(d2(x, ŷ), 0) − Eq_y, 0)²``

where ``Eq_y`` is the row's RECORDED round-trip error bound streamed
from the 4-byte sidecar (``|√d2(x,y) − √d2(x,ŷ)| ≤ ‖y − ŷ‖ ≤ Eq_y``
by the triangle inequality, and ``z ↦ (max(√z − Eq, 0))²`` is
1-Lipschitz so the kernel's own score error passes through
undiminished). The pool therefore ranks rows by how close they COULD
be, and its running rest-min is directly the per-query completeness
bound — no per-list worst-case widening term survives to the caller,
only the kernel-precision envelope.

Masks and outputs follow the fine-scan contract, generalized to a
static ``pool_depth``: probe-table membership + window-column masks to
the never-wins +inf, scores fold into the per-query 128-lane-class
top-``pool_depth`` pools with global slab rows, plus the running
(depth+1)-min certificate input. ``pool_depth=2`` is the ordinary
256-slot pool; the ``pq_widen`` rung re-runs at 4/8 for a 512/1024-
slot pool before the caller escalates to the exact f32 rerun. The
caller (``ann.ivf_pq``) exact-rescores the pooled candidates from the
retained f32 slab — failed queries widen, then rerun the exact f32
scan, so returned ids never degrade (see ``search_ivf_pq``).

4-bit codes stream PACKED (two codes per byte, low nibble = even
subspace) and unpack in-register — the HBM stream is the honest
``pq_dim/2`` bytes per row the cost model prices.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.fine_scan_pallas import (LISTS_PER_CELL,
                                           _split_hi_lo)
from raft_tpu.ops.utils import interpret_mode

_LANES = 128
_NT = (((1,), (1,)), ((), ()))

#: supported code widths: 4-bit codes pack two per byte
PQ_BITS = (4, 8)

#: supported pool depths (top-N per 128-lane class): 2 is the base
#: 256-slot pool, 4/8 are the pq_widen rungs (512/1024 slots)
PQ_POOL_DEPTHS = (2, 4, 8)


def pq_scan_vmem_footprint(Wk: int, nqp: int, pq_dim: int, K: int,
                           Lp: int, pq_bits: int = 8,
                           pool_depth: int = 2) -> int:
    """Estimated scoped-VMEM bytes of one PQ ADC cell: 2 DMA slots for
    the code window (+ the two f32 sidecars), the resident ADC table
    (f32 + its bf16 hi/lo split), the resident probe + centroid-dot
    tables, the per-subspace one-hot staging block, ~3 live [nqp, Wk]
    f32 score temporaries and the (2·depth+1)-buffer fold state.
    UNCALIBRATED — conservative, same spirit as
    ``fine_scan_vmem_footprint``."""
    code_bytes = pq_dim if pq_bits == 8 else -(-pq_dim // 2)
    bytes_ = 2 * Wk * code_bytes                 # 2 code DMA slots
    bytes_ += 2 * 2 * Wk * 4                     # 2×(‖ŷ‖², Eq) DMA slots
    bytes_ += nqp * pq_dim * K * (4 + 2 + 2)     # lut f32 + hi/lo bf16
    bytes_ += nqp * _LANES * 4                   # probe table
    bytes_ += nqp * Lp * 4                       # per-list x·c table
    bytes_ += Wk * pq_dim * K * 2                # one-hot staging (bf16)
    bytes_ += 3 * nqp * Wk * 4                   # d2/lb + temporaries
    bytes_ += (2 * pool_depth + 1) * nqp * _LANES * 4 * 2  # fold state
    return bytes_


def _pq_pool_out_shape(nqp: int, depth: int):
    """``depth`` (score, row) pool pairs + the running rest-min."""
    out = []
    for _ in range(depth):
        out.append(jax.ShapeDtypeStruct((nqp, _LANES), jnp.float32))
        out.append(jax.ShapeDtypeStruct((nqp, _LANES), jnp.int32))
    out.append(jax.ShapeDtypeStruct((nqp, _LANES), jnp.float32))
    return out


def _fold_pool_deep(acc, d2, base_row, nqp: int, Wk: int, depth: int):
    """Fold a masked [nqp, Wk] score window into the per-query
    ``depth``-deep 128-lane-class pool — the fine-scan ``_fold_pool``
    insertion cascade generalized from top-2 to top-``depth``, plus
    the running (depth+1)-min (certificate input — every row outside a
    lane's top-``depth`` scored ≥ that lane's rest-min). ``acc`` is
    the flat ``(a_1, i_1, …, a_depth, i_depth, rest)`` tuple."""
    a = [acc[2 * t] for t in range(depth)]
    i = [acc[2 * t + 1] for t in range(depth)]
    rest = acc[2 * depth]
    lane = jax.lax.broadcasted_iota(jnp.int32, (nqp, _LANES), 1)
    for r in range(Wk // _LANES):
        c = d2[:, r * _LANES:(r + 1) * _LANES]
        ci = base_row + r * _LANES + lane
        lt = [c < a[t] for t in range(depth)]
        lt_rest = c < rest
        rest = jnp.where(lt[depth - 1], a[depth - 1],
                         jnp.where(lt_rest, c, rest))
        for t in range(depth - 1, 0, -1):
            a[t] = jnp.where(lt[t - 1], a[t - 1],
                             jnp.where(lt[t], c, a[t]))
            i[t] = jnp.where(lt[t - 1], i[t - 1],
                             jnp.where(lt[t], ci, i[t]))
        a[0] = jnp.where(lt[0], c, a[0])
        i[0] = jnp.where(lt[0], ci, i[0])
    out = []
    for t in range(depth):
        out += [a[t], i[t]]
    return tuple(out) + (rest,)


def _decode_subspaces(codes, pq_dim: int, pq_bits: int):
    """Per-subspace int32 code columns of a streamed window. 8-bit
    codes are stored BIASED (code − 128) so the full 0..255 range fits
    int8; 4-bit codes are packed two per byte (low nibble = even
    subspace) and unpack with pure arithmetic — no bitwise ops on the
    possibly-negative int8 lanes."""
    v = codes.astype(jnp.int32)
    if pq_bits == 8:
        return [v[:, s] + 128 for s in range(pq_dim)]
    vu = jnp.where(v < 0, v + 256, v)
    cols = []
    for s in range(pq_dim):
        byte = vu[:, s // 2]
        cols.append(byte % 16 if s % 2 == 0 else byte // 16)
    return cols


def _adc_scores(lut_hi, lut_lo, codes, pq_dim: int, K: int,
                pq_bits: int, Wk: int):
    """``Σ_s lut[q, s, code[w, s]]`` for every (query, row) of one
    window — the table gather evaluated as a one-hot MXU contraction
    (one-hot lanes are exact in bf16, so only the hi/lo split of the
    table itself carries rounding)."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (Wk, K), 1)
    hot = []
    for s, col in enumerate(_decode_subspaces(codes, pq_dim, pq_bits)):
        hot.append((col[:, None] == iota).astype(jnp.bfloat16))
    onehot = jnp.concatenate(hot, axis=1)          # [Wk, pq_dim·K]
    acc = jax.lax.dot_general(lut_hi, onehot, _NT,
                              preferred_element_type=jnp.float32)
    acc = acc + jax.lax.dot_general(lut_lo, onehot, _NT,
                                    preferred_element_type=jnp.float32)
    return acc                                      # [nqp, Wk]


def _pq_kernel_body(sched_ref, xx_ref, probes_ref, cdot_ref, lut_ref,
                    codes_ref, yy_ref, eq_ref, *out_refs, Wk: int,
                    pq_dim: int, K: int, pq_bits: int, depth: int):
    """One grid cell: stream LISTS_PER_CELL probed lists' code windows
    (+ norm and error sidecars) through the 2-slot DMA pipeline,
    evaluate the ADC scores against the resident lookup table, subtract
    each row's recorded error bound into the certified lower-bound
    score, mask non-member queries / out-of-window columns to +inf and
    fold into the revisited per-query pools."""
    s = pl.program_id(0)
    nqp = xx_ref.shape[0]
    inf = jnp.full((nqp, _LANES), jnp.inf, jnp.float32)
    neg1 = jnp.full((nqp, _LANES), -1, jnp.int32)

    @pl.when(s == 0)
    def _():
        for t in range(depth):
            out_refs[2 * t][...] = inf
            out_refs[2 * t + 1][...] = neg1
        out_refs[2 * depth][...] = inf

    def body(cscratch, yscratch, escratch, csem, ysem, esem):
        def dma(slot, j):
            return (pltpu.make_async_copy(
                codes_ref.at[pl.ds(sched_ref[0, j], Wk), :],
                cscratch.at[slot], csem.at[slot]),
                pltpu.make_async_copy(
                    yy_ref.at[pl.ds(sched_ref[0, j], Wk), :],
                    yscratch.at[slot], ysem.at[slot]),
                pltpu.make_async_copy(
                    eq_ref.at[pl.ds(sched_ref[0, j], Wk), :],
                    escratch.at[slot], esem.at[slot]))

        def start(slot, j):
            for cp in dma(slot, j):
                cp.start()

        def wait(slot, j):
            for cp in dma(slot, j):
                cp.wait()

        j0 = s * LISTS_PER_CELL
        start(0, j0)
        xx = xx_ref[...]                                 # [nqp, 1]
        probes = probes_ref[...]                         # [nqp, Pp]
        cdot = cdot_ref[...]                             # [nqp, Lp]
        lut_hi, lut_lo = _split_hi_lo(lut_ref[...])      # [nqp, S·K]
        colv = jax.lax.broadcasted_iota(jnp.int32, (nqp, Wk), 1)
        acc = tuple(ref[...] for ref in out_refs)
        for jj in range(LISTS_PER_CELL):
            j = j0 + jj
            slot = jj % 2
            if jj + 1 < LISTS_PER_CELL:
                start((jj + 1) % 2, j + 1)           # prefetch next
            wait(slot, j)
            st = sched_ref[0, j]
            lsize = sched_ref[1, j]
            off = sched_ref[2, j]
            lid = sched_ref[3, j]
            adc = _adc_scores(lut_hi, lut_lo, cscratch[slot], pq_dim,
                              K, pq_bits, Wk)
            yyw = yscratch[slot].reshape(1, Wk)          # ‖ŷ‖² lanes
            eqw = escratch[slot].reshape(1, Wk)          # Eq_row lanes
            qc = jax.lax.dynamic_slice_in_dim(cdot, j, 1, 1)
            d2 = xx + yyw - 2.0 * qc - 2.0 * adc
            # the certified lower bound on the TRUE distance: pull the
            # ADC score toward 0 by the row's recorded round-trip
            # error (triangle inequality on the norms; 1-Lipschitz in
            # the score, so the kernel-precision envelope carries over
            # unchanged) — +inf masks propagate through the sqrt
            rad = jnp.sqrt(jnp.maximum(d2, 0.0))
            lb = jnp.maximum(rad - eqw, 0.0) ** 2
            member = jnp.sum((probes == lid).astype(jnp.float32),
                             axis=1, keepdims=True)      # [nqp, 1]
            lb = jnp.where(member > 0.0, lb, jnp.inf)
            valid = (colv >= off) & (colv < off + lsize)
            lb = jnp.where(valid, lb, jnp.inf)
            acc = _fold_pool_deep(acc, lb, st, nqp, Wk, depth)
        for t, ref in enumerate(out_refs):
            ref[...] = acc[t]

    code_bytes = pq_dim if pq_bits == 8 else pq_dim // 2
    pl.run_scoped(
        body,
        cscratch=pltpu.VMEM((2, Wk, code_bytes), jnp.int8),
        yscratch=pltpu.VMEM((2, Wk, 1), jnp.float32),
        escratch=pltpu.VMEM((2, Wk, 1), jnp.float32),
        csem=pltpu.SemaphoreType.DMA((2,)),
        ysem=pltpu.SemaphoreType.DMA((2,)),
        esem=pltpu.SemaphoreType.DMA((2,)))


@functools.partial(jax.jit,
                   static_argnames=("Wk", "pq_bits", "pool_depth"))
def pq_scan_list_major(sched, xx, probes, cdot, lut, codes, yy_pq,
                       eq_rows, Wk: int, pq_bits: int = 8,
                       pool_depth: int = 2) -> Tuple[jax.Array, ...]:
    """List-major ADC scan over the product-quantized codes slab.

    Args:
      sched: [4, Lp] int32 schedule rows — exactly
        ``ann.ivf_flat.build_list_schedule``'s output (window start,
        real length, in-window offset, list id; pads ``(0,0,0,−1)``).
      xx: [nqp, 1] exact f32 query squared norms (nqp a multiple of 8).
      probes: [nqp, 128] int32 probe table (pads −2).
      cdot: [nqp, Lp] f32 per-(query, scheduled list) centroid dot
        products ``x · c_{lid(j)}`` (column j pairs with schedule
        column j; pad-list columns are never read through the mask).
      lut: [nqp, pq_dim·K] f32 ADC table — ``lut[q, s·K + j] =
        x_{q,s} · cb_s[j]`` flattened subspace-major.
      codes: [R, pq_dim] int8 biased codes (8-bit: stored code−128) or
        [R, pq_dim/2] packed nibbles (4-bit).
      yy_pq: [R, 1] f32 reconstructed row norms ``‖ŷ‖²`` (pads 0).
      eq_rows: [R, 1] f32 recorded per-row round-trip error bounds
        ``‖y − ŷ‖`` (pads 0) — the adaptive-certificate sidecar.
      Wk: static window length, a multiple of 128.
      pq_bits: 4 or 8 (static — decides the decode path).
      pool_depth: static per-lane-class pool depth ∈ (2, 4, 8) —
        2 is the base 256-slot pool, 4/8 the ``pq_widen`` rungs.

    Returns:
      (a_1, i_1, …, a_depth, i_depth, rest): [nqp, 128] per-lane-class
      top-``pool_depth`` certified-lower-bound scores with GLOBAL slab
      rows, plus the running rest-min certificate input.
    """
    if Wk % _LANES:
        raise ValueError(f"pq_scan_list_major: Wk={Wk} must be a "
                         f"multiple of {_LANES}")
    if pq_bits not in PQ_BITS:
        raise ValueError(f"pq_scan_list_major: pq_bits must be one of "
                         f"{PQ_BITS}, got {pq_bits}")
    if pool_depth not in PQ_POOL_DEPTHS:
        raise ValueError(f"pq_scan_list_major: pool_depth must be one "
                         f"of {PQ_POOL_DEPTHS}, got {pool_depth}")
    Lp = sched.shape[1]
    if Lp % LISTS_PER_CELL:
        raise ValueError(f"pq_scan_list_major: schedule length {Lp} "
                         f"must be a multiple of {LISTS_PER_CELL}")
    nqp = xx.shape[0]
    code_bytes = codes.shape[1]
    pq_dim = code_bytes if pq_bits == 8 else 2 * code_bytes
    K = 1 << pq_bits
    if lut.shape[1] != pq_dim * K:
        raise ValueError(f"pq_scan_list_major: lut width "
                         f"{lut.shape[1]} != pq_dim·K = {pq_dim * K}")

    def kernel(sched_ref, xx_ref, probes_ref, cdot_ref, lut_ref,
               codes_ref, yy_ref, eq_ref, *out_refs):
        _pq_kernel_body(sched_ref, xx_ref, probes_ref, cdot_ref,
                        lut_ref, codes_ref, yy_ref, eq_ref, *out_refs,
                        Wk=Wk, pq_dim=pq_dim, K=K, pq_bits=pq_bits,
                        depth=pool_depth)

    n_cells = Lp // LISTS_PER_CELL
    n_out = 2 * pool_depth + 1
    out_spec = pl.BlockSpec((nqp, _LANES), lambda s, *_: (0, 0),
                            memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_cells,),
        in_specs=[
            pl.BlockSpec((nqp, 1), lambda s, *_: (0, 0),
                         memory_space=pltpu.VMEM),           # xx
            pl.BlockSpec((nqp, _LANES), lambda s, *_: (0, 0),
                         memory_space=pltpu.VMEM),           # probes
            pl.BlockSpec((nqp, Lp), lambda s, *_: (0, 0),
                         memory_space=pltpu.VMEM),           # cdot
            pl.BlockSpec((nqp, pq_dim * K), lambda s, *_: (0, 0),
                         memory_space=pltpu.VMEM),           # lut
            pl.BlockSpec(memory_space=pltpu.ANY),            # codes DMA
            pl.BlockSpec(memory_space=pltpu.ANY),            # yy DMA
            pl.BlockSpec(memory_space=pltpu.ANY),            # eq DMA
        ],
        out_specs=[out_spec] * n_out,
    )
    L = n_cells * LISTS_PER_CELL
    cost = pl.CostEstimate(
        # 2 hi/lo ADC contractions over the pq_dim·K one-hot lanes
        flops=2 * nqp * L * Wk * pq_dim * K * 2,
        bytes_accessed=(L * Wk * (code_bytes + 8)
                        + nqp * pq_dim * K * 4
                        + nqp * _LANES * 8 * n_out),
        # one sqrt per (query, streamed row) for the certified bound
        transcendentals=nqp * L * Wk,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_pq_pool_out_shape(nqp, pool_depth),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        cost_estimate=cost,
        interpret=interpret_mode(),
    )(sched, xx, probes, cdot, lut, codes, yy_pq, eq_rows)
