"""Blocked histogram kernel (Pallas/Mosaic) — the smem-histogram role.

(ref: cpp/include/raft/stats/detail/histogram.cuh — the shared-memory
``HistType`` strategies keep per-block bin counters in smem and merge via
atomics. TPU has neither smem atomics nor scatter; the Mosaic idiom is a
VMEM-RESIDENT ACCUMULATOR: the [n_bins, batch] output block is revisited
by every row-block grid step (sequential grid), each step folding its row
chunk as one-hot compare + sum — pure VPU ops.)

Rows are streamed in blocks; inside a block, small sub-chunks bound the
[n_bins, SUB, batch] one-hot temporary. Pad rows carry bin id -1, which
matches no bin. Counts are accumulated in int32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.utils import interpret_mode

_SUB = 8     # rows folded per one-hot temp (bounds VMEM: n_bins·SUB·batch)


def _hist_kernel(bins_ref, out_ref, *, Rb: int, n_bins: int):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    b = bins_ref[0]                                     # [Rb, batch] int32
    acc = out_ref[...]                                  # [n_bins, batch]
    ids = jax.lax.broadcasted_iota(jnp.int32, (n_bins, _SUB, 1), 0)
    for r0 in range(0, Rb, _SUB):
        sub = b[r0:r0 + _SUB][None, :, :]               # [1, SUB, batch]
        onehot = (sub == ids).astype(jnp.int32)         # [n_bins,SUB,batch]
        acc = acc + jnp.sum(onehot, axis=1)
    out_ref[...] = acc


@functools.partial(jax.jit, static_argnames=("n_bins", "Rb"))
def histogram_blocked(bins, n_bins: int, Rb: int = 1024) -> jax.Array:
    """counts [n_bins, batch] for bins [n, batch] int32 (entries outside
    [0, n_bins) are ignored). Grid-streamed rows, VMEM accumulator."""
    if Rb % _SUB:
        raise ValueError(f"histogram_blocked: Rb must be a multiple of "
                         f"{_SUB}, got {Rb}")
    n, batch = bins.shape
    if n == 0:  # grid=(0,) would leave the output uninitialized
        return jnp.zeros((n_bins, batch), jnp.int32)
    pad = (-n) % Rb
    if pad:
        bins = jnp.concatenate(
            [bins, jnp.full((pad, batch), -1, jnp.int32)])
    blocks = bins.reshape(-1, Rb, batch)
    return pl.pallas_call(
        functools.partial(_hist_kernel, Rb=Rb, n_bins=n_bins),
        grid=(blocks.shape[0],),
        in_specs=[pl.BlockSpec((1, Rb, batch), lambda j: (j, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((n_bins, batch), lambda j: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_bins, batch), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret_mode(),
    )(blocks)
