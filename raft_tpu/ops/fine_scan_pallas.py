"""List-major IVF fine-scan kernels (Pallas/Mosaic).

The inverted-index batching trade applied to the TPU streaming kernels:
the query-major fine scan (`raft_tpu.ann.ivf_flat._fine_scan`) gathers
each query's probe windows independently, so a hot list probed by q
queries is read q times from HBM — the exact nq× re-read pathology the
PR-3 database-major grid re-order removed from brute force, recorded
per frontier point as ``gather_overread`` by
:func:`raft_tpu.observability.costmodel.ivf_traffic_model`.

These kernels invert the schedule: the grid walks the PROBED LISTS
(8 lists per cell — the schedule builder buckets the probed-list table
to the 8-row quantum and rounds the cell count to a power of two so one
compiled program serves a sweep), each cell streams its lists' slab
windows from HBM ONCE through a manual 2-slot double-buffered DMA
pipeline (the ``_group_kernel_packed_dbuf`` idiom) while the WHOLE
query block stays VMEM-resident, and a per-(query, list) membership
test against the resident probe table masks queries that did not probe
the list to the never-wins +inf. Every scored row folds into a
per-query 128-slot candidate pool (per lane-class top-2 values + global
slab-row ids, plus the running 3rd-min — the same certificate shape the
fused brute kernels carry): outputs are revisited [nqp, 128] blocks, so
HBM sees each probed list once and the pools once.

Scores are APPROXIMATE (bf16 hi/lo MXU contraction; the int8 variant
reuses the PR-9 dequant-in-register idea — per-list scale applied to
the accumulated quantized partials, never a widened copy in VMEM). The
caller exact-rescores the pooled candidates from the f32 slab with the
query-major scorer's own formula and certifies completeness via the
pooled 3rd-min (`a3`): every probed row outside the pool scored ≥ its
slot's a3 ≥ min-over-slots a3, so
``min_slots a3 ≥ θ + (kernel-precision + quantization envelope)``
proves the true top-k cannot hide outside the pool. Failed queries
rerun the query-major scan — returned f32 ids are therefore
BIT-IDENTICAL to the query-major oracle in every case, and int8 id
SETS are identical (the quantized gather's own ordering of exact f32
value ties is quantization-noise-dependent — the PR-9 contract; see
``ann.ivf_flat._fine_scan_list``).

In-kernel norms: the slab tile's row norms are contracted on the MXU
(``ones · split_hi_lo(y²)`` — two extra passes) instead of streaming a
precomputed carrier; the 2⁻¹⁶-grade reconstruction error is part of the
certificate envelope, and the HBM stream stays exactly the slab bytes.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.utils import interpret_mode, round_up

_LANES = 128
#: lists per grid cell — the schedule builder pads the probed-list
#: table to a multiple of this (the 8-row sublane quantum)
LISTS_PER_CELL = 8
#: per-query candidate pool width: 128 lane-class slots × top-2
POOL_SLOTS = _LANES
POOL_WIDTH = 2 * POOL_SLOTS


def fine_scan_vmem_footprint(Wk: int, nqp: int, d: int,
                             q8: bool = False) -> int:
    """Estimated scoped-VMEM bytes of one list-major fine-scan cell:
    2 DMA window slots (f32 or int8), the resident query block (f32 +
    the bf16 hi/lo split), the resident probe table, ~3 live [nqp, Wk]
    f32 score temporaries (d2 + mask/select intermediates), and the
    5-buffer fold state. UNCALIBRATED (no Mosaic compile/reject
    measured for this kernel yet) — conservative, same spirit as the
    ``stream_dbuf`` factors in ``ops.fused_l2_topk_pallas``."""
    bytes_ = 2 * Wk * d * (1 if q8 else 4)        # 2 DMA window slots
    bytes_ += nqp * d * (4 + 2 + 2)               # x f32 + hi/lo bf16
    bytes_ += nqp * _LANES * 4                    # probe table (Pp=128)
    bytes_ += 3 * nqp * Wk * 4                    # d2 + temporaries
    bytes_ += Wk * d * (4 + 2 + 2)                # y², y² hi/lo split
    bytes_ += 5 * nqp * _LANES * 4 * 2            # fold state + temps
    return bytes_


def _split_hi_lo(v):
    """bf16 hi/lo split of an f32 value (reconstruction error ≤ 2⁻¹⁶
    relative — the certificate envelope's kernel-precision term)."""
    hi = v.astype(jnp.bfloat16)
    lo = (v - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


_NT = (((1,), (1,)), ((), ()))


def _scores_f32(xhi, xlo, ones_b, y):
    """Approximate ``yy − 2·x·y`` for an f32 y window: bf16x3 MXU
    contraction for the cross term plus two ``ones · split(y²)`` passes
    for the row norms — the norm rides the MXU so nothing but the slab
    itself streams from HBM."""
    yhi, ylo = _split_hi_lo(y)
    s = jax.lax.dot_general(xhi, yhi, _NT,
                            preferred_element_type=jnp.float32)
    s = s + jax.lax.dot_general(xhi, ylo, _NT,
                                preferred_element_type=jnp.float32)
    s = s + jax.lax.dot_general(xlo, yhi, _NT,
                                preferred_element_type=jnp.float32)
    y2hi, y2lo = _split_hi_lo(y * y)
    yy = jax.lax.dot_general(ones_b, y2hi, _NT,
                             preferred_element_type=jnp.float32)
    yy = yy + jax.lax.dot_general(ones_b, y2lo, _NT,
                                  preferred_element_type=jnp.float32)
    return yy - 2.0 * s


def _scores_q8(xhi, xlo, ones_b, yq, scale, passes: int):
    """Approximate ``‖ŷ‖² − 2·x·ŷ`` for an int8 window with per-list
    symmetric scale (ŷ = scale·yq): int8 magnitudes ≤ 127 are EXACT in
    bf16, so only x carries rounding (halved by the passes=3 x_lo pass,
    exactly :func:`ops.fused_l2_topk_pallas._contract_q8`'s argument);
    the scale rescales the ACCUMULATED partials in-register — the
    dequant-in-register path, never a widened copy in VMEM."""
    yqb = yq.astype(jnp.bfloat16)
    s = jax.lax.dot_general(xhi, yqb, _NT,
                            preferred_element_type=jnp.float32)
    if passes == 3:
        s = s + jax.lax.dot_general(xlo, yqb, _NT,
                                    preferred_element_type=jnp.float32)
    yqf = yq.astype(jnp.float32)
    y2hi, y2lo = _split_hi_lo(yqf * yqf)
    yy = jax.lax.dot_general(ones_b, y2hi, _NT,
                             preferred_element_type=jnp.float32)
    yy = yy + jax.lax.dot_general(ones_b, y2lo, _NT,
                                  preferred_element_type=jnp.float32)
    return (scale * scale) * yy - 2.0 * scale * s


def _fold_pool(acc, d2, base_row, nqp: int, Wk: int):
    """Fold a masked [nqp, Wk] score window into the per-query 128-slot
    pool: per lane class the two smallest scores with their GLOBAL slab
    rows, plus the running 3rd-min (certificate input — every row
    outside a slot's top-2 scored ≥ that slot's a3)."""
    a1, i1, a2, i2, a3 = acc
    lane = jax.lax.broadcasted_iota(jnp.int32, (nqp, _LANES), 1)
    for r in range(Wk // _LANES):
        c = d2[:, r * _LANES:(r + 1) * _LANES]
        ci = base_row + r * _LANES + lane
        lt1 = c < a1
        lt2 = c < a2
        lt3 = c < a3
        a3 = jnp.where(lt2, a2, jnp.where(lt3, c, a3))
        a2 = jnp.where(lt1, a1, jnp.where(lt2, c, a2))
        i2 = jnp.where(lt1, i1, jnp.where(lt2, ci, i2))
        a1 = jnp.where(lt1, c, a1)
        i1 = jnp.where(lt1, ci, i1)
    return a1, i1, a2, i2, a3


def _list_kernel_body(sched_ref, scale_ref, x_ref, xx_ref, probes_ref,
                      slab_ref, a1_ref, i1_ref, a2_ref, i2_ref, a3_ref,
                      *, Wk: int, q8: bool, passes: int):
    """One grid cell: stream LISTS_PER_CELL probed lists' windows
    through the 2-slot DMA pipeline, score the resident query block
    against each, mask non-member queries (probe-table comparison) and
    out-of-list window columns to the never-wins +inf, and fold into
    the revisited per-query pools."""
    s = pl.program_id(0)
    nqp, d = x_ref.shape
    inf = jnp.full((nqp, _LANES), jnp.inf, jnp.float32)
    neg1 = jnp.full((nqp, _LANES), -1, jnp.int32)

    @pl.when(s == 0)
    def _():
        a1_ref[...] = inf
        i1_ref[...] = neg1
        a2_ref[...] = inf
        i2_ref[...] = neg1
        a3_ref[...] = inf

    def body(scratch, sem):
        def dma(slot, j):
            return pltpu.make_async_copy(
                slab_ref.at[pl.ds(sched_ref[0, j], Wk), :],
                scratch.at[slot], sem.at[slot])

        j0 = s * LISTS_PER_CELL
        dma(0, j0).start()
        x = x_ref[...]
        xx = xx_ref[...]                                    # [nqp, 1]
        probes = probes_ref[...]                            # [nqp, Pp]
        xhi, xlo = _split_hi_lo(x)
        ones_b = jnp.ones((nqp, d), jnp.bfloat16)
        colv = jax.lax.broadcasted_iota(jnp.int32, (nqp, Wk), 1)
        acc = (a1_ref[...], i1_ref[...], a2_ref[...], i2_ref[...],
               a3_ref[...])
        for jj in range(LISTS_PER_CELL):
            j = j0 + jj
            slot = jj % 2
            if jj + 1 < LISTS_PER_CELL:
                dma((jj + 1) % 2, j + 1).start()         # prefetch next
            dma(slot, j).wait()
            st = sched_ref[0, j]
            lsize = sched_ref[1, j]
            off = sched_ref[2, j]
            lid = sched_ref[3, j]
            y = scratch[slot]
            if q8:
                r = _scores_q8(xhi, xlo, ones_b, y, scale_ref[j],
                               passes)
            else:
                r = _scores_f32(xhi, xlo, ones_b, y)
            d2 = xx + r
            # never-wins masks: queries whose probe table does not
            # contain this list, and window columns outside the list's
            # real rows (quantum pads, clamp slack, empty pad cells)
            member = jnp.sum((probes == lid).astype(jnp.float32),
                             axis=1, keepdims=True)         # [nqp, 1]
            d2 = jnp.where(member > 0.0, d2, jnp.inf)
            valid = (colv >= off) & (colv < off + lsize)
            d2 = jnp.where(valid, d2, jnp.inf)
            acc = _fold_pool(acc, d2, st, nqp, Wk)
        a1_ref[...], i1_ref[...], a2_ref[...], i2_ref[...], \
            a3_ref[...] = acc

    pl.run_scoped(
        body,
        scratch=pltpu.VMEM((2, Wk, x_ref.shape[1]),
                           jnp.int8 if q8 else jnp.float32),
        sem=pltpu.SemaphoreType.DMA((2,)))


def _pool_out_shape(nqp: int):
    return [
        jax.ShapeDtypeStruct((nqp, POOL_SLOTS), jnp.float32),  # a1
        jax.ShapeDtypeStruct((nqp, POOL_SLOTS), jnp.int32),    # i1
        jax.ShapeDtypeStruct((nqp, POOL_SLOTS), jnp.float32),  # a2
        jax.ShapeDtypeStruct((nqp, POOL_SLOTS), jnp.int32),    # i2
        jax.ShapeDtypeStruct((nqp, POOL_SLOTS), jnp.float32),  # a3
    ]


def _fine_scan_pallas_call(kernel, n_prefetch: int, n_cells: int,
                           nqp: int, Wk: int, d: int, q8: bool,
                           operands):
    out_spec = pl.BlockSpec((nqp, POOL_SLOTS), lambda s, *_: (0, 0),
                            memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(n_cells,),
        in_specs=[
            pl.BlockSpec((nqp, d), lambda s, *_: (0, 0),
                         memory_space=pltpu.VMEM),          # x
            pl.BlockSpec((nqp, 1), lambda s, *_: (0, 0),
                         memory_space=pltpu.VMEM),          # xx
            pl.BlockSpec((nqp, _LANES), lambda s, *_: (0, 0),
                         memory_space=pltpu.VMEM),          # probes
            pl.BlockSpec(memory_space=pltpu.ANY),           # slab (DMA)
        ],
        out_specs=[out_spec] * 5,
    )
    L = n_cells * LISTS_PER_CELL
    cost = pl.CostEstimate(
        # 3 bf16 cross passes + 2 norm passes (q8: ≤ 2 + 2)
        flops=2 * nqp * L * Wk * d * (4 if q8 else 5),
        bytes_accessed=(L * Wk * d * (1 if q8 else 4) + nqp * d * 4
                        + nqp * POOL_SLOTS * 8 * 5),
        transcendentals=0)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_pool_out_shape(nqp),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        cost_estimate=cost,
        interpret=interpret_mode(),
    )(*operands)


@functools.partial(jax.jit, static_argnames=("Wk",))
def fine_scan_list_major(sched, x, xx, probes, slab, Wk: int
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array, jax.Array]:
    """List-major fine scan over the f32 slab.

    Args:
      sched: [4, Lp] int32 schedule rows — (window start row, real list
        length, list-start offset within the window, list id); Lp a
        multiple of :data:`LISTS_PER_CELL`; pad entries carry
        ``(0, 0, 0, -1)``. Window starts are clamp-adjusted by the
        schedule builder so every [start, start+Wk) window stays inside
        the slab.
      x: [nqp, d] f32 resident query block (nqp a multiple of 8; pad
        rows zero).
      xx: [nqp, 1] exact f32 query squared norms.
      probes: [nqp, 128] int32 probe table (each query's probed list
        ids; pads −2 — they never match a list id, and pad LISTS carry
        id −1, which never matches a real probe).
      slab: [R, d] f32 padded ragged slab (R ≥ Wk).
      Wk: static window length, a multiple of 128 covering the index's
        probe window.

    Returns:
      (a1, i1, a2, i2, a3): [nqp, 128] per-lane-class top-2 approximate
      squared distances ``xx + yy − 2·x·y`` with GLOBAL slab-row ids
      (−1 = empty), and the running 3rd-min certificate input.
      Never-probed/pad entries stay (+inf, −1).
    """
    if Wk % _LANES:
        raise ValueError(f"fine_scan_list_major: Wk={Wk} must be a "
                         f"multiple of {_LANES}")
    Lp = sched.shape[1]
    if Lp % LISTS_PER_CELL:
        raise ValueError(f"fine_scan_list_major: schedule length {Lp} "
                         f"must be a multiple of {LISTS_PER_CELL}")
    nqp, d = x.shape

    def kernel_nq8(sched_ref, x_ref, xx_ref, probes_ref, slab_ref,
                   *out_refs):
        _list_kernel_body(sched_ref, None, x_ref, xx_ref, probes_ref,
                          slab_ref, *out_refs, Wk=Wk, q8=False,
                          passes=3)

    return _fine_scan_pallas_call(
        kernel_nq8, 1, Lp // LISTS_PER_CELL, nqp, Wk, d, False,
        (sched, x, xx, probes, slab))


@functools.partial(jax.jit, static_argnames=("Wk", "passes"))
def fine_scan_list_major_q8(sched, scale_l, x, xx, probes, slab_q,
                            Wk: int, passes: int = 3
                            ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                       jax.Array, jax.Array]:
    """INT8 list-major fine scan: same schedule/pool contract as
    :func:`fine_scan_list_major`, but the streamed window is the
    quantized slab (~¼ the probed bytes) and ``scale_l`` [Lp] f32
    carries each probed list's symmetric scale, applied to the
    accumulated partials in-register (the PR-9 ``_contract_q8``
    dequant-in-register path). Scores approximate ``‖ŷ‖² − 2·x·ŷ``
    against the dequantized rows ŷ — the caller's certificate widens by
    the recorded per-list Eq bound exactly like the query-major
    ``_fine_scan_q8``."""
    if Wk % _LANES:
        raise ValueError(f"fine_scan_list_major_q8: Wk={Wk} must be a "
                         f"multiple of {_LANES}")
    Lp = sched.shape[1]
    if Lp % LISTS_PER_CELL:
        raise ValueError(f"fine_scan_list_major_q8: schedule length "
                         f"{Lp} must be a multiple of {LISTS_PER_CELL}")
    nqp, d = x.shape

    def kernel_q8(sched_ref, scale_ref, x_ref, xx_ref, probes_ref,
                  slab_ref, *out_refs):
        _list_kernel_body(sched_ref, scale_ref, x_ref, xx_ref,
                          probes_ref, slab_ref, *out_refs, Wk=Wk,
                          q8=True, passes=passes)

    return _fine_scan_pallas_call(
        kernel_q8, 2, Lp // LISTS_PER_CELL, nqp, Wk, d, True,
        (sched, scale_l, x, xx, probes, slab_q))


def pad_window(W: int) -> int:
    """The kernel window for a probe window ``W``: rounded up to the
    128-lane quantum (the fold iterates lane chunks)."""
    return round_up(max(W, 1), _LANES)
