"""Matrix manipulation: slice, reverse, shift, diagonal, triangular, eye,
linewise op, print.

(ref: cpp/include/raft/matrix/slice.cuh, reverse.cuh, shift.cuh,
diagonal.cuh, triangular.cuh, init.cuh (eye), linewise_op.cuh +
matrix/detail/linewise_op.cuh (the vectorized row/col broadcast kernel),
print.hpp.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.linalg.types import Apply


def slice(res, matrix, x1: int, y1: int, x2: int, y2: int):  # noqa: A001
    """Copy the [x1:x2, y1:y2) submatrix. (ref: slice.cuh ``slice`` with
    slice_coordinates)"""
    matrix = jnp.asarray(matrix)
    expects(0 <= x1 < x2 <= matrix.shape[0] and 0 <= y1 < y2 <= matrix.shape[1],
            "slice: bad coordinates")
    return matrix[x1:x2, y1:y2]


def reverse(res, matrix, along_rows: bool = True):
    """Flip row order (along_rows) or column order.
    (ref: matrix/reverse.cuh ``col_reverse``/``row_reverse``)"""
    matrix = jnp.asarray(matrix)
    return matrix[::-1, :] if along_rows else matrix[:, ::-1]


col_reverse = lambda res, m: reverse(res, m, along_rows=False)  # noqa: E731
row_reverse = lambda res, m: reverse(res, m, along_rows=True)  # noqa: E731


def shift(res, matrix, offset: int, along_rows: bool = True, fill_value=0):
    """Shift rows (or columns) by ``offset`` slots, filling vacated lines.
    (ref: matrix/shift.cuh ``shift``; positive offset shifts toward higher
    indices.)"""
    matrix = jnp.asarray(matrix)
    axis = 0 if along_rows else 1
    n = matrix.shape[axis]
    expects(abs(offset) <= n, "shift: offset %d exceeds extent %d", offset, n)
    rolled = jnp.roll(matrix, offset, axis=axis)
    idx = jnp.arange(n)
    if offset >= 0:
        vacated = idx < offset
    else:
        vacated = idx >= n + offset
    mask = vacated[:, None] if along_rows else vacated[None, :]
    return jnp.where(mask, jnp.asarray(fill_value, matrix.dtype), rolled)


def get_diagonal(res, matrix):
    """(ref: matrix/diagonal.cuh ``get_diagonal_vector``)"""
    return jnp.diagonal(jnp.asarray(matrix))


def set_diagonal(res, matrix, diag):
    """(ref: diagonal.cuh ``set_diagonal``)"""
    matrix = jnp.asarray(matrix)
    n = min(matrix.shape)
    idx = jnp.arange(n)
    return matrix.at[idx, idx].set(jnp.asarray(diag)[:n])


def invert_diagonal(res, matrix):
    """(ref: diagonal.cuh ``invert_diagonal``)"""
    matrix = jnp.asarray(matrix)
    n = min(matrix.shape)
    idx = jnp.arange(n)
    return matrix.at[idx, idx].set(1.0 / matrix[idx, idx])


def upper_triangular(res, matrix):
    """Extract the upper triangle. (ref: matrix/triangular.cuh)"""
    return jnp.triu(jnp.asarray(matrix))


def lower_triangular(res, matrix):
    return jnp.tril(jnp.asarray(matrix))


def eye(res, n_rows: int, n_cols: Optional[int] = None, dtype=jnp.float32):
    """Identity. (ref: matrix/init.cuh ``eye``)"""
    return jnp.eye(n_rows, n_cols if n_cols is not None else n_rows, dtype=dtype)


def fill(res, shape, value, dtype=jnp.float32):
    """(ref: matrix/init.cuh ``fill``)"""
    return jnp.full(tuple(shape), value, dtype=dtype)


def linewise_op(res, matrix, *vecs, op: Callable,
                apply: Apply = Apply.ALONG_ROWS):
    """Apply op(row_or_col_element, v0[i], v1[i], ...) line-wise.
    (ref: matrix/linewise_op.cuh — alongLines=true applies vectors along
    each row.) ``ALONG_ROWS``: vectors have length n_cols and broadcast over
    rows; ``ALONG_COLUMNS``: length n_rows, broadcast over columns."""
    matrix = jnp.asarray(matrix)
    expand = (lambda v: jnp.asarray(v)[None, :]) if apply == Apply.ALONG_ROWS \
        else (lambda v: jnp.asarray(v)[:, None])
    return op(matrix, *[expand(v) for v in vecs])


def print_matrix(matrix, name: str = "", h_separator: str = " ",
                 v_separator: str = "\n") -> str:
    """Host-side pretty print. (ref: matrix/print.hpp)"""
    import numpy as np

    arr = np.asarray(matrix)
    body = v_separator.join(
        h_separator.join(f"{v}" for v in row) for row in np.atleast_2d(arr)
    )
    return f"{name}{v_separator}{body}" if name else body
