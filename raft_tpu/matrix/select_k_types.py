"""select_k algorithm selection types.

(ref: cpp/include/raft/matrix/select_k_types.hpp:28-70 ``enum SelectAlgo``:
kAuto, kRadix8bits, kRadix11bits, kRadix11bitsExtraPass, kWarpAuto,
kWarpImmediate, kWarpFiltered, kWarpDistributed, kWarpDistributedShm.)

The TPU algorithm space is different — there are no warp shuffles or shared-
memory histograms. The variants that exist here:

- ``AUTO``          — heuristic choice (see matrix/select_k.py)
- ``XLA_TOPK``      — ``jax.lax.top_k`` (XLA's sort-based top-k)
- ``SLOTTED``       — certified slot folding (select_k_slotted.py):
                      ~3 bandwidth-bound vector passes + exactness
                      certificate + per-row exact fallback — the
                      bandwidth-bound role of the reference's radix
                      filtering, without sort or histogram
- ``CHUNKED``       — exact per-chunk top-k + narrow merge
                      (select_k_chunked.py): the large-k regime where
                      one wide XLA TopK goes superlinear — the ROLE of
                      the reference's radix select at large k
- ``RADIX``         — alias of CHUNKED. A literal Pallas digit-histogram
                      kernel existed through round 3 and never won a
                      single measured cell (66 cells over two rounds,
                      5-40× behind XLA/SLOTTED — SELECT_K_MATRIX.json);
                      it was deleted, and the radix NAME dispatches to
                      the algorithm serving its large-k filtering role
- ``BITONIC``       — alias of SLOTTED (the warp-queue role; no warp
                      shuffles exist on TPU to build a literal bitonic
                      queue from)
- ``APPROX``        — ``jax.lax.approx_min_k/approx_max_k``: XLA's
                      TPU-hardware aggregate top-k with a recall target
                      (default 0.95). INEXACT by contract — a TPU-native
                      extension with no reference counterpart (the
                      reference's approximate selection lives in ANN,
                      which moved to cuVS). AUTO never chooses it.

The CUDA names are kept as aliases so reference-written code dispatches
meaningfully.
"""

from __future__ import annotations

import enum


class SelectAlgo(enum.Enum):
    AUTO = "auto"
    XLA_TOPK = "xla_topk"
    SLOTTED = "slotted"
    CHUNKED = "chunked"
    BITONIC = "bitonic"
    RADIX = "radix"
    APPROX = "approx"

    # reference-name aliases → nearest TPU variant
    @classmethod
    def from_reference_name(cls, name: str) -> "SelectAlgo":
        name = name.lower().replace("k", "", 1) if name.startswith("k") else name.lower()
        mapping = {
            "auto": cls.AUTO,
            "radix8bits": cls.RADIX,
            "radix11bits": cls.RADIX,
            "radix11bitsextrapass": cls.RADIX,
            "warpauto": cls.BITONIC,
            "warpimmediate": cls.BITONIC,
            "warpfiltered": cls.BITONIC,
            "warpdistributed": cls.BITONIC,
            "warpdistributedshm": cls.BITONIC,
        }
        return mapping[name]


def f32_comparable_keys(dtype) -> bool:
    """Whether selection keys of ``dtype`` compare EXACTLY after an f32
    cast — the shared dtype envelope of the SLOTTED and CHUNKED
    families (both compare keys in f32; f64/int keys could collide
    distinct values, so they take the XLA path). The ONE definition —
    the impls and AUTO's envelope check all call this."""
    import jax.numpy as jnp

    return bool(jnp.issubdtype(dtype, jnp.floating)
                and jnp.finfo(dtype).bits <= 32)
