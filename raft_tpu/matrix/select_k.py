"""Batched top-k selection — the flagship matrix primitive.

(ref: cpp/include/raft/matrix/select_k.cuh:75 public API;
matrix/detail/select_k-inl.cuh:38 ``choose_select_k_algorithm`` learned
decision tree, applied at :244; radix impl matrix/detail/select_radix.cuh;
warpsort impl matrix/detail/select_warpsort.cuh.)

Semantics preserved from the reference: batched rows, optional input
indices (defaults to 0..len-1 per row), ``select_min`` choosing smallest or
largest, sorted output, stable on the XLA path.

TPU-first algorithm space (no warp shuffles / SM histograms here):
``XLA_TOPK`` lowers to XLA's fused sort/top-k; ``SLOTTED`` is the
certified slot-fold (sort-free, bandwidth-bound, always exact —
select_k_slotted.py) — it plays the reference warpsort family's ROLE
(bandwidth-bound selection keeping per-bucket running minima in
registers) with folds instead of queues; ``CHUNKED`` is the exact
per-chunk+merge large-k algorithm (select_k_chunked.py). The literal
Pallas radix kernel was DELETED in round 3 after never winning any of
66 measured cells over two rounds (a VPU-bound digit histogram loses
to compare/select folds), and a literal bitonic lane-queue is an
anti-fit (every compare-exchange needs cross-lane relayouts) — so the
``RADIX``/``BITONIC`` reference names dispatch to CHUNKED/SLOTTED,
the algorithms serving their roles. The AUTO heuristic is
table-driven off measured TPU timings the way the reference's learned
tree is generated from benchmark sweeps.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.matrix.select_k_types import SelectAlgo
from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point


def _load_select_k_table():
    """Load the measured algorithm table (benchmarks/select_k_matrix.py →
    SELECT_K_MATRIX.json), if one has been committed. Returns a list of
    (log-coords, SelectAlgo) cells, or None."""
    import json
    import math
    import os

    from raft_tpu.native import _REPO_ROOT

    path = os.environ.get("RAFT_TPU_SELECTK_TABLE") or os.path.join(
        _REPO_ROOT, "SELECT_K_MATRIX.json")
    try:
        with open(path) as f:
            data = json.load(f)
        cells = []
        for row in data.get("rows", []):
            # RADIX is deliberately NOT a candidate: its kernel was
            # deleted (round 3) and the name now aliases CHUNKED, so
            # historical radix timings must not label cells
            timings = {name: row[name] for name in
                       ("XLA_TOPK", "SLOTTED", "CHUNKED")
                       if isinstance(row.get(name), (int, float))
                       and not isinstance(row.get(name), bool)
                       # 0.0 is a measurement artifact (sub-RTT clamp in
                       # Fixture.run), not a real timing — a cell must
                       # never be labeled off an artifact
                       and row[name] > 0.0}
            if not timings:
                continue
            best = min(timings, key=timings.get)
            cells.append(((math.log2(row["batch"]), math.log2(row["len"]),
                           math.log2(row["k"])), SelectAlgo[best]))
        return cells or None
    except Exception:
        # a malformed hand-edited table must never crash AUTO select_k —
        # degrade to the no-table default
        return None


_SELECT_K_TABLE = ...   # lazy-loaded sentinel


def _algo_in_envelope(algo: SelectAlgo, length: int, k: int,
                      dtype=None) -> bool:
    """Whether (length, k, dtype) is inside ``algo``'s implementation
    envelope — the same predicates whose violation makes the impls
    raise NotImplementedError. AUTO consults this BEFORE the table
    lookup so it never dispatches into a guaranteed internal fallback
    (wasted dispatch + mislabeled measurement)."""
    if algo in (SelectAlgo.SLOTTED, SelectAlgo.CHUNKED):
        from raft_tpu.matrix.select_k_types import f32_comparable_keys

        if dtype is not None and not f32_comparable_keys(dtype):
            return False
    if algo == SelectAlgo.SLOTTED:
        from raft_tpu.matrix.select_k_slotted import slotted_envelope

        return k <= slotted_envelope(length, k)[2]
    if algo == SelectAlgo.CHUNKED:
        from raft_tpu.matrix.select_k_chunked import chunked_envelope

        return chunked_envelope(length)
    return True


def choose_select_k_algorithm(n_rows: int, length: int, k: int,
                              dtype=None) -> SelectAlgo:
    """Heuristic algorithm choice. (ref: select_k-inl.cuh:38 — a learned
    decision tree over (rows, cols, k), generated from benchmark sweeps.)

    The TPU analog is table-driven the same way: when a measured
    ``SELECT_K_MATRIX.json`` exists (produced on real TPU by
    benchmarks/select_k_matrix.py — never from CPU timings), AUTO picks
    the measured-fastest algorithm of the nearest grid cell in
    (log batch, log len, log k), restricted to algorithms whose
    envelope admits (length, k, dtype) — AUTO never returns a choice
    that would raise internally. Without a table the only
    measurement-justified choice is XLA's top-k (round-1 anchor: XLA
    ≈4.7ms vs Pallas radix ≈43ms on [16,1M] f32, k=64 — the radix
    histogram is VPU-bound; SLOTTED had no TPU numbers yet)."""
    global _SELECT_K_TABLE
    if _SELECT_K_TABLE is ...:
        _SELECT_K_TABLE = _load_select_k_table()
    if _SELECT_K_TABLE:
        import math

        q = (math.log2(max(n_rows, 1)), math.log2(max(length, 1)),
             math.log2(max(k, 1)))
        ok = {a: _algo_in_envelope(a, length, k, dtype)
              for a in {cell[1] for cell in _SELECT_K_TABLE}}
        eligible = [cell for cell in _SELECT_K_TABLE if ok[cell[1]]]
        if eligible:
            _, algo = min(
                eligible,
                key=lambda cell: sum((a - b) ** 2
                                     for a, b in zip(cell[0], q)))
            return algo
    return SelectAlgo.XLA_TOPK


def _xla_select_k(in_val, in_idx, k: int, select_min: bool):
    vals = -in_val if select_min else in_val
    top_v, top_pos = jax.lax.top_k(vals, k)
    out_val = -top_v if select_min else top_v
    out_idx = jnp.take_along_axis(in_idx, top_pos, axis=1)
    return out_val, out_idx


@instrument("matrix.select_k")
def select_k(
    res,
    in_val,
    in_idx=None,
    k: int = 1,
    select_min: bool = True,
    sorted: bool = True,  # noqa: A002
    algo: SelectAlgo = SelectAlgo.AUTO,
    recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (or largest) entries per row.

    Returns ``(out_val [batch, k], out_idx [batch, k])``.
    (ref: matrix/select_k.cuh:75) ``recall_target`` applies to
    ``SelectAlgo.APPROX`` only (inexact by contract; see select_k_types).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.matrix import select_k
    >>> vals, idx = select_k(None, np.array([[3.0, 1.0, 2.0]]), k=2)
    >>> np.asarray(vals).tolist(), np.asarray(idx).tolist()
    ([[1.0, 2.0]], [[1, 2]])
    """
    fault_point("select_k")
    in_val = jnp.asarray(in_val)
    expects(in_val.ndim == 2, "select_k: in_val must be [batch, len]")
    batch, length = in_val.shape
    expects(0 < k <= length, "select_k: k=%d out of range for len=%d", k, length)
    if in_idx is None:
        in_idx = jnp.broadcast_to(jnp.arange(length, dtype=jnp.int32)[None, :],
                                  (batch, length))
    else:
        in_idx = jnp.asarray(in_idx)
        expects(in_idx.shape == in_val.shape, "select_k: in_idx shape mismatch")

    explicit = algo != SelectAlgo.AUTO
    if not explicit:
        algo = choose_select_k_algorithm(batch, length, k,
                                         dtype=in_val.dtype)

    if algo in (SelectAlgo.RADIX, SelectAlgo.BITONIC):
        # the Pallas radix kernel was DELETED in round 3: across two
        # measured matrices (66 cells) it never won a single cell —
        # 5-40× behind XLA/SLOTTED everywhere, including the large-k
        # regime it nominally served (SELECT_K_MATRIX.json; CHANGELOG).
        # The reference names keep dispatching to the algorithms that
        # play their ROLES: radix (large-k filtering) → CHUNKED,
        # warp-queue → SLOTTED.
        algo = (SelectAlgo.CHUNKED if algo == SelectAlgo.RADIX
                else SelectAlgo.SLOTTED)

    if algo == SelectAlgo.SLOTTED:
        from raft_tpu.matrix.select_k_slotted import select_k_slotted

        try:
            return select_k_slotted(in_val, in_idx, k, select_min)
        except NotImplementedError as e:
            # AUTO (nearest-cell lookup) may land outside the envelope —
            # that fallback is silent by design; only an EXPLICIT request
            # warns, because silently measuring the XLA path instead
            # would invalidate benchmarks/tests of the named algorithm
            if explicit:
                import warnings

                warnings.warn(
                    f"select_k: explicit algo=SLOTTED outside its "
                    f"envelope ({e}); falling back to XLA top-k",
                    RuntimeWarning, stacklevel=2)

    if algo == SelectAlgo.CHUNKED:
        from raft_tpu.matrix.select_k_chunked import select_k_chunked

        try:
            return select_k_chunked(in_val, in_idx, k, select_min)
        except NotImplementedError as e:
            if explicit:
                import warnings

                warnings.warn(
                    f"select_k: explicit algo=CHUNKED outside its "
                    f"envelope ({e}); falling back to XLA top-k",
                    RuntimeWarning, stacklevel=2)

    if algo == SelectAlgo.APPROX:
        # XLA's TPU-hardware aggregate top-k (recall-targeted, INEXACT —
        # see select_k_types). Returns positions; gather the caller ids.
        fn = jax.lax.approx_min_k if select_min else jax.lax.approx_max_k
        vals_a, pos = fn(in_val, k, recall_target=float(recall_target))
        return vals_a, jnp.take_along_axis(in_idx, pos, axis=1)

    return _xla_select_k(in_val, in_idx, k, select_min)
