"""Certified slotted select_k — the bandwidth-bound selection algorithm.

(ref: the role of matrix/detail/select_radix.cuh:639 — the reference's
radix select exists because sorting is too expensive; its filtering
passes stream the row at memory bandwidth. The TPU-native equivalent is
slot folding: partition each row into S slots, keep per-slot (min,
argmin, 2nd-min) — pure vector min/select ops that XLA fuses into ~3
linear passes — then select among slot-mins and CERTIFY exactness with
the 2nd-min bound. No sort, no histogram, no Pallas required: the memory
system is the only cost.)

Exactness: candidates are the top-C pool entries of per-group top-2 slot
mins; every non-candidate value is ≥ B = min(slot 2nd-min, group 3rd-min,
C-th pool value), so ``B ≥ θ`` (θ = k-th candidate) proves the candidate
top-k is the true top-k (same certificate as distance.knn_fused). Rows
that fail (two of the true top-k sharing a slot, ~k²/2S per row) are
re-solved exactly by XLA top_k and scattered back — the result is ALWAYS
exact; slotting only decides how fast.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.ops.folds import fold_group_top2

_POOL_PAD = 32


@partial(jax.jit, static_argnames=("k", "slot", "g", "fallback_rows"))
def _slotted_select_min(vals, k: int, slot: int, g: int,
                        fallback_rows: int) -> Tuple[jax.Array, jax.Array]:
    """Exact k smallest per row of ``vals`` [B, L] (L % slot == 0),
    ascending. Returns (values, positions)."""
    B, L = vals.shape
    S = L // slot
    v3 = vals.reshape(B, S, slot)

    # per-slot min / argmin / 2nd-min: three fused linear passes
    m1 = jnp.min(v3, axis=2)
    a1 = jnp.argmin(v3, axis=2).astype(jnp.int32)
    i1 = a1 + slot * jnp.arange(S, dtype=jnp.int32)[None, :]
    lane = jnp.arange(slot, dtype=jnp.int32)
    masked = jnp.where(lane[None, None, :] == a1[:, :, None], jnp.inf, v3)
    m2 = jnp.min(masked, axis=2)

    p1, pid1, p2, pid2, p3 = fold_group_top2(m1, i1, g)
    pool_v = jnp.concatenate([p1, p2], axis=1)
    pool_i = jnp.concatenate([pid1, pid2], axis=1)
    C = min(k + _POOL_PAD, pool_v.shape[1])
    neg, pos = jax.lax.top_k(-pool_v, C)
    cand_v = -neg
    cand_i = jnp.take_along_axis(pool_i, pos, axis=1)

    theta = cand_v[:, k - 1]
    bound = jnp.minimum(jnp.min(m2, axis=1), jnp.min(p3, axis=1))
    bound = jnp.minimum(bound, cand_v[:, C - 1])
    failed = bound < theta                                      # [B]
    # rows with < k finite values leave unfilled (-1) candidates; route
    # them through the exact fallback so positions stay distinct, exactly
    # like the XLA path's degenerate-row behavior
    failed = failed | jnp.any(cand_i[:, :k] < 0, axis=1)
    n_fail = jnp.sum(failed.astype(jnp.int32))

    out_v = cand_v[:, :k]
    out_i = cand_i[:, :k]

    def exact_rows(rows_v):
        nv, np_ = jax.lax.top_k(-rows_v, k)
        return -nv, np_.astype(jnp.int32)

    def no_fix(o):
        return o

    def small_fix(o):
        ov, oi = o
        _, fidx = jax.lax.top_k(failed.astype(jnp.int32), fallback_rows)
        fv, fi = exact_rows(vals[fidx])
        return ov.at[fidx].set(fv), oi.at[fidx].set(fi)

    def full_fix(o):
        return exact_rows(vals)

    if B <= fallback_rows:
        return jax.lax.cond(n_fail > 0, full_fix, no_fix, (out_v, out_i))
    return jax.lax.cond(
        n_fail == 0, no_fix,
        lambda o: jax.lax.cond(n_fail <= fallback_rows, small_fix,
                               full_fix, o),
        (out_v, out_i))


def slotted_envelope(L: int) -> Tuple[int, int, int]:
    """(slot, g, pool_capacity) the slotted algorithm uses for row length
    ``L`` — the single source of truth for the envelope (tests and the
    AUTO heuristic derive bounds from here, never re-hardcode)."""
    slot = 16 if L >= 4096 else 4
    g = 8
    Lp = -(-L // (slot * g)) * (slot * g)
    S = Lp // slot
    return slot, g, 2 * (S // min(g, S))


def select_k_slotted(in_val, in_idx, k: int, select_min: bool
                     ) -> Tuple[jax.Array, jax.Array]:
    """select_k via certified slot folding.

    Envelope (raises NotImplementedError outside, so callers fall back):
    - k ≤ pool capacity = 2·S/g — ≈ len/64 for the default slot=16, g=8
      (len ≥ 4096), ≈ len/16 for short rows (slot=4);
    - dtype: ≤ 32-bit floating keys (f32/bf16/f16 — selection keys are
      compared in f32, which is exact for those; f64/int keys would be
      silently rounded, so they take the XLA path instead).
    Returned values are GATHERED from the input, preserving its dtype."""
    in_val = jnp.asarray(in_val)
    if not (jnp.issubdtype(in_val.dtype, jnp.floating)
            and jnp.finfo(in_val.dtype).bits <= 32):
        raise NotImplementedError(
            f"slotted select_k: f32/bf16/f16 keys only, got {in_val.dtype}")
    keys = in_val.astype(jnp.float32)
    B, L = in_val.shape
    slot, g, pool = slotted_envelope(L)
    # pad rows so the slot count is a group multiple (the fold reshapes
    # [B, S] into [B, S/g, g])
    Lp = -(-L // (slot * g)) * (slot * g)
    S = Lp // slot
    if k > pool:
        raise NotImplementedError(
            f"slotted select_k: k={k} exceeds pool {pool} for len={L}")
    work = keys if select_min else -keys
    if Lp != L:
        work = jnp.pad(work, ((0, 0), (0, Lp - L)),
                       constant_values=jnp.inf)
    _, out_pos = _slotted_select_min(work, k, slot, min(g, S), 128)
    safe_pos = jnp.clip(out_pos, 0, L - 1)
    # gather from the ORIGINAL input: values keep the caller's dtype
    out_v = jnp.take_along_axis(in_val, safe_pos, axis=1)
    if in_idx is not None:
        out_idx = jnp.take_along_axis(jnp.asarray(in_idx), safe_pos, axis=1)
    else:
        out_idx = out_pos
    return out_v, out_idx
