"""Certified slotted select_k — the bandwidth-bound selection algorithm.

(ref: the role of matrix/detail/select_radix.cuh:639 — the reference's
radix select exists because sorting is too expensive; its filtering
passes stream the row at memory bandwidth. The TPU-native equivalent is
slot folding: partition each row into S slots, keep per-slot (min,
argmin, 2nd-min) — pure vector min/select ops that XLA fuses into ~3
linear passes — then select among slot-mins and CERTIFY exactness with
the 2nd-min bound. No sort, no histogram, no Pallas required: the memory
system is the only cost.)

Exactness: candidates are the top-C pool entries of per-group top-2 slot
mins; every non-candidate value is ≥ B = min(slot 2nd-min, group 3rd-min,
C-th pool value), so ``B ≥ θ`` (θ = k-th candidate) proves the candidate
top-k is the true top-k (same certificate as distance.knn_fused). Rows
that fail (two of the true top-k sharing a slot, ~k²/2S per row) are
re-solved exactly by XLA top_k and scattered back — the result is ALWAYS
exact; slotting only decides how fast.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.ops.folds import fold_group_top2
from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point

_POOL_PAD = 32


def _certified_fallback(vals, out_v, out_i, failed, k: int, tiers):
    """Shared certificate-fallback scaffolding for both slotted paths:
    rows flagged ``failed`` are re-solved exactly (XLA top_k on the
    gathered rows) in the smallest static tier that covers them, else
    the whole batch falls back. ALWAYS exact — slotting/packing only
    decide how fast."""
    B = vals.shape[0]
    n_fail = jnp.sum(failed.astype(jnp.int32))

    def exact_rows(rows_v):
        nv, np_ = jax.lax.top_k(-rows_v, k)
        return -nv, np_.astype(jnp.int32)

    def no_fix(o):
        return o

    def make_fix(F):
        def fix(o):
            ov, oi = o
            _, fidx = jax.lax.top_k(failed.astype(jnp.int32), F)
            fv, fi = exact_rows(vals[fidx])
            return ov.at[fidx].set(fv), oi.at[fidx].set(fi)
        return fix

    def full_fix(o):
        return exact_rows(vals)

    branch = full_fix
    for t in [t for t in sorted(tiers, reverse=True) if t < B]:
        branch = (lambda o, t=t, nxt=branch: jax.lax.cond(
            n_fail <= t, make_fix(t), nxt, o))
    return jax.lax.cond(n_fail == 0, no_fix, branch, (out_v, out_i))


@partial(jax.jit, static_argnames=("k", "slot", "g", "fallback_rows"))
def _slotted_select_min(vals, k: int, slot: int, g: int,
                        fallback_rows: int) -> Tuple[jax.Array, jax.Array]:
    """Exact k smallest per row of ``vals`` [B, L] (L % slot == 0),
    ascending. Returns (values, positions)."""
    B, L = vals.shape
    S = L // slot
    v3 = vals.reshape(B, S, slot)

    # per-slot min / argmin / 2nd-min: three fused linear passes
    m1 = jnp.min(v3, axis=2)
    a1 = jnp.argmin(v3, axis=2).astype(jnp.int32)
    i1 = a1 + slot * jnp.arange(S, dtype=jnp.int32)[None, :]
    lane = jnp.arange(slot, dtype=jnp.int32)
    masked = jnp.where(lane[None, None, :] == a1[:, :, None], jnp.inf, v3)
    m2 = jnp.min(masked, axis=2)

    p1, pid1, p2, pid2, p3 = fold_group_top2(m1, i1, g)
    pool_v = jnp.concatenate([p1, p2], axis=1)
    pool_i = jnp.concatenate([pid1, pid2], axis=1)
    C = min(k + _POOL_PAD, pool_v.shape[1])
    neg, pos = jax.lax.top_k(-pool_v, C)
    cand_v = -neg
    cand_i = jnp.take_along_axis(pool_i, pos, axis=1)

    theta = cand_v[:, k - 1]
    bound = jnp.minimum(jnp.min(m2, axis=1), jnp.min(p3, axis=1))
    bound = jnp.minimum(bound, cand_v[:, C - 1])
    # NaN-SAFE predicate (~(b ≥ θ), not b < θ): a NaN-poisoned bound —
    # NaN inputs, or ±inf through the packed path — must read as FAILED
    # so the row takes the exact fallback, never "certified". Rows with
    # < k finite values leave unfilled (-1) candidates; route them
    # through the fallback too so positions stay distinct.
    failed = ~(bound >= theta) | jnp.any(cand_i[:, :k] < 0, axis=1)
    return _certified_fallback(vals, cand_v[:, :k], cand_i[:, :k],
                               failed, k, (fallback_rows,))


# Pallas streaming path (L ≥ _PALLAS_MIN_L): one linear pass, packed
# candidate codes — see ops/select_slotted_pallas.py.
#
# Tile geometry is DMA-driven: a (Bb, T) block slices T·4 bytes from
# each of Bb rows of the [B, L] input, so the per-row run length must
# be large to amortize the row stride — (8, 8192) gives contiguous
# 32 KB runs (MEASURED: (256, 1024) blocks ran at 0.28 GB/s — 4 KB
# strided runs — 3.6 s for a [256, 1M] select). tpg=4 keeps
# tpg·(T/128) = 256 = the full packed code space.
_T_SEL = 8192
_BB_SEL = 8
_TPG_SEL = 4
_PALLAS_MIN_L = 4096
_FALLBACK_TIERS = (16, 128)


@partial(jax.jit, static_argnames=("k",))
def _slotted_select_min_pallas(work, k: int
                               ) -> Tuple[jax.Array, jax.Array]:
    """Exact k smallest per row of ``work`` [B, L] f32 via the packed
    Pallas streaming fold + certified pool selection. Same contract as
    :func:`_slotted_select_min`."""
    from raft_tpu.distance.knn_fused import decode_packed_pool
    from raft_tpu.ops.fused_l2_topk_pallas import _PACK_PAD
    from raft_tpu.ops.select_slotted_pallas import select_slot_topk_packed

    B, L = work.shape
    Lp = -(-L // _T_SEL) * _T_SEL
    Bb = _BB_SEL
    Bp = -(-B // Bb) * Bb
    # adaptive group size (from the envelope — the single source of
    # truth): large k needs more slots or 3-in-group collisions explode
    # (MEASURED: k=256 at [256, 1M] with tpg=4 fails ~16% of rows → the
    # 128-row fallback tier dominates at ~119 ms; tpg=1 quadruples the
    # slot count for ~1% failures)
    _, tpg, _ = slotted_envelope(L, k)
    w = jnp.pad(work, ((0, Bp - B), (0, Lp - L)),
                constant_values=_PACK_PAD)
    a1p, a2p, a3p = select_slot_topk_packed(w, T=_T_SEL, Bb=Bb,
                                            tpg=tpg)
    a1p, a2p, a3p = a1p[:B], a2p[:B], a3p[:B]
    S_ = a1p.shape[1]

    pool_p = jnp.concatenate([a1p, a2p], axis=1)        # [B, 2S'] packed
    C = min(k + _POOL_PAD, pool_p.shape[1])
    neg, pos = jax.lax.top_k(-pool_p, C)
    cand_p = -neg
    pid = decode_packed_pool(cand_p, pos, S_, _T_SEL, tpg)
    # candidates' TRUE values (gather — the select analog of the fused
    # pipeline's exact rescore; packing only perturbs the low mantissa
    # bits used for ORDERING, the returned values are the inputs')
    cand_true = jnp.take_along_axis(work, jnp.clip(pid, 0, L - 1), axis=1)
    cand_true = jnp.where(pid >= 0, cand_true, jnp.inf)
    neg_k, ord_k = jax.lax.top_k(-cand_true, k)
    out_v = -neg_k
    out_i = jnp.take_along_axis(pid, ord_k, axis=1)

    # certificate: every non-candidate's packed value ≥ B_packed =
    # min(group 3rd-mins, C-th pool entry); true ≥ packed − |packed|·2⁻¹⁵
    # (the merge orders by packed values, whose low _PACK_BITS mantissa
    # bits are the candidate code)
    theta = out_v[:, k - 1]
    b_packed = jnp.minimum(jnp.min(a3p, axis=1), cand_p[:, C - 1])
    b_true = b_packed - jnp.abs(b_packed) * 2.0 ** -15
    # NaN-SAFE predicate: ±inf inputs become NaN when code bits are
    # OR'd into their mantissa, silently dropping them from candidates
    # — but the same NaN poisons a3p and hence b_true, so ~(b ≥ θ)
    # routes any row containing ±inf/NaN to the exact fallback (the
    # pre-fix `b < θ` comparison read NaN as "certified": wrong top-k
    # with no error)
    failed = ~(b_true >= theta) | jnp.any(out_i < 0, axis=1)
    return _certified_fallback(work, out_v, out_i, failed, k,
                               _FALLBACK_TIERS)


def slotted_envelope(L: int, k: int = None) -> Tuple[int, int, int]:
    """(slot, g, pool_capacity) the slotted algorithm uses for row length
    ``L`` (and, on the Pallas path, request size ``k`` — the adaptive
    tpg switch means capacity GROWS for k > 64) — the single source of
    truth for the envelope (tests and the AUTO heuristic derive bounds
    from here, never re-hardcode). For L ≥ _PALLAS_MIN_L the streaming
    Pallas path is used and the pool is 2·128·G (G = tile groups);
    below it, the XLA slot fold. ``k=None`` reports the conservative
    (small-k) capacity."""
    if L >= _PALLAS_MIN_L:
        tpg = _TPG_SEL if (k is None or k <= 64) else 1
        n_tiles = -(-L // _T_SEL)
        G = -(-n_tiles // tpg)
        return _T_SEL // 128, tpg, 2 * 128 * G
    slot, g = 4, 8
    Lp = -(-L // (slot * g)) * (slot * g)
    S = Lp // slot
    return slot, g, 2 * (S // min(g, S))


@instrument("matrix.select_k_slotted")
def select_k_slotted(in_val, in_idx, k: int, select_min: bool
                     ) -> Tuple[jax.Array, jax.Array]:
    """select_k via certified slot folding.

    Envelope (raises NotImplementedError outside, so callers fall back):
    - k ≤ pool capacity per :func:`slotted_envelope` — for len ≥ 4096
      (the Pallas streaming path) 2·128·ceil(ceil(len/8192)/tpg) with
      the adaptive tpg (4 for k ≤ 64, 1 above); ≈ len/16 for short rows
      (XLA slot fold, slot=4);
    - dtype: ≤ 32-bit floating keys (f32/bf16/f16 — selection keys are
      compared in f32, which is exact for those; f64/int keys would be
      silently rounded, so they take the XLA path instead).
    Returned values are GATHERED from the input, preserving its dtype."""
    from raft_tpu.matrix.select_k_types import f32_comparable_keys

    fault_point("select_k_slotted")
    in_val = jnp.asarray(in_val)
    if not f32_comparable_keys(in_val.dtype):
        raise NotImplementedError(
            f"slotted select_k: f32/bf16/f16 keys only, got {in_val.dtype}")
    keys = in_val.astype(jnp.float32)
    B, L = in_val.shape
    slot, g, pool = slotted_envelope(L, k)
    if k > pool:
        raise NotImplementedError(
            f"slotted select_k: k={k} exceeds pool {pool} for len={L}")
    work = keys if select_min else -keys
    if L >= _PALLAS_MIN_L:
        # streaming packed Pallas fold (pads internally)
        _, out_pos = _slotted_select_min_pallas(work, k)
    else:
        # XLA slot fold for short rows; pad so the slot count is a
        # group multiple (the fold reshapes [B, S] into [B, S/g, g])
        Lp = -(-L // (slot * g)) * (slot * g)
        S = Lp // slot
        if Lp != L:
            work = jnp.pad(work, ((0, 0), (0, Lp - L)),
                           constant_values=jnp.inf)
        _, out_pos = _slotted_select_min(work, k, slot, min(g, S), 128)
    safe_pos = jnp.clip(out_pos, 0, L - 1)
    # gather from the ORIGINAL input: values keep the caller's dtype
    out_v = jnp.take_along_axis(in_val, safe_pos, axis=1)
    if in_idx is not None:
        out_idx = jnp.take_along_axis(jnp.asarray(in_idx), safe_pos, axis=1)
    else:
        out_idx = out_pos
    return out_v, out_idx
