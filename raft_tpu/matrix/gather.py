"""Row gather/scatter (+ conditional and map-transform variants).

(ref: cpp/include/raft/matrix/gather.cuh, matrix/detail/gather.cuh,
matrix/gather_inplace.cuh, matrix/scatter.cuh. The reference's in-place
variants exist for memory reasons; in functional JAX all variants return new
arrays — XLA elides the copy when it can.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects


def gather(res, matrix, gather_map, transform_op: Optional[Callable] = None):
    """out[i, :] = op(matrix[map[i], :]). (ref: gather.cuh ``gather``)"""
    matrix = jnp.asarray(matrix)
    gather_map = jnp.asarray(gather_map)
    out = matrix[gather_map, :]
    return transform_op(out) if transform_op else out


def gather_if(res, matrix, gather_map, stencil, pred_op: Callable,
              transform_op: Optional[Callable] = None):
    """Gather rows where pred_op(stencil[i]); other output rows are zero.
    (ref: gather.cuh ``gather_if``)"""
    gathered = gather(res, matrix, gather_map, transform_op)
    keep = pred_op(jnp.asarray(stencil)).astype(bool)
    return jnp.where(keep[:, None], gathered, jnp.zeros_like(gathered))


gather_inplace = gather  # (ref: gather_inplace.cuh — functional here)


def scatter(res, matrix, scatter_map):
    """out[map[i], :] = matrix[i, :]. (ref: matrix/scatter.cuh; map must be
    a permutation of 0..n_rows-1, as in the reference.)"""
    matrix = jnp.asarray(matrix)
    scatter_map = jnp.asarray(scatter_map)
    expects(scatter_map.shape[0] == matrix.shape[0],
            "scatter: map length %d != n_rows %d", scatter_map.shape[0], matrix.shape[0])
    return jnp.zeros_like(matrix).at[scatter_map, :].set(matrix)
