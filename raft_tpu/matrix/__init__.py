"""raft_tpu.matrix — matrix manipulation + batched top-k. (ref:
cpp/include/raft/matrix, SURVEY §2.4.)"""

from raft_tpu.matrix.select_k import select_k, choose_select_k_algorithm
from raft_tpu.matrix.select_k_types import SelectAlgo
from raft_tpu.matrix.gather import gather, gather_if, gather_inplace, scatter
from raft_tpu.matrix.manip import (
    slice,
    reverse,
    col_reverse,
    row_reverse,
    shift,
    get_diagonal,
    set_diagonal,
    invert_diagonal,
    upper_triangular,
    lower_triangular,
    eye,
    fill,
    linewise_op,
    print_matrix,
)
from raft_tpu.matrix.math_ops import (
    power,
    weighted_power,
    sqrt,
    ratio,
    reciprocal,
    zero_small_values,
    argmax,
    argmin,
    sign_flip,
    sample_rows,
    sort_cols_per_row,
)
