"""Elementwise matrix math with reference naming.

(ref: cpp/include/raft/matrix/power.cuh, sqrt.cuh, ratio.cuh,
reciprocal.cuh, threshold.cuh, argmax.cuh, argmin.cuh, sign_flip.cuh,
sample_rows.cuh, col_wise_sort.cuh.)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import ensure_resources


def weighted_power(res, matrix, weight=1.0):
    """out = weight * matrix^2. (ref: matrix/power.cuh ``weighted_power``)"""
    m = jnp.asarray(matrix)
    return weight * m * m


power = weighted_power  # (ref: power.cuh ``power`` — scale=1)


def sqrt(res, matrix, weight=1.0):
    """(ref: matrix/sqrt.cuh ``weighted_sqrt``)"""
    return weight * jnp.sqrt(jnp.asarray(matrix))


def ratio(res, matrix):
    """Divide by the sum of all elements. (ref: matrix/ratio.cuh)"""
    m = jnp.asarray(matrix)
    return m / jnp.sum(m)


def reciprocal(res, matrix, scalar=1.0, set_zero: bool = True, thres=1e-15):
    """out = scalar / matrix, zeroing entries below ``thres`` magnitude.
    (ref: matrix/reciprocal.cuh)"""
    m = jnp.asarray(matrix)
    small = jnp.abs(m) < thres
    safe = jnp.where(small, jnp.ones_like(m), m)
    out = scalar / safe
    return jnp.where(small, jnp.zeros_like(out), out) if set_zero else out


def zero_small_values(res, matrix, thres=1e-15):
    """(ref: matrix/threshold.cuh ``zero_small_values``)"""
    m = jnp.asarray(matrix)
    return jnp.where(jnp.abs(m) < thres, jnp.zeros_like(m), m)


def argmax(res, matrix):
    """Per-row argmax. (ref: matrix/argmax.cuh)"""
    return jnp.argmax(jnp.asarray(matrix), axis=1).astype(jnp.int32)


def argmin(res, matrix):
    """(ref: matrix/argmin.cuh)"""
    return jnp.argmin(jnp.asarray(matrix), axis=1).astype(jnp.int32)


def sign_flip(res, matrix):
    """Flip the sign of each *column* so its max-|.| element is positive —
    used to stabilize eigenvector output. (ref: matrix/sign_flip.cuh, used
    by pca as in linalg/detail/pca.cuh)"""
    m = jnp.asarray(matrix)
    pivot = jnp.take_along_axis(m, jnp.argmax(jnp.abs(m), axis=0)[None, :], axis=0)
    return m * jnp.sign(pivot)


def sample_rows(res, matrix, n_samples: int, key=None):
    """Random row subset without replacement.
    (ref: matrix/sample_rows.cuh — rng + gather)"""
    res = ensure_resources(res)
    matrix = jnp.asarray(matrix)
    if key is None:
        key = res.rng.next_key()
    idx = jax.random.choice(key, matrix.shape[0], shape=(n_samples,), replace=False)
    return matrix[idx, :]


def sort_cols_per_row(res, keys, values: Optional[jnp.ndarray] = None,
                      ascending: bool = True):
    """Sort each row's columns by key; optionally permute ``values`` along.
    (ref: matrix/col_wise_sort.cuh ``sort_cols_per_row`` — cub segmented
    sort; XLA's lax.sort is the TPU equivalent.) Returns sorted keys, or
    (sorted_keys, permuted_values)."""
    keys = jnp.asarray(keys)
    # stable both ways: descending sorts negated keys rather than reversing
    # (reversal would invert the relative order of equal keys)
    order = (jnp.argsort(keys, axis=1, stable=True) if ascending
             else jnp.argsort(-keys, axis=1, stable=True))
    sorted_keys = jnp.take_along_axis(keys, order, axis=1)
    if values is None:
        return sorted_keys
    vals = jnp.take_along_axis(jnp.asarray(values), order, axis=1)
    return sorted_keys, vals
