"""Chunked-merge select_k — the large-k selection algorithm.

(ref: the role of matrix/detail/select_radix.cuh:639 at large k — the
reference's radix select exists precisely because warp-queue methods
stop scaling past a few hundred k; its multi-pass digit filtering
bounds the working set. The TPU equivalent is a two-stage exact merge:
XLA's TopK cost grows superlinearly with row LENGTH at fixed k, so
splitting each row into ``nc`` chunks, taking top-k per chunk (any
chunk can contribute at most k of the global top-k, so per-chunk top-k
loses nothing), and merging the ``nc·k`` survivors with one narrow
TopK is strictly exact and turns one expensive wide selection into
cheap narrow ones.)
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point


@partial(jax.jit, static_argnames=("k", "nc"))
def _chunked_select_min(vals, k: int, nc: int):
    """Exact k smallest per row with positions, via per-chunk top-k +
    merge. ``vals`` [B, L] f32; returns (values asc, positions)."""
    B, L = vals.shape
    Lc = -(-L // nc)
    pad = nc * Lc - L
    if pad:
        vals = jnp.pad(vals, ((0, 0), (0, pad)), constant_values=jnp.inf)
    kc = min(k, Lc)
    v3 = vals.reshape(B * nc, Lc)
    neg, pos = jax.lax.top_k(-v3, kc)                   # [B·nc, kc]
    base = (jnp.arange(nc, dtype=jnp.int32) * Lc)[None, :, None]
    gpos = pos.reshape(B, nc, kc).astype(jnp.int32) + base
    cand_v = (-neg).reshape(B, nc * kc)
    cand_p = gpos.reshape(B, nc * kc)
    negk, sel = jax.lax.top_k(-cand_v, k)
    out_v = -negk
    out_p = jnp.take_along_axis(cand_p, sel, axis=1)
    return out_v, out_p


def chunked_envelope(length: int, nc: int = 8) -> bool:
    """Shape envelope of :func:`select_k_chunked` — the SINGLE source
    AUTO's eligibility check derives from (never re-hardcode)."""
    return length >= 2 * nc


@instrument("matrix.select_k_chunked")
def select_k_chunked(in_val, in_idx, k: int, select_min: bool,
                     nc: int = 8) -> Tuple[jax.Array, jax.Array]:
    """Exact chunked-merge select_k (see module doc). Selection keys
    are compared in f32 — exact for f32/bf16/f16 keys; wider/int keys
    raise (the f32 cast could collide distinct values — see
    select_k_types.f32_comparable_keys), so callers fall back to XLA's
    native-dtype top-k. Values are gathered from the input, keeping
    its dtype. ``nc`` = chunk count (k > len/nc degrades to plain XLA
    cost, never to wrong results — per-chunk k caps at the chunk
    length)."""
    from raft_tpu.matrix.select_k_types import f32_comparable_keys

    fault_point("select_k_chunked")
    in_val = jnp.asarray(in_val)
    if not f32_comparable_keys(in_val.dtype):
        raise NotImplementedError(
            f"chunked select_k: f32/bf16/f16 keys only, got "
            f"{in_val.dtype}")
    B, L = in_val.shape
    if not chunked_envelope(L, nc):
        raise NotImplementedError(
            f"chunked select_k: len={L} too short for nc={nc}")
    work = in_val.astype(jnp.float32)
    if not select_min:
        work = -work
    _, out_pos = _chunked_select_min(work, k, nc)
    safe = jnp.clip(out_pos, 0, L - 1)
    out_v = jnp.take_along_axis(in_val, safe, axis=1)
    if in_idx is not None:
        out_idx = jnp.take_along_axis(jnp.asarray(in_idx), safe, axis=1)
    else:
        out_idx = out_pos
    return out_v, out_idx
