"""raft_tpu.linalg — dense linear algebra. (ref: cpp/include/raft/linalg,
SURVEY §2.3.)"""

from raft_tpu.linalg.types import Apply, NormType
from raft_tpu.linalg.map import (
    map,
    map_offset,
    unary_op,
    write_only_unary_op,
    binary_op,
    ternary_op,
)
from raft_tpu.linalg.eltwise import (
    add, subtract, multiply, divide, power, sqrt,
    add_scalar, subtract_scalar, multiply_scalar, divide_scalar, power_scalar,
    scalar_add, scalar_multiply,
    eltwise_add, eltwise_sub, eltwise_multiply, eltwise_divide,
    eltwise_divide_check_zero,
)
from raft_tpu.linalg.reduce import (
    reduce,
    coalesced_reduction,
    strided_reduction,
    map_then_reduce,
    map_reduce,
    mean_squared_error,
)
from raft_tpu.linalg.norm import norm, row_norm, col_norm, normalize, row_normalize
from raft_tpu.linalg.matrix_vector import (
    matrix_vector_op,
    matrix_vector_op2,
    binary_mult,
    binary_mult_skip_zero,
    binary_div,
    binary_div_skip_zero,
    binary_add,
    binary_sub,
)
from raft_tpu.linalg.reduce_by_key import reduce_rows_by_key, reduce_cols_by_key
from raft_tpu.linalg.blas import gemm, gemv, axpy, dot
from raft_tpu.linalg.transpose import transpose, transpose_inplace
from raft_tpu.linalg.init import range_fill
