"""raft_tpu.linalg — dense linear algebra. (ref: cpp/include/raft/linalg,
SURVEY §2.3.)"""

from raft_tpu.linalg.types import Apply, NormType
from raft_tpu.linalg.map import (
    map,
    map_offset,
    unary_op,
    write_only_unary_op,
    binary_op,
    ternary_op,
)
from raft_tpu.linalg.eltwise import (
    add, subtract, multiply, divide, power, sqrt,
    add_scalar, subtract_scalar, multiply_scalar, divide_scalar, power_scalar,
    scalar_add, scalar_multiply,
    eltwise_add, eltwise_sub, eltwise_multiply, eltwise_divide,
    eltwise_divide_check_zero,
)
from raft_tpu.linalg.reduce import (
    reduce,
    coalesced_reduction,
    strided_reduction,
    map_then_reduce,
    map_reduce,
    mean_squared_error,
)
from raft_tpu.linalg.norm import norm, row_norm, col_norm, normalize, row_normalize
from raft_tpu.linalg.matrix_vector import (
    matrix_vector_op,
    matrix_vector_op2,
    binary_mult,
    binary_mult_skip_zero,
    binary_div,
    binary_div_skip_zero,
    binary_add,
    binary_sub,
)
from raft_tpu.linalg.reduce_by_key import reduce_rows_by_key, reduce_cols_by_key
from raft_tpu.linalg.blas import gemm, gemv, axpy, dot
from raft_tpu.linalg.transpose import transpose, transpose_inplace
from raft_tpu.linalg.init import range_fill
from raft_tpu.linalg.qr import qr_get_q, qr_get_qr
from raft_tpu.linalg.eig import eig_dc, eig_dc_selective, eig_jacobi
from raft_tpu.linalg.svd import (
    svd_qr,
    svd_qr_transpose_right_vec,
    svd_eig,
    svd_jacobi,
    svd_reconstruction,
    evaluate_svd_by_percentage,
)
from raft_tpu.linalg.rsvd import (
    randomized_svd,
    rsvd_fixed_rank,
    rsvd_fixed_rank_symmetric,
    rsvd_perc,
)
from raft_tpu.linalg.lstsq import (
    lstsq_svd_qr,
    lstsq_svd_jacobi,
    lstsq_eig,
    lstsq_qr,
)
from raft_tpu.linalg.cholesky import cholesky_r1_update
from raft_tpu.linalg.pca import (
    ParamsPCA,
    PCAModel,
    Solver,
    pca_fit,
    pca_fit_distributed,
    pca_transform,
    pca_inverse_transform,
)
from raft_tpu.linalg.tsvd import (
    ParamsTSVD,
    TSVDModel,
    tsvd_fit,
    tsvd_fit_distributed,
    tsvd_transform,
    tsvd_inverse_transform,
)
from raft_tpu.linalg.contractions import KernelPolicy, tiled_contraction
