"""Randomized SVD.

(ref: cpp/include/raft/linalg/rsvd.cuh:158 — the ``rsvd_fixed_rank`` /
``rsvd_fixed_rank_symmetric`` / ``rsvd_perc…`` variant family, and
``randomized_svd`` (detail/rsvd.cuh:33). Core recipe at
detail/rsvd.cuh:141-219: RngState gaussian sketch → QR orthonormalization
(optionally through the B Bᵀ / Bᵀ B small-matrix path with QR or eig) →
small SVD → project back.)

TPU-first: the sketch/QR/power-iteration pipeline is pure MXU work; the
small SVD runs on the k+p sized core matrix. Power iterations use QR
re-orthonormalization each step for stability (the reference's
subspace-iteration loop).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.resources import ensure_resources


def randomized_svd(
    res,
    A,
    k: int,
    p: int = 10,
    n_iters: int = 2,
    key=None,
    gen_U: bool = True,
    gen_V: bool = True,
):
    """Rank-k truncated SVD of A [m×n]. Returns (U [m×k], S [k], V [n×k]).
    (ref: detail/rsvd.cuh:33 ``randomized_svd``)"""
    res = ensure_resources(res)
    A = jnp.asarray(A)
    m, n = A.shape
    expects(0 < k <= min(m, n), "randomized_svd: bad rank k=%d", k)
    ell = min(k + p, n)
    if key is None:
        key = res.rng.next_key()
    omega = jax.random.normal(key, (n, ell), A.dtype)
    Y = A @ omega                                  # m × ell sketch
    Q, _ = jnp.linalg.qr(Y)
    for _ in range(n_iters):                       # subspace/power iterations
        Z, _ = jnp.linalg.qr(A.T @ Q)
        Q, _ = jnp.linalg.qr(A @ Z)
    B = Q.T @ A                                    # ell × n core
    Ub, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    U = (Q @ Ub)[:, :k] if gen_U else None
    V = Vt.T[:, :k] if gen_V else None
    return U, S[:k], V


def rsvd_fixed_rank(res, A, k: int, p: int = 10, n_iters: int = 2,
                    use_bbt: Optional[bool] = None, key=None):
    """(ref: rsvd.cuh ``rsvd_fixed_rank`` — fixed rank + oversampling.)"""
    return randomized_svd(res, A, k, p, n_iters, key)


def rsvd_fixed_rank_symmetric(res, A, k: int, p: int = 10, n_iters: int = 2,
                              key=None):
    """Symmetric-input variant: eigenpairs via the same sketch.
    (ref: rsvd.cuh ``rsvd_fixed_rank_symmetric``)"""
    U, S, V = randomized_svd(res, A, k, p, n_iters, key)
    # for symmetric A, U ≈ ±V; return (vals, vecs) in SVD convention
    return U, S, V


def rsvd_perc(res, A, sv_perc: float, p_perc: float = 0.05, n_iters: int = 2,
              key=None):
    """Rank and oversampling given as fractions of min(m,n).
    (ref: rsvd.cuh ``rsvd_perc`` family)"""
    A = jnp.asarray(A)
    mn = min(A.shape)
    k = max(1, int(round(sv_perc * mn)))
    p = max(1, int(round(p_perc * mn)))
    return randomized_svd(res, A, k, p, n_iters, key)
