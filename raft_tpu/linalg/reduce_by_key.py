"""Keyed reductions over rows/columns.

(ref: cpp/include/raft/linalg/reduce_rows_by_key.cuh,
reduce_cols_by_key.cuh — sum rows (or columns) of a matrix into output
slots selected by a per-row (per-column) key vector. TPU-first: this is a
one-hot matmul (MXU-friendly) for medium key counts and a segment-sum for
large ones; we use ``jax.ops.segment_sum`` which XLA lowers to an efficient
scatter-add.)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def reduce_rows_by_key(res, matrix, keys, n_unique_keys: int,
                       weights=None):
    """out[k, :] = sum over rows r with keys[r]==k of w[r]*matrix[r, :].
    (ref: reduce_rows_by_key.cuh)"""
    matrix = jnp.asarray(matrix)
    keys = jnp.asarray(keys)
    if weights is not None:
        matrix = matrix * jnp.asarray(weights)[:, None]
    return jax.ops.segment_sum(matrix, keys, num_segments=n_unique_keys)


def reduce_cols_by_key(res, matrix, keys, n_unique_keys: int):
    """out[:, k] = sum over columns c with keys[c]==k of matrix[:, c].
    (ref: reduce_cols_by_key.cuh)"""
    matrix = jnp.asarray(matrix)
    keys = jnp.asarray(keys)
    return jax.ops.segment_sum(matrix.T, keys, num_segments=n_unique_keys).T
