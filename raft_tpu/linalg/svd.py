"""SVD family.

(ref: cpp/include/raft/linalg/svd.cuh:195,332 — ``svd_qr`` (cusolver
gesvd), ``svd_eig`` (via eigendecomposition of the Gram matrix),
``svd_jacobi`` (gesvdj), ``svd_qr_transpose_right_vec``, plus
``svd_reconstruction`` / ``evaluate_svd_by_percentage`` validation helpers
and sign flip.)
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.linalg.eig import eig_jacobi


def svd_qr(res, A, gen_left_vec: bool = True, gen_right_vec: bool = True):
    """Full thin SVD; returns (U, S, V) with V as columns (not Vᵀ),
    matching the reference's output convention. (ref: svd.cuh:195)"""
    A = jnp.asarray(A)
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    return (u if gen_left_vec else None), s, (vt.T if gen_right_vec else None)


def svd_qr_transpose_right_vec(res, A):
    """(U, S, Vᵀ) variant. (ref: svd.cuh ``svd_qr_transpose_right_vec``)"""
    A = jnp.asarray(A)
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    return u, s, vt


def svd_eig(res, A, gen_left_vec: bool = True):
    """SVD via eigendecomposition of AᵀA — fast when n_rows >> n_cols.
    (ref: svd.cuh:332 ``svd_eig``; detail uses cov + eigDC.) Returns
    (U, S, V) with singular values DESCENDING like svd_qr."""
    A = jnp.asarray(A)
    n, p = A.shape
    expects(n >= p, "svd_eig: requires n_rows >= n_cols")
    G = (A.T @ A).astype(A.dtype)
    w, v = jnp.linalg.eigh(G)  # ascending
    w = w[::-1]
    v = v[:, ::-1]
    s = jnp.sqrt(jnp.maximum(w, 0.0))
    U = None
    if gen_left_vec:
        safe = jnp.where(s > 0, s, jnp.ones_like(s))
        U = (A @ v) / safe[None, :]
        U = jnp.where(s[None, :] > 0, U, jnp.zeros_like(U))
    return U, s, v


def svd_jacobi(res, A, tol: float = 1e-7, sweeps: int = 15,
               gen_left_vec: bool = True):
    """SVD via Jacobi eigensolver on the Gram matrix.
    (ref: svd.cuh ``svdJacobi`` → gesvdj)"""
    A = jnp.asarray(A)
    G = A.T @ A
    w, v = eig_jacobi(res, G, tol=tol, sweeps=sweeps)
    w = w[::-1]
    v = v[:, ::-1]
    s = jnp.sqrt(jnp.maximum(w, 0.0))
    U = None
    if gen_left_vec:
        safe = jnp.where(s > 0, s, jnp.ones_like(s))
        U = (A @ v) / safe[None, :]
    return U, s, v


def svd_reconstruction(res, U, S, V):
    """U diag(S) Vᵀ. (ref: svd.cuh ``svd_reconstruction``)"""
    return (jnp.asarray(U) * jnp.asarray(S)[None, :]) @ jnp.asarray(V).T


def evaluate_svd_by_percentage(res, A, U, S, V, percent: float = 1e-2) -> bool:
    """Is the reconstruction within percent·‖A‖_F?
    (ref: svd.cuh ``evaluate_svd_by_percentage``)"""
    A = jnp.asarray(A)
    err = jnp.linalg.norm(A - svd_reconstruction(res, U, S, V))
    return bool(err <= percent * jnp.linalg.norm(A))
