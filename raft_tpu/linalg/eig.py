"""Symmetric eigendecomposition.

(ref: cpp/include/raft/linalg/eig.cuh:121,152,190 — ``eig_dc`` (cusolver
[x]syevd divide&conquer, with the 64-bit API workaround at
detail/eig.cuh:83-102), ``eig_dc_selective`` (syevdx subset), and
``eig_jacobi`` (syevj with tolerance/sweep controls).)

TPU mapping: ``eig_dc`` → XLA's ``eigh`` (the tridiagonal-DC class solver).
``eig_jacobi`` is implemented as a REAL round-robin parallel two-sided
Jacobi — the classic systolic-array formulation: each round applies
⌊n/2⌋ disjoint rotations at once as one orthogonal similarity (pure matmul
work for the MXU), with a tournament schedule covering all pairs per sweep.
Eigenvalues ascend, matching the reference/cusolver order.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects


def eig_dc(res, A) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (eig_vals ascending, eig_vectors as columns).
    (ref: eig.cuh:121 ``eig_dc``)"""
    A = jnp.asarray(A)
    expects(A.ndim == 2 and A.shape[0] == A.shape[1], "eig_dc: square input required")
    w, v = jnp.linalg.eigh(A)
    return w, v


def eig_dc_selective(res, A, n_eig_vals: int, which: str = "largest"):
    """Subset of the spectrum. (ref: eig.cuh:152 ``eig_dc_selective``;
    cusolver syevdx range selection.) which ∈ {"largest", "smallest"}."""
    w, v = eig_dc(res, A)
    if which == "largest":
        return w[-n_eig_vals:], v[:, -n_eig_vals:]
    return w[:n_eig_vals], v[:, :n_eig_vals]


def _round_robin_schedule(n: int) -> np.ndarray:
    """Tournament pairings: (n-1) rounds × (n/2) disjoint pairs covering all
    index pairs once per sweep (host-side, static)."""
    m = n + (n % 2)  # pad to even with a bye slot
    players = list(range(m))
    rounds = []
    for _ in range(m - 1):
        pairs = [(players[i], players[m - 1 - i]) for i in range(m // 2)]
        rounds.append([(min(p, q), max(p, q)) for p, q in pairs if max(p, q) < n])
        players = [players[0]] + [players[-1]] + players[1:-1]
    return rounds


@partial(jax.jit, static_argnums=(1, 2))
def _jacobi(A, n_sweeps: int, schedule_tuple):
    n = A.shape[0]
    V = jnp.eye(n, dtype=A.dtype)
    schedule = [jnp.asarray(r, jnp.int32) for r in schedule_tuple]

    def apply_round(carry, pairs):
        A, V = carry
        p, q = pairs[:, 0], pairs[:, 1]
        app = A[p, p]
        aqq = A[q, q]
        apq = A[p, q]
        # rotation angle zeroing A[p,q]: theta = 0.5*atan2(2apq, aqq-app)
        theta = 0.5 * jnp.arctan2(2.0 * apq, aqq - app)
        c = jnp.cos(theta)[:, None]
        s = jnp.sin(theta)[:, None]
        # J has J[p,p]=J[q,q]=c, J[p,q]=s, J[q,p]=-s (disjoint pairs).
        # Apply JᵀAJ as paired row then column updates — O(n²) per round
        # instead of two dense n×n matmuls (O(n³)).
        Ap, Aq = A[p, :], A[q, :]
        A = A.at[p, :].set(c * Ap - s * Aq).at[q, :].set(s * Ap + c * Aq)
        Acp, Acq = A[:, p], A[:, q]
        A = A.at[:, p].set(c.T * Acp - s.T * Acq).at[:, q].set(s.T * Acp + c.T * Acq)
        Vp, Vq = V[:, p], V[:, q]
        V = V.at[:, p].set(c.T * Vp - s.T * Vq).at[:, q].set(s.T * Vp + c.T * Vq)
        return (A, V), None

    def sweep(carry, _):
        for r in schedule:
            carry, _ = apply_round(carry, r)
        return carry, None

    (A, V), _ = jax.lax.scan(sweep, (A, V), None, length=n_sweeps)
    return A, V


def eig_jacobi(res, A, tol: float = 1e-7, sweeps: int = 15):
    """Parallel two-sided Jacobi. Returns (eig_vals ascending, vectors).
    (ref: eig.cuh:190 ``eig_jacobi``; tol/sweeps mirror syevj params —
    sweeps is a static bound here, the TPU-friendly formulation.)"""
    A = jnp.asarray(A)
    expects(A.ndim == 2 and A.shape[0] == A.shape[1], "eig_jacobi: square input")
    n = A.shape[0]
    if n == 1:
        return A[0], jnp.ones((1, 1), A.dtype)
    schedule = tuple(tuple(map(tuple, r)) for r in _round_robin_schedule(n))
    D, V = _jacobi(A, sweeps, schedule)
    w = jnp.diagonal(D)
    order = jnp.argsort(w)
    return w[order], V[:, order]
