"""QR decomposition.

(ref: cpp/include/raft/linalg/qr.cuh — ``qrGetQ`` / ``qrGetQR`` over
cuSOLVER geqrf/orgqr. TPU path: XLA's QR (Householder, MXU-blocked) via
``jnp.linalg.qr``.)
"""

from __future__ import annotations

import jax.numpy as jnp


def qr_get_q(res, A):
    """Q factor only (reduced). (ref: qr.cuh ``qrGetQ``)"""
    q, _ = jnp.linalg.qr(jnp.asarray(A), mode="reduced")
    return q


def qr_get_qr(res, A):
    """(Q, R) reduced factorization. (ref: qr.cuh ``qrGetQR``)"""
    return jnp.linalg.qr(jnp.asarray(A), mode="reduced")
