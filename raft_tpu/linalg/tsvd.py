"""Truncated SVD fit/transform (moved into raft from cuML in 26.04).

(ref: cpp/include/raft/linalg/tsvd.cuh ``tsvd_fit`` /
``tsvd_transform`` / ``tsvd_inverse_transform``; params
linalg/pca_types.hpp ``paramsTSVD``; impl linalg/detail/tsvd.cuh — like PCA
but without mean-centering: eig of XᵀX.)
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.linalg.eig import eig_dc, eig_jacobi
from raft_tpu.linalg.pca import Solver
from raft_tpu.matrix.math_ops import sign_flip


@dataclasses.dataclass
class ParamsTSVD:
    """(ref: pca_types.hpp ``paramsTSVD``)"""

    n_components: int
    algorithm: Solver = Solver.COV_EIG_DC
    tol: float = 1e-7
    n_iterations: int = 15


class TSVDModel(NamedTuple):
    components: jnp.ndarray       # [n_components, n_features]
    explained_var: jnp.ndarray
    explained_var_ratio: jnp.ndarray
    singular_vals: jnp.ndarray


def _components_from_gram(res, G, prms: ParamsTSVD):
    """Shared eig tail (solver branch → descending → sign flip →
    truncate) — ONE copy for the single-device and distributed fits."""
    if prms.algorithm == Solver.COV_EIG_JACOBI:
        w, v = eig_jacobi(res, G, tol=prms.tol, sweeps=prms.n_iterations)
    else:
        w, v = eig_dc(res, G)
    w = jnp.maximum(w[::-1], 0.0)
    v = v[:, ::-1]
    components = sign_flip(res, v).T[: prms.n_components]
    return w, components


def tsvd_fit(res, X, prms: ParamsTSVD) -> TSVDModel:
    """(ref: tsvd.cuh ``tsvd_fit``)"""
    X = jnp.asarray(X)
    n, p = X.shape
    expects(0 < prms.n_components <= p, "tsvd_fit: bad n_components")
    G = X.T @ X
    w, components = _components_from_gram(res, G, prms)
    singular_vals = jnp.sqrt(w[: prms.n_components])
    # explained variance of the projected coordinates (population variance,
    # as the reference computes from the transform)
    T = X @ components.T
    explained_var = jnp.var(T, axis=0)
    total_var = jnp.sum(jnp.var(X, axis=0))
    explained_var_ratio = explained_var / total_var
    return TSVDModel(components, explained_var, explained_var_ratio,
                     singular_vals)


def tsvd_fit_distributed(res, X, prms: ParamsTSVD, mesh,
                         axis: str = "x") -> TSVDModel:
    """MNMG TSVD fit: rows sharded over ``mesh[axis]``; the gram matrix
    (+ column sums) and a CENTERED second variance pass run as psums
    inside ``shard_map``, the eig tail replicated (the OPG twin of
    linalg.pca.pca_fit_distributed; ref: the raft-dask distributed-fit
    role). The variance pass subtracts the exact means computed from
    pass 1 — the one-pass E[x²]−(E[x])² form cancels catastrophically
    in f32 for large-mean data, where jnp.var's two-pass (the
    single-device fit) does not. Non-divisible row counts are
    zero-padded and masked out of the statistics."""
    import jax
    from jax.sharding import PartitionSpec as P

    from raft_tpu.linalg.pca import pad_mask_shard

    X = jnp.asarray(X)
    n, p = X.shape
    expects(0 < prms.n_components <= p,
            "tsvd_fit_distributed: bad n_components")
    Xs, vs = pad_mask_shard(X, mesh, axis)

    def gram_and_colsum(x, v):
        xm = x * v[:, None]
        G = jax.lax.psum(
            jnp.matmul(xm.T, xm, preferred_element_type=jnp.float32),
            axis)
        return G, jax.lax.psum(jnp.sum(xm, axis=0), axis)

    G, colsum = jax.shard_map(
        gram_and_colsum, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=(P(), P()))(Xs, vs)
    w, components = _components_from_gram(res, G, prms)
    singular_vals = jnp.sqrt(w[: prms.n_components])
    mu_x = colsum / n
    mu_t = components @ mu_x                 # mean of T = X @ compᵀ

    def centered_var(x, vm, comp, mt, mx):
        t = x @ comp.T
        s2c = jax.lax.psum(
            jnp.sum(((t - mt[None, :]) ** 2) * vm[:, None], axis=0),
            axis)
        x2c = jax.lax.psum(
            jnp.sum(((x - mx[None, :]) ** 2) * vm[:, None], axis=0),
            axis)
        return s2c, x2c

    s2c, x2c = jax.shard_map(
        centered_var, mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P()),
        out_specs=(P(), P()))(Xs, vs, components, mu_t, mu_x)
    # population variance, matching jnp.var in the single-device fit
    explained_var = s2c / n
    total_var = jnp.sum(x2c) / n
    explained_var_ratio = explained_var / total_var
    return TSVDModel(components, explained_var, explained_var_ratio,
                     singular_vals)


def tsvd_transform(res, X, model: TSVDModel):
    """(ref: tsvd.cuh ``tsvd_transform``)"""
    return jnp.asarray(X) @ model.components.T


def tsvd_inverse_transform(res, T, model: TSVDModel):
    """(ref: tsvd.cuh ``tsvd_inverse_transform``)"""
    return jnp.asarray(T) @ model.components
