"""Truncated SVD fit/transform (moved into raft from cuML in 26.04).

(ref: cpp/include/raft/linalg/tsvd.cuh ``tsvd_fit`` /
``tsvd_transform`` / ``tsvd_inverse_transform``; params
linalg/pca_types.hpp ``paramsTSVD``; impl linalg/detail/tsvd.cuh — like PCA
but without mean-centering: eig of XᵀX.)
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.linalg.eig import eig_dc, eig_jacobi
from raft_tpu.linalg.pca import Solver
from raft_tpu.matrix.math_ops import sign_flip


@dataclasses.dataclass
class ParamsTSVD:
    """(ref: pca_types.hpp ``paramsTSVD``)"""

    n_components: int
    algorithm: Solver = Solver.COV_EIG_DC
    tol: float = 1e-7
    n_iterations: int = 15


class TSVDModel(NamedTuple):
    components: jnp.ndarray       # [n_components, n_features]
    explained_var: jnp.ndarray
    explained_var_ratio: jnp.ndarray
    singular_vals: jnp.ndarray


def tsvd_fit(res, X, prms: ParamsTSVD) -> TSVDModel:
    """(ref: tsvd.cuh ``tsvd_fit``)"""
    X = jnp.asarray(X)
    n, p = X.shape
    expects(0 < prms.n_components <= p, "tsvd_fit: bad n_components")
    G = X.T @ X
    if prms.algorithm == Solver.COV_EIG_JACOBI:
        w, v = eig_jacobi(res, G, tol=prms.tol, sweeps=prms.n_iterations)
    else:
        w, v = eig_dc(res, G)
    w = jnp.maximum(w[::-1], 0.0)
    v = v[:, ::-1]
    components = sign_flip(res, v).T[: prms.n_components]
    singular_vals = jnp.sqrt(w[: prms.n_components])
    # explained variance of the projected coordinates (population variance,
    # as the reference computes from the transform)
    T = X @ components.T
    explained_var = jnp.var(T, axis=0)
    total_var = jnp.sum(jnp.var(X, axis=0))
    explained_var_ratio = explained_var / total_var
    return TSVDModel(components, explained_var, explained_var_ratio,
                     singular_vals)


def tsvd_transform(res, X, model: TSVDModel):
    """(ref: tsvd.cuh ``tsvd_transform``)"""
    return jnp.asarray(X) @ model.components.T


def tsvd_inverse_transform(res, T, model: TSVDModel):
    """(ref: tsvd.cuh ``tsvd_inverse_transform``)"""
    return jnp.asarray(T) @ model.components
