"""The tiled contraction substrate.

(ref: cpp/include/raft/linalg/contractions.cuh + detail/contractions.cuh
(313 LoC) — the ``KernelPolicy`` smem-tiling base that the pre-cuVS
pairwise-distance kernels were built on; SURVEY §7 stage 10 names it the
substrate to rebuild.)

TPU-first rendering: the "policy" is the workspace-budgeted tile plan, and
the inner loop is an MXU contraction with a user epilogue — the same shape
as the reference's ``ldgXY/stsXY`` accumulate loop, but the compiler owns
the VMEM staging. ``tiled_contraction`` is what pairwise_distance and the
fused sweeps specialize.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import ensure_resources


class KernelPolicy:
    """Tile plan (ref: contractions.cuh ``KernelPolicy`` — smem tile
    extents become VMEM-friendly row/column tile sizes)."""

    def __init__(self, m_tile: int = 1024, n_tile: int = 8192):
        self.m_tile = int(m_tile)
        self.n_tile = int(n_tile)

    @classmethod
    def from_workspace(cls, res, n_cols: int, bytes_per_elem: int = 4
                       ) -> "KernelPolicy":
        res = ensure_resources(res)
        budget = res.workspace.allocation_limit
        n_tile = max(128, min(8192, budget // (2 * bytes_per_elem * max(n_cols, 1))))
        return cls(m_tile=1024, n_tile=n_tile)


def tiled_contraction(res, x, y, epilogue: Callable,
                      policy: Optional[KernelPolicy] = None,
                      accumulate: Optional[Callable] = None, init=None):
    """Compute ``epilogue(x_tile·yᵀ_tile, x_tile, y_tile)`` over row tiles
    of x and fold results with ``accumulate`` (or concatenate when None).

    epilogue(ip [mt, nt], x_tile [mt, d], y_tile [nt, d]) -> per-tile out.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if policy is None:
        policy = KernelPolicy.from_workspace(res, x.shape[1])
    outs = []
    acc = init
    for m0 in range(0, x.shape[0], policy.m_tile):
        xt = x[m0:m0 + policy.m_tile]
        row_outs = []
        for n0 in range(0, y.shape[0], policy.n_tile):
            yt = y[n0:n0 + policy.n_tile]
            ip = jnp.matmul(xt, yt.T, preferred_element_type=jnp.float32)
            out = epilogue(ip, xt, yt)
            if accumulate is None:
                row_outs.append(out)
            else:
                acc = accumulate(acc, out, m0, n0)
        if accumulate is None:
            outs.append(jnp.concatenate(row_outs, axis=1))
    if accumulate is None:
        return jnp.concatenate(outs, axis=0)
    return acc
