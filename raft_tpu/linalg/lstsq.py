"""Least squares solvers.

(ref: cpp/include/raft/linalg/lstsq.cuh — ``lstsq_svd_qr``
(detail/lstsq.cuh:111 ``lstsqSvdQR`` via gesvd), ``lstsq_svd_jacobi``
(:171 via gesvdj), ``lstsq_eig`` (normal equations + eigendecomposition),
``lstsq_qr`` (QR + triangular solve).)

All solve min_w ‖A w − b‖₂ for A [m×n], m ≥ n, returning w [n].
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

from raft_tpu.core.error import expects
from raft_tpu.linalg.eig import eig_jacobi
from raft_tpu.linalg.svd import svd_jacobi


def _pinv_solve(u, s, v, b, rcond=1e-7):
    cutoff = rcond * jnp.max(s)
    inv_s = jnp.where(s > cutoff, 1.0 / jnp.where(s > cutoff, s, 1.0), 0.0)
    return v @ (inv_s * (u.T @ b))


def lstsq_svd_qr(res, A, b):
    """(ref: lstsq.cuh ``lstsq_svd_qr``)"""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    return _pinv_solve(u, s, vt.T, b)


def lstsq_svd_jacobi(res, A, b, tol: float = 1e-7, sweeps: int = 15):
    """(ref: lstsq.cuh ``lstsq_svd_jacobi``)"""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    U, S, V = svd_jacobi(res, A, tol=tol, sweeps=sweeps)
    return _pinv_solve(U, S, V, b)


def lstsq_eig(res, A, b):
    """Normal equations via eigendecomposition: w = (AᵀA)⁻¹ Aᵀ b.
    (ref: lstsq.cuh ``lstsq_eig`` — covariance + eig path)"""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    G = A.T @ A
    w_eig, v = jnp.linalg.eigh(G)
    rhs = A.T @ b
    cutoff = 1e-7 * jnp.max(jnp.abs(w_eig))
    inv_w = jnp.where(jnp.abs(w_eig) > cutoff, 1.0 / jnp.where(jnp.abs(w_eig) > cutoff, w_eig, 1.0), 0.0)
    return v @ (inv_w * (v.T @ rhs))


def lstsq_qr(res, A, b):
    """QR + back-substitution. (ref: lstsq.cuh ``lstsq_qr``)"""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    expects(A.shape[0] >= A.shape[1], "lstsq_qr: need m >= n")
    q, r = jnp.linalg.qr(A, mode="reduced")
    return solve_triangular(r, q.T @ b, lower=False)
