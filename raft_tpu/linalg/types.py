"""Linalg shared types. (ref: cpp/include/raft/linalg/linalg_types.hpp)"""

from __future__ import annotations

import enum


class Apply(enum.Enum):
    """Which direction a rowwise/colwise op applies.
    (ref: linalg_types.hpp ``Apply::ALONG_ROWS / ALONG_COLUMNS``)

    Reference convention, kept exactly: for reductions, ALONG_ROWS outputs
    one value per ROW (each row is reduced across its columns) and
    ALONG_COLUMNS outputs one value per COLUMN. For broadcasts
    (matrix_vector_op / linewise_op), ALONG_ROWS means the vector spans a
    row (length == n_cols).
    """

    ALONG_ROWS = 0
    ALONG_COLUMNS = 1


class NormType(enum.Enum):
    """(ref: linalg/norm_types.hpp L1Norm/L2Norm/LinfNorm)"""

    L1 = 1
    L2 = 2
    LINF = 3
