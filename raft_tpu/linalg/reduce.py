"""Reductions: reduce / coalesced / strided / map-reduce / MSE.

(ref: cpp/include/raft/linalg/reduce.cuh, coalesced_reduction.cuh,
strided_reduction.cuh, map_then_reduce.cuh, mean_squared_error.cuh.
The reference picks between coalesced (thin/medium/thick policies,
linalg/detail/coalesced_reduction-inl.cuh:22-141 incl. a Kahan-sum variant)
and strided kernels based on layout × direction; XLA owns that scheduling on
TPU, so both spellings lower to an axis reduction. The semantic surface kept:
``main_op`` applied per element (with the index along the reduction axis —
column index for ALONG_ROWS, row index for ALONG_COLUMNS, as in the
reference's coalesced/strided kernel pair), reduction via ``op`` from
``init``, ``final_op`` on the result, optional ``inplace`` accumulate, and
the reference's row-major × along-rows/columns convention.)

Accumulation note (replacing the Kahan variant): reductions accumulate in
f32 at minimum — pass ``accumulate_dtype`` to widen (e.g. bf16 data summed
in f32), which is the TPU-idiomatic fix for the same numerical concern.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core import operators as ops
from raft_tpu.linalg.types import Apply


def _axis_for(apply: Apply, ndim: int) -> int:
    # Reference convention (linalg/reduce.cuh): ALONG_ROWS outputs one value
    # per row → reduce across the column axis (1); ALONG_COLUMNS outputs one
    # value per column → reduce down the row axis (0). 1-D inputs reduce
    # their only axis.
    if ndim == 1:
        return 0
    return 1 if apply == Apply.ALONG_ROWS else 0


_REDUCERS = {
    ops.add_op: jnp.sum,
    ops.min_op: jnp.min,
    ops.max_op: jnp.max,
    ops.mul_op: jnp.prod,
}


def reduce(
    res,
    data,
    apply: Apply = Apply.ALONG_ROWS,
    init=0,
    main_op: Callable = ops.identity_op,
    reduce_op: Callable = ops.add_op,
    final_op: Callable = ops.identity_op,
    inplace_target=None,
    accumulate_dtype=None,
):
    """General matrix reduction. (ref: linalg/reduce.cuh ``reduce``)

    ``main_op(value, reduction_axis_index)`` per element — the column index
    for ALONG_ROWS, the row index for ALONG_COLUMNS (matching
    detail/coalesced_reduction-inl.cuh / strided_reduction.cuh:41);
    associative ``reduce_op``
    folds with ``init``; if ``inplace_target`` is given it is folded in
    BEFORE ``final_op`` — matching the reference's
    ``final_op(reduce_op(dots, acc))`` ordering
    (detail/coalesced_reduction-inl.cuh).
    """
    data = jnp.asarray(data)
    axis = _axis_for(apply, data.ndim)
    # main_op receives the index ALONG THE REDUCTION AXIS, matching the
    # reference: coalesced kernels pass the column index (ALONG_ROWS,
    # detail/coalesced_reduction-inl.cuh), strided kernels pass the row
    # index (ALONG_COLUMNS, detail/strided_reduction.cuh:41).
    if data.ndim == 1:
        red_idx = jnp.arange(data.shape[0])
    elif axis == 1:
        red_idx = jnp.arange(data.shape[1])[None, :]
    else:
        red_idx = jnp.arange(data.shape[0])[:, None]
    mapped = main_op(data, jnp.broadcast_to(red_idx, data.shape))
    acc_dtype = accumulate_dtype
    if acc_dtype is None and mapped.dtype in (jnp.bfloat16, jnp.float16):
        acc_dtype = jnp.float32
    if acc_dtype is not None:
        mapped = mapped.astype(acc_dtype)

    reducer = _REDUCERS.get(reduce_op)
    if reducer is not None:
        folded = reducer(mapped, axis=axis)
        folded = reduce_op(folded, jnp.asarray(init, folded.dtype))
    else:
        # generic associative fold over the reduction axis
        moved = jnp.moveaxis(mapped, axis, 0)
        import jax

        folded = jax.lax.reduce(
            moved, jnp.asarray(init, moved.dtype), reduce_op, (0,)
        )
    if inplace_target is not None:
        folded = reduce_op(folded, jnp.asarray(inplace_target))
    return final_op(folded)


def coalesced_reduction(res, data, init=0, main_op=ops.identity_op,
                        reduce_op=ops.add_op, final_op=ops.identity_op,
                        inplace_target=None):
    """Reduce along the contiguous (last) dimension — one output per row.
    (ref: linalg/coalesced_reduction.cuh)"""
    return reduce(res, data, Apply.ALONG_ROWS, init, main_op, reduce_op,
                  final_op, inplace_target)


def strided_reduction(res, data, init=0, main_op=ops.identity_op,
                      reduce_op=ops.add_op, final_op=ops.identity_op,
                      inplace_target=None):
    """Reduce along the strided (first) dimension — one output per column.
    (ref: linalg/strided_reduction.cuh)"""
    return reduce(res, data, Apply.ALONG_COLUMNS, init, main_op, reduce_op,
                  final_op, inplace_target)


def map_then_reduce(res, *arrays, map_op: Callable = ops.identity_op,
                    reduce_op: Callable = ops.add_op, init=0,
                    final_op: Callable = ops.identity_op):
    """Full map-then-reduce to a scalar. (ref: linalg/map_then_reduce.cuh,
    map_reduce.cuh)"""
    mapped = map_op(*[jnp.asarray(a) for a in arrays])
    reducer = _REDUCERS.get(reduce_op)
    if reducer is not None:
        folded = reduce_op(reducer(mapped), jnp.asarray(init, mapped.dtype))
    else:
        import jax

        folded = jax.lax.reduce(
            mapped.reshape(-1), jnp.asarray(init, mapped.dtype), reduce_op, (0,)
        )
    return final_op(folded)


map_reduce = map_then_reduce


def mean_squared_error(res, a, b, weight: float = 1.0):
    """weight * mean((a-b)^2). (ref: linalg/mean_squared_error.cuh)"""
    a, b = jnp.asarray(a), jnp.asarray(b)
    return jnp.mean(ops.sqdiff_op(a, b)) * weight
