"""Row/column norms and normalization.

(ref: cpp/include/raft/linalg/norm.cuh — rowNorm/colNorm with
L1/L2/Linf × optional final sqrt; linalg/normalize.cuh — row normalization
with norm-type dispatch.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from raft_tpu.core import operators as ops
from raft_tpu.linalg.types import Apply, NormType


def _norm(data, norm_type: NormType, axis: int, final_sqrt: bool):
    if norm_type == NormType.L1:
        out = jnp.sum(jnp.abs(data), axis=axis)
    elif norm_type == NormType.L2:
        out = jnp.sum(data * data, axis=axis)
        if final_sqrt:
            out = jnp.sqrt(out)
    else:
        out = jnp.max(jnp.abs(data), axis=axis)
    return out


def row_norm(res, data, norm_type: NormType = NormType.L2,
             final_sqrt: bool = False, final_op: Callable = ops.identity_op):
    """One norm per row. (ref: norm.cuh ``rowNorm``; L2 returns the
    *squared* norm unless final_sqrt, matching the reference.)"""
    return final_op(_norm(jnp.asarray(data), norm_type, 1, final_sqrt))


def col_norm(res, data, norm_type: NormType = NormType.L2,
             final_sqrt: bool = False, final_op: Callable = ops.identity_op):
    """One norm per column. (ref: norm.cuh ``colNorm``)"""
    return final_op(_norm(jnp.asarray(data), norm_type, 0, final_sqrt))


def norm(res, data, norm_type: NormType = NormType.L2,
         apply: Apply = Apply.ALONG_ROWS, final_sqrt: bool = False,
         final_op: Callable = ops.identity_op):
    """mdspan-style entry, reference convention (norm.cuh): ALONG_ROWS →
    one norm per row (rowNorm), ALONG_COLUMNS → one per column (colNorm)."""
    if apply == Apply.ALONG_ROWS:
        return row_norm(res, data, norm_type, final_sqrt, final_op)
    return col_norm(res, data, norm_type, final_sqrt, final_op)


def normalize(res, data, norm_type: NormType = NormType.L2, eps: float = 1e-8):
    """Normalize each row by its norm. (ref: linalg/normalize.cuh
    ``row_normalize``; rows with norm <= eps are left as zeros, matching the
    reference's divide-by-zero guard.)"""
    data = jnp.asarray(data)
    if norm_type == NormType.L2:
        norms = jnp.sqrt(jnp.sum(data * data, axis=1, keepdims=True))
    elif norm_type == NormType.L1:
        norms = jnp.sum(jnp.abs(data), axis=1, keepdims=True)
    else:
        norms = jnp.max(jnp.abs(data), axis=1, keepdims=True)
    safe = jnp.where(norms <= eps, jnp.ones_like(norms), norms)
    return jnp.where(norms <= eps, jnp.zeros_like(data), data / safe)


row_normalize = normalize
