"""Matrix ⊕ vector broadcast ops.

(ref: cpp/include/raft/linalg/matrix_vector_op.cuh — ``matrix_vector_op``
broadcasting a vector along rows or columns with a custom op (the
``detail/matrix_vector_op.cuh`` linewise kernel), and
linalg/matrix_vector.cuh — named binary mult/div/add/sub variants incl.
skip-zero division.)

Convention: ``apply=Apply.ALONG_ROWS`` broadcasts the vector along rows
(vector length == n_cols, added to every row); ``ALONG_COLUMNS`` broadcasts
along columns (vector length == n_rows).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.linalg.types import Apply


def _bcast(vec, apply: Apply):
    vec = jnp.asarray(vec)
    return vec[None, :] if apply == Apply.ALONG_ROWS else vec[:, None]


def matrix_vector_op(res, matrix, vec, op: Callable,
                     apply: Apply = Apply.ALONG_ROWS):
    """(ref: matrix_vector_op.cuh:1-arg-vector overload)"""
    matrix = jnp.asarray(matrix)
    v = _bcast(vec, apply)
    n = matrix.shape[1] if apply == Apply.ALONG_ROWS else matrix.shape[0]
    expects(v.size == n, "matrix_vector_op: vector length %d != extent %d", v.size, n)
    return op(matrix, v)


def matrix_vector_op2(res, matrix, vec1, vec2, op: Callable,
                      apply: Apply = Apply.ALONG_ROWS):
    """Two-vector overload. (ref: matrix_vector_op.cuh 2-vector)"""
    matrix = jnp.asarray(matrix)
    return op(matrix, _bcast(vec1, apply), _bcast(vec2, apply))


# named variants (ref: linalg/matrix_vector.cuh)
def binary_mult(res, matrix, vec, apply: Apply = Apply.ALONG_ROWS):
    return matrix_vector_op(res, matrix, vec, lambda m, v: m * v, apply)


def binary_mult_skip_zero(res, matrix, vec, apply: Apply = Apply.ALONG_ROWS):
    """Multiply, treating zero vector entries as 1 (skip).
    (ref: matrix_vector.cuh ``binary_mult_skip_zero``)"""

    def op(m, v):
        return jnp.where(v == 0, m, m * v)

    return matrix_vector_op(res, matrix, vec, op, apply)


def binary_div(res, matrix, vec, apply: Apply = Apply.ALONG_ROWS):
    return matrix_vector_op(res, matrix, vec, lambda m, v: m / v, apply)


def binary_div_skip_zero(res, matrix, vec, apply: Apply = Apply.ALONG_ROWS,
                         return_zero: bool = False):
    """Divide, skipping zero vector entries (or zeroing the output there).
    (ref: matrix_vector.cuh ``binary_div_skip_zero``)"""

    def op(m, v):
        safe = jnp.where(v == 0, jnp.ones_like(v), v)
        if return_zero:
            return jnp.where(v == 0, jnp.zeros_like(m), m / safe)
        return jnp.where(v == 0, m, m / safe)

    return matrix_vector_op(res, matrix, vec, op, apply)


def binary_add(res, matrix, vec, apply: Apply = Apply.ALONG_ROWS):
    return matrix_vector_op(res, matrix, vec, lambda m, v: m + v, apply)


def binary_sub(res, matrix, vec, apply: Apply = Apply.ALONG_ROWS):
    return matrix_vector_op(res, matrix, vec, lambda m, v: m - v, apply)
