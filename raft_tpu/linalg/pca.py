"""PCA fit/transform (moved into raft from cuML in 26.04).

(ref: cpp/include/raft/linalg/pca.cuh:41 ``pca_fit`` /
``pca_transform`` / ``pca_inverse_transform``; params
linalg/pca_types.hpp:21-34 ``paramsPCA`` + ``solver::COV_EIG_DC /
COV_EIG_JACOBI``; impl linalg/detail/pca.cuh: mean-center → covariance →
eigDC/eigJacobi → descending sort → sign flip → variance bookkeeping.)
"""

from __future__ import annotations

import dataclasses
import enum
from typing import NamedTuple, Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.linalg.eig import eig_dc, eig_jacobi
from raft_tpu.matrix.math_ops import sign_flip


class Solver(enum.Enum):
    """(ref: pca_types.hpp ``solver``)"""

    COV_EIG_DC = "cov_eig_dc"
    COV_EIG_JACOBI = "cov_eig_jacobi"


@dataclasses.dataclass
class ParamsPCA:
    """(ref: pca_types.hpp:34 ``paramsPCA``)"""

    n_components: int
    whiten: bool = False
    algorithm: Solver = Solver.COV_EIG_DC
    tol: float = 1e-7  # jacobi tolerance
    n_iterations: int = 15  # jacobi sweeps


class PCAModel(NamedTuple):
    """Outputs of pca_fit (the reference fills caller buffers; we return a
    named bundle)."""

    components: jnp.ndarray        # [n_components, n_features]
    explained_var: jnp.ndarray     # [n_components]
    explained_var_ratio: jnp.ndarray
    singular_vals: jnp.ndarray
    mu: jnp.ndarray                # [n_features]
    noise_vars: jnp.ndarray        # scalar


def _model_from_cov(res, cov, mu, n: int, p: int,
                    prms: ParamsPCA) -> PCAModel:
    """Shared model-build tail: eig → descending → sign flip →
    variance bookkeeping (detail/pca.cuh's post-covariance pipeline) —
    one copy for the single-device and distributed fits."""
    if prms.algorithm == Solver.COV_EIG_JACOBI:
        w, v = eig_jacobi(res, cov, tol=prms.tol, sweeps=prms.n_iterations)
    else:
        w, v = eig_dc(res, cov)
    # descending order
    w = w[::-1]
    v = v[:, ::-1]
    w = jnp.maximum(w, 0.0)
    components = sign_flip(res, v).T[: prms.n_components]
    explained_var = w[: prms.n_components]
    total_var = jnp.sum(w)
    explained_var_ratio = explained_var / total_var
    singular_vals = jnp.sqrt(explained_var * (n - 1))
    k = prms.n_components
    noise_vars = jnp.where(k < p, jnp.sum(w[k:]) / jnp.maximum(p - k, 1), 0.0)
    return PCAModel(components, explained_var, explained_var_ratio,
                    singular_vals, mu, noise_vars)


def pca_fit(res, X, prms: ParamsPCA) -> PCAModel:
    """(ref: pca.cuh:41 ``pca_fit``; pipeline detail/pca.cuh)"""
    X = jnp.asarray(X)
    n, p = X.shape
    expects(0 < prms.n_components <= p, "pca_fit: bad n_components")
    mu = jnp.mean(X, axis=0)
    Xc = X - mu[None, :]
    cov = (Xc.T @ Xc) / (n - 1)
    return _model_from_cov(res, cov, mu, n, p, prms)


def pad_mask_shard(X, mesh, axis: str = "x"):
    """Zero-pad rows to a shard-count multiple and place both the array
    and a validity mask rank-sharded over ``mesh[axis]`` — the shared
    preamble of every distributed fit (masked statistics exclude the
    pad rows)."""
    from raft_tpu.parallel.mesh import shard_array

    X = jnp.asarray(X)
    n = X.shape[0]
    n_shards = int(mesh.shape[axis])
    npad = (-n) % n_shards
    valid = jnp.concatenate(
        [jnp.ones((n,), jnp.float32), jnp.zeros((npad,), jnp.float32)])
    if npad:
        X = jnp.concatenate(
            [X, jnp.zeros((npad,) + X.shape[1:], X.dtype)])
    return shard_array(X, mesh, axis), shard_array(valid, mesh, axis)


def pca_fit_distributed(res, X, prms: ParamsPCA, mesh,
                        axis: str = "x") -> PCAModel:
    """MNMG PCA fit: rows sharded over ``mesh[axis]``, mean/cov via
    psum inside ``shard_map``, the eig tail replicated — the OPG
    pattern the reference documents (docs/source/using_raft_comms.rst;
    the raft-dask distributed-fit role). Rows that don't divide the
    shard count are zero-padded and masked out of the statistics."""
    import jax
    from jax.sharding import PartitionSpec as P

    X = jnp.asarray(X)
    n, p = X.shape
    expects(0 < prms.n_components <= p,
            "pca_fit_distributed: bad n_components")
    Xs, vs = pad_mask_shard(X, mesh, axis)

    def stats(x, v):
        # n is static/global; psums reduce the shard partials
        mu = jax.lax.psum(jnp.sum(x * v[:, None], axis=0), axis) / n
        xc = (x - mu[None, :]) * v[:, None]     # padded rows zeroed
        cov = jax.lax.psum(
            jnp.matmul(xc.T, xc, preferred_element_type=jnp.float32),
            axis) / (n - 1)
        return mu, cov

    mu, cov = jax.shard_map(
        stats, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=(P(), P()))(Xs, vs)
    return _model_from_cov(res, cov, mu, n, p, prms)


def pca_transform(res, X, model: PCAModel, prms: ParamsPCA):
    """(ref: pca.cuh ``pca_transform``)"""
    X = jnp.asarray(X)
    t = (X - model.mu[None, :]) @ model.components.T
    if prms.whiten:
        scale = jnp.sqrt(jnp.maximum(model.explained_var, 1e-12))
        t = t / scale[None, :]
    return t


def pca_inverse_transform(res, T, model: PCAModel, prms: ParamsPCA):
    """(ref: pca.cuh ``pca_inverse_transform``)"""
    T = jnp.asarray(T)
    if prms.whiten:
        scale = jnp.sqrt(jnp.maximum(model.explained_var, 1e-12))
        T = T * scale[None, :]
    return T @ model.components + model.mu[None, :]
