"""Cholesky rank-1 expansion update.

(ref: cpp/include/raft/linalg/cholesky_r1_update.cuh — given the Cholesky
factor L of the leading (k−1)×(k−1) block of A and A's k-th column, compute
the k-th row/column of L without refactorizing; used by incremental
algorithms that grow a kernel/covariance matrix one column at a time.)

Functional TPU rendering: ``cholesky_r1_update(L_prev, a_col)`` returns the
expanded k×k lower factor. The triangular solve is XLA's blocked
``solve_triangular``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

from raft_tpu.core.error import expects


def cholesky_r1_update(res, L_prev, a_col, eps: float = 0.0):
    """Expand an existing factor by one row/column.

    L_prev: [k-1, k-1] lower-triangular factor of A[:k-1, :k-1]
    a_col:  [k] — the new column A[:k, k-1] (last entry is the diagonal)
    Returns L: [k, k]. (ref: cholesky_r1_update.cuh)
    """
    a_col = jnp.asarray(a_col)
    k = a_col.shape[0]
    if k == 1:
        return jnp.sqrt(jnp.maximum(a_col, eps)).reshape(1, 1)
    L_prev = jnp.asarray(L_prev)
    expects(L_prev.shape == (k - 1, k - 1), "cholesky_r1_update: shape mismatch")
    l_row = solve_triangular(L_prev, a_col[: k - 1], lower=True)
    d2 = a_col[k - 1] - jnp.dot(l_row, l_row)
    d = jnp.sqrt(jnp.maximum(d2, eps if eps > 0 else 0.0))
    L = jnp.zeros((k, k), L_prev.dtype)
    L = L.at[: k - 1, : k - 1].set(L_prev)
    L = L.at[k - 1, : k - 1].set(l_row)
    L = L.at[k - 1, k - 1].set(d)
    return L
