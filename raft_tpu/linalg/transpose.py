"""Transpose. (ref: cpp/include/raft/linalg/transpose.cuh — cublasgeam
out-of-place + an in-place swap kernel; on TPU both are XLA transposes,
usually free (layout change) when fused.)"""

from __future__ import annotations

import jax.numpy as jnp


def transpose(res, matrix):
    return jnp.asarray(matrix).T


transpose_inplace = transpose  # functional: "in-place" has no meaning in JAX
