"""Sequence init. (ref: cpp/include/raft/linalg/init.cuh ``range`` — fill a
vector with start..end.)"""

from __future__ import annotations

import jax.numpy as jnp


def range_fill(res, start: int, end: int, dtype=jnp.int32):
    """(ref: init.cuh ``range(out, start, end, stream)``)"""
    return jnp.arange(start, end, dtype=dtype)
