"""Elementwise map kernels.

(ref: cpp/include/raft/linalg/map.cuh:95,118,144 ``map``/``map_offset`` and
linalg/unary_op.cuh / binary_op.cuh / ternary_op.cuh — all elementwise ops in
the reference funnel into one vectorized map kernel,
linalg/detail/map.cuh. On TPU the fusion/vectorization is XLA's job: these
are thin functional wrappers that keep the reference's API vocabulary and
broadcast semantics, and they fuse into surrounding jitted code.)
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from raft_tpu.core.resources import Resources


def map(res: Resources | None, f: Callable, *arrays):  # noqa: A001
    """out[i] = f(a0[i], a1[i], ...). (ref: map.cuh:95)"""
    args = [jnp.asarray(a) for a in arrays]
    return f(*args)


def map_offset(res: Resources | None, shape, f: Callable, *arrays):
    """out[i] = f(i, a0[i], ...) — the index-aware variant.
    (ref: map.cuh ``map_offset``) For multi-d inputs the offset is the
    row-major linear index."""
    args = [jnp.asarray(a) for a in arrays]
    target_shape = tuple(shape) if shape is not None else args[0].shape
    n = 1
    for s in target_shape:
        n *= s
    idx = jnp.arange(n).reshape(target_shape)
    return f(idx, *args)


def unary_op(res, x, f: Callable):
    """(ref: linalg/unary_op.cuh ``unaryOp``)"""
    return f(jnp.asarray(x))


def write_only_unary_op(res, shape, dtype, f: Callable):
    """Generate an array from indices alone.
    (ref: unary_op.cuh ``writeOnlyUnaryOp``)"""
    n = 1
    for s in shape:
        n *= s
    idx = jnp.arange(n).reshape(tuple(shape))
    return f(idx).astype(dtype)


def binary_op(res, a, b, f: Callable):
    """(ref: linalg/binary_op.cuh)"""
    return f(jnp.asarray(a), jnp.asarray(b))


def ternary_op(res, a, b, c, f: Callable):
    """(ref: linalg/ternary_op.cuh)"""
    return f(jnp.asarray(a), jnp.asarray(b), jnp.asarray(c))
