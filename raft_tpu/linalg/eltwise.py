"""Elementwise arithmetic with the reference's naming.

(ref: cpp/include/raft/linalg/add.cuh, subtract.cuh, multiply.cuh,
divide.cuh, power.cuh, sqrt.cuh, eltwise.cuh — scalar and elementwise
variants. All are XLA-fused one-liners here; kept as named functions for API
parity and for composition inside bigger primitives.)
"""

from __future__ import annotations

import jax.numpy as jnp


def _a(x):
    return jnp.asarray(x)


# vector ⊕ vector
def add(res, a, b):
    return _a(a) + _a(b)


def subtract(res, a, b):
    return _a(a) - _a(b)


def multiply(res, a, b):
    return _a(a) * _a(b)


def divide(res, a, b):
    return _a(a) / _a(b)


def power(res, a, b):
    return _a(a) ** _a(b)


def sqrt(res, a):
    return jnp.sqrt(_a(a))


# vector ⊕ scalar (ref: *_scalar variants)
def add_scalar(res, a, scalar):
    return _a(a) + scalar


def subtract_scalar(res, a, scalar):
    return _a(a) - scalar


def multiply_scalar(res, a, scalar):
    return _a(a) * scalar


def divide_scalar(res, a, scalar):
    return _a(a) / scalar


def power_scalar(res, a, scalar):
    return _a(a) ** scalar


# eltwise aliases (ref: eltwise.cuh scalarAdd/scalarMultiply/eltwiseAdd/...)
scalar_add = add_scalar
scalar_multiply = multiply_scalar
eltwise_add = add
eltwise_sub = subtract
eltwise_multiply = multiply
eltwise_divide = divide


def eltwise_divide_check_zero(res, a, b):
    """(ref: eltwise.cuh ``eltwiseDivideCheckZero`` — 0 where divisor is 0)"""
    a, b = _a(a), _a(b)
    safe = jnp.where(b == 0, jnp.ones_like(b), b)
    return jnp.where(b == 0, jnp.zeros_like(a / safe), a / safe)
