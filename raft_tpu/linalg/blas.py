"""BLAS-level ops: gemm / gemv / axpy / dot.

(ref: cpp/include/raft/linalg/gemm.cuh:51 mdspan gemm,
linalg/detail/gemm.cuh ``legacy_matmul`` → cuBLASLt; gemv.cuh, axpy.cuh,
dot.cuh.) On TPU the MXU path is XLA's dot_general — the wrappers keep the
reference's alpha/beta/transpose surface and always set
``preferred_element_type`` so bf16 inputs accumulate in f32 on the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from raft_tpu.core.error import expects


def _preferred(dtype):
    return jnp.float32 if dtype in (jnp.bfloat16, jnp.float16) else dtype


def gemm(res, A, B, C: Optional[jnp.ndarray] = None, alpha=1.0, beta=0.0,
         trans_a: bool = False, trans_b: bool = False,
         preferred_element_type=None):
    """C = alpha * op(A) @ op(B) + beta * C. (ref: gemm.cuh:51)"""
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    if trans_a:
        A = A.T
    if trans_b:
        B = B.T
    pet = preferred_element_type or _preferred(A.dtype)
    out = alpha * jnp.matmul(A, B, preferred_element_type=pet)
    if C is not None and beta != 0.0:
        out = out + beta * jnp.asarray(C)
    return out.astype(A.dtype) if preferred_element_type is None else out


def gemv(res, A, x, y: Optional[jnp.ndarray] = None, alpha=1.0, beta=0.0,
         trans_a: bool = False):
    """y = alpha * op(A) @ x + beta * y. (ref: linalg/gemv.cuh)"""
    A = jnp.asarray(A)
    x = jnp.asarray(x)
    if trans_a:
        A = A.T
    expects(A.shape[1] == x.shape[0], "gemv: inner dim mismatch %d vs %d",
            A.shape[1], x.shape[0])
    out = alpha * jnp.matmul(A, x, preferred_element_type=_preferred(A.dtype))
    if y is not None and beta != 0.0:
        out = out + beta * jnp.asarray(y)
    return out.astype(A.dtype)


def axpy(res, alpha, x, y):
    """y = alpha*x + y. (ref: linalg/axpy.cuh)"""
    return alpha * jnp.asarray(x) + jnp.asarray(y)


def dot(res, x, y):
    """(ref: linalg/dot.cuh)"""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    return jnp.dot(x, y, preferred_element_type=_preferred(x.dtype)).astype(x.dtype)
