"""Baseline suppression for graftlint findings.

The baseline file (``tools/graftlint_baseline.json``) is the list of
*accepted* findings — every entry MUST carry a human reason string, so
a suppression is a documented decision, never a silent one. Matching
is by :attr:`Finding.fingerprint` (pass:rule:file:anchor — no line
numbers), so unrelated edits don't churn the file.

Apply semantics (pinned by tests/test_analysis.py):

- a finding whose fingerprint is in the baseline → suppressed;
- a finding NOT in the baseline → unsuppressed (fails the gate);
- a baseline entry matching no finding → *stale*, reported as a
  warning (clean it up) but never a gate failure;
- an entry with an empty reason → rejected at load (the file is part
  of the contract, not an escape hatch).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Sequence, Tuple

from .framework import Finding

DEFAULT_BASELINE_REL = "tools/graftlint_baseline.json"
SCHEMA = 1


@dataclasses.dataclass
class Baseline:
    entries: Dict[str, str]     # fingerprint → reason
    path: str = ""

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Load (missing file = empty baseline). Malformed files and
        reason-less entries raise — a broken baseline must never make
        the gate silently permissive."""
        if not os.path.exists(path):
            return cls(entries={}, path=path)
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        if (not isinstance(payload, dict)
                or payload.get("schema") != SCHEMA
                or not isinstance(payload.get("suppressions"), list)):
            raise ValueError(
                f"graftlint baseline {path}: expected "
                f"{{schema: {SCHEMA}, suppressions: [...]}}")
        entries: Dict[str, str] = {}
        for e in payload["suppressions"]:
            fp = e.get("fingerprint")
            reason = (e.get("reason") or "").strip()
            if not fp or not reason:
                raise ValueError(
                    f"graftlint baseline {path}: every suppression "
                    f"needs a fingerprint AND a non-empty reason "
                    f"(offending entry: {e!r})")
            entries[fp] = reason
        return cls(entries=entries, path=path)

    def save(self, path: str = "") -> None:
        path = path or self.path
        payload = {
            "schema": SCHEMA,
            "suppressions": [
                {"fingerprint": fp, "reason": reason}
                for fp, reason in sorted(self.entries.items())],
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")

    def apply(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[str]]:
        """→ (unsuppressed, suppressed, stale fingerprints)."""
        unsuppressed, suppressed = [], []
        seen = set()
        for f in findings:
            if f.fingerprint in self.entries:
                suppressed.append(f)
                seen.add(f.fingerprint)
            else:
                unsuppressed.append(f)
        stale = sorted(set(self.entries) - seen)
        return unsuppressed, suppressed, stale
