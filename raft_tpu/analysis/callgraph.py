"""Whole-program call graph over a parsed :class:`~.loader.Program`.

Name-based, deliberately conservative: an edge exists when the callee
expression resolves statically — plain names through the import symbol
table and lexical nesting, ``self.meth()``/``cls.meth()`` within the
enclosing class, and ``mod.fn()`` through imported-module attributes.
Dynamic dispatch (arbitrary ``obj.method()``) is recorded as an
*external* call under its canonicalized dotted name (import aliases
resolved, e.g. ``onp.asarray`` → ``numpy.asarray``) so hazard passes
can still match it; it never creates a reachability edge.

Qualnames are ``"pkg.mod:Class.fn"`` (module ``:`` in-module path).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .loader import ModuleInfo, Program, dotted


@dataclasses.dataclass
class FunctionInfo:
    qual: str                  # "pkg.mod:Class.fn"
    module: ModuleInfo
    node: ast.AST              # FunctionDef / AsyncFunctionDef
    path: Tuple[str, ...]      # in-module path components
    cls: Optional[str]         # innermost enclosing class (in-module
    #                            dotted path), None for free functions

    @property
    def name(self) -> str:
        return self.path[-1]

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclasses.dataclass
class CallSite:
    caller: str                       # qualname
    node: ast.Call
    resolved: Optional[str] = None    # callee qualname when static
    external: Optional[str] = None    # canonical dotted name otherwise

    @property
    def line(self) -> int:
        return self.node.lineno


class CallGraph:
    def __init__(self, program: Program):
        self.program = program
        self.functions: Dict[str, FunctionInfo] = {}
        self.calls: Dict[str, List[CallSite]] = {}
        self.edges: Dict[str, Set[str]] = {}

    # -- resolution --------------------------------------------------
    def resolve(self, info: ModuleInfo, scope: Tuple[str, ...],
                name: str, cls: Optional[str] = None
                ) -> Optional[str]:
        """Resolve a dotted ``name`` referenced from function scope
        ``scope`` of module ``info`` to a function qualname, or None."""
        head, _, rest = name.partition(".")
        # self.meth / cls.meth → the enclosing class's method
        if head in ("self", "cls") and cls is not None and rest:
            cand = f"{info.name}:{cls}.{rest}"
            if cand in self.functions:
                return cand
            return None
        if not rest:
            # lexical nesting: innermost enclosing prefix wins
            for i in range(len(scope), -1, -1):
                prefix = ".".join(scope[:i])
                cand = (f"{info.name}:{prefix}.{name}" if prefix
                        else f"{info.name}:{name}")
                if cand in self.functions:
                    return cand
        target = info.symbols.get(head)
        if target is None:
            return None
        full = f"{target}.{rest}" if rest else target
        # longest module prefix of `full` that exists in the program
        parts = full.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.program.get(".".join(parts[:i]))
            if mod is not None:
                cand = f"{mod.name}:{'.'.join(parts[i:])}"
                return cand if cand in self.functions else None
        return None

    def canonical(self, info: ModuleInfo, name: str) -> str:
        """Dotted name with its import-alias head resolved (``onp.x``
        → ``numpy.x``); unknown heads pass through unchanged."""
        head, _, rest = name.partition(".")
        target = info.symbols.get(head)
        if target is None:
            return name
        return f"{target}.{rest}" if rest else target

    # -- reachability -------------------------------------------------
    def reachable(self, roots) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(self.edges.get(q, ()))
        return seen

    def callers_of(self, qual: str) -> Set[str]:
        return {c for c, outs in self.edges.items() if qual in outs}

    def iter_calls(self, qual: str) -> Iterator[CallSite]:
        return iter(self.calls.get(qual, ()))


def _collect_functions(graph: CallGraph, info: ModuleInfo) -> None:
    def _walk(node: ast.AST, path: Tuple[str, ...],
              cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                p = path + (child.name,)
                qual = f"{info.name}:{'.'.join(p)}"
                graph.functions[qual] = FunctionInfo(
                    qual=qual, module=info, node=child, path=p, cls=cls)
                _walk(child, p, cls)
            elif isinstance(child, ast.ClassDef):
                p = path + (child.name,)
                _walk(child, p, ".".join(p))
            else:
                _walk(child, path, cls)
    _walk(info.tree, (), None)


def _collect_calls(graph: CallGraph, fn: FunctionInfo) -> None:
    """Call sites lexically inside ``fn`` but NOT inside a nested def
    (those belong to the nested function)."""
    sites: List[CallSite] = []

    def _walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                name = dotted(child.func)
                if name is None:
                    site = CallSite(fn.qual, child,
                                    external="<dynamic>")
                else:
                    resolved = graph.resolve(fn.module, fn.path, name,
                                             cls=fn.cls)
                    if resolved is not None:
                        site = CallSite(fn.qual, child,
                                        resolved=resolved)
                    else:
                        site = CallSite(
                            fn.qual, child,
                            external=graph.canonical(fn.module, name))
                sites.append(site)
            _walk(child)

    _walk(fn.node)
    graph.calls[fn.qual] = sites
    graph.edges[fn.qual] = {s.resolved for s in sites
                            if s.resolved is not None}


def build_call_graph(program: Program) -> CallGraph:
    graph = CallGraph(program)
    for info in program:
        _collect_functions(graph, info)
    for fn in list(graph.functions.values()):
        _collect_calls(graph, fn)
    return graph
