"""Lock-discipline pass over the threaded planes.

Lock identities are discovered statically: ``self._x =
threading.Lock()/RLock()/Condition(...)`` inside a class (identity
``"pkg.mod:Class._x"``) and module-level ``X = threading.Lock()``
(identity ``"pkg.mod:X"``). Acquisitions are ``with <lock>:`` blocks
plus explicit ``.acquire()`` calls; a ``<lock>.release()`` inside a
``with`` body *suspends* the held region until a matching
``.acquire()`` (the drop-the-lock-around-the-slow-part idiom in
``MutableIndex._ensure_delta_space_locked``).

Three rule families:

``lock-order-inversion``
    The whole-program acquisition graph (lock A held while acquiring
    B — directly or through any statically-resolvable call chain)
    contains a cycle. Self-edges on re-entrant locks (``RLock``,
    ``Condition`` — its default lock is an RLock) are legal;
    a self-edge on a plain ``Lock`` is reported as
    ``self-deadlock``.

``blocking-under-lock``
    A blocking call — ``fsync``/``fdatasync``, ``sleep``, thread
    ``.join()``, ``.result()``, device syncs
    (``block_until_ready``/``synchronize``/``sync_stream``/
    ``barrier``), or an ``Event.wait``/``Queue`` wait on an object
    other than the held lock — executes while a lock is held, directly
    or through a resolvable call chain. ``Condition.wait`` on the held
    condition itself is exempt (it releases the lock).

``unlocked-shared-state``
    A module-level name is written (``global`` declaration + store)
    from two or more distinct thread roots (``threading.Thread``
    targets, ``Timer`` callbacks, ``run()`` methods of Thread
    subclasses — plus everything else as the implicit main root) with
    no lock held at any writing site.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, FunctionInfo
from .framework import AnalysisPass, Finding, register_pass
from .loader import Program, dotted

_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": True,
               "Semaphore": False, "BoundedSemaphore": False}

#: canonical call names (or bare attribute names) that block the
#: calling thread; attribute entries match any receiver
_BLOCKING_CALLS = {"os.fsync": "fsync", "os.fdatasync": "fdatasync",
                   "time.sleep": "sleep",
                   # disk scans / deletes: a directory walk under a
                   # hot-path lock stalls every waiter behind the disk
                   "glob.glob": "glob", "os.listdir": "listdir",
                   "os.scandir": "scandir", "os.unlink": "unlink",
                   "os.remove": "remove",
                   "os.path.getsize": "getsize"}
_BLOCKING_ATTRS = {"fsync", "fdatasync", "join", "result",
                   "block_until_ready", "synchronize", "sync_stream",
                   "barrier"}


def _join_is_string_join(node: ast.Call, canon: Optional[str]) -> bool:
    """``", ".join(...)`` / ``os.path.join`` — not a thread join."""
    if canon is not None and canon.startswith("os.path."):
        return True
    recv = node.func.value if isinstance(node.func,
                                         ast.Attribute) else None
    return isinstance(recv, ast.Constant)
#: ``.wait(...)`` blocks too — but not on the held lock itself
#: (Condition.wait releases it while sleeping)
_WAIT_ATTR = "wait"


@dataclasses.dataclass(frozen=True)
class LockInfo:
    ident: str          # "pkg.mod:Class._x" or "pkg.mod:X"
    reentrant: bool
    rel: str
    line: int


@dataclasses.dataclass
class _Acquire:
    lock: str
    node: ast.AST       # the with-item / acquire call


def _ctor_kind(call: ast.expr, canonical) -> Optional[bool]:
    """→ reentrant flag when ``call`` constructs a lock, else None.
    ``Condition(lock)`` inherits the wrapped lock's reentrancy when
    statically visible; the bare default is an RLock."""
    if not isinstance(call, ast.Call):
        return None
    name = dotted(call.func)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    if last not in _LOCK_CTORS:
        return None
    canon = canonical(call.func) or name
    if not (canon.startswith("threading.") or "." not in canon):
        return None
    if last == "Condition" and call.args:
        inner = _ctor_kind(call.args[0], canonical)
        if inner is not None:
            return inner
    return _LOCK_CTORS[last]


class LockDisciplinePass(AnalysisPass):
    name = "lock-discipline"

    # -- discovery ----------------------------------------------------
    def _find_locks(self, program: Program, graph: CallGraph
                    ) -> Dict[str, LockInfo]:
        locks: Dict[str, LockInfo] = {}
        for info in program:
            canonical = lambda e, _m=info: (  # noqa: E731
                graph.canonical(_m, dotted(e)) if dotted(e) else None)
            # module-level: X = threading.Lock()
            for node in info.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    kind = _ctor_kind(node.value, canonical)
                    if kind is not None:
                        ident = f"{info.name}:{node.targets[0].id}"
                        locks[ident] = LockInfo(ident, kind, info.rel,
                                                node.lineno)
            # instance attributes: self._x = threading.Lock() anywhere
            # inside a class body (usually __init__)
            for fn in graph.functions.values():
                if fn.module is not info or fn.cls is None:
                    continue
                for node in ast.walk(fn.node):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1):
                        continue
                    t = node.targets[0]
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    kind = _ctor_kind(node.value, canonical)
                    if kind is not None:
                        ident = f"{info.name}:{fn.cls}.{t.attr}"
                        locks[ident] = LockInfo(ident, kind, info.rel,
                                                node.lineno)
        return locks

    def _lock_of(self, fn: FunctionInfo, expr: ast.expr,
                 locks: Dict[str, LockInfo]) -> Optional[str]:
        """Resolve a ``with``-item / receiver expression to a known
        lock identity (``self._x``, bare module-level name, or a
        ``mod.X`` attribute chain)."""
        name = dotted(expr)
        if name is None:
            return None
        if name.startswith("self.") and fn.cls is not None:
            ident = f"{fn.module.name}:{fn.cls}.{name[5:]}"
            return ident if ident in locks else None
        if "." not in name:
            ident = f"{fn.module.name}:{name}"
            return ident if ident in locks else None
        head, _, rest = name.rpartition(".")
        target = fn.module.symbols.get(head.split(".")[0])
        if target is not None:
            ident = f"{target}:{rest}"
            if ident in locks:
                return ident
        # attribute on an arbitrary object: match by UNIQUE attr name
        # across all class locks (self-alias through a local var stays
        # invisible otherwise); ambiguity = no match, stay conservative
        attr = name.rsplit(".", 1)[-1]
        cands = [i for i in locks if i.rsplit(".", 1)[-1] == attr
                 and ":" in i and "." in i.split(":")[1]]
        return cands[0] if len(cands) == 1 else None

    # -- per-function summaries --------------------------------------
    def _analyze_function(self, fn: FunctionInfo,
                          locks: Dict[str, LockInfo]):
        """Linear statement walk tracking the held-lock stack.
        Returns (acquire_edges, direct_acquires, held_calls,
        held_blocking, unlocked_global_writes, locked_global_writes):

        - ``acquire_edges``: (held, acquired, node) observed directly;
        - ``direct_acquires``: locks this function acquires with NO
          lock already held (its contribution to callers' edges);
        - ``held_calls``: (held_lock, call_site) for interprocedural
          propagation;
        - ``held_blocking``: (held_lock, rule, node, detail) direct
          blocking calls under a held lock;
        - global writes partitioned by whether any lock was held;
        - ``blocks_any``: (detail, recv_lock-or-None) for every
          blocking call in the function REGARDLESS of held state —
          the summary callers propagate (they may hold a lock around
          a call into this function).
        """
        edges: List[Tuple[str, str, ast.AST]] = []
        direct: Set[str] = set()
        held_calls: List[Tuple[str, ast.Call]] = []
        blocking: List[Tuple[str, str, ast.AST, str]] = []
        gl_unlocked: List[Tuple[str, ast.AST]] = []
        gl_locked: List[Tuple[str, ast.AST]] = []
        globals_declared: Set[str] = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)

        graph_canonical = self._graph.canonical
        canonical = lambda e, _m=fn.module: (  # noqa: E731
            graph_canonical(_m, dotted(e)) if dotted(e) else None)

        def _on_acquire(held: Sequence[str], lock: str,
                        node: ast.AST) -> None:
            if held:
                edges.append((held[-1], lock, node))
            else:
                direct.add(lock)

        def _walk(body: Sequence[ast.stmt], held: List[str]) -> None:
            suspended: List[str] = []
            for stmt in body:
                self._walk_stmt(stmt, held, suspended, fn, locks,
                                canonical, _on_acquire, held_calls,
                                blocking, gl_unlocked, gl_locked,
                                globals_declared, _walk)
            # a suspended lock not re-acquired by function end is a
            # modeling gap, not a finding — restore silently
            held.extend(suspended)

        _walk(fn.node.body, [])
        blocks_any = self._direct_blocking_any(fn, locks, canonical)
        return (edges, direct, held_calls, blocking, gl_unlocked,
                gl_locked, blocks_any)

    def _direct_blocking_any(self, fn: FunctionInfo,
                             locks: Dict[str, LockInfo], canonical
                             ) -> Set[Tuple[str, Optional[str]]]:
        """Blocking calls lexically in ``fn`` (nested defs excluded),
        with the receiver lock resolved for ``.wait()`` so callers can
        exempt a wait on the very lock they hold (Condition.wait
        releases it)."""
        out: Set[Tuple[str, Optional[str]]] = set()
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn.node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            canon = canonical(node.func)
            if canon in _BLOCKING_CALLS:
                out.add((canon, None))
            elif isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr == "join" \
                        and _join_is_string_join(node, canon):
                    continue
                if attr in _BLOCKING_ATTRS:
                    out.add((f".{attr}()", None))
                elif attr == _WAIT_ATTR:
                    out.add((".wait()",
                             self._lock_of(fn, node.func.value,
                                           locks)))
        return out

    def _walk_stmt(self, stmt, held, suspended, fn, locks, canonical,
                   on_acquire, held_calls, blocking, gl_unlocked,
                   gl_locked, globals_declared, walk_body) -> None:
        # nested defs get their own summaries — do not descend
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.With):
            inner = list(held)
            acquired_here: List[str] = []
            for item in stmt.items:
                lock = self._lock_of(fn, item.context_expr, locks)
                if lock is not None:
                    on_acquire(inner, lock, item.context_expr)
                    inner.append(lock)
                    acquired_here.append(lock)
                else:
                    self._scan_expr(item.context_expr, inner, fn,
                                    locks, canonical, held_calls,
                                    blocking, on_acquire)
            walk_body(stmt.body, inner)
            # locks released at block exit; anything the body acquired
            # beyond `inner` (explicit .acquire) stays with the caller
            for lock in inner:
                if lock not in held and lock not in acquired_here \
                        and lock not in suspended:
                    held.append(lock)
            return
        if isinstance(stmt, (ast.If, ast.While, ast.For)):
            self._scan_expr(getattr(stmt, "test", None)
                            or getattr(stmt, "iter", None),
                            held, fn, locks, canonical, held_calls,
                            blocking, on_acquire)
            walk_body(stmt.body, held)
            walk_body(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            walk_body(stmt.body, held)
            for h in stmt.handlers:
                walk_body(h.body, held)
            walk_body(stmt.orelse, held)
            walk_body(stmt.finalbody, held)
            return
        # release/acquire suspension inside a with-body
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute):
                recv = self._lock_of(fn, call.func.value, locks)
                if recv is not None and call.func.attr == "release" \
                        and recv in held:
                    held.remove(recv)
                    suspended.append(recv)
                elif recv is not None and call.func.attr == "acquire":
                    if recv in suspended:
                        suspended.remove(recv)
                        held.append(recv)
                    else:
                        on_acquire(held, recv, call)
                        held.append(recv)
                    return
        # global writes
        targets: List[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id in globals_declared:
                (gl_locked if held else gl_unlocked).append(
                    (t.id, stmt))
        # generic expression scan (calls, nested acquires)
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.expr):
                self._scan_expr(sub, held, fn, locks, canonical,
                                held_calls, blocking, on_acquire)

    def _scan_expr(self, expr, held, fn, locks, canonical, held_calls,
                   blocking, on_acquire) -> None:
        if expr is None:
            return
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            canon = canonical(node.func) if name else None
            if held:
                held_calls.append((held[-1], node))
                detail = None
                if canon in _BLOCKING_CALLS:
                    detail = canon
                elif isinstance(node.func, ast.Attribute):
                    attr = node.func.attr
                    if attr == "join" \
                            and _join_is_string_join(node, canon):
                        pass
                    elif attr in _BLOCKING_ATTRS:
                        detail = f".{attr}()"
                    elif attr == _WAIT_ATTR:
                        recv = self._lock_of(fn, node.func.value,
                                             locks)
                        if recv is None or recv not in held:
                            detail = ".wait()"
                if detail is not None:
                    blocking.append((held[-1], "blocking-under-lock",
                                     node, detail))
            # explicit acquire as a sub-expression
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                recv = self._lock_of(fn, node.func.value, locks)
                if recv is not None and recv not in held:
                    on_acquire(held, recv, node)

    # -- thread roots -------------------------------------------------
    def _thread_roots(self, program: Program, graph: CallGraph
                      ) -> Dict[str, Set[str]]:
        """root qualname → reachable functions, one entry per
        discovered thread entry point."""
        roots: Set[str] = set()
        for fn in graph.functions.values():
            for site in graph.iter_calls(fn.qual):
                last = (site.external or site.resolved
                        or "").rsplit(".", 1)[-1]
                if last.split(":")[-1] not in ("Thread", "Timer"):
                    continue
                target = None
                for kw in site.node.keywords:
                    if kw.arg in ("target", "function"):
                        target = kw.value
                if target is None:
                    continue
                name = dotted(target)
                if name is None:
                    continue
                q = graph.resolve(fn.module, fn.path, name, cls=fn.cls)
                if q is not None:
                    roots.add(q)
            # Thread subclasses: run() is a root
            if fn.cls is not None and fn.name == "run":
                roots.add(fn.qual)
        return {r: graph.reachable([r]) for r in sorted(roots)}

    # -- run -----------------------------------------------------------
    def run(self, program: Program, graph: CallGraph) -> List[Finding]:
        self._graph = graph
        locks = self._find_locks(program, graph)
        findings: List[Finding] = []

        summaries = {}
        for qual, fn in graph.functions.items():
            summaries[qual] = self._analyze_function(fn, locks)

        # fixpoint: locks a call may acquire / blocking ops it may
        # reach (transitively — the fsync usually sits in a helper the
        # lock-holder calls, not under the ``with`` itself)
        acq_during: Dict[str, Set[str]] = {
            q: set(s[1]) | {e[1] for e in s[0]}
            for q, s in summaries.items()}
        blk_any: Dict[str, Set[Tuple[str, Optional[str]]]] = {
            q: set(s[6]) for q, s in summaries.items()}
        changed = True
        while changed:
            changed = False
            for q in summaries:
                for callee in graph.edges.get(q, ()):
                    if callee == q:
                        continue
                    na = acq_during[callee] - acq_during[q]
                    nb = blk_any[callee] - blk_any[q]
                    if na:
                        acq_during[q] |= na
                        changed = True
                    if nb:
                        blk_any[q] |= nb
                        changed = True

        # acquisition graph: direct edges + held-call propagation
        graph_edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        for qual, summary in sorted(summaries.items()):
            edges, _d, held_calls = summary[0], summary[1], summary[2]
            fn = graph.functions[qual]
            for held, acquired, node in edges:
                graph_edges.setdefault(
                    (held, acquired),
                    (fn.module.rel, node.lineno, qual))
            for held, call in held_calls:
                name = dotted(call.func)
                q2 = (graph.resolve(fn.module, fn.path, name,
                                    cls=fn.cls) if name else None)
                if q2 is None:
                    continue
                for acquired in sorted(acq_during.get(q2, ())):
                    graph_edges.setdefault(
                        (held, acquired),
                        (fn.module.rel, call.lineno, qual))
                for detail, recv in sorted(
                        blk_any.get(q2, ()),
                        key=lambda t: (t[0], t[1] or "")):
                    if recv is not None and recv == held:
                        continue  # Condition.wait on the held lock
                    findings.append(self.finding(
                        "blocking-under-lock", fn.module.rel,
                        call.lineno,
                        f"call chain from {qual} (via "
                        f"{q2.split(':')[-1]}) reaches blocking "
                        f"{detail} while holding {held}",
                        where=f"{qual}->{q2.split(':')[-1]}#{detail}"
                        f"@{held}"))

        # direct blocking findings
        for qual, summary in sorted(summaries.items()):
            blocking = summary[3]
            fn = graph.functions[qual]
            for held, rule, node, detail in blocking:
                findings.append(self.finding(
                    rule, fn.module.rel, node.lineno,
                    f"blocking {detail} while holding {held} "
                    f"(in {qual})",
                    where=f"{qual}#{detail}@{held}"))

        # cycles (pairwise inversions + self-deadlock on plain locks)
        for (a, b), (rel, line, qual) in sorted(graph_edges.items()):
            if a == b:
                if a in locks and not locks[a].reentrant:
                    findings.append(self.finding(
                        "self-deadlock", rel, line,
                        f"non-reentrant lock {a} re-acquired while "
                        f"already held (in {qual})",
                        where=f"{a}#self"))
                continue
            if (b, a) in graph_edges and a < b:
                rel2, line2, qual2 = graph_edges[(b, a)]
                findings.append(self.finding(
                    "lock-order-inversion", rel, line,
                    f"lock order inversion: {a} → {b} here, but "
                    f"{b} → {a} at {rel2}:{line2} ({qual2}) — a "
                    f"two-thread interleaving deadlocks",
                    where=f"{a}<->{b}"))

        # unlocked shared module state across thread roots
        root_sets = self._thread_roots(program, graph)
        writers: Dict[Tuple[str, str], List[Tuple[str, str, int, bool]]] = {}
        for qual, summary in summaries.items():
            gl_unlocked, gl_locked = summary[4], summary[5]
            fn = graph.functions[qual]
            for name, node in gl_unlocked:
                writers.setdefault((fn.module.name, name), []).append(
                    (qual, fn.module.rel, node.lineno, False))
            for name, node in gl_locked:
                writers.setdefault((fn.module.name, name), []).append(
                    (qual, fn.module.rel, node.lineno, True))
        for (mod, name), sites in sorted(writers.items()):
            roots_hit = set()
            for qual, _rel, _line, _locked in sites:
                hit = [r for r, reach in root_sets.items()
                       if qual in reach]
                roots_hit.update(hit or ["<main>"])
            if len(roots_hit) < 2:
                continue
            unlocked = [s for s in sites if not s[3]]
            for qual, rel, line, _locked in sorted(unlocked):
                findings.append(self.finding(
                    "unlocked-shared-state", rel, line,
                    f"module global `{name}` written from {qual} "
                    f"with no lock held, and the write is reachable "
                    f"from {len(roots_hit)} thread roots "
                    f"({', '.join(sorted(roots_hit)[:3])}…)",
                    where=f"{mod}.{name}@{qual}"))
        # the gate covers the library tree; bench drivers thread too
        # but are not production surface
        return [f for f in findings if f.rel.startswith("raft_tpu/")]


register_pass(LockDisciplinePass)
