"""Registry-derivation pass: site registries derived *from source*.

Six hand-pinned tables used to define what a "site" is
(``check_instrumented``'s HOT_PATHS/FAULT_SITES/EVENT_SITES/…,
``faults.KNOWN_SITES``, ``flight.KNOWN_EVENT_KINDS``, the README
env-knob table). Every new subsystem grew them by hand — and could
ship half-registered. This pass derives the ground truth from the
AST and diffs it against every declared registry, in BOTH directions:

- ``unregistered-fault-site`` / ``orphan-fault-site`` — a
  ``fault_point("x")`` call whose site is missing from
  ``faults.KNOWN_SITES``, and a KNOWN_SITES entry no code ever arms;
- ``unknown-event-kind`` / ``orphan-event-kind`` — a timeline emitter
  recording a kind outside ``flight.KNOWN_EVENT_KINDS``, and a
  vocabulary kind no emitter produces;
- ``unregistered-hot-path`` — an ``@instrument``-decorated module
  function absent from ``check_instrumented.HOT_PATHS`` (the
  half-registered-subsystem bug, caught statically);
- ``unregistered-quality-site`` — a module calling the quality
  recorders with no QUALITY_SITES entry;
- ``unregistered-env-knob`` / ``undocumented-env-knob`` /
  ``stale-readme-knob`` — the code ⊆ ``core/env.KNOBS`` ⊆ README
  chain for every ``RAFT_TPU_*`` knob.

``tools/check_instrumented.py`` *imports* the derived registries from
here (``derive_registries``) instead of redeclaring them, so the two
tools can never disagree about what a site is (equality pinned by
tests/test_analysis.py).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph
from .framework import AnalysisPass, Finding, WARNING, register_pass
from .loader import (ModuleInfo, Program, dotted, load_program,
                     string_constants)

#: what the program spans beyond raft_tpu/ — the tools and bench
#: drivers carry registry tables and env knobs of their own
SCAN_PACKAGES: Tuple[str, ...] = ("raft_tpu", "tools", "benchmarks")
EXTRA_SCAN_FILES: Tuple[str, ...] = ("bench.py",)

FAULTS_MODULE = "raft_tpu/resilience/faults.py"
FLIGHT_MODULE = "raft_tpu/observability/flight.py"
TIMELINE_MODULE = "raft_tpu/observability/timeline.py"
QUALITY_MODULE = "raft_tpu/observability/quality.py"
ENV_MODULE = "raft_tpu/core/env.py"
CHECKER_MODULE = "tools/check_instrumented.py"
README = "README.md"

_KNOB_RE = re.compile(r"^RAFT_TPU_[A-Z0-9_]+$")
_README_KNOB_RE = re.compile(r"`(RAFT_TPU_[A-Z0-9_]+)")

#: emitters whose defining module is NOT timeline.py, mapped to the
#: flight event kind they (transitively) produce. The single curated
#: seam left: these are bridges (decorator → span, fault_point →
#: fault, quality recorders → quality) whose kind cannot be read off
#: a ``rec.record("<kind>", ...)`` literal in timeline.py.
ALIAS_EMITTERS: Dict[str, str] = {
    "instrument": "span",
    "span": "span",
    "fault_point": "fault",
    "record_collective": "collective",
    "record_drift": "drift",
    "record_certificate": "quality",
    "record_pending": "quality",
    "record_pq_rungs": "quality",
}

QUALITY_RECORDERS = ("record_certificate", "record_pending",
                    "record_pq_rungs", "ShadowSampler")


# ---------------------------------------------------------------- utils
def module_literal(info: Optional[ModuleInfo], name: str):
    """``ast.literal_eval`` of a module-level ``NAME = <literal>``
    assignment (AnnAssign included). None when absent/non-literal."""
    if info is None:
        return None
    for node in info.tree.body:
        targets: List[str] = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            targets = [node.target.id]
        if name in targets and getattr(node, "value", None) is not None:
            try:
                return ast.literal_eval(node.value)
            except ValueError:
                return None
    return None


def referenced_names(tree: ast.AST) -> Set[str]:
    """Plain names + attribute names + from-import names — the ONE
    definition of "module references emitter X" shared with
    check_instrumented."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
        elif isinstance(node, ast.ImportFrom):
            names.update(a.name for a in node.names)
    return names


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


# ------------------------------------------------------- derivations
def derive_fault_sites(program: Program) -> Dict[str, Tuple[str, ...]]:
    """module rel → literal sites armed via ``fault_point("<site>")``
    (the defining module excluded — its internal calls are plumbing)."""
    out: Dict[str, Tuple[str, ...]] = {}
    for info in program:
        if info.rel == FAULTS_MODULE \
                or not info.rel.startswith("raft_tpu/"):
            continue
        sites: Set[str] = set()
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Call) and node.args \
                    and _call_name(node) == "fault_point" \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                sites.add(node.args[0].value)
        if sites:
            out[info.rel] = tuple(sorted(sites))
    return out


def parse_known_sites(program: Program) -> Optional[Dict[str, tuple]]:
    return module_literal(program.rel(FAULTS_MODULE), "KNOWN_SITES")


def parse_known_event_kinds(program: Program) -> Optional[Set[str]]:
    val = module_literal(program.rel(FLIGHT_MODULE),
                         "KNOWN_EVENT_KINDS")
    return {str(v) for v in val} if val is not None else None


def derive_emitter_kinds(program: Program) -> Dict[str, str]:
    """emitter name → flight kind: every top-level ``emit_*`` /
    ``record_*`` def in timeline.py whose body records a literal kind,
    plus the curated :data:`ALIAS_EMITTERS` bridges."""
    out = dict(ALIAS_EMITTERS)
    info = program.rel(TIMELINE_MODULE)
    if info is None:
        return out
    for node in info.tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith(("emit_", "record_")):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and _call_name(sub) == "record" and sub.args \
                    and isinstance(sub.args[0], ast.Constant) \
                    and isinstance(sub.args[0].value, str):
                out[node.name] = sub.args[0].value
                break
    return out


def derive_event_sites(program: Program,
                       emitters: Optional[Dict[str, str]] = None
                       ) -> Dict[str, Tuple[str, ...]]:
    """module rel → timeline emitters the module references (names ∩
    known emitters), for every raft_tpu/ module. This IS the event-
    site registry — check_instrumented's policy checks run on top."""
    emitters = (derive_emitter_kinds(program) if emitters is None
                else emitters)
    out: Dict[str, Tuple[str, ...]] = {}
    for info in program:
        if not info.rel.startswith("raft_tpu/"):
            continue
        if info.rel in (TIMELINE_MODULE, FLIGHT_MODULE):
            continue   # the vocabulary itself, not an emitting site
        refs = referenced_names(info.tree) & set(emitters)
        if refs:
            out[info.rel] = tuple(sorted(refs))
    return out


def derive_instrumented(program: Program) -> Dict[str, Tuple[str, ...]]:
    """module rel → module-level functions decorated ``@instrument``
    (bare, called, or attribute spelling)."""
    def _is_instrument(dec: ast.expr) -> bool:
        if isinstance(dec, ast.Call):
            dec = dec.func
        name = dotted(dec)
        return name is not None \
            and name.rsplit(".", 1)[-1] == "instrument"

    out: Dict[str, Tuple[str, ...]] = {}
    for info in program:
        if not info.rel.startswith("raft_tpu/"):
            continue
        funcs = tuple(sorted(
            n.name for n in info.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and any(_is_instrument(d) for d in n.decorator_list)))
        if funcs:
            out[info.rel] = funcs
    return out


def derive_quality_sites(program: Program) -> Dict[str, Tuple[str, ...]]:
    """module rel → quality recorders referenced (defining module
    excluded)."""
    out: Dict[str, Tuple[str, ...]] = {}
    for info in program:
        if not info.rel.startswith("raft_tpu/") \
                or info.rel == QUALITY_MODULE \
                or info.rel.endswith("__init__.py"):
            continue   # package __init__ re-exports record nothing
        refs = referenced_names(info.tree) & set(QUALITY_RECORDERS)
        if refs:
            out[info.rel] = tuple(sorted(refs))
    return out


def derive_env_knobs(program: Program) -> Dict[str, Set[str]]:
    """knob name → module rels whose source carries the bare literal
    (the registry module itself excluded — it IS the declaration)."""
    out: Dict[str, Set[str]] = {}
    for info in program:
        if info.rel == ENV_MODULE:
            continue
        for value, _line in string_constants(info.tree):
            if _KNOB_RE.match(value):
                out.setdefault(value, set()).add(info.rel)
    return out


def parse_env_registry(program: Program) -> Optional[Set[str]]:
    """Knob names declared in ``core/env.py``: first argument of every
    ``_knob(...)`` / ``Knob(...)`` call. None when the module is
    missing (pre-registry tree)."""
    info = program.rel(ENV_MODULE)
    if info is None:
        return None
    names: Set[str] = set()
    for node in ast.walk(info.tree):
        if isinstance(node, ast.Call) and node.args \
                and _call_name(node) in ("_knob", "Knob") \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            names.add(node.args[0].value)
    return names or None


def parse_readme_knobs(root: str) -> Optional[Set[str]]:
    import os
    path = os.path.join(root, README)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        text = f.read()
    marker = "## Environment knobs"
    start = text.find(marker)
    if start < 0:
        return None
    end = text.find("\n## ", start + len(marker))
    section = text[start:end if end > 0 else len(text)]
    return set(_README_KNOB_RE.findall(section))


@dataclasses.dataclass
class Registries:
    """Everything derived from source in one pass — what
    check_instrumented imports instead of redeclaring."""
    fault_sites: Dict[str, Tuple[str, ...]]
    known_sites: Optional[Dict[str, tuple]]
    emitter_kinds: Dict[str, str]
    event_sites: Dict[str, Tuple[str, ...]]
    known_event_kinds: Optional[Set[str]]
    instrumented: Dict[str, Tuple[str, ...]]
    quality_sites: Dict[str, Tuple[str, ...]]
    env_knobs: Dict[str, Set[str]]
    env_registry: Optional[Set[str]]
    readme_knobs: Optional[Set[str]]


def derive_registries(root: str,
                      program: Optional[Program] = None) -> Registries:
    if program is None:
        program = load_program(root, packages=SCAN_PACKAGES,
                               extra_files=EXTRA_SCAN_FILES)
    emitters = derive_emitter_kinds(program)
    return Registries(
        fault_sites=derive_fault_sites(program),
        known_sites=parse_known_sites(program),
        emitter_kinds=emitters,
        event_sites=derive_event_sites(program, emitters),
        known_event_kinds=parse_known_event_kinds(program),
        instrumented=derive_instrumented(program),
        quality_sites=derive_quality_sites(program),
        env_knobs=derive_env_knobs(program),
        env_registry=parse_env_registry(program),
        readme_knobs=parse_readme_knobs(root),
    )


# --------------------------------------------------------------- pass
class RegistryPass(AnalysisPass):
    name = "registry"

    def run(self, program: Program, graph: CallGraph) -> List[Finding]:
        del graph
        regs = derive_registries(program.root, program=program)
        findings: List[Finding] = []

        # -- fault sites ⊆ KNOWN_SITES ⊆ fault sites ------------------
        if regs.known_sites is None:
            findings.append(self.finding(
                "missing-registry", FAULTS_MODULE, 1,
                "faults.KNOWN_SITES dict literal not found — the "
                "fault-site registry is gone", where="KNOWN_SITES"))
        else:
            used: Dict[str, str] = {}
            for rel, sites in sorted(regs.fault_sites.items()):
                for site in sites:
                    used.setdefault(site, rel)
                    if site not in regs.known_sites:
                        findings.append(self.finding(
                            "unregistered-fault-site", rel, 1,
                            f"fault_point({site!r}) is armed here but "
                            f"{site!r} is not in faults.KNOWN_SITES — "
                            f"the injection matrix would never test "
                            f"it", where=f"{site}@{rel}"))
            for site in sorted(set(regs.known_sites) - set(used)):
                findings.append(self.finding(
                    "orphan-fault-site", FAULTS_MODULE, 1,
                    f"faults.KNOWN_SITES[{site!r}] is registered but "
                    f"no module arms fault_point({site!r}) — dead "
                    f"registry entry", where=site))

        # -- emitter kinds ⊆ KNOWN_EVENT_KINDS ⊆ emitter kinds --------
        if regs.known_event_kinds is None:
            findings.append(self.finding(
                "missing-registry", FLIGHT_MODULE, 1,
                "flight.KNOWN_EVENT_KINDS tuple not found — the "
                "event vocabulary is gone", where="KNOWN_EVENT_KINDS"))
        else:
            for emitter, kind in sorted(regs.emitter_kinds.items()):
                if kind not in regs.known_event_kinds:
                    findings.append(self.finding(
                        "unknown-event-kind", TIMELINE_MODULE, 1,
                        f"emitter {emitter}() records kind {kind!r} "
                        f"which is not in flight.KNOWN_EVENT_KINDS",
                        where=f"{emitter}:{kind}"))
            produced = set(regs.emitter_kinds.values())
            for kind in sorted(regs.known_event_kinds - produced):
                findings.append(self.finding(
                    "orphan-event-kind", FLIGHT_MODULE, 1,
                    f"KNOWN_EVENT_KINDS kind {kind!r} has no emitter "
                    f"in timeline.py — vocabulary entry nothing can "
                    f"produce", where=kind))

        # -- instrumented functions registered as hot paths ----------
        checker = program.rel(CHECKER_MODULE)
        hot_paths = module_literal(checker, "HOT_PATHS")
        if hot_paths is None:
            findings.append(self.finding(
                "missing-registry", CHECKER_MODULE, 1,
                "check_instrumented.HOT_PATHS dict literal not found",
                where="HOT_PATHS"))
        else:
            for rel, funcs in sorted(regs.instrumented.items()):
                missing = set(funcs) - set(hot_paths.get(rel, ()))
                for fn in sorted(missing):
                    findings.append(self.finding(
                        "unregistered-hot-path", rel, 1,
                        f"{fn}() is @instrument-decorated but absent "
                        f"from check_instrumented.HOT_PATHS[{rel!r}] "
                        f"— it would ship outside the tier-1 "
                        f"instrumentation gate", where=f"{rel}:{fn}"))

        # -- quality recorders registered ----------------------------
        quality_sites = module_literal(checker, "QUALITY_SITES") or {}
        for rel, refs in sorted(regs.quality_sites.items()):
            if rel not in quality_sites:
                findings.append(self.finding(
                    "unregistered-quality-site", rel, 1,
                    f"module references quality recorders "
                    f"({', '.join(refs)}) but has no "
                    f"check_instrumented.QUALITY_SITES entry",
                    where=rel, severity=WARNING))

        # -- env knobs: code ⊆ registry ⊆ README ---------------------
        if regs.env_registry is None:
            findings.append(self.finding(
                "missing-registry", ENV_MODULE, 1,
                "core/env.py knob registry not found — every "
                "RAFT_TPU_* knob must be declared there",
                where="KNOBS"))
        else:
            for knob in sorted(regs.env_knobs):
                if knob not in regs.env_registry:
                    rels = sorted(regs.env_knobs[knob])
                    findings.append(self.finding(
                        "unregistered-env-knob", rels[0], 1,
                        f"{knob} is read in code ({', '.join(rels)}) "
                        f"but not declared in core/env.KNOBS",
                        where=knob))
            if regs.readme_knobs is None:
                findings.append(self.finding(
                    "missing-registry", README, 1,
                    "README '## Environment knobs' table not found",
                    where="readme-knobs"))
            else:
                for knob in sorted(regs.env_registry
                                   - regs.readme_knobs):
                    findings.append(self.finding(
                        "undocumented-env-knob", ENV_MODULE, 1,
                        f"{knob} is declared in core/env.KNOBS but "
                        f"missing from the README env-knob table",
                        where=knob))
                for knob in sorted(regs.readme_knobs
                                   - regs.env_registry):
                    findings.append(self.finding(
                        "stale-readme-knob", README, 1,
                        f"README documents {knob} but core/env.KNOBS "
                        f"does not declare it — stale or misspelled "
                        f"row", where=knob))
                for knob in sorted(regs.env_registry
                                   - set(regs.env_knobs)):
                    findings.append(self.finding(
                        "orphan-env-knob", ENV_MODULE, 1,
                        f"{knob} is declared in core/env.KNOBS but "
                        f"never read anywhere in code",
                        where=knob, severity=WARNING))
        return findings


register_pass(RegistryPass)
