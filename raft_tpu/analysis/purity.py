"""Trace-purity pass: host-sync / retrace hazards inside traced code.

Entry points — the functions JAX will trace — are found statically:

- ``jax.jit(f)`` / ``jit(f)`` calls and ``@jit`` /
  ``@functools.partial(jax.jit, ...)`` decorators;
- ``jax.shard_map(f, ...)`` (incl. nested ``jit(shard_map(f))``);
- ``pl.pallas_call(kernel, ...)`` kernels;
- ``_aot_call(res, name, statics, fn, ...)`` — the runtime AOT entry
  (``fn`` is the traced callable, ``statics`` the compile-cache key).

The traced set is closed transitively over the call graph, plus a
fixpoint over control-flow combinators (``lax.scan`` / ``fori_loop`` /
``cond`` / ``vmap`` …): a function-valued argument to a combinator
called from traced code is itself traced. Bodies passed to the host
escapes (``pure_callback`` / ``io_callback`` / ``debug_callback``)
intentionally run on host and are exempt.

Hazards flagged inside the traced set:

=====================  ================================================
rule                   meaning
=====================  ================================================
host-sync-item         ``.item()`` / ``.tolist()`` on a traced value —
                       a device sync per call
host-sync-block        ``.block_until_ready()`` inside traced code
host-np-in-trace       ``np.asarray``/``np.array``/… on an expression
                       involving a traced argument (host transfer)
host-cast-in-trace     ``float()``/``int()``/``bool()`` on an
                       expression involving a traced argument
                       (ConcretizationTypeError or a silent sync)
host-time-in-trace     ``time.*`` — trace-time constant, NOT runtime
                       time; retraces bake a new value
host-rng-in-trace      ``random.*`` / ``np.random.*`` — host RNG baked
                       at trace time (use ``jax.random``)
env-read-in-trace      ``os.environ`` read — trace-time constant that
                       silently ignores later env changes
unhashable-static-key  list/dict/set flowing into the ``statics``
                       compile-cache key of ``_aot_call`` — the
                       post-warmup-compile-miss gate, made static
=====================  ================================================
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FunctionInfo
from .framework import AnalysisPass, Finding, register_pass
from .loader import ModuleInfo, Program, dotted

#: wrappers whose function argument is ALWAYS traced
TRACE_WRAPPERS = ("jit", "shard_map", "pallas_call")
#: combinators whose function arguments are traced when the CALL SITE
#: is already inside traced code
COMBINATORS = ("fori_loop", "scan", "while_loop", "cond", "switch",
               "map", "vmap", "pmap", "checkpoint", "remat",
               "associative_scan", "custom_jvp", "custom_vjp")
#: host escapes: their callables intentionally run host-side
HOST_ESCAPES = ("pure_callback", "io_callback", "debug_callback",
                "callback", "host_callback")
#: the runtime AOT entry: positional index of the traced callable and
#: of the compile-cache statics tuple in ``_aot_call(res, name,
#: statics, fn, *args)``
AOT_ENTRY, AOT_FN_ARG, AOT_STATICS_ARG = "_aot_call", 3, 2

_SYNC_ATTRS = {"item": "host-sync-item", "tolist": "host-sync-item",
               "block_until_ready": "host-sync-block"}
_NP_CONVERSIONS = {"numpy.asarray", "numpy.array",
                   "numpy.ascontiguousarray", "numpy.asfortranarray",
                   "numpy.copy"}
_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
               "time.sleep", "time.process_time", "time.time_ns",
               "time.perf_counter_ns", "time.monotonic_ns"}
_CASTS = {"float", "int", "bool"}


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _is_entry(name: Optional[str]) -> Optional[str]:
    """canonical dotted callee → wrapper kind, or None."""
    if name is None:
        return None
    last = _last(name)
    if last in TRACE_WRAPPERS:
        return last
    if last == AOT_ENTRY:
        return AOT_ENTRY
    return None


def _unwrap_fn_exprs(call: ast.Call, kind: str,
                     canonical) -> List[ast.expr]:
    """The function-valued expressions an entry call traces. Nested
    wrappers unwrap (``jit(shard_map(f, ...))`` → ``f``)."""
    if kind == AOT_ENTRY:
        args = call.args[AOT_FN_ARG:AOT_FN_ARG + 1]
    elif _last(canonical(call.func) or "") == "partial":
        args = call.args[1:2]
    else:
        args = call.args[:1]
    out: List[ast.expr] = []
    for a in args:
        while isinstance(a, ast.Call):
            name = canonical(a.func)
            inner = _is_entry(name)
            if inner is None and _last(name or "") not in COMBINATORS \
                    and _last(name or "") != "partial":
                break
            nxt = (a.args[AOT_FN_ARG] if inner == AOT_ENTRY
                   and len(a.args) > AOT_FN_ARG else
                   a.args[1] if _last(name or "") == "partial"
                   and len(a.args) > 1 else
                   a.args[0] if a.args else None)
            if nxt is None:
                break
            a = nxt
        out.append(a)
    return out


#: attribute chains that are STATIC under tracing (shape metadata) —
#: ``int(x.shape[0])`` concretizes nothing
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize",
                 "nbytes"}


def _mentions_traced(node: ast.expr, names: Set[str]) -> bool:
    """True when the expression mentions one of ``names`` OUTSIDE a
    static metadata chain (``.shape``/``.ndim``/…, ``len(...)``)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            continue
        if isinstance(n, ast.Name) and n.id in names:
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


def _param_names(node: ast.AST) -> Set[str]:
    a = node.args
    params = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        params.append(a.vararg.arg)
    if a.kwarg:
        params.append(a.kwarg.arg)
    return {p for p in params if p not in ("self", "cls")}


class TracePurityPass(AnalysisPass):
    name = "trace-purity"

    # -- root discovery ----------------------------------------------
    def _resolve_expr(self, graph: CallGraph, info: ModuleInfo,
                      scope: Tuple[str, ...], cls: Optional[str],
                      expr: ast.expr) -> Optional[str]:
        name = dotted(expr)
        if name is None:
            return None
        return graph.resolve(info, scope, name, cls=cls)

    def _roots(self, program: Program, graph: CallGraph
               ) -> Dict[str, str]:
        """qualname → entry-kind for every statically-traced root."""
        roots: Dict[str, str] = {}

        def _add(qual: Optional[str], kind: str) -> None:
            if qual is not None:
                roots.setdefault(qual, kind)

        # decorators: @jit / @jax.jit / @partial(jax.jit, ...)
        for fn in graph.functions.values():
            canon = lambda e, _m=fn.module: (  # noqa: E731
                graph.canonical(_m, dotted(e)) if dotted(e) else None)
            for dec in getattr(fn.node, "decorator_list", ()):
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = canon(target)
                if name and _last(name) == "partial" \
                        and isinstance(dec, ast.Call) and dec.args:
                    name = canon(dec.args[0])
                if name and _last(name) in TRACE_WRAPPERS:
                    _add(fn.qual, _last(name))
        # call expressions inside functions
        for fn in graph.functions.values():
            for site in graph.iter_calls(fn.qual):
                name = (site.external if site.external else None)
                if site.resolved and _last(site.resolved) == AOT_ENTRY:
                    name = AOT_ENTRY
                kind = _is_entry(name)
                if kind is None:
                    continue
                for expr in _unwrap_fn_exprs(
                        site.node, kind,
                        lambda e, _m=fn.module: (
                            graph.canonical(_m, dotted(e))
                            if dotted(e) else None)):
                    _add(self._resolve_expr(graph, fn.module, fn.path,
                                            fn.cls, expr), kind)
        # module-level entry calls (fn = jax.jit(_core) at import time)
        for info in program:
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                canonical = (graph.canonical(info, name)
                             if name else None)
                kind = _is_entry(canonical)
                if kind is None:
                    continue
                for expr in _unwrap_fn_exprs(
                        node, kind,
                        lambda e, _m=info: (
                            graph.canonical(_m, dotted(e))
                            if dotted(e) else None)):
                    n2 = dotted(expr)
                    if n2:
                        _add(graph.resolve(info, (), n2), kind)
        return roots

    def _traced_set(self, program: Program, graph: CallGraph,
                    roots: Dict[str, str]) -> Set[str]:
        """Transitive closure + combinator fixpoint."""
        traced = graph.reachable(roots)
        while True:
            new: Set[str] = set()
            for qual in traced:
                fn = graph.functions[qual]
                for site in graph.iter_calls(qual):
                    name = site.external or ""
                    if _last(name) not in COMBINATORS:
                        continue
                    for arg in site.node.args:
                        q2 = self._resolve_expr(graph, fn.module,
                                                fn.path, fn.cls, arg)
                        if q2 is not None and q2 not in traced:
                            new.add(q2)
            if not new:
                return traced
            traced |= graph.reachable(new)

    # -- hazard scan --------------------------------------------------
    def _escape_spans(self, fn: FunctionInfo, graph: CallGraph
                      ) -> List[ast.expr]:
        """Argument expressions of host-escape calls — hazard scans
        skip anything lexically inside them."""
        out: List[ast.expr] = []
        for site in graph.iter_calls(fn.qual):
            if _last(site.external or "") in HOST_ESCAPES:
                out.extend(site.node.args)
        return out

    def _scan(self, fn: FunctionInfo, graph: CallGraph,
              kind: str, is_root: bool) -> List[Finding]:
        findings: List[Finding] = []
        # parameters are PROVABLY traced only in root functions (jit /
        # shard_map / pallas operands); transitive callees may receive
        # static config values, so the param-based cast/conversion
        # rules stay root-only to keep the signal clean
        params = _param_names(fn.node) if is_root else set()
        skip_nodes = set()
        for span in self._escape_spans(fn, graph):
            skip_nodes.update(id(n) for n in ast.walk(span))

        def _flag(rule: str, node: ast.AST, msg: str,
                  anchor: str) -> None:
            findings.append(self.finding(
                rule, fn.module.rel, node.lineno,
                f"{msg} inside traced code (reached from a {kind} "
                f"entry via {fn.qual})",
                where=f"{fn.qual}#{anchor}"))

        for site in graph.iter_calls(fn.qual):
            node = site.node
            if id(node) in skip_nodes:
                continue
            name = site.external
            if name is None:
                continue
            last = _last(name)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_ATTRS:
                _flag(_SYNC_ATTRS[node.func.attr], node,
                      f"`.{node.func.attr}()` forces a device→host "
                      f"sync", node.func.attr)
            elif name in _NP_CONVERSIONS and node.args \
                    and _mentions_traced(node.args[0], params):
                _flag("host-np-in-trace", node,
                      f"`{name}` on a traced argument pulls the value "
                      f"to host", last)
            elif name in _TIME_CALLS:
                _flag("host-time-in-trace", node,
                      f"`{name}()` is a trace-time constant, not "
                      f"runtime time", last)
            elif (name.startswith("random.")
                  or name.startswith("numpy.random.")):
                _flag("host-rng-in-trace", node,
                      f"`{name}()` bakes host randomness at trace "
                      f"time (use jax.random)", last)
            elif name in _CASTS and len(node.args) == 1 \
                    and _mentions_traced(node.args[0], params):
                _flag("host-cast-in-trace", node,
                      f"`{name}()` on a traced argument concretizes "
                      f"it", name)
        # os.environ access (read or subscript — not only calls);
        # nested defs carry their own scan, so skip their subtrees
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn.node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) or id(node) in skip_nodes:
                continue
            if isinstance(node, ast.Attribute) \
                    and node.attr == "environ" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "os":
                _flag("env-read-in-trace", node,
                      "`os.environ` read is a trace-time constant",
                      "environ")
            stack.extend(ast.iter_child_nodes(node))
        return findings

    def _static_key_hazards(self, graph: CallGraph) -> List[Finding]:
        """list/dict/set literals flowing into the ``statics``
        compile-cache key of an ``_aot_call`` — unhashable keys break
        the compile cache (a miss per dispatch)."""
        findings: List[Finding] = []
        for fn in graph.functions.values():
            for site in graph.iter_calls(fn.qual):
                name = site.resolved or site.external or ""
                if _last(name.split(":")[-1]) != AOT_ENTRY:
                    continue
                if len(site.node.args) <= AOT_STATICS_ARG:
                    continue
                statics = site.node.args[AOT_STATICS_ARG]
                for sub in ast.walk(statics):
                    if isinstance(sub, (ast.List, ast.Dict, ast.Set,
                                        ast.ListComp, ast.SetComp,
                                        ast.DictComp)):
                        findings.append(self.finding(
                            "unhashable-static-key", fn.module.rel,
                            sub.lineno,
                            f"unhashable {type(sub).__name__.lower()} "
                            f"in the statics compile-cache key of "
                            f"_aot_call — every dispatch would be a "
                            f"compile miss (or a TypeError)",
                            where=f"{fn.qual}#statics"))
                        break
        return findings

    # -- entry ---------------------------------------------------------
    def run(self, program: Program, graph: CallGraph) -> List[Finding]:
        roots = self._roots(program, graph)
        traced = self._traced_set(program, graph, roots)
        findings: List[Finding] = []
        for qual in sorted(traced):
            fn = graph.functions[qual]
            kind = roots.get(qual, "traced-callee")
            findings.extend(self._scan(fn, graph, kind,
                                       is_root=qual in roots))
        findings.extend(self._static_key_hazards(graph))
        # roots may live anywhere (bench drivers jit too) but findings
        # gate the library tree only
        return [f for f in findings if f.rel.startswith("raft_tpu/")]

    # exposed for tests / the CLI's --explain
    def traced_functions(self, program: Program,
                         graph: CallGraph) -> Set[str]:
        return self._traced_set(program, graph,
                                self._roots(program, graph))


register_pass(TracePurityPass)
