"""Pass registry + the finding/severity model.

A pass is a callable ``run(program, graph) -> List[Finding]`` with a
``name``; :func:`run_passes` builds the program + call graph once and
feeds every requested pass. Findings carry a line for humans and a
line-independent *fingerprint* for the baseline file — a suppressed
finding stays suppressed across unrelated edits, and a genuinely new
one fails the gate.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

from .callgraph import CallGraph, build_call_graph
from .loader import Program, load_program

ERROR, WARNING = "error", "warning"


@dataclasses.dataclass
class Finding:
    """One diagnostic. ``rule`` is the stable machine name
    (``host-sync-item``, ``lock-order-inversion``, …); ``where`` is the
    stable location token (function qualname, registry key, …) the
    fingerprint uses INSTEAD of the line number."""
    pass_name: str
    rule: str
    rel: str              # repo-relative file
    line: int
    message: str
    where: str = ""       # qualname / key — line-independent anchor
    severity: str = ERROR

    @property
    def fingerprint(self) -> str:
        return f"{self.pass_name}:{self.rule}:{self.rel}:{self.where}"

    def render(self) -> str:
        sev = "" if self.severity == ERROR else f" [{self.severity}]"
        return (f"{self.rel}:{self.line}: {self.rule}{sev}: "
                f"{self.message}")


class AnalysisPass:
    """Base class: subclasses set ``name`` and implement ``run``."""
    name = "?"

    def run(self, program: Program, graph: CallGraph) -> List[Finding]:
        raise NotImplementedError

    def finding(self, rule: str, rel: str, line: int, message: str,
                where: str = "", severity: str = ERROR) -> Finding:
        return Finding(pass_name=self.name, rule=rule, rel=rel,
                       line=line, message=message, where=where,
                       severity=severity)


_PASSES: Dict[str, Callable[[], AnalysisPass]] = {}


def register_pass(factory: Callable[[], AnalysisPass]) -> None:
    _PASSES[factory().name] = factory


def all_passes() -> List[str]:
    _ensure_registered()
    return sorted(_PASSES)


def _ensure_registered() -> None:
    # the flagship passes self-register on import; imported here (not
    # at module top) so framework ↔ pass modules stay cycle-free.
    # Unconditional: a partial registry (e.g. only the registry pass,
    # pulled in by the package __init__) must still complete
    from . import locks, purity, registry  # noqa: F401


def run_passes(root: str, names: Optional[Sequence[str]] = None,
               program: Optional[Program] = None,
               graph: Optional[CallGraph] = None
               ) -> Dict[str, List[Finding]]:
    """Run the named passes (default: all) over ``root``. Returns
    pass name → findings, deterministically ordered."""
    _ensure_registered()
    if program is None:
        from .registry import EXTRA_SCAN_FILES, SCAN_PACKAGES
        program = load_program(root, packages=SCAN_PACKAGES,
                               extra_files=EXTRA_SCAN_FILES)
    if graph is None:
        graph = build_call_graph(program)
    out: Dict[str, List[Finding]] = {}
    for name in (names if names is not None else all_passes()):
        if name not in _PASSES:
            raise KeyError(f"graftlint: unknown pass {name!r} "
                           f"(have: {', '.join(all_passes())})")
        findings = sorted(_PASSES[name]().run(program, graph),
                          key=lambda f: (f.rel, f.line, f.rule,
                                         f.where))
        # one finding per fingerprint: several call sites can reach
        # the same hazard; the baseline suppresses them as one
        seen, unique = set(), []
        for f in findings:
            if f.fingerprint not in seen:
                seen.add(f.fingerprint)
                unique.append(f)
        out[name] = unique
    return out
