"""Module loader: parse the whole ``raft_tpu`` tree into one program.

A :class:`Program` is the unit every pass runs over: each ``*.py``
file under the analyzed packages parsed into an ``ast.Module`` with
its repo-relative path, dotted module name and source lines kept
alongside, plus the per-module symbol table (what every imported name
resolves to) the call-graph builder consumes.

Stdlib-only by design — the tools load this package without importing
``raft_tpu`` (no jax needed), so the gate runs on any checkout.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

#: packages walked by default (repo-relative); single files may be
#: added via ``extra_files`` (bench.py, tools/*.py for registry diffs)
DEFAULT_PACKAGES: Tuple[str, ...] = ("raft_tpu",)


@dataclasses.dataclass
class ModuleInfo:
    """One parsed source file."""
    rel: str                 # repo-relative posix path
    name: str                # dotted module name ("raft_tpu.core.env")
    path: str                # absolute path
    tree: ast.Module
    source: str

    #: import symbol table: local name → dotted target. ``import x.y``
    #: binds "x" → "x"; ``import x.y as z`` binds "z" → "x.y";
    #: ``from x import y as w`` binds "w" → "x.y".
    symbols: Dict[str, str] = dataclasses.field(default_factory=dict)

    def __repr__(self) -> str:  # keep pytest diffs readable
        return f"ModuleInfo({self.rel})"


@dataclasses.dataclass
class Program:
    """Every parsed module, indexed both ways."""
    root: str
    modules: Dict[str, ModuleInfo]      # dotted name → info
    by_rel: Dict[str, ModuleInfo]       # repo-relative path → info

    def __iter__(self) -> Iterator[ModuleInfo]:
        return iter(self.modules.values())

    def get(self, name: str) -> Optional[ModuleInfo]:
        return self.modules.get(name)

    def rel(self, rel: str) -> Optional[ModuleInfo]:
        return self.by_rel.get(rel)


def _module_name(rel: str) -> str:
    parts = rel[:-3].split("/")          # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _symbol_table(tree: ast.Module, modname: str) -> Dict[str, str]:
    """Local name → dotted target for every import in the module
    (module-level and nested — deferred imports inside functions are
    how this tree breaks cycles, so they resolve too)."""
    symbols: Dict[str, str] = {}
    pkg_parts = modname.split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    symbols[a.asname] = a.name
                else:
                    symbols[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import → absolute
                base = pkg_parts[:len(pkg_parts) - node.level]
                mod = ".".join(base + ([node.module]
                                       if node.module else []))
            else:
                mod = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                symbols[a.asname or a.name] = (f"{mod}.{a.name}"
                                               if mod else a.name)
    return symbols


def load_program(root: str,
                 packages: Sequence[str] = DEFAULT_PACKAGES,
                 extra_files: Sequence[str] = ()) -> Program:
    """Parse every ``*.py`` under ``packages`` (plus ``extra_files``)
    into a :class:`Program`. Unparseable files raise — a syntax error
    anywhere in the tree is itself a finding-worthy failure, surfaced
    loudly rather than skipped."""
    modules: Dict[str, ModuleInfo] = {}
    by_rel: Dict[str, ModuleInfo] = {}

    def _add(rel: str) -> None:
        path = os.path.join(root, rel)
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=rel)
        name = _module_name(rel)
        info = ModuleInfo(rel=rel, name=name, path=path, tree=tree,
                          source=source,
                          symbols=_symbol_table(tree, name))
        modules[name] = info
        by_rel[rel] = info

    for pkg in packages:
        pkg_dir = os.path.join(root, pkg)
        if not os.path.isdir(pkg_dir):
            continue
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames
                                 if not d.startswith((".", "__pycache"))
                                 )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn),
                                      root).replace(os.sep, "/")
                _add(rel)
    for rel in extra_files:
        if os.path.exists(os.path.join(root, rel)):
            _add(rel.replace(os.sep, "/"))
    return Program(root=root, modules=modules, by_rel=by_rel)


# ---------------------------------------------------------------- scans
def iter_functions(info: ModuleInfo
                   ) -> Iterator[Tuple[str, ast.AST]]:
    """Yield ``(qualname, def-node)`` for every function in the module,
    methods and nested defs included. Qualnames are
    ``"pkg.mod:Outer.inner"`` — the ``:`` separates module from the
    in-module path so passes can split unambiguously."""
    def _walk(node: ast.AST, prefix: str) -> Iterator[Tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield f"{info.name}:{q}", child
                yield from _walk(child, q)
            elif isinstance(child, ast.ClassDef):
                q = (f"{prefix}.{child.name}" if prefix
                     else child.name)
                yield from _walk(child, q)
            else:
                yield from _walk(child, prefix)
    yield from _walk(info.tree, "")


def dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` attribute/name chain → ``"a.b.c"`` (None when the
    chain bottoms out in a call/subscript — dynamic, unresolvable)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def string_constants(tree: ast.AST) -> Iterator[Tuple[str, int]]:
    """Every string constant with its line (f-string parts included)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, getattr(node, "lineno", 0)
