"""graftlint — whole-program AST analysis for the raft_tpu tree.

The RAFT heritage ships clang-tidy + pre-commit as first-class
infrastructure; this package is the TPU-native equivalent: a module
loader + call-graph builder over the ``raft_tpu/`` packages, a pass
registry with a finding/severity model, and a baseline-suppression
file, fronted by ``tools/graftlint.py`` and wired into tier-1.

Flagship passes
---------------
``trace-purity``
    Computes the set of functions reachable from ``jit`` /
    ``shard_map`` / ``pallas_call`` / ``_aot_call`` entry points and
    flags host-sync and retrace hazards inside them (``.item()``,
    ``float()/int()`` on traced values, ``np.asarray``,
    ``.block_until_ready()``, ``time.*``/RNG calls, ``os.environ``
    reads, unhashable values flowing into static compile-cache keys).

``lock-discipline``
    Extracts the lock-acquisition graph from the threaded planes and
    reports lock-order inversions, blocking calls (``fsync``, joins,
    waits, host syncs) while holding a lock, and module-level mutable
    state written from two or more thread roots with no lock in scope.

``registry``
    Derives fault sites, timeline-emitter kinds, quality sites, env
    knobs, and instrumented hot paths *from source* and diffs them
    against ``faults.KNOWN_SITES``, ``flight.KNOWN_EVENT_KINDS``, the
    ``core/env.py`` knob registry, the README env-knob table, and
    ``tools/check_instrumented.py``'s curated tables — a new subsystem
    can never ship half-registered.

The package is deliberately stdlib-only (``ast`` + ``os``): the tools
load it standalone (no ``raft_tpu``/jax import) via
``importlib``, so the gate runs anywhere the source tree exists.
"""

from .framework import (AnalysisPass, Finding, all_passes,  # noqa: F401
                        run_passes)
from .baseline import Baseline  # noqa: F401
from .loader import Program, load_program  # noqa: F401
from .callgraph import CallGraph, build_call_graph  # noqa: F401
from . import registry  # noqa: F401  (derived-registry surface for tools)

__all__ = [
    "AnalysisPass", "Finding", "Baseline", "Program", "CallGraph",
    "load_program", "build_call_graph", "run_passes", "all_passes",
]
