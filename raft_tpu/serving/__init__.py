"""raft_tpu.serving — the online micro-batching query engine (ISSUE 7).

The production front door over the KNN stack: a thread-safe request
queue coalescing arriving queries into dynamic micro-batches padded to
a small pre-AOT-compiled bucket ladder (no request ever pays a
trace/compile after warm-up), per-request admission control + deadline
scopes reusing the resilience runtime (overload SHEDS instead of
queueing unboundedly), immutable index snapshots with background
rebuild-and-swap, and the PR-4 query-sharded replicated-index mode as
the multi-chip data plane.

- :class:`~raft_tpu.serving.engine.ServingEngine` — the engine.
- :mod:`~raft_tpu.serving.buckets` — the bucket ladder
  (``RAFT_TPU_SERVING_BUCKETS``).
- :mod:`~raft_tpu.serving.snapshot` — immutable snapshots +
  :class:`~raft_tpu.serving.snapshot.SnapshotStore`.

SLO evidence: ``benchmarks/bench_serving.py`` drives a closed-loop
Poisson load through the engine and writes ``BENCH_SERVING.json``
(p50/p99 latency, throughput, shed/compile-miss counts), gated by
``tools/bench_report.py --check`` like the other artifacts.
"""

from raft_tpu.serving.buckets import (bucket_for, bucket_ladder,
                                      default_bucket_ladder)
from raft_tpu.serving.engine import (BATCHES, LATENCY, QUEUE_DEPTH,
                                     REQUESTS, SHED, OverloadShedError,
                                     RequestTooLargeError, ServingEngine,
                                     ServingFuture, execute_batch)
from raft_tpu.serving.snapshot import (IndexSnapshot, SnapshotStore,
                                       build_snapshot)

__all__ = [
    "BATCHES",
    "LATENCY",
    "QUEUE_DEPTH",
    "REQUESTS",
    "SHED",
    "IndexSnapshot",
    "OverloadShedError",
    "RequestTooLargeError",
    "ServingEngine",
    "ServingFuture",
    "SnapshotStore",
    "bucket_for",
    "bucket_ladder",
    "build_snapshot",
    "default_bucket_ladder",
    "execute_batch",
]
