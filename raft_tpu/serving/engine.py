"""The closed-loop micro-batching query engine (ISSUE 7 tentpole).

The repo's front door so far is a library call — one caller, one batch.
This module is the millions-of-users shape: a thread-safe request queue
that COALESCES arriving queries into dynamic micro-batches, pads each
batch up to the bucket ladder (:mod:`raft_tpu.serving.buckets` — a
small fixed set of pre-AOT-compiled shapes, warmed at engine start via
``runtime.entry_points.knn_query``, so no live request ever pays a
trace/compile), and dispatches them against an immutable
:class:`~raft_tpu.serving.snapshot.IndexSnapshot` (background
rebuild-and-swap for updates — readers never block on a swap).

Resilience is the PR-5 runtime, reused:

- per-request **admission control**: an oversized request (> the top
  bucket) is rejected with a classified :class:`RequestTooLargeError`
  (never silently truncated); a full queue **sheds** the request with
  :class:`OverloadShedError` — recorded as a NEW degradation-ladder
  rung (``shed:overload``) rather than letting latency grow into a
  hang; a request whose deadline expires while still queued is failed
  with ``DeadlineExceededError`` at batch-assembly time instead of
  wasting a dispatch.
- per-batch :func:`raft_tpu.resilience.deadline` scopes: the batcher
  thread arms the MINIMUM remaining budget across the batch, so a hung
  dispatch converts into a typed error within one poll interval. The
  thread-safe re-entrant token rework (this PR) is what makes per-batch
  scopes on a worker thread safe next to callers' own scopes.
- fault sites ``serving_enqueue`` / ``serving_flush`` make both halves
  of the pipe injectable (``RAFT_TPU_FAULTS``).

Observability: every admitted request, flush, shed and swap emits a
``serving`` flight-recorder event (:func:`raft_tpu.observability.
timeline.emit_serving`); queue depth is a live gauge, request latency a
p50/p99-capable histogram, and every batch/bucket/shed transition a
labeled counter through the MetricsRegistry — the evidence surface
``benchmarks/bench_serving.py`` turns into the ``BENCH_SERVING.json``
SLO artifact.

Quality plane (ISSUE 10):

- **per-request flow tracing** — every admitted request gets a
  monotonic id at enqueue and emits Perfetto flow points
  (:func:`~raft_tpu.observability.timeline.emit_flow`): ``s`` on the
  client thread at enqueue, ``t`` steps through batch assembly and
  dispatch on the batcher thread, ``f`` at the terminus — so one
  request renders as ONE connected flow across lanes in the trace, and
  shed / queue-expiry / requeue / deadline outcomes annotate the
  terminus instead of vanishing into counters.
- **online recall shadow-sampling** — a configurable fraction of live
  requests (``RAFT_TPU_SERVING_SHADOW_FRAC`` or ``shadow_frac=``) is
  re-scored against the exact brute-force oracle on a background
  thread (:class:`~raft_tpu.observability.quality.ShadowSampler`);
  the rolling recall@k gauge plus a ``drift`` flight event below the
  floor is the ONLINE counterpart of the offline ANN recall gate — an
  index swap or a bad ``RAFT_TPU_ANN_NPROBES`` can no longer silently
  degrade answers between benchmark rounds.

Env knobs (see README "Serving & SLO workflow" + "Quality telemetry
& request tracing"):

- ``RAFT_TPU_SERVING_BUCKETS``   — bucket ladder (buckets.py)
- ``RAFT_TPU_SERVING_FLUSH_MS``  — flush window for a partial batch
  (default 2 ms: the oldest queued request never waits longer than
  this for co-riders before dispatching)
- ``RAFT_TPU_SERVING_QUEUE_CAP`` — max queued QUERY ROWS before
  admission sheds (default 4096)
- ``RAFT_TPU_SERVING_DEADLINE_S`` — default per-request deadline
  budget (unset = requests carry no deadline unless submitted with one)
- ``RAFT_TPU_SERVING_SHADOW_FRAC`` / ``RAFT_TPU_SERVING_SHADOW_FLOOR``
  — shadow-sampling fraction (0 = off) and recall floor (0.95)
- ``RAFT_TPU_DURABLE_DIR`` / ``RAFT_TPU_WAL_SYNC`` — the durability
  plane's directory (``durable=True``) and WAL fsync policy
  (``always`` / ``batch`` [default] / ``none`` — README "Durability &
  recovery")
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from raft_tpu.core import env, interruptible
from raft_tpu.core.error import (DeadlineExceededError, LogicError,
                                 RaftException, expects)
from raft_tpu.core.logger import log_warn
from raft_tpu.core.resources import ensure_resources
from raft_tpu.observability import instrument
from raft_tpu.observability.metrics import percentile
from raft_tpu.observability.quality import (ShadowSampler,
                                            shadow_floor_default,
                                            shadow_frac_default)
from raft_tpu.observability.timeline import emit_flow, emit_serving
from raft_tpu.resilience import deadline, fault_point, record_degradation
from raft_tpu.serving.buckets import bucket_for, bucket_ladder
from raft_tpu.serving.snapshot import IndexSnapshot, SnapshotStore

# metric names (the serving slice of the registry vocabulary)
REQUESTS = "raft_tpu_serving_requests_total"
LATENCY = "raft_tpu_serving_latency_seconds"
QUEUE_DEPTH = "raft_tpu_serving_queue_rows"
BATCHES = "raft_tpu_serving_batches_total"
BATCH_PAD_ROWS = "raft_tpu_serving_batch_pad_rows_total"
SHED = "raft_tpu_serving_shed_total"

FLUSH_MS_ENV = "RAFT_TPU_SERVING_FLUSH_MS"
QUEUE_CAP_ENV = "RAFT_TPU_SERVING_QUEUE_CAP"
DEADLINE_ENV = "RAFT_TPU_SERVING_DEADLINE_S"

#: bounded retries for requests bumped out of a batch by a NEIGHBOR's
#: deadline firing (the request itself still has budget) — one requeue,
#: then honest failure
_MAX_REQUEUES = 1


class RequestTooLargeError(LogicError):
    """Request exceeds the largest bucket of the serving ladder —
    rejected at admission (classified, never silently truncated; split
    client-side or raise the ladder via RAFT_TPU_SERVING_BUCKETS)."""


class OverloadShedError(RaftException):
    """Admission control shed this request: the queue is at its row
    cap. Shedding is the engine's overload degradation rung — callers
    back off / retry; the engine never converts overload into unbounded
    queueing latency."""


class ServingFuture:
    """Completion handle for one submitted request."""

    __slots__ = ("_event", "_vals", "_ids", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._vals = None
        self._ids = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def _complete(self, vals, ids) -> None:
        self._vals, self._ids = vals, ids
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._event.wait(timeout):
            raise TimeoutError("serving request still pending")
        return self._error

    def result(self, timeout: Optional[float] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Block for this request's (values [n, k], ids [n, k]);
        re-raises the request's classified failure."""
        if not self._event.wait(timeout):
            raise TimeoutError("serving request still pending")
        if self._error is not None:
            raise self._error
        return self._vals, self._ids


class _Request:
    __slots__ = ("x", "n", "enqueued_at", "deadline_at", "future",
                 "requeues", "rid", "kind", "ids", "explain")

    def __init__(self, x, n, enqueued_at, deadline_at, future,
                 rid=0, kind="query", ids=None, explain=False):
        self.x = x
        self.n = n
        self.enqueued_at = enqueued_at
        self.deadline_at = deadline_at
        self.future = future
        self.requeues = 0
        self.rid = rid          # monotonic flow-trace id (enqueue order)
        self.kind = kind        # "query" | "upsert" | "delete"
        self.ids = ids          # external row ids (mutation requests)
        self.explain = explain  # capture an explain record for the
        #                         batch this request rides


@instrument("serving.execute_batch")
def execute_batch(plane, snap: IndexSnapshot, x: np.ndarray, bucket: int,
                  n_valid: int, budget_s: Optional[float] = None
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Dispatch ONE coalesced micro-batch against one snapshot.

    ``x`` [n_valid, d] is the concatenated request rows; it is padded
    up to ``bucket`` (a pre-warmed shape — see the module doc) and run
    through the engine's data ``plane``. ``budget_s`` (the minimum
    remaining request budget) arms a :func:`deadline` scope on THIS
    thread; the completion wait polls the cancellation token, so a hung
    dispatch converts instead of blocking the batcher forever. Carries
    the ``serving_flush`` fault site — OOM/error/timeout/hang at the
    flush are all injectable without touching the engine."""
    emit_serving("flush", bucket=bucket, rows=n_valid,
                 generation=snap.generation,
                 budget_s=budget_s)
    from raft_tpu.distance.knn_fused import pad_query_rows

    xp = pad_query_rows(x, bucket)

    def _dispatch():
        # the fault site sits INSIDE the deadline scope: an injected
        # hang here must be cancellable exactly like a real stuck
        # dispatch (the scope converts it within one poll interval)
        fault_point("serving_flush")
        vals, ids = plane(snap, xp)
        interruptible.synchronize(vals, ids)
        return vals, ids

    if budget_s is not None:
        with deadline(budget_s, label="serving_flush"):
            vals, ids = _dispatch()
    else:
        vals, ids = _dispatch()
    return np.asarray(vals)[:n_valid], np.asarray(ids)[:n_valid]


class ServingEngine:
    """Dynamic micro-batching KNN serving engine.

    ``index`` may be a prepared :class:`~raft_tpu.distance.knn_fused.
    KnnIndex` or a raw [m, d] matrix (prepared at construction).
    ``mesh`` switches the data plane from the single-device AOT entry
    (``runtime.knn_query``) to the PR-4 query-sharded replicated-index
    mode (``knn_fused_sharded(shard_mode="query")``) — data-parallel
    queries over the mesh axis, zero cross-shard merge traffic.

    Lifecycle::

        eng = ServingEngine(index, k=64)
        eng.start()                      # warms every bucket (AOT)
        fut = eng.submit(q, deadline_s=0.05)
        vals, ids = fut.result()
        eng.update_index(new_y)          # background rebuild-and-swap
        eng.stop()

    ``clock`` is injectable (tests/benchmarks pin a deterministic
    clock for deadline/ageing accounting; the batcher's waits stay
    real-time ticks).
    """

    def __init__(self, index, k: int, *, res=None, mesh=None,
                 axis: str = "x",
                 buckets: Union[str, Sequence[int], None] = None,
                 flush_interval_s: Optional[float] = None,
                 max_queue_rows: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 passes: int = 3, metric: str = "l2",
                 T: Optional[int] = None, Qb: Optional[int] = None,
                 g: Optional[int] = None,
                 grid_order: Optional[str] = None,
                 store_yp: bool = True,
                 rescore: Optional[bool] = None,
                 certify: str = "kernel",
                 algorithm: str = "brute",
                 n_lists: Optional[int] = None,
                 n_probes: Optional[int] = None,
                 pq_dim: Optional[int] = None,
                 pq_bits: Optional[int] = None,
                 db_dtype: Optional[str] = None,
                 shadow_frac: Optional[float] = None,
                 shadow_floor: Optional[float] = None,
                 mutable: bool = False,
                 index_ids=None,
                 compact_threshold: Optional[int] = None,
                 delta_cap: Optional[int] = None,
                 durable: bool = False,
                 durable_dir: Optional[str] = None,
                 wal_sync: Optional[str] = None,
                 explain_frac: Optional[float] = None,
                 debug_port: Optional[int] = None,
                 blackbox_path: Optional[str] = None,
                 watchdog_s: Optional[float] = None,
                 slo=None,
                 clock=time.monotonic):
        from raft_tpu.ann import IvfFlatIndex
        from raft_tpu.distance.knn_fused import KnnIndex

        # algorithm="ivf_flat": the SnapshotStore holds an IVF snapshot
        # (built via ann.build_ivf_flat, swapped like any other) and
        # the data plane serves APPROXIMATE queries through
        # ann.search_ivf_flat behind the exact same bucket ladder —
        # the speed/recall knob (n_probes) rides the serving tier.
        # algorithm="ivf_pq" is the compressed tier on the same plane:
        # ann.build_ivf_pq snapshots + ann.search_ivf_pq serving (ADC
        # over the codes slab, certified exact f32 rescore).
        if algorithm not in ("brute", "ivf_flat", "ivf_pq"):
            raise ValueError(f"ServingEngine: algorithm must be "
                             f"'brute', 'ivf_flat' or 'ivf_pq', got "
                             f"{algorithm!r}")
        if algorithm in ("ivf_flat", "ivf_pq"):
            expects(mesh is None,
                    "ServingEngine: algorithm=%r serves single-device "
                    "planes (shard the lists via ann.shard_ivf_lists "
                    "outside the engine)" % (algorithm,))
            expects(metric == "l2",
                    "ServingEngine: algorithm=%r serves metric='l2' "
                    "only" % (algorithm,))
        self._algorithm = algorithm
        self._n_lists, self._n_probes = n_lists, n_probes
        self._pq_dim, self._pq_bits = pq_dim, pq_bits
        self.res = ensure_resources(res)
        self.k = int(k)
        self._mesh, self._axis = mesh, axis
        self._rescore, self._certify = rescore, certify
        self._clock = clock
        # db_dtype threads through EVERY snapshot rebuild/swap: an
        # engine serving an int8-streamed index keeps serving int8
        # after background updates (None = the per-plane default —
        # bf16-streamed brute, f32 IVF slab; env RAFT_TPU_DB_DTYPE
        # sets the fleet default without a code change)
        if db_dtype is None:
            db_dtype = env.raw("RAFT_TPU_DB_DTYPE")
        self._db_dtype = db_dtype
        self._build_kw = dict(passes=passes, metric=metric, T=T, Qb=Qb,
                              g=g, grid_order=grid_order,
                              store_yp=store_yp)
        if db_dtype is not None:
            self._build_kw["db_dtype"] = db_dtype
        # durable=True (ISSUE 12): the mutation plane writes ahead —
        # every upsert/delete is WAL-appended + fsynced (per wal_sync /
        # RAFT_TPU_WAL_SYNC) BEFORE its future resolves, the compactor
        # commits an atomic checkpoint at every swap, and constructing
        # an engine over a directory that already holds durable state
        # RECOVERS from it (newest-valid-checkpoint + WAL tail replay
        # through the warmed rebuild machinery) instead of cold-building
        # from `index`. Implies mutable=True. Default OFF: the serving
        # hot path is byte-for-byte the non-durable one.
        self._durable = bool(durable)
        self._recovery = None
        if durable:
            mutable = True
            if durable_dir is None:
                from raft_tpu.mutable.checkpoint import DURABLE_DIR_ENV

                durable_dir = env.raw(DURABLE_DIR_ENV)
            expects(durable_dir is not None,
                    "serving: durable=True needs durable_dir= (or "
                    "RAFT_TPU_DURABLE_DIR)")
        self._durable_dir = durable_dir if durable else None
        # mutable=True: the engine fronts a MutableIndex — queries see a
        # consistent view per batch, and upsert()/delete() requests ride
        # the SAME queue, admission control and deadline scopes as
        # queries (the ISSUE-11 mutation plane). The engine's store IS
        # the mutable index's SnapshotStore, so generation accounting,
        # swap events and the snapshot gauges stay one surface.
        self._mutable = None
        if mutable:
            expects(mesh is None,
                    "ServingEngine: the mutable plane is single-device "
                    "(shard outside the engine)")
            from raft_tpu.mutable import MutableIndex

            src = (index if isinstance(index, (KnnIndex, IvfFlatIndex))
                   else np.asarray(index, np.float32))
            mut_kw = dict(algorithm=algorithm, passes=passes,
                          metric=metric, T=T, Qb=Qb, g=g,
                          db_dtype=db_dtype, n_lists=n_lists,
                          n_probes=n_probes,
                          compact_threshold=compact_threshold,
                          delta_cap=delta_cap)
            if algorithm == "ivf_pq":
                mut_kw.update(pq_dim=pq_dim, pq_bits=pq_bits)
            if durable:
                from raft_tpu.mutable.checkpoint import (
                    has_durable_state, recover)

                recovered = None
                if has_durable_state(durable_dir):
                    expects(not isinstance(index,
                                           (KnnIndex, IvfFlatIndex)),
                            "serving: durable recovery rebuilds the "
                            "index from disk — pass the raw matrix "
                            "(the bootstrap fallback), not a prepared "
                            "index")
                    recovered = recover(durable_dir, res=self.res,
                                        wal_sync=wal_sync, **mut_kw)
                if recovered is not None:
                    self._mutable, self._recovery = recovered
                else:
                    self._mutable = MutableIndex(
                        src, ids=index_ids, res=self.res,
                        durable_dir=durable_dir, wal_sync=wal_sync,
                        **mut_kw)
            else:
                self._mutable = MutableIndex(src, ids=index_ids,
                                             res=self.res, **mut_kw)
            expects(self.k <= self._mutable.n_rows,
                    "ServingEngine: k=%d > index size %d", self.k,
                    self._mutable.n_rows)
            self.d = self._mutable.d_orig
            self._store = self._mutable.store
            qb_hint = self._mutable.Qb
        else:
            if isinstance(index, (KnnIndex, IvfFlatIndex)):
                from raft_tpu.ann import IvfPqIndex

                want = ("ivf_pq" if isinstance(index, IvfPqIndex)
                        else "ivf_flat"
                        if isinstance(index, IvfFlatIndex)
                        else "brute")
                if want != algorithm:
                    raise ValueError(
                        "ServingEngine: prepared index type does not "
                        "match algorithm=%r" % (algorithm,))
                initial = index
            else:
                initial = self._build_index(np.asarray(index,
                                                       np.float32))
            expects(self.k <= initial.n_rows,
                    "ServingEngine: k=%d > index size %d", self.k,
                    initial.n_rows)
            self.d = initial.d_orig
            self._store = SnapshotStore(self._build_index,
                                        initial_index=initial)
            qb_hint = initial.Qb
        if buckets is None or isinstance(buckets, str):
            self._ladder = bucket_ladder(qb_hint, buckets)
        else:
            self._ladder = bucket_ladder(
                qb_hint, ",".join(str(int(b)) for b in buckets))
        if flush_interval_s is None:
            flush_interval_s = env.get(FLUSH_MS_ENV) / 1e3
        self._flush_interval_s = max(1e-4, float(flush_interval_s))
        if max_queue_rows is None:
            max_queue_rows = env.get(QUEUE_CAP_ENV)
        self._max_queue_rows = max(self._ladder[-1], int(max_queue_rows))
        if default_deadline_s is None:
            default_deadline_s = env.get(DEADLINE_ENV)
        self._default_deadline_s = default_deadline_s

        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._depth_rows = 0
        self._stop = False
        self._busy = False
        self._force_flush = False
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._latencies: collections.deque = collections.deque(
            maxlen=4096)
        self._stats = collections.Counter()
        self._next_rid = 0       # per-request flow-trace ids
        # online recall shadow-sampling (ISSUE 10): frac 0 = off;
        # constructor args win, env sets the fleet default
        self._shadow_frac = (shadow_frac_default() if shadow_frac is None
                             else max(0.0, min(1.0, float(shadow_frac))))
        self._shadow_floor = (shadow_floor_default()
                              if shadow_floor is None
                              else float(shadow_floor))
        self._shadow: Optional[ShadowSampler] = None
        # per-query explain capture (PR 16): frac 0 = off; constructor
        # wins over RAFT_TPU_EXPLAIN_FRAC; submit(explain=True) forces
        # capture for one request regardless of the fraction
        from raft_tpu.observability.explain import explain_frac_default

        self._explain_frac = (explain_frac_default()
                              if explain_frac is None
                              else max(0.0, min(1.0,
                                                float(explain_frac))))
        # windowed SLO burn-rate engine: always on (evaluation is one
        # registry snapshot per window interval); injectable for tests
        if slo is None:
            from raft_tpu.observability.slo import SloEngine

            slo = SloEngine(registry=self.res.metrics,
                            clock=self._clock)
        self._slo = slo
        # debugz server: constructor wins over RAFT_TPU_DEBUGZ_PORT
        # (0 = ephemeral port; None/unset = no server)
        if debug_port is None:
            debug_port = env.get("RAFT_TPU_DEBUGZ_PORT")
        self._debug_port = debug_port
        self._debugz = None
        # forensics plane (ISSUE 17): crash-durable blackbox + hang
        # watchdog, both defaults-off; constructor wins over
        # RAFT_TPU_BLACKBOX_PATH / RAFT_TPU_WATCHDOG_S
        self._blackbox_path = blackbox_path
        self._watchdog_s = watchdog_s
        self._blackbox = None
        self._owns_blackbox = False
        self._watchdog = None
        self._crash_report: Optional[dict] = None

    # -- construction helpers --------------------------------------------
    def _build_index(self, y):
        if self._algorithm == "ivf_pq":
            from raft_tpu.ann import build_ivf_pq

            n_lists = self._n_lists or max(
                1, min(1024, int(round(y.shape[0] ** 0.5))))
            return build_ivf_pq(self.res, y, n_lists=n_lists,
                                pq_dim=self._pq_dim,
                                pq_bits=self._pq_bits,
                                n_probes=self._n_probes)
        if self._algorithm == "ivf_flat":
            from raft_tpu.ann import build_ivf_flat

            n_lists = self._n_lists or max(
                1, min(1024, int(round(y.shape[0] ** 0.5))))
            kw = ({"db_dtype": self._db_dtype}
                  if self._db_dtype is not None else {})
            return build_ivf_flat(self.res, y, n_lists=n_lists,
                                  n_probes=self._n_probes, **kw)
        from raft_tpu.distance.knn_fused import prepare_knn_index

        return prepare_knn_index(y, **self._build_kw)

    def _plane(self, snap, xb):
        """The data plane for one padded bucket batch: the AOT runtime
        entry on one device, the PR-4 query-sharded replicated-index
        mode over the mesh, the ANN tier's IVF probe search
        (``algorithm="ivf_flat"``), or the mutable two-slab search
        (``mutable=True`` — ``snap`` is then a MutableView)."""
        if self._mutable is not None:
            from raft_tpu.mutable import MutableView, search_view

            view = (snap if isinstance(snap, MutableView)
                    else self._mutable.view())
            return search_view(self._mutable, xb, self.k, view=view,
                               n_probes=self._n_probes, res=self.res)
        if self._algorithm == "ivf_pq":
            from raft_tpu.ann import search_ivf_pq

            return search_ivf_pq(self.res, snap.index, xb, self.k,
                                 n_probes=self._n_probes)
        if self._algorithm == "ivf_flat":
            from raft_tpu.ann import search_ivf_flat

            return search_ivf_flat(self.res, snap.index, xb, self.k,
                                   n_probes=self._n_probes)
        if self._mesh is not None:
            from raft_tpu.distance.knn_sharded import knn_fused_sharded

            return knn_fused_sharded(
                xb, snap.index, self.k, mesh=self._mesh,
                axis=self._axis, shard_mode="query",
                rescore=self._rescore, certify=self._certify,
                res=self.res)
        from raft_tpu.runtime.entry_points import knn_query

        return knn_query(self.res, snap.index, xb, self.k,
                         rescore=self._rescore, certify=self._certify)

    # -- lifecycle --------------------------------------------------------
    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._ladder

    @property
    def started(self) -> bool:
        return self._started

    @property
    def slo(self):
        """The attached :class:`~raft_tpu.observability.slo.SloEngine`
        (burn-rate alerts), or None."""
        return self._slo

    def start(self) -> "ServingEngine":
        """Warm every bucket shape (AOT compile through the runtime
        entry — live requests then always hit the compile cache) and
        start the batcher thread. Idempotent."""
        with self._cond:
            if self._started:
                return self
            self._started = True
            self._stop = False
        self._boot_forensics()
        self._warm_snapshot(self._store.current())
        if self._shadow_frac > 0.0 and self._shadow is None:
            self._shadow = ShadowSampler(
                self._shadow_oracle, self.k, self._shadow_frac,
                floor=self._shadow_floor).start()
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-batcher",
                                        daemon=True)
        self._thread.start()
        if self._debug_port is not None and self._debugz is None:
            from tools.debugz import DebugzServer

            self._debugz = DebugzServer(
                engine=self, port=int(self._debug_port)).start()
        if self._watchdog is not None:
            self._watchdog.start()
        return self

    def _boot_forensics(self) -> None:
        """Open the blackbox (env/constructor-gated) — surfacing and
        preserving a prior run's unclean file first — and build the
        watchdog. Never raises: forensics must not block serving."""
        from raft_tpu.observability import blackbox as blackbox_mod
        from raft_tpu.observability.watchdog import Watchdog

        try:
            booted = blackbox_mod.boot(path=self._blackbox_path)
            self._blackbox = booted.recorder
            self._owns_blackbox = booted.created
            prior = booted.prior
            if prior is not None and prior.get("verdict") != "clean":
                # the previous run died violently: keep the evidence
                # (reconstructed + preserved as <path>.prev), serve it
                # at /crashz, and count it
                self._crash_report = prior
                self.res.metrics.counter(
                    blackbox_mod.UNCLEAN_SHUTDOWNS,
                    help="Prior-run blackboxes found without an "
                         "epilogue at engine start").inc()
                log_warn("serving: prior run died unclean (verdict "
                         "%r, %d records) — postmortem at /crashz",
                         prior.get("verdict"), prior.get("records"))
            if self._blackbox is not None:
                # the run-start snapshot: the verdict floor a killed
                # process is guaranteed to leave behind
                self._blackbox.snapshot()
        except Exception:
            self._blackbox, self._owns_blackbox = None, False
        try:
            wd = Watchdog(engine=self, interval_s=self._watchdog_s)
            self._watchdog = wd if wd.enabled else None
        except Exception:
            self._watchdog = None

    def stop(self, timeout: float = 30.0) -> None:
        """Drain the queue, then stop the batcher (and the shadow
        scorer, after it drains its own queue)."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._blackbox is not None and self._owns_blackbox:
            # the epilogue: what distinguishes this stop from a kill
            from raft_tpu.observability import blackbox as blackbox_mod

            blackbox_mod.shutdown(reason="clean")
            self._blackbox, self._owns_blackbox = None, False
        if self._debugz is not None:
            self._debugz.stop()
            self._debugz = None
        if self._shadow is not None:
            self._shadow.flush(timeout=min(10.0, timeout))
            self._shadow.stop()
        if self._durable and self._mutable is not None:
            # flush + close the WAL: a clean stop is indistinguishable
            # from a crash-after-fsync to the recovery path (restart =
            # construct a durable engine over the same directory)
            self._mutable.wait_for_compaction(timeout=min(30.0, timeout))
            self._mutable.close()
        with self._cond:
            self._started = False

    def _shadow_oracle(self, x):
        """The exact reference plane the shadow sampler re-scores
        against: brute-force certified KNN over the CURRENT snapshot
        (for the IVF plane, the degenerate ``n_probes = n_lists`` exact
        search — bit-for-bit the brute oracle over the same rows). Runs
        on the shadow thread, never on the serving path."""
        if self._mutable is not None:
            from raft_tpu.mutable import search_view

            return search_view(self._mutable, x, self.k, exact=True,
                               res=self.res)
        snap = self._store.current()
        if self._algorithm == "ivf_pq":
            # degenerate n_probes = n_lists runs the certified exact
            # scan over the retained f32 slab — the brute oracle
            from raft_tpu.ann import search_ivf_pq

            return search_ivf_pq(self.res, snap.index, x, self.k,
                                 n_probes=snap.index.n_lists)
        if self._algorithm == "ivf_flat":
            from raft_tpu.ann import search_ivf_flat

            return search_ivf_flat(self.res, snap.index, x, self.k,
                                   n_probes=snap.index.n_lists)
        from raft_tpu.distance.knn_fused import knn_fused

        return knn_fused(x, snap.index, self.k)

    @property
    def shadow(self) -> Optional[ShadowSampler]:
        return self._shadow

    def _warm_snapshot(self, snap: IndexSnapshot) -> None:
        """Pre-compile every bucket shape against ``snap`` — run at
        start-up AND against a freshly rebuilt snapshot BEFORE it is
        swapped in, so a geometry-changing update cannot push a compile
        onto the request path."""
        misses0 = self.res.compile_cache.misses
        for b in self._ladder:
            x0 = np.zeros((b, self.d), np.float32)
            vals, ids = self._plane(snap, x0)
            interruptible.synchronize(vals, ids)
            if self._algorithm == "ivf_flat" and self._mutable is None:
                # the IVF fine scan has TWO schedules (ISSUE 14): the
                # bucket warmup above compiled whichever one the
                # synthetic probe pattern resolved to; pre-compile the
                # list-major programs for every schedule-cell rung this
                # bucket can reach, so a live batch whose probe pattern
                # flips the resolve_fine_scan crossover (or lands on a
                # different cell rung) never pays a compile
                from raft_tpu.ann.ivf_flat import warm_fine_scan

                warm_fine_scan(
                    self.res, snap.index, b, self.k,
                    self._n_probes or snap.index.n_probes_default)
            if self._algorithm == "ivf_pq" and self._mutable is None:
                # same bucket-ladder contract for the compressed tier:
                # warm the ADC rungs AND the flat fallback programs so
                # neither the chooser nor a certificate rerun can push
                # a compile onto a live request
                from raft_tpu.ann import warm_pq_scan

                warm_pq_scan(
                    self.res, snap.index, b, self.k,
                    self._n_probes or snap.index.n_probes_default)
            emit_serving("warmup", bucket=b, generation=snap.generation)
        self._stats["warmed_buckets"] = len(self._ladder)
        self._stats["warmup_compiles"] += (
            self.res.compile_cache.misses - misses0)

    # -- admission --------------------------------------------------------
    def submit(self, x, deadline_s: Optional[float] = None,
               explain: bool = False) -> ServingFuture:
        """Enqueue one request of [n, d] (or [d]) query rows; returns a
        :class:`ServingFuture`. Admission control happens HERE:
        oversized requests raise :class:`RequestTooLargeError`, a full
        queue raises :class:`OverloadShedError` (counted as the
        ``shed:overload`` degradation rung). Carries the
        ``serving_enqueue`` fault site.

        ``explain=True`` forces an explain record for the batch this
        request rides (otherwise a deterministic hash-sample of rids at
        ``RAFT_TPU_EXPLAIN_FRAC`` decides)."""
        fault_point("serving_enqueue")
        x = np.asarray(x, np.float32)
        if x.ndim == 1:
            x = x[None]
        expects(x.ndim == 2 and x.shape[1] == self.d,
                "serving: request must be [n, %d] query rows (got %s)",
                self.d, x.shape)
        n = x.shape[0]
        if n == 0:
            fut = ServingFuture()
            fut._complete(np.zeros((0, self.k), np.float32),
                          np.zeros((0, self.k), np.int32))
            return fut
        # flow trace: the request's journey starts HERE (client
        # thread); every admission outcome terminates the same flow id
        with self._cond:
            self._next_rid += 1
            rid = self._next_rid
        emit_flow("enqueue", rid, ph="s", rows=n)
        if n > self._ladder[-1]:
            self._count_request("rejected")
            emit_serving("reject", rows=n, top_bucket=self._ladder[-1],
                         rid=rid)
            emit_flow("reject", rid, ph="f", outcome="reject")
            raise RequestTooLargeError(
                f"serving: request of {n} rows exceeds the largest "
                f"bucket {self._ladder[-1]} — split it client-side or "
                f"raise the ladder (RAFT_TPU_SERVING_BUCKETS)")
        now = self._clock()
        budget = (deadline_s if deadline_s is not None
                  else self._default_deadline_s)
        from raft_tpu.observability import explain as explain_mod

        req = _Request(x, n, now,
                       now + budget if budget else None,
                       ServingFuture(), rid=rid,
                       explain=(bool(explain)
                                or explain_mod.want(rid,
                                                    self._explain_frac)))
        with self._cond:
            if self._depth_rows + n > self._max_queue_rows:
                self._count_request("shed")
                self._stats["shed"] += 1
                try:
                    self.res.metrics.counter(
                        SHED, help="Requests shed by admission control "
                                   "(queue at its row cap)").inc()
                except Exception:
                    pass
                record_degradation("serving.engine", "shed:overload")
                emit_serving("shed", rows=n,
                             queue_rows=self._depth_rows, rid=rid)
                emit_flow("shed", rid, ph="f", outcome="shed")
                raise OverloadShedError(
                    f"serving: queue at capacity "
                    f"({self._depth_rows}/{self._max_queue_rows} rows)"
                    f" — request shed; back off and retry")
            self._queue.append(req)
            self._depth_rows += n
            self._gauge_depth()
            emit_serving("enqueue", rows=n,
                         queue_rows=self._depth_rows,
                         deadline_s=budget, rid=rid)
            self._cond.notify_all()
        return req.future

    def query(self, x, deadline_s: Optional[float] = None,
              timeout: Optional[float] = 60.0
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Blocking convenience: submit + wait."""
        return self.submit(x, deadline_s=deadline_s).result(timeout)

    # -- mutations (mutable=True) ------------------------------------------
    def _submit_mutation(self, kind: str, ids, rows,
                         deadline_s: Optional[float]) -> ServingFuture:
        """Enqueue one mutation request — the SAME pipe as queries:
        admission control (queue row cap sheds, an upsert past the
        delta capacity is rejected classified), FIFO ordering with the
        queries around it, per-request deadline scopes on the batcher
        thread, and flow tracing end to end."""
        from raft_tpu.core.error import expects as _expects

        _expects(self._mutable is not None,
                 "serving: %s() needs a mutable engine "
                 "(ServingEngine(..., mutable=True))", kind)
        fault_point("serving_enqueue")
        ids = np.atleast_1d(np.asarray(ids, np.int32))
        if rows is not None:
            rows = np.asarray(rows, np.float32)
            if rows.ndim == 1:
                rows = rows[None]
            _expects(rows.ndim == 2 and rows.shape[1] == self.d,
                     "serving: %s rows must be [n, %d] (got %s)", kind,
                     self.d, rows.shape)
            _expects(ids.shape[0] == rows.shape[0],
                     "serving: %s ids/rows length mismatch", kind)
        n = int(ids.shape[0])
        if n == 0:
            fut = ServingFuture()
            fut._complete({"applied": 0, "kind": kind}, None)
            return fut
        with self._cond:
            self._next_rid += 1
            rid = self._next_rid
        emit_flow("enqueue", rid, ph="s", rows=n, op=kind)
        if rows is not None and n > self._mutable.delta_cap:
            self._count_request("rejected")
            emit_serving("reject", rows=n, op=kind, rid=rid,
                         delta_cap=self._mutable.delta_cap)
            emit_flow("reject", rid, ph="f", outcome="reject")
            raise RequestTooLargeError(
                f"serving: upsert of {n} rows exceeds the delta "
                f"capacity {self._mutable.delta_cap} — split it or "
                f"raise RAFT_TPU_DELTA_CAP")
        now = self._clock()
        budget = (deadline_s if deadline_s is not None
                  else self._default_deadline_s)
        req = _Request(rows, n, now, now + budget if budget else None,
                       ServingFuture(), rid=rid, kind=kind, ids=ids)
        with self._cond:
            if self._depth_rows + n > self._max_queue_rows:
                self._count_request("shed")
                self._stats["shed"] += 1
                try:
                    self.res.metrics.counter(
                        SHED, help="Requests shed by admission control "
                                   "(queue at its row cap)").inc()
                except Exception:
                    pass
                record_degradation("serving.engine", "shed:overload")
                emit_serving("shed", rows=n, op=kind,
                             queue_rows=self._depth_rows, rid=rid)
                emit_flow("shed", rid, ph="f", outcome="shed")
                raise OverloadShedError(
                    f"serving: queue at capacity "
                    f"({self._depth_rows}/{self._max_queue_rows} rows)"
                    f" — {kind} shed; back off and retry")
            self._queue.append(req)
            self._depth_rows += n
            self._gauge_depth()
            emit_serving("enqueue", rows=n, op=kind,
                         queue_rows=self._depth_rows,
                         deadline_s=budget, rid=rid)
            self._cond.notify_all()
        return req.future

    def upsert(self, ids, rows, deadline_s: Optional[float] = None
               ) -> ServingFuture:
        """Enqueue an upsert of ``rows`` [n, d] under external ``ids``
        [n] (mutable engines). The future resolves to a dict with the
        applied count and the index seq/generation once the batcher
        applies it — strictly ordered against the queries around it."""
        return self._submit_mutation("upsert", ids, rows, deadline_s)

    def delete(self, ids, deadline_s: Optional[float] = None
               ) -> ServingFuture:
        """Enqueue a delete of external ``ids`` (mutable engines) —
        visible to every query batch dispatched after it."""
        return self._submit_mutation("delete", ids, None, deadline_s)

    # -- index updates ----------------------------------------------------
    @property
    def mutable(self):
        """The engine's MutableIndex (None on immutable engines)."""
        return self._mutable

    @property
    def recovery(self):
        """Stats of the startup crash recovery this engine performed
        (None when it cold-started — a fresh durable dir or
        durable=False)."""
        return dict(self._recovery) if self._recovery else None

    def update_index(self, y, block: bool = False):
        """Rebuild the index from ``y`` and swap it in — in the
        background by default; queries keep hitting the current
        snapshot until the new one is built AND pre-warmed (every
        bucket compiled against the new geometry before the swap), so
        readers never block and never pay a compile."""
        expects(self._mutable is None,
                "serving: a mutable engine updates through upsert()/"
                "delete() (compaction folds the delta in the "
                "background) — update_index is the immutable path")
        y = np.asarray(y, np.float32)
        expects(y.ndim == 2 and y.shape[1] == self.d,
                "serving: replacement index must be [m, %d] (got %s)",
                self.d, y.shape)
        expects(self.k <= y.shape[0],
                "serving: k=%d > replacement index size %d", self.k,
                y.shape[0])
        store = self._store

        def _builder(yy, **kw):
            idx = self._build_index(yy)
            if self._started:
                # pre-swap warm on a TEMP snapshot (generation stamped
                # by the store when it swaps)
                self._warm_snapshot(IndexSnapshot(idx, -1))
            return idx

        prev_builder = store._builder
        store._builder = _builder
        try:
            return store.update(y, block=block)
        finally:
            if block:
                store._builder = prev_builder

    @property
    def snapshot(self) -> IndexSnapshot:
        return self._store.current()

    # -- metrics helpers --------------------------------------------------
    def _count_request(self, status: str) -> None:
        self._stats[f"requests_{status}"] += 1
        try:
            self.res.metrics.counter(
                REQUESTS, {"status": status},
                help="Serving requests by terminal status").inc()
        except Exception:
            pass

    def _gauge_depth(self) -> None:
        try:
            self.res.metrics.gauge(
                QUEUE_DEPTH, help="Query rows currently queued"
            ).set(self._depth_rows)
        except Exception:
            pass

    def _observe_latency(self, seconds: float) -> None:
        self._latencies.append(seconds)
        try:
            self.res.metrics.histogram(
                LATENCY, help="End-to-end request latency (enqueue → "
                              "completion)").observe(seconds)
        except Exception:
            pass

    def stats(self) -> dict:
        """Live counters + latency percentiles (engine-side; the
        BENCH_SERVING artifact measures client-side). Percentiles use
        the shared interpolating :func:`~raft_tpu.observability.
        metrics.percentile` (the old index pick reported the max for
        small windows)."""
        with self._cond:
            out = dict(self._stats)
            out["queue_rows"] = self._depth_rows
            lat = list(self._latencies)
        if lat:
            out["p50_ms"] = 1e3 * percentile(lat, 50)
            out["p99_ms"] = 1e3 * percentile(lat, 99)
        out["generation"] = self._store.generation
        out["compile_misses"] = self.res.compile_cache.misses
        out["buckets"] = self._ladder
        if self._mutable is not None:
            out["mutable"] = self._mutable.stats()
            if self._mutable.durability is not None:
                out["durability"] = self._mutable.durability.stats()
        if self._recovery is not None:
            out["recovery"] = dict(self._recovery)
        if self._shadow is not None:
            out.update(self._shadow.snapshot())
        if self._slo is not None:
            try:
                out["slo"] = self._slo.status()
            except Exception:
                pass
        from raft_tpu.observability.explain import explain_records

        out["explain"] = {"frac": self._explain_frac,
                          "records": len(explain_records())}
        if self._debugz is not None:
            out["debugz_port"] = self._debugz.port
        if self._blackbox is not None:
            out["blackbox"] = self._blackbox.stats()
        if self._watchdog is not None:
            out["watchdog"] = self._watchdog.stats()
        if self._crash_report is not None:
            out["prior_crash"] = {
                "verdict": self._crash_report.get("verdict"),
                "records": self._crash_report.get("records"),
                "preserved_path":
                    self._crash_report.get("preserved_path")}
        return out

    @property
    def crash_report(self) -> Optional[dict]:
        """The prior run's postmortem reconstruction when this engine's
        start() found an epilogue-less blackbox (else None) — the
        /crashz body."""
        return self._crash_report

    @property
    def blackbox(self):
        """The installed crash-durable recorder, or None."""
        return self._blackbox

    def inflight_requests(self) -> List[dict]:
        """Snapshot of queued requests (age, remaining deadline) — the
        watchdog's stall evidence and the blackbox's in-flight table.
        Takes the cond only long enough to copy the queue."""
        with self._cond:
            reqs = list(self._queue)
            busy = self._busy
        now = self._clock()
        out = [{"rid": r.rid, "kind": r.kind, "rows": r.n,
                "age_s": round(now - r.enqueued_at, 6),
                "deadline_in_s": (round(r.deadline_at - now, 6)
                                  if r.deadline_at is not None
                                  else None)}
               for r in reqs]
        if busy:
            out.append({"rid": None, "kind": "dispatch", "rows": 0,
                        "age_s": 0.0, "deadline_in_s": None})
        return out

    # the name the quality-telemetry plane documents; same snapshot
    snapshot_stats = stats

    # -- the batcher ------------------------------------------------------
    def flush(self, timeout: float = 30.0) -> bool:
        """Force-drain the queue; returns True once empty and idle.
        The deterministic lever tests and benchmarks use instead of
        sleeping through flush windows."""
        t_end = time.monotonic() + timeout
        with self._cond:
            self._force_flush = True
            self._cond.notify_all()
            while ((self._queue or self._busy)
                   and time.monotonic() < t_end):
                self._cond.wait(0.01)
            drained = not self._queue and not self._busy
            self._force_flush = False
            return drained

    def _pop_batch_locked(self):
        """Assemble the next batch under the lock: greedy pops up to
        the top bucket, failing queue-expired requests on the way (the
        admission half of the deadline contract — an expired request
        never wastes a dispatch)."""
        now = self._clock()
        batch = []
        total = 0
        expired = []
        mutation = None
        while self._queue:
            req = self._queue[0]
            if req.deadline_at is not None and req.deadline_at <= now:
                self._queue.popleft()
                self._depth_rows -= req.n
                expired.append(req)
                continue
            if req.kind != "query":
                # a mutation is a strict ordering barrier: queries
                # ahead of it dispatch first (this batch), the mutation
                # runs alone next, queries behind it see its effect
                if batch:
                    break
                self._queue.popleft()
                self._depth_rows -= req.n
                mutation = req
                break
            if total + req.n > self._ladder[-1]:
                break
            self._queue.popleft()
            self._depth_rows -= req.n
            batch.append(req)
            total += req.n
        self._gauge_depth()
        return batch, total, expired, mutation

    def _fail_expired(self, expired) -> None:
        for req in expired:
            self._count_request("deadline")
            self._stats["expired_in_queue"] += 1
            emit_flow("expire", req.rid, ph="f", outcome="expired")
            req.future._fail(DeadlineExceededError(
                "serving: request deadline expired while queued",
                seconds=(req.deadline_at - req.enqueued_at
                         if req.deadline_at else None)))

    def _loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._stop:
                        break
                    if self._queue:
                        now = self._clock()
                        total = sum(r.n for r in self._queue)
                        oldest = self._queue[0].enqueued_at
                        if (self._force_flush
                                or total >= self._ladder[-1]
                                or now - oldest
                                >= self._flush_interval_s):
                            break
                        self._cond.wait(self._flush_interval_s / 2)
                    else:
                        # empty-queue flush timer tick: nothing to
                        # dispatch — the timer is a no-op, not a batch
                        self._cond.wait(self._flush_interval_s)
                        if self._slo is not None:
                            # break out so the SLO tick runs OUTSIDE
                            # the cond lock (it snapshots the registry)
                            break
                if self._stop and not self._queue:
                    self._busy = False
                    self._cond.notify_all()
                    return
                batch, total, expired, mutation = \
                    self._pop_batch_locked()
                self._busy = bool(batch) or mutation is not None
            wd = self._watchdog
            if wd is not None:
                # liveness heartbeat, OUTSIDE the cond (one dict store)
                wd.beat()
            bb = self._blackbox
            if bb is not None:
                # rate-limited (snapshot_interval_s): most calls are
                # one clock read; keeps the "final metrics snapshot"
                # fresh even when no watchdog ticks
                bb.maybe_snapshot()
            self._fail_expired(expired)
            if batch or mutation is not None:
                try:
                    if batch:
                        self._run_batch(batch, total)
                    if mutation is not None:
                        self._run_mutation(mutation)
                finally:
                    with self._cond:
                        self._busy = False
                        self._cond.notify_all()
            if self._slo is not None:
                # self-rate-limited (MetricWindows.interval_s): most
                # calls are one clock read; never raises
                self._slo.tick()

    def _run_batch(self, batch, total: int) -> None:
        # ONE snapshot/view per batch — every rider sees one index
        snap = (self._mutable.view() if self._mutable is not None
                else self._store.current())
        bucket = bucket_for(total, self._ladder)
        x = (batch[0].x if len(batch) == 1
             else np.concatenate([r.x for r in batch], axis=0))
        now = self._clock()
        budgets = [r.deadline_at - now for r in batch
                   if r.deadline_at is not None]
        budget = min(budgets) if budgets else None
        if budget is not None and budget <= 0:
            # raced to expiry between assembly and dispatch
            self._fail_expired([r for r in batch
                                if r.deadline_at is not None
                                and r.deadline_at <= now])
            batch = [r for r in batch
                     if r.deadline_at is None or r.deadline_at > now]
            if not batch:
                return
            return self._run_batch(batch, sum(r.n for r in batch))
        self._stats["batches"] += 1
        self._stats["padded_rows"] += bucket - total
        # flow trace: each rider steps onto the batcher thread (batch
        # assembly), then through the dispatch — the t points connect
        # the client-thread `s` to the terminus across lanes
        for req in batch:
            emit_flow("batch", req.rid, ph="t", bucket=bucket,
                      riders=len(batch))
        try:
            self.res.metrics.counter(
                BATCHES, {"bucket": str(bucket)},
                help="Dispatched micro-batches by bucket").inc()
            self.res.metrics.counter(
                BATCH_PAD_ROWS,
                help="Pad rows dispatched (bucket − real rows)"
            ).inc(bucket - total)
        except Exception:
            pass
        for req in batch:
            emit_flow("dispatch", req.rid, ph="t",
                      generation=snap.generation)
        from raft_tpu.observability import explain as explain_mod

        # explain capture spans the dispatch: any flagged rider opens
        # one record for the whole batch (the plane/margin notes land
        # in it from the kernels below); begin_capture returns None
        # when no rider is flagged, and every hook no-ops then
        cap = (explain_mod.begin_capture([r.rid for r in batch])
               if any(r.explain for r in batch) else None)
        try:
            with explain_mod.stage("execute_batch"):
                vals, ids = execute_batch(self._plane, snap, x, bucket,
                                          total, budget)
        except DeadlineExceededError as e:
            explain_mod.end_capture(cap, outcome="deadline",
                                    bucket=bucket, riders=len(batch))
            self._on_batch_deadline(batch, e)
            return
        except Exception as e:
            explain_mod.end_capture(cap, outcome="error",
                                    bucket=bucket, riders=len(batch))
            for req in batch:
                self._count_request("error")
                emit_flow("fail", req.rid, ph="f", outcome="error")
                req.future._fail(e)
            return
        off = 0
        done = self._clock()
        for req in batch:
            req.future._complete(vals[off:off + req.n],
                                 ids[off:off + req.n])
            emit_flow("response", req.rid, ph="f", outcome="ok")
            if self._shadow is not None and self._shadow.want(req.rid):
                # off the hot path: queue (request, served ids) for the
                # background oracle re-score; a full shadow queue drops
                # the sample, never blocks the batcher
                self._shadow.submit(req.rid, req.x,
                                    np.asarray(ids[off:off + req.n]))
            off += req.n
            self._count_request("ok")
            self._observe_latency(max(0.0, done - req.enqueued_at))
        explain_mod.end_capture(cap, outcome="ok", bucket=bucket,
                                rows=total, riders=len(batch),
                                generation=snap.generation)

    def _run_mutation(self, req) -> None:
        """Apply ONE mutation request on the batcher thread, inside its
        own deadline scope — the write half of the serving contract:
        strictly ordered against query batches, never concurrent with a
        dispatch, and an expired/hung apply fails typed exactly like a
        query batch would."""
        from raft_tpu.mutable import apply_delete, apply_upsert

        now = self._clock()
        budget = (req.deadline_at - now if req.deadline_at is not None
                  else None)
        if budget is not None and budget <= 0:
            self._fail_expired([req])
            return
        emit_flow("dispatch", req.rid, ph="t", op=req.kind)
        emit_serving("mutate", op=req.kind, rows=req.n, rid=req.rid,
                     budget_s=budget)
        self._stats[f"{req.kind}s"] += 1

        def _apply():
            if req.kind == "upsert":
                return apply_upsert(self._mutable, req.ids, req.x)
            return apply_delete(self._mutable, req.ids)

        try:
            if budget is not None:
                with deadline(budget, label="serving_mutation"):
                    applied = _apply()
            else:
                applied = _apply()
        except DeadlineExceededError as e:
            self._count_request("deadline")
            emit_flow("fail", req.rid, ph="f", outcome="deadline")
            req.future._fail(e)
            return
        except Exception as e:
            self._count_request("error")
            emit_flow("fail", req.rid, ph="f", outcome="error")
            req.future._fail(e)
            return
        done = self._clock()
        emit_flow("response", req.rid, ph="f", outcome="ok")
        self._count_request("ok")
        self._observe_latency(max(0.0, done - req.enqueued_at))
        req.future._complete(
            {"kind": req.kind, "applied": int(applied),
             "seq": self._mutable.seq,
             "generation": self._mutable.generation}, None)

    def _on_batch_deadline(self, batch, err: DeadlineExceededError
                           ) -> None:
        """A batch deadline fired: requests whose OWN budget expired
        fail with the deadline error; riders that still have budget are
        re-queued once (at the head — they have waited longest) and
        fail honestly on a second strike."""
        now = self._clock()
        requeue = []
        for req in batch:
            if req.deadline_at is not None and req.deadline_at <= now:
                self._count_request("deadline")
                emit_flow("fail", req.rid, ph="f", outcome="deadline")
                req.future._fail(err)
            elif req.requeues >= _MAX_REQUEUES:
                self._count_request("error")
                emit_flow("fail", req.rid, ph="f", outcome="error")
                req.future._fail(err)
            else:
                req.requeues += 1
                emit_flow("requeue", req.rid, ph="t",
                          outcome="requeue", attempt=req.requeues)
                requeue.append(req)
        if requeue:
            self._stats["requeued"] += len(requeue)
            with self._cond:
                for req in reversed(requeue):
                    self._queue.appendleft(req)
                    self._depth_rows += req.n
                self._gauge_depth()
                self._cond.notify_all()
