"""Immutable index snapshots with background rebuild-and-swap.

The serving engine never queries a mutable index: it queries an
:class:`IndexSnapshot` — a frozen (prepared index, generation) pair —
taken ONCE per micro-batch, so every request coalesced into a batch
sees one consistent index even while an update is in flight (the
snapshot-swap-mid-batch consistency contract pinned by
tests/test_serving.py).

Updates go through :class:`SnapshotStore`:

- ``current()`` is a lock-free attribute read — readers NEVER block on
  a swap (the reference ecosystem's index objects get the same
  copy-on-write treatment in cuVS serving deployments).
- ``update(y)`` rebuilds the index on a background thread (operand prep
  is the expensive part — ~3 ms at 1M×128, arbitrarily long at scale)
  and atomically swaps the new snapshot in when done; queries keep
  hitting the OLD snapshot until the swap, then new batches pick up the
  new generation. A failed build leaves the old snapshot untouched
  (counted + logged, never propagated into the query path).
- generation numbers are monotonic; ids returned for one request are
  always consistent with exactly one generation.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point

SNAPSHOT_SWAPS = "raft_tpu_serving_snapshot_swaps_total"
SNAPSHOT_FAILURES = "raft_tpu_serving_snapshot_failures_total"
#: the CURRENT snapshot generation, as a gauge (an operator watching
#: dashboards sees swaps land without diffing counters)
SNAPSHOT_GENERATION = "raft_tpu_serving_snapshot_generation"
#: background rebuilds currently in flight (0 or 1 — at most one runs)
REBUILD_INFLIGHT = "raft_tpu_serving_snapshot_rebuild_inflight"
#: update() builds whose swap was coalesced away by a NEWER generation
#: winning the race — previously this drop was silent
SNAPSHOT_COALESCED = "raft_tpu_serving_snapshot_coalesced_total"


def _gauge(name: str, value: float, help: str) -> None:
    try:
        from raft_tpu.observability import get_registry

        get_registry().gauge(name, help=help).set(value)
    except Exception:
        pass


class IndexSnapshot:
    """One frozen (index, generation) pair. The ``index`` is a prepared
    :class:`~raft_tpu.distance.knn_fused.KnnIndex` (or sharded sibling)
    whose operands are immutable jax arrays — nothing here is ever
    mutated after construction."""

    __slots__ = ("index", "generation", "n_rows")

    def __init__(self, index, generation: int):
        self.index = index
        self.generation = generation
        self.n_rows = int(getattr(index, "n_rows", 0))

    def __repr__(self):
        return (f"IndexSnapshot(gen={self.generation}, "
                f"n_rows={self.n_rows})")


@instrument("serving.build_snapshot")
def build_snapshot(y, builder: Callable, generation: int,
                   **build_kw) -> IndexSnapshot:
    """Build one snapshot: run the index ``builder`` (default:
    ``distance.prepare_knn_index`` — the engine passes the bound
    builder for its data plane) over the new matrix. Carries the
    ``serving_snapshot`` fault site so a failing rebuild is injectable;
    a failure here must leave the store's current snapshot untouched
    (SnapshotStore.update guarantees that)."""
    fault_point("serving_snapshot")
    return IndexSnapshot(builder(y, **build_kw), generation)


class SnapshotStore:
    """Holder of the current :class:`IndexSnapshot` + the background
    rebuild machinery. ``current()`` is one attribute read; ``swap()``
    and generation accounting hold a small lock; at most one background
    rebuild runs at a time (a second ``update`` while one is in flight
    queues behind it on the builder thread's completion)."""

    def __init__(self, builder: Callable, initial_index=None):
        self._builder = builder
        self._lock = threading.Lock()
        self._generation = 0
        self._current: Optional[IndexSnapshot] = None
        self._build_thread: Optional[threading.Thread] = None
        self._last_error: Optional[BaseException] = None
        if initial_index is not None:
            self._current = IndexSnapshot(initial_index, 0)

    # -- readers (lock-free) ---------------------------------------------
    def current(self) -> Optional[IndexSnapshot]:
        """The live snapshot — a bare attribute read, never blocking on
        an in-flight rebuild/swap."""
        return self._current

    @property
    def generation(self) -> int:
        return self._generation

    @property
    def last_error(self) -> Optional[BaseException]:
        """The most recent FAILED rebuild's error (diagnostic only —
        failures never surface into the query path)."""
        return self._last_error

    # -- writers ----------------------------------------------------------
    def swap(self, snapshot: IndexSnapshot) -> IndexSnapshot:
        """Atomically install ``snapshot`` as current; returns the
        previous one. Counted + emitted so swaps are visible in the
        flight timeline next to the batches they interleave with."""
        with self._lock:
            prev, self._current = self._current, snapshot
        try:
            from raft_tpu.observability import get_registry
            from raft_tpu.observability.timeline import emit_serving

            get_registry().counter(
                SNAPSHOT_SWAPS,
                help="Index snapshot swaps installed").inc()
            _gauge(SNAPSHOT_GENERATION, snapshot.generation,
                   "Generation of the currently-serving index snapshot")
            emit_serving("swap", generation=snapshot.generation,
                         n_rows=snapshot.n_rows,
                         db_dtype=getattr(snapshot.index, "db_dtype",
                                          None))
        except Exception:
            pass
        return prev

    def update(self, y, block: bool = False, **build_kw):
        """Rebuild from ``y`` and swap when ready. ``block=False``
        (default) runs the build on a background thread and returns it
        immediately — readers keep the old snapshot until the swap;
        ``block=True`` builds inline (tests, cold start). A failed
        build counts + records the error and leaves the current
        snapshot in place."""
        with self._lock:
            self._generation += 1
            gen = self._generation

        def _build():
            _gauge(REBUILD_INFLIGHT, 1,
                   "Background snapshot rebuilds currently in flight")
            try:
                snap = build_snapshot(y, self._builder, gen, **build_kw)
            except Exception as e:
                self._last_error = e
                try:
                    from raft_tpu.observability import get_registry

                    get_registry().counter(
                        SNAPSHOT_FAILURES,
                        help="Index snapshot rebuilds that failed "
                             "(old snapshot kept serving)").inc()
                except Exception:
                    pass
                from raft_tpu.core.logger import log_warn

                log_warn("serving: snapshot rebuild (gen %d) failed "
                         "(%s: %s) — keeping the current snapshot",
                         gen, type(e).__name__, str(e)[:200])
                return
            finally:
                _gauge(REBUILD_INFLIGHT, 0,
                       "Background snapshot rebuilds currently in flight")
            with self._lock:
                # a swap is installed only if no NEWER generation beat
                # us to it (two racing updates: last requested wins) —
                # the coalesced build is COUNTED, not silently dropped
                cur = self._current
                if cur is not None and cur.generation > gen:
                    try:
                        from raft_tpu.observability import get_registry

                        get_registry().counter(
                            SNAPSHOT_COALESCED,
                            help="Snapshot rebuilds coalesced away by a "
                                 "newer generation winning the race"
                        ).inc()
                    except Exception:
                        pass
                    return
            self.swap(snap)

        if block:
            _build()
            return None
        t = threading.Thread(target=_build, name=f"snapshot-build-{gen}",
                             daemon=True)
        with self._lock:
            self._build_thread = t
        t.start()
        return t

    def wait_for_builds(self, timeout: Optional[float] = None) -> None:
        """Join the most recent background build (tests/shutdown)."""
        t = self._build_thread
        if t is not None:
            t.join(timeout)
