"""The bucket ladder: the small fixed set of pre-compiled batch shapes.

Every request batch the serving engine dispatches is padded up to one
of a few fixed row counts — the *bucket ladder* — so after the engine's
start-up warm-up each dispatch hits an ALREADY-COMPILED executable
(per-bucket compile-cache keys in ``runtime.entry_points.knn_query``;
per-bucket jit-cache keys in the query-sharded mesh plane). Dynamic
shapes would re-trace per distinct batch size — the one latency cliff a
serving path cannot afford.

Ladder shape: ascending multiples of 8 (the fused kernel's query-block
sublane quantum), topped by the autotuner's ``Qb`` sweet spot by
default — the batch size the measured-best fused config was tuned at,
so a full bucket runs the kernel exactly at its tuned operating point.
Smaller rungs exist so a near-empty queue is not taxed with a full
``Qb`` pad (pad rows cost real kernel time).

Env knobs:

- ``RAFT_TPU_SERVING_BUCKETS`` — comma-separated row counts (each
  rounded UP to a multiple of 8, sorted, deduplicated; at most
  :data:`MAX_BUCKETS` rungs). An unparseable spec degrades to the
  default ladder with a logged reason and a ``marker`` timeline event
  (the tune-table loader contract: corrupt config must never break
  serving).
"""

from __future__ import annotations

import os

from raft_tpu.core import env
from typing import Optional, Sequence, Tuple

#: quantum every bucket rounds up to (the fused kernel's query sublanes)
ROW_QUANTUM = 8
#: ladder length cap — each rung is one warmed executable per geometry
MAX_BUCKETS = 8

BUCKETS_ENV = "RAFT_TPU_SERVING_BUCKETS"


def default_bucket_ladder(qb: int) -> Tuple[int, ...]:
    """The built-in ladder for a tuned query-block sweet spot ``qb``:
    geometric rungs qb/16 → qb/4 → qb (each rounded up to the row
    quantum, deduplicated) — small enough that a trickle of traffic
    pays little padding, topped at the tuned batch size."""
    qb = max(ROW_QUANTUM, int(qb))
    raw = (qb // 16, qb // 4, qb)
    out = []
    for b in raw:
        b = max(ROW_QUANTUM, -(-b // ROW_QUANTUM) * ROW_QUANTUM)
        if b not in out:
            out.append(b)
    return tuple(sorted(out))


def _degrade(spec: str, reason: str, qb: int) -> Tuple[int, ...]:
    from raft_tpu.core.logger import log_warn

    log_warn("%s=%r is invalid (%s) — using the default bucket ladder",
             BUCKETS_ENV, spec, reason)
    try:
        from raft_tpu.observability.timeline import emit_marker

        emit_marker("serving.buckets.degraded", spec=spec[:100],
                    reason=reason)
    except Exception:
        pass
    return default_bucket_ladder(qb)


def bucket_ladder(qb: int, spec: Optional[str] = None) -> Tuple[int, ...]:
    """Resolve the bucket ladder: explicit ``spec`` (or the
    ``RAFT_TPU_SERVING_BUCKETS`` env), validated and normalized —
    ascending, multiples of :data:`ROW_QUANTUM`, ≤ :data:`MAX_BUCKETS`
    rungs — falling back to :func:`default_bucket_ladder` on anything
    unusable."""
    spec = (env.raw(BUCKETS_ENV) or "") if spec is None else spec
    spec = spec.strip()
    if not spec:
        return default_bucket_ladder(qb)
    try:
        raw = [int(tok) for tok in spec.replace(";", ",").split(",")
               if tok.strip()]
    except ValueError as e:
        return _degrade(spec, f"not integers: {e}", qb)
    if not raw:
        return _degrade(spec, "empty ladder", qb)
    if any(b <= 0 for b in raw):
        return _degrade(spec, "buckets must be positive", qb)
    out = []
    for b in raw:
        b = -(-b // ROW_QUANTUM) * ROW_QUANTUM   # round UP to the quantum
        if b not in out:
            out.append(b)
    out.sort()
    if len(out) > MAX_BUCKETS:
        return _degrade(spec, f"more than {MAX_BUCKETS} rungs", qb)
    return tuple(out)


def bucket_for(n_rows: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest bucket that fits ``n_rows``, or None when the batch is
    larger than the top rung (the caller splits — or, for one oversized
    REQUEST, rejects with a classified error)."""
    for b in ladder:
        if n_rows <= b:
            return b
    return None
