"""Micro-benchmark harness.

(ref: cpp/bench/prims/common/benchmark.hpp:59,99 — the google-benchmark
``fixture`` with RMM pool option and ``cuda_event_timer`` for device-time
measurement, plus data generators like ``BlobsFixture:176``. The TPU
equivalent measures device time by forcing completion with a one-element
fetch and subtracting the transport round-trip (tunneled devices may
return from block_until_ready before execution finishes — measured fact on
the axon transport).)
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import ensure_resources


class Fixture:
    """(ref: bench/prims/common/benchmark.hpp ``class fixture``)"""

    _trivial = None   # class-cached jitted RTT probe (stable identity)

    def __init__(self, res=None, reps: int = 5, warmup: int = 1):
        self.res = ensure_resources(res)
        self.reps = reps
        self.warmup = warmup
        self._rtt: Optional[float] = None

    def _measure_rtt(self, probe) -> float:
        """MIN of three probes, refreshed (min-merged) on every run():
        the tunnel RTT jitters by tens of ms, and a single stale
        overestimate SILENTLY DEFLATES every later measurement by
        rtt_err/reps (observed: a tune sweep reporting 35 ms for a
        config that honestly times at 48 ms in a fresh process). Using
        the running min biases rtt low, which inflates reported op time
        — the honest direction."""
        if Fixture._trivial is None:
            Fixture._trivial = jax.jit(lambda x: x.ravel()[0] * 2.0)
        trivial = Fixture._trivial
        float(np.asarray(trivial(probe)))  # compile (cached across runs)
        spans = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(np.asarray(trivial(probe)))
            spans.append(time.perf_counter() - t0)
        rtt = min(spans)
        self._rtt = rtt if self._rtt is None else min(self._rtt, rtt)
        return self._rtt

    def run(self, fn: Callable, *args, name: Optional[str] = None,
            model: Optional[Dict] = None) -> Dict[str, float]:
        """Time fn(*args); returns {"seconds", "rtt"} with transport
        round-trip subtracted. (ref: ``cuda_event_timer`` role)

        ``model`` (optional) is an analytic-prediction dict (e.g.
        ``costmodel.fused_traffic_model``) merged into the result under
        ``model_*`` keys — the predicted half of every
        predicted-vs-measured comparison rides the same artifact as the
        measured half, so divergence is visible wherever the numbers
        land (BENCH_*.json, tune tables, the metrics registry).

        The result is also emitted through the observability registry
        (``raft_tpu_benchmark_seconds{bench=<name>}`` + a ``benchmark``
        event, keyed by ``name`` or the function's ``__name__``) so
        BENCH_*.json trajectories and ad-hoc measurements flow from one
        code path — see ``observability.bench_results()``.

        When tracing is enabled the result ALSO carries the static cost
        model: ``flops``, ``bytes_accessed``, ``arithmetic_intensity``,
        ``peak_hbm_bytes``, ``bound`` (compute-/memory-bound at the
        chip's ridge) and ``roofline_frac`` (roofline-perfect time /
        measured time) — captured once per (name, shape signature) via
        ``res.profiler`` (one analysis lowering, memoized), so every
        future BENCH artifact records FLOPs/bytes, not just seconds. A
        callable the cost model cannot lower (host-side control flow)
        simply omits the fields.

        All ``reps`` dispatches are timed in ONE span with a single
        completion fetch at the end: a single device queues executions in
        dispatch order, so total = reps·t_op + one RTT. This amortizes the
        round-trip and resolves ops far cheaper than the ~30-70ms tunnel
        RTT (per-rep timing clamps those to 0). Two spans are timed and
        the MIN taken, so a transient host stall (GC, tunnel hiccup) in
        one span cannot inflate the result."""
        out = fn(*args)
        leaf = jax.tree_util.tree_leaves(out)[0]
        float(np.asarray(leaf.ravel()[0]))  # compile + completion (scalar fetch)
        rtt = self._measure_rtt(jax.tree_util.tree_leaves(args)[0])
        spans = []
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(self.reps):
                out = fn(*args)
            leaf = jax.tree_util.tree_leaves(out)[0]
            # device-side index first: fetch ONE scalar, not the whole leaf
            float(np.asarray(leaf.ravel()[0]))
            spans.append(time.perf_counter() - t0)
        op_total = min(spans) - rtt
        # resolution contract, consumed by the measurement scripts (ONE
        # implementation — benchmarks must not reinvent the clamp):
        # a span whose op part is within RTT-jitter territory (< 1/4 of
        # an RTT) is UNRESOLVED; callers should escalate reps or report
        # `resolution` (= rtt/reps, the per-rep upper bound) marked as a
        # bound, never the noise-derived number.
        result = {"seconds": max(op_total / self.reps, 1e-9),
                  "rtt": rtt,
                  "resolved": op_total >= 0.25 * rtt,
                  "resolution": rtt / self.reps}
        bench_name = name or getattr(fn, "__name__", repr(fn))
        result.update(self._cost_fields(bench_name, fn, args,
                                        result["seconds"]))
        # resilience provenance: a nonzero degradation counter means
        # some hot path ran a ladder fallback this process — stamp it
        # so bench_report --check can refuse to gate (or baseline)
        # degraded evidence. Omitted when zero, keeping clean artifacts
        # byte-identical to the pre-resilience schema.
        try:
            from raft_tpu.resilience import degradation_count

            dc = degradation_count()
            if dc:
                result["resilience_degradations"] = dc
        except Exception:
            pass
        if model:
            result.update({
                (k if str(k).startswith("model_") else f"model_{k}"): v
                for k, v in model.items()})
        # drift ledger: the cost model's prediction vs THIS measurement,
        # per site. predicted_seconds is the roofline-perfect time the
        # model says this executable needs (roofline_frac · measured);
        # ``measured`` is True only on real TPU hardware — CPU-suite
        # entries are model-shape evidence and are never drift-gated
        # (tools/bench_report.py --check gates the measured ones).
        try:
            from raft_tpu.observability.timeline import record_drift

            rf = result.get("roofline_frac")
            if isinstance(rf, (int, float)) and rf > 0:
                record_drift(
                    bench_name,
                    predicted_seconds=rf * result["seconds"],
                    predicted_bytes=result.get(
                        "model_total_bytes", result.get("bytes_accessed")),
                    measured_seconds=result["seconds"],
                    measured_bytes=result.get("bytes_accessed"),
                    measured=jax.default_backend() == "tpu",
                    platform=jax.default_backend())
        except Exception:
            pass
        # quality telemetry (ISSUE 10): drain the pending certificate
        # stats (the measured program has completed — the device
        # scalars resolve for free) and stamp the cumulative quality
        # block, so fixup-rate evidence rides every BENCH artifact in
        # the already-gated schema (bench_report --check [quality]).
        # Omitted when the process recorded none, keeping quality-free
        # artifacts byte-identical to the previous schema.
        try:
            from raft_tpu.observability.quality import quality_block

            qb = quality_block()
            if qb:
                result["quality"] = qb
        except Exception:
            pass
        from raft_tpu.observability import record_benchmark

        record_benchmark(bench_name, result)
        return result

    def _cost_fields(self, name: str, fn: Callable, args,
                     seconds: float) -> Dict[str, float]:
        """Static-cost + roofline fields for one measured callable (see
        run()); {} when tracing is disabled or the fn resists analysis.
        Runs AFTER timing, so the analysis compile never pollutes the
        measurement."""
        from raft_tpu import observability as obs
        from raft_tpu.observability import costmodel

        if not obs.tracing_enabled():
            return {}
        profiler = self.res.profiler
        rec = profiler.capture_fn(name, fn, *args)
        if rec is None:
            return {}
        est = costmodel.roofline(rec, profiler.spec, seconds=seconds)
        out = {"flops": rec.flops, "bytes_accessed": rec.bytes_accessed,
               "arithmetic_intensity": rec.arithmetic_intensity,
               "peak_hbm_bytes": rec.peak_hbm_bytes, "bound": est.bound}
        if est.utilization is not None:
            out["roofline_frac"] = est.utilization
        return out

    def throughput(self, fn: Callable, nbytes: float, *args,
                   name: Optional[str] = None) -> Dict[str, float]:
        r = self.run(fn, *args, name=name)
        r["gb_per_s"] = nbytes / r["seconds"] / 1e9
        return r


class BlobsFixture(Fixture):
    """(ref: benchmark.hpp ``BlobsFixture:176``)"""

    def __init__(self, n_samples: int, n_features: int, n_clusters: int = 8,
                 seed: int = 0, **kw):
        super().__init__(**kw)
        from raft_tpu.random import RngState, make_blobs

        self.X, self.labels = make_blobs(
            self.res, RngState(seed), n_samples, n_features,
            n_clusters=n_clusters)
        jax.block_until_ready(self.X)
