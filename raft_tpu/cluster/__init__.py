"""raft_tpu.cluster — balanced k-means on the fused primitives. (ref:
cpp/include/raft/cluster — kmeans.cuh / kmeans_balanced.cuh, the coarse
trainers behind the reference's ANN stack.)"""

from raft_tpu.cluster.kmeans import (DEFAULT_BALANCE_ALPHA, KMeansResult,
                                     kmeans_fit, kmeans_inertia,
                                     kmeans_predict)

__all__ = [
    "DEFAULT_BALANCE_ALPHA",
    "KMeansResult",
    "kmeans_fit",
    "kmeans_inertia",
    "kmeans_predict",
]
