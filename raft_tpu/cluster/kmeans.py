"""Balanced k-means on the fused distance primitives.

(ref: cpp/include/raft/cluster/kmeans.cuh +
kmeans_balanced.cuh / detail/kmeans_balanced.cuh — the coarse trainer
behind the reference's IVF indexes. The reference's Lloyd loop is
"minClusterAndDistance (a fusedL2NN sweep) → update_centroids (a
segmented reduction)"; this module is the same decomposition on the
TPU primitives: assignment through
:func:`raft_tpu.distance.fused_l2nn.fused_l2_nn_argmin`, the centroid
update via ``jax.ops.segment_sum``, with the balanced variant applying
a per-iteration cluster-size penalty to the assignment scores the way
``kmeans_balanced``'s adjustCenters pass biases against oversized
clusters.)

Why balance matters here: the IVF-Flat index (:mod:`raft_tpu.ann`)
pads every inverted list to a row quantum and probes whole lists — a
skewed clustering both wastes pad rows and makes per-probe cost
unpredictable. The balanced penalty trades a little inertia for
near-uniform list sizes, which is exactly the trade the reference
makes for its ANN coarse quantizers.

Observability: every fit is ``@instrument``-ed, carries the
``kmeans_fit`` / ``kmeans_iteration`` fault sites
(``RAFT_TPU_FAULTS``), emits one ``marker`` flight event per Lloyd
iteration (inertia, shift, size spread — the convergence trail is
reconstructable from a post-mortem dump), and captures the assignment
step's XLA cost through ``res.profiler.capture_fn`` so the roofline
report can attribute it.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.resources import ensure_resources
from raft_tpu.observability import instrument
from raft_tpu.observability.timeline import emit_marker
from raft_tpu.resilience import fault_point

#: default balanced-penalty exponent: assignment scores are multiplied
#: by ((size + 1) / (mean_size + 1)) ** alpha — oversized clusters look
#: farther, undersized (and empty) ones look closer. 0 disables.
DEFAULT_BALANCE_ALPHA = 0.25

#: row-chunk bound for the weighted assignment sweep: the [chunk, k]
#: score tile stays under ~64 MB f32 at any k
_ASSIGN_TILE = 1 << 24


class KMeansResult(NamedTuple):
    """The fit artifact: ``centroids [k, d]``, the final ``labels [n]``,
    the (true, unpenalized) ``inertia``, iterations run, and the final
    ``cluster_sizes [k]``."""

    centroids: jax.Array
    labels: jax.Array
    inertia: float
    n_iter: int
    cluster_sizes: jax.Array


@partial(jax.jit, static_argnames=("k",))
def _kmeanspp_init(key, Xs, k: int):
    """k-means++ on the (sub)sampled rows ``Xs``: first center uniform,
    then each next center sampled ∝ current min-d2 — one fori_loop, the
    min-d2 carry updated against only the newest center (O(k·n·d)).
    (ref: detail/kmeans_init_plus_plus.cuh.)"""
    n, d = Xs.shape
    xs2 = jnp.sum(Xs * Xs, axis=1)

    def body(i, carry):
        key, centers, mind2 = carry
        key, kc = jax.random.split(key)
        # i == 0: mind2 is all-ones → uniform first pick
        logits = jnp.log(jnp.maximum(mind2, 1e-30))
        idx = jax.random.categorical(kc, logits)
        c = Xs[idx]
        centers = centers.at[i].set(c)
        d2 = jnp.maximum(
            xs2 + jnp.sum(c * c) - 2.0 * (Xs @ c), 0.0)
        return key, centers, jnp.minimum(mind2, d2)

    centers = jnp.zeros((k, d), jnp.float32)
    _, centers, _ = jax.lax.fori_loop(
        0, k, body, (key, centers, jnp.ones((n,), jnp.float32)))
    return centers


@partial(jax.jit, static_argnames=("k",))
def _assign_chunk(Xc, valid, centroids, weights, k: int):
    """One weighted-assignment chunk: expanded-L2 scores [C, k] (the
    same score function fusedL2NN evaluates), multiplied by the
    per-cluster balance weights for the ARGMIN only — the returned
    inertia is the true unpenalized d2. Returns per-chunk labels,
    inertia sum, centroid partial sums and counts (segment-sum — the
    reference's update_centroids reduction)."""
    xx = jnp.sum(Xc * Xc, axis=1, keepdims=True)
    cc = jnp.sum(centroids * centroids, axis=1)
    d2 = jnp.maximum(
        xx + cc[None, :] - 2.0 * (Xc @ centroids.T), 0.0)
    labels = jnp.argmin(d2 * weights[None, :], axis=1).astype(jnp.int32)
    best = jnp.take_along_axis(d2, labels[:, None], axis=1)[:, 0]
    w = valid.astype(jnp.float32)
    inertia = jnp.sum(best * w)
    # pads are routed to segment k (dropped by num_segments=k)
    seg = jnp.where(valid, labels, k)
    sums = jax.ops.segment_sum(Xc * w[:, None], seg, num_segments=k)
    counts = jax.ops.segment_sum(w, seg, num_segments=k)
    return labels, inertia, sums, counts


def _balance_weights(counts, alpha: float):
    """((size + 1) / (mean + 1)) ** alpha — empty clusters get weight
    < 1 (they attract their nearest points back), oversized ones > 1.
    The +1 regularization keeps the weight finite and non-zero at
    size 0, so an empty cluster can never swallow EVERY point in one
    step the way a raw 0-weight would."""
    mean = jnp.mean(counts)
    return ((counts + 1.0) / (mean + 1.0)) ** alpha


def _assign_sweep(X, centroids, weights, k: int, res):
    """Full weighted assignment over chunked rows (python chunk loop on
    a fixed-shape jitted tile — one compile per fit geometry). Returns
    (labels [n], inertia, sums [k, d], counts [k])."""
    n, d = X.shape
    chunk = max(8, min(n, _ASSIGN_TILE // max(1, 4 * k)))
    labels_out, inertia = [], 0.0
    sums = jnp.zeros((k, d), jnp.float32)
    counts = jnp.zeros((k,), jnp.float32)
    # one cost capture per fit geometry (memoized by shape signature):
    # the assignment tile is the hot ~O(n·k·d) kernel of the loop
    try:
        res.profiler.capture_fn(
            "cluster.kmeans_assign", _assign_chunk,
            X[:chunk] if n >= chunk else
            jnp.zeros((chunk, d), jnp.float32),
            jnp.ones((chunk,), jnp.bool_), centroids, weights, k=k)
    except Exception:
        pass
    for s in range(0, n, chunk):
        Xc = X[s:s + chunk]
        c = Xc.shape[0]
        valid = jnp.ones((chunk,), jnp.bool_)
        if c < chunk:
            Xc = jnp.concatenate(
                [Xc, jnp.zeros((chunk - c, d), jnp.float32)])
            valid = jnp.arange(chunk) < c
        lab, ine, sm, ct = _assign_chunk(Xc, valid, centroids, weights,
                                         k=k)
        labels_out.append(lab[:c])
        inertia = inertia + ine
        sums = sums + sm
        counts = counts + ct
    return jnp.concatenate(labels_out), inertia, sums, counts


@instrument("cluster.kmeans_fit")
def kmeans_fit(res, X, n_clusters: int, max_iter: int = 20,
               tol: float = 1e-4, seed: int = 0,
               balanced: bool = False,
               balance_alpha: float = DEFAULT_BALANCE_ALPHA,
               init: str = "kmeans++",
               init_centroids=None,
               n_init: int = 1,
               max_init_rows: Optional[int] = None) -> KMeansResult:
    """Lloyd k-means (ref: cluster/kmeans.cuh ``kmeans::fit``;
    ``balanced=True`` ≈ cluster/kmeans_balanced.cuh).

    - **init**: ``"kmeans++"`` (on a sub-sample of at most
      ``max_init_rows`` rows — default ``max(16·k, 2048)``, the
      reference's trainset_fraction idea) or ``"random"`` (uniform row
      sample). ``init_centroids`` short-circuits both. ``n_init`` > 1
      restarts from that many seeds and keeps the lowest-inertia run
      (the sklearn convention — k-means++ still lands in local optima).
    - **assignment**: the expanded-L2 score fusedL2NN evaluates;
      ``balanced=True`` multiplies the scores per cluster by
      ``((size+1)/(mean+1))**balance_alpha`` — the per-iteration
      cluster-size penalty. The reported inertia is always the TRUE
      (unpenalized) d2 sum.
    - **update**: segment-sum centroid means; empty clusters keep
      their previous centroid (the balanced penalty pulls them back).
    - **convergence**: relative inertia delta ≤ ``tol`` (checked on
      host per iteration — each iteration emits a ``marker`` flight
      event with inertia / max-centroid-shift / size spread).
    """
    fault_point("kmeans_fit")
    res = ensure_resources(res)
    if n_init > 1 and init_centroids is None:
        best = None
        for i in range(int(n_init)):
            r = kmeans_fit(res, X, n_clusters, max_iter=max_iter,
                           tol=tol, seed=seed + i, balanced=balanced,
                           balance_alpha=balance_alpha, init=init,
                           max_init_rows=max_init_rows)
            if best is None or r.inertia < best.inertia:
                best = r
        return best
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    k = int(n_clusters)
    expects(k >= 1, "kmeans_fit: n_clusters must be >= 1, got %d", k)
    expects(n >= k, "kmeans_fit: %d rows < n_clusters=%d", n, k)
    expects(init in ("kmeans++", "random"),
            "kmeans_fit: init must be 'kmeans++' or 'random', got %r",
            init)
    key = jax.random.PRNGKey(seed)
    if init_centroids is not None:
        centroids = jnp.asarray(init_centroids, jnp.float32)
        expects(centroids.shape == (k, d),
                "kmeans_fit: init_centroids shape %s != (%d, %d)",
                centroids.shape, k, d)
    else:
        cap = max_init_rows or max(16 * k, 2048)
        key, ks = jax.random.split(key)
        if n > cap:
            sub = X[jax.random.choice(ks, n, (cap,), replace=False)]
        else:
            sub = X
        if init == "kmeans++":
            key, ki = jax.random.split(key)
            centroids = _kmeanspp_init(ki, sub, k)
        else:
            key, ki = jax.random.split(key)
            centroids = sub[jax.random.choice(
                ki, sub.shape[0], (k,), replace=False)]

    weights = jnp.ones((k,), jnp.float32)
    counts = jnp.zeros((k,), jnp.float32)
    labels = jnp.zeros((n,), jnp.int32)
    inertia = float("inf")
    it = 0
    for it in range(1, max_iter + 1):
        fault_point("kmeans_iteration")
        if balanced and balance_alpha > 0.0:
            weights = _balance_weights(counts, balance_alpha)
        labels, ine, sums, counts = _assign_sweep(
            X, centroids, weights, k, res)
        new_centroids = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts[:, None], 1.0), centroids)
        ine = float(ine)
        shift = float(jnp.max(jnp.sum(
            (new_centroids - centroids) ** 2, axis=1)))
        centroids = new_centroids
        emit_marker("kmeans_iteration", it=it, inertia=ine,
                    max_shift2=shift,
                    size_min=float(jnp.min(counts)),
                    size_max=float(jnp.max(counts)),
                    balanced=bool(balanced))
        if inertia != float("inf") and ine >= inertia * (1.0 - tol):
            inertia = min(inertia, ine)
            break
        inertia = ine
    return KMeansResult(centroids, labels, inertia, it,
                        counts.astype(jnp.int32))


@instrument("cluster.kmeans_predict")
def kmeans_predict(res, centroids, X):
    """Nearest-centroid labels for ``X`` — the fusedL2NN argmin sweep
    (ref: kmeans.cuh ``kmeans::predict`` = minClusterAndDistance).
    Balance weights are a TRAINING bias only; prediction is always the
    true nearest centroid."""
    from raft_tpu.distance.fused_l2nn import fused_l2_nn_argmin

    res = ensure_resources(res)
    X = jnp.asarray(X, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    expects(X.shape[1] == centroids.shape[1],
            "kmeans_predict: dim mismatch %d != %d", X.shape[1],
            centroids.shape[1])
    _, labels = fused_l2_nn_argmin(res, X, centroids)
    return labels


def kmeans_inertia(res, centroids, X, labels=None) -> float:
    """True d2 inertia of a labeling (computed via the argmin sweep
    when ``labels`` is None)."""
    from raft_tpu.distance.fused_l2nn import fused_l2_nn_argmin

    res = ensure_resources(res)
    X = jnp.asarray(X, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    if labels is None:
        d2, _ = fused_l2_nn_argmin(res, X, centroids)
        return float(jnp.sum(d2))
    diff = X - centroids[jnp.asarray(labels, jnp.int32)]
    return float(jnp.sum(diff * diff))
