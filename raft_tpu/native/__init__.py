"""Native hostops loader.

(ref: python/libraft/libraft/load.py:15-30 — the dlopen shim for
libraft.so. Same role here: locate/build cpp/build/libraft_tpu_hostops.so,
bind via ctypes (no pybind11 in this environment), and degrade to
pure-python fallbacks when no toolchain is available.)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CPP_DIR = os.path.join(_REPO_ROOT, "cpp")
_SO_PATH = os.path.join(_CPP_DIR, "build", "libraft_tpu_hostops.so")

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_load_attempted = False


def _try_build() -> bool:
    try:
        subprocess.run(["make", "-C", _CPP_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def load() -> Optional[ctypes.CDLL]:
    """dlopen the hostops library, building it on first use."""
    global _lib, _load_attempted
    with _lib_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not os.path.exists(_SO_PATH) and not _try_build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.pcg32_fill_uint32.argtypes = [
            ctypes.c_uint64, ctypes.c_uint64,
            np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
            ctypes.c_int64]
        lib.pcg32_fill_uniform.argtypes = [
            ctypes.c_uint64, ctypes.c_uint64,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_int64]
        lib.host_select_k.argtypes = [
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
        lib.host_pairwise_l2.argtypes = [
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")]
        lib.host_coo_coalesce.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")]
        lib.host_coo_coalesce.restype = ctypes.c_int64
        lib.tiled_layout_sizes.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
        lib.tiled_layout_fill.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")]
        try:
            lib.tiled_layout_v2_sizes.argtypes = [
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
            lib.tiled_layout_v2_fill.argtypes = [
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")]
        except AttributeError:
            pass   # stale .so predating the v2 symbols — v2 falls back
        lib.pair_layout_sizes.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
        lib.pair_layout_fill.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


# ---------------- PCG32 (native or pure-python fallback) ----------------
def _pcg32_python(seed: int, stream: int, n: int) -> np.ndarray:
    """Bit-exact python rendering of the same PCG32 XSH-RR stream."""
    mask64 = (1 << 64) - 1
    state = 0
    inc = ((stream << 1) | 1) & mask64

    def step(state):
        return (state * 6364136223846793005 + inc) & mask64

    def output(old):
        xorshifted = (((old >> 18) ^ old) >> 27) & 0xFFFFFFFF
        rot = old >> 59
        return ((xorshifted >> rot) | (xorshifted << ((32 - rot) & 31))) & 0xFFFFFFFF

    state = step(state)
    state = (state + seed) & mask64
    state = step(state)
    out = np.empty(n, np.uint32)
    for i in range(n):
        old = state
        state = step(state)
        out[i] = output(old)
    return out


def pcg32_uint32(seed: int, n: int, stream: int = 0) -> np.ndarray:
    """PCG32 random uint32 stream (reference-compatible semantics).
    (ref: thirdparty/pcg/pcg_basic.c stream behavior; GenPC in
    random/rng_state.hpp)"""
    lib = load()
    if lib is not None:
        out = np.empty(n, np.uint32)
        lib.pcg32_fill_uint32(seed, stream, out, n)
        return out
    return _pcg32_python(seed, stream, n)


def pcg32_uniform(seed: int, n: int, stream: int = 0) -> np.ndarray:
    """Uniform [0,1) floats from the PCG32 stream (top 24 bits)."""
    lib = load()
    if lib is not None:
        out = np.empty(n, np.float32)
        lib.pcg32_fill_uniform(seed, stream, out, n)
        return out
    bits = _pcg32_python(seed, stream, n)
    return ((bits >> 8).astype(np.float32) * (1.0 / 16777216.0)).astype(np.float32)


# ---------------- host verification kernels ----------------
def host_select_k(values: np.ndarray, k: int, select_min: bool = True):
    """Host reference top-k (native when available).
    (ref: the naive host loops in cpp/tests/test_utils)"""
    values = np.ascontiguousarray(values, np.float32)
    n_rows, row_len = values.shape
    k = min(k, row_len)  # clamp; keeps native and fallback shapes identical
    lib = load()
    if lib is not None:
        out_v = np.empty((n_rows, k), np.float32)
        out_i = np.empty((n_rows, k), np.int32)
        lib.host_select_k(values, n_rows, row_len, k, int(select_min),
                          out_v, out_i)
        return out_v, out_i
    order = np.argsort(values if select_min else -values, axis=1, kind="stable")
    idx = order[:, :k].astype(np.int32)
    return np.take_along_axis(values, idx, axis=1), idx


def host_pairwise_l2(x: np.ndarray, y: np.ndarray, sqrt: bool = False):
    x = np.ascontiguousarray(x, np.float32)
    y = np.ascontiguousarray(y, np.float32)
    lib = load()
    if lib is not None:
        out = np.empty((x.shape[0], y.shape[0]), np.float32)
        lib.host_pairwise_l2(x, y, x.shape[0], y.shape[0], x.shape[1],
                             int(sqrt), out)
        return out
    d2 = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    return np.sqrt(d2) if sqrt else d2


def host_coo_coalesce(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                      n_cols: int):
    """Sort + sum-duplicates on host (native fast path for the sparse
    coalesce used by add/symmetrize/laplacian)."""
    rows = np.ascontiguousarray(rows, np.int32)
    cols = np.ascontiguousarray(cols, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    lib = load()
    if lib is not None:
        out_r = np.empty_like(rows)
        out_c = np.empty_like(cols)
        out_v = np.empty_like(vals)
        n = lib.host_coo_coalesce(rows, cols, vals, len(rows), n_cols,
                                  out_r, out_c, out_v)
        return out_r[:n], out_c[:n], out_v[:n]
    keys = rows.astype(np.int64) * n_cols + cols
    uniq, inverse = np.unique(keys, return_inverse=True)
    out_v = np.zeros(len(uniq), np.float32)
    np.add.at(out_v, inverse, vals)
    return ((uniq // n_cols).astype(np.int32), (uniq % n_cols).astype(np.int32),
            out_v)


def tiled_layout(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                 n_rows: int, n_cols: int, C: int, R: int, E: int):
    """Native tiled-ELL layout (see cpp/hostops.cpp tiled_layout_*).
    Returns the same tuple the numpy path in sparse/tiled.py builds, or
    None when the native library is unavailable."""
    lib = load()
    if lib is None or len(rows) == 0:
        return None
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    # the C++ pass indexes histograms by id/tile with no bounds checks —
    # validate HERE so bad input raises instead of corrupting the heap
    if (rows.min() < 0 or cols.min() < 0
            or rows.max() >= n_rows or cols.max() >= n_cols):
        raise ValueError(
            "tiled_layout: row/col ids out of range for shape "
            f"({n_rows}, {n_cols})")
    rows = np.ascontiguousarray(rows, np.int32)
    cols = np.ascontiguousarray(cols, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    nnz = len(rows)
    sizes = np.zeros(2, np.int64)
    lib.tiled_layout_sizes(rows, cols, nnz, n_rows, n_cols, C, R, E, sizes)
    gp, sp = int(sizes[0]), int(sizes[1])
    n_row_tiles = max(1, -(-n_rows // R))
    pv = np.empty(gp, np.float32)
    pc = np.empty(gp, np.int32)
    cct = np.empty(gp // E, np.int32)
    perm = np.empty(sp, np.int32)
    rloc = np.empty(sp, np.int32)
    crt = np.empty(sp // E, np.int32)
    visited = np.zeros(n_row_tiles, np.uint8)
    lib.tiled_layout_fill(rows, cols, vals, nnz, n_rows, n_cols, C, R, E,
                          pv, pc, cct, perm, rloc, crt, visited)
    return pv, pc, cct, perm, rloc, crt, visited.astype(bool)


def tiled_layout_v2(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                    n_rows: int, n_cols: int, C: int, R: int, E: int):
    """Native v2 tiled-ELL layout (8-aligned buckets, ROW-granular perm
    — see cpp/hostops.cpp tiled_layout_v2_*). Returns (pv, pc, cct,
    perm_rows, rloc, crt, visited) bit-identical to the numpy v2 branch
    in sparse/tiled.py, or None when the native library is unavailable
    (or predates the symbol)."""
    lib = load()
    if lib is None or len(rows) == 0 or not hasattr(lib,
                                                    "tiled_layout_v2_fill"):
        return None
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    # the C++ pass indexes by id/tile with no bounds checks — validate
    # HERE so bad input raises instead of corrupting the heap
    if (rows.min() < 0 or cols.min() < 0
            or rows.max() >= n_rows or cols.max() >= n_cols):
        raise ValueError(
            "tiled_layout_v2: row/col ids out of range for shape "
            f"({n_rows}, {n_cols})")
    rows = np.ascontiguousarray(rows, np.int32)
    cols = np.ascontiguousarray(cols, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    nnz = len(rows)
    sizes = np.zeros(2, np.int64)
    lib.tiled_layout_v2_sizes(rows, cols, nnz, n_rows, n_cols, C, R, E,
                              sizes)
    gp, sp = int(sizes[0]), int(sizes[1])
    n_row_tiles = max(1, -(-n_rows // R))
    pv = np.empty(gp, np.float32)
    pc = np.empty(gp, np.int32)
    cct = np.empty(gp // E, np.int32)
    perm_rows = np.empty(sp // 8, np.int32)
    rloc = np.empty(sp, np.int32)
    crt = np.empty(sp // E, np.int32)
    visited = np.zeros(n_row_tiles, np.uint8)
    lib.tiled_layout_v2_fill(rows, cols, vals, nnz, n_rows, n_cols,
                             C, R, E, gp, sp,
                             pv, pc, cct, perm_rows, rloc, crt, visited)
    return pv, pc, cct, perm_rows, rloc, crt, visited.astype(bool)


def pair_layout(rows: np.ndarray, cols: np.ndarray, n_rows: int,
                n_cols: int, R: int, C: int, E: int):
    """Native pair-tiled layout (see cpp/hostops.cpp pair_layout_*).
    Returns (rloc, cloc, chunk_row_tile, chunk_col_tile, pos) — the same
    arrays the numpy path in sparse/tiled.py builds — or None when the
    native library is unavailable."""
    lib = load()
    if lib is None or len(rows) == 0:
        return None
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    # the C++ pass indexes by id/tile with no bounds checks — validate
    # HERE so bad input raises instead of corrupting the heap
    if (rows.min() < 0 or cols.min() < 0
            or rows.max() >= n_rows or cols.max() >= n_cols):
        raise ValueError(
            "pair_layout: row/col ids out of range for shape "
            f"({n_rows}, {n_cols})")
    rows = np.ascontiguousarray(rows, np.int32)
    cols = np.ascontiguousarray(cols, np.int32)
    nnz = len(rows)
    size = np.zeros(1, np.int64)
    lib.pair_layout_sizes(rows, cols, nnz, n_cols, R, C, E, size)
    p = int(size[0])
    rloc = np.empty(p, np.int32)
    cloc = np.empty(p, np.int32)
    crt = np.empty(p // E, np.int32)
    cct = np.empty(p // E, np.int32)
    pos = np.empty(nnz, np.int32)
    lib.pair_layout_fill(rows, cols, nnz, n_cols, R, C, E,
                         rloc, cloc, crt, cct, pos)
    return rloc, cloc, crt, cct, pos
