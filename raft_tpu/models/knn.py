"""Brute-force nearest neighbors estimator — the flagship compute path
(fused distance + top-k; BASELINE config 2). (ref: the pre-cuVS
brute_force knn surface.)"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.distance.fused_l2nn import knn as _knn


class NearestNeighbors:
    def __init__(self, n_neighbors: int = 5, metric: str = "sqeuclidean",
                 mesh=None, mesh_axis: str = "x",
                 n_shards: Optional[int] = None,
                 merge: str = "auto",
                 algorithm: str = "brute",
                 n_lists: Optional[int] = None,
                 n_probes: Optional[int] = None,
                 pq_dim: Optional[int] = None,
                 pq_bits: Optional[int] = None,
                 res: Optional[Resources] = None):
        """``mesh``: a ``jax.sharding.Mesh`` makes ``kneighbors`` MNMG
        — the INDEX rows shard over ``mesh[mesh_axis]`` (the
        bigger-than-HBM index mode: per-shard local select + one
        all-gather merge; distance.knn_index_sharded).

        ``n_shards``: shard the index over that many devices through
        the CERTIFIED sharded fused pipeline
        (:func:`raft_tpu.distance.knn_fused_sharded` — per-shard
        stream-once fused kernel + the ``merge`` strategy: "auto" picks
        the ICI cost-model crossover between the allgather and
        tournament merges). Falls back to the streamed
        ``knn_index_sharded`` path for metrics outside the fused
        envelope. Default (both None) keeps the current single-device
        behavior.

        ``algorithm="ivf_flat"`` switches ``fit`` to building an
        IVF-Flat index (:func:`raft_tpu.ann.build_ivf_flat` — balanced
        k-means coarse quantizer + padded ragged inverted lists) and
        ``kneighbors`` to the approximate probe search with
        ``n_probes`` lists per query (``n_probes = n_lists`` degrades
        to exact — the degenerate-exact invariant). L2-family metrics
        only; the default ``"brute"`` keeps every existing path
        unchanged. With ``n_shards``, the lists distribute over the
        mesh (:func:`raft_tpu.ann.shard_ivf_lists`) and per-shard
        top-k candidates merge with the ``merge`` strategy.

        ``algorithm="ivf_pq"`` is the compressed tier
        (:func:`raft_tpu.ann.build_ivf_pq` — per-subspace product-
        quantized codes over the same inverted lists, ~16–32× fewer
        streamed bytes, every returned candidate exact-rescored from
        the retained f32 slab): ``pq_dim`` subspaces of ``pq_bits``-
        bit codes (defaults d/4 and ``RAFT_TPU_ANN_PQ_BITS``).
        Single-device; L2 family only."""
        if algorithm not in ("brute", "ivf_flat", "ivf_pq"):
            raise ValueError(
                f"NearestNeighbors: algorithm must be 'brute', "
                f"'ivf_flat' or 'ivf_pq', got {algorithm!r}")
        if algorithm in ("ivf_flat", "ivf_pq") and metric not in (
                "sqeuclidean", "euclidean", "l2"):
            raise ValueError(
                f"NearestNeighbors: algorithm={algorithm!r} serves "
                f"the L2 family only, got metric={metric!r}")
        if algorithm == "ivf_pq" and n_shards is not None:
            raise ValueError(
                "NearestNeighbors: algorithm='ivf_pq' is single-device"
                " (shard the flat tier via algorithm='ivf_flat')")
        self.res = ensure_resources(res)
        self.n_neighbors = n_neighbors
        self.metric = metric
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.merge = merge
        self.algorithm = algorithm
        self.n_lists = n_lists
        self.n_probes = n_probes
        self.pq_dim = pq_dim
        self.pq_bits = pq_bits
        if n_shards is not None and mesh is None:
            import jax

            from raft_tpu.parallel import make_mesh

            devs = jax.devices()
            if n_shards > len(devs):
                raise ValueError(
                    f"NearestNeighbors: n_shards={n_shards} > "
                    f"{len(devs)} available devices")
            mesh_axis = "x"
            self.mesh_axis = mesh_axis
            self.mesh = make_mesh({mesh_axis: n_shards},
                                  devices=devs[:n_shards])
        self.n_shards = n_shards
        self._index = None

    def fit(self, X) -> "NearestNeighbors":
        if self.algorithm == "ivf_pq":
            from raft_tpu.ann import build_ivf_pq

            X = jnp.asarray(X, jnp.float32)
            n_lists = self.n_lists or max(
                1, min(1024, int(round(X.shape[0] ** 0.5))))
            self._index = build_ivf_pq(self.res, X, n_lists=n_lists,
                                       pq_dim=self.pq_dim,
                                       pq_bits=self.pq_bits,
                                       n_probes=self.n_probes)
            self._n_index = self._index.n_rows
            self._prepared = None
            return self
        if self.algorithm == "ivf_flat":
            from raft_tpu.ann import build_ivf_flat, shard_ivf_lists

            X = jnp.asarray(X, jnp.float32)
            n_lists = self.n_lists or max(
                1, min(1024, int(round(X.shape[0] ** 0.5))))
            self._index = build_ivf_flat(self.res, X, n_lists=n_lists,
                                         n_probes=self.n_probes)
            self._n_index = self._index.n_rows
            self._prepared = None
            if self.mesh is not None:
                self._index = shard_ivf_lists(self._index, self.mesh,
                                              self.mesh_axis)
            return self
        if self.mesh is not None and self.n_shards is not None:
            # fused sharded path: build the ShardedFusedIndex once
            kernel_metric = {"sqeuclidean": "l2", "euclidean": "l2",
                             "l2": "l2",
                             "inner_product": "ip"}.get(self.metric)
            if kernel_metric is not None:
                from raft_tpu.distance.knn_sharded import \
                    prepare_knn_index_sharded

                self._index = prepare_knn_index_sharded(
                    X, mesh=self.mesh, axis=self.mesh_axis,
                    metric=kernel_metric, res=self.res)
                self._n_index = self._index.n_rows
                self._prepared = None
                return self
            # metric outside the fused envelope: the streamed sharded
            # path below still serves it
        if self.mesh is not None:
            # MNMG: pad + shard ONCE, straight from host — the full
            # matrix never materializes on one device (the
            # bigger-than-HBM index mode this exists for)
            from raft_tpu.distance.fused_l2nn import prepare_index_sharded

            self._index = prepare_index_sharded(self.res, X, self.mesh,
                                                self.mesh_axis)
            self._n_index = self._index.n
            self._prepared = None
            return self
        self._index = jnp.asarray(X, jnp.float32)
        self._n_index = self._index.shape[0]
        # build/query split: prepare the fused-pipeline index operands
        # once, mirroring knn()'s own auto-routing condition (TPU +
        # fused-eligible shape); anything else stays unprepared and
        # takes knn()'s normal dispatch
        self._prepared = None
        kernel_metric = {"sqeuclidean": "l2", "euclidean": "l2",
                         "l2": "l2", "inner_product": "ip"}.get(self.metric)
        try:
            from raft_tpu.distance.knn_fused import (
                fused_eligible, prepare_knn_index)

            if (kernel_metric is not None
                    and fused_eligible(*self._index.shape)):
                self._prepared = prepare_knn_index(
                    self._index, metric=kernel_metric)
                # the KnnIndex's row-padded yp already holds the full
                # f32 matrix; keeping self._index too would pin a
                # redundant ~512 MB copy in HBM at 1M×128
                self._index = None
        except Exception:
            self._prepared = None   # preparation is an optimization only
        return self

    @property
    def _index_matrix(self):
        from raft_tpu.distance.knn_sharded import ShardedFusedIndex

        if isinstance(self._index, ShardedFusedIndex):
            # sharded fused fit: the true rows of the row-sharded yp
            return self._index.yp_s[:self._index.n_rows,
                                    :self._index.d_orig]
        if self.mesh is not None:
            # sharded fit: slice the true rows of the global array
            return self._index.idx_s[:self._index.n]
        if self._index is not None:
            return self._index
        p = self._prepared
        return p.yp[:p.n_rows, :p.d_orig]

    def kneighbors(self, queries, n_neighbors: Optional[int] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        k = n_neighbors or self.n_neighbors
        if self.algorithm == "ivf_pq":
            from raft_tpu.ann import search_ivf_pq

            dists, idx = search_ivf_pq(self.res, self._index, queries,
                                       k, n_probes=self.n_probes)
            if self.metric in ("euclidean", "l2"):
                dists = jnp.sqrt(jnp.maximum(dists, 0.0))
            return dists, idx
        if self.algorithm == "ivf_flat":
            from raft_tpu.ann import search_ivf_flat

            dists, idx = search_ivf_flat(
                self.res, self._index, queries, k,
                n_probes=self.n_probes, merge=self.merge)
            if self.metric in ("euclidean", "l2"):
                dists = jnp.sqrt(jnp.maximum(dists, 0.0))
            return dists, idx
        from raft_tpu.distance.knn_sharded import ShardedFusedIndex

        if isinstance(self._index, ShardedFusedIndex):
            from raft_tpu.distance.knn_sharded import knn_fused_sharded

            dists, idx = knn_fused_sharded(
                queries, self._index, k, mesh=self.mesh,
                axis=self.mesh_axis, merge=self.merge, res=self.res)
            if self.metric in ("euclidean", "l2"):
                dists = jnp.sqrt(jnp.maximum(dists, 0.0))
            return dists, idx
        if self.mesh is not None:
            from raft_tpu.distance.fused_l2nn import knn_index_sharded

            return knn_index_sharded(self.res, self._index, queries, k,
                                     mesh=self.mesh, axis=self.mesh_axis,
                                     metric=self.metric)
        if self._prepared is not None and k <= self._prepared.n_rows:
            try:
                return _knn(self.res, self._prepared, queries, k,
                            metric=self.metric)
            except NotImplementedError:
                pass   # off-envelope k: fall through to normal dispatch
        return _knn(self.res, self._index_matrix, queries, k,
                    metric=self.metric)

    def kneighbors_graph(self, queries):
        """KNN as a CSR adjacency (for spectral embedding pipelines)."""
        from raft_tpu.core.sparse_types import CSRMatrix

        d, i = self.kneighbors(queries)
        nq, k = i.shape
        indptr = jnp.arange(nq + 1, dtype=jnp.int32) * k
        return CSRMatrix(indptr, i.reshape(-1), d.reshape(-1),
                         (nq, self._n_index))
