"""Brute-force nearest neighbors estimator — the flagship compute path
(fused distance + top-k; BASELINE config 2). (ref: the pre-cuVS
brute_force knn surface.)"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.distance.fused_l2nn import knn as _knn


class NearestNeighbors:
    def __init__(self, n_neighbors: int = 5, metric: str = "sqeuclidean",
                 res: Optional[Resources] = None):
        self.res = ensure_resources(res)
        self.n_neighbors = n_neighbors
        self.metric = metric
        self._index = None

    def fit(self, X) -> "NearestNeighbors":
        self._index = jnp.asarray(X, jnp.float32)
        return self

    def kneighbors(self, queries, n_neighbors: Optional[int] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        k = n_neighbors or self.n_neighbors
        return _knn(self.res, self._index, queries, k, metric=self.metric)

    def kneighbors_graph(self, queries):
        """KNN as a CSR adjacency (for spectral embedding pipelines)."""
        from raft_tpu.core.sparse_types import CSRMatrix

        d, i = self.kneighbors(queries)
        nq, k = i.shape
        indptr = jnp.arange(nq + 1, dtype=jnp.int32) * k
        return CSRMatrix(indptr, i.reshape(-1), d.reshape(-1),
                         (nq, self._index.shape[0]))
