"""KMeans estimator — the sklearn-shaped wrapper over
:mod:`raft_tpu.cluster`. (ref: the reference's kmeans.cuh fit/predict
surface as consumed by cuML's KMeans.)"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources


class KMeans:
    """scikit-learn-compatible k-means.

    ``balanced=True`` routes through the balanced variant (the
    per-iteration cluster-size penalty à la ``kmeans_balanced`` — the
    coarse trainer the IVF tier uses). Attributes after ``fit``:
    ``cluster_centers_``, ``labels_``, ``inertia_``, ``n_iter_``."""

    def __init__(self, n_clusters: int = 8, max_iter: int = 300,
                 tol: float = 1e-4, random_state: int = 0,
                 balanced: bool = False, init: str = "kmeans++",
                 n_init: int = 3,
                 res: Optional[Resources] = None):
        self.res = ensure_resources(res)
        self.n_clusters = int(n_clusters)
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.random_state = int(random_state)
        self.balanced = bool(balanced)
        self.init = init
        self.n_init = int(n_init)
        self.cluster_centers_ = None
        self.labels_ = None
        self.inertia_ = None
        self.n_iter_ = None

    def fit(self, X) -> "KMeans":
        from raft_tpu.cluster import kmeans_fit

        r = kmeans_fit(self.res, X, self.n_clusters,
                       max_iter=self.max_iter, tol=self.tol,
                       seed=self.random_state, balanced=self.balanced,
                       init=self.init, n_init=self.n_init)
        self.cluster_centers_ = r.centroids
        self.labels_ = r.labels
        self.inertia_ = float(r.inertia)
        self.n_iter_ = int(r.n_iter)
        return self

    def predict(self, X):
        from raft_tpu.cluster import kmeans_predict

        if self.cluster_centers_ is None:
            raise RuntimeError("KMeans: call fit() before predict()")
        return kmeans_predict(self.res, self.cluster_centers_, X)

    def fit_predict(self, X):
        return self.fit(X).labels_

    def transform(self, X):
        """Distances (euclidean, sklearn convention) to each center."""
        if self.cluster_centers_ is None:
            raise RuntimeError("KMeans: call fit() before transform()")
        from raft_tpu.distance.pairwise import pairwise_distance

        return pairwise_distance(self.res, jnp.asarray(X, jnp.float32),
                                 self.cluster_centers_,
                                 metric="euclidean")
