"""TruncatedSVD estimator. (ref: linalg/tsvd.cuh pipeline.)"""

from __future__ import annotations

from typing import Optional

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.linalg.pca import Solver
from raft_tpu.linalg.tsvd import (
    ParamsTSVD,
    TSVDModel,
    tsvd_fit,
    tsvd_fit_distributed,
    tsvd_inverse_transform,
    tsvd_transform,
)


class TruncatedSVD:
    def __init__(self, n_components: int, solver: Solver = Solver.COV_EIG_DC,
                 mesh=None, mesh_axis: str = "x",
                 res: Optional[Resources] = None):
        """``mesh``: a ``jax.sharding.Mesh`` makes ``fit`` MNMG (rows
        shard over ``mesh[mesh_axis]``; see tsvd_fit_distributed)."""
        self.res = ensure_resources(res)
        self.prms = ParamsTSVD(n_components=n_components, algorithm=solver)
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.model: Optional[TSVDModel] = None

    def fit(self, X) -> "TruncatedSVD":
        if self.mesh is not None:
            self.model = tsvd_fit_distributed(self.res, X, self.prms,
                                              self.mesh, self.mesh_axis)
        else:
            self.model = tsvd_fit(self.res, X, self.prms)
        return self

    def transform(self, X):
        return tsvd_transform(self.res, X, self.model)

    def fit_transform(self, X):
        return self.fit(X).transform(X)

    def inverse_transform(self, T):
        return tsvd_inverse_transform(self.res, T, self.model)

    @property
    def components_(self):
        return self.model.components

    @property
    def explained_variance_(self):
        return self.model.explained_var

    @property
    def explained_variance_ratio_(self):
        return self.model.explained_var_ratio

    @property
    def singular_values_(self):
        return self.model.singular_vals
