"""Spectral embedding estimator — the BASELINE config-4 pipeline
(COO Laplacian + Lanczos) as a model. (ref: spectral analysis layer +
sparse/solver/lanczos; SURVEY §2.6 note that the BASELINE "spectral
embedding" = compute_graph_laplacian + lanczos_compute_eigenpairs.)"""

from __future__ import annotations

from typing import Optional, Union

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.spectral.analysis import fit_embedding


class SpectralEmbedding:
    def __init__(self, n_components: int = 2, normalized: bool = True,
                 drop_first: bool = True, ncv: Optional[int] = None,
                 tolerance: float = 1e-5, max_iterations: int = 2000,
                 seed: int = 42, jit_loop=None, tiled="auto",
                 mesh=None, mesh_axis: str = "x",
                 res: Optional[Resources] = None):
        """``mesh``: a ``jax.sharding.Mesh`` makes the fit MNMG — the
        Laplacian's rows shard over ``mesh[mesh_axis]`` and the Lanczos
        matvec runs the shard_map SpMV (see
        spectral.analysis.fit_embedding)."""
        self.res = ensure_resources(res)
        self.n_components = n_components
        self.normalized = normalized
        self.drop_first = drop_first
        self.ncv = ncv
        self.tolerance = tolerance
        self.max_iterations = max_iterations
        self.seed = seed
        self.jit_loop = jit_loop
        self.tiled = tiled
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.eigenvalues_ = None
        self.embedding_ = None

    def fit(self, adjacency: Union[COOMatrix, CSRMatrix]) -> "SpectralEmbedding":
        vals, emb = fit_embedding(
            self.res, adjacency, self.n_components, ncv=self.ncv,
            tolerance=self.tolerance, max_iterations=self.max_iterations,
            seed=self.seed, drop_first=self.drop_first,
            normalized=self.normalized, jit_loop=self.jit_loop,
            tiled=self.tiled, mesh=self.mesh, mesh_axis=self.mesh_axis)
        self.eigenvalues_ = vals
        self.embedding_ = emb
        return self

    def fit_transform(self, adjacency):
        return self.fit(adjacency).embedding_
