"""PCA estimator. (ref: the cuML-style PCA the reference's linalg/pca.cuh
serves — linalg/pca_types.hpp params; estimator shape follows sklearn.)"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.linalg.pca import (
    ParamsPCA,
    PCAModel,
    Solver,
    pca_fit,
    pca_fit_distributed,
    pca_inverse_transform,
    pca_transform,
)


class PCA:
    def __init__(self, n_components: int, whiten: bool = False,
                 solver: Solver = Solver.COV_EIG_DC, mesh=None,
                 mesh_axis: str = "x", res: Optional[Resources] = None):
        """``mesh``: a ``jax.sharding.Mesh`` makes ``fit`` MNMG — rows
        shard over ``mesh[mesh_axis]`` and the mean/cov statistics run
        as psums inside shard_map (linalg.pca.pca_fit_distributed)."""
        self.res = ensure_resources(res)
        self.prms = ParamsPCA(n_components=n_components, whiten=whiten,
                              algorithm=solver)
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.model: Optional[PCAModel] = None

    def fit(self, X) -> "PCA":
        if self.mesh is not None:
            self.model = pca_fit_distributed(self.res, X, self.prms,
                                             self.mesh, self.mesh_axis)
        else:
            self.model = pca_fit(self.res, X, self.prms)
        return self

    def transform(self, X):
        return pca_transform(self.res, X, self.model, self.prms)

    def fit_transform(self, X):
        return self.fit(X).transform(X)

    def inverse_transform(self, T):
        return pca_inverse_transform(self.res, T, self.model, self.prms)

    @property
    def components_(self):
        return self.model.components

    @property
    def explained_variance_(self):
        return self.model.explained_var

    @property
    def explained_variance_ratio_(self):
        return self.model.explained_var_ratio

    @property
    def singular_values_(self):
        return self.model.singular_vals

    @property
    def mean_(self):
        return self.model.mu

    @property
    def noise_variance_(self):
        return self.model.noise_vars
