"""raft_tpu.models — estimator-style wrappers over the primitive layer.

The reference is a primitives library; its "models" are the composite
pipelines downstream RAPIDS products assemble (PCA/TSVD fit-transform,
spectral embedding, brute-force KNN). These wrappers are those pipelines
with a scikit-learn-shaped API, and they are the flagship entry points the
driver compile-checks (__graft_entry__).
"""

from raft_tpu.models.pca import PCA
from raft_tpu.models.tsvd import TruncatedSVD
from raft_tpu.models.spectral_embedding import SpectralEmbedding
from raft_tpu.models.knn import NearestNeighbors
from raft_tpu.models.kmeans import KMeans

__all__ = ["PCA", "TruncatedSVD", "SpectralEmbedding",
           "NearestNeighbors", "KMeans"]
