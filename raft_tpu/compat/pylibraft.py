"""pylibraft-compatible API.

(ref: python/pylibraft/pylibraft — ``DeviceResources``
(common/handle.pyx:21-123), deprecated ``Handle`` (:125),
``@auto_sync_handle`` (:196), ``device_ndarray``
(common/device_ndarray.py:16-157), ``sparse.linalg.eigsh``
(sparse/linalg/lanczos.pyx:100), ``svds`` (sparse/linalg/svds.pyx:73),
``random.rmat`` (random/rmat_rectangular_generator.pyx).)

A pylibraft user should be able to switch imports to
``raft_tpu.compat`` and keep their code.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import DeviceResources, Handle, ensure_resources


def auto_sync_handle(fn):
    """Decorator: default handle when none given, block on the result
    before returning — pylibraft's synchronous call contract.
    (ref: common/handle.pyx:196 ``@auto_sync_handle``)"""

    @functools.wraps(fn)
    def wrapper(*args, handle: Optional[DeviceResources] = None, **kwargs):
        handle = ensure_resources(handle)
        out = fn(*args, handle=handle, **kwargs)
        jax.block_until_ready(out)
        return out

    return wrapper


class device_ndarray:  # noqa: N801 — pylibraft spelling
    """NumPy-like device array. (ref: common/device_ndarray.py:16 — a
    device buffer with numpy semantics; here backed by a jax.Array.)"""

    def __init__(self, np_arr):
        self._array = jnp.asarray(np_arr)

    @classmethod
    def empty(cls, shape, dtype=np.float32, order="C"):
        return cls(jnp.zeros(shape, dtype=dtype))

    @classmethod
    def zeros(cls, shape, dtype=np.float32):
        return cls(jnp.zeros(shape, dtype=dtype))

    @classmethod
    def ones(cls, shape, dtype=np.float32):
        return cls(jnp.ones(shape, dtype=dtype))

    @property
    def shape(self):
        return self._array.shape

    @property
    def dtype(self):
        return self._array.dtype

    @property
    def ndim(self):
        return self._array.ndim

    def copy_to_host(self) -> np.ndarray:
        """(ref: device_ndarray.copy_to_host)"""
        return np.asarray(self._array)

    def to_jax(self) -> jax.Array:
        return self._array

    def __array__(self, dtype=None):
        host = self.copy_to_host()
        return host.astype(dtype) if dtype is not None else host

    def __repr__(self):
        return f"device_ndarray(shape={self.shape}, dtype={self.dtype})"


def _unwrap(x):
    return x.to_jax() if isinstance(x, device_ndarray) else jnp.asarray(x)


class ai_wrapper:  # noqa: N801 — pylibraft spelling
    """Adapter over any object exposing the numpy ``__array_interface__``
    (or buffer protocol). (ref: pylibraft/common/ai_wrapper.py — shape/
    dtype introspection + zero-copy handoff into primitives.)"""

    def __init__(self, obj):
        self._np = np.asarray(obj)

    @property
    def shape(self):
        return self._np.shape

    @property
    def dtype(self):
        return self._np.dtype

    @property
    def c_contiguous(self) -> bool:
        return self._np.flags["C_CONTIGUOUS"]

    def to_jax(self) -> jax.Array:
        return jnp.asarray(self._np)


class cai_wrapper:  # noqa: N801 — pylibraft spelling
    """Device-array adapter. (ref: pylibraft/common/cai_wrapper.py — wraps
    ``__cuda_array_interface__`` objects; the TPU analog accepts anything
    speaking dlpack — jax/torch/cupy arrays — falling back to a host copy
    for strided/exotic layouts dlpack can't express zero-copy.)"""

    def __init__(self, obj):
        if isinstance(obj, device_ndarray):
            self._jax = obj.to_jax()
        elif isinstance(obj, jax.Array):
            self._jax = obj
        else:
            self._jax = None
            if hasattr(obj, "__dlpack__"):
                try:
                    self._jax = jnp.from_dlpack(obj)
                except Exception:
                    self._jax = None  # non-compact striding → copy below
            if self._jax is None:
                self._jax = jnp.asarray(np.asarray(obj))

    @property
    def shape(self):
        return self._jax.shape

    @property
    def dtype(self):
        return np.dtype(self._jax.dtype)

    @property
    def c_contiguous(self) -> bool:
        return True  # jax arrays are logically dense row-major

    def to_jax(self) -> jax.Array:
        return self._jax


def eigsh(A, k: int = 6, which: str = "LM", v0=None, ncv: Optional[int] = None,
          maxiter: Optional[int] = None, tol: float = 0.0, seed: int = 42,
          handle: Optional[DeviceResources] = None):
    """scipy.sparse.linalg.eigsh-compatible Lanczos.
    (ref: sparse/linalg/lanczos.pyx:100 — same signature/defaults:
    which="LM", maxiter=None → 10·n, tol=0 → machine eps; accepts
    scipy sparse, raft_tpu sparse types, device_ndarray or dense.)
    Returns (eigenvalues, eigenvectors)."""
    from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
    from raft_tpu.sparse.solver.lanczos import lanczos_compute_eigenpairs
    from raft_tpu.sparse.solver.lanczos_types import LANCZOS_WHICH, LanczosSolverConfig

    handle = ensure_resources(handle)
    if isinstance(A, (COOMatrix, CSRMatrix)):
        op = A
    elif hasattr(A, "tocoo"):  # scipy sparse
        coo = A.tocoo()
        op = COOMatrix(jnp.asarray(coo.row, jnp.int32),
                       jnp.asarray(coo.col, jnp.int32),
                       jnp.asarray(coo.data.astype(np.float32)), coo.shape)
    else:
        op = _unwrap(A)
    n = op.shape[0]
    if maxiter is None:
        maxiter = 10 * n  # (ref: lanczos.pyx:174-175)
    # tol=0 → machine eps OF THE OPERAND DTYPE (ref: lanczos.pyx:176-177) —
    # sparse inputs are f32 here, but a dense f64 operand (x64 mode) keeps
    # its dtype through the solver
    op_dtype = np.dtype(getattr(op, "dtype", np.float32))
    if not np.issubdtype(op_dtype, np.floating):
        op_dtype = np.dtype(np.float32)
    config = LanczosSolverConfig(
        n_components=k, max_iterations=maxiter, ncv=ncv,
        tolerance=tol if tol > 0 else float(np.finfo(op_dtype).eps),
        which=LANCZOS_WHICH[which.upper()], seed=seed)
    vals, vecs = lanczos_compute_eigenpairs(handle, op, config, v0=v0)
    jax.block_until_ready(vecs)
    return vals, vecs


def svds(A, k: int, n_oversamples: int = 10, n_power_iters: int = 2,
         seed: int = 42, handle: Optional[DeviceResources] = None):
    """Sparse randomized SVD. (ref: sparse/linalg/svds.pyx:73)
    Returns (U, S, V)."""
    from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
    from raft_tpu.sparse.convert import coo_to_csr
    from raft_tpu.sparse.solver.randomized_svds import SvdsConfig, randomized_svds

    handle = ensure_resources(handle)
    if hasattr(A, "tocoo"):
        coo = A.tocoo()
        A = coo_to_csr(COOMatrix(jnp.asarray(coo.row, jnp.int32),
                                 jnp.asarray(coo.col, jnp.int32),
                                 jnp.asarray(coo.data.astype(np.float32)),
                                 coo.shape))
    elif isinstance(A, COOMatrix):
        A = coo_to_csr(A)
    out = randomized_svds(handle, A, SvdsConfig(
        n_components=k, n_oversamples=n_oversamples,
        n_power_iters=n_power_iters, seed=seed))
    jax.block_until_ready(out)
    return out


def rmat(out, theta, r_scale: int, c_scale: int, seed: int = 12345,
         handle: Optional[DeviceResources] = None):
    """R-MAT edge generator, pylibraft signature: fills ``out`` [n_edges, 2]
    (returned, since jax arrays are immutable).
    (ref: random/rmat_rectangular_generator.pyx ``rmat``)"""
    from raft_tpu.random.rmat import rmat_rectangular_gen
    from raft_tpu.random.rng_state import RngState

    handle = ensure_resources(handle)
    n_edges = out.shape[0] if hasattr(out, "shape") else int(out)
    src, dst = rmat_rectangular_gen(handle, RngState(seed), n_edges, r_scale,
                                    c_scale, theta=theta)
    result = jnp.stack([src, dst], axis=1)
    jax.block_until_ready(result)
    if isinstance(out, device_ndarray):
        out._array = result
        return out
    return result
