"""raft_tpu.compat — the pylibraft-compatible API surface.

(ref: python/pylibraft — SURVEY §7: "keep the pylibraft API names
(eigsh, svds, rmat, DeviceResources) as the compat surface".)
"""

from raft_tpu.compat.pylibraft import (
    DeviceResources,
    ai_wrapper,
    cai_wrapper,
    Handle,
    auto_sync_handle,
    device_ndarray,
    eigsh,
    rmat,
    svds,
)

__all__ = [
    "DeviceResources", "Handle", "auto_sync_handle", "device_ndarray",
    "ai_wrapper", "cai_wrapper", "eigsh", "svds", "rmat",
]
