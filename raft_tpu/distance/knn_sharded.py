"""Sharded stream-once KNN — database-parallel fused top-k across the mesh.

(ref: the reference's MNMG brute-force path — each GPU runs the fused
L2/top-k over its database shard and the per-shard candidate lists meet
in ``knn_merge_parts`` (spatial/knn/detail/knn_merge_parts.cuh) over the
comms layer; FAISS's multi-GPU ``IndexShards`` applies the same
database-sharding pattern. The TPU rendering: the index rows shard over
a named mesh axis with ``shard_map``, every device runs the PR-3 packed
db-major fused kernel (:mod:`raft_tpu.distance.knn_fused`) over its
shard — so each chip streams ITS slice of the database from HBM once —
and the per-shard candidates merge over ICI.)

Two merge strategies, selected by the ICI cost model
(:func:`raft_tpu.observability.costmodel.choose_merge_strategy`):

- ``"allgather"``: one ring all-gather of every shard's [nq, k]
  candidate block (value + global id), then ONE select over the
  p·k-wide pool. Minimal rounds (one collective + one select); per-
  device egress grows with p−1.
- ``"tournament"``: a log₂(p)-round butterfly of ``collective_permute``
  pair-exchanges; each round every rank merges its k candidates with
  its partner's via a select over 2k. log₂(p) blocks of wire instead of
  p−1 — less traffic for p ≥ 4, at the price of serialized rounds.
  Needs a power-of-two shard count (requests on other counts downgrade
  to allgather with a logged reason).

Both merges are deterministic and rank-ordered (lower mesh index's
candidates first), so every shard computes the bit-identical merged
result — the output is truly replicated, and ties break the same way
on every device.

**Overlapped merge**: queries split into ``micro_batches`` blocks inside
ONE traced program. Block i's local fused kernel has no data dependence
on block i−1's merge collectives, so XLA's latency-hiding scheduler is
free to overlap the ICI rounds with the next block's MXU work — the
SPMD analog of the reference's stream-overlapped ``knn_merge_parts``
copy-in. On CPU (the tier-1 suite) the split is correctness-only.

**Query-sharded mode** (``shard_mode="query"``): the serving shape —
index replicated (it fits one chip), queries data-parallel over the
axis, no merge at all. The sharded sibling of
:func:`raft_tpu.distance.fused_l2nn.knn_sharded` but on the fused
certified pipeline with a prepared index.

Everything is CPU-testable under
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (interpret-mode
Pallas inside shard_map) and bit-exact against the single-device
:func:`knn_fused` oracle — see tests/test_knn_sharded.py.
"""

from __future__ import annotations

import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.comms import MeshComms
from raft_tpu.core.error import (DeviceError, OutOfMemoryError,
                                 device_errors, expects)
from raft_tpu.core.resources import ensure_resources
from raft_tpu.observability import instrument
from raft_tpu.observability.costmodel import (MERGE_STRATEGIES,
                                              choose_merge_strategy)
from raft_tpu.resilience import (PoisonedOutputError, degrade_merge,
                                 fault_point, faults_active,
                                 record_degradation, record_exhausted,
                                 record_retry)
from raft_tpu.distance.knn_fused import (
    _D_SINGLE_SHOT, _DC, _LANES, _PACK_BITS, _PBITS_MAX, _POOL_PAD,
    _Q_CHUNK, DB_DTYPES, GRID_ORDERS, KnnIndex, _knn_fused_core,
    _prepare_ops, _prepare_ops_q8, auto_pack_bits, fit_config,
    fixup_tiers_for, fused_config, pool_select_algo, prepare_knn_index,
    rescore_pool_width, resolve_db_dtype, resolve_grid_order,
    resolve_pool_algo)
from raft_tpu.observability.quality import record_pending

SHARD_MODES = ("db", "query")

# compiled shard_map programs, keyed by the full static geometry — a
# fresh closure per call would defeat the jit cache (same pattern as
# fused_l2nn._SHARDED_KNN_CACHE)
_SHARDED_FUSED_CACHE: dict = {}


def resolve_merge_strategy(merge: str, p: int, nq: int, k: int) -> str:
    """EFFECTIVE merge strategy for a call — decided (and logged) in the
    non-jitted wrapper like ``resolve_grid_order``, so a downgraded
    request is visible per call. ``"auto"`` takes the ICI cost-model
    crossover; a tournament request on a non-power-of-two shard count
    downgrades to allgather (the butterfly needs a partner every
    round). ``"host"`` — the bottom rung of the collective-failure
    ladder — is also requestable directly: no merge collective at all,
    per-shard candidates gathered and selected on the host."""
    if merge not in ("auto", "host") + MERGE_STRATEGIES:
        raise ValueError(f"merge must be 'auto', 'host' or one of "
                         f"{MERGE_STRATEGIES}, got {merge!r}")
    if merge == "host":
        return merge
    if merge == "auto":
        return choose_merge_strategy(p, nq, k)
    if merge == "tournament" and (p & (p - 1)):
        from raft_tpu.core.logger import log_warn

        log_warn("merge='tournament' needs a power-of-two shard count "
                 "(got p=%d) — using 'allgather' for this call", p)
        return "allgather"
    return merge


def default_micro_batches(nq: int, Qb: int) -> int:
    """Micro-batch count when the caller (or a tuned table) doesn't say:
    enough blocks that merge rounds have a next block to hide behind,
    but never blocks smaller than one kernel query block. Also bounds
    each block at ``_Q_CHUNK`` (the fused pipeline's slot-array
    budget)."""
    if nq <= max(Qb, 8):
        nb = 1
    else:
        nb = min(4, max(1, nq // max(Qb, 8)))
    return max(nb, -(-nq // _Q_CHUNK))


class ShardedFusedIndex:
    """A database-sharded fused-KNN index: the :class:`KnnIndex` operand
    set laid out as row-sharded global arrays over a mesh axis, each
    shard padded to whole certificate groups. Build once with
    :func:`prepare_knn_index_sharded`; query with
    :func:`knn_fused_sharded`. The tiling config, metric and mesh are
    frozen at build time (the per-shard row padding bakes them in)."""

    def __init__(self, yp_s, y_hi_s, y_lo_s, yyh_s, yy_s, n_rows: int,
                 rows_per: int, mesh, axis: str, T: int, Qb: int, g: int,
                 passes: int, metric: str, d_orig: int, pbits: int,
                 grid_order: str, db_dtype: str = "bf16",
                 y_q_s=None, scale_s=None, eq_s=None):
        self.yp_s = yp_s                  # [p·rows_per, d_eff] or None
        self.y_hi_s, self.y_lo_s = y_hi_s, y_lo_s
        self.yyh_s, self.yy_s = yyh_s, yy_s
        self.n_rows = n_rows              # true (unpadded) global rows
        self.rows_per = rows_per          # rows per shard (padded)
        self.mesh, self.axis = mesh, axis
        self.T, self.Qb, self.g = T, Qb, g
        self.passes, self.metric = passes, metric
        self.d_orig = d_orig
        self.pbits = pbits
        self.grid_order = grid_order
        # quantized-streaming state (db_dtype="int8"): each shard
        # quantizes ITS groups — scales and the per-group Eq bound are
        # per-shard values, so every shard's certificate widens by its
        # own worst group, never a remote one's
        self.db_dtype = db_dtype
        self.y_q_s = y_q_s                # [p·rows_per, d_eff] int8
        self.scale_s = scale_s            # [p·G_loc, 8, 128] f32
        self.eq_s = eq_s                  # [p·G_loc] f32

    @property
    def stream_width(self) -> int:
        src = self.y_q_s if self.db_dtype == "int8" else self.y_hi_s
        return src.shape[1]

    @property
    def n_shards(self) -> int:
        return int(self.mesh.shape[self.axis])


def prepare_knn_index_sharded(y, mesh=None, axis: str = "x",
                              passes: int = 3, metric: str = "l2",
                              T: Optional[int] = None,
                              Qb: Optional[int] = None,
                              g: Optional[int] = None,
                              store_yp: bool = True,
                              grid_order: Optional[str] = None,
                              db_dtype: str = "bf16",
                              res=None) -> ShardedFusedIndex:
    """Build a :class:`ShardedFusedIndex`: rows pad to ``p`` equal
    shards of whole certificate groups (``g·T`` rows for the
    database-major orders, ``T`` otherwise) ON HOST, land row-sharded
    via one ``device_put`` (the full f32 matrix never materializes on
    one device — the point of the bigger-than-HBM mode), and the
    index-side operand prep (bf16 hi/lo split, norms, sentinel carrier)
    runs per shard inside ``shard_map``, with each shard's real-row
    count threaded as a traced value so global pad rows carry the
    never-wins sentinel.

    The tiling config resolves against the PER-SHARD shape (pack width
    from the shard's tile count — a 10M-row index split 8 ways packs
    like a 1.25M-row one), so per-device kernels run exactly the config
    a single-chip index of that size would."""
    res = ensure_resources(res)
    if mesh is None:
        mesh = res.mesh
    expects(mesh is not None,
            "prepare_knn_index_sharded: pass mesh= or set it on res")
    expects(axis in mesh.axis_names,
            "prepare_knn_index_sharded: axis %r not in mesh axes %s",
            axis, tuple(mesh.axis_names))
    if metric not in ("l2", "ip"):
        raise ValueError(f"prepare_knn_index_sharded: metric must be "
                         f"'l2' or 'ip', got {metric!r}")
    if db_dtype not in DB_DTYPES:
        raise ValueError(f"prepare_knn_index_sharded: db_dtype must be "
                         f"one of {DB_DTYPES}, got {db_dtype!r}")
    y = np.asarray(y, np.float32)
    m, d = y.shape
    p = int(mesh.shape[axis])
    dcfg = fused_config(passes, db_dtype)
    T = dcfg.T if T is None else T
    Qb = dcfg.Qb if Qb is None else Qb
    grid_order = dcfg.grid_order if grid_order is None else grid_order
    if grid_order not in GRID_ORDERS:
        raise ValueError(f"prepare_knn_index_sharded: grid_order must "
                         f"be one of {GRID_ORDERS}, got {grid_order!r}")
    if db_dtype == "int8" and grid_order == "query":
        grid_order = "db"      # quantized kernels are database-major
    T, Qb = fit_config(T, Qb, d, passes, g or dcfg.g, grid_order,
                       db_dtype)
    m_shard = -(-m // p)
    n_tiles_est = max(1, -(-m_shard // T))
    if g is None:
        g = max(dcfg.g, (1 << auto_pack_bits(n_tiles_est, T))
                // (T // _LANES))
    pbits = min(_PBITS_MAX, max(_PACK_BITS, int(math.ceil(math.log2(
        max(g * (T // _LANES), 2))))))
    packed = g * (T // _LANES) <= (1 << pbits)
    grid_order = resolve_grid_order(grid_order, d, packed)
    db_dtype = resolve_db_dtype(db_dtype, d, packed, grid_order,
                                store_yp)
    row_mult = g * T if grid_order in ("db", "dbuf") else T
    rows_per = max(1, -(-m_shard // row_mult)) * row_mult
    dpad = (-d) % (_DC if d > _D_SINGLE_SHOT else _LANES)
    d_eff = d + dpad
    # host-side global pad: [p·rows_per, d_eff]; pads all trail the real
    # rows, so shard i owns global rows [i·rows_per, (i+1)·rows_per)
    yg = np.zeros((p * rows_per, d_eff), np.float32)
    yg[:m, :d] = y
    ys = jax.device_put(yg, NamedSharding(mesh, P(axis)))

    if db_dtype == "int8":
        fault_point("quantize_index")

        def _prep_q8(y_loc):
            r = jax.lax.axis_index(axis)
            m_loc = jnp.clip(
                jnp.int32(m) - r.astype(jnp.int32) * rows_per,
                0, rows_per)
            return _prepare_ops_q8(y_loc, T, g, metric, pbits=pbits,
                                   grid_order=grid_order, n_valid=m_loc)

        fn = jax.jit(jax.shard_map(
            _prep_q8, mesh=mesh, in_specs=(P(axis),),
            out_specs=(P(axis), P(axis), P(axis), P(None, axis),
                       P(None, axis), P(axis)),
            check_vma=False))
        yp_s, y_q_s, scale_s, yyh_s, yy_s, eq_s = fn(ys)
        return ShardedFusedIndex(yp_s, None, None, yyh_s, yy_s, m,
                                 rows_per, mesh, axis, T, Qb, g, passes,
                                 metric, d, pbits, grid_order,
                                 db_dtype="int8", y_q_s=y_q_s,
                                 scale_s=scale_s, eq_s=eq_s)

    def _prep(y_loc):
        r = jax.lax.axis_index(axis)
        m_loc = jnp.clip(jnp.int32(m) - r.astype(jnp.int32) * rows_per,
                         0, rows_per)
        return _prepare_ops(y_loc, T, g, metric, pbits=pbits,
                            grid_order=grid_order, n_valid=m_loc)

    fn = jax.jit(jax.shard_map(
        _prep, mesh=mesh, in_specs=(P(axis),),
        out_specs=(P(axis), P(axis), P(axis), P(None, axis),
                   P(None, axis)),
        check_vma=False))
    yp_s, y_hi_s, y_lo_s, yyh_s, yy_s = fn(ys)
    if not store_yp:
        yp_s = None
        if passes == 1:
            y_lo_s = None   # the 1-pass kernel and lite fixup never read it
    return ShardedFusedIndex(yp_s, y_hi_s, y_lo_s, yyh_s, yy_s, m,
                             rows_per, mesh, axis, T, Qb, g, passes,
                             metric, d, pbits, grid_order)


def _merge_allgather(comms: MeshComms, p: int, k: int, v, i):
    """All-gather every shard's [nq, k] candidates and select k of p·k.
    Pool order is rank-major per query — identical on every shard, so
    the merged result is replicated bit-for-bit (ties included)."""
    gv = comms.allgather(v)                                # [p, nq, k]
    gi = comms.allgather(i)
    nq = v.shape[0]
    gv = jnp.moveaxis(gv, 0, 1).reshape(nq, p * k)
    gi = jnp.moveaxis(gi, 0, 1).reshape(nq, p * k)
    neg, pos = jax.lax.top_k(-gv, k)
    return -neg, jnp.take_along_axis(gi, pos, axis=1)


def _merge_tournament(comms: MeshComms, p: int, k: int, v, i):
    """log₂(p) butterfly rounds of collective_permute pair-merges, each
    a select over 2k. Concatenation order is (lower mesh index first)
    on BOTH partners, so each round's inputs — and therefore the final
    top-k, ties included — are identical across the pair; by induction
    the result is replicated over the whole axis."""
    rr = comms.get_rank()
    rounds = int(math.log2(p)) if p > 1 else 0
    for j in range(rounds):
        dlt = 1 << j
        perm = [(s, s ^ dlt) for s in range(p)]
        ov = comms.collective_permute(v, perm)
        oi = comms.collective_permute(i, perm)
        low_first = (rr & dlt) == 0                  # traced scalar bool
        cat_v = jnp.where(low_first,
                          jnp.concatenate([v, ov], axis=1),
                          jnp.concatenate([ov, v], axis=1))
        cat_i = jnp.where(low_first,
                          jnp.concatenate([i, oi], axis=1),
                          jnp.concatenate([oi, i], axis=1))
        neg, pos = jax.lax.top_k(-cat_v, k)
        v = -neg
        i = jnp.take_along_axis(cat_i, pos, axis=1)
    return v, i


def _merge_host_pool(gv, gi, k: int):
    """Host-side merge — the bottom rung of the collective-failure
    ladder: the shard_map program returns each shard's LOCAL candidates
    (out_specs sharded over the axis → [p, nq, k] on host), and the
    final select runs outside the SPMD program, with no merge
    collective in the compiled graph at all. Pool order is rank-major
    per query — the exact pool :func:`_merge_allgather` builds — so the
    result is bit-identical to the collective merges, ties included."""
    p, nqp, kk = gv.shape
    pool_v = jnp.moveaxis(gv, 0, 1).reshape(nqp, p * kk)
    pool_i = jnp.moveaxis(gi, 0, 1).reshape(nqp, p * kk)
    neg, pos = jax.lax.top_k(-pool_v, k)
    return -neg, jnp.take_along_axis(pool_i, pos, axis=1)


@instrument("distance.knn_fused_sharded")
def knn_fused_sharded(x, y, k: int, mesh=None, axis: str = "x",
                      shard_mode: str = "db", merge: str = "auto",
                      micro_batches: Optional[int] = None,
                      passes: int = 3, metric: str = "l2",
                      T: Optional[int] = None, Qb: Optional[int] = None,
                      g: Optional[int] = None,
                      grid_order: Optional[str] = None,
                      db_dtype: str = "bf16",
                      rescore: Optional[bool] = None,
                      certify: str = "kernel", store_yp: bool = True,
                      res=None) -> Tuple[jax.Array, jax.Array]:
    """Certified fused brute-force KNN over a device mesh.

    ``shard_mode="db"`` (default): the INDEX rows shard over
    ``mesh[axis]`` — the bigger-than-HBM mode. ``y`` may be a raw
    [m, d] matrix (prepared inline) or a :class:`ShardedFusedIndex`
    (preferred for repeated query batches; its frozen config wins).
    Each shard runs the packed fused kernel over its slice (db-major
    orders stream the shard from HBM once), local ids shift to global
    by the shard's row offset, and per-shard candidates merge with the
    strategy picked by ``merge`` ("auto" = the ICI cost-model
    crossover; see the module doc). ``micro_batches`` splits the query
    batch so block i's local compute can overlap block i−1's merge
    collectives (None = :func:`default_micro_batches`, or a tuned
    table's value via :func:`raft_tpu.tune.sharded.sharded_config`).

    ``shard_mode="query"``: replicated index, data-parallel queries —
    the serving shape. ``y`` may be a raw matrix or a single-device
    :class:`KnnIndex`; ``merge``/``micro_batches`` are ignored (no
    cross-shard candidates exist).

    Returns the same contract as :func:`knn_fused`: (values [nq, k]
    ascending — IP descending —, global ids [nq, k]), exact under the
    same certificates, bit-exact vs the single-device oracle.
    """
    res = ensure_resources(res)
    if shard_mode not in SHARD_MODES:
        raise ValueError(f"knn_fused_sharded: shard_mode must be one "
                         f"of {SHARD_MODES}, got {shard_mode!r}")
    if mesh is None:
        mesh = (y.mesh if isinstance(y, ShardedFusedIndex)
                else getattr(res, "mesh", None))
    expects(mesh is not None,
            "knn_fused_sharded: pass mesh= or set it on res")
    expects(axis in mesh.axis_names,
            "knn_fused_sharded: axis %r not in mesh axes %s", axis,
            tuple(mesh.axis_names))
    p = int(mesh.shape[axis])
    x = jnp.asarray(x, jnp.float32)
    nq = x.shape[0]

    if shard_mode == "query":
        fault_point("sharded_dispatch")
        with device_errors("distance.knn_fused_sharded[query]"):
            return _knn_query_sharded(x, y, k, mesh, axis, passes,
                                      metric, T, Qb, g, grid_order,
                                      rescore, certify, res,
                                      db_dtype=db_dtype)

    if isinstance(y, ShardedFusedIndex):
        idx = y
        expects(idx.axis == axis and idx.mesh == mesh,
                "knn_fused_sharded: index prepared for a different "
                "mesh/axis — re-prepare or pass its mesh")
    else:
        idx = prepare_knn_index_sharded(
            y, mesh=mesh, axis=axis, passes=passes, metric=metric,
            T=T, Qb=Qb, g=g, store_yp=store_yp, grid_order=grid_order,
            db_dtype=db_dtype, res=res)
    m = idx.n_rows
    quant = idx.db_dtype == "int8"
    expects(k <= m, "knn_fused_sharded: k=%d > index size %d", k, m)
    if nq == 0:
        return (jnp.zeros((0, k), jnp.float32),
                jnp.zeros((0, k), jnp.int32))
    # per-shard pool envelope: every shard must be able to yield k local
    # candidates (the global top-k is a subset of the per-shard unions)
    n_tiles_loc = idx.rows_per // idx.T
    pool_loc = 2 * (-(-n_tiles_loc // idx.g)) * _LANES
    if k > pool_loc:
        raise NotImplementedError(
            f"knn_fused_sharded: k={k} too large for the per-shard "
            f"candidate pool {pool_loc} (fewer shards, or shrink g/T)")
    if rescore is None:
        rescore = idx.yp_s is not None
    if rescore and idx.yp_s is None:
        raise ValueError("knn_fused_sharded: rescore=True needs a "
                         "yp-storing index (store_yp=True)")
    if certify == "f32" and not rescore:
        raise ValueError("knn_fused_sharded: certify='f32' needs the "
                         "exact rescore (store_yp=True)")
    if quant and not rescore:
        raise ValueError("knn_fused_sharded: an int8-streamed index is "
                         "always exact-rescored")

    # ---- micro-batch request (caller / tuned table / default) -------
    nb_req = micro_batches
    if nb_req is None:
        from raft_tpu.tune.sharded import sharded_config

        tuned = sharded_config(p)
        nb_req = tuned.get("micro_batches") if tuned else None

    d_eff = idx.stream_width
    if x.shape[1] != idx.d_orig:
        raise ValueError(f"knn_fused_sharded: query width {x.shape[1]} "
                         f"!= index {idx.d_orig}")
    if d_eff != x.shape[1]:
        x = jnp.concatenate(
            [x, jnp.zeros((nq, d_eff - x.shape[1]), jnp.float32)], axis=1)

    S_pool = -(-n_tiles_loc // idx.g) * _LANES
    packed = idx.g * (idx.T // _LANES) <= (1 << idx.pbits)
    pool_len = S_pool if packed else 2 * S_pool
    pool_algo = resolve_pool_algo(pool_select_algo(), pool_len,
                                  min(k + _POOL_PAD, pool_len))

    has_yp = idx.yp_s is not None
    has_ylo = idx.y_lo_s is not None

    def _geometry(nb, Qb_base):
        """Static query-block geometry for one (micro-batch, Qb)
        attempt — recomputed per ladder rung."""
        nb = (default_micro_batches(nq, Qb_base) if nb is None
              else int(nb))
        nb = max(1, min(nb, nq))
        nb = max(nb, -(-nq // _Q_CHUNK))   # keep blocks under _Q_CHUNK
        qb0 = -(-nq // nb)
        Qb_eff = min(Qb_base, ((qb0 + 7) // 8) * 8)
        qb_len = -(-qb0 // Qb_eff) * Qb_eff
        return nb, Qb_eff, qb_len, nb * qb_len

    def _dispatch(merge_eff, nb_in, Qb_base):
        """Build (or reuse) and run the compiled SPMD program for one
        (merge strategy, micro-batches, Qb) point — the unit the
        degradation ladder retries with different arguments."""
        nb, Qb_eff, qb_len, nq_pad = _geometry(nb_in, Qb_base)
        xq = x
        if nq_pad != nq:
            xq = jnp.concatenate(
                [x, jnp.zeros((nq_pad - nq, d_eff), jnp.float32)])
        key = ("db", mesh, axis, k, idx.T, Qb_eff, idx.g, idx.passes,
               idx.metric, idx.rows_per, m, nb, qb_len, merge_eff,
               bool(rescore), idx.pbits, certify, pool_algo,
               idx.grid_order, idx.db_dtype, has_yp, has_ylo)
        fn = _SHARDED_FUSED_CACHE.get(key)
        if fn is None:
            comms = MeshComms(axis, size=p)
            merge_fn = {"allgather": _merge_allgather,
                        "tournament": _merge_tournament,
                        "host": None}[merge_eff]
            rows_per, T_, g_ = idx.rows_per, idx.T, idx.g
            passes_, metric_, pbits_ = idx.passes, idx.metric, idx.pbits
            order_, dtype_ = idx.grid_order, idx.db_dtype

            def shard_fn(*ops_and_x):
                *ops, xq_l = ops_and_x
                it = iter(ops)
                yp_l = next(it) if has_yp else None
                if quant:
                    yhi_l = ylo_l = None
                    yq_l, scl_l, eq_l = next(it), next(it), next(it)
                else:
                    yq_l = scl_l = eq_l = None
                    yhi_l = next(it)
                    ylo_l = next(it) if has_ylo else None
                yyh_l = next(it)
                yy_l = next(it)
                r = jax.lax.axis_index(axis)
                m_loc = jnp.clip(
                    jnp.int32(m) - r.astype(jnp.int32) * rows_per,
                    0, rows_per)
                off = r.astype(jnp.int32) * rows_per
                out_v, out_i = [], []
                nf = jnp.zeros((), jnp.int32)
                # micro-batch pipeline: block b's kernel is independent
                # of block b−1's merge collectives — the scheduler may
                # overlap
                for b in range(nb):
                    xb = jax.lax.slice_in_dim(xq_l, b * qb_len,
                                              (b + 1) * qb_len, axis=0)
                    # margin (4th with_stats output) is DCE'd here: the
                    # sharded out_specs stay (vals, ids, n_fail) —
                    # per-shard margins would need a gather the explain
                    # plane doesn't ask for
                    vals, ids, nfb, _ = _knn_fused_core(
                        xb, yp_l, yhi_l, ylo_l, yyh_l, yy_l,
                        k=k, T=T_, Qb=Qb_eff, g=g_, passes=passes_,
                        metric=metric_, m=rows_per, rescore=rescore,
                        pbits=pbits_, certify=certify,
                        pool_algo=pool_algo, grid_order=order_,
                        db_dtype=dtype_, with_stats=True, y_q=yq_l,
                        y_scale_k=scl_l, eq_groups=eq_l, m_valid=m_loc)
                    nf = nf + nfb
                    # local → global ids; pad/sentinel candidates (id -1
                    # or non-finite value) must lose every merge
                    gid = jnp.where((ids >= 0) & jnp.isfinite(vals),
                                    ids + off, -1)
                    vals = jnp.where(gid >= 0, vals, jnp.inf)
                    if merge_fn is not None:
                        vals, gid = merge_fn(comms, p, k, vals, gid)
                    out_v.append(vals)
                    out_i.append(gid)
                cat_v = jnp.concatenate(out_v, axis=0)
                cat_i = jnp.concatenate(out_i, axis=0)
                # per-shard certificate-failure count: rank-major [p]
                # on the host side of the shard_map (quality telemetry)
                if merge_fn is None:   # host merge: per-shard locals out
                    return cat_v[None], cat_i[None], nf.reshape(1)
                return cat_v, cat_i, nf.reshape(1)

            if quant:
                # yp + (y_q, scale, eq) — all row/group-sharded
                row_specs = [P(axis)] * 4
            else:
                row_specs = [P(axis)] * (1 + int(has_yp) + int(has_ylo))
            in_specs = tuple(row_specs
                             + [P(None, axis), P(None, axis), P()])
            out_specs = ((P(axis), P(axis), P(axis))
                         if merge_eff == "host"
                         else (P(), P(), P(axis)))
            fn = jax.jit(jax.shard_map(
                shard_fn, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False))
            _SHARDED_FUSED_CACHE[key] = fn

        if quant:
            operands = [idx.yp_s, idx.y_q_s, idx.scale_s, idx.eq_s,
                        idx.yyh_s, idx.yy_s]
        else:
            operands = [o for o in (idx.yp_s, idx.y_hi_s, idx.y_lo_s)
                        if o is not None] + [idx.yyh_s, idx.yy_s]
        vals, ids, nf = fn(*operands, xq)
        if merge_eff == "host":
            vals, ids = _merge_host_pool(vals, ids, k)
        if nq_pad != nq:
            vals, ids = vals[:nq], ids[:nq]
        return vals, ids, nf

    # ---- resilience driver ------------------------------------------
    # The fast path is one trip through the loop body with zero extra
    # dispatches; the except arms walk the graceful-degradation ladder
    # (see raft_tpu.resilience.policy): classified OOM → halve Qb,
    # then grow micro-batches; collective failure (device error or
    # injected timeout at the merge) → tournament → allgather → host
    # merge. DeadlineExceededError is never caught here — a deadline
    # is the caller's global budget. Every rung is bit-identical in
    # ids to the undegraded oracle (tests/test_resilience.py).
    _, _, qb_len0, _ = _geometry(nb_req, idx.Qb)
    merge_eff = resolve_merge_strategy(merge, p, qb_len0, k)
    validate = (faults_active()
                or bool(os.environ.get("RAFT_TPU_VALIDATE_OUTPUTS")))
    site = "distance.knn_fused_sharded"
    Qb_base, nb_cur, retries = idx.Qb, nb_req, 0
    while True:
        try:
            poison = fault_point("sharded_dispatch")
            if merge_eff == "tournament":
                fault_point("merge_permute")
            elif merge_eff == "allgather":
                fault_point("merge_allgather")
            with device_errors(site):
                vals, ids, nf_shards = _dispatch(merge_eff, nb_cur,
                                                 Qb_base)
            if poison == "nan":   # simulated kernel-output poisoning
                vals = jnp.full_like(vals, jnp.nan)
            if validate and not bool(jnp.isfinite(vals).all()):
                try:
                    from raft_tpu.resilience import POISONED

                    res.metrics.counter(
                        POISONED, {"site": site},
                        help="Outputs that failed the finiteness "
                             "guard").inc()
                except Exception:
                    pass
                raise PoisonedOutputError(
                    f"{site}: non-finite values in merged top-k")
            break
        except PoisonedOutputError as e:
            retries += 1
            pol = res.resilience.policy_for(site)
            if retries > pol.max_retries:
                record_exhausted(site)
                raise
            record_retry(site, e, retries)
        except OutOfMemoryError:
            nb_now = _geometry(nb_cur, Qb_base)[0]
            if Qb_base > 8:
                new_Qb = max(8, (Qb_base // 2) // 8 * 8)
                record_degradation(site, f"fit:Qb:{Qb_base}->{new_Qb}")
                Qb_base = new_Qb
            elif nb_now < min(nq, 64):
                record_degradation(
                    site,
                    f"fit:micro_batches:{nb_now}->{2 * nb_now}")
                nb_cur = min(nq, 2 * nb_now)
            else:
                record_exhausted(site)
                raise
        except DeviceError as e:
            nxt = degrade_merge(merge_eff)
            if nxt is None:
                record_exhausted(site)
                raise
            record_degradation(site, f"merge:{merge_eff}->{nxt}")
            merge_eff = nxt
    # quality telemetry: the per-shard certificate-failure counts stay
    # a device [p] array here — quality.drain() sums them host-side
    # later (every shard evaluates the certificate over the whole
    # padded query batch)
    try:
        record_pending(
            site, nf_shards, n_queries=p * _geometry(nb_cur, Qb_base)[3],
            pool_width=rescore_pool_width(
                k, -(-n_tiles_loc // idx.g) * _LANES, packed),
            fix_tiers=fixup_tiers_for(idx.rows_per),
            db_dtype=idx.db_dtype, merge=merge_eff, shards=p)
    except Exception:
        pass
    if idx.metric == "ip":
        return -vals, ids           # internal −x·y ascending → IP desc
    return vals, ids


def _knn_query_sharded(x, y, k, mesh, axis, passes, metric, T, Qb, g,
                       grid_order, rescore, certify, res,
                       db_dtype: str = "bf16"):
    """Query-sharded serving mode: replicated prepared index, queries
    row-sharded over the axis, per-shard certified fused pipeline —
    zero cross-shard candidate traffic (each query's top-k depends only
    on the full index)."""
    if isinstance(y, KnnIndex):
        idx = y
    else:
        idx = prepare_knn_index(jnp.asarray(y, jnp.float32),
                                passes=passes, metric=metric, T=T,
                                Qb=Qb, g=g, grid_order=grid_order,
                                db_dtype=db_dtype)
    m = idx.n_rows
    expects(k <= m, "knn_fused_sharded: k=%d > index size %d", k, m)
    nq = x.shape[0]
    if nq == 0:
        return (jnp.zeros((0, k), jnp.float32),
                jnp.zeros((0, k), jnp.int32))
    if rescore is None:
        rescore = idx.yp is not None
    p = int(mesh.shape[axis])
    # per-shard query block: a multiple of the kernel block size,
    # bounded at _Q_CHUNK (the fused pipeline's slot-array budget —
    # bigger batches chunk BEFORE the shard_map, like knn_fused's own
    # wrapper)
    qs0 = -(-nq // p)
    if qs0 > _Q_CHUNK:
        step = p * _Q_CHUNK
        outs = [_knn_query_sharded(x[s:s + step], idx, k, mesh, axis,
                                   passes, metric, T, Qb, g, grid_order,
                                   rescore, certify, res)
                for s in range(0, nq, step)]
        return (jnp.concatenate([o[0] for o in outs]),
                jnp.concatenate([o[1] for o in outs]))
    quant = idx.db_dtype == "int8"
    d_eff = idx.stream_width
    if x.shape[1] != idx.d_orig:
        raise ValueError(f"knn_fused_sharded: query width {x.shape[1]} "
                         f"!= index {idx.d_orig}")
    if d_eff != x.shape[1]:
        x = jnp.concatenate(
            [x, jnp.zeros((nq, d_eff - x.shape[1]), jnp.float32)], axis=1)
    Qb_eff = min(idx.Qb, ((qs0 + 7) // 8) * 8)
    qs_len = -(-qs0 // Qb_eff) * Qb_eff
    nq_pad = p * qs_len
    if nq_pad != nq:
        x = jnp.concatenate(
            [x, jnp.zeros((nq_pad - nq, d_eff), jnp.float32)])

    n_tiles = idx.yyh_k.shape[1] // idx.T
    S_pool = -(-n_tiles // idx.g) * _LANES
    packed = idx.g * (idx.T // _LANES) <= (1 << idx.pbits)
    pool_len = S_pool if packed else 2 * S_pool
    if k > 2 * S_pool:
        raise NotImplementedError(
            f"knn_fused_sharded: k={k} too large for pool {2 * S_pool}")
    pool_algo = resolve_pool_algo(pool_select_algo(), pool_len,
                                  min(k + _POOL_PAD, pool_len))
    has_yp = idx.yp is not None
    has_ylo = idx.y_lo is not None
    key = ("query", mesh, axis, k, idx.T, Qb_eff, idx.g, idx.passes,
           idx.metric, m, qs_len, bool(rescore), idx.pbits, certify,
           pool_algo, idx.grid_order, idx.db_dtype, has_yp, has_ylo)
    fn = _SHARDED_FUSED_CACHE.get(key)
    if fn is None:
        T_, g_, passes_, metric_ = idx.T, idx.g, idx.passes, idx.metric
        pbits_, order_, dtype_ = idx.pbits, idx.grid_order, idx.db_dtype

        def shard_fn(*ops_and_x):
            *ops, xq = ops_and_x
            it = iter(ops)
            yp_l = next(it) if has_yp else None
            if quant:
                yhi_l = ylo_l = None
                yq_l, scl_l, eq_l = next(it), next(it), next(it)
            else:
                yq_l = scl_l = eq_l = None
                yhi_l = next(it)
                ylo_l = next(it) if has_ylo else None
            yyh_l = next(it)
            yy_l = next(it)
            v, i, nf, _ = _knn_fused_core(
                xq, yp_l, yhi_l, ylo_l, yyh_l, yy_l,
                k=k, T=T_, Qb=Qb_eff, g=g_, passes=passes_,
                metric=metric_, m=m, rescore=rescore, pbits=pbits_,
                certify=certify, pool_algo=pool_algo, grid_order=order_,
                db_dtype=dtype_, with_stats=True, y_q=yq_l,
                y_scale_k=scl_l, eq_groups=eq_l)
            return v, i, nf.reshape(1)

        n_repl = (1 + 3 if quant
                  else 1 + int(has_yp) + int(has_ylo)) + 2
        in_specs = tuple([P()] * n_repl + [P(axis)])
        fn = jax.jit(jax.shard_map(
            shard_fn, mesh=mesh, in_specs=in_specs,
            out_specs=(P(axis), P(axis), P(axis)), check_vma=False))
        _SHARDED_FUSED_CACHE[key] = fn

    from raft_tpu.parallel import replicated

    if quant:
        srcs = (idx.yp, idx.y_q, idx.y_scale_k, idx.eq_groups)
    else:
        srcs = tuple(o for o in (idx.yp, idx.y_hi, idx.y_lo)
                     if o is not None)
    operands = [jax.device_put(o, replicated(mesh)) for o in srcs]
    operands += [jax.device_put(idx.yyh_k, replicated(mesh)),
                 jax.device_put(idx.yy_raw, replicated(mesh))]
    xs = jax.device_put(x, NamedSharding(mesh, P(axis)))
    vals, ids, nf_shards = fn(*operands, xs)
    try:
        record_pending(
            "distance.knn_fused_sharded", nf_shards, n_queries=nq_pad,
            pool_width=rescore_pool_width(k, S_pool, packed),
            fix_tiers=fixup_tiers_for(idx.yyh_k.shape[1]),
            db_dtype=idx.db_dtype, merge="query_sharded", shards=p)
    except Exception:
        pass
    if nq_pad != nq:
        vals, ids = vals[:nq], ids[:nq]
    if idx.metric == "ip":
        return -vals, ids
    return vals, ids
