"""Pairwise distances.

(ref: the pre-cuVS ``raft::distance::pairwise_distance`` surface, built on
the contraction tiling substrate that survives at
cpp/include/raft/linalg/detail/contractions.cuh:313 — rebuilt TPU-first per
SURVEY §7 stage 10 / BASELINE configs 1-2.)

TPU design: "expanded" metrics (L2/cosine/correlation/IP/hellinger/russell-
rao/jaccard/dice) contract on the MXU as X·Yᵀ plus rank-1 norm corrections —
that's where the 10M×256 GB/s target comes from. "Unexpanded" metrics
(L1/Linf/Canberra/Minkowski/Hamming/KL/JS/BrayCurtis) need the |x−y| form,
which has no matmul decomposition: the streaming Pallas kernel
(ops/unexpanded_pallas.py) forms per-feature terms on VMEM-resident tiles
and folds them into [Qb, 128] accumulators — no [n, m, d] broadcast at any
memory level (the role the reference's smem tiling policies play — SURVEY
§2.3 contractions row, contractions.cuh:313). Ineligible calls take a
single fully-jitted XLA program whose broadcast-reduce fuses per row tile.
"""

from __future__ import annotations

from typing import Union

import functools

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.resources import ensure_resources
from raft_tpu.distance.types import METRIC_NAMES, DistanceType
from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point


def _as_type(metric: Union[str, DistanceType]) -> DistanceType:
    if isinstance(metric, DistanceType):
        return metric
    expects(metric in METRIC_NAMES, "unknown metric %r", metric)
    return METRIC_NAMES[metric]


def _expanded_l2(x, y, sqrt: bool):
    xx = jnp.sum(x * x, axis=1)[:, None]
    yy = jnp.sum(y * y, axis=1)[None, :]
    d2 = xx + yy - 2.0 * jnp.matmul(x, y.T, preferred_element_type=jnp.float32)
    d2 = jnp.maximum(d2, 0.0)
    return jnp.sqrt(d2) if sqrt else d2


def _cosine(x, y):
    xn = jnp.sqrt(jnp.sum(x * x, axis=1))[:, None]
    yn = jnp.sqrt(jnp.sum(y * y, axis=1))[None, :]
    denom = jnp.maximum(xn * yn, 1e-30)
    sim = jnp.matmul(x, y.T, preferred_element_type=jnp.float32) / denom
    return 1.0 - sim


def _correlation(x, y):
    xc = x - jnp.mean(x, axis=1, keepdims=True)
    yc = y - jnp.mean(y, axis=1, keepdims=True)
    return _cosine(xc, yc)


def _is_batch_traced(*arrays) -> bool:
    """Best-effort vmap detection: True when any operand is a batching
    tracer at dispatch time (vmap(pairwise_distance), or vmap inside an
    enclosing jit). ``vmap(jit(f))`` callers trace f under the jit
    trace — invisible here — and should pass ``batched=True``."""
    from jax.interpreters import batching

    return any(isinstance(a, batching.BatchTracer) for a in arrays)


@instrument("distance.pairwise_distance")
def pairwise_distance(res, x, y=None, metric: Union[str, DistanceType] = "euclidean",
                      p: float = 2.0, precision=None,
                      assume_finite: bool = False,
                      batched: bool = None) -> jax.Array:
    """Full [n, m] distance matrix. (ref: pre-cuVS
    raft::distance::pairwise_distance; pylibraft.distance.pairwise_distance)

    Precision note (expanded metrics): with ``precision=None`` the MXU
    contraction runs at JAX's default matmul precision — one-pass bf16 on
    TPU, which is the same precision CLASS as the reference's default on
    A100 (cuBLAS runs f32 GEMMs on TF32 tensor cores, 10-bit mantissa).
    Pass ``precision=jax.lax.Precision.HIGHEST`` for f32-grade
    contractions (3-pass bf16 split — BEYOND the reference's default), or
    use ``jax.default_matmul_precision`` to set it globally.

    ``assume_finite=True`` promises the inputs contain no inf/NaN,
    letting the unexpanded metrics skip the in-program finiteness guard
    in front of the streaming Pallas kernel (non-finite values would
    poison its one-hot selector contraction; with the default guard
    they are routed to the XLA path, which preserves inf/NaN
    semantics).

    ``batched=True`` tells the unexpanded dispatch the caller is
    vmapped: under vmap the guard's ``lax.cond`` lowers to ``select``
    and BOTH branches execute per batch element (round-5 finding), so
    batched callers are short-circuited straight to the XLA path
    (inf/NaN-correct, one branch). ``None`` auto-detects a batching
    trace on the operands; ``vmap(jit(...))`` callers — invisible to
    the detection — should pass it explicitly (or vouch with
    ``assume_finite=True``, which skips the guard entirely and keeps
    the Pallas kernel).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.distance import pairwise_distance
    >>> x = np.array([[0.0, 0.0], [3.0, 4.0]])
    >>> np.asarray(pairwise_distance(None, x, metric="euclidean")).round(1).tolist()
    [[0.0, 5.0], [5.0, 0.0]]
    """
    fault_point("pairwise_distance")
    x = jnp.asarray(x)
    y = x if y is None else jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "pairwise_distance: inputs must be [n,d],[m,d]")
    t = _as_type(metric)
    if batched is None:
        batched = _is_batch_traced(x, y)
    if precision is not None:
        if isinstance(precision, jax.lax.Precision):
            precision = precision.name.lower()
        with jax.default_matmul_precision(precision):
            return _pairwise_dispatch(res, x, y, t, p, assume_finite,
                                      batched)
    return _pairwise_dispatch(res, x, y, t, p, assume_finite, batched)


_UNEXPANDED_TYPES = frozenset({
    DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
    DistanceType.L1, DistanceType.Linf, DistanceType.LpUnexpanded,
    DistanceType.Canberra, DistanceType.HammingUnexpanded,
    DistanceType.BrayCurtis, DistanceType.KLDivergence,
    DistanceType.JensenShannon,
})


def _pairwise_dispatch(res, x, y, t: DistanceType, p: float,
                       assume_finite: bool = False,
                       batched: bool = False) -> jax.Array:
    if t not in _UNEXPANDED_TYPES:
        # ONE jitted program for the expanded metrics: eagerly, the
        # 5-6 ops each cost a per-op transport dispatch (~2 ms on the
        # tunneled TPU — config 1's entire 11 ms "compute" was
        # dispatch overhead, ref contractions.cuh:1's single-launch
        # small-shape path)
        return _pairwise_expanded_jit(x, y, t, p)
    # unexpanded (broadcast-form) metrics: every one of them accumulates
    # elementwise over features, so the [tile, m, d] broadcast is folded
    # over FEATURE CHUNKS with a [tile, m]-shaped carry — the d-axis
    # analog of the reference's k-blocked smem policy
    # (linalg/detail/contractions.cuh:313). Peak temp = [tile, m, dc].
    return _unexpanded(res, x, y, t, p, assume_finite, batched)


@functools.partial(jax.jit, static_argnames=("t", "p"))
def _pairwise_expanded_jit(x, y, t: DistanceType, p: float) -> jax.Array:

    if t == DistanceType.L2Expanded:
        return _expanded_l2(x, y, sqrt=False)
    if t == DistanceType.L2SqrtExpanded:
        return _expanded_l2(x, y, sqrt=True)
    if t == DistanceType.InnerProduct:
        return jnp.matmul(x, y.T, preferred_element_type=jnp.float32)
    if t == DistanceType.CosineExpanded:
        return _cosine(x, y)
    if t == DistanceType.CorrelationExpanded:
        return _correlation(x, y)
    if t == DistanceType.HellingerExpanded:
        ip = jnp.matmul(jnp.sqrt(jnp.abs(x)), jnp.sqrt(jnp.abs(y)).T,
                        preferred_element_type=jnp.float32)
        return jnp.sqrt(jnp.maximum(1.0 - jnp.minimum(ip, 1.0), 0.0))
    if t == DistanceType.RussellRaoExpanded:
        d = x.shape[1]
        ip = jnp.matmul((x != 0).astype(jnp.float32), (y != 0).astype(jnp.float32).T,
                        preferred_element_type=jnp.float32)
        return (d - ip) / d
    if t in (DistanceType.JaccardExpanded, DistanceType.DiceExpanded):
        xb = (x != 0).astype(jnp.float32)
        yb = (y != 0).astype(jnp.float32)
        inter = jnp.matmul(xb, yb.T, preferred_element_type=jnp.float32)
        nx = jnp.sum(xb, axis=1)[:, None]
        ny = jnp.sum(yb, axis=1)[None, :]
        if t == DistanceType.JaccardExpanded:
            union = jnp.maximum(nx + ny - inter, 1e-30)
            return 1.0 - inter / union
        return 1.0 - 2.0 * inter / jnp.maximum(nx + ny, 1e-30)
    raise ValueError(f"_pairwise_expanded_jit: unexpanded metric {t}")


def _kl_term(a, b):
    r = jnp.where((a > 0) & (b > 0), a / jnp.where(b > 0, b, 1.0), 1.0)
    return jnp.where(a > 0, a * jnp.log(r), 0.0)


def _unexp_terms(xs, ys, t: DistanceType, p: float, acc_dtype):
    """Per-feature terms on a broadcastable (xs, ys) pair — the ONE
    definition of every unexpanded metric's inner form, shared by the
    jitted XLA path and the Pallas kernel's emulation tests."""
    diff = xs - ys
    if t in (DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded):
        return (diff * diff,)
    if t == DistanceType.L1 or t == DistanceType.Linf:
        return (jnp.abs(diff),)
    if t == DistanceType.LpUnexpanded:
        return (jnp.abs(diff) ** p,)
    if t == DistanceType.Canberra:
        denom = jnp.abs(xs) + jnp.abs(ys)
        safe = jnp.where(denom == 0, 1.0, denom)
        return (jnp.where(denom == 0, 0.0, jnp.abs(diff) / safe),)
    if t == DistanceType.HammingUnexpanded:
        return ((xs != ys).astype(acc_dtype),)
    if t == DistanceType.BrayCurtis:
        return (jnp.abs(diff), jnp.abs(xs + ys))
    if t == DistanceType.KLDivergence:
        return (_kl_term(xs, ys),)
    if t == DistanceType.JensenShannon:
        mid = 0.5 * (xs + ys)
        return (_kl_term(xs, mid) + _kl_term(ys, mid),)
    raise NotImplementedError(t)


def _unexp_finalize(accs, t: DistanceType, p: float, d: int):
    a = accs[0]
    if t == DistanceType.L2SqrtUnexpanded:
        return jnp.sqrt(a)
    if t == DistanceType.LpUnexpanded:
        return a ** (1.0 / p)
    if t == DistanceType.HammingUnexpanded:
        return a / d
    if t == DistanceType.BrayCurtis:
        return a / jnp.maximum(accs[1], 1e-30)
    if t == DistanceType.JensenShannon:
        return jnp.sqrt(jnp.maximum(0.5 * a, 0.0))
    return a


@functools.partial(jax.jit,
                   static_argnames=("t", "p", "d_true", "tile", "dc"))
def _unexpanded_jit(x, y, t: DistanceType, p: float, d_true: int,
                    tile: int, dc: int = 16) -> jax.Array:
    """The whole unexpanded pairwise op as ONE compiled program: a map
    over row tiles whose body folds FEATURE CHUNKS of ``dc`` with a
    [tile, m] carry — the d-axis analog of the reference's k-blocked
    smem policy (linalg/detail/contractions.cuh:313). The explicit
    chunk fold makes peak temp [tile, m, dc] by construction instead of
    trusting XLA to fuse a [tile, m, d] broadcast into the reduction
    (round-4 advisor: multi-term metrics / non-TPU backends may not
    fuse, and an unfused broadcast would be d/dc times the budgeted
    memory). Single dispatch — the round-3 Python loop paid ~2 ms
    transport RTT PER eager op on the tunneled v5e."""
    n, d0 = x.shape
    m = y.shape[0]
    acc_dtype = jnp.promote_types(jnp.promote_types(x.dtype, y.dtype),
                                  jnp.float32)
    reduce_d = jnp.max if t == DistanceType.Linf else jnp.sum
    combine = jnp.maximum if t == DistanceType.Linf else jnp.add
    n_acc = 2 if t == DistanceType.BrayCurtis else 1

    dc = max(1, min(dc, d0))
    dpad = (-d0) % dc
    if dpad:
        # zero features are term identities for every unexpanded metric
        # (tested: test_kernel_odd_shapes_and_padding)
        x = jnp.concatenate([x, jnp.zeros((n, dpad), x.dtype)], axis=1)
        y = jnp.concatenate([y, jnp.zeros((m, dpad), y.dtype)], axis=1)
    n_ch = (d0 + dpad) // dc
    yc = y.astype(acc_dtype).reshape(m, n_ch, dc).transpose(1, 0, 2)

    def one_tile(xt):
        xc = xt.astype(acc_dtype).reshape(tile, n_ch, dc)
        xc = xc.transpose(1, 0, 2)                   # [n_ch, tile, dc]

        def fold(carry, ch):
            xcc, ycc = ch                # [tile, dc], [m, dc]
            terms = _unexp_terms(xcc[:, None, :], ycc[None, :, :],
                                 t, p, acc_dtype)
            return tuple(combine(c, reduce_d(tm, axis=2))
                         for c, tm in zip(carry, terms)), None

        init = tuple(jnp.zeros((tile, m), acc_dtype)
                     for _ in range(n_acc))
        accs, _ = jax.lax.scan(fold, init, (xc, yc))
        return _unexp_finalize(accs, t, p, d_true)

    n_tiles = -(-n // tile)
    npad = n_tiles * tile - n
    xp = jnp.concatenate([x, jnp.zeros((npad, x.shape[1]), x.dtype)]) \
        if npad else x
    out = jax.lax.map(one_tile, xp.reshape(n_tiles, tile, x.shape[1]))
    return out.reshape(n_tiles * tile, m)[:n]


@functools.partial(jax.jit,
                   static_argnames=("t", "p", "d_true", "tile", "dc"))
def _unexpanded_guarded(x, y, t: DistanceType, p: float, d_true: int,
                        tile: int, dc: int) -> jax.Array:
    """Kernel-or-XLA chosen by an IN-PROGRAM finiteness check: the
    streaming Pallas path is reachable from jitted callers (the round-4
    dispatch required concrete inputs, so every estimator pipeline got
    the fallback), and eager callers pay one dispatch with no host
    sync instead of two blocking isfinite scans. Non-finite inputs take
    the XLA branch, whose semantics cover inf/NaN (the kernel's one-hot
    selector dot would turn them into whole-chunk NaNs).

    Cost note for ``vmap`` callers: under vmap, ``lax.cond`` lowers to
    ``select`` — BOTH branches execute for every batch element, so a
    vmapped caller pays kernel + XLA fallback distance computation and
    keeps only one result. The dispatcher therefore SHORT-CIRCUITS
    known-batched callers straight to ``_unexpanded_jit`` (detected
    via the operands' batching trace, or the explicit ``batched=``
    kwarg) — this guarded path is only entered unbatched. A batched
    pipeline that can vouch for finite inputs should instead pass
    ``assume_finite=True`` (skips the guard AND keeps the kernel)."""
    finite = jnp.isfinite(x).all() & jnp.isfinite(y).all()
    from raft_tpu.ops.unexpanded_pallas import unexpanded_pairwise_tiled

    return jax.lax.cond(
        finite,
        lambda a, b: unexpanded_pairwise_tiled(a, b, t, p),
        lambda a, b: _unexpanded_jit(a, b, t, p, d_true, tile, dc=dc),
        x, y)


def _unexpanded(res, x, y, t: DistanceType, p: float,
                assume_finite: bool = False,
                batched: bool = False) -> jax.Array:
    n, d = x.shape
    m = y.shape[0]
    acc_dtype = jnp.promote_types(jnp.promote_types(x.dtype, y.dtype),
                                  jnp.float32)
    if d == 0:
        return jnp.zeros((n, m), acc_dtype)

    # Pallas streaming path (TPU): [Qb, T] VMEM accumulators, terms
    # formed on VMEM-resident tiles — no [tile, m, d] broadcast at any
    # memory level (the contraction-substrate role, contractions.cuh:313)
    from raft_tpu.ops.unexpanded_pallas import (unexpanded_eligible,
                                                unexpanded_pairwise_tiled)

    # fallback tiling: budget the materialized [tile, m, dc] chunk temp
    # (×3 for term intermediates) — holds whether or not XLA fuses
    itemsize = jnp.dtype(acc_dtype).itemsize
    res = ensure_resources(res)
    dc = max(1, min(16, d))
    budget_rows = res.workspace.batch_rows(m * dc * 3 * itemsize)
    tile = int(max(1, min(n, budget_rows)))

    if unexpanded_eligible(t, n, m, d, x.dtype, y.dtype):
        if assume_finite:
            # caller vouches for the kernel envelope: skip even the
            # in-program finiteness reduction
            return unexpanded_pairwise_tiled(x, y, t, p)
        if batched:
            # known-batched caller: the guard's cond would lower to
            # select under vmap and execute BOTH branches per batch
            # element — the XLA path alone (inf/NaN-correct) is
            # strictly cheaper than kernel + XLA with one discarded
            return _unexpanded_jit(x, y, t, float(p), d, tile, dc=dc)
        return _unexpanded_guarded(x, y, t, float(p), d, tile, dc)
    return _unexpanded_jit(x, y, t, float(p), d, tile, dc=dc)
