"""Pairwise distances.

(ref: the pre-cuVS ``raft::distance::pairwise_distance`` surface, built on
the contraction tiling substrate that survives at
cpp/include/raft/linalg/detail/contractions.cuh:313 — rebuilt TPU-first per
SURVEY §7 stage 10 / BASELINE configs 1-2.)

TPU design: "expanded" metrics (L2/cosine/correlation/IP/hellinger/russell-
rao/jaccard/dice) contract on the MXU as X·Yᵀ plus rank-1 norm corrections —
that's where the 10M×256 GB/s target comes from. "Unexpanded" metrics
(L1/Linf/Canberra/Minkowski/Hamming/KL/JS/BrayCurtis) need the |x−y| form;
they are computed in row tiles sized to the workspace budget so the
[tile, n, d] broadcast intermediate stays in HBM bounds (the role the
reference's smem tiling policies play — SURVEY §2.3 contractions row).
"""

from __future__ import annotations

from typing import Optional, Union

import functools

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.resources import ensure_resources
from raft_tpu.distance.types import METRIC_NAMES, DistanceType


def _as_type(metric: Union[str, DistanceType]) -> DistanceType:
    if isinstance(metric, DistanceType):
        return metric
    expects(metric in METRIC_NAMES, "unknown metric %r", metric)
    return METRIC_NAMES[metric]


def _expanded_l2(x, y, sqrt: bool):
    xx = jnp.sum(x * x, axis=1)[:, None]
    yy = jnp.sum(y * y, axis=1)[None, :]
    d2 = xx + yy - 2.0 * jnp.matmul(x, y.T, preferred_element_type=jnp.float32)
    d2 = jnp.maximum(d2, 0.0)
    return jnp.sqrt(d2) if sqrt else d2


def _cosine(x, y):
    xn = jnp.sqrt(jnp.sum(x * x, axis=1))[:, None]
    yn = jnp.sqrt(jnp.sum(y * y, axis=1))[None, :]
    denom = jnp.maximum(xn * yn, 1e-30)
    sim = jnp.matmul(x, y.T, preferred_element_type=jnp.float32) / denom
    return 1.0 - sim


def _correlation(x, y):
    xc = x - jnp.mean(x, axis=1, keepdims=True)
    yc = y - jnp.mean(y, axis=1, keepdims=True)
    return _cosine(xc, yc)


def _tile_rows(res, x, y, body, row_bytes: Optional[int] = None):
    """Apply ``body(x_tile, y) -> [tile, m]`` over row tiles of x, sized by
    the workspace budget (the contraction-tiling stand-in). ``row_bytes``
    is the caller's true per-row peak; default assumes a [tile, m, d]
    broadcast."""
    res = ensure_resources(res)
    n, d = x.shape
    m = y.shape[0]
    if row_bytes is None:
        row_bytes = (m * d + m) * 4
    tile = max(1, min(n, res.workspace.batch_rows(row_bytes)))
    if tile >= n:
        return body(x, y)
    outs = []
    for start in range(0, n, tile):
        outs.append(body(x[start:start + tile], y))
    return jnp.concatenate(outs, axis=0)


def pairwise_distance(res, x, y=None, metric: Union[str, DistanceType] = "euclidean",
                      p: float = 2.0, precision=None) -> jax.Array:
    """Full [n, m] distance matrix. (ref: pre-cuVS
    raft::distance::pairwise_distance; pylibraft.distance.pairwise_distance)

    Precision note (expanded metrics): with ``precision=None`` the MXU
    contraction runs at JAX's default matmul precision — one-pass bf16 on
    TPU, which is the same precision CLASS as the reference's default on
    A100 (cuBLAS runs f32 GEMMs on TF32 tensor cores, 10-bit mantissa).
    Pass ``precision=jax.lax.Precision.HIGHEST`` for f32-grade
    contractions (3-pass bf16 split — BEYOND the reference's default), or
    use ``jax.default_matmul_precision`` to set it globally.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.distance import pairwise_distance
    >>> x = np.array([[0.0, 0.0], [3.0, 4.0]])
    >>> np.asarray(pairwise_distance(None, x, metric="euclidean")).round(1).tolist()
    [[0.0, 5.0], [5.0, 0.0]]
    """
    x = jnp.asarray(x)
    y = x if y is None else jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "pairwise_distance: inputs must be [n,d],[m,d]")
    t = _as_type(metric)
    if precision is not None:
        if isinstance(precision, jax.lax.Precision):
            precision = precision.name.lower()
        with jax.default_matmul_precision(precision):
            return _pairwise_dispatch(res, x, y, t, p)
    return _pairwise_dispatch(res, x, y, t, p)


_UNEXPANDED_TYPES = frozenset({
    DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
    DistanceType.L1, DistanceType.Linf, DistanceType.LpUnexpanded,
    DistanceType.Canberra, DistanceType.HammingUnexpanded,
    DistanceType.BrayCurtis, DistanceType.KLDivergence,
    DistanceType.JensenShannon,
})


def _pairwise_dispatch(res, x, y, t: DistanceType, p: float) -> jax.Array:
    if t not in _UNEXPANDED_TYPES:
        # ONE jitted program for the expanded metrics: eagerly, the
        # 5-6 ops each cost a per-op transport dispatch (~2 ms on the
        # tunneled TPU — config 1's entire 11 ms "compute" was
        # dispatch overhead, ref contractions.cuh:1's single-launch
        # small-shape path)
        return _pairwise_expanded_jit(x, y, t, p)
    # unexpanded (broadcast-form) metrics: every one of them accumulates
    # elementwise over features, so the [tile, m, d] broadcast is folded
    # over FEATURE CHUNKS with a [tile, m]-shaped carry — the d-axis
    # analog of the reference's k-blocked smem policy
    # (linalg/detail/contractions.cuh:313). Peak temp = [tile, m, dc].
    return _unexpanded(res, x, y, t, p)


@functools.partial(jax.jit, static_argnames=("t", "p"))
def _pairwise_expanded_jit(x, y, t: DistanceType, p: float) -> jax.Array:

    if t == DistanceType.L2Expanded:
        return _expanded_l2(x, y, sqrt=False)
    if t == DistanceType.L2SqrtExpanded:
        return _expanded_l2(x, y, sqrt=True)
    if t == DistanceType.InnerProduct:
        return jnp.matmul(x, y.T, preferred_element_type=jnp.float32)
    if t == DistanceType.CosineExpanded:
        return _cosine(x, y)
    if t == DistanceType.CorrelationExpanded:
        return _correlation(x, y)
    if t == DistanceType.HellingerExpanded:
        ip = jnp.matmul(jnp.sqrt(jnp.abs(x)), jnp.sqrt(jnp.abs(y)).T,
                        preferred_element_type=jnp.float32)
        return jnp.sqrt(jnp.maximum(1.0 - jnp.minimum(ip, 1.0), 0.0))
    if t == DistanceType.RussellRaoExpanded:
        d = x.shape[1]
        ip = jnp.matmul((x != 0).astype(jnp.float32), (y != 0).astype(jnp.float32).T,
                        preferred_element_type=jnp.float32)
        return (d - ip) / d
    if t in (DistanceType.JaccardExpanded, DistanceType.DiceExpanded):
        xb = (x != 0).astype(jnp.float32)
        yb = (y != 0).astype(jnp.float32)
        inter = jnp.matmul(xb, yb.T, preferred_element_type=jnp.float32)
        nx = jnp.sum(xb, axis=1)[:, None]
        ny = jnp.sum(yb, axis=1)[None, :]
        if t == DistanceType.JaccardExpanded:
            union = jnp.maximum(nx + ny - inter, 1e-30)
            return 1.0 - inter / union
        return 1.0 - 2.0 * inter / jnp.maximum(nx + ny, 1e-30)
    raise ValueError(f"_pairwise_expanded_jit: unexpanded metric {t}")


_FEATURE_CHUNK = 32


def _kl_term(a, b):
    r = jnp.where((a > 0) & (b > 0), a / jnp.where(b > 0, b, 1.0), 1.0)
    return jnp.where(a > 0, a * jnp.log(r), 0.0)


def _unexpanded(res, x, y, t: DistanceType, p: float) -> jax.Array:
    n, d = x.shape
    m = y.shape[0]
    acc_dtype = jnp.promote_types(jnp.promote_types(x.dtype, y.dtype),
                                  jnp.float32)
    if d == 0:
        return jnp.zeros((n, m), acc_dtype)
    dc = min(_FEATURE_CHUNK, d)
    dpad = (-d) % dc
    if dpad:
        # zero features are identities for every unexpanded metric's
        # per-feature term (Canberra/KL/JS mask zero operands; Hamming's
        # finalize divides by the ORIGINAL d)
        x = jnp.concatenate([x, jnp.zeros((n, dpad), x.dtype)], axis=1)
        y = jnp.concatenate([y, jnp.zeros((m, dpad), y.dtype)], axis=1)
    n_chunks = x.shape[1] // dc

    n_acc = 2 if t == DistanceType.BrayCurtis else 1
    combine = (jnp.maximum if t == DistanceType.Linf else jnp.add)

    def chunk_terms(xs, ys):
        """Per-feature terms on a [tile, m, dc] broadcast."""
        diff = xs - ys
        if t in (DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded):
            return (diff * diff,)
        if t == DistanceType.L1 or t == DistanceType.Linf:
            return (jnp.abs(diff),)
        if t == DistanceType.LpUnexpanded:
            return (jnp.abs(diff) ** p,)
        if t == DistanceType.Canberra:
            denom = jnp.abs(xs) + jnp.abs(ys)
            safe = jnp.where(denom == 0, 1.0, denom)
            return (jnp.where(denom == 0, 0.0, jnp.abs(diff) / safe),)
        if t == DistanceType.HammingUnexpanded:
            return ((xs != ys).astype(acc_dtype),)
        if t == DistanceType.BrayCurtis:
            return (jnp.abs(diff), jnp.abs(xs + ys))
        if t == DistanceType.KLDivergence:
            return (_kl_term(xs, ys),)
        if t == DistanceType.JensenShannon:
            mid = 0.5 * (xs + ys)
            return (_kl_term(xs, mid) + _kl_term(ys, mid),)
        raise NotImplementedError(t)

    def finalize(accs):
        a = accs[0]
        if t == DistanceType.L2SqrtUnexpanded:
            return jnp.sqrt(a)
        if t == DistanceType.LpUnexpanded:
            return a ** (1.0 / p)
        if t == DistanceType.HammingUnexpanded:
            return a / d
        if t == DistanceType.BrayCurtis:
            return a / jnp.maximum(accs[1], 1e-30)
        if t == DistanceType.JensenShannon:
            return jnp.sqrt(jnp.maximum(0.5 * a, 0.0))
        return a

    def body(xt, yt):
        tile = xt.shape[0]

        reduce_chunk = jnp.max if t == DistanceType.Linf else jnp.sum

        def step(c, accs):
            xs = jax.lax.dynamic_slice_in_dim(xt, c * dc, dc, axis=1)
            ys = jax.lax.dynamic_slice_in_dim(yt, c * dc, dc, axis=1)
            terms = chunk_terms(xs[:, None, :], ys[None, :, :])
            return tuple(combine(acc, reduce_chunk(term, axis=2))
                         for acc, term in zip(accs, terms))

        init = tuple(jnp.zeros((tile, m), acc_dtype)
                     for _ in range(n_acc))
        return finalize(jax.lax.fori_loop(0, n_chunks, step, init))

    # budget by the true peak: [tile, m, dc] chunk temps + [tile, m] accs
    itemsize = jnp.dtype(acc_dtype).itemsize
    return _tile_rows(res, x, y, body,
                      row_bytes=(m * dc * 3 + m * (n_acc + 1)) * itemsize)
