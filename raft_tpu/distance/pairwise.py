"""Pairwise distances.

(ref: the pre-cuVS ``raft::distance::pairwise_distance`` surface, built on
the contraction tiling substrate that survives at
cpp/include/raft/linalg/detail/contractions.cuh:313 — rebuilt TPU-first per
SURVEY §7 stage 10 / BASELINE configs 1-2.)

TPU design: "expanded" metrics (L2/cosine/correlation/IP/hellinger/russell-
rao/jaccard/dice) contract on the MXU as X·Yᵀ plus rank-1 norm corrections —
that's where the 10M×256 GB/s target comes from. "Unexpanded" metrics
(L1/Linf/Canberra/Minkowski/Hamming/KL/JS/BrayCurtis) need the |x−y| form,
which has no matmul decomposition: the streaming Pallas kernel
(ops/unexpanded_pallas.py) forms per-feature terms on VMEM-resident tiles
and folds them into [Qb, 128] accumulators — no [n, m, d] broadcast at any
memory level (the role the reference's smem tiling policies play — SURVEY
§2.3 contractions row, contractions.cuh:313). Ineligible calls take a
single fully-jitted XLA program whose broadcast-reduce fuses per row tile.
"""

from __future__ import annotations

from typing import Union

import functools

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.resources import ensure_resources
from raft_tpu.distance.types import METRIC_NAMES, DistanceType


def _as_type(metric: Union[str, DistanceType]) -> DistanceType:
    if isinstance(metric, DistanceType):
        return metric
    expects(metric in METRIC_NAMES, "unknown metric %r", metric)
    return METRIC_NAMES[metric]


def _expanded_l2(x, y, sqrt: bool):
    xx = jnp.sum(x * x, axis=1)[:, None]
    yy = jnp.sum(y * y, axis=1)[None, :]
    d2 = xx + yy - 2.0 * jnp.matmul(x, y.T, preferred_element_type=jnp.float32)
    d2 = jnp.maximum(d2, 0.0)
    return jnp.sqrt(d2) if sqrt else d2


def _cosine(x, y):
    xn = jnp.sqrt(jnp.sum(x * x, axis=1))[:, None]
    yn = jnp.sqrt(jnp.sum(y * y, axis=1))[None, :]
    denom = jnp.maximum(xn * yn, 1e-30)
    sim = jnp.matmul(x, y.T, preferred_element_type=jnp.float32) / denom
    return 1.0 - sim


def _correlation(x, y):
    xc = x - jnp.mean(x, axis=1, keepdims=True)
    yc = y - jnp.mean(y, axis=1, keepdims=True)
    return _cosine(xc, yc)


def pairwise_distance(res, x, y=None, metric: Union[str, DistanceType] = "euclidean",
                      p: float = 2.0, precision=None) -> jax.Array:
    """Full [n, m] distance matrix. (ref: pre-cuVS
    raft::distance::pairwise_distance; pylibraft.distance.pairwise_distance)

    Precision note (expanded metrics): with ``precision=None`` the MXU
    contraction runs at JAX's default matmul precision — one-pass bf16 on
    TPU, which is the same precision CLASS as the reference's default on
    A100 (cuBLAS runs f32 GEMMs on TF32 tensor cores, 10-bit mantissa).
    Pass ``precision=jax.lax.Precision.HIGHEST`` for f32-grade
    contractions (3-pass bf16 split — BEYOND the reference's default), or
    use ``jax.default_matmul_precision`` to set it globally.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.distance import pairwise_distance
    >>> x = np.array([[0.0, 0.0], [3.0, 4.0]])
    >>> np.asarray(pairwise_distance(None, x, metric="euclidean")).round(1).tolist()
    [[0.0, 5.0], [5.0, 0.0]]
    """
    x = jnp.asarray(x)
    y = x if y is None else jnp.asarray(y)
    expects(x.ndim == 2 and y.ndim == 2 and x.shape[1] == y.shape[1],
            "pairwise_distance: inputs must be [n,d],[m,d]")
    t = _as_type(metric)
    if precision is not None:
        if isinstance(precision, jax.lax.Precision):
            precision = precision.name.lower()
        with jax.default_matmul_precision(precision):
            return _pairwise_dispatch(res, x, y, t, p)
    return _pairwise_dispatch(res, x, y, t, p)


_UNEXPANDED_TYPES = frozenset({
    DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
    DistanceType.L1, DistanceType.Linf, DistanceType.LpUnexpanded,
    DistanceType.Canberra, DistanceType.HammingUnexpanded,
    DistanceType.BrayCurtis, DistanceType.KLDivergence,
    DistanceType.JensenShannon,
})


def _pairwise_dispatch(res, x, y, t: DistanceType, p: float) -> jax.Array:
    if t not in _UNEXPANDED_TYPES:
        # ONE jitted program for the expanded metrics: eagerly, the
        # 5-6 ops each cost a per-op transport dispatch (~2 ms on the
        # tunneled TPU — config 1's entire 11 ms "compute" was
        # dispatch overhead, ref contractions.cuh:1's single-launch
        # small-shape path)
        return _pairwise_expanded_jit(x, y, t, p)
    # unexpanded (broadcast-form) metrics: every one of them accumulates
    # elementwise over features, so the [tile, m, d] broadcast is folded
    # over FEATURE CHUNKS with a [tile, m]-shaped carry — the d-axis
    # analog of the reference's k-blocked smem policy
    # (linalg/detail/contractions.cuh:313). Peak temp = [tile, m, dc].
    return _unexpanded(res, x, y, t, p)


@functools.partial(jax.jit, static_argnames=("t", "p"))
def _pairwise_expanded_jit(x, y, t: DistanceType, p: float) -> jax.Array:

    if t == DistanceType.L2Expanded:
        return _expanded_l2(x, y, sqrt=False)
    if t == DistanceType.L2SqrtExpanded:
        return _expanded_l2(x, y, sqrt=True)
    if t == DistanceType.InnerProduct:
        return jnp.matmul(x, y.T, preferred_element_type=jnp.float32)
    if t == DistanceType.CosineExpanded:
        return _cosine(x, y)
    if t == DistanceType.CorrelationExpanded:
        return _correlation(x, y)
    if t == DistanceType.HellingerExpanded:
        ip = jnp.matmul(jnp.sqrt(jnp.abs(x)), jnp.sqrt(jnp.abs(y)).T,
                        preferred_element_type=jnp.float32)
        return jnp.sqrt(jnp.maximum(1.0 - jnp.minimum(ip, 1.0), 0.0))
    if t == DistanceType.RussellRaoExpanded:
        d = x.shape[1]
        ip = jnp.matmul((x != 0).astype(jnp.float32), (y != 0).astype(jnp.float32).T,
                        preferred_element_type=jnp.float32)
        return (d - ip) / d
    if t in (DistanceType.JaccardExpanded, DistanceType.DiceExpanded):
        xb = (x != 0).astype(jnp.float32)
        yb = (y != 0).astype(jnp.float32)
        inter = jnp.matmul(xb, yb.T, preferred_element_type=jnp.float32)
        nx = jnp.sum(xb, axis=1)[:, None]
        ny = jnp.sum(yb, axis=1)[None, :]
        if t == DistanceType.JaccardExpanded:
            union = jnp.maximum(nx + ny - inter, 1e-30)
            return 1.0 - inter / union
        return 1.0 - 2.0 * inter / jnp.maximum(nx + ny, 1e-30)
    raise ValueError(f"_pairwise_expanded_jit: unexpanded metric {t}")


def _kl_term(a, b):
    r = jnp.where((a > 0) & (b > 0), a / jnp.where(b > 0, b, 1.0), 1.0)
    return jnp.where(a > 0, a * jnp.log(r), 0.0)


def _unexp_terms(xs, ys, t: DistanceType, p: float, acc_dtype):
    """Per-feature terms on a broadcastable (xs, ys) pair — the ONE
    definition of every unexpanded metric's inner form, shared by the
    jitted XLA path and the Pallas kernel's emulation tests."""
    diff = xs - ys
    if t in (DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded):
        return (diff * diff,)
    if t == DistanceType.L1 or t == DistanceType.Linf:
        return (jnp.abs(diff),)
    if t == DistanceType.LpUnexpanded:
        return (jnp.abs(diff) ** p,)
    if t == DistanceType.Canberra:
        denom = jnp.abs(xs) + jnp.abs(ys)
        safe = jnp.where(denom == 0, 1.0, denom)
        return (jnp.where(denom == 0, 0.0, jnp.abs(diff) / safe),)
    if t == DistanceType.HammingUnexpanded:
        return ((xs != ys).astype(acc_dtype),)
    if t == DistanceType.BrayCurtis:
        return (jnp.abs(diff), jnp.abs(xs + ys))
    if t == DistanceType.KLDivergence:
        return (_kl_term(xs, ys),)
    if t == DistanceType.JensenShannon:
        mid = 0.5 * (xs + ys)
        return (_kl_term(xs, mid) + _kl_term(ys, mid),)
    raise NotImplementedError(t)


def _unexp_finalize(accs, t: DistanceType, p: float, d: int):
    a = accs[0]
    if t == DistanceType.L2SqrtUnexpanded:
        return jnp.sqrt(a)
    if t == DistanceType.LpUnexpanded:
        return a ** (1.0 / p)
    if t == DistanceType.HammingUnexpanded:
        return a / d
    if t == DistanceType.BrayCurtis:
        return a / jnp.maximum(accs[1], 1e-30)
    if t == DistanceType.JensenShannon:
        return jnp.sqrt(jnp.maximum(0.5 * a, 0.0))
    return a


@functools.partial(jax.jit, static_argnames=("t", "p", "d_true", "tile"))
def _unexpanded_jit(x, y, t: DistanceType, p: float, d_true: int,
                    tile: int) -> jax.Array:
    """The whole unexpanded pairwise op as ONE compiled program: a scan
    over row tiles whose body is reduce(term(broadcast)) — XLA:TPU's
    loop fusion consumes the [tile, m, d] broadcast inside the reduction
    without materializing it in HBM (verified in the kernel-path bench:
    benchmarks/bench_unexpanded.py), and the single dispatch removes the
    per-tile transport RTT the round-3 Python loop paid (measured ~2 ms
    PER eager op on the tunneled v5e — memory: config-1 floor)."""
    n, d = x.shape
    m = y.shape[0]
    acc_dtype = jnp.promote_types(jnp.promote_types(x.dtype, y.dtype),
                                  jnp.float32)
    reduce_d = jnp.max if t == DistanceType.Linf else jnp.sum

    def one_tile(xt):
        terms = _unexp_terms(xt[:, None, :].astype(acc_dtype),
                             y[None, :, :].astype(acc_dtype),
                             t, p, acc_dtype)
        return _unexp_finalize(tuple(reduce_d(tm, axis=2) for tm in terms),
                               t, p, d_true)

    n_tiles = -(-n // tile)
    npad = n_tiles * tile - n
    xp = jnp.concatenate([x, jnp.zeros((npad, d), x.dtype)]) if npad else x
    out = jax.lax.map(one_tile, xp.reshape(n_tiles, tile, d))
    return out.reshape(n_tiles * tile, m)[:n]


def _unexpanded(res, x, y, t: DistanceType, p: float) -> jax.Array:
    n, d = x.shape
    m = y.shape[0]
    acc_dtype = jnp.promote_types(jnp.promote_types(x.dtype, y.dtype),
                                  jnp.float32)
    if d == 0:
        return jnp.zeros((n, m), acc_dtype)

    # Pallas streaming path (TPU): [Qb, T] VMEM accumulators, terms
    # formed on VMEM-resident tiles — no [tile, m, d] broadcast at any
    # memory level (the contraction-substrate role, contractions.cuh:313)
    from raft_tpu.ops.unexpanded_pallas import (unexpanded_eligible,
                                                unexpanded_pairwise_tiled)

    if unexpanded_eligible(t, n, m, d, x.dtype, y.dtype):
        # kernel envelope: finite inputs (0·inf = NaN through its
        # one-hot selector dot). The check needs concrete values — a
        # traced call (inside a user jit) takes the XLA path, whose
        # semantics cover non-finites
        concrete = not (isinstance(x, jax.core.Tracer)
                        or isinstance(y, jax.core.Tracer))
        if concrete and bool(jnp.isfinite(x).all()) \
                and bool(jnp.isfinite(y).all()):
            return unexpanded_pairwise_tiled(x, y, t, p)

    # jitted XLA fallback: one program, fused broadcast-reduce; tile
    # rows so XLA's scheduling (and any non-fused corner) stays inside
    # the workspace budget
    itemsize = jnp.dtype(acc_dtype).itemsize
    res = ensure_resources(res)
    budget_rows = res.workspace.batch_rows(m * 8 * itemsize)
    tile = int(max(1, min(n, budget_rows)))
    return _unexpanded_jit(x, y, t, float(p), d, tile)
