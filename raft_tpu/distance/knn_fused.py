"""Certified fused KNN — the flagship TPU pipeline.

(ref: the reference's fused distance→select path: brute-force knn =
pairwise distance + matrix::select_k, with select_radix.cuh /
select_warpsort.cuh consuming distance tiles; BASELINE config 2.)

Pipeline (all one jit program):

1. ``ops.fused_l2_topk_pallas.fused_l2_group_topk`` streams index tiles
   through VMEM: MXU contraction + an IN-KERNEL top-2+3rd-min fold per
   (lane-class, tile-group) — output blocks are revisited across ``g``
   consecutive index tiles, so the fold accumulates in VMEM and the
   distance tiles never touch HBM; only the [Q, 2·S'] group summary does
   (S' = ceil(n_tiles/g)·128 slots). (Round-2 profile: the earlier
   XLA-side group fold re-read ~1 GB of per-(tile,lane) slot arrays and
   cost 3× the kernel itself.)
2. TWIN-POOL selection (packed path): ``top_k`` picks Ca = k + pad
   winners from the a1 (per-group best) array alone — XLA's TopK is
   superlinear in pool width inside the composite program, so the
   2·S'-wide concat pool is never built — then each winner's a2 TWIN
   is pulled by position and the 2·Ca candidates are pruned back to C
   by kernel order; the C survivors are rescored EXACTLY (f32, HIGHEST
   precision) and the final top-k is taken on exact values.
3. EXACTNESS CERTIFICATE, per query: every point outside the candidate
   set has kernel-distance ≥ B = min(group-3rd-min, Ca-th a1 value,
   C-th pruned kernel value) — an a1 loser is ≥ the Ca-th a1 value, an
   a2 twin of an a1 loser is ≥ its own a1 (merge invariant a2 ≥ a1),
   a pruned candidate is ≥ the C-th pruned value, and anything outside
   a bucket's top-2 is ≥ that bucket's 3rd-min. With |kernel − exact|
   ≤ E, ``B − E ≥ θ*`` (θ* = exact k-th candidate distance) proves no
   point can beat the returned top-k. Every term is ≥ the whole-pool
   C-th value the round-2 design used, so the bound only tightened.
   The bound needs NO second distance pass — it falls out of the fold.
4. Queries that fail the certificate (THREE true neighbors sharing a
   (lane, group): ~k³/6S'² per query — single digits per 2048 queries
   at production scale; certify="f32"'s wider margin can fail
   hundreds) are re-solved exactly and scattered back: tiered static
   batches (16/128/512/1024, each eligible only while its [F, M] tile
   fits the fixup budget) that materialize an [F, M] distance tile
   and take one top_k; a full streamed fallback covers pathological
   batches (cond) and the empty-ladder regime (M too large for any
   tile).

Modes:
- ``passes=3`` (exact): bf16 hi/lo split contraction (hi·hi + hi·lo +
  lo·hi) ⇒ f32-grade kernel distances; E is a rigorous norm-based bound,
  so the result is certified exact w.r.t. f32 distances.
- ``passes=1`` (fast): single bf16 contraction; E = 0, so the certificate
  guarantees exactness w.r.t. the bf16 score function; recall vs f32 is
  empirical (≥0.99 typical — measured in benchmarks/).

Precision contract: the score function is the EXPANDED squared L2,
``‖x‖² + ‖y‖² − 2x·y``, evaluated in f32 — the same functional form the
reference's fusedL2NN/pairwise kernels evaluate on GPU. Like the
reference, expanded f32 carries cancellation noise of order
``ulp(‖x‖² + ‖y‖²)`` when true distances are tiny relative to the norms
(near-duplicate points); "certified exact" means exact top-k of THAT
score function, with returned values within ulp-noise of the infinite-
precision expanded scores (validated in tests against an f64 oracle).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point

from raft_tpu.ops.fused_l2_topk_pallas import (
    _LANES, _PACK_BITS, _PACK_MASK, _PACK_PAD, _PBITS_MAX,
    fused_l2_group_topk, fused_l2_group_topk_dchunk,
    fused_l2_group_topk_packed, fused_l2_group_topk_packed_db,
    fused_l2_group_topk_packed_db_q8, fused_l2_group_topk_packed_dbuf,
    fused_l2_group_topk_packed_dbuf_q8,
    fused_l2_group_topk_packed_dchunk, split_hi_lo, vmem_budget,
    vmem_footprint)

# grid iteration orders for the packed fused kernel (see the
# DATABASE-MAJOR block comment in ops.fused_l2_topk_pallas):
#   "query" — grid (nq, n_tiles): y re-fetched per query block
#             (y HBM traffic nq·M·d bytes — the historical default);
#   "db"    — super-blocked grid (n_groups, nq): each [g·T, d] group
#             VMEM-resident, y streams from HBM once (M·d·2 bytes);
#   "dbuf"  — grid (n_groups,): explicit 2-slot double-buffered y-tile
#             DMA, y streams once and only 2 tiles are VMEM-resident.
GRID_ORDERS = ("query", "db", "dbuf")

# storage dtypes for the STREAMED database slab:
#   "bf16" — the historical hi(/lo) bf16 split: M·d·2 (p1) or M·d·4
#            (p3) bytes per stream;
#   "int8" — per-certificate-group symmetric-scale quantization:
#            M·d·1 bytes per stream regardless of passes, with the
#            twin-pool certificate widened by the recorded per-group
#            quantization bound Eq and candidates ALWAYS exact-rescored
#            in f32 from the original rows — returned ids are certified
#            identical to the f32 oracle's (ROADMAP item 2).
DB_DTYPES = ("bf16", "int8")

# int8 quantization geometry: symmetric (zero_point = 0), code range
# ±_Q8_LEVELS; the per-element round-trip error bound is
# scale · _Q8_ERR (½ ulp of the code grid + headroom for the f32
# divide/round/multiply chain — the property test drives adversarial
# scale-boundary values at it)
_Q8_LEVELS = 127
_Q8_ERR = 0.5 * (1.0 + 2.0 ** -10)

# past this feature width the single-shot kernel's [Qb/T, d] VMEM tiles
# stop fitting; the d-chunked kernel (VMEM scratch accumulator) takes over
_D_SINGLE_SHOT = 512
_DC = 256          # d-chunk width for the wide-feature kernel

# static fixup batches: queries whose certificate failed re-run exactly
# against the whole index. Tiered (16 first) because the cond pays the
# whole static tier even for one failed query; with the group kernel's
# top-2-per-group certificate the typical failure count is single-digit
# per 2048 queries, so the small tier almost always suffices. The
# larger tiers exist for certify="f32" (adaptive precision), whose
# wider margin can fail hundreds of queries — without them anything
# past the 128 tier hit the catastrophic full streamed fallback. A
# tier is only eligible when its [F, M] f32 distance tile fits the
# budget (at 10M rows the 512+ tiers would be 20+ GB).
_FIXUP_TIERS = (16, 128, 512, 1024)
# budget for ONE [F, M] f32 tile; the materialized branch holds ~2 live
# copies (d2 + the negated top_k input), so peak ≈ 2× this + operands —
# 4.2 GB keeps the 1024 tier at the 1M driver shape (2·4.1 GB + ~2 GB
# of index operands < 16 GB v5e HBM) and sheds it past ~1.05M rows
_FIXUP_TILE_BUDGET = 4_200_000_000
# pool oversampling beyond k before exact rescoring
_POOL_PAD = 32
# query-chunk bound: the [Q, S] slot arrays + [Q, C, d] rescore gather are
# sized by Q — queries are processed in chunks of this many (≈1 GB peak at
# the 1M×128 BASELINE shape), the fused path's analog of the streamed
# path's workspace-budgeted tile
_Q_CHUNK = 2048


def _err_bound_coeff(d: int) -> float:
    """Analytic upper bound on |d2_kernel − d2_exact| / (‖x‖·‖y‖) for the
    bf16x3 mode. Components (unit roundoffs: bf16 2⁻⁸ — 7 stored
    mantissa bits, round-to-nearest — and f32 2⁻²⁴):
      - dropped lo·lo term: Σ|lo(x)||lo(y)| ≤ 2⁻¹⁶·‖x‖‖y‖
      - bf16 re-rounding of the lo factors (x = hi + lo + δ,
        |δ| ≤ 2⁻¹⁶|x|): ≤ 2·2⁻¹⁶·‖x‖‖y‖
      - f32 accumulation, textbook bound d·2⁻²⁴·Σ|x·y| per matmul, three
        matmuls: ≤ 3d·2⁻²⁴·‖x‖‖y‖
    S_err ≤ (3·2⁻¹⁶ + 3d·2⁻²⁴)·‖x‖‖y‖; doubled for d2 = 2·S_err and
    doubled again as safety margin ⇒ ≤ (1.5·2⁻¹³ + 1.5·d·2⁻²¹)·‖x‖‖y‖,
    rounded UP to a clean power of two. The margin's only cost is fixup
    rate, but the BOUND ITSELF must hold for the exactness certificate
    to be sound. (Round 4: the first version assumed bf16 u = 2⁻⁹ and
    shipped 2⁻¹⁵ — understated ~4× against the adversarial worst case,
    though ~30× above errors observed on random/clustered data.)"""
    return 2.0 ** -12 + d * 2.0 ** -20


def _err_bound_coeff_p1(d: int) -> float:
    """|d2_kernel − d2_f32| / (‖x‖·‖y‖) bound for the ONE-pass bf16
    contraction — the margin behind ``certify="f32"`` at passes=1
    (adaptive precision: p1 speed, f32-exact certificate, failures
    re-solved by the exact fixup). Components (bf16 u = 2⁻⁸):
      - bf16 rounding of both factors: ≤ (2·2⁻⁸ + 2⁻¹⁶)·‖x‖‖y‖
      - f32 accumulation: ≤ d·2⁻²⁴·‖x‖‖y‖
    Doubled for d2 = 2·S_err and doubled again as safety margin ⇒
    ≤ (2⁻⁵ + 2⁻¹⁴ + d·2⁻²²)·‖x‖‖y‖ — the 2⁻¹⁴ is the doubled 2⁻¹⁶
    cross term, kept so every component is rounded UP like
    _err_bound_coeff's (a loose margin only raises fixup rate; the
    bound itself must hold)."""
    return 2.0 ** -5 + 2.0 ** -14 + d * 2.0 ** -22


def pool_select_algo() -> str:
    """The pool-selection routing for knn_fused, from
    ``RAFT_TPU_POOL_SELECT`` (xla | two_stage | slotted | chunked).
    Read by the NON-jitted entry points and threaded into the core as a
    static argument — an env read inside the jitted core would be
    frozen into the first-traced executable and silently ignore later
    changes (A/B harnesses flip this between calls)."""
    algo = os.environ.get("RAFT_TPU_POOL_SELECT", "xla")
    if algo not in ("xla", "two_stage", "slotted", "chunked"):
        from raft_tpu.core.logger import log_warn

        log_warn("RAFT_TPU_POOL_SELECT=%r unknown — using 'xla'", algo)
        algo = "xla"
    return algo


def resolve_pool_algo(algo: str, pool_len: int, c: int) -> str:
    """Decide the EFFECTIVE pool-selection algorithm for a pool of width
    ``pool_len`` selecting ``c`` — called from the NON-jitted wrapper
    BEFORE the core, so the downgrade decision (and its warning) happens
    per call. Deciding inside the jitted core was an observability-
    truthfulness bug: the trace-time ``log_warn`` fired once, and every
    later call served from the compiled cache ran the XLA fallback
    silently — A/B runs flipping ``RAFT_TPU_POOL_SELECT`` after the
    first trace were mislabeled. The envelope predicates mirror the
    selectors' own NotImplementedError checks (pool values are always
    f32, so only the shape envelopes apply)."""
    if algo == "slotted":
        from raft_tpu.matrix.select_k_slotted import slotted_envelope

        _, _, pool_cap = slotted_envelope(pool_len, c)
        if c <= pool_cap:
            return algo
        reason = f"k={c} exceeds slotted pool {pool_cap}"
    elif algo in ("two_stage", "chunked"):
        from raft_tpu.matrix.select_k_chunked import chunked_envelope

        nc = 2 if algo == "two_stage" else 8
        if chunked_envelope(pool_len, nc):
            return algo
        reason = f"len={pool_len} too short for nc={nc}"
    else:
        return "xla"
    from raft_tpu.core.logger import log_warn

    log_warn("pool select %r outside envelope on len=%d→%d (%s) — "
             "using XLA top_k for this call", algo, pool_len, c, reason)
    return "xla"


def _pool_smallest(a, c: int, algo: str = "xla"):
    """Exact c smallest per row of the candidate pool ``a`` →
    (values ascending, positions). The driver profile attributes ~4.5
    of 19.3 ms e2e to this selection (XLA's TopK measured ~2.5×
    superlinear in width in-composite, round 3) — route it to any of
    the repo's EXACT selection algorithms so the A/B
    (benchmarks/r4_pool_select.py) can flip algorithms end-to-end
    without code edits. Exactness is non-negotiable here: the twin-pool
    certificate's bound_a1 / C-th-pruned terms assume exact selection
    (an approximate selector leaves skipped bucket-top-2 entries with
    no floor — the a3 term does not cover them). Values are re-gathered
    from ``a`` so packed mantissa codes survive bit-exactly.

    ``algo`` must already be the EFFECTIVE algorithm: the non-jitted
    wrapper resolves the shape envelope via :func:`resolve_pool_algo`
    per call (an out-of-envelope algo here raises at trace time instead
    of silently mislabeling what ran — the old in-core fallback logged
    once at trace time and lied for every cached call after)."""
    B, S = a.shape
    if algo in ("two_stage", "slotted", "chunked"):
        from raft_tpu.matrix.select_k_chunked import select_k_chunked
        from raft_tpu.matrix.select_k_slotted import select_k_slotted

        idx = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None],
                               (B, S))
        if algo == "slotted":
            vals, pos = select_k_slotted(a, idx, c, True)
        else:
            # two_stage IS the chunked merge with 2 chunks
            vals, pos = select_k_chunked(
                a, idx, c, True, nc=2 if algo == "two_stage" else 8)
        # bit-exact packed codes: re-gather from the input
        return jnp.take_along_axis(a, pos, axis=1), pos
    neg, pos = jax.lax.top_k(-a, c)
    return -neg, pos


def decode_packed_pool(cand_p, pos, S_: int, T: int, g: int,
                       pbits: int = _PACK_BITS):
    """Candidate columns from (packed value, pool position) — THE
    decode for the packed kernel's mantissa codes, shared by the
    production pipeline and the profiler so they cannot drift. Returns
    -1 for sentinel/empty entries."""
    n_ch = T // _LANES
    slot = pos % S_
    local = (jax.lax.bitcast_convert_type(cand_p, jnp.int32)
             & ((1 << pbits) - 1))
    col = ((slot // _LANES) * g + local // n_ch) * T \
        + (local % n_ch) * _LANES + (slot % _LANES)
    return jnp.where(cand_p < _PACK_PAD * 0.25, col, -1)


def auto_pack_bits(n_tiles: int, T: int) -> int:
    """Pack-code width for an index of ``n_tiles`` tiles of length T:
    the candidate pool (and the certificate's bucket count) is
    M/2^pbits wide, so pick the widest codes that keep ≥ ~2.5k buckets
    (fixup rate ∝ 1/buckets²), clamped to [8, 13] (value perturbation
    2^(pbits−23) must stay well under the error margins). ONE
    definition — prepare_knn_index and the north-star benchmark both
    call it, so the measured configuration cannot drift from
    production's."""
    import math

    return min(_PBITS_MAX, max(_PACK_BITS, int(math.floor(
        math.log2(max(n_tiles * T / 2560.0, 256.0))))))


def _pad_rows_to(y, mult: int):
    from raft_tpu.distance.fused_l2nn import _pad_rows

    return _pad_rows(y, mult)[0]


def pad_query_rows(x, rows: int):
    """Pad a RAGGED query batch up to a fixed ``rows`` count with zero
    rows — the serving engine's bucket shapes (raft_tpu.serving) and the
    AOT ``knn_query`` runtime entry both route ragged request batches
    through this so every dispatch hits a pre-compiled shape. Zero-row
    queries are inert through the whole pipeline (their top-k is
    computed and discarded — the certificate and fixup maths are
    per-query, so pads cannot perturb real rows); callers slice the
    first ``n`` result rows back out. Raises when the batch is LARGER
    than the bucket: silently truncating requests is exactly the
    failure mode the serving ladder's reject path exists to prevent."""
    n = x.shape[0]
    if n > rows:
        raise ValueError(f"pad_query_rows: batch of {n} rows does not "
                         f"fit the {rows}-row bucket")
    if n == rows:
        return x
    return jnp.concatenate(
        [x, jnp.zeros((rows - n, x.shape[1]), x.dtype)], axis=0)


@functools.partial(jax.jit, static_argnames=("T", "g", "metric",
                                             "pbits", "grid_order"))
def _prepare_ops(y, T: int, g: int, metric: str,
                 pbits: int = _PACK_BITS, grid_order: str = "query",
                 n_valid=None, rows_valid=None):
    """Index-side operand prep: row padding, bf16 hi/lo split, norms and
    the [8, M] half-norm sentinel carrier. ~3 ms at 1M×128 on v5e —
    hoisted out of the query path so a prepared index (KnnIndex) pays
    it ONCE instead of per query batch.

    Database-major grid orders pad the index to WHOLE certificate
    groups (g·T rows — each super-block is one resident y block /
    one DMA group); padded columns carry the same never-wins sentinel
    either way, so the extra rows are certificate-invisible.

    ``n_valid`` overrides the real-row count when the caller passes an
    ALREADY-PADDED matrix (the sharded index prep pads globally to a
    whole number of equal shards before splitting, so the trailing
    rows of ``y`` itself are pads that must carry the sentinel). It may
    be a plain int or a TRACED scalar — inside the sharded prep's
    shard_map one traced program serves every shard, and each shard's
    real-row count is a value (a function of its mesh coordinate), not
    a shape.

    ``rows_valid`` is the RAGGED generalization of ``n_valid``: a [m]
    bool mask over the INPUT rows marking which are real — pads may be
    interspersed anywhere, not just trailing. This is the layout the
    IVF-Flat inverted lists (raft_tpu.ann — each list padded to a row
    quantum, so pads sit at every list tail) and the serving engine's
    bucket padding share. Masked-out rows carry the same never-wins
    sentinel trailing pads do, so they are invisible to the fold and
    the certificate; rows appended here to reach the tile multiple are
    masked too. Mutually exclusive with ``n_valid``."""
    if rows_valid is not None:
        m = y.shape[0]       # geometric row count; masking is per-row
    else:
        m = y.shape[0] if n_valid is None else n_valid
    yp = _pad_rows_to(y, g * T if grid_order in ("db", "dbuf") else T)
    M = yp.shape[0]
    yy_raw = jnp.sum(yp * yp, axis=1)[None, :]                  # [1,M] f32
    n_ch = T // _LANES
    packed = g * n_ch <= (1 << pbits)
    pad_sentinel = _PACK_PAD if packed else jnp.inf
    if rows_valid is not None:
        rv = jnp.asarray(rows_valid, jnp.bool_).reshape(-1)
        pad = M - rv.shape[0]
        if pad:
            rv = jnp.concatenate([rv, jnp.zeros((pad,), jnp.bool_)])
        valid = rv[None, :]
    else:
        valid = (jnp.arange(M, dtype=jnp.int32) < m)[None, :]
    if metric == "ip":
        # r = 0/2 − x·(y/2) = −x·y/2 → score −x·y = 2·r (+ xx_r = 0)
        y_hi, y_lo = split_hi_lo(yp * 0.5)
        yyh_k = jnp.where(valid, 0.0, pad_sentinel)
    else:
        y_hi, y_lo = split_hi_lo(yp)
        yyh_k = jnp.where(valid, 0.5 * yy_raw, pad_sentinel)
    # [8, M] sublane-replicated carrier (see fused_l2_group_topk)
    yyh_k = jnp.broadcast_to(yyh_k, (8, M))
    return yp, y_hi, y_lo, yyh_k, yy_raw


def quantize_rows_q8(z, gid, n_groups: int, valid=None):
    """Per-group symmetric int8 quantization of the stream operand
    ``z`` [M, d] (group of row i = ``gid[i]``): scale_g =
    max|z_group| / 127 (zero_point 0 — L2/IP operands are centered by
    construction), codes clipped to ±127 so an f32 divide landing
    epsilon past the last level can never overflow the int8 range.
    Returns (y_q int8 [M, d], scales f32 [n_groups]). ``valid`` masks
    rows out of the scale computation (pad/garbage rows must not
    inflate a group's scale); their codes are still produced but every
    consumer hides them behind the never-wins sentinel."""
    absz = jnp.abs(z)
    if valid is not None:
        absz = jnp.where(valid.reshape(-1, 1), absz, 0.0)
    row_max = jnp.max(absz, axis=1)
    gmax = jax.ops.segment_max(row_max, gid, num_segments=n_groups)
    gmax = jnp.maximum(gmax, 0.0)          # empty segment → -inf → 0
    scales = jnp.where(gmax > 0, gmax / _Q8_LEVELS, 1.0)
    srow = jnp.take(scales, gid).reshape(-1, 1)
    q = jnp.clip(jnp.round(z / srow), -_Q8_LEVELS, _Q8_LEVELS)
    return q.astype(jnp.int8), scales


def q8_eq_bound(scales, d: int):
    """Per-group quantization error bound Eq: an upper bound on the
    ROW-VECTOR L2 error ‖z_row − dequant(quant(z_row))‖ for any row of
    a group with scale ``scales[g]`` — per element the round-trip error
    is ≤ scale·_Q8_ERR (½ code step + f32 divide/round/multiply
    headroom; clipped boundary values err by ≤ scale·127·2⁻²³, well
    inside), so the row bound is scale·_Q8_ERR·√d. Padded feature
    columns are exactly zero → quantize exactly → contribute 0, so the
    padded √d is simply a looser-but-sound bound. This is the margin
    the twin-pool certificate is widened by (see _knn_fused_core), and
    the bound the property test attacks with adversarial
    scale-boundary values."""
    import math

    return scales * (_Q8_ERR * math.sqrt(max(d, 1)))


@functools.partial(jax.jit, static_argnames=("T", "g", "metric",
                                             "pbits", "grid_order"))
def _prepare_ops_q8(y, T: int, g: int, metric: str,
                    pbits: int = _PACK_BITS, grid_order: str = "db",
                    n_valid=None, rows_valid=None):
    """INT8 sibling of :func:`_prepare_ops` — index-side operand prep
    for the quantized-streaming kernels: row padding to WHOLE
    certificate groups, per-group symmetric int8 quantization of the
    stream operand (y for l2, y/2 for ip), the group-scale tile, and
    carriers computed from the DEQUANTIZED rows ŷ so the kernel's
    folded value is exactly d2(x, ŷ)/2 (l2) — the codes, decode and
    certificate algebra downstream are untouched.

    Returns ``(yp, y_q, scale_k, yyh_k, yy_raw, eq_groups)``:
    yp [M, d] f32 row-padded ORIGINAL rows (the exact-rescore source —
    int8 indexes always store it), y_q [M, d] int8, scale_k
    [G, 8, 128] f32 group-replicated, yyh_k [8, M] the dequantized
    half-norm sentinel carrier, yy_raw [1, M] the dequantized
    full-scale norms (the bf16 error bound's ymax), eq_groups [G] the
    per-group quantization bound (see :func:`q8_eq_bound`).

    ``n_valid``/``rows_valid`` follow _prepare_ops' contract (trailing
    vs ragged pads). Packed/database-major only — the quantized
    kernels are the stream-once ones."""
    if grid_order not in ("db", "dbuf"):
        raise ValueError("_prepare_ops_q8: int8 streaming is "
                         "database-major only (grid_order 'db'/'dbuf')")
    n_ch = T // _LANES
    if g * n_ch > (1 << pbits):
        raise ValueError("_prepare_ops_q8: int8 streaming needs the "
                         "packed-code envelope (g·(T/128) ≤ 2^pbits)")
    if rows_valid is not None:
        m = y.shape[0]
    else:
        m = y.shape[0] if n_valid is None else n_valid
    yp = _pad_rows_to(y, g * T)
    M, d = yp.shape
    G = M // (g * T)
    if rows_valid is not None:
        rv = jnp.asarray(rows_valid, jnp.bool_).reshape(-1)
        pad = M - rv.shape[0]
        if pad:
            rv = jnp.concatenate([rv, jnp.zeros((pad,), jnp.bool_)])
        valid_row = rv
    else:
        valid_row = jnp.arange(M, dtype=jnp.int32) < m
    z = yp * 0.5 if metric == "ip" else yp
    gid = jnp.arange(M, dtype=jnp.int32) // (g * T)
    y_q, scales = quantize_rows_q8(z, gid, G, valid=valid_row)
    eq_groups = q8_eq_bound(scales, d)
    # dequantized stream operand ẑ — the rows the kernel actually
    # scores; its norms ride the carrier so kernel values are exactly
    # d2(x, ẑ) (l2) and the Eq widening is the ONLY new error term
    zq = y_q.astype(jnp.float32) * jnp.take(scales, gid).reshape(-1, 1)
    valid = valid_row[None, :]
    if metric == "ip":
        yyh_k = jnp.where(valid, 0.0, _PACK_PAD)
        yhat_full = 2.0 * zq       # full-scale dequantized ŷ (= 2·ẑ)
    else:
        yy_hat = jnp.sum(zq * zq, axis=1)[None, :]
        yyh_k = jnp.where(valid, 0.5 * yy_hat, _PACK_PAD)
        yhat_full = zq
    yy_raw = jnp.sum(yhat_full * yhat_full, axis=1)[None, :]
    yyh_k = jnp.broadcast_to(yyh_k, (8, M))
    scale_k = jnp.broadcast_to(scales.reshape(G, 1, 1), (G, 8, _LANES))
    return yp, y_q, scale_k, yyh_k, yy_raw, eq_groups


@functools.partial(jax.jit,
                   static_argnames=("k", "T", "Qb", "g", "passes", "metric",
                                    "m", "rescore", "pbits", "certify",
                                    "pool_algo", "grid_order", "db_dtype",
                                    "_diag", "with_stats"))
def _knn_fused_core(x, yp, y_hi, y_lo, yyh_k, yy_raw,
                    k: int, T: int, Qb: int, g: int, passes: int,
                    metric: str, m: int, rescore: bool = True,
                    pbits: int = _PACK_BITS, certify: str = "kernel",
                    pool_algo: str = "xla", grid_order: str = "query",
                    db_dtype: str = "bf16",
                    _diag: bool = False, with_stats: bool = False,
                    m_valid=None, rows_valid=None,
                    y_q=None, y_scale_k=None,
                    eq_groups=None) -> Tuple[jax.Array, ...]:
    """Certified fused KNN on PREPARED operands (see _prepare_ops).

    ``m_valid`` (optional TRACED scalar) overrides the static ``m`` in
    every real-row mask (kernel column mask, rescore id clamp, fixup
    column masks). The sharded pipeline (distance.knn_sharded) needs it:
    one shard_map-traced program serves every shard, but each shard owns
    a different number of real rows — a value, not a shape. ``m`` keeps
    sizing the static fixup-tier geometry.

    ``rows_valid`` (optional TRACED [M] bool, M = the PREPARED row
    count) is the RAGGED mask: real rows may be interspersed with pads
    (the IVF-Flat slab layout — every inverted list tail is padding).
    The operands must have been prepared with the SAME mask (the
    sentinel carrier is what hides pads from the kernel fold); here it
    only replaces the prefix column masks in the fixup sweeps and
    widens the rescore clamp to the whole slab. Packed-path only: the
    unpacked kernels prefix-mask in-kernel by ``m_real`` and cannot
    honor an arbitrary mask.

    x [Q, d] f32 (Q % Qb == 0, d % 128 == 0 — caller pads), y [m, d] f32
    un-padded rows; returns exact (score [Q, k] ascending, ids [Q, k]).
    ``metric="l2"`` scores expanded squared L2; ``metric="ip"`` scores
    ``−x·y`` (so ascending = best inner products first) by feeding the
    SAME kernel zeros for xx/yy and the hi/lo split of y/2:
    d2 = 0 + 0 − 2·x·(y/2) = −x·y. The certificate algebra is
    metric-blind (it only needs "every non-candidate ≥ its slot's
    2nd-min"); the bf16x3 error bound uses the TRUE operand norms.

    The kernel folds the HALF-SCORE r = yy/2 − x·y (a positive-scale +
    per-row-shift of d2, so per-row ordering is identical — one fewer
    live [Qb, T] buffer in-kernel); padded index columns carry a
    "never wins" sentinel so they lose every strict < in the fold (no
    in-kernel masking). True distances are recovered as 2·r + xx on
    the tiny [Q, S'] outputs.

    PACKED path (production whenever the per-group slot count fits the
    _PACK_BITS code space): candidate ids ride in the low mantissa
    bits of the half-scores — no id selects in the merge, no id output
    arrays, no pool-id gather; the candidate column reconstructs from
    (pool position, embedded code). Packing perturbs values by
    ≤ |v|·2⁻¹⁵, absorbed into the certificate margin e_pack.
    """
    Q, d = x.shape
    quant = db_dtype == "int8"
    M = (y_q if quant else y_hi).shape[0]
    n_ch = T // _LANES
    packed = g * n_ch <= (1 << pbits)
    if quant:
        # the quantized-streaming contract (prepare_knn_index resolves
        # requests outside it down to bf16 BEFORE the core): packed
        # database-major kernels only, and the exact f32 rescore is
        # mandatory — lite int8 results would be exact w.r.t. ŷ, a
        # score function no caller asked for
        if not packed or grid_order not in ("db", "dbuf"):
            raise ValueError(
                "_knn_fused_core: db_dtype='int8' needs the packed "
                "database-major envelope (grid_order 'db'/'dbuf', "
                "g·(T/128) ≤ 2^pbits)")
        if not rescore or yp is None:
            raise ValueError(
                "_knn_fused_core: db_dtype='int8' requires the exact "
                "f32 rescore (store_yp=True) — returned ids are "
                "certified against the ORIGINAL rows, not ŷ")
        if y_q is None or y_scale_k is None or eq_groups is None:
            raise ValueError(
                "_knn_fused_core: db_dtype='int8' needs y_q, "
                "y_scale_k and eq_groups (prepare with "
                "_prepare_ops_q8)")

    xx = jnp.sum(x * x, axis=1, keepdims=True)                  # [Q,1] f32
    if metric == "ip":
        xx_r = jnp.zeros((Q, 1), jnp.float32)
    else:
        xx_r = xx
    # m_eff: the real-row count every mask uses — static m, or the
    # traced per-shard override (see the m_valid contract above). The
    # ragged rows_valid mode has no prefix count: m_eff covers the whole
    # slab (pads are hidden by the sentinel carrier + the mask gathers
    # below), and the unpacked kernels — which prefix-mask in-kernel —
    # are out of envelope.
    if rows_valid is not None:
        if not packed:
            raise ValueError(
                "_knn_fused_core: rows_valid (ragged mask) needs the "
                "packed kernel envelope (g·(T/128) ≤ 2^pbits) — the "
                "unpacked kernels mask by prefix count in-kernel")
        rows_valid = jnp.asarray(rows_valid, jnp.bool_).reshape(-1)
        m_eff = jnp.int32(M)
        m_real = jnp.full((1,), M, jnp.int32)
    else:
        m_eff = m if m_valid is None else \
            jnp.asarray(m_valid, jnp.int32).reshape(())
        m_real = (jnp.full((1,), m, jnp.int32) if m_valid is None
                  else jnp.reshape(m_eff, (1,)))

    if packed:
        if quant:
            kern = (fused_l2_group_topk_packed_db_q8
                    if grid_order == "db"
                    else fused_l2_group_topk_packed_dbuf_q8)
            kw = {"pbits": pbits,
                  "pair": passes == 1 and (T // _LANES) % 2 == 0}
        elif d > _D_SINGLE_SHOT:
            kern, kw = fused_l2_group_topk_packed_dchunk, {
                "dc": _DC, "pbits": pbits}
        elif grid_order in ("db", "dbuf"):
            # database-major: y streams from HBM once instead of nq
            # times (see GRID_ORDERS / the DATABASE-MAJOR block comment
            # in ops.fused_l2_topk_pallas); same outputs, codes and
            # certificate semantics, so everything downstream of the
            # kernel call is untouched
            kern = (fused_l2_group_topk_packed_db if grid_order == "db"
                    else fused_l2_group_topk_packed_dbuf)
            kw = {"pbits": pbits,
                  "pair": passes == 1 and (T // _LANES) % 2 == 0}
        else:
            # streamed chunk contraction (MXU/VPU co-issue — measured
            # p1 10.9→4.4 ms, p3 15.6→9.8 ms at 2048×1M×128); the pair
            # pre-reduction pays only in p1 (p3 is matmul-floor-bound)
            # and T/128 must be even for it
            kern = fused_l2_group_topk_packed
            kw = {"stream": True, "pbits": pbits,
                  "pair": passes == 1 and (T // _LANES) % 2 == 0}
        # the query half-norm rides INTO the kernel: packed values are
        # then d2/2 (l2) — small, so pack perturbation is relative to
        # the distances compared, not to the norm-dominated half-score
        # (measured at clustered 10M×256: the norm-scaled error failed
        # the certificate for ~80% of queries at pbits=11)
        xxh = 0.5 * xx if metric != "ip" else jnp.zeros_like(xx)
        if quant:
            a1p, a2p, a3p = kern(x, y_q, yyh_k, y_scale_k, m_real,
                                 T=T, Qb=Qb, passes=passes, tpg=g,
                                 xxh=xxh, **kw)
        else:
            a1p, a2p, a3p = kern(x, y_hi, y_lo, yyh_k, m_real, T=T,
                                 Qb=Qb, passes=passes, tpg=g, xxh=xxh,
                                 **kw)
        S_ = a1p.shape[1]
        # TWIN-POOL selection (round-3 redesign): top_k over a1p ONLY —
        # the XLA TopK measured ~2.5× superlinear in pool width inside
        # the composite program (14.8 ms at 7936 wide vs 3.8 at 3968) —
        # then pull each winner's a2p TWIN by position (the only a2
        # entries that can matter: a2 ≥ a1 elementwise, so an a2 whose
        # a1-twin lost to the C-th a1 value is itself ≥ that value),
        # and prune the 2C candidates back to C by kernel order.
        # Certificate terms per non-candidate class:
        #   a1 beyond top-C           ≥ C-th a1 value
        #   a2 twin of unselected a1  ≥ its a1 ≥ C-th a1 value
        #   pruned candidate          ≥ C-th pruned kernel value
        #   outside any bucket top-2  ≥ a3_min
        # Each term is ≥ the old whole-pool C-th value, so this bound
        # is ≥ the round-2 bound — fewer or equal fixups.
        # Ca MUST oversample beyond k: bound_a1 is the Ca-th smallest
        # bucket-min, and when the true top-k spread over k distinct
        # buckets the k-th bucket-min IS θ — with Ca = k the margin
        # check bound ≥ θ + err then fails for EVERY query (measured:
        # n_fail 2048/2048 at 10M×256, a 14 s full-fallback). The +pad
        # buys bound_a1 ≈ the (k+pad)-th neighbor value instead.
        Ca = min(k + _POOL_PAD, S_)
        # the envelope admits k up to 2·S_ (both twins of every bucket):
        # the pruned candidate count must cover k even when S_ < k+pad
        C = min(k + _POOL_PAD, 2 * Ca)
        # packed f32 order == value order (negation flips only the sign
        # bit, so codes survive the top_k round-trip)
        a1_sel, pos1 = _pool_smallest(a1p, Ca, pool_algo)
        a2_sel = jnp.take_along_axis(a2p, pos1, axis=1)
        cands = jnp.concatenate([a1_sel, a2_sel], axis=1)       # [Q, 2Ca]
        cpos = jnp.concatenate([pos1, pos1], axis=1)
        neg_top, sel = jax.lax.top_k(-cands, C)
        cand_p = -neg_top
        pos = jnp.take_along_axis(cpos, sel, axis=1)
        cand_pid = decode_packed_pool(cand_p, pos, S_, T, g, pbits)
        cand_v_hat = 2.0 * cand_p                       # = d2 (xx folded)
        bound_a1 = 2.0 * a1_sel[:, Ca - 1]
        a3_half_min = jnp.min(a3p, axis=1)
        a3_min = jnp.minimum(2.0 * a3_half_min, bound_a1)
        # packing error margin, PER QUERY from the actual magnitudes in
        # play: each compared value v = 2·half + xx carries
        # |Δv| ≤ 2·|half|·2^(pbits−23); bound and θ each contribute one
        # perturbed half, and the largest |half| among the used values
        # (candidate heads/tails, the a3 minimum, the Ca-th a1) bounds
        # both. ×2 for the two sides, ×2 safety. The round-2 formula
        # used the GLOBAL worst case (xx + 2·yymax)/2 — at clustered
        # 10M×256 scale that margin (~2× the true bound−θ gap) failed
        # the certificate for every query (measured).
        # SENTINEL terms are excluded from the magnitude: a pool with
        # fewer than C real rows (the mutable delta tail, tiny ragged
        # slabs) puts the 2^125 never-wins pad in the C-th/Ca-th slot,
        # and folding ITS magnitude into e_pack blew the margin to
        # ~2^105 — every query failed into the fixup. Sound because a
        # sentinel-valued term only ever appears inside bound's min()
        # — either it is discarded by a finite term whose perturbation
        # the finite magnitudes below already cover, or bound itself is
        # sentinel-scale and exceeds θ + err by ~2^100 even after its
        # own (≤ |v|·2^−10) perturbation.
        def _real_half(v):
            return jnp.where(v < _PACK_PAD * 0.25, jnp.abs(v), 0.0)

        # the θ-slot magnitude stays UNMASKED: lite-mode θ is a cleaned
        # packed value whose own perturbation must be covered, and the
        # ascending order no longer bounds it by the (masked) C-th
        # term. When the k-th slot IS a sentinel (< k real rows) the
        # blown margin just forces the fixup θ = inf forces anyway.
        half_mag = jnp.maximum(
            jnp.maximum(_real_half(cand_p[:, 0]),
                        _real_half(cand_p[:, C - 1])),
            jnp.maximum(
                jnp.maximum(_real_half(a3_half_min),
                            _real_half(a1_sel[:, Ca - 1])),
                jnp.abs(cand_p[:, k - 1])))
        e_pack = 8.0 * half_mag * 2.0 ** (pbits - 23)
    else:
        if d > _D_SINGLE_SHOT:
            a1, id1, a2, id2, a3 = fused_l2_group_topk_dchunk(
                x, y_hi, y_lo, yyh_k, m_real, T=T, Qb=Qb, passes=passes,
                tpg=g, dc=_DC)
        else:
            a1, id1, a2, id2, a3 = fused_l2_group_topk(
                x, y_hi, y_lo, yyh_k, m_real, T=T, Qb=Qb, passes=passes,
                tpg=g)
        # recover kernel-score space (d2 for l2, −x·y for ip); +inf
        # stays +inf, ids untouched
        a1 = 2.0 * a1 + xx_r
        a2 = 2.0 * a2 + xx_r
        pool_v = jnp.concatenate([a1, a2], axis=1)              # [Q, 2S']
        pool_id = jnp.concatenate([id1, id2], axis=1)
        C = min(k + _POOL_PAD, pool_v.shape[1])
        cand_v_hat, pos = _pool_smallest(pool_v, C, pool_algo)  # ascending
        cand_pid = jnp.take_along_axis(pool_id, pos, axis=1)    # point ids
        cand_pid = jnp.where(jnp.isfinite(cand_v_hat), cand_pid, -1)
        a3_min = 2.0 * jnp.min(a3, axis=1) + xx_r[:, 0]
        e_pack = jnp.zeros((Q,), jnp.float32)

    if rescore:
        if yp is None:
            raise ValueError("_knn_fused_core: rescore=True needs the "
                             "stored f32 index (prepare with "
                             "store_yp=True)")
        # exact f32 rescore of the C candidates (gather + HIGHEST
        # contraction; safe_pid is clamped to real rows, so gathering
        # from the row-padded yp returns identical data to the original
        # matrix)
        safe_pid = jnp.minimum(jnp.maximum(cand_pid, 0),
                               jnp.maximum(m_eff, 1) - 1)
        yc = jnp.take(yp, safe_pid, axis=0)                     # [Q, C, d]
        if metric == "ip":
            d2c = -jnp.einsum("qd,qcd->qc", x, yc,
                              precision=jax.lax.Precision.HIGHEST)
        else:
            d2c = (xx + jnp.sum(yc * yc, axis=2)
                   - 2.0 * jnp.einsum("qd,qcd->qc", x, yc,
                                      precision=jax.lax.Precision.HIGHEST))
            d2c = jnp.maximum(d2c, 0.0)
        d2c = jnp.where(cand_pid >= 0, d2c, jnp.inf)
        neg_k, ord_k = jax.lax.top_k(-d2c, k)
        vals = -neg_k                                           # exact, asc
        ids = jnp.take_along_axis(cand_pid, ord_k, axis=1)
    else:
        # LITE mode: the returned top-k is the exact top-k of the
        # KERNEL score function (bf16 for passes=1, bf16x3 for 3) —
        # candidates are already sorted ascending by kernel order, so
        # the head IS the result; values only need the embedded code
        # bits cleared (≤ |v|·2^(pbits−23) perturbation, 2⁻¹⁵..2⁻¹⁰
        # over the allowed pbits range — already inside the
        # e_pack certificate margin). No yp, no rescore gather: the
        # mode that serves f32-index-larger-than-HBM scales (10M×256).
        if packed:
            clean = jax.lax.bitcast_convert_type(
                jax.lax.bitcast_convert_type(cand_p, jnp.int32)
                & ~((1 << pbits) - 1), jnp.float32)
            cand_v_clean = 2.0 * clean                  # = d2 (xx folded)
        else:
            cand_v_clean = cand_v_hat
        vals = cand_v_clean[:, :k]
        if metric != "ip":
            vals = jnp.maximum(vals, 0.0)
        vals = jnp.where(cand_pid[:, :k] >= 0, vals, jnp.inf)
        ids = cand_pid[:, :k]

    # ---- certificate ----
    theta = vals[:, k - 1]
    # every point outside its group's kept top-2 is ≥ that group's a3;
    # every pool entry not among the C candidates is ≥ the C-th pool value
    bound = jnp.minimum(a3_min, cand_v_hat[:, C - 1])
    if quant or passes == 3 or certify == "f32":
        # ONE margin construction for both f32-certified modes; only
        # the coefficient differs. certify="f32" at passes=1 is
        # ADAPTIVE PRECISION: θ is the exact-f32 k-th candidate value
        # (rescore mode) and every non-candidate's bf16 kernel score is
        # ≥ bound, hence its f32 score ≥ bound − E1; bound − E1 ≥ θ
        # proves the f32 top-k lives inside the exactly-rescored
        # candidate set, and margin failures pay the exact-f32 fixup.
        coeff = (_err_bound_coeff(d) if passes == 3
                 else _err_bound_coeff_p1(d))
        ymax = jnp.sqrt(jnp.max(yy_raw))   # finite norms (padded rows: 0)
        err = coeff * jnp.sqrt(xx[:, 0]) * ymax + e_pack
    else:
        err = e_pack
    if quant:
        # QUANTIZATION widening: kernel scores are exact-w.r.t.-ŷ (the
        # dequantized rows — their norms ride the carrier), so a
        # non-candidate j has d2(x, ŷ_j) ≥ bound − err. If its TRUE
        # d2(x, y_j) were < θ then ‖x − y_j‖ < √θ and
        # d2(x, ŷ_j) ≤ d2(x, y_j) + 2‖x−y_j‖‖e_j‖ + ‖e_j‖²
        #            < (√θ + Eq)², Eq = max_g eq_groups[g] —
        # so bound − err ≥ (√θ + Eq)² = θ + 2√θ·Eq + Eq² excludes every
        # violator. For IP the score is linear in y: |Δ| = |x·(ŷ−y)| ≤
        # ‖x‖·2·Eq (Eq bounds the HALVED stream operand ŷ/2).
        # The bf16 coeff·√xx·ymax term above covers the kernel-vs-ŷ
        # arithmetic error (y_q is exact in bf16, so the p1/p3 bounds —
        # which budget both factors rounding — safely envelope the
        # x-only rounding plus the post-matmul scale multiply).
        eq_max = jnp.max(eq_groups)
        if metric == "ip":
            err = err + 2.0 * jnp.sqrt(xx[:, 0]) * eq_max
        else:
            sq_theta = jnp.sqrt(jnp.maximum(theta, 0.0))
            err = err + 2.0 * sq_theta * eq_max + eq_max * eq_max
    certified = bound >= theta + err                            # [Q] bool
    failed = ~certified
    n_fail = jnp.sum(failed.astype(jnp.int32))
    # per-query certificate margin (pre-fixup): how much headroom the
    # certificate had — negative exactly where the fixup runs. Rides
    # out on the with_stats/_diag paths for the explain plane
    # (observability.explain); computed either way, so with_stats adds
    # one [Q] f32 output and zero extra compute.
    margin = bound - (theta + err)                              # [Q] f32

    # ---- fixup: exact sweep for failed queries ----
    # shape-aware tier ladder: only tiers whose [F, M] f32 tile fits
    # the budget are built (static — M is known at trace time). An
    # EMPTY ladder (M > ~65M rows) routes every failure to the
    # streamed full fallback — never a budget-busting tile
    fix_tiers = tuple(t for t in _FIXUP_TIERS
                      if t * M * 4 <= _FIXUP_TILE_BUDGET)

    def exact_rows(xq):
        """Exact top-k for a [F, d] query block.

        rescore mode: f32 HIGHEST against the stored yp — exact w.r.t.
        f32 scores. Lite mode (yp is None): the SAME bf16(x3)
        contraction the kernel runs, against y_hi/y_lo — exact w.r.t.
        the kernel score function, which is what lite results are
        certified against.

        Small blocks materialize the whole [F, M] distance tile and take
        ONE top_k: MEASURED (v5e, 2048×1M×128) the old per-tile
        merge loop (489 sequential top_k's on [F, k+T]) cost ~90 ms —
        3× the entire rest of the pipeline — and ran on nearly every
        batch because the certificate fires for a handful of queries at
        production scale. Tile size is bounded by the ladder filter:
        fix_tiers[-1]·M·4 ≤ _FIXUP_TILE_BUDGET (≤ ~4 GB — e.g.
        [128, 1M] = 512 MB single-digit ms; [1024, 1M] = 4 GB, the
        certify="f32" deep-failure regime)."""
        F = xq.shape[0]
        xs = jnp.sum(xq * xq, axis=1)
        nt_dims = (((1,), (1,)), ((), ()))

        def scores(yt_f32, yt_hi, yt_lo, yy_seg):
            if yp is not None:
                s = jax.lax.dot_general(
                    xq, yt_f32, nt_dims,
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)
            else:
                xhi = xq.astype(jnp.bfloat16)
                s = jax.lax.dot_general(
                    xhi, yt_hi, nt_dims,
                    preferred_element_type=jnp.float32)
                if passes == 3:
                    # barrier: XLA:TPU's bf16 pass folds the split
                    # (see split_hi_lo) — lo would collapse to ~0
                    xhi_b = jax.lax.optimization_barrier(xhi)
                    xlo = (xq - xhi_b.astype(jnp.float32)
                           ).astype(jnp.bfloat16)
                    s = s + jax.lax.dot_general(
                        xhi, yt_lo, nt_dims,
                        preferred_element_type=jnp.float32)
                    s = s + jax.lax.dot_general(
                        xlo, yt_hi, nt_dims,
                        preferred_element_type=jnp.float32)
            if metric == "ip":
                # lite operands are the hi/lo split of y/2 (the kernel
                # feeds them to the same scorer) — recover -x·y with
                # the ×2 the packed pipeline applies; the stored-yp
                # path contracts the full-scale y
                return -s if yp is not None else -2.0 * s
            return jnp.maximum(
                xs[:, None] + yy_seg[None, :] - 2.0 * s, 0.0)

        if fix_tiers and F <= fix_tiers[-1]:
            yy_all = (yy_raw[0] if yp is None
                      else jnp.sum(yp * yp, axis=1))
            d2 = scores(yp, y_hi, y_lo, yy_all)                 # [F, M]
            col = jnp.arange(M, dtype=jnp.int32)
            col_ok = (rows_valid[None, :] if rows_valid is not None
                      else col[None, :] < m_eff)
            d2 = jnp.where(col_ok, d2, jnp.inf)
            # (A/B MEASURED: routing this top_k through the slotted
            # select — 2.5 vs 3.0 ms standalone at [16, 1M] — showed
            # no e2e win in-composite; the plain top_k stays)
            nt, ni = jax.lax.top_k(-d2, k)
            return -nt, ni

        # full-batch fallback: streamed per-tile merge (the [Q, M] tile
        # would not fit HBM); rare — needs > fix_tiers[-1] failures
        n_tiles = M // T

        def body(j, carry):
            bv, bi = carry
            if yp is not None:
                yt = jax.lax.dynamic_slice_in_dim(yp, j * T, T, axis=0)
                yth = ytl = None
                yy_seg = jnp.sum(yt * yt, axis=1)
            else:
                yt = None
                yth = jax.lax.dynamic_slice_in_dim(y_hi, j * T, T, axis=0)
                ytl = (jax.lax.dynamic_slice_in_dim(y_lo, j * T, T, axis=0)
                       if passes == 3 else None)
                yy_seg = jax.lax.dynamic_slice_in_dim(yy_raw[0], j * T, T)
            d2 = scores(yt, yth, ytl, yy_seg)
            col = j * T + jnp.arange(T, dtype=jnp.int32)
            col_ok = (jax.lax.dynamic_slice_in_dim(
                rows_valid, j * T, T)[None, :]
                if rows_valid is not None else col[None, :] < m_eff)
            d2 = jnp.where(col_ok, d2, jnp.inf)
            av = jnp.concatenate([bv, d2], axis=1)
            ai = jnp.concatenate(
                [bi, jnp.broadcast_to(col[None, :], d2.shape)], axis=1)
            nt, np_ = jax.lax.top_k(-av, k)
            return -nt, jnp.take_along_axis(ai, np_, axis=1)

        bv = jnp.full((F, k), jnp.inf, jnp.float32)
        bi = jnp.full((F, k), -1, jnp.int32)
        return jax.lax.fori_loop(0, n_tiles, body, (bv, bi))

    def no_fixup(operand):
        vals, ids = operand
        return vals, ids

    def make_fixup(F):
        def fixup(operand):
            vals, ids = operand
            _, fidx = jax.lax.top_k(failed.astype(jnp.int32), F)
            fv, fi = exact_rows(x[fidx])
            # padded rows of fidx are healthy queries — recomputing them
            # exactly and writing back is harmless (same answer)
            return vals.at[fidx].set(fv), ids.at[fidx].set(fi)
        return fixup

    def full_fallback(operand):
        return exact_rows(x)

    if _diag:
        # measurement-only: the certified pipeline WITHOUT the fixup
        # cascade, plus the failure count and the certificate internals
        # (bound, θ, err) — benchmarks/ use this to attribute time and
        # to see WHY queries fail instead of guessing; NOT a valid
        # exactness contract
        return vals, ids, n_fail, bound, theta, err

    # tiered cascade: n_fail==0 → no-op; else the smallest tier that
    # covers n_fail; else the full fallback
    branch = full_fallback
    for t in [t for t in reversed(fix_tiers) if t < Q]:
        branch = (lambda op, t=t, nxt=branch: jax.lax.cond(
            n_fail <= t, make_fixup(t), nxt, op))
    vals, ids = jax.lax.cond(n_fail == 0, no_fixup, branch, (vals, ids))
    if with_stats:
        # ``with_stats``: the certificate-failure count rides out as a
        # third (scalar) output so the NON-jitted wrappers can report
        # fixup-rate telemetry host-side (observability.quality), plus
        # the PRE-FIXUP per-query margin as a fourth so the explain
        # plane can histogram it — one int32 + one [Q] f32 per program,
        # no extra compute, fixup semantics untouched
        return vals, ids, n_fail, margin
    return vals, ids


def rescore_pool_width(k: int, S_pool: int, packed: bool) -> int:
    """The candidate-pool width C the core exact-rescores — the HOST
    mirror of the static pool geometry inside ``_knn_fused_core``
    (packed: twin-pool Ca oversample then prune to C; unpacked: one
    pick over the 2·S' concat pool). Quality telemetry reports it so
    q8 rescore pool widths are observable without re-deriving kernel
    geometry (observability.quality)."""
    if packed:
        ca = min(k + _POOL_PAD, S_pool)
        return min(k + _POOL_PAD, 2 * ca)
    return min(k + _POOL_PAD, 2 * S_pool)


def fixup_tiers_for(m_padded: int) -> Tuple[int, ...]:
    """The eligible static fixup tiers at a PREPARED (padded) row count
    — the host mirror of the ladder filter in ``_knn_fused_core``
    (a tier is eligible only while its [F, M] f32 tile fits the
    budget). Quality telemetry maps a drained failure count back to
    the tier that absorbed it (quality.fixup_tier_for)."""
    return tuple(t for t in _FIXUP_TIERS
                 if t * m_padded * 4 <= _FIXUP_TILE_BUDGET)


_TUNED = ...   # lazy sentinel: {passes: (T, Qb, g)} once loaded


def fit_config(T: int, Qb: int, d: int, passes: int,
               g: Optional[int] = None, grid_order: str = "query",
               db_dtype: str = "bf16"):
    """Scoped-VMEM guard: shrink (T, Qb) until the kernel footprint fits
    Mosaic's stack budget — a config over it is a guaranteed compile
    failure (observed: the tuned-at-passes=1 winner OOMs at passes=3).
    Shrinks Qb first (pure throughput knob), then T (weakens the
    certificate's slot count, so last). Shared by knn_fused and the
    measurement scripts so they can never profile a config production
    would silently shrink. (For grid_order="dbuf" the Qb loop is a
    no-op — its footprint prices the whole query batch — so the T loop
    carries the shrink.)"""
    budget = vmem_budget()
    while (footprint_for(T, Qb, d, passes, g, grid_order,
                         db_dtype) > budget and Qb > 8):
        Qb = max(8, (Qb // 2) // 8 * 8)
    while (footprint_for(T, Qb, d, passes, g, grid_order,
                         db_dtype) > budget and T > 2 * _LANES):
        T = max(2 * _LANES, (T // 2) // _LANES * _LANES)
    return T, Qb


def footprint_for(T: int, Qb: int, d: int, passes: int,
                  g: Optional[int] = None,
                  grid_order: str = "query",
                  db_dtype: str = "bf16") -> int:
    """Scoped-VMEM footprint of the fused kernel at a RAW (unpadded)
    feature width — applies the same d-padding / d-chunk routing AND
    packed-vs-unpacked kernel choice ``knn_fused`` itself uses, so
    callers (the tune sweep's skip predicate, the in-call shrink guard)
    can't diverge from it. ``g`` (tiles per group) decides the packed
    envelope; None assumes UNPACKED — the larger footprint, so an
    uninformed caller fails safe (over-shrinks) rather than shipping a
    Mosaic scoped-VMEM reject.

    ``grid_order`` routes to the database-major models; "dbuf" prices
    the worst-case padded query batch (_Q_CHUNK) instead of Qb, because
    its one-cell-per-group design holds the whole batch's fold state
    (the wrapper chunks queries at _Q_CHUNK, so that IS the bound)."""
    d_eff = d + (-d) % (_DC if d > _D_SINGLE_SHOT else _LANES)
    # the auto pack-width clamp makes any g ≤ 2^_PBITS_MAX codes
    # packed; the single-shot packed path is the STREAM kernel (no
    # [Qb, T] buffer)
    packed = g is not None and g * (T // _LANES) <= (1 << _PBITS_MAX)
    dchunk = d_eff > _D_SINGLE_SHOT
    if packed and not dchunk and grid_order in ("db", "dbuf"):
        q8 = db_dtype == "int8"
        kern = ("stream_db_q8" if q8 else "stream_db") \
            if grid_order == "db" \
            else ("stream_dbuf_q8" if q8 else "stream_dbuf")
        if grid_order == "dbuf":
            Qb = _Q_CHUNK
        return vmem_footprint(T, Qb, d_eff, passes, kernel=kern,
                              g=g or 16)
    kern = ("packed" if dchunk else "stream") if packed else "group"
    return vmem_footprint(T, Qb, d_eff, passes, dchunk=dchunk,
                          kernel=kern)


def resolve_grid_order(grid_order: str, d: int, packed: bool) -> str:
    """EFFECTIVE grid order for a call — decided (and logged) in the
    non-jitted wrapper like resolve_pool_algo, so a downgraded request
    is visible per call instead of silently mislabeling what ran. The
    database-major kernels are packed-only and single-shot-only
    (d ≤ _D_SINGLE_SHOT); anything outside that envelope runs the
    query-major pipeline."""
    if grid_order not in GRID_ORDERS:
        raise ValueError(f"grid_order must be one of {GRID_ORDERS}, "
                         f"got {grid_order!r}")
    if grid_order == "query":
        return grid_order
    reason = None
    if d > _D_SINGLE_SHOT:
        reason = f"d={d} > {_D_SINGLE_SHOT} takes the d-chunked kernel"
    elif not packed:
        reason = "config is outside the packed-code envelope"
    if reason is None:
        return grid_order
    from raft_tpu.core.logger import log_warn

    log_warn("grid_order=%r outside the database-major envelope (%s) — "
             "using 'query' for this call", grid_order, reason)
    return "query"


def resolve_db_dtype(db_dtype: str, d: int, packed: bool,
                     grid_order: str, store_yp: bool = True) -> str:
    """EFFECTIVE database storage dtype for an index build — decided
    (and logged) in the non-jitted prepare path like
    :func:`resolve_grid_order`, so a downgraded request is visible per
    build instead of silently mislabeling what streams. int8 needs the
    packed database-major envelope (the quantized kernels exist for
    "db"/"dbuf" only) and the stored f32 rows for the mandatory exact
    rescore; requests outside it downgrade to "bf16" with a logged
    reason. A lite int8 index is an ERROR, not a downgrade — the
    caller asked for two contradictory contracts."""
    if db_dtype not in DB_DTYPES:
        raise ValueError(f"db_dtype must be one of {DB_DTYPES}, "
                         f"got {db_dtype!r}")
    if db_dtype == "bf16":
        return db_dtype
    if not store_yp:
        raise ValueError(
            "db_dtype='int8' requires store_yp=True: quantized results "
            "are certified by exact-rescoring candidates from the "
            "original f32 rows — a lite index has nothing to rescore "
            "from")
    reason = None
    if d > _D_SINGLE_SHOT:
        reason = f"d={d} > {_D_SINGLE_SHOT} takes the d-chunked kernel"
    elif not packed:
        reason = "config is outside the packed-code envelope"
    elif grid_order not in ("db", "dbuf"):
        reason = f"grid_order={grid_order!r} is not database-major"
    if reason is None:
        return db_dtype
    from raft_tpu.core.logger import log_warn

    log_warn("db_dtype='int8' outside the quantized-streaming envelope "
             "(%s) — storing bf16 for this index", reason)
    return "bf16"


def _valid_cfg(T, Qb, g, grid_order: str = "query") -> bool:
    # semantic validation, not just parseability: bad values would crash
    # every knn() call downstream; g = tiles-per-group ≥ 1
    return (T > 0 and T % _LANES == 0 and Qb > 0 and Qb % 8 == 0
            and 0 < g <= 4096 and grid_order in GRID_ORDERS)


class FusedConfig(Tuple[int, int, int, str]):
    """(T, Qb, g, grid_order) — the fused pipeline's tiling config."""

    __slots__ = ()

    def __new__(cls, T: int, Qb: int, g: int, grid_order: str = "query"):
        return tuple.__new__(cls, (T, Qb, g, grid_order))

    T = property(lambda s: s[0])
    Qb = property(lambda s: s[1])
    g = property(lambda s: s[2])
    grid_order = property(lambda s: s[3])


_BUILTIN_CONFIG = FusedConfig(2048, 256, 16, "query")


def _row_db_dtype(row) -> Optional[str]:
    """The row's database storage dtype: absent (schema ≤ 3 rows were
    all bf16-streamed) → "bf16"; an unknown value → None (the row is
    rejected with a logged reason — serving an int4 row nobody measured
    would route production to an unswept point)."""
    dt = row.get("db_dtype", "bf16")
    if dt not in DB_DTYPES:
        from raft_tpu.tune.fused import table_degraded

        table_degraded("fused", "row_rejected",
                       f"row db_dtype={dt!r} is not one of {DB_DTYPES}")
        return None
    return dt


def _row_config(row, d: Optional[int], passes: int) -> Optional[FusedConfig]:
    """A validated FusedConfig from one table row, or None. Beyond
    parseability, the config must (a) pass _valid_cfg and (b) survive
    fit_config UNshrunk at the table's feature width — a config the
    scoped-VMEM guard would shrink was never actually measured as
    written, so serving it would route production to an unswept point
    (the round-2 failure mode, now rejected at load instead of
    shipped)."""
    try:
        cfg = FusedConfig(int(row["T"]), int(row["Qb"]), int(row["g"]),
                          str(row.get("grid_order", "query")))
    except (KeyError, TypeError, ValueError):
        return None
    if not _valid_cfg(*cfg):
        return None
    db_dtype = _row_db_dtype(row)
    if db_dtype is None:
        return None
    if d is not None and fit_config(cfg.T, cfg.Qb, d, passes, cfg.g,
                                    cfg.grid_order,
                                    db_dtype) != (cfg.T, cfg.Qb):
        from raft_tpu.tune.fused import table_degraded

        table_degraded(
            "fused", "row_rejected",
            f"row (T={cfg.T}, Qb={cfg.Qb}, g={cfg.g}, "
            f"{cfg.grid_order}, passes={passes}, {db_dtype}) fails "
            f"the scoped-VMEM fit at d={d}")
        return None
    return cfg


def _load_tuned() -> dict:
    """Parse + validate the tune table → {passes: FusedConfig}. Any
    corrupt, stale or future-schema table degrades to {} (built-in
    defaults) with a logged reason — it must never break knn. Every
    degraded load is counted under ``tune.table_degraded{table=fused,
    reason=...}`` (WARN once per process — see
    :func:`raft_tpu.tune.fused.table_degraded`); the read carries the
    ``tune_table_read`` fault site so a torn/corrupt table is
    injectable."""
    import json
    import os

    from raft_tpu.core.logger import log_info
    from raft_tpu.native import _REPO_ROOT
    from raft_tpu.tune.fused import (TUNE_SCHEMA_VERSION, table_degraded,
                                     validate_tune_table)

    path_env = os.environ.get("RAFT_TPU_TUNE_FUSED")
    path = path_env or os.path.join(_REPO_ROOT, "TUNE_FUSED.json")
    if fault_point("tune_table_read") == "corrupt":
        table_degraded("fused", "unreadable",
                       f"{path}: injected corrupt table read")
        return {}
    tuned: dict = {}
    try:
        with open(path) as f:
            tbl = json.load(f)
    except FileNotFoundError:
        if path_env:   # an explicitly-named table that is absent IS
            #            a degradation; the default path missing is
            #            just the untuned state
            table_degraded("fused", "missing", path)
        return {}
    except Exception as e:
        table_degraded("fused", "unreadable",
                       f"{path}: {type(e).__name__}: {e}")
        return {}
    try:
        errors = validate_tune_table(tbl)
        if errors:
            table_degraded("fused", "invalid",
                           f"{path}: " + "; ".join(errors))
            return {}
        if int(tbl.get("schema", 1)) > TUNE_SCHEMA_VERSION:
            table_degraded(
                "fused", "future_schema",
                f"{path}: schema {tbl.get('schema')} (this build "
                f"understands ≤ {TUNE_SCHEMA_VERSION})")
            return {}
        shape = tbl.get("shape")
        d = (int(shape[2]) if isinstance(shape, (list, tuple))
             and len(shape) >= 3 else None)
        # per-(passes, db_dtype) winners from the measured rows; the
        # legacy single "best" entry seeds any mode its passes matches
        # (or both, for tables that never recorded passes). Rows
        # without a db_dtype (every schema ≤ 3 table, incl. the
        # committed measured v5e one) are bf16 — that loading stays
        # byte-identical to the schema-3 behavior.
        for row in sorted((r for r in tbl.get("rows", [])
                           if "seconds" in r),
                          key=lambda r: r["seconds"], reverse=True):
            p = int(row.get("passes", 0)) or None
            dt = _row_db_dtype(row)
            cfg = _row_config(row, d, p or 3)
            if cfg is not None and dt is not None:
                tuned[(p, dt)] = cfg
        # explicit winners: schema ≥ 4 keys "passes:db_dtype", schema 3
        # keys bare "passes" (bf16); both take precedence over the
        # legacy single "best"
        best_by = dict(tbl.get("best_by_passes") or {})
        best_by.update(tbl.get("best_by_passes_dtype") or {})
        for key_str, row in best_by.items():
            try:
                p_str, _, dt_str = str(key_str).partition(":")
                p = int(p_str)
            except (TypeError, ValueError):
                continue
            dt = dt_str or _row_db_dtype(row)
            if dt not in DB_DTYPES:
                continue
            cfg = _row_config(row, d, p)
            if cfg is not None:
                tuned.setdefault((p, dt), cfg)
        best = tbl.get("best")
        if best:
            dt = _row_db_dtype(best)
            for p in (1, 3):
                if dt is not None and int(best.get("passes", p)) == p:
                    cfg = _row_config(best, d, p)
                    if cfg is not None:
                        tuned.setdefault((p, dt), cfg)
        prov = tbl.get("provenance", {})
        log_info("fused_defaults: loaded %s (schema %s, chip=%s, "
                 "commit=%s, measured=%s, written=%s)", path,
                 tbl.get("schema", "legacy"),
                 prov.get("chip", "unknown"),
                 prov.get("git_commit", "unknown"),
                 prov.get("measured", "unknown"),
                 prov.get("timestamp", "unknown"))
    except Exception:
        return {}  # malformed table must never break knn
    return tuned


def fused_config(passes: int = 3, db_dtype: str = "bf16") -> FusedConfig:
    """(T, Qb, g, grid_order) for the fused pipeline: the measured-best
    point from ``TUNE_FUSED.json`` (produced by the
    :mod:`raft_tpu.tune` autotuner — the analog of the reference's
    fitted select_k heuristic) when one is committed, else the
    hand-chosen defaults. The table is schema-validated and its rows
    re-checked against the scoped-VMEM fit at load; a corrupt/stale/
    future table degrades to the built-ins with a logged reason.

    Best rows are keyed by ``passes``: the score-tile VMEM footprint
    differs ~2× between the modes (see ops.fused_l2_topk_pallas.
    vmem_footprint), so the passes=1 winner can be a passes=3 compile
    failure — round 2's driver bench hit exactly that. ``passes`` itself
    is never taken from the table — it is an exactness contract, not a
    tuning knob."""
    global _TUNED
    if _TUNED is ...:
        _TUNED = _load_tuned()
    hit = (_TUNED.get((passes, db_dtype))
           or _TUNED.get((None, db_dtype)))
    if hit is not None:
        return hit
    if db_dtype != "bf16":
        # no tuned int8 row yet: start from the bf16 winner's geometry
        # (the stream-once shape is the same; only the y byte width
        # changed), forcing a database-major order — "query" has no
        # quantized kernel to run
        base = fused_config(passes, "bf16")
        if base.grid_order == "query":
            return FusedConfig(base.T, base.Qb, base.g, "db")
        return base
    return _BUILTIN_CONFIG


def fused_defaults(passes: int = 3) -> Tuple[int, int, int]:
    """(T, Qb, g) — :func:`fused_config` without the grid order (the
    historical surface; callers that route kernels want fused_config)."""
    return tuple(fused_config(passes)[:3])


def fused_eligible(n_rows: int, d: int) -> bool:
    """THE fused-pipeline eligibility gate (backend + shape envelope),
    shared by knn()'s auto-routing, models.NearestNeighbors.fit's
    prepare decision, and bench.py — one predicate, no drifting
    copies."""
    return (jax.default_backend() == "tpu"
            and n_rows >= 4096 and d <= 4096)


class KnnIndex:
    """Prepared fused-KNN index: the index-side operands (row/feature
    padding, bf16 hi/lo split, norms + sentinel carrier — ~3 ms at
    1M×128 on v5e) computed ONCE at build time, the build/query split
    of the reference ecosystem's index objects. Build with
    :func:`prepare_knn_index`; query via ``knn_fused(x, index)`` or
    ``distance.knn(res, index, queries, ...)``. The tiling config and
    metric are frozen at build time."""

    def __init__(self, yp, y_hi, y_lo, yyh_k, yy_raw, n_rows: int,
                 T: int, Qb: int, g: int, passes: int, metric: str,
                 d_orig: int, pbits: int = _PACK_BITS,
                 grid_order: str = "query", db_dtype: str = "bf16",
                 y_q=None, y_scale_k=None, eq_groups=None,
                 rows_valid=None, ids=None):
        # yp is the ROW-PADDED index; the original matrix is yp[:n_rows]
        # (NOT stored separately — at 1M×128 that would pin a redundant
        # ~512 MB f32 copy in HBM for the index lifetime)
        self.yp = yp
        self.y_hi, self.y_lo = y_hi, y_lo
        self.yyh_k, self.yy_raw = yyh_k, yy_raw
        self.n_rows = n_rows
        self.T, self.Qb, self.g = T, Qb, g
        self.passes, self.metric = passes, metric
        self.d_orig = d_orig
        self.pbits = pbits
        # frozen at build: database-major indexes are row-padded to
        # whole [g·T] groups, so the grid order cannot change per query
        self.grid_order = grid_order
        # quantized-streaming state (db_dtype="int8"): the int8 slab
        # the kernel streams, the group-scale tile, and the per-group
        # quantization bound Eq the certificate is widened by; y_hi /
        # y_lo are None (nothing bf16 is streamed — the HBM win)
        self.db_dtype = db_dtype
        self.y_q = y_q
        self.y_scale_k = y_scale_k
        self.eq_groups = eq_groups
        # RAGGED layout state (built from an IndexLayout / rows_valid):
        # the live-row mask over the PREPARED slab (pads may be
        # interspersed anywhere — the PR-8 never-wins sentinel path)
        # and the slab-position → global-id map queries decode through
        self.rows_valid = rows_valid
        self.ids = ids

    @property
    def stream_width(self) -> int:
        """Feature width of the operand the kernel streams (row-padded
        d) — the shape queries must be padded to."""
        src = self.y_q if self.db_dtype == "int8" else self.y_hi
        return src.shape[1]


@instrument("distance.prepare_knn_index")
def prepare_knn_index(y, passes: int = 3, metric: str = "l2",
                      T: Optional[int] = None, Qb: Optional[int] = None,
                      g: Optional[int] = None,
                      store_yp: bool = True,
                      grid_order: Optional[str] = None,
                      db_dtype: str = "bf16",
                      rows_valid=None, ids=None) -> KnnIndex:
    """Build a :class:`KnnIndex` for repeated queries against ``y``.

    ``store_yp=False`` builds a LITE index: the f32 row-padded matrix
    (and, for passes=1, the unused bf16 lo split) is dropped, ~3×
    smaller HBM residency — the only index kind that fits f32-larger-
    than-HBM scales (10M×256 ≈ 10 GB f32 vs ~5.5 GB lite). Queries
    against a lite index run ``rescore=False``: results are the exact
    top-k of the KERNEL score function (bf16 / bf16x3), values within
    2^(pbits−23) relative of those scores (2⁻¹⁵ at the minimum pack
    width, up to 2⁻¹⁰ at the auto-pack maximum pbits=13).

    ``db_dtype="int8"`` (:data:`DB_DTYPES`) packs the STREAMED slab
    int8 with per-certificate-group symmetric scales: the kernel
    streams M·d·1 bytes instead of bf16's M·d·2(·2), the twin-pool
    certificate is widened by the recorded per-group bound Eq, and
    candidates are exact-rescored in f32 from the original rows —
    returned ids are certified identical to the f32 oracle's.
    Requires ``store_yp=True``; requests outside the packed
    database-major envelope downgrade to bf16 with a logged reason
    (RAFT_TPU_DB_DTYPE env sets the fleet-wide default at call sites
    that pass none — see the serving engine).

    ``y`` may also be an :class:`~raft_tpu.mutable.layout.IndexLayout`
    — the explicit slab struct the mutable subsystem shares with the
    IVF plane — in which case its slab/ids/``rows_valid`` drive a
    RAGGED build: pads (and tombstones) may be interspersed anywhere,
    carried through the PR-8 never-wins sentinel path, and queries
    decode slab positions back through ``ids``. Ragged builds force
    the packed-code envelope (the unpacked kernels mask by prefix
    count only). ``rows_valid``/``ids`` may equally be passed
    directly with a raw matrix."""
    try:
        from raft_tpu.mutable.layout import IndexLayout

        if isinstance(y, IndexLayout):
            rows_valid = y.rows_valid if rows_valid is None else rows_valid
            ids = y.ids if ids is None else ids
            y = y.slab
    except ImportError:
        pass
    if metric not in ("l2", "ip"):
        raise ValueError(f"prepare_knn_index: metric must be 'l2' or "
                         f"'ip', got {metric!r}")
    if db_dtype not in DB_DTYPES:
        raise ValueError(f"prepare_knn_index: db_dtype must be one of "
                         f"{DB_DTYPES}, got {db_dtype!r}")
    y = jnp.asarray(y, jnp.float32)
    m, d = y.shape
    dcfg = fused_config(passes, db_dtype)
    T = dcfg.T if T is None else T
    Qb = dcfg.Qb if Qb is None else Qb
    grid_order = dcfg.grid_order if grid_order is None else grid_order
    if grid_order not in GRID_ORDERS:
        raise ValueError(f"prepare_knn_index: grid_order must be one of "
                         f"{GRID_ORDERS}, got {grid_order!r}")
    if db_dtype == "int8" and grid_order == "query":
        # the quantized kernels are database-major; an int8 request on
        # a query-major (tuned or explicit) geometry takes the
        # stream-once order — that is the configuration the dtype
        # exists to accelerate
        grid_order = "db"
    T, Qb = fit_config(T, Qb, d, passes, g or dcfg.g, grid_order,
                       db_dtype)
    n_tiles_est = max(1, -(-m // T))
    if g is None:
        g = max(dcfg.g, (1 << auto_pack_bits(n_tiles_est, T))
                // (T // _LANES))
    # codes beyond 13 bits would perturb values past the margins the
    # certificate budgets for — such a g simply routes to the UNPACKED
    # kernel (g·n_ch > 2^pbits ⇒ packed=False, +inf sentinels), the
    # same fallback the core and _prepare_ops agree on
    import math

    pbits = min(_PBITS_MAX, max(_PACK_BITS, int(math.ceil(math.log2(
        max(g * (T // _LANES), 2))))))
    if rows_valid is not None and g * (T // _LANES) > (1 << pbits):
        # the ragged mask rides the packed sentinel carrier only — the
        # unpacked kernels prefix-mask in-kernel and cannot honor it
        g = max(1, (1 << pbits) // (T // _LANES))
    # the database-major kernels are packed-only/single-shot-only:
    # resolve the EFFECTIVE order now so the index rows are padded for
    # the kernel that will actually run (a db-padded index serves the
    # query-major kernel fine, but not vice versa)
    packed = g * (T // _LANES) <= (1 << pbits)
    grid_order = resolve_grid_order(grid_order, d, packed)
    db_dtype = resolve_db_dtype(db_dtype, d, packed, grid_order,
                                store_yp)
    dpad = (-d) % (_DC if d > _D_SINGLE_SHOT else _LANES)
    if dpad:
        y = jnp.concatenate([y, jnp.zeros((m, dpad), jnp.float32)], axis=1)
    rv_in = (None if rows_valid is None
             else jnp.asarray(rows_valid, jnp.bool_).reshape(-1))

    def _ragged_state(M: int):
        """(rows_valid, ids) padded to the PREPARED row count M."""
        if rv_in is None:
            return None, None
        rv = rv_in
        if M > rv.shape[0]:
            rv = jnp.concatenate(
                [rv, jnp.zeros((M - rv.shape[0],), jnp.bool_)])
        id_map = None
        if ids is not None:
            id_map = jnp.asarray(ids, jnp.int32).reshape(-1)
            if M > id_map.shape[0]:
                id_map = jnp.concatenate(
                    [id_map,
                     jnp.full((M - id_map.shape[0],), -1, jnp.int32)])
        return rv, id_map

    if db_dtype == "int8":
        fault_point("quantize_index")
        yp, y_q, scale_k, yyh_k, yy_raw, eq = _prepare_ops_q8(
            y, T, g, metric, pbits=pbits, grid_order=grid_order,
            rows_valid=rv_in)
        try:
            from raft_tpu.core.resources import ensure_resources
            from raft_tpu.observability.timeline import emit_marker

            emit_marker("quantize_index", n_rows=m, d=d,
                        n_groups=int(eq.shape[0]),
                        eq_max=float(jnp.max(eq)),
                        db_dtype=db_dtype)
            ensure_resources(None).profiler.capture_fn(
                "distance.quantize_index", _prepare_ops_q8, y, T, g,
                metric, pbits=pbits, grid_order=grid_order)
        except Exception:
            pass
        rv, id_map = _ragged_state(yp.shape[0])
        return KnnIndex(yp, None, None, yyh_k, yy_raw, m, T, Qb, g,
                        passes, metric, d, pbits=pbits,
                        grid_order=grid_order, db_dtype="int8",
                        y_q=y_q, y_scale_k=scale_k, eq_groups=eq,
                        rows_valid=rv, ids=id_map)
    yp, y_hi, y_lo, yyh_k, yy_raw = _prepare_ops(y, T, g, metric,
                                                 pbits=pbits,
                                                 grid_order=grid_order,
                                                 rows_valid=rv_in)
    rv, id_map = _ragged_state(yp.shape[0])
    if not store_yp:
        yp = None
        if passes == 1:
            y_lo = None    # the 1-pass kernel and lite fixup never read it
    return KnnIndex(yp, y_hi, y_lo, yyh_k, yy_raw, m, T, Qb, g, passes,
                    metric, d, pbits=pbits, grid_order=grid_order,
                    rows_valid=rv, ids=id_map)


@instrument("distance.knn_fused")
def knn_fused(x, y, k: int, passes: int = 3,
              T: Optional[int] = None, Qb: Optional[int] = None,
              g: Optional[int] = None, metric: str = "l2",
              rescore: Optional[bool] = None, certify: str = "kernel",
              grid_order: Optional[str] = None,
              db_dtype: Optional[str] = None
              ) -> Tuple[jax.Array, jax.Array]:
    """Certified fused brute-force KNN.

    ``y`` may be a raw [m, d] index matrix (operands prepared inline per
    call) or a :class:`KnnIndex` (prepared once — preferred for repeated
    query batches; its frozen T/Qb/g/passes/metric override the
    corresponding arguments).

    ``rescore`` — None (default) rescores exactly in f32 when the index
    stores yp (regular indexes) and falls back to lite results on a
    ``store_yp=False`` index; True forces rescoring (error on a lite
    index); False forces lite results (exact top-k of the kernel score
    function, values within 2^(pbits−23) of those scores — 2⁻¹⁵..2⁻¹⁰
    over the allowed pbits range).

    ``metric="l2"`` (default): (d2 [Q, k] f32 exact ascending, ids).
    ``metric="ip"``: (scores = x·y [Q, k] f32 exact DESCENDING, ids) —
    the same kernel fed zeros for the norm terms and y/2 operands (see
    _knn_fused_core). ``passes=3`` is certified-exact w.r.t. f32 scores;
    ``passes=1`` trades that for ~3× contraction speed (exact w.r.t.
    bf16 scores). ``T``/``Qb``/``g`` default to :func:`fused_defaults`
    (measured-best when a tuning table is committed); ``g`` is the
    number of consecutive index tiles folded into one certificate
    group inside the kernel (tpg), so the candidate pool holds
    ``2 · ceil(n_tiles/g) · 128`` entries.

    ``certify="f32"`` (ADAPTIVE PRECISION, passes=1 + rescore only):
    p1 kernel cost with the p3 guarantee — the certificate margin is
    widened by the one-pass bf16 error bound (_err_bound_coeff_p1), so
    certified queries are provably exact w.r.t. f32 scores and only
    margin failures pay the exact-f32 fixup. At passes=3 it is a no-op
    (p3 is already f32-certified).

    ``grid_order`` selects the kernel's grid iteration order (see
    :data:`GRID_ORDERS`): "query" re-fetches the database per query
    block; "db"/"dbuf" stream it from HBM ~once (the round-6 roofline
    work). None takes the tuned default; requests outside the
    database-major envelope (unpacked configs, d > 512) downgrade to
    "query" with a logged reason. A :class:`KnnIndex` freezes the
    order at build time.
    """
    fault_point("knn_fused")
    idx: Optional[KnnIndex] = y if isinstance(y, KnnIndex) else None
    if idx is not None:
        T, Qb, g = idx.T, idx.Qb, idx.g
        passes, metric = idx.passes, idx.metric
        m, d = idx.n_rows, idx.d_orig
        grid_order = idx.grid_order
        db_dtype = idx.db_dtype
    elif db_dtype is None:
        db_dtype = "bf16"
    if db_dtype not in DB_DTYPES:
        raise ValueError(f"knn_fused: db_dtype must be one of "
                         f"{DB_DTYPES}, got {db_dtype!r}")
    if metric not in ("l2", "ip"):
        raise ValueError(f"knn_fused: metric must be 'l2' or 'ip', "
                         f"got {metric!r}")
    if certify not in ("kernel", "f32"):
        raise ValueError(f"knn_fused: certify must be 'kernel' or "
                         f"'f32', got {certify!r}")
    if certify == "f32" and rescore is False:
        raise ValueError("knn_fused: certify='f32' needs the exact "
                         "rescore (θ must be an f32 value) — a lite "
                         "index cannot carry the f32 certificate")
    if passes == 3:
        certify = "kernel"   # p3 is already f32-certified — normalize
        #                      so the static arg doesn't fork the jit
        #                      cache with an identical program
    x = jnp.asarray(x, jnp.float32)
    Q, d_x = x.shape
    if idx is None:
        y = jnp.asarray(y, jnp.float32)
        m, d = y.shape
        dcfg = fused_config(passes, db_dtype)
        T = dcfg.T if T is None else T
        Qb = dcfg.Qb if Qb is None else Qb
        g = dcfg.g if g is None else g
        grid_order = dcfg.grid_order if grid_order is None else grid_order
        if grid_order not in GRID_ORDERS:
            raise ValueError(f"knn_fused: grid_order must be one of "
                             f"{GRID_ORDERS}, got {grid_order!r}")
        T, Qb = fit_config(T, Qb, d, passes, g, grid_order, db_dtype)
    if d_x != d:
        raise ValueError(f"knn_fused: query width {d_x} != index {d}")
    if k > m:
        raise ValueError(f"knn_fused: k={k} > index size {m}")
    if Q == 0:
        return (jnp.zeros((0, k), jnp.float32),
                jnp.zeros((0, k), jnp.int32))
    if g < 1:
        raise ValueError(f"knn_fused: g={g} must be ≥ 1 (tiles per group)")
    # the group fold iterates T // 128 lane-chunks and the carriers
    # reshape Qb // 8 — a non-multiple T would silently skip the tail
    # columns (no pool entry AND no certificate coverage)
    if T % _LANES:
        raise ValueError(f"knn_fused: T={T} must be a multiple of {_LANES}")
    if Qb % 8:
        raise ValueError(f"knn_fused: Qb={Qb} must be a multiple of 8")
    n_tiles = (max(m, T) + T - 1) // T
    pool = 2 * (-(-n_tiles // g)) * _LANES
    if k > pool:
        raise NotImplementedError(
            f"knn_fused: k={k} too large for pool size {pool} "
            f"(shrink g or T, or use the streamed path)")
    if Q > _Q_CHUNK:
        # bound the [Q, S] slot arrays / rescore gather: chunk the
        # queries (prepare once so chunks share the index operands)
        if idx is None:
            idx = prepare_knn_index(y, passes=passes, metric=metric,
                                    T=T, Qb=Qb, g=g,
                                    grid_order=grid_order,
                                    db_dtype=db_dtype)
        outs = [knn_fused(x[s:s + _Q_CHUNK], idx, k, rescore=rescore,
                          certify=certify)
                for s in range(0, Q, _Q_CHUNK)]
        return (jnp.concatenate([o[0] for o in outs]),
                jnp.concatenate([o[1] for o in outs]))
    # pad query feature dim to the index's padded width, queries to the
    # block size
    if idx is None:
        idx = prepare_knn_index(y, passes=passes, metric=metric,
                                T=T, Qb=Qb, g=g, grid_order=grid_order,
                                db_dtype=db_dtype)
    # the EFFECTIVE order/dtype (prepare resolves the database-major
    # and quantized envelopes and pads the index rows accordingly)
    grid_order = idx.grid_order
    db_dtype = idx.db_dtype
    dpad = idx.stream_width - d
    if dpad:
        x = jnp.concatenate(
            [x, jnp.zeros((Q, dpad), jnp.float32)], axis=1)
    Qb = min(Qb, ((Q + 7) // 8) * 8)
    qpad = (-Q) % Qb
    if qpad:
        x = jnp.concatenate([x, jnp.zeros((qpad, x.shape[1]), x.dtype)])
    if rescore is None:
        rescore = idx.yp is not None
    if certify == "f32" and not rescore:
        raise ValueError("knn_fused: certify='f32' needs a yp-storing "
                         "index (store_yp=True) for the exact rescore")
    if db_dtype == "int8" and not rescore:
        raise ValueError("knn_fused: an int8-streamed index is always "
                         "exact-rescored (rescore=False would return "
                         "top-k of the QUANTIZED score function)")
    # effective pool-selection algorithm, decided (and logged) HERE in
    # the non-jitted wrapper, per call — the core's static pool geometry
    # reproduced exactly (S' = ceil(n_tiles/g)·128; packed pools are S'
    # wide, unpacked 2·S')
    S_pool = -(-n_tiles // g) * _LANES
    packed_env = g * (T // _LANES) <= (1 << idx.pbits)
    pool_len = S_pool if packed_env else 2 * S_pool
    pool_algo = resolve_pool_algo(pool_select_algo(), pool_len,
                                  min(k + _POOL_PAD, pool_len))
    vals, ids, n_fail, margin = _knn_fused_core(
        x, idx.yp, idx.y_hi, idx.y_lo, idx.yyh_k, idx.yy_raw,
        k=k, T=T, Qb=Qb, g=g, passes=passes, metric=metric, m=m,
        rescore=rescore, pbits=idx.pbits, certify=certify,
        pool_algo=pool_algo, grid_order=grid_order,
        db_dtype=db_dtype, with_stats=True, y_q=idx.y_q,
        y_scale_k=idx.y_scale_k, eq_groups=idx.eq_groups,
        rows_valid=idx.rows_valid)
    # certificate/fixup telemetry: the failure count is a device scalar
    # — queue it UNRESOLVED (quality.drain() converts later, after the
    # program's results have been consumed; no sync on this path).
    # The margin likewise stays a device-array REFERENCE: the explain
    # plane resolves it at finalize (post-response-sync) or drops it
    # unreferenced when no capture is active.
    try:
        from raft_tpu.observability import explain
        from raft_tpu.observability.quality import record_pending

        record_pending(
            "distance.knn_fused", n_fail, n_queries=x.shape[0],
            pool_width=rescore_pool_width(k, S_pool, packed_env),
            fix_tiers=fixup_tiers_for(idx.yyh_k.shape[1]),
            db_dtype=db_dtype, passes=passes, certify=certify)
        if explain.active() is not None:
            # pad rows carry vacuous margins — slice them off (the
            # slice dispatch only happens under an active capture)
            explain.note_margin("distance.knn_fused",
                                margin[:Q] if qpad else margin)
    except Exception:
        pass
    if vals.shape[0] != Q:
        vals, ids = vals[:Q], ids[:Q]
    # else: identity slices would still cost an eager dispatch each
    # (~2 ms RTT on the tunneled device) — skip when Q needed no pad
    if idx.ids is not None:
        # ragged-layout index: slab positions decode to global ids;
        # non-finite rows (fewer live rows than k) carry raw columns
        # out of the fixup's unmasked top_k — sentinel them to −1
        ids = jnp.where((ids >= 0) & jnp.isfinite(vals),
                        jnp.take(idx.ids, jnp.maximum(ids, 0)), -1)
    if metric == "ip":
        return -vals, ids           # internal −x·y ascending → IP desc
    return vals, ids
