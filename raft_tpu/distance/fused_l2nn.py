"""Fused L2 nearest-neighbor and brute-force KNN.

(ref: the pre-cuVS ``raft::distance::fusedL2NN`` — per-query argmin over a
distance matrix that is never materialized — and brute-force knn
(distance + matrix::select_k). BASELINE config 2: "fused L2-NN + select_k
top-64 on 1M×128". Rebuilt TPU-first per SURVEY §7 stage 10.)

Design: stream over column tiles of Y. Each tile does one MXU contraction
X·Y_tileᵀ plus norm corrections, then folds into a running (value, index)
minimum — or a running top-k via merge-and-reselect for knn. Peak memory is
[n, tile] + [n, k], never [n, m]; the tile size comes from the handle's
workspace budget (the reference sizes its smem tiles the same way,
linalg/detail/contractions.cuh). The per-tile loop is a ``lax.fori_loop``
over a static tile count so the whole sweep is one compiled program.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.kvp import KeyValuePair
from raft_tpu.core.resources import ensure_resources
from raft_tpu.observability import instrument
from raft_tpu.resilience import fault_point


def _pad_rows(y, tile):
    """Pad to a tile multiple with zeros; padded columns are masked out via
    the m_real bound in every sweep (zeros keep the matmul NaN-free)."""
    m = y.shape[0]
    pad = (-m) % tile
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad, y.shape[1]), y.dtype)])
    return y, m + pad


@partial(jax.jit, static_argnames=("tile", "sqrt"))
def _fused_l2nn(x, y_padded, m_real: jax.Array, tile: int, sqrt: bool):
    n = x.shape[0]
    xx = jnp.sum(x * x, axis=1)
    n_tiles = y_padded.shape[0] // tile

    def body(i, carry):
        best_v, best_i = carry
        yt = jax.lax.dynamic_slice_in_dim(y_padded, i * tile, tile, axis=0)
        yy = jnp.sum(yt * yt, axis=1)
        d2 = xx[:, None] + yy[None, :] - 2.0 * jnp.matmul(
            x, yt.T, preferred_element_type=jnp.float32)
        col = i * tile + jnp.arange(tile)
        valid = col[None, :] < m_real
        d2 = jnp.where(valid, d2, jnp.inf)
        tv = jnp.min(d2, axis=1)
        ti = jnp.argmin(d2, axis=1).astype(jnp.int32) + i * tile
        take = (tv < best_v) | ((tv == best_v) & (ti < best_i))
        return (jnp.where(take, tv, best_v), jnp.where(take, ti, best_i))

    best_v = jnp.full((n,), jnp.inf, jnp.float32)
    best_i = jnp.full((n,), jnp.iinfo(jnp.int32).max, jnp.int32)
    best_v, best_i = jax.lax.fori_loop(0, n_tiles, body, (best_v, best_i))
    best_v = jnp.maximum(best_v, 0.0)
    if sqrt:
        best_v = jnp.sqrt(best_v)
    return best_v, best_i


@instrument("distance.fused_l2_nn_argmin")
def fused_l2_nn_argmin(res, x, y, sqrt: bool = False,
                       tile: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
    """For each row of x, the nearest row of y under (squared) L2.
    Returns (min_dist [n], argmin [n]). (ref: pre-cuVS fusedL2NN /
    pylibraft.distance.fused_l2_nn_argmin)"""
    fault_point("fused_l2nn")
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    expects(x.shape[1] == y.shape[1], "fused_l2_nn: dim mismatch")
    if tile is None:
        # [n, tile] f32 intermediate within workspace budget
        tile = max(128, min(y.shape[0],
                            res.workspace.allocation_limit // (8 * max(x.shape[0], 1))))
        tile = min(tile, 8192)
    y_padded, _ = _pad_rows(y, tile)
    return _fused_l2nn(x, y_padded, jnp.asarray(y.shape[0]), int(tile), sqrt)


def fused_l2_nn(res, x, y, sqrt: bool = False) -> KeyValuePair:
    """KVP-returning variant mirroring the reference's out type."""
    v, i = fused_l2_nn_argmin(res, x, y, sqrt)
    return KeyValuePair(key=i, value=v)


def _merge_topk(best_v, best_i, tile_v, tile_i, k: int, select_min: bool):
    """Merge a running top-k with a new tile and reselect (delegates to the
    one top-k implementation in matrix/select_k)."""
    from raft_tpu.matrix.select_k import _xla_select_k

    allv = jnp.concatenate([best_v, tile_v], axis=1)
    alli = jnp.concatenate([best_i, tile_i], axis=1)
    return _xla_select_k(allv, alli, k, select_min)


@partial(jax.jit, static_argnames=("k", "tile"))
def _knn_sweep(x_sq, x, y_padded, m_real, k: int, tile: int):
    """Streamed fused top-k with threshold-gated merging — the same pruning
    idea as the reference's filtered warpsort queues
    (select_warpsort.cuh ``warp_sort_filtered``): a tile only pays for the
    O(n·tile·log) top-k merge when some query's running k-th-best improves;
    otherwise the tile costs one MXU contraction + a fused compare. After
    the first few tiles almost everything is pruned, so the sweep runs at
    matmul speed instead of sort speed."""
    n = x.shape[0]
    n_tiles = y_padded.shape[0] // tile

    def body(i, carry):
        best_v, best_i = carry                         # [n, k]
        yt = jax.lax.dynamic_slice_in_dim(y_padded, i * tile, tile, axis=0)
        yy = jnp.sum(yt * yt, axis=1)
        d2 = x_sq[:, None] + yy[None, :] - 2.0 * jnp.matmul(
            x, yt.T, preferred_element_type=jnp.float32)
        col = i * tile + jnp.arange(tile, dtype=jnp.int32)
        valid = col[None, :] < m_real
        d2 = jnp.where(valid, d2, jnp.inf)
        threshold = best_v[:, k - 1]                   # current k-th best
        improves = jnp.any(d2 < threshold[:, None])
        cols = jnp.broadcast_to(col[None, :], d2.shape)

        def do_merge(_):
            return _merge_topk(best_v, best_i, d2, cols, k, True)

        def skip(_):
            return best_v, best_i

        return jax.lax.cond(improves, do_merge, skip, None)

    best_v = jnp.full((n, k), jnp.inf, jnp.float32)
    best_i = jnp.full((n, k), -1, jnp.int32)
    return jax.lax.fori_loop(0, n_tiles, body, (best_v, best_i))


@partial(jax.jit, static_argnames=("k", "tile"))
def _knn_certified_approx(x, y_padded, m_real, k: int, tile: int):
    """Certified-approx KNN sweep (the fast path for big indexes).

    Sweep A streams tiles through TPU's native bucketed ``approx_min_k``
    merge — sort-free, ~6× cheaper than exact top-k merges. Sweep B then
    CERTIFIES the result with one exact fused count pass: for each query
    it counts entries with d2 ≤ θ (θ = the approx k-th). If the count is
    exactly k, the approx set provably IS the exact top-k (any missed
    entry would have to be ≤ θ and would make the count exceed k). If any
    query fails certification, a ``lax.cond`` branch runs the exact merge
    sweep instead — so the returned result is always exact and the whole
    function stays traceable under jit with no host synchronization.

    (ref: the role of the kAuto heuristic + filtered warpsort queues in
    matrix/detail/select_k-inl.cuh — cheap path when it provably works,
    exact fallback otherwise.)
    """
    q = x.shape[0]
    x_sq = jnp.sum(x * x, axis=1)
    n_tiles = y_padded.shape[0] // tile

    def body_approx(i, best):
        yt = jax.lax.dynamic_slice_in_dim(y_padded, i * tile, tile, axis=0)
        yy = jnp.sum(yt * yt, axis=1)
        d2 = x_sq[:, None] + yy[None, :] - 2.0 * jnp.matmul(
            x, yt.T, preferred_element_type=jnp.float32)
        col = i * tile + jnp.arange(tile)
        d2 = jnp.where(col[None, :] < m_real, d2, jnp.inf)
        merged_v = jnp.concatenate([best[0], d2], axis=1)
        merged_i = jnp.concatenate(
            [best[1], jnp.broadcast_to(col[None, :], d2.shape).astype(jnp.int32)],
            axis=1)
        nv, pos = jax.lax.approx_min_k(merged_v, k)
        return nv, jnp.take_along_axis(merged_i, pos, axis=1)

    best_v = jnp.full((q, k), jnp.inf, jnp.float32)
    best_i = jnp.full((q, k), -1, jnp.int32)
    best_v, best_i = jax.lax.fori_loop(0, n_tiles, body_approx,
                                       (best_v, best_i))
    theta = best_v[:, -1]

    def body_count(i, cnt):
        yt = jax.lax.dynamic_slice_in_dim(y_padded, i * tile, tile, axis=0)
        yy = jnp.sum(yt * yt, axis=1)
        d2 = x_sq[:, None] + yy[None, :] - 2.0 * jnp.matmul(
            x, yt.T, preferred_element_type=jnp.float32)
        col = i * tile + jnp.arange(tile)
        ok = (d2 <= theta[:, None]) & (col[None, :] < m_real)
        return cnt + jnp.sum(ok.astype(jnp.int32), axis=1)

    counts = jax.lax.fori_loop(0, n_tiles, body_count,
                               jnp.zeros((q,), jnp.int32))
    all_certified = jnp.all(counts == k)

    # traced fallback: when any query fails the certificate, run the exact
    # merge sweep — lax.cond keeps knn fully jittable with no host sync
    def exact(_):
        return _knn_sweep(x_sq, x, y_padded, m_real, k, tile)

    def keep(_):
        return best_v, best_i

    return jax.lax.cond(all_certified, keep, exact, None)


@instrument("distance.knn")
def knn(res, index, queries, k: int, metric: str = "sqeuclidean",
        tile: Optional[int] = None, algo: str = "auto",
        certify: str = "kernel") -> Tuple[jax.Array, jax.Array]:
    """Brute-force k nearest neighbors. Returns (distances [nq, k],
    indices [nq, k]), nearest first.
    (ref: pre-cuVS brute_force::knn = pairwise distance + select_k, fused)

    ``algo``:
      - ``"auto"``: the fused Pallas pipeline (certified-exact slotted
        top-k, see knn_fused) on TPU when shapes fit its envelope;
        the streamed XLA sweep otherwise.
      - ``"fused"`` / ``"fused_fast"``: force the Pallas pipeline
        (bf16x3 exact / 1-pass bf16).
      - ``"streamed"``: force the streamed XLA sweep.

    ``tile`` sizes the streamed sweep only; the fused pipeline has its own
    tiling and bounds its workspace by chunking queries internally.

    ``metric="cosine"`` solves certified-exact squared-L2 on
    row-normalized operands (monotone-equivalent ranking) and returns
    ``1 − cos_sim = d2/2`` — so the fused Pallas pipeline serves cosine
    too. Degenerate zero-norm rows normalize to the zero vector
    (distance 0.5 to every unit vector) where the pairwise convention
    reports 1.0.

    ``index`` may be a :class:`~raft_tpu.distance.knn_fused.KnnIndex`
    (built once with ``prepare_knn_index`` — the build/query split for
    repeated query batches); the metric must match what the index was
    prepared for ("l2" serves sqeuclidean/euclidean/l2, "ip" serves
    inner_product; prepare on pre-normalized data for cosine).

    ``certify="f32"`` (fused pipeline, passes=1 indexes): adaptive
    precision — f32-certified results at 1-pass kernel cost (see
    knn_fused).
    """
    res = ensure_resources(res)
    from raft_tpu.distance.knn_fused import KnnIndex, knn_fused

    expects(certify in ("kernel", "f32"),
            "knn: certify must be 'kernel' or 'f32', got %r", certify)
    if isinstance(index, KnnIndex):
        queries = jnp.asarray(queries, jnp.float32)
        if metric in ("sqeuclidean", "euclidean", "l2"):
            expects(index.metric == "l2",
                    "knn: index prepared for %r, metric %r needs 'l2'",
                    index.metric, metric)
            dists, idx = knn_fused(queries, index, k, certify=certify)
            if metric in ("euclidean", "l2"):
                dists = jnp.sqrt(jnp.maximum(dists, 0.0))
            return dists, idx
        expects(metric == "inner_product" and index.metric == "ip",
                "knn: prepared-index metric %r cannot serve %r",
                index.metric, metric)
        return knn_fused(queries, index, k, certify=certify)
    index = jnp.asarray(index, jnp.float32)
    queries = jnp.asarray(queries, jnp.float32)
    expects(metric in ("sqeuclidean", "euclidean", "l2", "inner_product",
                       "cosine"),
            "knn: unsupported metric %r", metric)
    if metric == "cosine":
        def _unit(a):
            # same zero-norm guard as pairwise._cosine (1e-30), so both
            # cosine surfaces share one degenerate-input convention
            n = jnp.sqrt(jnp.sum(a * a, axis=1, keepdims=True))
            return a / jnp.maximum(n, 1e-30)

        d2, idx = knn(res, _unit(index), _unit(queries), k,
                      metric="sqeuclidean", tile=tile, algo=algo,
                      certify=certify)
        return d2 * 0.5, idx
    expects(k <= index.shape[0], "knn: k larger than index size")
    expects(algo in ("auto", "fused", "fused_fast", "streamed"),
            "knn: unknown algo %r", algo)
    n = index.shape[0]

    forced_fused = algo in ("fused", "fused_fast")
    # the fused pipeline's candidate pool is 2·128·ceil(n_tiles/g)
    # entries per query under its active (possibly tuned) tiling —
    # mirror knn_fused's own envelope so auto never round-trips an
    # exception
    from raft_tpu.distance.knn_fused import fused_config

    # auto-routing only ever runs passes=3, and FORCED fused requests
    # rely on knn_fused's own envelope errors (re-raised below), so the
    # pool precheck mirrors the passes=3 defaults. (The tuned config
    # may carry a database-major grid_order; the pool geometry below is
    # order-invariant — ceil(ceil(n/T)/g) == ceil(n/(g·T)), so the
    # db-padded index yields the same group count.)
    _cfg = fused_config(3)
    _T, _g = _cfg.T, _cfg.g
    # pool = 2·128 per tile-GROUP (g = tiles per group), matching
    # knn_fused's own pool construction — NOT 2·128/g per tile
    _n_tiles = -(-max(n, _T) // _T)
    fused_pool = 2 * (-(-_n_tiles // _g)) * 128
    # d ≤ 512 takes the single-shot kernel; wider features take the
    # d-chunked kernel (VMEM scratch accumulator) up to a pragmatic cap;
    # fused_eligible is THE shared backend/shape gate (also used by
    # models.NearestNeighbors.fit and bench.py's prepare decision)
    from raft_tpu.distance.knn_fused import fused_eligible

    auto_fused = (algo == "auto" and fused_eligible(n, queries.shape[1])
                  and k <= fused_pool)
    if forced_fused or auto_fused:
        from raft_tpu.distance.knn_fused import knn_fused

        try:
            dists, idx = knn_fused(
                queries, index, k,
                passes=1 if algo == "fused_fast" else 3,
                metric="ip" if metric == "inner_product" else "l2",
                certify=certify)
            if metric in ("euclidean", "l2"):
                dists = jnp.sqrt(jnp.maximum(dists, 0.0))
            return dists, idx
        except NotImplementedError:
            if algo != "auto":
                raise

    expects(certify == "kernel",
            "knn: certify='f32' is a fused-pipeline contract, but this "
            "call routed to the streamed sweep (shape/backend outside "
            "the fused envelope) — it cannot be honored silently")
    if tile is None:
        tile = max(128, min(index.shape[0],
                            res.workspace.allocation_limit
                            // (8 * max(queries.shape[0], 1))))
        tile = min(tile, 8192)
    y_padded, _ = _pad_rows(index, int(tile))
    if metric == "inner_product":
        return _ip_sweep(queries, y_padded, jnp.asarray(index.shape[0]),
                         k, int(tile))
    x_sq = jnp.sum(queries * queries, axis=1)
    use_certified = n >= 16 * int(tile) and k <= 256
    if use_certified:
        dists, idx = _knn_certified_approx(
            queries, y_padded, jnp.asarray(n), k, int(tile))
    else:
        dists, idx = _knn_sweep(x_sq, queries, y_padded, jnp.asarray(n),
                                k, int(tile))
    if metric in ("euclidean", "l2"):
        dists = jnp.sqrt(jnp.maximum(dists, 0.0))
    return dists, idx


@partial(jax.jit, static_argnames=("k", "tile"))
def _ip_sweep(x, y_padded, m_real, k: int, tile: int):
    n = x.shape[0]
    n_tiles = y_padded.shape[0] // tile

    def body(i, carry):
        best_v, best_i = carry
        yt = jax.lax.dynamic_slice_in_dim(y_padded, i * tile, tile, axis=0)
        ip = jnp.matmul(x, yt.T, preferred_element_type=jnp.float32)
        col = i * tile + jnp.arange(tile, dtype=jnp.int32)
        valid = col[None, :] < m_real
        ip = jnp.where(valid, ip, -jnp.inf)
        threshold = best_v[:, k - 1]
        improves = jnp.any(ip > threshold[:, None])
        cols = jnp.broadcast_to(col[None, :], ip.shape)

        def do_merge(_):
            return _merge_topk(best_v, best_i, ip, cols, k, False)

        def skip(_):
            return best_v, best_i

        return jax.lax.cond(improves, do_merge, skip, None)

    best_v = jnp.full((n, k), -jnp.inf, jnp.float32)
    best_i = jnp.full((n, k), -1, jnp.int32)
    return jax.lax.fori_loop(0, n_tiles, body, (best_v, best_i))


_SHARDED_KNN_CACHE: dict = {}


@instrument("distance.knn_sharded")
def knn_sharded(res, index, queries, k: int, mesh=None, axis: str = "x",
                metric: str = "sqeuclidean", algo: str = "auto"
                ) -> Tuple[jax.Array, jax.Array]:
    """Data-parallel brute-force KNN over a device mesh: queries are
    row-sharded over ``axis``, the index is replicated, and every shard
    runs the (fused or streamed) single-chip pipeline locally — no
    cross-shard communication is needed because each query's top-k
    depends only on the full index. (ref: the MNMG data-parallel model,
    SURVEY §2.12 — raft-dask shards work across workers the same way.)

    Returns globally-assembled (distances [nq, k], indices [nq, k]).
    """
    from jax.sharding import PartitionSpec as P

    from raft_tpu.parallel import replicated, shard_array

    res = ensure_resources(res)
    if mesh is None:
        mesh = res.mesh
    expects(mesh is not None, "knn_sharded: pass mesh= or set it on res")
    expects(axis in mesh.axis_names,
            "knn_sharded: axis %r not in mesh axes %s", axis,
            tuple(mesh.axis_names))
    ndev = mesh.shape[axis]
    if ndev == 1:
        import warnings

        warnings.warn(
            "knn_sharded over a 1-device mesh shards nothing — set a "
            "multi-device mesh on the handle or pass mesh=",
            RuntimeWarning, stacklevel=2)
    index = jnp.asarray(index, jnp.float32)
    queries = jnp.asarray(queries, jnp.float32)
    nq = queries.shape[0]
    queries, _ = _pad_rows(queries, ndev)

    # cache the shard_map-wrapped callable: a fresh closure per call would
    # defeat the jit cache and recompile every invocation. The workspace
    # budget is in the key because knn() sizes its tile from it at trace
    # time.
    key = (mesh, axis, k, metric, algo, res.workspace.allocation_limit)
    fn = _SHARDED_KNN_CACHE.get(key)
    if fn is None:
        # capture only the scalar budget, not the caller's handle — a
        # cached closure holding res would pin it for process lifetime
        # and silently reuse the FIRST caller's handle on key collisions
        ws_limit = res.workspace.allocation_limit

        def shard_fn(q_shard, idx_repl):
            from raft_tpu.core.resources import (
                DeviceResources, WorkspaceResource)

            local = DeviceResources()
            local.set_workspace_resource(WorkspaceResource(ws_limit))
            return knn(local, idx_repl, q_shard, k=k, metric=metric,
                       algo=algo)

        fn = jax.jit(jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=(P(axis), P(axis)),
            check_vma=False))
        _SHARDED_KNN_CACHE[key] = fn
    qs = shard_array(queries, mesh, axis)
    ir = jax.device_put(index, replicated(mesh))
    d, i = fn(qs, ir)
    return d[:nq], i[:nq]


class ShardedKnnIndex(NamedTuple):
    """A row-sharded, row-padded KNN index prepared ONCE
    (:func:`prepare_index_sharded`) — the build/query split for the
    model-parallel mode: queries against it never re-pad or re-shard
    the index."""

    idx_s: jax.Array       # [n_pad, d] f32, sharded over (mesh, axis)
    n: int                 # true (unpadded) row count
    mesh: object           # the Mesh it was sharded over
    axis: str


def prepare_index_sharded(res, index, mesh=None, axis: str = "x"
                          ) -> ShardedKnnIndex:
    """Pad the index rows to a shard multiple ON HOST and place the
    shards directly (device_put with a NamedSharding streams each
    shard from host memory — the full matrix never materializes on one
    device, which is the point of the bigger-than-HBM index mode)."""
    import numpy as np

    from raft_tpu.parallel import shard_array

    res = ensure_resources(res)
    if mesh is None:
        mesh = res.mesh
    expects(mesh is not None,
            "prepare_index_sharded: pass mesh= or set it on res")
    expects(axis in mesh.axis_names,
            "prepare_index_sharded: axis %r not in mesh axes %s", axis,
            tuple(mesh.axis_names))
    arr = np.asarray(index, np.float32)
    n = arr.shape[0]
    ndev = int(mesh.shape[axis])
    npad = (-n) % ndev
    if npad:
        arr = np.concatenate(
            [arr, np.zeros((npad, arr.shape[1]), np.float32)])
    return ShardedKnnIndex(shard_array(arr, mesh, axis), n, mesh, axis)


def knn_index_sharded(res, index, queries, k: int, mesh=None,
                      axis: str = "x", metric: str = "sqeuclidean",
                      algo: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """Model-parallel brute-force KNN: the INDEX rows are sharded over
    ``axis`` (the mode for indexes too large for one chip's HBM — each
    chip holds n/ndev rows), queries replicated. Every shard selects
    its local top-k, local ids shift to global by the shard's row
    offset, the per-shard candidates ride ONE ``all_gather`` over the
    mesh axis (k·nq values — the only cross-chip traffic), and a final
    merge top-k assembles the exact global result. (ref: the
    raft-dask/legacy ``knn_merge_parts`` pattern — per-worker partial
    KNN + cross-worker merge; SURVEY §2.12's MNMG model with the model
    axis sharded instead of the data axis.)

    Exact for every metric/algo the single-chip ``knn`` serves: each
    shard over-selects by the pad count (zero-padded rows — all in the
    last shard — can rank inside a local top-k, so selecting
    k + n_pads locally guarantees ≥ k REAL candidates per shard), the
    merge masks pads by global id, and the global top-k is then a
    subset of the union of per-shard real candidates."""
    from jax.sharding import PartitionSpec as P

    from raft_tpu.parallel import replicated, shard_array

    res = ensure_resources(res)
    if mesh is None:
        mesh = res.mesh
    expects(mesh is not None,
            "knn_index_sharded: pass mesh= or set it on res")
    expects(axis in mesh.axis_names,
            "knn_index_sharded: axis %r not in mesh axes %s", axis,
            tuple(mesh.axis_names))
    ndev = mesh.shape[axis]
    queries = jnp.asarray(queries, jnp.float32)
    if isinstance(index, ShardedKnnIndex):
        expects(index.axis == axis,
                "knn_index_sharded: index prepared for axis %r, got %r",
                index.axis, axis)
        # the PREPARED mesh wins — a mismatched mesh= would silently
        # re-lay-out the whole index across devices on every query
        # (full cross-device transfer at bigger-than-HBM scale)
        expects(index.mesh == mesh,
                "knn_index_sharded: index prepared for a different "
                "mesh — re-prepare or pass its mesh")
        idx_prepared, n = index.idx_s, index.n
        index_p = idx_prepared
    else:
        index = jnp.asarray(index, jnp.float32)
        n = index.shape[0]
        index_p, _ = _pad_rows(index, ndev)
        idx_prepared = None
    expects(k <= n, "knn_index_sharded: k larger than index size")
    rows_per = index_p.shape[0] // ndev
    n_pads = index_p.shape[0] - n
    k_loc = k + n_pads                      # over-select past any pads
    expects(k_loc <= rows_per,
            "knn_index_sharded: k=%d (+%d pad slots) exceeds the "
            "per-shard row count %d — use fewer shards or the "
            "query-sharded mode", k, n_pads, rows_per)

    # rows_per is baked into the cached closure (the global-id shift):
    # the index geometry MUST be part of the key
    key = ("idx", mesh, axis, k_loc, rows_per, n, metric, algo,
           res.workspace.allocation_limit)
    fn = _SHARDED_KNN_CACHE.get(key)
    if fn is None:
        ws_limit = res.workspace.allocation_limit

        def shard_fn(idx_shard, q_repl):
            from raft_tpu.core.resources import (
                DeviceResources, WorkspaceResource)

            local = DeviceResources()
            local.set_workspace_resource(WorkspaceResource(ws_limit))
            d_loc, i_loc = knn(local, idx_shard, q_repl, k=k_loc,
                               metric=metric, algo=algo)
            gid = i_loc + jax.lax.axis_index(axis) * rows_per
            dg = jax.lax.all_gather(d_loc, axis, axis=1,
                                    tiled=True)          # [nq, ndev·k]
            ig = jax.lax.all_gather(gid, axis, axis=1, tiled=True)
            return dg, ig

        fn = jax.jit(jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=(P(), P()),
            check_vma=False))
        _SHARDED_KNN_CACHE[key] = fn

    idx_s = (idx_prepared if idx_prepared is not None
             else shard_array(index_p, mesh, axis))
    qr = jax.device_put(queries, replicated(mesh))
    dg, ig = fn(idx_s, qr)
    # merge: exact top-k of the gathered per-shard candidates; padded
    # rows (global id ≥ n) masked out
    dg = jnp.where(ig < n, dg, jnp.inf if metric != "inner_product"
                   else -jnp.inf)
    if metric == "inner_product":
        top, pos = jax.lax.top_k(dg, k)
    else:
        neg, pos = jax.lax.top_k(-dg, k)
        top = -neg
    return top, jnp.take_along_axis(ig, pos, axis=1)
