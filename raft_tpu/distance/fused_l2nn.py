"""Fused L2 nearest-neighbor and brute-force KNN.

(ref: the pre-cuVS ``raft::distance::fusedL2NN`` — per-query argmin over a
distance matrix that is never materialized — and brute-force knn
(distance + matrix::select_k). BASELINE config 2: "fused L2-NN + select_k
top-64 on 1M×128". Rebuilt TPU-first per SURVEY §7 stage 10.)

Design: stream over column tiles of Y. Each tile does one MXU contraction
X·Y_tileᵀ plus norm corrections, then folds into a running (value, index)
minimum — or a running top-k via merge-and-reselect for knn. Peak memory is
[n, tile] + [n, k], never [n, m]; the tile size comes from the handle's
workspace budget (the reference sizes its smem tiles the same way,
linalg/detail/contractions.cuh). The per-tile loop is a ``lax.fori_loop``
over a static tile count so the whole sweep is one compiled program.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.kvp import KeyValuePair
from raft_tpu.core.resources import ensure_resources


def _pad_rows(y, tile):
    """Pad to a tile multiple with zeros; padded columns are masked out via
    the m_real bound in every sweep (zeros keep the matmul NaN-free)."""
    m = y.shape[0]
    pad = (-m) % tile
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad, y.shape[1]), y.dtype)])
    return y, m + pad


@partial(jax.jit, static_argnames=("tile", "sqrt"))
def _fused_l2nn(x, y_padded, m_real: jax.Array, tile: int, sqrt: bool):
    n = x.shape[0]
    xx = jnp.sum(x * x, axis=1)
    n_tiles = y_padded.shape[0] // tile

    def body(i, carry):
        best_v, best_i = carry
        yt = jax.lax.dynamic_slice_in_dim(y_padded, i * tile, tile, axis=0)
        yy = jnp.sum(yt * yt, axis=1)
        d2 = xx[:, None] + yy[None, :] - 2.0 * jnp.matmul(
            x, yt.T, preferred_element_type=jnp.float32)
        col = i * tile + jnp.arange(tile)
        valid = col[None, :] < m_real
        d2 = jnp.where(valid, d2, jnp.inf)
        tv = jnp.min(d2, axis=1)
        ti = jnp.argmin(d2, axis=1).astype(jnp.int32) + i * tile
        take = (tv < best_v) | ((tv == best_v) & (ti < best_i))
        return (jnp.where(take, tv, best_v), jnp.where(take, ti, best_i))

    best_v = jnp.full((n,), jnp.inf, jnp.float32)
    best_i = jnp.full((n,), jnp.iinfo(jnp.int32).max, jnp.int32)
    best_v, best_i = jax.lax.fori_loop(0, n_tiles, body, (best_v, best_i))
    best_v = jnp.maximum(best_v, 0.0)
    if sqrt:
        best_v = jnp.sqrt(best_v)
    return best_v, best_i


def fused_l2_nn_argmin(res, x, y, sqrt: bool = False,
                       tile: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
    """For each row of x, the nearest row of y under (squared) L2.
    Returns (min_dist [n], argmin [n]). (ref: pre-cuVS fusedL2NN /
    pylibraft.distance.fused_l2_nn_argmin)"""
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    expects(x.shape[1] == y.shape[1], "fused_l2_nn: dim mismatch")
    if tile is None:
        # [n, tile] f32 intermediate within workspace budget
        tile = max(128, min(y.shape[0],
                            res.workspace.allocation_limit // (8 * max(x.shape[0], 1))))
        tile = min(tile, 8192)
    y_padded, _ = _pad_rows(y, tile)
    return _fused_l2nn(x, y_padded, jnp.asarray(y.shape[0]), int(tile), sqrt)


def fused_l2_nn(res, x, y, sqrt: bool = False) -> KeyValuePair:
    """KVP-returning variant mirroring the reference's out type."""
    v, i = fused_l2_nn_argmin(res, x, y, sqrt)
    return KeyValuePair(key=i, value=v)


def _merge_topk(best_v, best_i, tile_v, tile_i, k: int, select_min: bool):
    """Merge a running top-k with a new tile and reselect (delegates to the
    one top-k implementation in matrix/select_k)."""
    from raft_tpu.matrix.select_k import _xla_select_k

    allv = jnp.concatenate([best_v, tile_v], axis=1)
    alli = jnp.concatenate([best_i, tile_i], axis=1)
    return _xla_select_k(allv, alli, k, select_min)


@partial(jax.jit, static_argnames=("k", "tile"))
def _knn_sweep(x_sq, x, y_padded, m_real, k: int, tile: int):
    n = x.shape[0]
    n_tiles = y_padded.shape[0] // tile

    def body(i, carry):
        best_v, best_i = carry                         # [n, k]
        yt = jax.lax.dynamic_slice_in_dim(y_padded, i * tile, tile, axis=0)
        yy = jnp.sum(yt * yt, axis=1)
        d2 = x_sq[:, None] + yy[None, :] - 2.0 * jnp.matmul(
            x, yt.T, preferred_element_type=jnp.float32)
        col = i * tile + jnp.arange(tile, dtype=jnp.int32)
        valid = col[None, :] < m_real
        d2 = jnp.where(valid, d2, jnp.inf)
        return _merge_topk(best_v, best_i, d2,
                           jnp.broadcast_to(col[None, :], d2.shape), k, True)

    best_v = jnp.full((n, k), jnp.inf, jnp.float32)
    best_i = jnp.full((n, k), -1, jnp.int32)
    return jax.lax.fori_loop(0, n_tiles, body, (best_v, best_i))


def knn(res, index, queries, k: int, metric: str = "sqeuclidean",
        tile: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
    """Brute-force k nearest neighbors: streamed fused distance + top-k.
    Returns (distances [nq, k], indices [nq, k]), nearest first.
    (ref: pre-cuVS brute_force::knn = pairwise distance + select_k, fused)"""
    res = ensure_resources(res)
    index = jnp.asarray(index, jnp.float32)
    queries = jnp.asarray(queries, jnp.float32)
    expects(metric in ("sqeuclidean", "euclidean", "l2", "inner_product"),
            "knn: unsupported metric %r", metric)
    expects(k <= index.shape[0], "knn: k larger than index size")
    if tile is None:
        tile = max(128, min(index.shape[0],
                            res.workspace.allocation_limit
                            // (8 * max(queries.shape[0], 1))))
        tile = min(tile, 8192)
    y_padded, _ = _pad_rows(index, int(tile))
    if metric == "inner_product":
        return _ip_sweep(queries, y_padded, jnp.asarray(index.shape[0]),
                         k, int(tile))
    x_sq = jnp.sum(queries * queries, axis=1)
    dists, idx = _knn_sweep(x_sq, queries, y_padded,
                            jnp.asarray(index.shape[0]), k, int(tile))
    if metric in ("euclidean", "l2"):
        dists = jnp.sqrt(jnp.maximum(dists, 0.0))
    return dists, idx


@partial(jax.jit, static_argnames=("k", "tile"))
def _ip_sweep(x, y_padded, m_real, k: int, tile: int):
    n = x.shape[0]
    n_tiles = y_padded.shape[0] // tile

    def body(i, carry):
        best_v, best_i = carry
        yt = jax.lax.dynamic_slice_in_dim(y_padded, i * tile, tile, axis=0)
        ip = jnp.matmul(x, yt.T, preferred_element_type=jnp.float32)
        col = i * tile + jnp.arange(tile, dtype=jnp.int32)
        valid = col[None, :] < m_real
        ip = jnp.where(valid, ip, -jnp.inf)
        return _merge_topk(best_v, best_i, ip,
                           jnp.broadcast_to(col[None, :], ip.shape), k, False)

    best_v = jnp.full((n, k), -jnp.inf, jnp.float32)
    best_i = jnp.full((n, k), -1, jnp.int32)
    return jax.lax.fori_loop(0, n_tiles, body, (best_v, best_i))
