"""Distance type vocabulary.

(ref: the pre-cuVS ``raft::distance::DistanceType`` enum — removed from this
snapshot with the distance component (SURVEY "critical scoping fact") but
required by BASELINE configs 1-2; rebuilt here with the same metric set.)
"""

from __future__ import annotations

import enum


class DistanceType(enum.Enum):
    L2Expanded = "l2_expanded"            # squared L2 via gemm expansion
    L2SqrtExpanded = "l2_sqrt_expanded"   # L2 via gemm expansion
    L2Unexpanded = "l2_unexpanded"        # squared L2 via direct diff
    L2SqrtUnexpanded = "l2_sqrt_unexpanded"
    InnerProduct = "inner_product"
    CosineExpanded = "cosine"
    CorrelationExpanded = "correlation"
    L1 = "l1"
    Linf = "linf"
    LpUnexpanded = "minkowski"
    Canberra = "canberra"
    HammingUnexpanded = "hamming"
    HellingerExpanded = "hellinger"
    JensenShannon = "jensen_shannon"
    KLDivergence = "kl_divergence"
    BrayCurtis = "braycurtis"
    RussellRaoExpanded = "russellrao"
    JaccardExpanded = "jaccard"
    DiceExpanded = "dice"


# pylibraft-style metric-name strings → enum
METRIC_NAMES = {
    "euclidean": DistanceType.L2SqrtExpanded,
    "sqeuclidean": DistanceType.L2Expanded,
    "l2": DistanceType.L2SqrtExpanded,
    "inner_product": DistanceType.InnerProduct,
    "cosine": DistanceType.CosineExpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "linf": DistanceType.Linf,
    "chebyshev": DistanceType.Linf,
    "minkowski": DistanceType.LpUnexpanded,
    "canberra": DistanceType.Canberra,
    "hamming": DistanceType.HammingUnexpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "jensenshannon": DistanceType.JensenShannon,
    "kl_divergence": DistanceType.KLDivergence,
    "braycurtis": DistanceType.BrayCurtis,
    "russellrao": DistanceType.RussellRaoExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "dice": DistanceType.DiceExpanded,
}
