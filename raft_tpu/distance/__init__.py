"""raft_tpu.distance — pairwise distances + fused L2-NN / brute-force KNN.
(The pre-cuVS RAFT distance surface required by BASELINE, SURVEY §7
stage 10.)"""

from raft_tpu.distance.types import DistanceType, METRIC_NAMES
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.fused_l2nn import (
    ShardedKnnIndex,
    fused_l2_nn,
    fused_l2_nn_argmin,
    knn,
    knn_index_sharded,
    knn_sharded,
    prepare_index_sharded,
)
from raft_tpu.distance.knn_fused import KnnIndex, prepare_knn_index
from raft_tpu.distance.knn_sharded import (
    ShardedFusedIndex,
    knn_fused_sharded,
    prepare_knn_index_sharded,
)

__all__ = [
    "DistanceType", "METRIC_NAMES", "pairwise_distance",
    "fused_l2_nn", "fused_l2_nn_argmin", "knn", "knn_sharded",
    "knn_index_sharded", "ShardedKnnIndex", "prepare_index_sharded",
    "KnnIndex", "prepare_knn_index",
    "ShardedFusedIndex", "knn_fused_sharded", "prepare_knn_index_sharded",
]
