"""Declarative SLOs with multi-window burn-rate alerts.

A single threshold on a raw counter either pages on every blip (too
fast a window) or hours after the budget is gone (too slow). The
standard fix (the Google SRE multiwindow recipe) alerts on the **burn
rate** — the bad-event fraction divided by the SLO's error budget, so
``burn = 1`` exactly spends the budget over the SLO period — and only
fires when BOTH a fast and a slow window exceed the threshold: the
fast window gives detection latency, the slow window de-flaps it, and
recovery clears the alert as soon as the fast window drops back under.

:class:`SloEngine` evaluates a list of :class:`SloObjective` over a
:class:`~raft_tpu.observability.windows.MetricWindows` ring.
:func:`default_objectives` declares the serving SLOs:

- **availability** — 1 − (shed + deadline + error) / total over
  ``raft_tpu_serving_requests_total`` status deltas;
- **latency** — fraction of requests over the latency threshold,
  straight from ``raft_tpu_serving_latency_seconds`` bucket deltas (a
  histogram IS a pre-aggregated threshold-violation counter — pick the
  bucket, no per-request state needed);
- **shadow recall** — shadow-floor breaches over shadow samples (the
  online recall plane's breach counter, PR 14).

Each objective carries two severity rungs: ``page`` (fast 60 s / slow
300 s at 14.4× burn — budget gone in ~2 days at that rate) and
``ticket`` (300 s / 3600 s at 6×). Transitions emit an ``"alert"``
flight event (:func:`~raft_tpu.observability.timeline.emit_alert`),
bump ``raft_tpu_slo_burn_alerts_total{slo,severity}``, and surface in
:meth:`SloEngine.status` — what ``ServingEngine.stats()``, ``/statusz``
and the ``/healthz`` 503 flip read. The engine holds no thread: the
serving batcher loop (or a test) calls :meth:`tick`; evaluation is
pure snapshot arithmetic, rate-limited by the windows ring's interval.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Tuple

from raft_tpu.observability.metrics import MetricsRegistry, get_registry
from raft_tpu.observability.timeline import emit_alert
from raft_tpu.observability.windows import MetricWindows

#: alert-transition counter (bumped once per firing transition, not per
#: tick the alert stays active — dashboards count pages, not samples)
BURN_ALERTS = "raft_tpu_slo_burn_alerts_total"

#: serving metric names mirrored here (slo.py must not import the
#: serving engine — observability stays importable without it); pinned
#: equal to serving.engine by tests/test_slo.py.
REQUESTS = "raft_tpu_serving_requests_total"
LATENCY = "raft_tpu_serving_latency_seconds"
SHADOW_SAMPLES = "raft_tpu_serving_shadow_samples_total"
SHADOW_BREACHES = "raft_tpu_serving_shadow_breaches_total"

#: request statuses that consume the availability error budget
BAD_STATUSES = ("shed", "deadline", "error")

#: default latency SLO threshold (seconds) — requests slower than this
#: count against the latency budget
LATENCY_THRESHOLD_S = 0.250


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One severity rung: fire when burn(fast) AND burn(slow) both
    exceed ``factor``; clear when burn(fast) drops back under."""

    severity: str          # "page" | "ticket"
    fast_s: float
    slow_s: float
    factor: float


#: the SRE-book pairs: page on a 14.4× burn (1h-scale budget
#: exhaustion), ticket on a sustained 6×.
DEFAULT_WINDOWS = (
    BurnWindow("page", fast_s=60.0, slow_s=300.0, factor=14.4),
    BurnWindow("ticket", fast_s=300.0, slow_s=3600.0, factor=6.0),
)


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declarative objective.

    ``bad_fraction(windows, window_s)`` returns the bad-event fraction
    over the window — or None when the window has no evidence (no
    traffic, no shadow samples): an evidence-free window neither fires
    nor clears anything. ``objective`` is the good-fraction target
    (0.99 availability ⇒ a 0.01 error budget)."""

    name: str
    objective: float
    bad_fraction: Callable[[MetricWindows, float], Optional[float]]
    windows: Tuple[BurnWindow, ...] = DEFAULT_WINDOWS

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - float(self.objective))

    def burn(self, windows: MetricWindows,
             window_s: float) -> Optional[float]:
        """Burn rate over one window: bad fraction / error budget
        (1.0 = exactly spending the budget); None without evidence."""
        bad = self.bad_fraction(windows, window_s)
        if bad is None:
            return None
        return max(0.0, float(bad)) / self.budget


# -- the default serving objectives -------------------------------------
def _availability_bad(w: MetricWindows, window_s: float
                      ) -> Optional[float]:
    total = w.delta(REQUESTS, window_s=window_s)
    if total <= 0.0:
        return None
    bad = sum(w.delta(REQUESTS, {"status": s}, window_s=window_s)
              for s in BAD_STATUSES)
    return bad / total


def _latency_bad(threshold_s: float):
    def bad(w: MetricWindows, window_s: float) -> Optional[float]:
        br = w._bracket(window_s)
        if br is None:
            return None
        old, new = br
        total = 0.0
        slow = 0.0
        for (n, lk), (bounds, cum, _s) in new.hists.items():
            if n != LATENCY:
                continue
            old_h = old.hists.get((n, lk))
            old_cum = old_h[1] if old_h is not None else [0] * len(cum)
            d_total = cum[-1] - old_cum[-1]
            if d_total <= 0:
                continue
            # requests at or under the threshold: the cumulative count
            # of the last bucket bound <= threshold (bucket edges are
            # the only resolution a histogram has — the declared
            # threshold should sit on one)
            le = 0.0
            for i, b in enumerate(bounds):
                if b <= threshold_s:
                    le = cum[i] - old_cum[i]
            total += d_total
            slow += d_total - le
        if total <= 0.0:
            return None
        return slow / total

    return bad


def _recall_bad(w: MetricWindows, window_s: float) -> Optional[float]:
    samples = w.delta(SHADOW_SAMPLES, window_s=window_s)
    if samples <= 0.0:
        return None
    return w.delta(SHADOW_BREACHES, window_s=window_s) / samples


def default_objectives(availability: float = 0.99,
                       latency_objective: float = 0.99,
                       latency_threshold_s: float = LATENCY_THRESHOLD_S,
                       recall_objective: float = 0.95,
                       windows: Tuple[BurnWindow, ...] = DEFAULT_WINDOWS
                       ) -> List[SloObjective]:
    """The serving SLO set (see module doc). ``windows`` is injectable
    so tests shrink the rungs to seconds."""
    return [
        SloObjective("availability", availability, _availability_bad,
                     windows),
        SloObjective("latency_p99", latency_objective,
                     _latency_bad(latency_threshold_s), windows),
        SloObjective("shadow_recall", recall_objective, _recall_bad,
                     windows),
    ]


class SloEngine:
    """Evaluate objectives over a windows ring; own the alert state
    machine (see module doc)."""

    def __init__(self, windows: Optional[MetricWindows] = None,
                 objectives: Optional[List[SloObjective]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None):
        if windows is None:
            windows = MetricWindows(registry=registry,
                                    **({} if clock is None
                                       else {"clock": clock}))
        self.windows = windows
        self.objectives = (default_objectives() if objectives is None
                           else list(objectives))
        self._registry = registry
        self._lock = threading.Lock()
        #: {(slo, severity): {"since": ts, "burn_fast": x, ...}}
        self._active: Dict[Tuple[str, str], Dict] = {}

    @property
    def registry(self) -> MetricsRegistry:
        return (self._registry if self._registry is not None
                else get_registry())

    # -- evaluation -------------------------------------------------------
    def tick(self, force: bool = False) -> List[Dict]:
        """Snapshot the registry (rate-limited by the windows ring) and
        re-evaluate every objective. Returns the alert TRANSITIONS this
        tick (firing/resolved events, not steady state). Never raises —
        the batcher loop calls this inline."""
        try:
            if not self.windows.tick(force=force) and not force:
                return []
            return self._evaluate()
        except Exception:
            return []

    def _evaluate(self) -> List[Dict]:
        transitions: List[Dict] = []
        for obj in self.objectives:
            for rung in obj.windows:
                key = (obj.name, rung.severity)
                fast = obj.burn(self.windows, rung.fast_s)
                slow = obj.burn(self.windows, rung.slow_s)
                firing = (fast is not None and slow is not None
                          and fast >= rung.factor
                          and slow >= rung.factor)
                clearing = fast is not None and fast < rung.factor
                with self._lock:
                    active = key in self._active
                    if firing and not active:
                        info = {"slo": obj.name,
                                "severity": rung.severity,
                                "state": "firing",
                                "burn_fast": round(fast, 3),
                                "burn_slow": round(slow, 3),
                                "factor": rung.factor}
                        self._active[key] = dict(info)
                        transitions.append(info)
                    elif active and clearing:
                        info = dict(self._active.pop(key))
                        info.update(state="resolved",
                                    burn_fast=round(fast, 3))
                        transitions.append(info)
                    elif active and fast is not None:
                        self._active[key]["burn_fast"] = round(fast, 3)
                        if slow is not None:
                            self._active[key]["burn_slow"] = round(
                                slow, 3)
        for t in transitions:
            if t["state"] == "firing":
                self.registry.counter(
                    BURN_ALERTS,
                    {"slo": t["slo"], "severity": t["severity"]},
                    help="SLO burn-rate alert firing transitions",
                ).inc()
            emit_alert(t["slo"], t["severity"], t["state"],
                       burn_fast=t.get("burn_fast"),
                       burn_slow=t.get("burn_slow"),
                       factor=t.get("factor"))
        return transitions

    # -- read surfaces ----------------------------------------------------
    def active_alerts(self) -> List[Dict]:
        """Currently-firing alerts (copies), page severity first."""
        with self._lock:
            alerts = [dict(v) for v in self._active.values()]
        alerts.sort(key=lambda a: (a["severity"] != "page", a["slo"]))
        return alerts

    def burning(self, severity: str = "page") -> bool:
        """Is any alert of this severity active? (the ``/healthz`` 503
        predicate)"""
        with self._lock:
            return any(sev == severity for _, sev in self._active)

    def status(self) -> Dict:
        """The SLO panel: per-objective burn rates at every rung plus
        the active alerts — what ``stats()``/``/statusz`` render."""
        objectives = []
        for obj in self.objectives:
            rungs = []
            for rung in obj.windows:
                fast = obj.burn(self.windows, rung.fast_s)
                slow = obj.burn(self.windows, rung.slow_s)
                rungs.append({
                    "severity": rung.severity,
                    "factor": rung.factor,
                    "burn_fast": (None if fast is None
                                  else round(fast, 3)),
                    "burn_slow": (None if slow is None
                                  else round(slow, 3)),
                    "firing": (obj.name, rung.severity) in self._active,
                })
            objectives.append({"slo": obj.name,
                               "objective": obj.objective,
                               "windows": rungs})
        return {"objectives": objectives,
                "active_alerts": self.active_alerts(),
                "covered_s": round(self.windows.covered_s(), 3),
                "healthy": not self.burning("page")}
