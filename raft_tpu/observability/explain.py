"""The per-query explain plane: WHY did this search resolve this way?

The quality plane (PR 10) answers "how often do certificates fail"
with cumulative counters; ROADMAP item 2 (adaptive bounds) needs to
know WHY — the per-query margin distribution, the chosen plane with
its ``resolve_*`` downgrade reasons, the probed lists, the fixup
outcome. This module captures that decision record for a deterministic
hash-sampled fraction of live searches (the ShadowSampler idiom:
``RAFT_TPU_EXPLAIN_FRAC`` sets the fleet default, a per-request
``explain=True`` flag through :meth:`ServingEngine.submit` forces full
capture for one request) and keeps the records in a bounded ring
(``/explainz``, :func:`explain_records`).

Design contract (the NULL_FLIGHT idiom, applied to capture):

- **Zero allocation when disabled.** Capture state lives in a
  ``threading.local``; every hook (:func:`note`, :func:`note_margin`,
  :func:`stage`) is one attribute fetch + None check when no capture
  is active — no dict, no context-manager object (``stage`` returns a
  shared null context), no device sync. With ``RAFT_TPU_EXPLAIN_FRAC``
  unset the dispatch path is byte-for-byte the pre-explain one.
- **Margins stay on device until finalize.** The certificate margin
  (``bound − (θ + err)``, the scalar the core computes anyway — see
  ``_knn_fused_core``'s ``with_stats`` path) is noted as an ARRAY
  REFERENCE during capture and resolved to numpy only when the record
  finalizes — after the batch already synchronized for its response,
  so explain never adds a host sync to the dispatch path.
- **Deterministic sampling.** :func:`want` reuses the quality plane's
  Knuth multiplicative hash on the request id, so the sampled set
  replays bit-identically across runs (the serving tests rely on it).

Finalized records feed three surfaces: the bounded ring (``/explainz``
+ ``ServingEngine.stats()``), an ``"explain"`` flight event per record
(:func:`~raft_tpu.observability.timeline.emit_explain` — the record
lands on the Perfetto timeline next to its request's flow arrows), and
the ``raft_tpu_certificate_margin`` histogram per site — the margin
distribution evidence base the first TPU session collects.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from raft_tpu.core import env
from raft_tpu.observability.metrics import get_registry, tracing_enabled
from raft_tpu.observability.quality import _sample_hash
from raft_tpu.observability.timeline import emit_explain

#: per-site certificate-margin distribution (bound − θ − err; negative
#: = certificate failed, the fixup ran). Buckets span the failure tail
#: through the comfortable-pass region — the evidence ROADMAP item 2's
#: adaptive-bounds work reads.
MARGIN_HISTOGRAM = "raft_tpu_certificate_margin"
MARGIN_BUCKETS = (-100.0, -10.0, -1.0, -0.1, -0.01, 0.0,
                  0.01, 0.1, 1.0, 10.0, 100.0, 1000.0)

#: explain-ring capacity: bounded like every other evidence ring here
#: (flight recorder, latency deque) — old records fall off the back.
RING_CAPACITY = 256

EXPLAIN_FRAC_ENV = "RAFT_TPU_EXPLAIN_FRAC"

_tls = threading.local()


def explain_frac_default() -> float:
    """The fleet-default capture fraction (``RAFT_TPU_EXPLAIN_FRAC``,
    clamped to [0, 1]); the engine constructor's ``explain_frac=``
    wins."""
    try:
        return max(0.0, min(1.0, float(env.get(EXPLAIN_FRAC_ENV))))
    except (TypeError, ValueError):
        return 0.0


def want(rid: int, frac: float) -> bool:
    """Deterministic per-request sampling decision (Knuth hash — the
    same coin the shadow sampler flips, so a request sampled for
    explain on one run is sampled on every run)."""
    if frac <= 0.0:
        return False
    return frac >= 1.0 or _sample_hash(rid) < frac


class _NullCtx:
    """Shared no-op context manager — what :func:`stage` returns when
    no capture is active (one object for the whole process: the
    disabled path allocates nothing)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _StageTimer:
    __slots__ = ("_cap", "_name", "_t0")

    def __init__(self, cap: "ExplainCapture", name: str):
        self._cap = cap
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        st = self._cap.stages
        st[self._name] = st.get(self._name, 0.0) + dt
        return False


class ExplainCapture:
    """One in-flight explain record: a scratch dict the search path
    annotates through :func:`note`/:func:`note_margin`/:func:`stage`
    while active, finalized into an immutable record dict afterwards.
    Single-threaded by construction — it is installed in the capturing
    thread's ``threading.local`` and never shared."""

    __slots__ = ("rids", "data", "stages", "margins", "t0")

    def __init__(self, rids: List[int]):
        self.rids = list(rids)
        self.data: Dict = {}
        self.stages: Dict[str, float] = {}
        #: (site, device-or-host array) pairs — resolved at finalize
        self.margins: List = []
        self.t0 = time.perf_counter()

    def note(self, **kv) -> None:
        for key, value in kv.items():
            prev = self.data.get(key)
            if prev is None:
                self.data[key] = value
            elif isinstance(prev, list):
                prev.append(value)
            elif prev != value:
                self.data[key] = [prev, value]

    def finalize(self, outcome: str = "ok", **kv) -> Optional[Dict]:
        """Resolve the noted margins (ONE host transfer each — the
        batch already synchronized for its response), observe the
        margin histograms, build the record, push it to the ring and
        emit the ``explain`` flight event. Never raises."""
        try:
            record: Dict = {
                "ts": time.time(),
                "rids": self.rids,
                "outcome": outcome,
                "wall_s": round(time.perf_counter() - self.t0, 6),
            }
            record.update(self.data)
            record.update({k: v for k, v in kv.items() if v is not None})
            if self.stages:
                record["stages"] = {k: round(v, 6)
                                    for k, v in self.stages.items()}
            if self.margins:
                record["margins"] = margins = {}
                reg = get_registry()
                for site, m in self.margins:
                    arr = np.asarray(m, np.float64).ravel()
                    if arr.size == 0:
                        continue
                    arr = arr[np.isfinite(arr)]
                    if arr.size == 0:
                        continue
                    hist = reg.histogram(
                        MARGIN_HISTOGRAM, {"site": site},
                        help="Per-query certificate margin "
                             "(bound - theta - err; negative = fixup)",
                        buckets=MARGIN_BUCKETS)
                    for v in arr:
                        hist.observe(float(v))
                    entry = margins.setdefault(
                        site, {"n": 0, "min": float("inf"),
                               "n_negative": 0})
                    entry["n"] += int(arr.size)
                    entry["min"] = float(min(entry["min"], arr.min()))
                    entry["n_negative"] += int((arr < 0.0).sum())
            _ring().append(record)
            emit_explain(str(record.get("plane", "search")),
                         rid=self.rids[0] if self.rids else 0,
                         outcome=outcome,
                         riders=len(self.rids),
                         margin_min=min(
                             (m["min"] for m in
                              record.get("margins", {}).values()),
                             default=None))
            return record
        except Exception:
            return None


# -- the active-capture hooks (the search paths call these) -------------
def active() -> Optional[ExplainCapture]:
    """The calling thread's active capture, or None — THE disabled-mode
    fast path: one attribute fetch."""
    return getattr(_tls, "capture", None)


def note(**kv) -> None:
    """Annotate the active capture (no-op without one). Repeated keys
    with differing values collect into a list — a chunked search notes
    each chunk's resolution without losing any."""
    cap = getattr(_tls, "capture", None)
    if cap is None:
        return
    cap.note(**kv)


def note_margin(site: str, margin) -> None:
    """Attach one per-query certificate-margin array (device array OK —
    held by reference, resolved only at finalize) to the active
    capture. No-op without one: the ``with_stats`` margin output is
    computed by the compiled program either way; this hook only decides
    whether anything HOLDS it."""
    cap = getattr(_tls, "capture", None)
    if cap is None:
        return
    cap.margins.append((site, margin))


def stage(name: str):
    """Context manager timing one pipeline stage (coarse/fine/rescore/
    merge/dispatch) into the active capture; the shared null context
    when none is active."""
    cap = getattr(_tls, "capture", None)
    return _NULL_CTX if cap is None else _StageTimer(cap, name)


def begin_capture(rids) -> Optional[ExplainCapture]:
    """Install a capture for the calling thread (the engine calls this
    right before dispatching a batch with sampled riders). Returns None
    — and installs nothing — when tracing is globally disabled or a
    capture is already active (no nesting: the outer record owns the
    request)."""
    if not tracing_enabled():
        return None
    if getattr(_tls, "capture", None) is not None:
        return None
    cap = ExplainCapture(rids if isinstance(rids, (list, tuple))
                         else [rids])
    _tls.capture = cap
    return cap


def end_capture(cap: Optional[ExplainCapture], outcome: str = "ok",
                **kv) -> Optional[Dict]:
    """Uninstall ``cap`` and finalize it into the ring. Tolerates
    ``cap=None`` (the begin that returned None) so call sites stay
    branch-free."""
    if cap is None:
        return None
    if getattr(_tls, "capture", None) is cap:
        _tls.capture = None
    return cap.finalize(outcome=outcome, **kv)


class _ExplainScope:
    """The ``with explain.capture(...)`` form of begin/end — what tests
    and library callers (no engine) use around a direct search call."""

    __slots__ = ("_rids", "_outcome", "cap", "record")

    def __init__(self, rids, outcome: str):
        self._rids = rids
        self._outcome = outcome
        self.cap: Optional[ExplainCapture] = None
        self.record: Optional[Dict] = None

    def __enter__(self) -> "_ExplainScope":
        self.cap = begin_capture(self._rids)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.record = end_capture(
            self.cap,
            outcome=self._outcome if exc_type is None else "error")
        return False


def capture(rids=0, outcome: str = "ok") -> _ExplainScope:
    """Scope an explain capture around a direct library search::

        with explain.capture(rids=7) as scope:
            knn_query(res, idx, x, k)
        scope.record["margins"]  # per-site margin summaries

    The scope's ``record`` is the finalized dict (None when tracing is
    disabled)."""
    return _ExplainScope(rids, outcome)


# -- the record ring ----------------------------------------------------
# a bare deque(maxlen=...): append and list() are atomic under the GIL,
# and records are only ever appended whole — no lock needed for the
# bounded-evidence-ring semantics every other surface here uses
_ring_obj: collections.deque = collections.deque(maxlen=RING_CAPACITY)


def _ring() -> collections.deque:
    return _ring_obj


def explain_records(outcome: Optional[str] = None,
                    limit: Optional[int] = None) -> List[Dict]:
    """Snapshot of the ring, NEWEST first, optionally filtered by
    outcome (``ok`` / ``error`` / ``deadline`` — the ``/explainz``
    query surface)."""
    records = list(_ring_obj)
    records.reverse()
    if outcome is not None:
        records = [r for r in records if r.get("outcome") == outcome]
    if limit is not None:
        records = records[:max(0, int(limit))]
    return [dict(r) for r in records]


def clear_records() -> None:
    """Drop the ring (tests)."""
    _ring_obj.clear()
