"""Hang watchdog: heartbeat tracking + thread-stack dumps into the blackbox.

A SIGKILL leaves an epilogue-less blackbox and the verdict is easy. A
*hang* is worse: the process is alive, /statusz still answers, but the
batcher thread is wedged (a deadlock, a stuck collective, an interpreter
pile-up) and every queued request silently ages past its deadline. The
watchdog is the component that notices — and that writes down WHAT the
process was doing while it still can, because once someone kill -9's
the hung process, the stacks are gone.

Discipline (the SloEngine model): the watchdog is a daemon thread that
ticks on its own clock and NEVER sleeps or does I/O while holding a
lock. Heartbeats land under a tiny dedicated lock; engine state is read
through :meth:`ServingEngine.inflight_requests`, which takes the
batcher cond only long enough to copy the queue. On a stall — a
heartbeat silent past ``stall_after_s``, or an in-flight request aged
past its deadline by more than a tick — it:

- dumps every Python thread stack (``sys._current_frames``), each
  annotated with its *blocked-at* site (the innermost non-``threading``
  frame beneath a ``wait``/``acquire``/``join``), plus the in-flight
  request table, into the blackbox as a ``dump`` record;
- emits one ``stall`` flight event (mirrored into the blackbox too),
  latched once per stall episode so a wedged batcher does not flood
  the ring it is trying to preserve.

Each healthy tick also drives the blackbox's periodic metrics snapshot
and folds flight-ring evictions into ``raft_tpu_flight_dropped_total``.
Enable with ``RAFT_TPU_WATCHDOG_S`` (tick seconds; unset/0 = off, the
defaults-off contract) or ``ServingEngine(watchdog_s=...)``; live
dumps are served read-only at debugz ``/stackz``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from raft_tpu.core import env

WATCHDOG_ENV = "RAFT_TPU_WATCHDOG_S"

#: a heartbeat is stalled after this many tick intervals of silence
STALL_TICKS = 4

#: threading.py functions that mean "this thread is parked on a lock" —
#: the first frame beneath them is the blocked-at site
_WAIT_FNS = frozenset(
    {"wait", "wait_for", "acquire", "join", "_wait_for_tstate_lock"})


def interval_from_env() -> Optional[float]:
    """The configured tick interval, or None when the watchdog is off."""
    try:
        s = env.get(WATCHDOG_ENV)
        if s is None:
            return None
        val = float(s)
        return val if val > 0 else None
    except (TypeError, ValueError):
        return None


# ----------------------------------------------------------- stack dumps
def dump_stacks() -> Dict:
    """Every Python thread's stack as a JSON-friendly dict: thread
    name/ident/daemon flag, outermost-first frames, and the blocked-at
    annotation for threads parked inside :mod:`threading`. Read-only
    and lock-free (``sys._current_frames`` snapshots atomically under
    the GIL); safe to call from any thread, including /stackz."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    threads: List[Dict] = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        stack = traceback.extract_stack(frame)
        entries = [{"where": f"{fs.filename}:{fs.lineno}",
                    "fn": fs.name, "code": fs.line or ""}
                   for fs in stack]
        threads.append({
            "name": t.name if t else f"ident-{ident}",
            "ident": ident,
            "daemon": bool(t.daemon) if t else None,
            "blocked_at": _blocked_at(stack),
            "frames": entries,
        })
    threads.sort(key=lambda d: str(d["name"]))
    return {"pid": os.getpid(), "ts": time.perf_counter(),
            "wall": time.time(), "threads": threads}


def _blocked_at(stack: List[traceback.FrameSummary]) -> Optional[str]:
    """The held-lock site: for a thread whose innermost frames sit in
    ``threading.py`` ``wait``/``acquire``/``join``, the first frame
    beneath them — i.e. the caller that took the lock. None for a
    running (or C-blocked) thread."""
    waiting = False
    for fs in reversed(stack):
        if os.path.basename(fs.filename) == "threading.py":
            if fs.name in _WAIT_FNS:
                waiting = True
            continue
        if waiting:
            return f"{fs.filename}:{fs.lineno} in {fs.name}"
        return None
    return None


def format_stacks(dump: Optional[Dict] = None) -> str:
    """The human rendering of :func:`dump_stacks` (the /stackz body)."""
    d = dump if dump is not None else dump_stacks()
    lines = [f"thread dump — pid {d['pid']} — "
             f"{len(d['threads'])} thread(s)", ""]
    for t in d["threads"]:
        head = f"== {t['name']} (ident {t['ident']}"
        if t.get("daemon"):
            head += ", daemon"
        head += ")"
        if t.get("blocked_at"):
            head += f" blocked at {t['blocked_at']}"
        lines.append(head)
        for fr in t["frames"]:
            lines.append(f"  {fr['where']} in {fr['fn']}")
            if fr["code"]:
                lines.append(f"    {fr['code']}")
        lines.append("")
    return "\n".join(lines)


# -------------------------------------------------------------- watchdog
class Watchdog:
    """Daemon-thread hang detector over named heartbeats + an engine's
    in-flight request table.

    ``clock`` is injectable (tests drive :meth:`tick` by hand with a
    fake monotonic clock); ``engine`` is duck-typed to anything with an
    ``inflight_requests()`` method. ``stall_after_s`` defaults to
    :data:`STALL_TICKS` intervals of silence.
    """

    def __init__(self, engine=None, interval_s: Optional[float] = None,
                 stall_after_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if interval_s is None:
            interval_s = interval_from_env()
        self.interval_s = float(interval_s) if interval_s else 0.0
        self.stall_after_s = (float(stall_after_s) if stall_after_s
                              else max(self.interval_s * STALL_TICKS,
                                       0.001))
        self._engine = engine
        self._clock = clock
        self._beat_lock = threading.Lock()
        self._beats: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stall_active = False
        self.ticks = 0
        self.stalls = 0

    @property
    def enabled(self) -> bool:
        return self.interval_s > 0

    # -- heartbeats (the hot path: one dict store under a tiny lock) ------
    def beat(self, name: str = "serving-batcher") -> None:
        """Record one liveness heartbeat (the batcher calls this every
        loop iteration, OUTSIDE its cond lock)."""
        now = self._clock()
        with self._beat_lock:
            self._beats[name] = now

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Watchdog":
        """Start the daemon tick thread (no-op when disabled)."""
        if not self.enabled or self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 2 * self.interval_s))
            self._thread = None

    def _loop(self) -> None:
        # Event.wait is the sleep — no lock is ever held across it
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # the watchdog must never take the process down
                pass

    # -- detection ---------------------------------------------------------
    def tick(self) -> Optional[Dict]:
        """One detection pass. Returns the stall-dump dict when THIS
        tick opened a stall episode, else None."""
        self.ticks += 1
        now = self._clock()
        with self._beat_lock:
            beats = dict(self._beats)
        stalled = {name: now - t for name, t in beats.items()
                   if now - t > self.stall_after_s}
        inflight: List[Dict] = []
        if self._engine is not None:
            try:
                inflight = self._engine.inflight_requests()
            except Exception:
                inflight = []
        overdue = [r for r in inflight if self._is_overdue(r)]
        from raft_tpu.observability import blackbox, flight

        flight.sync_dropped_metric()
        bb = blackbox.active()
        if not stalled and not overdue:
            self._stall_active = False
            if bb is not None:
                bb.maybe_snapshot(inflight=inflight or None)
            return None
        if self._stall_active:
            return None      # one dump per episode — no ring flooding
        self._stall_active = True
        self.stalls += 1
        source = (next(iter(sorted(stalled)))
                  if stalled else "inflight-deadline")
        age = (max(stalled.values()) if stalled
               else max((r.get("age_s") or 0.0) for r in overdue))
        dump = dump_stacks()
        dump["trigger"] = {"source": source,
                           "stalled_heartbeats": stalled,
                           "overdue_requests": len(overdue),
                           "age_s": age}
        dump["inflight"] = inflight
        if bb is not None:
            bb.dump(dump)
            bb.snapshot(inflight=inflight or None)
        from raft_tpu.observability.timeline import emit_stall

        emit_stall(source, age_s=age, inflight=len(inflight),
                   overdue=len(overdue), stalls=self.stalls)
        return dump

    def _is_overdue(self, req: Dict) -> bool:
        deadline_in = req.get("deadline_in_s")
        if deadline_in is not None:
            # a request past its deadline by more than a full tick means
            # nobody is failing expired requests — the batcher is gone
            return deadline_in < -max(self.interval_s, 0.001)
        return (req.get("age_s") or 0.0) > self.stall_after_s

    def stats(self) -> Dict:
        with self._beat_lock:
            beats = dict(self._beats)
        now = self._clock()
        return {"enabled": self.enabled,
                "interval_s": self.interval_s,
                "stall_after_s": self.stall_after_s,
                "ticks": self.ticks,
                "stalls": self.stalls,
                "stall_active": self._stall_active,
                "heartbeats": {k: round(now - v, 6)
                               for k, v in beats.items()}}
