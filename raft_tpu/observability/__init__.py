"""raft_tpu.observability — unified metrics + span tracing.

The reference scatters observability across three headers — NVTX ranges
(core/nvtx.hpp), the rapids_logger-backed logger, and the range-attributed
memory monitor (mr/resource_monitor.hpp). This package unifies the
TPU-native port's equivalents behind ONE substrate:

- :class:`MetricsRegistry` — counters / gauges / fixed-bucket histograms,
  thread-safe, with a disabled mode whose fast path is a no-op attribute
  lookup (:data:`NULL_METRIC`).
- :func:`span` / :func:`instrument` — tracing layered on the
  ``core.nvtx`` thread-local range stack; every span attributes its
  metrics to the innermost enclosing range, the same attribution rule
  ``core.memory.ResourceMonitor`` applies to memory samples.
- hooks — comms collectives, ``CompileCache`` hit/miss, ``MemoryTracker``
  allocations and ``benchmark.Fixture`` results all report in
  (:mod:`raft_tpu.observability.hooks`).
- exporters — Prometheus text exposition, JSON lines, a human
  summary table, and the Perfetto/Chrome trace-event view of the
  flight recorder (:mod:`raft_tpu.observability.exporters`).
- flight recorder — a process-wide lock-guarded ring buffer of typed
  timeline events (spans, collectives, compiles, faults, retries,
  degradation rungs, deadlines), Perfetto-exportable, with automatic
  post-mortem dumps to ``RAFT_TPU_FLIGHT_DIR`` on classified device
  errors and fired deadlines (:mod:`raft_tpu.observability.flight` +
  :mod:`raft_tpu.observability.timeline`), plus the model-vs-measured
  :class:`DriftLedger` gated by ``tools/bench_report.py --check``.
- forensics plane — the crash-durable blackbox (a memory-mapped
  CRC-framed ring file mirroring every flight event + periodic metrics
  snapshots, readable after SIGKILL — :mod:`raft_tpu.observability
  .blackbox`), the hang watchdog (heartbeat tracking + thread-stack
  stall dumps, :mod:`raft_tpu.observability.watchdog`), and the
  offline reconstruction CLI ``tools/postmortem.py`` with its live
  debugz routes ``/stackz`` and ``/crashz``.
- telemetry front door — the per-query explain plane (hash-sampled
  decision records with certificate margins,
  :mod:`raft_tpu.observability.explain`), windowed metric aggregation
  (:mod:`raft_tpu.observability.windows`) feeding declarative SLOs with
  multi-window burn-rate alerts (:mod:`raft_tpu.observability.slo`),
  all served live over HTTP by ``tools/debugz.py``.
- cost model — static XLA ``cost_analysis``/``memory_analysis`` capture
  per compiled executable plus roofline attribution against the
  per-TPU-generation peaks in :mod:`raft_tpu.utils.arch`
  (:mod:`raft_tpu.observability.costmodel`); :class:`Profiler` is the
  ``res.profiler`` resource slot and :func:`roofline_report` the
  per-primitive %%-of-roofline summary
  (:mod:`raft_tpu.observability.profiler`).

Disabled globally when env ``RAFT_TPU_DISABLE_TRACING`` is set (the same
switch ``core/nvtx.py`` honors): ``instrument`` then returns functions
undecorated and the registry records nothing.

Examples
--------
>>> from raft_tpu.observability import MetricsRegistry
>>> from raft_tpu.observability.exporters import export_prometheus
>>> reg = MetricsRegistry()
>>> reg.counter("demo_total", {"kind": "x"}).inc(3)
>>> print(export_prometheus(reg), end="")
# TYPE demo_total counter
demo_total{kind="x"} 3
"""

from raft_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NULL_METRIC,
    DEFAULT_TIME_BUCKETS,
    COMPILE_TIME_BUCKETS,
    get_registry,
    set_registry,
    enable,
    disable,
    percentile,
    tracing_enabled,
)
from raft_tpu.observability.flight import (
    FlightRecorder,
    KNOWN_EVENT_KINDS,
    NULL_FLIGHT,
    disable_flight,
    enable_flight,
    flight_enabled,
    get_flight_recorder,
    post_mortem,
    set_flight_recorder,
)
from raft_tpu.observability.timeline import (
    DRIFT_BAND,
    DriftLedger,
    emit_marker,
    get_drift_ledger,
    record_drift,
    set_drift_ledger,
)
from raft_tpu.observability.spans import (
    instrument,
    span,
    tree_nbytes,
)
from raft_tpu.observability.hooks import (
    record_alloc,
    record_benchmark,
    record_cache,
    record_collective,
    record_free,
)
from raft_tpu.observability.exporters import (
    bench_results,
    export_jsonl,
    export_perfetto,
    export_prometheus,
    summary_table,
)
from raft_tpu.observability.costmodel import (
    CostRecord,
    RooflineEstimate,
    choose_merge_strategy,
    classify,
    extract_cost,
    ici_time_model,
    ici_traffic_model,
    roofline,
    roofline_report,
)
from raft_tpu.observability.profiler import (
    Profiler,
    get_profiler,
    set_profiler,
)
from raft_tpu.observability.quality import (
    ShadowSampler,
    quality_block,
    quality_enabled,
    recall_at_k,
    record_certificate,
    record_pending,
)
from raft_tpu.observability.explain import (
    clear_records,
    explain_records,
)
from raft_tpu.observability.slo import (
    BurnWindow,
    SloEngine,
    SloObjective,
    default_objectives,
)
from raft_tpu.observability.windows import MetricWindows
from raft_tpu.observability.blackbox import (
    BlackBox,
    reconstruct,
)
from raft_tpu.observability.watchdog import (
    Watchdog,
    dump_stacks,
    format_stacks,
)


def reset() -> None:
    """Clear the process-global registry (metrics AND events), the
    flight-recorder ring, and any pending (undrained) quality
    records."""
    from raft_tpu.observability import quality as _quality

    get_registry().reset()
    get_flight_recorder().clear()
    _quality.clear()


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NULL_METRIC",
    "DEFAULT_TIME_BUCKETS",
    "COMPILE_TIME_BUCKETS",
    "FlightRecorder",
    "KNOWN_EVENT_KINDS",
    "NULL_FLIGHT",
    "get_flight_recorder",
    "set_flight_recorder",
    "enable_flight",
    "disable_flight",
    "flight_enabled",
    "post_mortem",
    "DRIFT_BAND",
    "DriftLedger",
    "emit_marker",
    "get_drift_ledger",
    "set_drift_ledger",
    "record_drift",
    "export_perfetto",
    "get_registry",
    "set_registry",
    "enable",
    "disable",
    "tracing_enabled",
    "instrument",
    "span",
    "tree_nbytes",
    "record_alloc",
    "record_benchmark",
    "record_cache",
    "record_collective",
    "record_free",
    "bench_results",
    "export_jsonl",
    "export_prometheus",
    "summary_table",
    "reset",
    "CostRecord",
    "RooflineEstimate",
    "choose_merge_strategy",
    "ici_time_model",
    "ici_traffic_model",
    "classify",
    "extract_cost",
    "roofline",
    "roofline_report",
    "Profiler",
    "get_profiler",
    "set_profiler",
    "percentile",
    "ShadowSampler",
    "quality_block",
    "quality_enabled",
    "recall_at_k",
    "record_certificate",
    "record_pending",
    "explain_records",
    "clear_records",
    "MetricWindows",
    "BurnWindow",
    "SloEngine",
    "SloObjective",
    "default_objectives",
    "BlackBox",
    "reconstruct",
    "Watchdog",
    "dump_stacks",
    "format_stacks",
]
