"""Span tracing layered on the :mod:`raft_tpu.core.nvtx` range stack.

A *span* is an nvtx range that also reports into the metrics registry:
call count, dispatch wall time, bytes in/out — attributed to the
innermost ENCLOSING range at entry, exactly the way
``core.memory.ResourceMonitor`` attributes its memory samples. The span
itself is pushed as an nvtx range, so nested instrumented primitives
attribute to their caller's span (``distance.knn`` shows up as the
``range`` label of the ``matrix.select_k`` spans it triggers).

Timing semantics — *dispatch* vs *execute*: on an async runtime a
Python-side timer brackets trace+dispatch, not device execution (and
under ``jit`` tracing it runs once, at trace time). Span timings are
therefore exported as ``raft_tpu_span_seconds`` (dispatch wall time,
honest for eager callers, trace-time for jitted ones) while *execute*
time flows through :meth:`raft_tpu.benchmark.Fixture.run`, which forces
completion and subtracts the transport RTT via its probe, and emits
``raft_tpu_benchmark_seconds`` through the same registry.

Disabled contract (``RAFT_TPU_DISABLE_TRACING``): ``instrument`` applied
in a disabled process returns the function UNCHANGED — zero overhead, no
wrapper frame. A runtime :func:`raft_tpu.observability.disable` leaves
the wrapper in place but short-circuits after one boolean attribute
check.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Callable, Iterator, Optional

import jax
import numpy as np

from raft_tpu.core import nvtx
from raft_tpu.observability.metrics import ENV_DISABLED, get_registry
from raft_tpu.observability.timeline import emit_span

SPAN_CALLS = "raft_tpu_span_calls_total"
SPAN_ERRORS = "raft_tpu_span_errors_total"
SPAN_SECONDS = "raft_tpu_span_seconds"
SPAN_BYTES_IN = "raft_tpu_span_bytes_in_total"
SPAN_BYTES_OUT = "raft_tpu_span_bytes_out_total"


def tree_nbytes(tree) -> int:
    """Total array payload bytes in a pytree. Non-array leaves (handles,
    scalars, strings) contribute 0; tracers report their aval size, so
    byte accounting stays correct under jit tracing."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = getattr(leaf, "nbytes", None)
        if isinstance(n, (int, np.integer)):
            total += int(n)
    return total


def _record(name: str, parent: str, seconds: float, bytes_in: int,
            bytes_out: int, error: bool) -> None:
    emit_span(name, parent, seconds, bytes_in, bytes_out, error,
              stack=nvtx.range_stack())
    reg = get_registry()
    labels = {"span": name, "range": parent}
    reg.counter(SPAN_CALLS, labels,
                help="Instrumented-span invocations").inc()
    if error:
        reg.counter(SPAN_ERRORS, labels,
                    help="Spans that exited with an exception").inc()
    reg.histogram(SPAN_SECONDS, labels,
                  help="Span dispatch wall time (seconds; trace-time "
                       "under jit)").observe(seconds)
    if bytes_in:
        reg.counter(SPAN_BYTES_IN, labels,
                    help="Array bytes entering the span").inc(bytes_in)
    if bytes_out:
        reg.counter(SPAN_BYTES_OUT, labels,
                    help="Array bytes produced by the span").inc(bytes_out)
    reg.emit({"type": "span", "span": name, "range": parent,
              "seconds": seconds, "bytes_in": bytes_in,
              "bytes_out": bytes_out, "error": error})


@contextlib.contextmanager
def span(name: str) -> Iterator[None]:
    """Scoped span: an ``nvtx.annotate`` range that also records call
    count and wall time, attributed to the enclosing range."""
    if not get_registry().enabled:
        yield
        return
    parent = nvtx.current_range() or ""
    t0 = time.perf_counter()
    error = False
    try:
        with nvtx.annotate(name):
            yield
    except BaseException:
        error = True
        raise
    finally:
        _record(name, parent, time.perf_counter() - t0, 0, 0, error)


def instrument(name: Optional[str] = None) -> Callable:
    """Decorator marking a hot-path primitive for observation.

    Records per call: ``raft_tpu_span_calls_total``, dispatch wall time
    into ``raft_tpu_span_seconds``, array bytes in/out, plus a span
    event — all labeled ``{span=<name>, range=<enclosing range>}``.
    ``tools/check_instrumented.py`` statically asserts the hot-path
    modules apply this decorator.
    """

    def decorate(fn: Callable) -> Callable:
        span_name = name or f"{fn.__module__}.{fn.__qualname__}"
        if ENV_DISABLED:
            # the documented near-zero-overhead contract: no wrapper at all
            fn.__instrumented__ = span_name
            return fn

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not get_registry().enabled:
                return fn(*args, **kwargs)
            parent = nvtx.current_range() or ""
            bytes_in = tree_nbytes((args, kwargs))
            t0 = time.perf_counter()
            error = False
            try:
                with nvtx.annotate(span_name):
                    out = fn(*args, **kwargs)
            except BaseException:
                error = True
                raise
            finally:
                if error:
                    _record(span_name, parent, time.perf_counter() - t0,
                            bytes_in, 0, True)
            _record(span_name, parent, time.perf_counter() - t0,
                    bytes_in, tree_nbytes(out), False)
            return out

        wrapper.__instrumented__ = span_name
        return wrapper

    return decorate
