"""Flight recorder: a process-wide ring buffer of typed timeline events.

The metrics registry (PR 1) answers "how much" and the cost model (PR 2)
"how much SHOULD it be" — neither answers "WHEN". A deadline-exceeded
sharded query or a mis-overlapped micro-batch schedule cannot be
reconstructed from counters: you need the fault injection, the retry,
the degradation rung, the merge collectives and the compile events in
time order. (ref: the reference fills this role on GPU with NVTX ranges
+ the range-attributed ``resource_monitor`` timeline viewed in nsys;
here the viewer is Perfetto/chrome://tracing via
:func:`raft_tpu.observability.exporters.export_perfetto`.)

Design (the ``MetricsRegistry`` contract, applied to a timeline):

- **One process-wide recorder** (:func:`get_flight_recorder`), a
  lock-guarded fixed-capacity ring (``collections.deque(maxlen=N)``;
  env ``RAFT_TPU_FLIGHT_EVENTS``, default 4096). Old events fall off
  the back; ``dropped`` counts them so a dump is honest about what it
  no longer holds.
- **Typed events**: every event carries a ``kind`` from
  :data:`KNOWN_EVENT_KINDS`, a ``name``, a MONOTONIC timestamp
  (``time.perf_counter`` — orderable within the process, immune to
  wall-clock steps), a Chrome-trace phase (``ph``: ``"X"`` complete
  with ``dur``, ``"i"`` instant), a ``lane`` (thread, device or shard
  attribution — the Perfetto ``tid``) and the nvtx range ``stack`` at
  emit time. The emit helpers live in
  :mod:`raft_tpu.observability.timeline`; call sites never build raw
  dicts.
- **Zero-overhead disabled mode**: ``RAFT_TPU_DISABLE_TRACING`` (the
  one switch shared with nvtx/metrics) or :func:`disable_flight`
  leaves every ``record()`` as ONE boolean test — no event dict is
  allocated, the ring stays untouched. The timeline helpers check the
  same boolean before computing any event field.
- **Post-mortem dumps**: when ``RAFT_TPU_FLIGHT_DIR`` is set,
  :func:`post_mortem` writes the ring as Perfetto JSON. It is invoked
  automatically when :func:`raft_tpu.core.error.classify_xla_error`
  classifies a device failure and when a
  :func:`raft_tpu.resilience.deadline` scope fires (the
  ``DeadlineExceededError`` raise in ``interruptible.yield_``), capped
  at ``RAFT_TPU_FLIGHT_MAX_DUMPS`` (default 16) per process so a retry
  storm cannot fill a disk. ``DeviceError``/``DeadlineExceededError``
  additionally carry the last-:data:`TAIL_EVENTS` events in their
  ``flight_tail`` payload, like the span stack today.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

#: the closed vocabulary of timeline event kinds. tools/check_instrumented
#: .py's EVENT_SITES gate (EMITTER_KINDS) is pinned consistent with this
#: tuple by tests/test_flight.py — a new kind ships with its static gate.
KNOWN_EVENT_KINDS = (
    "span",          # instrumented-call complete events (begin+dur)
    "collective",    # comms collectives with per-shard payload bytes
    "compile",       # CompileCache miss/hit + AOT compile wall time
    "dispatch",      # AOT executable dispatch
    "fault",         # injected faults (resilience.faults)
    "retry",         # bounded-retry attempts (resilience.policy)
    "degradation",   # graceful-degradation ladder rungs
    "deadline",      # deadline scopes armed / fired
    "error",         # classified device errors
    "benchmark",     # Fixture.run results
    "drift",         # model-vs-measured drift ledger records
    "marker",        # free-form instants (benchmark phases etc.)
    "serving",       # serving engine: enqueue/flush/shed/swap/warmup
    "quality",       # certificate failures / fixups / q8 reruns
    "flow",          # per-request Perfetto flow points (ph s/t/f)
    "mutation",      # mutable-index write-ahead stream: upsert/delete/
    #                  compact_start/compact_swap (raft_tpu.mutable)
    "explain",       # per-query explain records (observability.explain)
    "alert",         # SLO burn-rate alerts firing/resolving
    #                  (observability.slo)
    "stall",         # hang-watchdog stall detections
    #                  (observability.watchdog)
    "epilogue",      # clean-shutdown marker the blackbox appends last
    #                  (observability.blackbox)
)

#: events attached to DeviceError/DeadlineExceededError payloads
TAIL_EVENTS = 64

DEFAULT_CAPACITY = 4096

FLIGHT_EVENTS_TOTAL = "raft_tpu_flight_events_total"

#: ring evictions surfaced to the registry by :func:`sync_dropped_metric`
#: — truncated evidence must be visible before anyone trusts a dump
FLIGHT_DROPPED = "raft_tpu_flight_dropped_total"

#: crash-durable mirror (an observability.blackbox.BlackBox, installed
#: by blackbox.install()) — None is the disabled state, and the cost of
#: disabled is exactly one module-attribute read + None test per event.
_mirror = None


def _env_capacity() -> int:
    try:
        n = int(os.environ.get("RAFT_TPU_FLIGHT_EVENTS", DEFAULT_CAPACITY))
        return max(16, n)
    except (TypeError, ValueError):
        return DEFAULT_CAPACITY


class FlightRecorder:
    """Lock-guarded fixed-capacity ring of typed timeline events.

    ``enabled`` is the hot-path switch: ``record()`` on a disabled
    recorder returns after one boolean test, allocating nothing. The
    ring itself is a ``deque(maxlen=capacity)`` — append past capacity
    evicts the oldest event (wraparound), counted in ``dropped``.
    """

    def __init__(self, capacity: Optional[int] = None,
                 enabled: bool = True):
        self.enabled = enabled
        self.capacity = capacity if capacity else _env_capacity()
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._seq = 0          # total events ever recorded

    # -- emit -------------------------------------------------------------
    def record(self, kind: str, name: str, ts: Optional[float] = None,
               dur: float = 0.0, ph: str = "i",
               lane: Optional[str] = None,
               stack: Optional[List[str]] = None, **args) -> None:
        """Append one event. ``ts`` is the event's BEGIN time on the
        ``time.perf_counter`` clock (stamped now if omitted); ``dur``
        seconds for ``ph="X"`` complete events. Never raises."""
        if not self.enabled:
            return
        ev: Dict = {"kind": kind, "name": name,
                    "ts": time.perf_counter() if ts is None else ts,
                    "ph": ph,
                    "lane": lane if lane is not None
                    else threading.current_thread().name}
        if dur:
            ev["dur"] = dur
        if stack:
            ev["stack"] = list(stack)
        if args:
            ev.update(args)
        with self._lock:
            self._seq += 1
            self._ring.append(ev)
        # crash-durable mirror, outside the ring lock: the blackbox
        # serializes internally and its append never raises
        bb = _mirror
        if bb is not None:
            bb.append_event(ev)

    # -- queries ----------------------------------------------------------
    def events(self) -> List[Dict]:
        """Snapshot of the ring, oldest first (copies of the dicts so a
        caller cannot mutate recorded history)."""
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def tail(self, n: int = TAIL_EVENTS) -> List[Dict]:
        """The newest ``n`` events, oldest-of-the-tail first."""
        with self._lock:
            if n >= len(self._ring):
                return [dict(ev) for ev in self._ring]
            return [dict(ev) for ev in
                    list(self._ring)[len(self._ring) - n:]]

    @property
    def dropped(self) -> int:
        """Events evicted by wraparound since the last clear()."""
        with self._lock:
            return self._seq - len(self._ring)

    @property
    def seq(self) -> int:
        """Total events ever recorded (monotonic; survives wraparound)."""
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0


#: shared do-nothing recorder — what a disabled process records into.
#: One object, never replaced: the disabled fast path is one boolean.
NULL_FLIGHT = FlightRecorder(capacity=16, enabled=False)

# RAFT_TPU_DISABLE_TRACING is the one switch shared with core/nvtx.py and
# the metrics registry: set, it disables ranges, spans, metrics AND the
# flight recorder (the "--no-nvtx build").
_ENV_DISABLED = bool(os.environ.get("RAFT_TPU_DISABLE_TRACING"))

_global_recorder = NULL_FLIGHT if _ENV_DISABLED else FlightRecorder()
_global_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    """The process-global recorder every timeline helper emits into."""
    return _global_recorder


def set_flight_recorder(recorder: FlightRecorder) -> FlightRecorder:
    """Swap the process-global recorder (tests). Returns the previous."""
    global _global_recorder
    with _global_lock:
        prev, _global_recorder = _global_recorder, recorder
        return prev


def enable_flight() -> None:
    """Runtime re-enable (a process started with
    RAFT_TPU_DISABLE_TRACING keeps the shared null recorder — swap in a
    real one with :func:`set_flight_recorder` if you truly want both)."""
    _global_recorder.enabled = _global_recorder is not NULL_FLIGHT


def disable_flight() -> None:
    """Runtime disable: record() becomes a one-boolean no-op."""
    _global_recorder.enabled = False


def flight_enabled() -> bool:
    return _global_recorder.enabled


# ---------------------------------------------------------------- dumps
_dump_lock = threading.Lock()
_dump_count = 0


def flight_dir() -> Optional[str]:
    """The post-mortem dump directory, or None when dumps are off."""
    d = os.environ.get("RAFT_TPU_FLIGHT_DIR", "").strip()
    return d or None


def _max_dumps() -> int:
    try:
        return int(os.environ.get("RAFT_TPU_FLIGHT_MAX_DUMPS", "16"))
    except (TypeError, ValueError):
        return 16


def post_mortem(trigger: str, error: Optional[BaseException] = None,
                directory: Optional[str] = None) -> Optional[str]:
    """Dump the ring as Perfetto JSON for post-mortem analysis.

    Writes ``flight_<pid>_<seq>_<trigger>.json`` into ``directory`` (or
    ``RAFT_TPU_FLIGHT_DIR``; no-op returning None when neither is set,
    when the recorder is disabled/empty, or past the per-process dump
    cap). The file is the standard Chrome trace-event object — open it
    at https://ui.perfetto.dev — with a ``raft_tpu`` metadata section
    recording the trigger, the error and the drop count. NEVER raises:
    a failed dump must not mask the error being diagnosed."""
    global _dump_count
    try:
        rec = get_flight_recorder()
        out_dir = directory or flight_dir()
        if out_dir is None or not rec.enabled or not len(rec):
            return None
        with _dump_lock:
            if _dump_count >= _max_dumps():
                return None
            _dump_count += 1
            n = _dump_count
        from raft_tpu.observability.exporters import export_perfetto

        trace = export_perfetto(rec)
        trace["raft_tpu"] = {
            "trigger": trigger,
            "error": f"{type(error).__name__}: {error}"[:500]
            if error is not None else None,
            "dropped_events": rec.dropped,
            "wallclock": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
        }
        os.makedirs(out_dir, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "-_." else "-"
                       for c in trigger)[:64]
        path = os.path.join(
            out_dir, f"flight_{os.getpid()}_{n:03d}_{safe}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(trace, f, indent=1, default=str)
            f.write("\n")
        os.replace(tmp, path)
        return path
    except Exception:
        return None


_dropped_sync_lock = threading.Lock()
_dropped_exported = 0


def sync_dropped_metric(recorder: Optional[FlightRecorder] = None) -> int:
    """Fold ring evictions since the last sync into the monotone
    :data:`FLIGHT_DROPPED` counter; returns the recorder's current
    ``dropped`` count. Called from /statusz renders, watchdog ticks and
    blackbox snapshots — cheap (two lock-guarded reads) and never
    raises past the registry. A ``clear()`` (which resets ``dropped``)
    only rebaselines: the counter never decrements."""
    rec = recorder if recorder is not None else _global_recorder
    dropped = rec.dropped
    global _dropped_exported
    with _dropped_sync_lock:
        delta = dropped - _dropped_exported
        _dropped_exported = dropped
    if delta > 0:
        try:
            from raft_tpu.observability.metrics import get_registry

            get_registry().counter(
                FLIGHT_DROPPED,
                help="Flight-recorder events evicted by ring wraparound",
            ).inc(delta)
        except Exception:
            pass
    return dropped


def error_tail() -> List[Dict]:
    """The last-:data:`TAIL_EVENTS` events, for attaching to a
    classified error's payload ([] when disabled — no allocation on the
    disabled path). Never raises."""
    try:
        rec = get_flight_recorder()
        if not rec.enabled:
            return []
        return rec.tail(TAIL_EVENTS)
    except Exception:
        return []
