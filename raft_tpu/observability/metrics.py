"""Metrics substrate: counters, gauges, fixed-bucket histograms.

The TPU-native rendering of the observability the reference scatters
across NVTX (core/nvtx.hpp), rapids_logger, and the range-attributed
``resource_monitor`` (mr/resource_monitor.hpp): one process-wide
:class:`MetricsRegistry` every layer reports into, exported by
:mod:`raft_tpu.observability.exporters`.

Design constraints (why this is not just ``prometheus_client``):

- **Cheap enough to leave on.** Metric handles are get-or-create by
  ``(name, labels)``; the hot path after creation is one lock-guarded
  float add. Callers that run per-dispatch cache their handles.
- **A disabled mode that is a no-op attribute lookup.** When the
  registry is disabled (``RAFT_TPU_DISABLE_TRACING``, or
  :func:`disable`), ``counter()``/``gauge()``/``histogram()`` return the
  shared :data:`NULL_METRIC` whose methods do nothing and which never
  creates a registry entry — the same contract ``core/nvtx.py``
  documents for ranges.
- **Thread-safe.** Registry creation and every metric mutation hold a
  lock; the ``ResourceMonitor`` sampling thread and user threads can
  report concurrently.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

LabelDict = Optional[Dict[str, str]]
_LabelKey = Tuple[Tuple[str, str], ...]

# Fixed default buckets for wall-time histograms: 1 µs .. 30 s. NOTE the
# 30 s ceiling: anything slower lands only in the (always-emitted)
# cumulative ``le="+Inf"`` bucket, losing resolution — and a cold
# north-star compile has been observed to exceed 30 s. Compile-time
# histograms must use COMPILE_TIME_BUCKETS instead.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    1e-6, 1e-5, 1e-4, 1e-3, 3e-3, 1e-2, 3e-2, 1e-1, 3e-1, 1.0, 3.0, 10.0, 30.0,
)

# Compile-time preset: same decade ladder, extended to 300 s so cold
# AOT/north-star compiles (minutes, not seconds) keep bucket resolution
# instead of piling into +Inf. Used by runtime.entry_points._aot_call's
# raft_tpu_compile_seconds histogram.
COMPILE_TIME_BUCKETS: Tuple[float, ...] = (
    1e-3, 1e-2, 1e-1, 3e-1, 1.0, 3.0, 10.0, 30.0, 100.0, 300.0,
)


def _label_key(labels: LabelDict) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def percentile(values: Iterable[float], q: float) -> float:
    """Linear-interpolated percentile of raw samples (numpy's default
    "linear" method: rank = (n−1)·q/100, interpolate between the two
    neighboring order statistics).

    THE shared implementation: ``ServingEngine.snapshot_stats`` and the
    benchmarks use this instead of the old ``min(len−1, int(n·0.99))``
    index pick (which reported the 99.6th percentile at n=250 and the
    max at n<100). ``tools/bench_report.py`` carries a mirror (that
    tool stays raft_tpu-import-free); tests pin the two equal."""
    vs = sorted(float(v) for v in values)
    if not vs:
        raise ValueError("percentile: empty sample set")
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile: q={q} outside [0, 100]")
    if len(vs) == 1:
        return vs[0]
    rank = (len(vs) - 1) * (q / 100.0)
    lo = int(math.floor(rank))
    hi = min(lo + 1, len(vs) - 1)
    frac = rank - lo
    return vs[lo] + (vs[hi] - vs[lo]) * frac


class Counter:
    """Monotonically increasing value. (Prometheus counter semantics.)"""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: LabelDict = None):
        self.name = name
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Value that can go up and down."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: LabelDict = None):
        self.name = name
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus ``le`` semantics).

    ``buckets`` are the finite upper bounds; an implicit ``+Inf`` bucket
    always exists, so ``bucket_counts`` has ``len(buckets) + 1`` entries
    and the last one equals ``count``.
    """

    __slots__ = ("name", "labels", "buckets", "_lock", "_bucket_counts",
                 "_sum", "_count")

    def __init__(self, name: str, labels: LabelDict = None,
                 buckets: Iterable[float] = DEFAULT_TIME_BUCKETS):
        self.name = name
        self.labels = dict(labels or {})
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name}: needs at least one bucket")
        self._lock = threading.Lock()
        self._bucket_counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        # linear scan: bucket lists are short (≤ ~16) and the scan is
        # branch-predictable; bisect would pay more in call overhead
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if value <= b:
                idx = i
                break
        with self._lock:
            self._bucket_counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> List[int]:
        """Per-bucket (non-cumulative) counts, +Inf bucket last."""
        with self._lock:
            return list(self._bucket_counts)

    def cumulative_counts(self) -> List[int]:
        """Cumulative counts per ``le`` bound, +Inf last (== count)."""
        with self._lock:
            out, acc = [], 0
            for c in self._bucket_counts:
                acc += c
                out.append(acc)
            return out

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-th percentile from the bucket counts (the
        ``histogram_quantile`` method, via :func:`bucket_percentile`).

        Interpolation contract (pinned against :func:`percentile` on
        raw samples by tests/test_observability.py):

        - the target rank is ``(q/100)·count``; the answer is a linear
          interpolation inside the first bucket whose CUMULATIVE count
          reaches it — the estimate is therefore exact only up to one
          bucket width (a single populated bucket ``(lo, b]`` reports
          a point inside ``[lo, b]``, not the sample's true value);
        - the first bucket's lower edge is 0 for non-negative bounds
          (``min(0, b0)`` otherwise);
        - observations past the last finite bound (the ``+Inf``
          overflow bucket) clamp to that bound — an all-in-+Inf
          histogram reports ``buckets[-1]`` for every q;
        - None when empty — exporters render a dash instead of a fake
          zero."""
        cum = self.cumulative_counts()
        return bucket_percentile(self.buckets, cum, q)


def bucket_percentile(buckets: Tuple[float, ...], cumulative: List[int],
                      q: float) -> Optional[float]:
    """The ``histogram_quantile`` interpolation over explicit bucket
    state: ``buckets`` are the finite upper bounds, ``cumulative`` the
    cumulative counts per bound with the ``+Inf`` entry LAST (length
    ``len(buckets) + 1``). Shared by :meth:`Histogram.percentile` (live
    totals) and :mod:`raft_tpu.observability.windows` (windowed count
    DELTAS — the same math over a snapshot difference). See the method
    docstring for the full interpolation contract."""
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile: q={q} outside [0, 100]")
    total = cumulative[-1]
    if total == 0:
        return None
    rank = (q / 100.0) * total
    for i, b in enumerate(buckets):
        if cumulative[i] >= rank:
            lo = (buckets[i - 1] if i > 0 else min(0.0, b))
            prev = cumulative[i - 1] if i > 0 else 0
            in_bucket = cumulative[i] - prev
            frac = ((rank - prev) / in_bucket) if in_bucket else 1.0
            return lo + (b - lo) * frac
    return buckets[-1]   # +Inf bucket: clamp to the last finite bound


class _NullMetric:
    """Shared do-nothing metric returned by a disabled registry.

    Every mutating method of Counter/Gauge/Histogram exists here as a
    no-op, so call sites never branch on enablement — the disabled fast
    path is one attribute lookup plus an empty call.
    """

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    @property
    def value(self) -> float:
        return 0.0

    @property
    def count(self) -> int:
        return 0

    @property
    def sum(self) -> float:
        return 0.0

    def percentile(self, q: float) -> Optional[float]:
        return None


NULL_METRIC = _NullMetric()

_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Process-wide (or test-local) store of named metrics + event log.

    Metrics are keyed by ``(name, labels)``; ``name`` is bound to one
    kind (counter/gauge/histogram) at first creation and a kind
    collision raises. The event log is a bounded deque of dicts — the
    substrate of the JSON-lines exporter (span ends, benchmark results).
    """

    def __init__(self, enabled: bool = True, max_events: int = 4096):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, _LabelKey], object] = {}
        self._kinds: Dict[str, str] = {}
        self._help: Dict[str, str] = {}
        self.events: collections.deque = collections.deque(maxlen=max_events)

    # -- get-or-create ----------------------------------------------------
    def _get(self, kind: str, name: str, labels: LabelDict, help: str = "",
             **kw):
        if not self.enabled:
            return NULL_METRIC
        key = (name, _label_key(labels))
        with self._lock:
            bound = self._kinds.get(name)
            if bound is not None and bound != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {bound}, "
                    f"requested {kind}")
            metric = self._metrics.get(key)
            if metric is None:
                metric = _KINDS[kind](name, labels, **kw)
                self._metrics[key] = metric
                self._kinds[name] = kind
                if help:
                    self._help.setdefault(name, help)
            return metric

    def counter(self, name: str, labels: LabelDict = None,
                help: str = "") -> Counter:
        return self._get("counter", name, labels, help)

    def gauge(self, name: str, labels: LabelDict = None,
              help: str = "") -> Gauge:
        return self._get("gauge", name, labels, help)

    def histogram(self, name: str, labels: LabelDict = None, help: str = "",
                  buckets: Iterable[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
        return self._get("histogram", name, labels, help, buckets=buckets)

    # -- events -----------------------------------------------------------
    def emit(self, event: Dict) -> None:
        """Append an event (a JSON-serializable dict) to the bounded log;
        a ``ts`` wall-clock field is stamped if absent."""
        if not self.enabled:
            return
        event.setdefault("ts", time.time())
        self.events.append(event)

    # -- introspection ----------------------------------------------------
    def kind_of(self, name: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(name)

    def help_of(self, name: str) -> str:
        with self._lock:
            return self._help.get(name, "")

    def collect(self) -> List[object]:
        """Stable-ordered snapshot of all live metrics (by name, then
        label key) — the exporters' single entry point."""
        with self._lock:
            items = sorted(self._metrics.items(), key=lambda kv: kv[0])
            return [m for _, m in items]

    def __len__(self) -> int:
        with self._lock:
            return len(self._metrics)

    def reset(self) -> None:
        """Drop every metric and event (tests; long-running re-baselining)."""
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()
            self._help.clear()
        self.events.clear()


def validate_buckets(buckets: Iterable[float]) -> Tuple[float, ...]:
    """Sorted finite bucket bounds or raise — shared by callers that
    accept user-provided bucket lists."""
    bs = tuple(sorted(float(b) for b in buckets))
    if not bs or any(not math.isfinite(b) for b in bs):
        raise ValueError("buckets must be a non-empty list of finite bounds")
    return bs


# -- the process-global registry -----------------------------------------
# RAFT_TPU_DISABLE_TRACING is the one switch shared with core/nvtx.py: set,
# it disables ranges, spans, AND metrics (the "--no-nvtx build").
ENV_DISABLED = bool(os.environ.get("RAFT_TPU_DISABLE_TRACING"))

_global_registry = MetricsRegistry(enabled=not ENV_DISABLED)
_global_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global registry every built-in hook reports into."""
    return _global_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests; multi-tenant embedding).
    Returns the previous one."""
    global _global_registry
    with _global_lock:
        prev, _global_registry = _global_registry, registry
        return prev


def enable() -> None:
    """Runtime re-enable (no effect on already-decorated functions if the
    process started with RAFT_TPU_DISABLE_TRACING — those compiled to the
    bare function; see spans.instrument)."""
    _global_registry.enabled = True


def disable() -> None:
    """Runtime disable: hooks fall through to NULL_METRIC no-ops and new
    registry entries stop appearing."""
    _global_registry.enabled = False


def tracing_enabled() -> bool:
    return _global_registry.enabled
