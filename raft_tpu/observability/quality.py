"""Quality-of-results telemetry (ISSUE 10 tentpole).

The registry (PR 1) sees *performance* and the flight recorder (PR 6)
sees *when* — neither sees whether the ANSWERS are still right. This
module makes result quality a first-class telemetry plane:

- **Certificate / fixup counters** — every certified result path
  (``distance.knn_fused``, ``distance.knn_sharded``, the IVF q8 rescore
  in ``ann.ivf_flat``, the ``runtime.knn_query`` AOT serving entry)
  reports how many queries it checked, how many failed the twin-pool
  certificate (and therefore paid the exact fixup), which static fixup
  tier absorbed them, and how wide its exact-rescore pool was. ROADMAP
  item 2 needs exactly this evidence ("production fixup-rate") before
  per-query Eq tightening can be justified; until now the failure count
  lived only inside the jitted program.
- **Deferred host-side recording** — the failure count is a traced
  scalar. :func:`record_pending` keeps the DEVICE value in a bounded
  queue (no host sync on the dispatch path — async dispatch semantics
  are untouched); :func:`drain` resolves the pending scalars the next
  time anyone looks (``statusz``, ``Fixture.run``, an artifact writer,
  ``quality_block``) — by then the program has long completed, so the
  conversion costs one buffer read, zero traced-program time. Paths
  that already sync host-side (the IVF q8 certificate-failure rerun)
  record directly via :func:`record_certificate`.
- **Online recall shadow-sampling** — :class:`ShadowSampler` re-runs a
  configurable fraction of LIVE serving requests against a brute-force
  oracle on a background thread (off the hot path), maintains a rolling
  ``recall@k`` gauge, and emits a ``drift`` flight event + breach
  counter when the rolling recall drops below a floor: the online
  counterpart of ``bench_report --check``'s offline ANN recall gate. A
  bad ``RAFT_TPU_ANN_NPROBES`` setting or a corrupted index swap now
  shows up in minutes, not at the next offline benchmark round.

Env knobs (README "Quality telemetry & request tracing"):

- ``RAFT_TPU_SERVING_SHADOW_FRAC``  — fraction of live requests shadow
  sampled (default 0 = off; the serving engine reads it at start()).
- ``RAFT_TPU_SERVING_SHADOW_FLOOR`` — rolling-recall floor below which
  the sampler emits a ``drift`` flight event (default 0.95 — the same
  floor the offline ANN gate enforces).
- ``RAFT_TPU_DISABLE_QUALITY``      — turn the quality plane off
  without touching the rest of tracing (``RAFT_TPU_DISABLE_TRACING``
  disables it too, like every other observability surface).
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.observability.metrics import get_registry, tracing_enabled

# ---- the quality slice of the metric vocabulary -----------------------
#: queries whose certificate was evaluated, per site
CERT_CHECKS = "raft_tpu_certificate_checks_total"
#: queries that FAILED the certificate and paid the exact fixup
CERT_FIXUPS = "raft_tpu_certificate_fixups_total"
#: fixup batch sizes — the static tier (16/128/512/1024 or the full
#: fallback) that absorbed each nonzero failure batch
FIXUP_ROWS = "raft_tpu_certificate_fixup_rows"
#: exact-rescore candidate-pool widths (C = k + pad clamped to the pool)
RESCORE_POOL = "raft_tpu_rescore_pool_width"
#: IVF chunks whose q8 certificate failure forced an exact f32-scan rerun
IVF_RERUNS = "raft_tpu_ivf_cert_rerun_total"
#: record_pending calls skipped because they executed under tracing
#: (n_fail was a Tracer — see the guard in record_pending)
TRACE_SKIPS = "raft_tpu_certificate_trace_skips_total"
#: per-rung outcomes of the PQ certification ladder
#: (rung ∈ certified / widened / exact_rerun)
PQ_RUNGS = "raft_tpu_pq_cert_rung_total"
#: running per-site fraction of PQ queries that escalated past the
#: widen rungs to the full exact rerun (the BENCH_ANN cert_rerun_frac,
#: live)
PQ_RERUN_FRAC = "raft_tpu_pq_cert_rerun_frac"
#: shadow-sampled requests re-scored against the oracle
SHADOW_SAMPLES = "raft_tpu_serving_shadow_samples_total"
#: shadow candidates dropped because the sampler queue was full
SHADOW_DROPPED = "raft_tpu_serving_shadow_dropped_total"
#: rolling recall@k of shadow-sampled responses vs the oracle
SHADOW_RECALL = "raft_tpu_serving_shadow_recall"
#: rolling-recall drops below the floor (each emits a drift event)
SHADOW_BREACHES = "raft_tpu_serving_shadow_breaches_total"

#: power-of-two-ish count buckets for fixup batch sizes / pool widths
#: (DEFAULT_TIME_BUCKETS are seconds — wrong unit for row counts)
COUNT_BUCKETS: Tuple[float, ...] = (
    1., 2., 4., 8., 16., 32., 64., 128., 256., 512., 1024., 2048., 4096.)

DEFAULT_SHADOW_FLOOR = 0.95
SHADOW_FRAC_ENV = "RAFT_TPU_SERVING_SHADOW_FRAC"
SHADOW_FLOOR_ENV = "RAFT_TPU_SERVING_SHADOW_FLOOR"


def quality_enabled() -> bool:
    """One switch for the whole quality plane: follows the global
    tracing kill switch, plus its own opt-out."""
    return (tracing_enabled()
            and not os.environ.get("RAFT_TPU_DISABLE_QUALITY"))


def shadow_frac_default() -> float:
    """The env-configured shadow-sampling fraction (0 = off)."""
    try:
        return max(0.0, min(1.0, float(
            os.environ.get(SHADOW_FRAC_ENV, "0") or 0.0)))
    except (TypeError, ValueError):
        return 0.0


def shadow_floor_default() -> float:
    try:
        return float(os.environ.get(SHADOW_FLOOR_ENV,
                                    DEFAULT_SHADOW_FLOOR))
    except (TypeError, ValueError):
        return DEFAULT_SHADOW_FLOOR


# ---------------------------------------------------------- recording
def fixup_tier_for(n_fail: int, fix_tiers: Sequence[int],
                   n_queries: int) -> int:
    """The fixup batch size the tiered cascade dispatched for ``n_fail``
    failures — the HOST mirror of the ``jax.lax.cond`` ladder in
    ``_knn_fused_core`` (smallest eligible tier covering the count,
    else the full fallback over all ``n_queries``)."""
    if n_fail <= 0:
        return 0
    for t in sorted(int(t) for t in fix_tiers):
        if n_fail <= t and t < n_queries:
            return t
    return int(n_queries)


def record_certificate(site: str, n_queries: int, n_fail: int,
                       pool_width: Optional[int] = None,
                       fixup_rows: Optional[int] = None,
                       rerun: bool = False, **meta) -> None:
    """Host-side record of one certificate evaluation batch. Never
    raises into the result path."""
    if not quality_enabled():
        return
    try:
        reg = get_registry()
        labels = {"site": site}
        reg.counter(CERT_CHECKS, labels,
                    help="Queries whose exactness certificate was "
                         "evaluated").inc(max(0, int(n_queries)))
        reg.counter(CERT_FIXUPS, labels,
                    help="Queries that failed the certificate and paid "
                         "the exact fixup").inc(max(0, int(n_fail)))
        if pool_width:
            reg.histogram(RESCORE_POOL, labels,
                          help="Exact-rescore candidate-pool widths",
                          buckets=COUNT_BUCKETS).observe(int(pool_width))
        if fixup_rows:
            reg.histogram(FIXUP_ROWS, labels,
                          help="Static fixup-tier batch sizes "
                               "dispatched for failed queries",
                          buckets=COUNT_BUCKETS).observe(int(fixup_rows))
        if rerun:
            reg.counter(IVF_RERUNS, labels,
                        help="IVF q8 chunks rerun through the exact "
                             "f32 scan after a certificate failure"
                        ).inc()
        if n_fail:
            from raft_tpu.observability.timeline import emit_quality

            emit_quality(site, n_fail=int(n_fail),
                         n_queries=int(n_queries),
                         fixup_rows=fixup_rows, rerun=bool(rerun),
                         **meta)
    except Exception:
        pass


# per-site running PQ rung tallies: site -> [total_queries, exact_reruns]
# — the evidence expected_pq_rerun_frac's MEASURED branch reads
_pq_tally: Dict[str, List[int]] = {}
_pq_lock = threading.Lock()


def record_pq_rungs(site: str, certified: int, widened: int,
                    exact_rerun: int) -> None:
    """Host-side record of one PQ certification-ladder batch: how many
    queries each rung resolved (``certified`` = base ADC pool cleared
    the bound, ``widened`` = a 2x/4x re-ADC pool cleared it,
    ``exact_rerun`` = escalated to the full exact scan). Maintains the
    per-rung counters and the running ``raft_tpu_pq_cert_rerun_frac``
    gauge. Never raises into the result path."""
    if not quality_enabled():
        return
    try:
        total = max(0, int(certified)) + max(0, int(widened)) \
            + max(0, int(exact_rerun))
        if not total:
            return
        with _pq_lock:
            tally = _pq_tally.setdefault(site, [0, 0])
            tally[0] += total
            tally[1] += max(0, int(exact_rerun))
            frac = tally[1] / tally[0]
        reg = get_registry()
        for rung, n in (("certified", certified), ("widened", widened),
                        ("exact_rerun", exact_rerun)):
            if n > 0:
                reg.counter(PQ_RUNGS, {"site": site, "rung": rung},
                            help="PQ queries resolved per "
                                 "certification-ladder rung"
                            ).inc(int(n))
        reg.gauge(PQ_RERUN_FRAC, {"site": site},
                  help="Running fraction of PQ queries escalating to "
                       "the full exact rerun").set(round(frac, 6))
    except Exception:
        pass


def measured_rerun_frac(site: str,
                        min_checks: int = 64) -> Optional[float]:
    """The process-measured exact-rerun fraction at ``site``, or None
    until at least ``min_checks`` queries have walked the ladder —
    the chooser's measured-beats-modeled evidence."""
    with _pq_lock:
        tally = _pq_tally.get(site)
        if tally is None or tally[0] < max(1, int(min_checks)):
            return None
        return tally[1] / tally[0]


# pending certificate stats whose failure count is still a device value:
# (site, n_fail_device, n_queries, pool_width, fix_tiers, meta)
_PENDING_CAP = 4096
_pending: collections.deque = collections.deque(maxlen=_PENDING_CAP)
_pending_lock = threading.Lock()


def record_pending(site: str, n_fail, n_queries: int,
                   pool_width: Optional[int] = None,
                   fix_tiers: Sequence[int] = (), **meta) -> None:
    """Queue certificate stats whose ``n_fail`` is an UNRESOLVED device
    scalar/array — no host sync here, so the dispatch path keeps its
    async semantics; :func:`drain` converts later (the value is a tiny
    output of a program whose results the caller consumes anyway)."""
    if not quality_enabled():
        return
    try:
        from jax.core import Tracer

        if isinstance(n_fail, Tracer):
            # the recorder was reached AT TRACE TIME (a host wrapper
            # traced whole, e.g. knn under fused_l2nn.knn_sharded's
            # shard_map) — a tracer must never enter the pending ring:
            # drain() cannot resolve it and used to drop the entry
            # silently. Count the skip so the gap is visible.
            _count_trace_skip(site)
            return
    except ImportError:       # no jax on this host: nothing traced
        pass
    with _pending_lock:
        _pending.append((site, n_fail, int(n_queries),
                         pool_width, tuple(fix_tiers), dict(meta)))


def _count_trace_skip(site: str) -> None:
    try:
        from raft_tpu.observability import get_registry

        get_registry().counter(
            TRACE_SKIPS, {"site": site},
            help="Certificate stats skipped because the recorder ran "
                 "under tracing (tracer n_fail)").inc()
    except Exception:
        pass


def drain() -> int:
    """Resolve every pending certificate record into the registry;
    returns how many were drained. Safe to call from any thread; a
    conversion failure drops that entry rather than raising."""
    n = 0
    while True:
        with _pending_lock:
            if not _pending:
                return n
            site, nf, nq, pw, tiers, meta = _pending.popleft()
        try:
            n_fail = int(np.sum(np.asarray(nf)))
        except Exception:
            continue
        record_certificate(
            site, nq, n_fail, pool_width=pw,
            fixup_rows=fixup_tier_for(n_fail, tiers, nq), **meta)
        n += 1


def pending_count() -> int:
    with _pending_lock:
        return len(_pending)


def clear() -> None:
    """Drop pending (undrained) records and the PQ rung tallies —
    tests."""
    with _pending_lock:
        _pending.clear()
    with _pq_lock:
        _pq_tally.clear()


# ------------------------------------------------------------ snapshot
def quality_block(registry=None, drain_first: bool = True
                  ) -> Optional[Dict]:
    """The ``quality`` block BENCH/MULTICHIP/ANN/SERVING artifacts carry
    (gated by ``tools/bench_report.py --check``): per-site certificate
    checks / fixups / fixup_rate, rescore-pool width stats, and the
    shadow-recall gauges when a sampler ran. None when the process
    recorded no quality telemetry at all."""
    if drain_first:
        drain()
    reg = registry if registry is not None else get_registry()
    sites: Dict[str, Dict] = {}
    pools: Dict[str, Dict] = {}
    shadow: Dict[str, float] = {}
    for metric in reg.collect():
        site = metric.labels.get("site")
        if metric.name == CERT_CHECKS and site:
            sites.setdefault(site, {})["checks"] = int(metric.value)
        elif metric.name == CERT_FIXUPS and site:
            sites.setdefault(site, {})["fixups"] = int(metric.value)
        elif metric.name == IVF_RERUNS and site:
            sites.setdefault(site, {})["cert_reruns"] = int(metric.value)
        elif metric.name == PQ_RERUN_FRAC and site:
            sites.setdefault(site, {})["pq_rerun_frac"] = round(
                float(metric.value), 6)
        elif metric.name == PQ_RUNGS and site:
            sites.setdefault(site, {}).setdefault("pq_rungs", {})[
                metric.labels.get("rung", "?")] = int(metric.value)
        elif metric.name == RESCORE_POOL and site:
            cnt = metric.count
            pools[site] = {"count": cnt,
                           "mean": round(metric.sum / cnt, 2) if cnt
                           else 0.0}
        elif metric.name == SHADOW_RECALL:
            shadow["shadow_recall"] = round(float(metric.value), 4)
        elif metric.name == SHADOW_SAMPLES:
            shadow["shadow_samples"] = int(metric.value)
        elif metric.name == SHADOW_BREACHES:
            shadow["shadow_breaches"] = int(metric.value)
    if not sites and not shadow:
        return None
    checks = sum(s.get("checks", 0) for s in sites.values())
    fixups = sum(s.get("fixups", 0) for s in sites.values())
    for s in sites.values():
        c = s.get("checks", 0)
        s["fixup_rate"] = round(s.get("fixups", 0) / c, 6) if c else 0.0
    out: Dict = {
        "fixup_rate": round(fixups / checks, 6) if checks else 0.0,
        "certificate_checks": checks,
        "certificate_fixups": fixups,
        "sites": sites,
    }
    if pools:
        out["rescore_pool_widths"] = pools
    out.update(shadow)
    return out


# ------------------------------------------------- shadow recall sampler
def _sample_hash(rid: int) -> float:
    """Deterministic per-request uniform in [0, 1) (Knuth multiplicative
    hash) — the sampling decision replays bit-identically across runs,
    which the deterministic serving tests rely on."""
    return ((int(rid) * 2654435761) & 0xFFFFFFFF) / 2.0 ** 32


def recall_at_k(served_ids: np.ndarray, true_ids: np.ndarray) -> float:
    """Mean per-row |served ∩ true| / k — the same recall the offline
    ANN benchmark reports (``benchmarks/bench_ann.py``)."""
    served = np.asarray(served_ids)
    true = np.asarray(true_ids)
    if served.ndim == 1:
        served = served[None]
    if true.ndim == 1:
        true = true[None]
    k = true.shape[1]
    hits = [len(set(int(i) for i in served[r] if i >= 0)
                & set(int(i) for i in true[r]))
            for r in range(true.shape[0])]
    return float(np.mean(hits)) / max(1, k)


class ShadowSampler:
    """Online recall shadow-sampling for the serving engine.

    A sampled (request, served ids) pair is queued (bounded — overload
    DROPS samples, counted, rather than backing up into the serving
    path) and re-scored on a daemon thread: ``oracle(x) -> (vals,
    ids)`` is the exact brute-force plane for the engine's current
    snapshot. Recall@k per sample feeds a rolling window; the window
    mean is the ``raft_tpu_serving_shadow_recall`` gauge, and a mean
    below ``floor`` (after ``min_samples``) emits a ``drift`` flight
    event + breach counter — quality drift surfaces on the same
    timeline as every other anomaly.
    """

    def __init__(self, oracle: Callable, k: int, frac: float,
                 floor: Optional[float] = None, window: int = 256,
                 max_queue: int = 64, min_samples: int = 4,
                 site: str = "serving.shadow", registry=None):
        self._oracle = oracle
        self.k = int(k)
        self.frac = max(0.0, min(1.0, float(frac)))
        self.floor = (shadow_floor_default() if floor is None
                      else float(floor))
        self.site = site
        self._reg = registry
        self._window: collections.deque = collections.deque(
            maxlen=max(1, int(window)))
        self._min_samples = max(1, int(min_samples))
        self._max_queue = max(1, int(max_queue))
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._stop = False
        self._busy = False
        self._samples = 0
        self._dropped = 0
        self._breaches = 0
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "ShadowSampler":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(target=self._loop,
                                            name="serving-shadow",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every queued sample is scored (tests/benchmarks;
        the live path never waits on the shadow)."""
        import time as _time

        t_end = _time.monotonic() + timeout
        with self._cond:
            self._cond.notify_all()
            while ((self._queue or self._busy)
                   and _time.monotonic() < t_end):
                self._cond.wait(0.01)
            return not self._queue and not self._busy

    # -- sampling ---------------------------------------------------------
    def want(self, rid: int) -> bool:
        """Deterministic sampling decision for request ``rid``."""
        return self.frac > 0.0 and _sample_hash(rid) < self.frac

    def submit(self, rid: int, x, served_ids) -> bool:
        """Queue one sampled request; False (and a drop count) when the
        queue is full — shadow work never backs up into serving."""
        with self._cond:
            if len(self._queue) >= self._max_queue:
                self._dropped += 1
                self._metric("counter", SHADOW_DROPPED,
                             "Shadow samples dropped (queue full)")
                return False
            self._queue.append((int(rid), np.asarray(x, np.float32),
                                np.asarray(served_ids)))
            self._cond.notify_all()
        return True

    # -- the scorer thread ------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(0.05)
                if self._stop and not self._queue:
                    return
                item = self._queue.popleft()
                self._busy = True
            try:
                self._score(*item)
            except Exception:
                pass
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _score(self, rid: int, x: np.ndarray,
               served_ids: np.ndarray) -> None:
        _, true_ids = self._oracle(x)
        r = recall_at_k(served_ids, np.asarray(true_ids))
        with self._cond:
            self._window.append(r)
            self._samples += 1
            rolling = float(np.mean(self._window))
            breach = (self._samples >= self._min_samples
                      and rolling < self.floor)
            if breach:
                self._breaches += 1
        self._metric("counter", SHADOW_SAMPLES,
                     "Shadow-sampled requests re-scored vs the oracle")
        self._metric("gauge", SHADOW_RECALL,
                     "Rolling recall@k of served vs oracle results",
                     value=rolling)
        if breach:
            self._metric("counter", SHADOW_BREACHES,
                         "Rolling shadow recall fell below the floor")
            try:
                from raft_tpu.observability.flight import \
                    get_flight_recorder

                rec = get_flight_recorder()
                if rec.enabled:
                    # quality drift rides the same event kind as
                    # model-vs-measured drift: one timeline, one alarm
                    rec.record("drift", self.site, lane="serving",
                               recall=round(rolling, 4),
                               floor=self.floor, rid=int(rid),
                               measured=True)
            except Exception:
                pass

    def _metric(self, kind: str, name: str, help: str,
                value: Optional[float] = None) -> None:
        try:
            reg = self._reg if self._reg is not None else get_registry()
            if kind == "gauge":
                reg.gauge(name, help=help).set(float(value))
            else:
                reg.counter(name, help=help).inc()
        except Exception:
            pass

    # -- queries ----------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._cond:
            rolling = (float(np.mean(self._window)) if self._window
                       else None)
            return {"shadow_frac": self.frac,
                    "shadow_floor": self.floor,
                    "shadow_samples": self._samples,
                    "shadow_dropped": self._dropped,
                    "shadow_breaches": self._breaches,
                    "shadow_recall": (round(rolling, 4)
                                      if rolling is not None else None)}
