"""Static XLA cost capture + roofline attribution.

The *analytical* half of observability (the PR-1 registry/spans are the
*measured* half): for every AOT-compiled executable we record what XLA
says the program must do — FLOPs and bytes accessed from
``compiled.cost_analysis()``, peak/temp HBM from
``compiled.memory_analysis()`` — and combine it with the per-generation
hardware peaks in :mod:`raft_tpu.utils.arch` to answer the question every
perf PR must answer: *how far is this primitive from what the hardware
allows?* (Roofline model — Williams et al., CACM 2009.)

Everything here is measurement-free and backend-agnostic: on the CPU
tier-1 suite the same capture → classify → report path runs against the
synthetic :data:`raft_tpu.utils.arch.CPU_SPEC` peaks, so the pipeline is
tested end-to-end without TPU hardware.

Capture NEVER raises into the caller: ``cost_analysis`` is best-effort
across backends/JAX versions (dict vs list-of-dict, missing keys), and a
primitive without a cost record simply shows up without roofline columns.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

from raft_tpu.observability.metrics import MetricsRegistry, get_registry
from raft_tpu.utils.arch import ChipSpec, chip_spec

COST_FLOPS = "raft_tpu_cost_flops"
COST_BYTES = "raft_tpu_cost_bytes_accessed"
COST_PEAK_HBM = "raft_tpu_cost_peak_hbm_bytes"
COST_TEMP_BYTES = "raft_tpu_cost_temp_bytes"
COST_CAPTURES = "raft_tpu_cost_captures_total"


@dataclasses.dataclass
class CostRecord:
    """Static cost of ONE compiled executable (entry + shape signature).

    ``flops``/``bytes_accessed`` come from XLA's cost analysis of the
    optimized HLO; ``*_bytes`` fields from the compiled memory analysis.
    ``peak_hbm_bytes`` is the arguments + outputs + temporaries sum — the
    executable's HBM high-water mark (code size excluded)."""

    entry: str                      # primitive name (e.g. "randomized_svds")
    key: str = ""                   # shape+sharding signature
    flops: float = 0.0
    bytes_accessed: float = 0.0
    transcendentals: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    peak_hbm_bytes: int = 0
    generated_code_bytes: int = 0
    platform: str = ""

    @property
    def arithmetic_intensity(self) -> float:
        """FLOP per HBM byte; inf for byte-free programs (degenerate)."""
        if self.bytes_accessed <= 0:
            return math.inf if self.flops > 0 else 0.0
        return self.flops / self.bytes_accessed

    def to_event(self) -> Dict:
        ev = dataclasses.asdict(self)
        ev["type"] = "cost"
        ev["arithmetic_intensity"] = self.arithmetic_intensity
        return ev


def _first_cost_dict(cost) -> Dict:
    """Normalize ``compiled.cost_analysis()`` across JAX versions: a dict,
    a list of per-program dicts, or None."""
    if cost is None:
        return {}
    if isinstance(cost, dict):
        return cost
    if isinstance(cost, (list, tuple)) and cost:
        return cost[0] if isinstance(cost[0], dict) else {}
    return {}


def extract_cost(compiled, entry: str, key: str = "") -> Optional[CostRecord]:
    """Build a :class:`CostRecord` from a ``jax.stages.Compiled`` (or any
    object exposing ``cost_analysis``/``memory_analysis``). Returns None
    when the backend exposes neither — never raises."""
    rec = CostRecord(entry=entry, key=key)
    got = False
    try:
        cost = _first_cost_dict(compiled.cost_analysis())
        if cost:
            rec.flops = float(cost.get("flops", 0.0))
            rec.bytes_accessed = float(cost.get("bytes accessed", 0.0))
            rec.transcendentals = float(cost.get("transcendentals", 0.0))
            got = True
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            rec.argument_bytes = int(
                getattr(mem, "argument_size_in_bytes", 0))
            rec.output_bytes = int(getattr(mem, "output_size_in_bytes", 0))
            rec.temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0))
            rec.generated_code_bytes = int(
                getattr(mem, "generated_code_size_in_bytes", 0))
            rec.peak_hbm_bytes = (rec.argument_bytes + rec.output_bytes
                                  + rec.temp_bytes)
            got = True
    except Exception:
        pass
    return rec if got else None


def publish(rec: CostRecord,
            registry: Optional[MetricsRegistry] = None) -> None:
    """Cost record → registry: per-entry gauges (latest capture wins —
    static facts, not accumulating measurements) + a ``cost`` event that
    carries the full record including the shape key."""
    reg = registry if registry is not None else get_registry()
    if not reg.enabled:
        return
    labels = {"entry": rec.entry}
    reg.counter(COST_CAPTURES, labels,
                help="XLA cost/memory analyses captured").inc()
    reg.gauge(COST_FLOPS, labels,
              help="XLA cost_analysis FLOPs of the latest compiled "
                   "executable").set(rec.flops)
    reg.gauge(COST_BYTES, labels,
              help="XLA cost_analysis bytes accessed (HBM traffic)"
              ).set(rec.bytes_accessed)
    reg.gauge(COST_PEAK_HBM, labels,
              help="args+outputs+temps of the compiled executable"
              ).set(rec.peak_hbm_bytes)
    reg.gauge(COST_TEMP_BYTES, labels,
              help="XLA temp (scratch) bytes of the compiled executable"
              ).set(rec.temp_bytes)
    reg.emit(rec.to_event())


# ---------------------------------------------------------------- roofline
COMPUTE_BOUND = "compute-bound"
MEMORY_BOUND = "memory-bound"


def classify(arithmetic_intensity: float, spec: Optional[ChipSpec] = None,
             f32: bool = False) -> str:
    """Compute- vs memory-bound at the chip's ridge point
    (peak FLOP/s ÷ HBM bytes/s)."""
    spec = spec if spec is not None else chip_spec()
    ridge = spec.ridge_f32 if f32 else spec.ridge
    return COMPUTE_BOUND if arithmetic_intensity >= ridge else MEMORY_BOUND


@dataclasses.dataclass
class RooflineEstimate:
    """One primitive placed on the roofline.

    ``roof_flops`` is the ATTAINABLE FLOP/s at this arithmetic intensity
    — ``min(peak_flops, AI · hbm_bw)``; ``roof_seconds`` the time a
    roofline-perfect execution would take. With a measured ``seconds``,
    ``utilization`` = roof_seconds / seconds (1.0 = at the roofline) and
    ``achieved_flops``/``achieved_bw`` are the realized rates."""

    entry: str
    flops: float
    bytes_accessed: float
    arithmetic_intensity: float
    bound: str
    roof_flops: float
    roof_seconds: float
    seconds: Optional[float] = None
    achieved_flops: Optional[float] = None
    achieved_bw: Optional[float] = None
    utilization: Optional[float] = None
    spec_name: str = ""


def roofline(rec: CostRecord, spec: Optional[ChipSpec] = None,
             seconds: Optional[float] = None,
             f32: bool = False) -> RooflineEstimate:
    """Place one cost record on the roofline, optionally attributing a
    measured execute time (``benchmark.Fixture.run`` seconds)."""
    spec = spec if spec is not None else chip_spec()
    ai = rec.arithmetic_intensity
    peak = spec.peak_flops_f32 if f32 else spec.peak_flops
    bound = classify(ai, spec, f32=f32)
    roof_flops = peak if bound == COMPUTE_BOUND else ai * spec.hbm_bw
    # roofline-perfect time: compute time or memory time, whichever rules
    roof_seconds = max(rec.flops / peak if peak else 0.0,
                       rec.bytes_accessed / spec.hbm_bw if spec.hbm_bw
                       else 0.0)
    est = RooflineEstimate(
        entry=rec.entry, flops=rec.flops,
        bytes_accessed=rec.bytes_accessed, arithmetic_intensity=ai,
        bound=bound, roof_flops=roof_flops, roof_seconds=roof_seconds,
        seconds=seconds, spec_name=spec.name)
    if seconds and seconds > 0:
        est.achieved_flops = rec.flops / seconds
        est.achieved_bw = rec.bytes_accessed / seconds
        est.utilization = min(roof_seconds / seconds, 1.0) \
            if roof_seconds else None
    return est


# ------------------------------------------------- fused traffic model

#: bytes per streamed database element, by storage dtype — the ONE
#: place the quantized-streaming bytes arithmetic lives (models, bench
#: stamping and the bench_report quantized gate all read it)
DB_DTYPE_BYTES = {"f32": 4, "bf16": 2, "int8": 1}


def db_stream_bytes_per_el(db_dtype: str, passes: int) -> int:
    """Streamed bytes per database element of the fused pipeline:
    bf16 streams the hi (and, at passes=3, lo) split; int8 streams one
    byte regardless of passes (only the query operand is split)."""
    if db_dtype == "int8":
        return 1
    return DB_DTYPE_BYTES["bf16"] * (2 if passes == 3 else 1)


def fused_traffic_model(Q: int, m: int, d: int, k: int,
                        T: int, Qb: int, g: int, passes: int,
                        grid_order: str = "query",
                        db_dtype: str = "bf16") -> Dict:
    """Analytic HBM traffic of the packed fused L2 top-k pipeline for
    one query batch — the per-variant bytes model the grid-order work
    is judged by (ISSUE 3): query-major re-fetches the database once
    per query block (y traffic ``nq·M·d`` bytes), the database-major
    orders stream it once (``M·d``), trading a bounded amount of x /
    output revisit traffic. Emitted next to XLA's ``bytes_accessed`` in
    BENCH artifacts so predicted-vs-measured divergence is visible in
    the evidence trail, and used by :mod:`raft_tpu.tune` to rank
    candidates deterministically on CPU.

    Mirrors the real pipeline's geometry: feature padding, row padding
    to tiles (or whole groups for db orders), query chunking at
    ``_Q_CHUNK`` (each chunk is a separate kernel launch, so y
    re-streams per chunk), bf16 (passes=1) vs bf16 hi+lo (passes=3)
    database bytes, and the 3 packed [Q, G·128] outputs. The model
    assumes the packed production path — the unpacked fallback's extra
    id outputs are not priced."""
    from raft_tpu.distance.knn_fused import (_DC, _D_SINGLE_SHOT,
                                             _Q_CHUNK)

    lanes = 128
    d_eff = d + (-d) % (_DC if d > _D_SINGLE_SHOT else lanes)
    row_mult = g * T if grid_order in ("db", "dbuf") else T
    M = -(-max(m, 1) // row_mult) * row_mult
    n_tiles = M // T
    G = -(-n_tiles // g)
    bpe = db_stream_bytes_per_el(db_dtype, passes)
    y_stream = M * d_eff * bpe
    yy_stream = 8 * M * 4
    if db_dtype == "int8":
        yy_stream += G * 8 * lanes * 4      # per-group scale tiles
    y_streams = 0.0
    x_bytes = 0.0
    out_bytes = 0.0
    q_left = Q
    while q_left > 0:
        qc = min(q_left, _Q_CHUNK)
        q_left -= qc
        qb_eff = min(Qb, -(-qc // 8) * 8)
        qp = -(-qc // qb_eff) * qb_eff
        nq = qp // qb_eff
        if grid_order == "query":
            y_streams += nq                 # y re-fetched per query block
            x_bytes += qp * d_eff * 4       # x fetched once per block
        elif grid_order == "db":
            y_streams += 1                  # super-block resident
            x_bytes += (M // (g * T)) * qp * d_eff * 4   # x per group
        else:                               # dbuf: both single-stream
            y_streams += 1
            x_bytes += qp * d_eff * 4
        out_bytes += 3 * qp * G * lanes * 4
    return {
        "grid_order": grid_order,
        "db_dtype": db_dtype,
        "y_bytes_per_el": bpe,
        "y_bytes": y_streams * y_stream,
        "y_stream_bytes": float(y_stream),
        "y_stream_factor": y_streams,
        "x_bytes": x_bytes,
        "yy_bytes": y_streams * yy_stream,
        "out_bytes": out_bytes,
        "total_bytes": (y_streams * (y_stream + yy_stream)
                        + x_bytes + out_bytes),
    }


def quantized_bytes_ratio(Q: int, m: int, d: int, k: int,
                          T: int, Qb: int, g: int, passes: int,
                          grid_order: str = "db") -> float:
    """Modeled streamed-database-bytes ratio of the int8 path over the
    bf16 baseline for the same geometry — the number the bench
    artifacts stamp and ``bench_report --check`` gates at ≤ 0.55×
    (exactly 1/2 at passes=1, 1/4 at passes=3, before the small scale-
    tile overhead in the yy stream)."""
    q8 = fused_traffic_model(Q, m, d, k, T, Qb, g, passes, grid_order,
                             "int8")
    bf = fused_traffic_model(Q, m, d, k, T, Qb, g, passes, grid_order,
                             "bf16")
    return q8["y_bytes"] / max(bf["y_bytes"], 1.0)


def fused_traffic_record(Q: int, m: int, d: int, k: int,
                         T: int, Qb: int, g: int, passes: int,
                         grid_order: str = "query",
                         db_dtype: str = "bf16") -> CostRecord:
    """The traffic model as a :class:`CostRecord` (entry
    ``fused_traffic_model``) so it can ride the same roofline path as
    XLA-captured costs — the deterministic ranking key of the
    :mod:`raft_tpu.tune` CPU fallback."""
    model = fused_traffic_model(Q, m, d, k, T, Qb, g, passes,
                                grid_order, db_dtype)
    lanes = 128
    d_eff = d + (-d) % lanes if d <= 512 else d + (-d) % 256
    # int8 folds at most two MXU passes (x hi + lo); bf16x3 runs three
    n_mm = ((2 if passes == 3 else 1) if db_dtype == "int8"
            else (3 if passes == 3 else 1))
    flops = 2.0 * Q * (-(-m // T) * T) * d_eff * n_mm
    return CostRecord(
        entry="fused_traffic_model",
        key=f"{grid_order};T={T};Qb={Qb};g={g};p={passes};"
            f"{db_dtype};{Q}x{m}x{d}",
        flops=flops,
        bytes_accessed=model["total_bytes"])


#: list-major wins the fine-scan crossover only past this modeled
#: gather/stream ratio — margin for the schedule build, the pool
#: rescore and the masked-MXU work the bytes model does not price
FINE_SCAN_MARGIN = 1.25

#: the ADC kernel wins the PQ crossover only past this modeled
#: flat/pq bytes ratio — margin for the LUT build, the one-hot MXU
#: work and the mandatory pool rescore the bytes model prices only
#: approximately
PQ_SCAN_MARGIN = 1.25


def pq_bytes_ratio(d: int, pq_dim: int, pq_bits: int) -> float:
    """Modeled streamed-database-bytes ratio of the PQ codes slab over
    the f32 slab for the same rows — the PQ tier's analog of
    :func:`quantized_bytes_ratio` (slab stream only, sidecars excluded
    on both sides, exactly like the int8 ratio compares y bytes).
    1/16 at 8-bit codes with ``pq_dim = d/4``, 1/32 at 4-bit — the
    number the bench artifacts stamp and ``bench_report --check``
    gates at ≤ 0.10×."""
    lanes = 128
    d_eff = d + (-d) % lanes
    code_bytes = pq_dim * pq_bits / 8.0
    return code_bytes / max(d_eff * 4.0, 1.0)


def pq_index_bytes(m: int, d: int, n_lists: int, pq_dim: int,
                   pq_bits: int, pad_frac: float = 0.05) -> Dict:
    """Modeled RESIDENT bytes of the compressed IVF-PQ tier for an
    ``m × d`` database: the packed codes slab + the per-row norm/id
    sidecar + the coarse centroids + the per-subspace codebooks — the
    set the ADC scan actually touches, which is what must fit a
    chip's HBM at the 100M-row scale (the f32 rescore slab is the
    uncompressed tier: host- or peer-resident at that scale, streamed
    only for the ~256-row candidate pools). ``pad_frac`` models the
    ragged row-quantum padding."""
    K = 1 << pq_bits
    dsub = max(1, d // max(pq_dim, 1))
    R = float(m) * (1.0 + max(0.0, pad_frac))
    code_bytes = pq_dim * pq_bits / 8.0
    codes = R * code_bytes
    sidecar = R * (4 + 4)                      # ‖ŷ‖² + global id
    coarse = float(n_lists) * d * 4
    books = float(pq_dim) * K * dsub * 4
    geometry = float(n_lists + 1) * 4 * 3
    total = codes + sidecar + coarse + books + geometry
    return {
        "rows": int(m),
        "d": int(d),
        "pq_dim": int(pq_dim),
        "pq_bits": int(pq_bits),
        "codes_bytes": codes,
        "sidecar_bytes": sidecar,
        "coarse_bytes": coarse,
        "codebook_bytes": books,
        "total_bytes": total,
        "f32_slab_bytes": R * d * 4.0,
        "compression": (R * d * 4.0) / max(codes + sidecar, 1.0),
    }


def choose_pq_scan(model: Dict,
                   rerun_frac: Optional[float] = None) -> str:
    """The cost-model half of ``ann.ivf_pq.resolve_pq_scan``:
    ``"pq"`` when the best FLAT schedule's modeled fine-scan bytes
    beat the EXPECTED ADC bytes by :data:`PQ_SCAN_MARGIN`, else
    ``"flat"``. Takes an :func:`ivf_traffic_model` result carrying
    the pq keys.

    Expected ADC bytes are NOT the best case: every certificate-
    failing query pays the flat rerun on top of the codes stream, so
    the comparison prices ``pq_stream + rerun_frac · flat``
    (``rerun_frac`` overrides the model's own ``pq_rerun_frac`` key;
    both default 0 — the PR-15 blind spot this closes)."""
    pq = model.get("pq_stream_bytes")
    if not isinstance(pq, (int, float)) or pq <= 0:
        return "flat"
    flat = min(model.get("fine_stream_bytes", float("inf")),
               model.get("fine_gather_bytes", float("inf")))
    frac = model.get("pq_rerun_frac", 0.0) if rerun_frac is None \
        else rerun_frac
    frac = min(1.0, max(0.0, float(frac)))
    expected = pq + frac * flat
    return "pq" if flat > PQ_SCAN_MARGIN * max(expected, 1.0) \
        else "flat"

#: per-query candidate pool the list-major kernels exact-rescore
#: (2 × 128 lane-class slots — ops.fine_scan_pallas.POOL_WIDTH)
_LIST_POOL = 256


def choose_fine_scan(model: Dict) -> str:
    """The cost-model half of ``resolve_fine_scan``: ``"list"`` when
    the query-major gather re-reads enough shared probed bytes to beat
    the list-major stream by :data:`FINE_SCAN_MARGIN`, else
    ``"query"``. Takes an :func:`ivf_traffic_model` result."""
    gather = model.get("fine_gather_bytes", 0.0)
    stream = model.get("fine_stream_bytes", 0.0)
    return "list" if gather > FINE_SCAN_MARGIN * max(stream, 1.0) \
        else "query"


def ivf_traffic_model(nq: int, m: int, d: int, k: int, n_lists: int,
                      n_probes: int, probe_window: int,
                      slab_rows: int, db_dtype: str = "f32",
                      list_sizes=None, padded_sizes=None,
                      pq_dim: Optional[int] = None,
                      pq_bits: Optional[int] = None,
                      pq_rerun_frac: float = 0.0) -> Dict:
    """Analytic HBM traffic of one IVF-Flat search batch
    (:mod:`raft_tpu.ann`) next to the brute-force bytes it displaces —
    the model behind BENCH_ANN.json's speed/recall frontier.

    - ``coarse_bytes``: the [L, d] centroid sweep (+ query rows);
    - ``probed_frac``: probed slab rows / total slab rows — the
      fraction of database bytes a query touches (the knob recall is
      traded against);
    - ``fine_stream_bytes``: the LIST-MAJOR schedule — every probed
      list streams from HBM once per query chunk (the IVF analog of
      PR-3's db-major grid re-order; ``ann.ivf_flat`` runs it through
      the ``ops.fine_scan_pallas`` kernels), plus the per-query
      candidate-pool exact rescore that schedule pays
      (``list_rescore_bytes``). With ``list_sizes``/``padded_sizes``
      (the index's ACTUAL list-size histogram) the streamed-list
      expectation uses size-biased probe probabilities and the
      per-chunk union of probed lists — balanced k-means reduces but
      does not eliminate skew, and the :func:`choose_fine_scan`
      crossover depends on it; without them the legacy uniform
      mean-window model applies;
    - ``fine_gather_bytes``: what the query-major XLA gather schedule
      reads — each query re-fetches its own probe windows, the exact
      nq× re-read pathology the PR-3 work removed from brute force
      (the committed frontier carries both numbers; their ratio is
      ``gather_overread``, the factor the list-major kernel removes);
    - ``brute_bytes``: the stream-once fused pipeline's y traffic for
      the same batch (database streamed ONCE per _Q_CHUNK query chunk,
      bf16 hi+lo — the baseline this tier must beat);
    - ``modeled_speedup``: brute_bytes / stream total — both pipelines
      are HBM-bound, so the bytes ratio IS the modeled speedup, and
      ``hbm_bw · speedup`` is the effective database-scan rate a
      roofline-perfect chip would sustain;
    - with ``pq_dim``/``pq_bits`` (the IVF-PQ compressed tier,
      ``ann.ivf_pq``): ``pq_stream_bytes`` prices the list-major ADC
      schedule — packed code bytes + the 4-byte ``‖ŷ‖²`` and 4-byte
      per-row ``Eq`` sidecars per streamed row, the per-chunk ADC
      table build (codebooks in, the ``[nq, pq_dim·2^pq_bits]`` table
      out) and the mandatory 256-row f32 pool rescore — and
      ``pq_bytes_ratio`` is the pure codes-vs-f32 slab-stream ratio
      (:func:`pq_bytes_ratio`) the quantized gate bounds at ≤ 0.10×.
      ``pq_rerun_frac`` (measured-or-modeled expected certificate-
      rerun fraction) adds the flat-rerun bytes those queries pay:
      ``pq_expected_bytes = pq_stream + frac · fine_stream`` — what
      :func:`choose_pq_scan` actually compares.
    """
    from raft_tpu.distance.knn_fused import _Q_CHUNK

    if db_dtype not in DB_DTYPE_BYTES:
        raise ValueError(f"ivf_traffic_model: db_dtype must be one of "
                         f"{tuple(DB_DTYPE_BYTES)}, got {db_dtype!r}")
    lanes = 128
    d_eff = d + (-d) % lanes
    coarse_bytes = float(n_lists * d_eff * 4 + nq * d_eff * 4
                         + nq * n_lists * 4)
    # per probed row: slab row at its storage width + norm + id, plus
    # the int8 sidecar (scale + Eq) and the per-query exact rescore of
    # the pruned candidate pool from the f32 slab
    bpe = DB_DTYPE_BYTES[db_dtype]
    per_row_f32 = d_eff * 4 + 4 + 4
    per_row = d_eff * bpe + 4 + 4 + (8 if db_dtype == "int8" else 0)
    out_bytes = float(nq) * k * 8
    chunks = max(1, -(-nq // _Q_CHUNK))
    nq_chunk = max(1, -(-nq // chunks))
    if list_sizes is not None:
        # the ACTUAL histogram: probe probability is size-biased (a
        # query lands on a list roughly in proportion to its share of
        # the rows — the balanced trainer narrows but never flattens
        # the distribution), probed rows per query are the size-biased
        # expected padded window, and the list-major stream is the
        # expected per-chunk UNION of probed lists
        sizes = [max(0.0, float(s)) for s in list_sizes]
        padded = ([max(0.0, float(s)) for s in padded_sizes]
                  if padded_sizes is not None
                  else [-(-s // 8) * 8 for s in sizes])
        tot = max(1.0, sum(sizes))
        probed_rows = n_probes * sum(
            s * w for s, w in zip(sizes, padded)) / tot
        probed_frac = min(1.0, probed_rows / max(1, slab_rows))
        stream_rows = 0.0
        for s, w in zip(sizes, padded):
            p_l = min(1.0, float(n_probes) * s / tot)
            stream_rows += (1.0 - (1.0 - p_l) ** nq_chunk) * w
        stream_rows = min(stream_rows, float(slab_rows))
    else:
        probed_frac = min(1.0, float(n_probes) * probe_window
                          / max(1, slab_rows))
        stream_rows = probed_frac * max(slab_rows, 1)
    rescore_bytes = (float(nq) * min(k + 32, n_probes * probe_window)
                     * d_eff * 4 if db_dtype == "int8" else 0.0)
    # the list-major schedule always exact-rescores its pooled
    # candidates from the f32 slab (that is what keeps its ids
    # bit-identical to the query-major oracle)
    list_rescore_bytes = (float(nq)
                          * min(_LIST_POOL, n_probes * probe_window)
                          * d_eff * 4)
    fine_stream_bytes = (float(chunks) * stream_rows * per_row
                         + list_rescore_bytes)
    fine_gather_bytes = (float(nq) * n_probes * probe_window * per_row
                         + rescore_bytes)
    total_stream = coarse_bytes + fine_stream_bytes + out_bytes
    total_gather = coarse_bytes + fine_gather_bytes + out_bytes
    brute_bytes = float(chunks) * max(m, 1) * d_eff * 2 * 2 \
        + float(nq) * d_eff * 4
    fine_gather_f32 = (float(nq) * n_probes * probe_window
                       * per_row_f32)
    pq_keys = {}
    if pq_dim is not None and pq_bits is not None:
        K = 1 << int(pq_bits)
        dsub = max(1, d // max(int(pq_dim), 1))
        code_bytes = int(pq_dim) * int(pq_bits) / 8.0
        # codes + ‖ŷ‖² + per-row Eq (adaptive certificate) + id
        per_row_pq = code_bytes + 4 + 4 + 4
        adc_table_bytes = (float(chunks) * pq_dim * K * dsub * 4
                           + float(nq) * pq_dim * K * 4 * 2)
        pq_stream = (float(chunks) * stream_rows * per_row_pq
                     + list_rescore_bytes + adc_table_bytes)
        frac = min(1.0, max(0.0, float(pq_rerun_frac)))
        pq_expected = pq_stream + frac * (float(chunks) * stream_rows
                                          * per_row
                                          + list_rescore_bytes)
        pq_total = coarse_bytes + pq_expected + out_bytes
        pq_keys = {
            "pq_dim": int(pq_dim),
            "pq_bits": int(pq_bits),
            "pq_stream_bytes": pq_stream,
            "pq_rerun_frac": frac,
            "pq_expected_bytes": pq_expected,
            "pq_total_bytes": pq_total,
            "adc_table_bytes": adc_table_bytes,
            "pq_bytes_ratio": pq_bytes_ratio(d, int(pq_dim),
                                             int(pq_bits)),
            "modeled_speedup_pq": brute_bytes / max(pq_total, 1.0),
        }
    return {
        **pq_keys,
        "db_dtype": db_dtype,
        "coarse_bytes": coarse_bytes,
        "fine_stream_bytes": fine_stream_bytes,
        "fine_gather_bytes": fine_gather_bytes,
        "rescore_bytes": rescore_bytes,
        "list_rescore_bytes": list_rescore_bytes,
        "out_bytes": out_bytes,
        "total_bytes": total_stream,
        "total_gather_bytes": total_gather,
        "brute_bytes": brute_bytes,
        "probed_frac": probed_frac,
        "modeled_speedup": brute_bytes / max(total_stream, 1.0),
        "gather_overread": total_gather / max(total_stream, 1.0),
        # probed-gather bytes vs the f32 slab gather of the same
        # geometry — the IVF analog of quantized_bytes_ratio
        "quantized_gather_ratio": (fine_gather_bytes
                                   / max(fine_gather_f32, 1.0)),
    }


# ------------------------------------------------- ICI traffic model
MERGE_STRATEGIES = ("allgather", "tournament")


def ici_traffic_model(p: int, nq: int, k: int, strategy: str,
                      cand_bytes: int = 8) -> Dict:
    """Modeled ICI traffic of ONE sharded-KNN merge over ``p`` shards
    for a query block of ``nq`` rows selecting ``k`` — the analytic
    half of the merge-strategy crossover (ISSUE 4) and the bytes every
    MULTICHIP artifact records next to ``roofline_frac``.

    Per candidate ``cand_bytes`` = 8 (f32 value + int32 global id).
    Wire bytes are PER-DEVICE EGRESS (the nccl-tests/BUSBW_BENCH
    convention, so busbw fractions divide by the per-chip ``ici_bw``):

    - ``allgather``: ring all-gather of each shard's [nq, k] candidate
      block — every rank forwards p−1 chunks, so egress is
      ``(p−1)·nq·k·cand_bytes``; ONE select over the p·k-wide pool.
    - ``tournament``: log₂(p) butterfly rounds of collective_permute
      pair-exchanges, each moving one [nq, k] block
      (``nq·k·cand_bytes`` egress per round) followed by a select over
      2k — less wire for p ≥ 4 (log₂(p) < p−1 blocks) at the price of
      log₂(p) serialized rounds and selects.
    """
    if strategy not in MERGE_STRATEGIES:
        raise ValueError(f"ici_traffic_model: strategy must be one of "
                         f"{MERGE_STRATEGIES}, got {strategy!r}")
    block = float(nq) * k * cand_bytes
    if strategy == "allgather":
        rounds, wire, width = 1, (p - 1) * block, p * k
    else:
        if p & (p - 1):
            raise ValueError(f"ici_traffic_model: tournament needs a "
                             f"power-of-two shard count, got p={p}")
        rounds = max(1, p.bit_length() - 1) if p > 1 else 0
        wire, width = rounds * block, 2 * k
    return {
        "strategy": strategy,
        "p": p,
        "rounds": rounds,
        "wire_bytes_per_device": wire,
        "bytes_per_round": block if strategy == "tournament"
        else (p - 1) * block,
        "select_width": width,
        # bytes each select pass reads+writes on-device (vals + ids in,
        # k out — the non-wire cost of a merge round)
        "select_bytes": float(nq) * (width + k) * cand_bytes,
    }


def ici_time_model(p: int, nq: int, k: int, strategy: str,
                   spec: Optional[ChipSpec] = None,
                   cand_bytes: int = 8) -> Dict:
    """Modeled merge time on ``spec``: wire time (egress ÷ ``ici_bw``)
    + per-round latency + select time (select_bytes ÷ ``hbm_bw`` per
    round). Deterministic — the CPU suite ranks strategies with it."""
    spec = spec if spec is not None else chip_spec()
    m = ici_traffic_model(p, nq, k, strategy, cand_bytes)
    ici_bw = spec.ici_bw or spec.hbm_bw   # never divide by zero
    wire_s = m["wire_bytes_per_device"] / ici_bw
    select_s = m["rounds"] * (m["select_bytes"] / spec.hbm_bw)
    lat_s = m["rounds"] * spec.ici_latency
    m.update({
        "wire_seconds": wire_s,
        "select_seconds": select_s,
        "latency_seconds": lat_s,
        "merge_seconds": wire_s + select_s + lat_s,
    })
    return m


def choose_merge_strategy(p: int, nq: int, k: int,
                          spec: Optional[ChipSpec] = None) -> str:
    """The modeled-time crossover between the two merge strategies —
    the ``merge="auto"`` policy of :func:`raft_tpu.distance.
    knn_sharded.knn_fused_sharded`. Non-power-of-two shard counts can
    only run the allgather merge (the butterfly needs pairs every
    round); p ≤ 2 ties on wire bytes, where the single allgather round
    wins on latency."""
    if p <= 2 or (p & (p - 1)):
        return "allgather"
    spec = spec if spec is not None else chip_spec()
    t_ag = ici_time_model(p, nq, k, "allgather", spec)["merge_seconds"]
    t_tr = ici_time_model(p, nq, k, "tournament", spec)["merge_seconds"]
    return "allgather" if t_ag <= t_tr else "tournament"


def _fmt_count(v: float) -> str:
    for unit, scale in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= scale:
            return f"{v / scale:.3g}{unit}"
    return f"{v:.3g}"


def _cost_records_from_registry(reg: MetricsRegistry) -> List[CostRecord]:
    """Latest ``cost`` event per entry → CostRecords (events hold the
    full record; the gauges are only the scrape surface)."""
    latest: Dict[str, CostRecord] = {}
    for ev in reg.events:
        if ev.get("type") != "cost":
            continue
        fields = {f.name: ev[f.name] for f in dataclasses.fields(CostRecord)
                  if f.name in ev}
        latest[ev.get("entry", "?")] = CostRecord(**fields)
    return list(latest.values())


def roofline_report(registry: Optional[MetricsRegistry] = None,
                    spec: Optional[ChipSpec] = None,
                    records: Optional[List[CostRecord]] = None,
                    timings: Optional[Dict[str, float]] = None) -> str:
    """Per-primitive roofline summary table, worst utilization first.

    Rows come from ``records`` (e.g. ``res.profiler.records()``) or, by
    default, the latest ``cost`` event per entry in the registry; execute
    times from ``timings`` (entry → seconds) or, by default, matching
    benchmark events (``observability.bench_results()``). Entries with no
    measured time still rank (by static distance data) but show ``-`` in
    the measured columns — the report must degrade to the static story
    rather than hide uncaptured primitives."""
    from raft_tpu.observability.exporters import bench_results

    reg = registry if registry is not None else get_registry()
    spec = spec if spec is not None else chip_spec()
    if records is None:
        records = _cost_records_from_registry(reg)
    if timings is None:
        timings = {name: r["seconds"]
                   for name, r in bench_results(reg).items()
                   if isinstance(r.get("seconds"), (int, float))}
    ests = [roofline(r, spec, seconds=timings.get(r.entry))
            for r in records]
    # worst-first: measured rows by utilization ascending, then unmeasured
    ests.sort(key=lambda e: (e.utilization is None,
                             e.utilization if e.utilization is not None
                             else 0.0))
    header = (f"roofline: {spec.name} — peak {spec.peak_flops / 1e12:.3g} "
              f"TFLOP/s, HBM {spec.hbm_bw / 1e9:.4g} GB/s, ridge "
              f"{spec.ridge:.3g} FLOP/B")
    cols = ("entry", "flops", "bytes", "AI", "bound", "time", "GB/s",
            "%roof")
    rows = []
    for e in ests:
        rows.append((
            e.entry, _fmt_count(e.flops), _fmt_count(e.bytes_accessed),
            f"{e.arithmetic_intensity:.3g}", e.bound,
            f"{e.seconds * 1e3:.3g}ms" if e.seconds else "-",
            f"{e.achieved_bw / 1e9:.3g}" if e.achieved_bw else "-",
            f"{e.utilization * 100:.1f}" if e.utilization is not None
            else "-"))
    if not rows:
        return header + "\n(no cost records captured)\n"
    widths = [max(len(c), *(len(r[i]) for r in rows))
              for i, c in enumerate(cols)]
    lines = [header,
             "  ".join(c.ljust(w) for c, w in zip(cols, widths)),
             "  ".join("-" * w for w in widths)]
    for r in rows:
        lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return "\n".join(lines) + "\n"
