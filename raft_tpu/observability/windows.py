"""Windowed aggregation over the MetricsRegistry: rate(), delta(),
windowed percentiles.

Every metric in the registry is cumulative-since-process-start — the
right substrate for scrapes, useless on its own for "is the error rate
high NOW". This module keeps a bounded ring of per-window metric
snapshots (one cheap ``tick()`` per interval: counters copy one float,
histograms one short cumulative-count list) and answers windowed
questions by SUBTRACTING snapshots:

- :meth:`MetricWindows.delta` — counter/histogram-count increase over
  the last ``window_s`` seconds (summed across label sets by default,
  so ``delta(REQUESTS)`` is total traffic and
  ``delta(REQUESTS, {"status": "shed"})`` the shed slice);
- :meth:`MetricWindows.rate` — delta divided by the ACTUAL covered
  interval (the ring stores real tick timestamps — a late tick widens
  the denominator instead of inflating the rate);
- :meth:`MetricWindows.percentile` — the ``histogram_quantile``
  interpolation (:func:`~raft_tpu.observability.metrics.
  bucket_percentile`) over windowed bucket-count DELTAS — a true
  rolling p50/p99, not the since-start estimate;
- :meth:`MetricWindows.gauge` — the newest sampled gauge value.

The clock is injectable (tests tick a fake clock through hours of
burn-rate history in microseconds) and the ring is bounded: capacity ×
interval is the longest lookback any SLO window can ask for — sized by
the caller (:class:`~raft_tpu.observability.slo.SloEngine` sizes it to
cover its slowest burn window).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from raft_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                            MetricsRegistry,
                                            bucket_percentile,
                                            get_registry)

_LabelKey = Tuple[Tuple[str, str], ...]


def _key(name: str, labels: Optional[Dict[str, str]]) -> Tuple:
    return (name, tuple(sorted((str(k), str(v))
                               for k, v in (labels or {}).items())))


class _Snap:
    """One tick's frozen view: scalar values for counters/gauges,
    (bounds, cumulative counts, sum) for histograms."""

    __slots__ = ("ts", "scalars", "hists")

    def __init__(self, ts: float):
        self.ts = ts
        self.scalars: Dict[Tuple, float] = {}
        self.hists: Dict[Tuple, Tuple[Tuple[float, ...], List[int],
                                      float]] = {}


class MetricWindows:
    """A ring of per-window registry snapshots (see module doc).

    ``interval_s`` is the nominal tick spacing — :meth:`tick` is
    rate-limited to it, so wiring it into a hot loop is safe (extra
    calls are one clock read). ``capacity`` bounds the lookback to
    ``capacity × interval_s`` seconds."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 5.0, capacity: int = 720,
                 clock=time.monotonic):
        self._registry = registry
        self.interval_s = max(1e-3, float(interval_s))
        self.capacity = max(2, int(capacity))
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: List[_Snap] = []

    @property
    def registry(self) -> MetricsRegistry:
        return (self._registry if self._registry is not None
                else get_registry())

    # -- ticking ----------------------------------------------------------
    def tick(self, force: bool = False) -> bool:
        """Snapshot the registry if a full interval has passed since
        the last tick (``force=True`` snapshots regardless — tests and
        the end-of-run bench stamp). Returns whether a snapshot was
        taken."""
        now = self._clock()
        with self._lock:
            if (not force and self._ring
                    and now - self._ring[-1].ts < self.interval_s):
                return False
        snap = _Snap(now)
        for metric in self.registry.collect():
            mk = _key(metric.name, metric.labels)
            if isinstance(metric, Histogram):
                snap.hists[mk] = (metric.buckets,
                                  metric.cumulative_counts(),
                                  metric.sum)
            elif isinstance(metric, (Counter, Gauge)):
                snap.scalars[mk] = metric.value
        with self._lock:
            self._ring.append(snap)
            if len(self._ring) > self.capacity:
                del self._ring[:len(self._ring) - self.capacity]
        return True

    def _bracket(self, window_s: float) -> Optional[Tuple[_Snap, _Snap]]:
        """(oldest snapshot covering the window, newest snapshot) — or
        None with fewer than two ticks. The old edge is the NEWEST
        snapshot at least ``window_s`` old (so the covered interval is
        ≥ the asked window when history allows), falling back to the
        oldest one held."""
        with self._lock:
            if len(self._ring) < 2:
                return None
            newest = self._ring[-1]
            cutoff = newest.ts - float(window_s)
            old = self._ring[0]
            for snap in self._ring[:-1]:
                if snap.ts <= cutoff:
                    old = snap
                else:
                    break
            if old is newest:
                old = self._ring[-2]
            return old, newest

    # -- windowed reads ---------------------------------------------------
    def _scalar_sum(self, snap: _Snap, name: str,
                    labels: Optional[Dict[str, str]]) -> float:
        if labels is not None:
            return snap.scalars.get(_key(name, labels), 0.0)
        total = 0.0
        for (n, _lk), v in snap.scalars.items():
            if n == name:
                total += v
        return total

    def _hist_count(self, snap: _Snap, name: str,
                    labels: Optional[Dict[str, str]]) -> float:
        total = 0.0
        for (n, lk), (_b, cum, _s) in snap.hists.items():
            if n != name:
                continue
            if labels is not None and lk != _key(name, labels)[1]:
                continue
            total += cum[-1]
        return total

    def delta(self, name: str, labels: Optional[Dict[str, str]] = None,
              window_s: Optional[float] = None) -> float:
        """Counter increase (or histogram observation-count increase)
        over the window — summed across label sets when ``labels`` is
        None. 0.0 with insufficient history (an honest "no evidence
        yet", never a crash)."""
        br = self._bracket(window_s if window_s is not None
                           else self.interval_s)
        if br is None:
            return 0.0
        old, new = br
        d = (self._scalar_sum(new, name, labels)
             - self._scalar_sum(old, name, labels))
        if d == 0.0:
            d = (self._hist_count(new, name, labels)
                 - self._hist_count(old, name, labels))
        return max(0.0, d)

    def rate(self, name: str, labels: Optional[Dict[str, str]] = None,
             window_s: Optional[float] = None) -> float:
        """Per-second rate over the window: delta over the ACTUAL
        interval the bracketing snapshots cover."""
        br = self._bracket(window_s if window_s is not None
                           else self.interval_s)
        if br is None:
            return 0.0
        old, new = br
        dt = new.ts - old.ts
        if dt <= 0.0:
            return 0.0
        return self.delta(name, labels, window_s) / dt

    def percentile(self, name: str, q: float,
                   labels: Optional[Dict[str, str]] = None,
                   window_s: Optional[float] = None) -> Optional[float]:
        """Windowed histogram percentile: the bucket interpolation over
        cumulative-count DELTAS between the bracketing snapshots,
        merged across label sets when ``labels`` is None. None without
        enough history or observations in the window."""
        br = self._bracket(window_s if window_s is not None
                           else self.interval_s)
        if br is None:
            return None
        old, new = br
        want_lk = None if labels is None else _key(name, labels)[1]
        bounds: Optional[Tuple[float, ...]] = None
        window_cum: Optional[List[float]] = None
        for (n, lk), (b, cum, _s) in new.hists.items():
            if n != name or (want_lk is not None and lk != want_lk):
                continue
            old_h = old.hists.get((n, lk))
            old_cum = old_h[1] if old_h is not None else [0] * len(cum)
            d = [max(0, c1 - c0) for c1, c0 in zip(cum, old_cum)]
            if bounds is None:
                bounds = b
                window_cum = d
            elif b == bounds and window_cum is not None:
                window_cum = [a + x for a, x in zip(window_cum, d)]
        if bounds is None or window_cum is None:
            return None
        return bucket_percentile(bounds, window_cum, q)

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None
              ) -> Optional[float]:
        """The newest sampled value of a gauge (or counter) — None when
        it has never been sampled."""
        with self._lock:
            if not self._ring:
                return None
            newest = self._ring[-1]
        mk = _key(name, labels)
        if labels is None:
            for (n, _lk), v in newest.scalars.items():
                if n == name:
                    return v
            return None
        return newest.scalars.get(mk)

    def covered_s(self) -> float:
        """Seconds of history the ring currently holds."""
        with self._lock:
            if len(self._ring) < 2:
                return 0.0
            return self._ring[-1].ts - self._ring[0].ts

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
